"""Blocks: Header, Commit, CommitSig, Data.

Hash rules (behavior parity with reference types/block.go):
- Header.Hash = merkle over the 14 proto-encoded header fields, primitives
  wrapped in gogo wrapper messages (reference types/block.go:438-473 +
  types/encoding_helper.go cdcEncode); empty primitives hash as nil leaves.
- Commit.Hash = merkle over proto-encoded CommitSigs (types/block.go:835).
- Data.Hash = merkle over SHA-256 tx hashes (types/tx.go Txs.Hash).
- Commit.VoteSignBytes rebuilds the canonical precommit each signer signed
  (types/block.go:879): per-validator timestamp and flag-dependent BlockID.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..crypto import merkle
from ..crypto.keys import tmhash
from ..encoding import proto as pb
from .basic import BlockID, Timestamp, ZERO_BLOCK_ID, ZERO_TIME
from .vote import SignedMsgType, canonical_vote_bytes

MAX_HEADER_BYTES = 660  # 626 reference fields + the 34-byte da_root leaf
BLOCK_PART_SIZE_BYTES = 65536  # reference types/part_set.go BlockPartSizeBytes


class BlockIDFlag(enum.IntEnum):
    UNKNOWN = 0
    ABSENT = 1
    COMMIT = 2
    NIL = 3


# IntEnum.__call__ is slow; the columnar decode loop looks flags up here
# (misses fall through to the constructor, which raises for bad values)
_FLAG_CACHE = {f.value: f for f in BlockIDFlag}


def _wrap_string(s: str) -> bytes:
    return pb.f_string(1, s) if s else b""


def _wrap_int64(v: int) -> bytes:
    return pb.f_varint(1, v) if v else b""


def _wrap_bytes(b: bytes) -> bytes:
    return pb.f_bytes(1, b) if b else b""


@dataclass(frozen=True)
class Consensus:
    """Version info (reference proto cometbft/version/v1 Consensus)."""

    block: int = 11  # reference version/version.go BlockProtocol
    app: int = 0

    def encode(self) -> bytes:
        return pb.f_varint(1, self.block) + pb.f_varint(2, self.app)

    @classmethod
    def decode(cls, buf: bytes) -> "Consensus":
        d = pb.fields_to_dict(buf)
        return cls(int(d.get(1, 0)), int(d.get(2, 0)))


@dataclass
class Header:
    version: Consensus = field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time: Timestamp = ZERO_TIME
    last_block_id: BlockID = ZERO_BLOCK_ID
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    # DA extension (da/commit.py): root of the erasure-coded chunk
    # commitment; empty when DA is disabled — and then it contributes
    # neither a hash leaf nor wire bytes, so legacy headers stay
    # bit-identical
    da_root: bytes = b""

    def hash(self) -> bytes | None:
        if not self.validators_hash:
            return None
        leaves = [
            self.version.encode(),
            _wrap_string(self.chain_id),
            _wrap_int64(self.height),
            self.time.encode(),
            self.last_block_id.encode(),
            _wrap_bytes(self.last_commit_hash),
            _wrap_bytes(self.data_hash),
            _wrap_bytes(self.validators_hash),
            _wrap_bytes(self.next_validators_hash),
            _wrap_bytes(self.consensus_hash),
            _wrap_bytes(self.app_hash),
            _wrap_bytes(self.last_results_hash),
            _wrap_bytes(self.evidence_hash),
            _wrap_bytes(self.proposer_address),
        ]
        if self.da_root:
            leaves.append(_wrap_bytes(self.da_root))
        return merkle.hash_from_byte_slices(leaves)

    def encode(self) -> bytes:
        return (
            pb.f_embedded(1, self.version.encode())
            + pb.f_string(2, self.chain_id)
            + pb.f_varint(3, self.height)
            + pb.f_embedded(4, self.time.encode())
            + pb.f_embedded(5, self.last_block_id.encode())
            + pb.f_bytes(6, self.last_commit_hash)
            + pb.f_bytes(7, self.data_hash)
            + pb.f_bytes(8, self.validators_hash)
            + pb.f_bytes(9, self.next_validators_hash)
            + pb.f_bytes(10, self.consensus_hash)
            + pb.f_bytes(11, self.app_hash)
            + pb.f_bytes(12, self.last_results_hash)
            + pb.f_bytes(13, self.evidence_hash)
            + pb.f_bytes(14, self.proposer_address)
            + pb.f_bytes(15, self.da_root)
        )

    @classmethod
    def decode(cls, buf: bytes) -> "Header":
        d = pb.fields_to_dict(buf)
        return cls(
            version=Consensus.decode(pb.as_bytes(d.get(1, b""))),
            chain_id=pb.as_bytes(d.get(2, b"")).decode("utf-8"),
            height=pb.to_i64(d.get(3, 0)),
            time=Timestamp.decode(pb.as_bytes(d.get(4, b""))),
            last_block_id=BlockID.decode(pb.as_bytes(d.get(5, b""))),
            last_commit_hash=pb.as_bytes(d.get(6, b"")),
            data_hash=pb.as_bytes(d.get(7, b"")),
            validators_hash=pb.as_bytes(d.get(8, b"")),
            next_validators_hash=pb.as_bytes(d.get(9, b"")),
            consensus_hash=pb.as_bytes(d.get(10, b"")),
            app_hash=pb.as_bytes(d.get(11, b"")),
            last_results_hash=pb.as_bytes(d.get(12, b"")),
            evidence_hash=pb.as_bytes(d.get(13, b"")),
            proposer_address=pb.as_bytes(d.get(14, b"")),
            da_root=pb.as_bytes(d.get(15, b"")),
        )


@dataclass
class CommitSig:
    """One validator's slot in a commit (reference types/block.go:594)."""

    block_id_flag: BlockIDFlag = BlockIDFlag.ABSENT
    validator_address: bytes = b""
    timestamp: Timestamp = ZERO_TIME
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    def is_absent(self) -> bool:
        return self.block_id_flag == BlockIDFlag.ABSENT

    def is_commit(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def effective_block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this slot's vote was cast for
        (reference types/block.go CommitSig.BlockID)."""
        if self.block_id_flag == BlockIDFlag.COMMIT:
            return commit_block_id
        return ZERO_BLOCK_ID

    def encode(self) -> bytes:
        return (
            pb.f_varint(1, int(self.block_id_flag))
            + pb.f_bytes(2, self.validator_address)
            + pb.f_embedded(3, self.timestamp.encode())
            + pb.f_bytes(4, self.signature)
        )

    @classmethod
    def decode(cls, buf: bytes) -> "CommitSig":
        d = pb.fields_to_dict(buf)
        return cls(
            block_id_flag=BlockIDFlag(int(d.get(1, 0))),
            validator_address=pb.as_bytes(d.get(2, b"")),
            timestamp=Timestamp.decode(pb.as_bytes(d.get(3, b""))),
            signature=pb.as_bytes(d.get(4, b"")),
        )

    @classmethod
    def _decode_span(cls, buf: bytes, i: int, end: int) -> "CommitSig":
        """Decode from buf[i:end] without slicing out sub-buffers: a
        commit carries one of these per validator and the generic
        dict-of-fields walk was replay's single largest host cost."""
        rv = pb.read_uvarint
        flag = 0
        addr = b""
        ts_s = 0  # bug-compatible with the generic decoder's absent-field default
        ts_n = 0
        sig = b""
        while i < end:
            tag, i = rv(buf, i)
            f, wt = tag >> 3, tag & 7
            if wt == 0:
                v, i = rv(buf, i)
                # a varint must not run past the span into the next
                # field (the generic decoder's sub-buffer slice raised
                # here; match it)
                if i > end:
                    raise ValueError("truncated varint in CommitSig")
                if f == 1:
                    flag = v
            elif wt == 2:
                ln, i = rv(buf, i)
                j = i + ln
                if j > end or i > end:
                    raise ValueError("truncated commit sig field")
                if f == 2:
                    addr = buf[i:j]
                elif f == 4:
                    sig = buf[i:j]
                elif f == 3:
                    while i < j:
                        t2, i = rv(buf, i)
                        if t2 & 7 != 0:
                            raise ValueError("bad timestamp wire type")
                        v2, i = rv(buf, i)
                        if i > j:
                            raise ValueError("truncated timestamp varint")
                        if t2 >> 3 == 1:
                            ts_s = pb.to_i64(v2)
                        elif t2 >> 3 == 2:
                            ts_n = pb.to_i64(v2)
                i = j
            else:
                raise ValueError(f"unsupported wire type {wt} in CommitSig")
        if i > end:
            raise ValueError("truncated varint in CommitSig")
        return cls(
            block_id_flag=BlockIDFlag(flag),
            validator_address=addr,
            timestamp=Timestamp(ts_s, ts_n),
            signature=sig,
        )


class _LazySigList:
    """CommitSig list materialized on first ELEMENT access.

    Natively-decoded commits carry columnar views (Commit.verify_columns)
    that the batched replay path consumes directly; building 1000
    CommitSig objects per block cost more than the wire parse itself.
    Length/truthiness never materialize (validate_block's size checks
    stay free); iteration, indexing, and equality build the real list
    once and delegate."""

    __slots__ = ("_n", "_mk", "_real")

    def __init__(self, n: int, mk):
        self._n = n
        self._mk = mk
        self._real = None

    def _mat(self) -> list:
        if self._real is None:
            self._real = self._mk()
            self._mk = None
        return self._real

    def __len__(self):
        return self._n

    def __bool__(self):
        return self._n > 0

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]

    def __eq__(self, other):
        if isinstance(other, _LazySigList):
            other = other._mat()
        if isinstance(other, list):
            return self._mat() == other
        return NotImplemented

    def __repr__(self):
        return repr(self._mat())


@dataclass
class Commit:
    """+2/3 precommit evidence for a block (reference types/block.go:835)."""

    height: int = 0
    round: int = 0
    block_id: BlockID = ZERO_BLOCK_ID
    signatures: list[CommitSig] = field(default_factory=list)

    def hash(self) -> bytes:
        # memoized: commits are immutable once decoded/sealed, and block
        # validation re-merkles the predecessor's 100+ signatures per
        # height otherwise
        h = self.__dict__.get("_hash_memo")
        if h is None:
            # leaves are each slot's canonical encoding; a commit decoded
            # from bytes THIS node wrote (trusted_bytes) reuses the decode
            # spans — byte-identical to cs.encode() since our own encoder
            # produced them (wire-received commits never take this path:
            # a non-canonical adversarial encoding must not define the
            # hash)
            leaves = self.__dict__.get("_sig_spans")
            if leaves is None:
                leaves = [cs.encode() for cs in self.signatures]
            h = merkle.hash_from_byte_slices(leaves)
            self.__dict__["_hash_memo"] = h
        return h

    def size(self) -> int:
        return len(self.signatures)

    def _sb_parts(self, chain_id: str):
        """Commit-invariant sign-bytes parts (prefix variants + chain-id
        tail), cached per (Commit, chain_id)."""
        cache = self.__dict__.get("_sb_cache")
        if cache is None or cache[0] != chain_id:
            head = (
                pb.f_varint(1, int(SignedMsgType.PRECOMMIT))
                + pb.f_sfixed64(2, self.height)
                + pb.f_sfixed64(3, self.round)
            )
            cache = (
                chain_id,
                head + pb.f_embedded_opt(4, self.block_id.encode_canonical()),
                head + pb.f_embedded_opt(4, ZERO_BLOCK_ID.encode_canonical()),
                pb.f_string(6, chain_id),
            )
            self.__dict__["_sb_cache"] = cache
        return cache

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Rebuild the canonical precommit bytes validator idx signed
        (reference types/block.go:879).

        Byte-identical to canonical_vote_bytes; the commit-invariant
        prefix (type, height, round, block id) and suffix (chain id) are
        built once per Commit — verify_commit calls this for every
        validator and the per-call proto assembly was half its cost."""
        cs = self.signatures[idx]
        cache = self._sb_parts(chain_id)
        _, with_bid, nil_bid, tail = cache
        is_commit = cs.block_id_flag == BlockIDFlag.COMMIT
        key = (cache, is_commit, cs.timestamp)
        sb = cs.__dict__.get("_sb")
        if sb is not None and sb[0] == key and sb[0][0] is cache:
            return sb[1]
        prefix = with_bid if is_commit else nil_bid
        out = pb.length_prefixed(
            prefix + pb.f_embedded(5, cs.timestamp.encode()) + tail
        )
        # memo per CommitSig, keyed on the prefix-cache identity (which
        # changes whenever chain_id/height/round/block_id change) plus
        # the slot fields the bytes depend on: vote gossip and repeated
        # commit verification rebuild these bytes many times
        cs.__dict__["_sb"] = (key, out)
        return out

    def vote_sign_bytes_all(self, chain_id: str) -> list:
        """Sign bytes for every slot in one pass (None for absent).

        Byte-identical to vote_sign_bytes per index, minus the per-call
        memo machinery: window replay builds a hundred of these per
        block, where the per-slot work is just prefix + timestamp
        varints + tail."""
        if not self.signatures:
            return []
        _, with_bid, nil_bid, tail = self._sb_parts(chain_id)
        lp = pb.length_prefixed
        fv = pb.f_varint
        fe = pb.f_embedded
        commit_flag = BlockIDFlag.COMMIT
        absent_flag = BlockIDFlag.ABSENT
        out = []
        for cs in self.signatures:
            if cs.block_id_flag == absent_flag:
                out.append(None)
                continue
            ts = cs.timestamp
            prefix = with_bid if cs.block_id_flag == commit_flag else nil_bid
            out.append(
                lp(prefix + fe(5, fv(1, ts.seconds) + fv(2, ts.nanos)) + tail)
            )
        return out

    def verify_columns(self):
        """Columnar views for batch verification: (flags u8, addrs
        (n,20) u8, addr_lens u8, sig_lens u8, sigs (n,64) u8, ts_s i64,
        ts_n i64) numpy arrays, or None when this commit was not decoded through
        the native columnar parser (wire/store decode is the replay
        path; hand-built commits take the per-slot path)."""
        cols = self.__dict__.get("_cols")
        if cols is None:
            return None
        import numpy as np

        n, flags, addr_lens, addrs, ts_s, ts_n, sig_lens, sigs = cols
        return (
            np.frombuffer(flags, np.uint8, n),
            np.frombuffer(addrs, np.uint8, n * 20).reshape(n, 20),
            np.frombuffer(addr_lens, np.uint8, n),
            np.frombuffer(sig_lens, np.uint8, n),
            np.frombuffer(sigs, np.uint8, n * 64).reshape(n, 64),
            np.frombuffer(ts_s, np.int64, n),
            np.frombuffer(ts_n, np.int64, n),
        )

    def vote_sign_bytes_blob(self, chain_id: str):
        """(msgs blob, lens uint32 array) covering every slot (absent
        slots have length 0), built in one native call from the decode
        columns — byte-identical to vote_sign_bytes per index. None
        when columns or the native lib are unavailable."""
        cols = self.__dict__.get("_cols")
        if cols is None:
            return None
        from ..crypto import native as _native

        import numpy as np

        n, flags, addr_lens, addrs, ts_s, ts_n, sig_lens, sigs = cols
        _, with_bid, nil_bid, tail = self._sb_parts(chain_id)
        return _native.commit_sign_bytes(
            n, np.frombuffer(flags, np.uint8, n),
            np.frombuffer(ts_s, np.int64, n),
            np.frombuffer(ts_n, np.int64, n),
            with_bid, nil_bid, tail,
        )

    def invalidate_memos(self) -> None:
        """Drop every derived-bytes memo (encode, hash, sign-bytes
        parts, decode columns, spans). Commits are immutable on every
        production path — decode, make_commit, and VoteSet.make_commit
        all seal before exposing — so only code that mutates a
        CommitSig in place afterwards (test factories, corruption
        harnesses) must call this, or stale memoized bytes will be
        served."""
        d = self.__dict__
        for k in ("_enc_memo", "_hash_memo", "_cols", "_sig_spans",
                  "_sb_cache"):
            d.pop(k, None)

    def encode(self) -> bytes:
        # memoized: commits are immutable once constructed (decode /
        # make_commit / VoteSet.make_commit all seal before exposing),
        # and the hot paths re-encode them constantly — every
        # save_block, gossip frame, and embedded LastCommit encodes the
        # same 1000-signature list again. In-place mutators must call
        # invalidate_memos().
        memo = self.__dict__.get("_enc_memo")
        if memo is not None:
            return memo
        out = (
            pb.f_varint(1, self.height)
            + pb.f_varint(2, self.round)
            + pb.f_embedded(3, self.block_id.encode())
        )
        for cs in self.signatures:
            out += pb.f_embedded(4, cs.encode())
        self.__dict__["_enc_memo"] = out
        return out

    @classmethod
    def decode(cls, buf: bytes, trusted_bytes: bool = False) -> "Commit":
        # columnar fast path: one C call parses the whole signature list
        # (csrc/commit_codec.inc); Python only materializes the objects.
        # Falls through to the pure-Python walk when the native lib is
        # absent or the wire shape needs its exact error behavior.
        from ..crypto import native as _native

        parsed = _native.commit_parse(buf) if _native.available() else None
        if parsed is not None:
            h_u64, r_u64, bid_span, cols = parsed
            n, flags, addr_lens, addrs, ts_s, ts_n, sig_lens, sigs, spans = cols
            # flag validation must stay DECODE-time even though the
            # CommitSig objects are lazy: the pure-Python walk raises
            # ValueError on an out-of-range flag while parsing, and
            # native/non-native builds must reject identical bytes
            # identically (test_commit_codec_diff pins this)
            if n and max(flags[:n]) > 3:
                raise ValueError(
                    f"{max(flags[:n])} is not a valid BlockIDFlag"
                )

            def _mk_sigs():
                sig_list = []
                flag_cache = _FLAG_CACHE
                flag_of = BlockIDFlag
                ts_of = Timestamp
                cs_of = CommitSig
                for i in range(n):
                    a0 = i * 20
                    s0 = i * 64
                    fv = flags[i]
                    fl = flag_cache.get(fv)
                    if fl is None:  # UNKNOWN(0) is falsy; don't use `or`
                        fl = flag_of(fv)
                    sig_list.append(
                        cs_of(
                            fl,
                            addrs[a0 : a0 + addr_lens[i]],
                            ts_of(ts_s[i], ts_n[i]),
                            sigs[s0 : s0 + sig_lens[i]],
                        )
                    )
                return sig_list

            spans_out = None
            if trusted_bytes:
                spans_out = [
                    buf[spans[2 * i] : spans[2 * i] + spans[2 * i + 1]]
                    for i in range(n)
                ]
            bid_off, bid_len = bid_span
            commit = cls(
                pb.to_i64(h_u64),
                pb.to_i64(r_u64),
                BlockID.decode(buf[bid_off : bid_off + bid_len])
                if bid_len or bid_off
                else ZERO_BLOCK_ID,
                _LazySigList(n, _mk_sigs),
            )
            if spans_out is not None:
                commit.__dict__["_sig_spans"] = spans_out
            # stash the columnar views for the batch-verify fast path
            # (replay verifies 1000-signature commits; re-extracting
            # per-CommitSig fields there costs more than the decode)
            commit.__dict__["_cols"] = (
                n, flags, addr_lens, addrs, ts_s, ts_n, sig_lens, sigs
            )
            return commit
        # specialized walk (one pass, no per-sig sub-buffer dicts): the
        # signature list dominates and replay decodes one commit per
        # block. trusted_bytes (store-loaded only) additionally stashes
        # each slot's wire span as its canonical encoding for hash()
        height = round_ = 0
        block_id = ZERO_BLOCK_ID
        sigs = []
        spans = [] if trusted_bytes else None
        rv = pb.read_uvarint
        i, n = 0, len(buf)
        while i < n:
            tag, i = rv(buf, i)
            f, wt = tag >> 3, tag & 7
            if wt == 0:
                v, i = rv(buf, i)
                if f == 1:
                    height = pb.to_i64(v)
                elif f == 2:
                    round_ = pb.to_i64(v)
            elif wt == 2:
                ln, i = rv(buf, i)
                j = i + ln
                if j > n:
                    raise ValueError("truncated commit field")
                if f == 4:
                    sigs.append(CommitSig._decode_span(buf, i, j))
                    if spans is not None:
                        spans.append(buf[i:j])
                elif f == 3:
                    block_id = BlockID.decode(buf[i:j])
                i = j
            else:
                raise ValueError(f"unsupported wire type {wt} in Commit")
        commit = cls(height, round_, block_id, sigs)
        if spans is not None:
            commit.__dict__["_sig_spans"] = spans
        return commit


def tx_hash(tx: bytes) -> bytes:
    return tmhash(tx)


def block_id_for(block: "Block") -> BlockID:
    """Canonical BlockID: header hash + part-set header over the block bytes
    (reference types/block.go MakePartSet + BlockID).

    Memoized per Block instance: callers compute the id of a COMPLETE
    block (decoded from the store/wire or finalized by consensus), and
    replay/validation would otherwise re-encode + re-merkle the same
    ~10 KB block three times per height."""
    memo = block.__dict__.get("_bid_memo")
    if memo is not None:
        return memo
    from .part_set import PartSet

    # blocks decoded with trusted_bytes=True carry their own canonical
    # store bytes; encode() itself stays memo-free so post-decode
    # mutations (e.g. re-saving an edited block) always re-encode
    enc = block.__dict__.get("_enc_memo")
    if enc is None:
        enc = block.encode()
    ps = PartSet.from_data(enc)
    bid = BlockID(block.hash(), ps.header)
    block.__dict__["_bid_memo"] = bid
    return bid


@dataclass
class Data:
    txs: list[bytes] = field(default_factory=list)

    def hash(self) -> bytes:
        # columnar fast path (mempool/txcolumns.py): the batch memoizes
        # its per-tx hash column — bit-identical leaves, merkle unchanged
        hashes = getattr(self.txs, "tx_hashes", None)
        if hashes is not None:
            return merkle.hash_from_byte_slices(hashes())
        return merkle.hash_from_byte_slices([tx_hash(t) for t in self.txs])

    def encode(self) -> bytes:
        # columnar fast path: the batch memoizes the exact repeated
        # f_bytes(1, tx, emit_empty=True) payload this loop produces
        enc = getattr(self.txs, "encode_data", None)
        if enc is not None:
            return enc()
        out = b""
        for t in self.txs:
            out += pb.f_bytes(1, t, emit_empty=True)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "Data":
        return cls([pb.as_bytes(v) for f, _, v in pb.parse_fields(buf) if f == 1])


@dataclass
class Block:
    header: Header
    data: Data = field(default_factory=Data)
    evidence: list = field(default_factory=list)
    last_commit: Commit = field(default_factory=Commit)

    def hash(self) -> bytes | None:
        return self.header.hash()

    def encode(self) -> bytes:
        # EvidenceList: repeated oneof-wrapped Evidence (field 1)
        ev_payload = b"".join(pb.f_embedded(1, ev.wrapped()) for ev in self.evidence)
        return (
            pb.f_embedded(1, self.header.encode())
            + pb.f_embedded(2, self.data.encode())
            + pb.f_embedded(3, ev_payload)
            + pb.f_embedded_opt(4, self.last_commit.encode() if self.last_commit else None)
        )

    @classmethod
    def decode(cls, buf: bytes, trusted_bytes: bool = False) -> "Block":
        """trusted_bytes=True stashes `buf` as the encode memo — ONLY
        for bytes this node wrote itself (the block store): re-encoding
        for BlockID/part-set work then reuses them. Wire-received bytes
        must never be trusted here (a non-canonical adversarial encoding
        would define this node's BlockID)."""
        from .agg_commit import decode_commit_any
        from .evidence import decode_evidence

        d = pb.fields_to_dict(buf)
        evidence = []
        for f, _, v in pb.parse_fields(pb.as_bytes(d.get(3, b""))):
            if f == 1:
                evidence.append(decode_evidence(pb.as_bytes(v)))
        blk = cls(
            header=Header.decode(pb.as_bytes(d.get(1, b""))),
            data=Data.decode(pb.as_bytes(d.get(2, b""))),
            evidence=evidence,
            last_commit=(
                decode_commit_any(
                    pb.as_bytes(d.get(4, b"")), trusted_bytes=trusted_bytes
                )
                if 4 in d
                else Commit()
            ),
        )
        if trusted_bytes:
            blk.__dict__["_enc_memo"] = bytes(buf)
        return blk
