"""Block proposals (reference types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding import proto as pb
from .basic import BlockID, Timestamp, ZERO_BLOCK_ID, ZERO_TIME
from .vote import SignedMsgType, canonical_proposal_bytes


@dataclass
class Proposal:
    height: int = 0
    round: int = 0
    pol_round: int = -1  # proof-of-lock round; -1 when none
    block_id: BlockID = ZERO_BLOCK_ID
    timestamp: Timestamp = ZERO_TIME
    signature: bytes = b""

    @property
    def type(self) -> SignedMsgType:
        return SignedMsgType.PROPOSAL

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_proposal_bytes(
            self.height, self.round, self.pol_round, self.block_id,
            self.timestamp, chain_id,
        )

    def basic_validate(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if self.pol_round < -1 or (
            self.pol_round >= 0 and self.pol_round >= self.round
        ):
            raise ValueError("invalid POL round")
        if self.block_id.is_zero():
            raise ValueError("proposal for nil block")

    def encode(self) -> bytes:
        return (
            pb.f_varint(1, int(SignedMsgType.PROPOSAL))
            + pb.f_varint(2, self.height)
            + pb.f_varint(3, self.round)
            + pb.f_varint(4, self.pol_round)
            + pb.f_embedded(5, self.block_id.encode())
            + pb.f_embedded(6, self.timestamp.encode())
            + pb.f_bytes(7, self.signature)
        )

    @classmethod
    def decode(cls, buf: bytes) -> "Proposal":
        d = pb.fields_to_dict(buf)
        return cls(
            height=pb.to_i64(d.get(2, 0)),
            round=pb.to_i64(d.get(3, 0)),
            pol_round=pb.to_i64(d.get(4, 0)),
            block_id=BlockID.decode(pb.as_bytes(d.get(5, b""))),
            timestamp=Timestamp.decode(pb.as_bytes(d.get(6, b""))),
            signature=pb.as_bytes(d.get(7, b"")),
        )
