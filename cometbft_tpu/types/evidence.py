"""Evidence of Byzantine behavior.

Behavior parity: reference types/evidence.go —
- DuplicateVoteEvidence (:36): two conflicting signed votes at one HRS;
  constructor orders VoteA/VoteB by BlockID key (:58-66).
- LightClientAttackEvidence (:210): a conflicting light block + the common
  height, with the byzantine subset (:253 GetByzantineValidators).
- EvidenceList hash = merkle over each evidence's oneof-wrapped proto
  bytes (types/evidence.go EvidenceList.Hash).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle
from ..crypto.keys import tmhash
from ..encoding import proto as pb
from .basic import Timestamp, ZERO_TIME
from .validator_set import ValidatorSet
from .vote import SignedMsgType, Vote


class EvidenceError(Exception):
    pass


@dataclass
class DuplicateVoteEvidence:
    vote_a: Vote = None
    vote_b: Vote = None
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: Timestamp = ZERO_TIME

    ABCI_TYPE = 1  # MisbehaviorType duplicate vote

    @classmethod
    def from_votes(cls, a: Vote, b: Vote, validator_power: int,
                   total_voting_power: int, time: Timestamp
                   ) -> "DuplicateVoteEvidence":
        if a is None or b is None:
            raise EvidenceError("missing vote")
        # order by block id key (reference NewDuplicateVoteEvidence :58)
        if b.block_id.key() < a.block_id.key():
            a, b = b, a
        return cls(a, b, total_voting_power, validator_power, time)

    @property
    def height(self) -> int:
        return self.vote_a.height

    def address(self) -> bytes:
        return self.vote_a.validator_address

    def encode(self) -> bytes:
        return (
            pb.f_embedded(1, self.vote_a.encode())
            + pb.f_embedded(2, self.vote_b.encode())
            + pb.f_varint(3, self.total_voting_power)
            + pb.f_varint(4, self.validator_power)
            + pb.f_embedded(5, self.timestamp.encode())
        )

    @classmethod
    def decode(cls, buf: bytes) -> "DuplicateVoteEvidence":
        d = pb.fields_to_dict(buf)
        return cls(
            Vote.decode(pb.as_bytes(d.get(1, b""))),
            Vote.decode(pb.as_bytes(d.get(2, b""))),
            pb.to_i64(d.get(3, 0)),
            pb.to_i64(d.get(4, 0)),
            Timestamp.decode(pb.as_bytes(d.get(5, b""))),
        )

    def wrapped(self) -> bytes:
        """Evidence oneof wrapper (field 1 = duplicate vote)."""
        return pb.f_embedded(1, self.encode())

    def hash(self) -> bytes:
        return tmhash(self.wrapped())

    def to_abci_list(self):
        from ..abci.types import Misbehavior

        return [Misbehavior(
            type=self.ABCI_TYPE,
            validator_address=self.address(),
            validator_power=self.validator_power,
            height=self.height,
            time=self.timestamp,
            total_voting_power=self.total_voting_power,
        )]

    def verify(self, chain_id: str, vals: ValidatorSet) -> None:
        """Structural + signature verification
        (reference internal/evidence/verify.go VerifyDuplicateVote :~180)."""
        a, b = self.vote_a, self.vote_b
        if a.height != b.height or a.round != b.round or a.type != b.type:
            raise EvidenceError("votes from different HRS")
        if a.type not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise EvidenceError("invalid vote type")
        if a.validator_address != b.validator_address:
            raise EvidenceError("votes from different validators")
        if a.block_id == b.block_id:
            raise EvidenceError("votes for the same block are not equivocation")
        if b.block_id.key() < a.block_id.key():
            raise EvidenceError("votes not ordered by block id")
        _, val = vals.get_by_address(a.validator_address)
        if val is None:
            raise EvidenceError("validator not in set at evidence height")
        if val.voting_power != self.validator_power:
            raise EvidenceError("validator power mismatch")
        if vals.total_voting_power() != self.total_voting_power:
            raise EvidenceError("total power mismatch")
        for v in (a, b):
            if not val.pub_key.verify_signature(v.sign_bytes(chain_id), v.signature):
                raise EvidenceError("invalid vote signature in evidence")


@dataclass
class LightClientAttackEvidence:
    """A conflicting (forged) light block (reference types/evidence.go:210)."""

    conflicting_block: object = None  # light.LightBlock
    common_height: int = 0
    byzantine_validators: list = field(default_factory=list)  # addresses
    total_voting_power: int = 0
    timestamp: Timestamp = ZERO_TIME

    ABCI_TYPE = 2

    @property
    def height(self) -> int:
        return self.common_height

    def encode(self) -> bytes:
        cb = self.conflicting_block
        payload = pb.f_embedded(1, cb.signed_header.encode()) if cb else b""
        from ..state.types import encode_validator_set

        if cb is not None:
            payload += pb.f_embedded(2, encode_validator_set(cb.validators))
        payload += pb.f_varint(3, self.common_height)
        for addr in self.byzantine_validators:
            payload += pb.f_bytes(4, addr, emit_empty=True)
        payload += pb.f_varint(5, self.total_voting_power)
        payload += pb.f_embedded(6, self.timestamp.encode())
        return payload

    @classmethod
    def decode(cls, buf: bytes) -> "LightClientAttackEvidence":
        from ..light.types import LightBlock, SignedHeader
        from ..state.types import decode_validator_set

        sh = vals = None
        common = tvp = 0
        ts = ZERO_TIME
        byz = []
        for f, _, v in pb.parse_fields(buf):
            if f == 1:
                sh = SignedHeader.decode(pb.as_bytes(v))
            elif f == 2:
                vals = decode_validator_set(pb.as_bytes(v))
            elif f == 3:
                common = pb.to_i64(v)
            elif f == 4:
                byz.append(pb.as_bytes(v))
            elif f == 5:
                tvp = pb.to_i64(v)
            elif f == 6:
                ts = Timestamp.decode(pb.as_bytes(v))
        cb = LightBlock(sh, vals) if sh is not None and vals is not None else None
        return cls(cb, common, byz, tvp, ts)

    def wrapped(self) -> bytes:
        return pb.f_embedded(2, self.encode())

    def hash(self) -> bytes:
        return tmhash(self.wrapped())

    def to_abci_list(self):
        """One Misbehavior per byzantine validator with its power
        (reference types/evidence.go LightClientAttackEvidence.ABCI)."""
        from ..abci.types import Misbehavior

        vals = self.conflicting_block.validators if self.conflicting_block else None
        out = []
        for addr in self.byzantine_validators:
            power = 0
            if vals is not None:
                _, v = vals.get_by_address(addr)
                power = v.voting_power if v else 0
            out.append(Misbehavior(
                type=self.ABCI_TYPE,
                validator_address=addr,
                validator_power=power,
                height=self.common_height,
                time=self.timestamp,
                total_voting_power=self.total_voting_power,
            ))
        return out


def decode_evidence(buf: bytes):
    """Evidence oneof -> concrete type."""
    fields = pb.parse_fields(buf)
    if not fields:
        raise EvidenceError("empty evidence")
    fnum, _, v = fields[0]
    if fnum == 1:
        return DuplicateVoteEvidence.decode(pb.as_bytes(v))
    if fnum == 2:
        return LightClientAttackEvidence.decode(pb.as_bytes(v))
    raise EvidenceError(f"unknown evidence tag {fnum}")


def evidence_list_hash(evidence: list) -> bytes:
    return merkle.hash_from_byte_slices([ev.wrapped() for ev in evidence])
