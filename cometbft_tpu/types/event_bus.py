"""EventBus: typed pub/sub facade over the pubsub server.

Behavior parity: reference types/event_bus.go (:34) + types/events.go —
publishes EventNewBlock, EventNewBlockHeader, EventTx, EventVote,
EventValidatorSetUpdates with the standard composite keys
(`tm.event='NewBlock'`, `tx.height`, `tx.hash`) that subscribers and
indexers filter on.
"""

from __future__ import annotations

from ..utils.pubsub import PubSubServer, Subscription

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_TX = "Tx"
EVENT_VOTE = "Vote"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"

TYPE_KEY = "tm.event"


class EventBus:
    def __init__(self):
        self._server = PubSubServer()

    def subscribe(self, client_id: str, query: str) -> Subscription:
        return self._server.subscribe(client_id, query)

    def unsubscribe(self, client_id: str, query: str) -> None:
        self._server.unsubscribe(client_id, query)

    def unsubscribe_all(self, client_id: str) -> None:
        self._server.unsubscribe_all(client_id)

    # ------------------------------------------------------------------
    def publish_new_block(self, block, finalize_resp) -> None:
        h = str(block.header.height)
        events = {TYPE_KEY: [EVENT_NEW_BLOCK], "block.height": [h]}
        _merge_abci_events(events, getattr(finalize_resp, "events", []))
        self._server.publish(
            {"type": EVENT_NEW_BLOCK, "block": block, "result": finalize_resp},
            events,
        )

    def publish_tx(self, height: int, index: int, tx: bytes, result) -> None:
        from ..crypto.keys import tmhash

        events = {
            TYPE_KEY: [EVENT_TX],
            "tx.height": [str(height)],
            "tx.hash": [tmhash(tx).hex().upper()],
        }
        _merge_abci_events(events, getattr(result, "events", []))
        self._server.publish(
            {"type": EVENT_TX, "height": height, "index": index, "tx": tx,
             "result": result},
            events,
        )

    def publish_vote(self, vote) -> None:
        self._server.publish(
            {"type": EVENT_VOTE, "vote": vote}, {TYPE_KEY: [EVENT_VOTE]}
        )

    def publish_validator_set_updates(self, updates) -> None:
        self._server.publish(
            {"type": EVENT_VALIDATOR_SET_UPDATES, "updates": updates},
            {TYPE_KEY: [EVENT_VALIDATOR_SET_UPDATES]},
        )


def _merge_abci_events(events: dict, abci_events) -> None:
    """ABCI events are (type, [(key, value)]) pairs; composite key is
    type.key (reference types/events.go)."""
    for ev in abci_events or []:
        etype = getattr(ev, "type", None) or (ev[0] if isinstance(ev, tuple) else None)
        attrs = getattr(ev, "attributes", None) or (
            ev[1] if isinstance(ev, tuple) else []
        )
        for item in attrs:
            k = item[0] if isinstance(item, tuple) else getattr(item, "key", "")
            v = item[1] if isinstance(item, tuple) else getattr(item, "value", "")
            if isinstance(k, bytes):
                k = k.decode("utf-8", "replace")
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            events.setdefault(f"{etype}.{k}", []).append(str(v))
