from .server import RPCServer
from .client import HTTPClient

__all__ = ["RPCServer", "HTTPClient"]
