"""JSON -> core types: the inverse of the route encoders in routes.py.

The light client's HTTP provider rebuilds Header/Commit/ValidatorSet
from RPC JSON and re-derives every hash itself — nothing from the wire
is trusted until the recomputed hashes and signatures check out
(reference light/provider/http parses rpc types the same way).
"""

from __future__ import annotations

from ..types import Timestamp
from ..types.basic import BlockID, PartSetHeader
from ..types.block import BlockIDFlag, Commit, CommitSig, Consensus, Header
from ..types.validator_set import Validator, ValidatorSet


def _hb(s: str | None) -> bytes:
    return bytes.fromhex(s) if s else b""


def _time_from_json(d: dict) -> Timestamp:
    return Timestamp(int(d.get("seconds", 0)), int(d.get("nanos", 0)))


def block_id_from_json(d: dict) -> BlockID:
    parts = d.get("parts") or {}
    return BlockID(
        hash=_hb(d.get("hash")),
        part_set_header=PartSetHeader(
            int(parts.get("total", 0)), _hb(parts.get("hash"))
        ),
    )


def header_from_json(d: dict) -> Header:
    ver = d.get("version") or {}
    return Header(
        version=Consensus(int(ver.get("block", 0)), int(ver.get("app", 0))),
        chain_id=d.get("chain_id", ""),
        height=int(d.get("height", 0)),
        time=_time_from_json(d.get("time") or {}),
        last_block_id=block_id_from_json(d.get("last_block_id") or {}),
        last_commit_hash=_hb(d.get("last_commit_hash")),
        data_hash=_hb(d.get("data_hash")),
        validators_hash=_hb(d.get("validators_hash")),
        next_validators_hash=_hb(d.get("next_validators_hash")),
        consensus_hash=_hb(d.get("consensus_hash")),
        app_hash=_hb(d.get("app_hash")),
        last_results_hash=_hb(d.get("last_results_hash")),
        evidence_hash=_hb(d.get("evidence_hash")),
        proposer_address=_hb(d.get("proposer_address")),
        da_root=_hb(d.get("da_root")),
    )


def commit_from_json(d: dict) -> Commit:
    return Commit(
        height=int(d.get("height", 0)),
        round=int(d.get("round", 0)),
        block_id=block_id_from_json(d.get("block_id") or {}),
        signatures=[
            CommitSig(
                block_id_flag=BlockIDFlag(int(s.get("block_id_flag", 0))),
                validator_address=_hb(s.get("validator_address")),
                timestamp=_time_from_json(s.get("timestamp") or {}),
                signature=_hb(s.get("signature")),
            )
            for s in d.get("signatures", [])
        ],
    )


def pub_key_from_json(type_tag: str, raw: bytes):
    if "Secp256k1" in type_tag:
        from ..crypto.secp256k1 import Secp256k1PubKey

        return Secp256k1PubKey(raw)
    if "Sr25519" in type_tag:
        from ..crypto.sr25519 import Sr25519PubKey

        return Sr25519PubKey(raw)
    from ..crypto.ed25519 import Ed25519PubKey

    return Ed25519PubKey(raw)


def validator_set_from_json(d: dict) -> ValidatorSet:
    vals = []
    for v in d.get("validators", []):
        pk = pub_key_from_json(
            v.get("pub_key_type", "tendermint/PubKeyEd25519"),
            _hb(v.get("pub_key")),
        )
        vals.append(
            Validator(
                address=_hb(v.get("address")),
                pub_key=pk,
                voting_power=int(v.get("voting_power", 0)),
                proposer_priority=int(v.get("proposer_priority", 0)),
            )
        )
    return ValidatorSet(vals)
