"""RPC route handlers (reference rpc/core/routes.go + rpc/core/*.go).

Every handler takes the Env (handles to the node's stores and services,
reference rpc/core/env.go) and JSON params, returning JSON-able dicts.
Bytes are hex-encoded strings; blocks/commits are rendered structurally.
"""

from __future__ import annotations

import base64

from ..crypto import merkle
from ..crypto.keys import tmhash
from ..mempool.mempool import ErrMempoolFull, ErrTxInCache, ErrTxTooLarge
from ..utils import txlife as _txlife


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class Env:
    """reference rpc/core/env.go Environment."""

    def __init__(self, *, block_store=None, state_store=None, consensus=None,
                 mempool=None, switch=None, event_bus=None, tx_indexer=None,
                 block_indexer=None, genesis_doc=None, app_conns=None,
                 node_info=None, evidence_pool=None, pex_reactor=None,
                 consensus_reactor=None, light_serve=None, da_serve=None,
                 replication_feed=None, replication_replica=None):
        self.evidence_pool = evidence_pool
        self.pex_reactor = pex_reactor
        self.consensus_reactor = consensus_reactor
        self.light_serve = light_serve
        self.da_serve = da_serve
        self.replication_feed = replication_feed
        self.replication_replica = replication_replica
        self.block_store = block_store
        self.state_store = state_store
        self.consensus = consensus
        self.mempool = mempool
        self.switch = switch
        self.event_bus = event_bus
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.genesis_doc = genesis_doc
        self.app_conns = app_conns
        self.node_info = node_info


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _hx(b: bytes | None) -> str:
    return (b or b"").hex().upper()


def _block_id_json(bid) -> dict:
    return {
        "hash": _hx(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hx(bid.part_set_header.hash),
        },
    }


def _header_json(h) -> dict:
    # Full fidelity: every hashed field travels (version and the part-set
    # half of last_block_id are part of the header hash), so a client can
    # rebuild the Header and recompute its hash (rpc/codec.py is the
    # inverse; reference light/provider/http relies on the same property).
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": {"seconds": h.time.seconds, "nanos": h.time.nanos},
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hx(h.last_commit_hash),
        "data_hash": _hx(h.data_hash),
        "validators_hash": _hx(h.validators_hash),
        "next_validators_hash": _hx(h.next_validators_hash),
        "consensus_hash": _hx(h.consensus_hash),
        "app_hash": _hx(h.app_hash),
        "last_results_hash": _hx(h.last_results_hash),
        "evidence_hash": _hx(h.evidence_hash),
        "proposer_address": _hx(h.proposer_address),
        "da_root": _hx(h.da_root),
    }


def _commit_json(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": int(cs.block_id_flag),
                "validator_address": _hx(cs.validator_address),
                "timestamp": {"seconds": cs.timestamp.seconds,
                              "nanos": cs.timestamp.nanos},
                "signature": _hx(cs.signature),
            }
            for cs in c.signatures
        ],
    }


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [_hx(tx) for tx in b.data.txs]},
        "last_commit": _commit_json(b.last_commit),
    }


# ------------------------------------------------------------------ routes
def health(env, params):
    return {}


def dump_trace(env, params):
    """Tail of the node's trace sink (observability debug aid).

    Returns the last `n` JSONL records (default 100, hard cap 1000 so a
    large sink can't balloon the RPC response) written by utils.trace;
    empty when tracing is disabled. `limit` is accepted as an alias for
    `n` (cosmos-style paging name). Optional filters: `name` keeps
    records whose span name contains the substring (e.g. ``name=p2p.``
    for the wire hooks), `kind` requires an exact kind ("span" or
    "event"), `tenant` keeps records touching that tenant (a record's
    ``tenant`` field, or membership in its comma-separated ``tenants``
    list — the shared-scheduler coalesce spans carry the latter). With
    filters, the last `n` MATCHING records out of the newest 1000 are
    returned.
    """
    from ..utils import trace

    n = int(params.get("limit", params.get("n", 100)) or 100)
    n = max(1, min(n, 1000))
    name = str(params.get("name", "") or "")
    kind = str(params.get("kind", "") or "")
    tenant = str(params.get("tenant", "") or "")

    def _tenant_match(r):
        if not tenant:
            return True
        if str(r.get("tenant", "")) == tenant:
            return True
        ts = r.get("tenants", "")
        if isinstance(ts, str):
            return tenant in ts.split(",")
        return isinstance(ts, (list, tuple)) and tenant in ts

    if not trace.enabled:
        records = []
    elif name or kind or tenant:
        records = [
            r for r in trace.tail(1000)
            if (not name or name in str(r.get("name", "")))
            and (not kind or r.get("kind") == kind)
            and _tenant_match(r)
        ][-n:]
    else:
        records = trace.tail(n)
    return {
        "enabled": trace.enabled,
        "path": trace.path() or "",
        "records": records,
    }


def status(env, params):
    bs = env.block_store
    latest = bs.height() if bs else 0
    header = None
    if bs and latest:
        blk = bs.load_block(latest)
        header = blk.header if blk else None
    return {
        "node_info": {
            "id": env.node_info.node_id if env.node_info else "",
            "network": env.genesis_doc.chain_id if env.genesis_doc else "",
            "moniker": env.node_info.moniker if env.node_info else "",
            "version": env.node_info.version if env.node_info else "",
        },
        "sync_info": {
            "latest_block_height": str(latest),
            "latest_block_hash": _hx(header.hash() if header else b""),
            "latest_app_hash": _hx(
                env.consensus.sm_state.app_hash if env.consensus else b""
            ),
            "catching_up": False,
        },
        "validator_info": {
            "address": _hx(
                env.consensus.privval.address()
                if env.consensus and env.consensus.privval else b""
            ),
        },
    }


def abci_info(env, params):
    info = env.app_conns.query.info()
    return {
        "response": {
            "data": info.data,
            "version": info.version,
            "last_block_height": str(info.last_block_height),
            "last_block_app_hash": _hx(info.last_block_app_hash),
        }
    }


def abci_query(env, params):
    path = params.get("path", "")
    data = bytes.fromhex(params.get("data", ""))
    height = int(params.get("height", 0))
    r = env.app_conns.query.query(path, data, height)
    return {
        "response": {
            "code": r.code,
            "key": _hx(r.key),
            "value": _hx(r.value),
            "height": str(r.height),
            "log": r.log,
        }
    }


def _get_height(env, params, default_latest=True):
    h = params.get("height")
    if h is None:
        if not default_latest:
            raise RPCError(-32602, "height required")
        return env.block_store.height()
    return int(h)


def block(env, params):
    h = _get_height(env, params)
    blk = env.block_store.load_block(h)
    if blk is None:
        raise RPCError(-32603, f"no block at height {h}")
    return {"block_id": {"hash": _hx(blk.hash())}, "block": _block_json(blk)}


def block_by_hash(env, params):
    want = bytes.fromhex(params.get("hash", ""))
    blk = env.block_store.load_block_by_hash(want)
    if blk is not None:
        return {"block_id": {"hash": _hx(want)}, "block": _block_json(blk)}
    raise RPCError(-32603, "block not found")


def header(env, params):
    h = _get_height(env, params)
    blk = env.block_store.load_block(h)
    if blk is None:
        raise RPCError(-32603, f"no block at height {h}")
    return {"header": _header_json(blk.header)}


def header_by_hash(env, params):
    """Header lookup by block hash (reference rpc/core/blocks.go:108
    HeaderByHash; an absent block returns an empty result, not an
    error, matching the reference)."""
    want = bytes.fromhex(params.get("hash", ""))
    blk = env.block_store.load_block_by_hash(want)
    if blk is None:
        return {"header": None}
    return {"header": _header_json(blk.header)}


def blockchain(env, params):
    """BlockchainInfo: block metas for [min_height, max_height], newest
    first, at most 20 (reference rpc/core/blocks.go:27 BlockchainInfo +
    filterMinMax :59 — zero means "default", min is clamped to the store
    base so pruned heights degrade gracefully)."""
    limit = 20
    bs = env.block_store
    base, height = bs.base(), bs.height()
    try:
        mn = int(params.get("min_height", 0) or 0)
        mx = int(params.get("max_height", 0) or 0)
    except (TypeError, ValueError):
        raise RPCError(-32602, "min_height/max_height must be integers")
    if mn < 0 or mx < 0:
        raise RPCError(-32602, "heights must be non-negative")
    mn = mn or 1
    mx = min(height, mx or height)
    mn = max(base, mn, mx - limit + 1)
    if mn > mx:
        raise RPCError(
            -32602, f"min height {mn} can't be greater than max height {mx}"
        )
    metas = []
    for h in range(mx, mn - 1, -1):
        meta = bs.load_block_meta(h)
        if meta is None:
            continue
        blk, size = meta
        metas.append({
            "block_id": {"hash": _hx(blk.hash())},
            "block_size": str(size),
            "header": _header_json(blk.header),
            "num_txs": str(len(blk.data.txs)),
        })
    return {"last_height": str(height), "block_metas": metas}


def commit(env, params):
    h = _get_height(env, params)
    blk = env.block_store.load_block(h)
    c = env.block_store.load_block_commit(h) or env.block_store.load_seen_commit(h)
    if blk is None or c is None:
        raise RPCError(-32603, f"no commit at height {h}")
    return {
        "signed_header": {
            "header": _header_json(blk.header),
            "commit": _commit_json(c),
        },
        "canonical": env.block_store.load_block_commit(h) is not None,
    }


def block_results(env, params):
    h = _get_height(env, params)
    if env.state_store is None:
        raise RPCError(-32603, "state store unavailable")
    rhash = env.state_store.load_finalize_response(h)
    out = {"height": str(h), "results_hash": _hx(rhash or b"")}
    raw = env.state_store.load_abci_responses(h)
    if raw:
        from ..abci import wire as W

        resp = W.dec_finalize_resp(raw)
        out["txs_results"] = [
            {
                "code": tr.code,
                "data": _hx(tr.data),
                "log": tr.log,
                "gas_wanted": str(tr.gas_wanted),
                "gas_used": str(tr.gas_used),
            }
            for tr in resp.tx_results
        ]
        out["validator_updates"] = [
            {
                "pub_key": _hx(vu.pub_key_bytes),
                "pub_key_type": vu.pub_key_type,
                "power": str(vu.power),
            }
            for vu in resp.validator_updates
        ]
        out["app_hash"] = _hx(resp.app_hash)
    return out


def validators(env, params):
    h = _get_height(env, params)
    vals = env.state_store.load_validators(h) if env.state_store else None
    if vals is None:
        raise RPCError(-32603, f"no validators at height {h}")
    return {
        "block_height": str(h),
        "validators": [
            {
                "address": _hx(v.address),
                "pub_key": _hx(v.pub_key.bytes()),
                "pub_key_type": v.pub_key.type_tag(),
                "voting_power": str(v.voting_power),
                "proposer_priority": str(v.proposer_priority),
            }
            for v in vals.validators
        ],
        "count": str(len(vals)),
        "total": str(len(vals)),
    }


def genesis(env, params):
    import json as _json

    return {"genesis": _json.loads(env.genesis_doc.to_json())}


def net_info(env, params):
    peers = env.switch.peers() if env.switch else []
    return {
        "listening": True,
        "n_peers": str(len(peers)),
        "peers": [
            {"node_info": {"id": p.id, "moniker": p.node_info.moniker}}
            for p in peers
        ],
    }


def _rs_lock(cs):
    """The consensus round-state mutex (consensus.state rs_mutex): the
    consensus thread holds it across every _process, so acquiring it
    here yields a snapshot that cannot mix two heights' fields. Stubbed
    consensus objects (tests) without the mutex degrade to lock-free."""
    lock = getattr(cs, "rs_mutex", None)
    if lock is None:
        import contextlib

        return contextlib.nullcontext()
    return lock


def consensus_state(env, params):
    cs = env.consensus
    with _rs_lock(cs):
        return {
            "round_state": {
                "height": str(cs.height),
                "round": cs.round,
                "step": int(cs.step),
                "locked_round": cs.locked_round,
                "valid_round": cs.valid_round,
            }
        }


def _vote_set_json(vs) -> dict | None:
    if vs is None:
        return None
    ba = vs.bit_array()
    maj, ok = vs.two_thirds_majority()
    return {
        "votes_bit_array": "".join(
            "x" if ba.get(i) else "_" for i in range(ba.size())
        ),
        "count": vs.size(),
        "two_thirds_majority": _block_id_json(maj) if ok and maj else None,
    }


def dump_consensus_state(env, params):
    """Full round-state dump plus per-peer consensus states (reference
    rpc/core/consensus.go:56 DumpConsensusState). The concise summary
    lives at consensus_state; this one carries the vote bitmaps and the
    reactor's per-peer (height, round, step) view for operators
    debugging a stall.

    Consistency: the consensus thread mutates the round state
    concurrently, and a naive field-by-field read could mix heights
    (e.g. height N's round with height N+1's locked block). The gather
    runs under cs.rs_mutex — held by the consensus thread across each
    _process transition — so the snapshot is a single consistent round
    state, replacing the old sample-and-retry heuristic (which could
    still return a torn snapshot after its retry budget)."""
    cs = env.consensus
    with _rs_lock(cs):
        votes = []
        hvs = cs.votes
        for r in sorted(hvs._sets):
            votes.append({
                "round": r,
                "prevotes": _vote_set_json(hvs.prevotes(r)),
                "precommits": _vote_set_json(hvs.precommits(r)),
            })
        rs = {
            "height": str(cs.height),
            "round": cs.round,
            "step": int(cs.step),
            "locked_round": cs.locked_round,
            "locked_block_hash": _hx(
                cs.locked_block.hash()
                if getattr(cs, "locked_block", None) else b""
            ),
            "valid_round": cs.valid_round,
            "valid_block_hash": _hx(
                cs.valid_block.hash()
                if getattr(cs, "valid_block", None) else b""
            ),
            "proposal": cs.proposal is not None,
            "height_vote_set": votes,
        }
    peers = []
    reactor = env.consensus_reactor
    if reactor is not None:
        for ps in list(reactor._peers.values()):
            peers.append({
                "node_address": ps.peer.id,
                "peer_state": {
                    "height": str(ps.height),
                    "round": ps.round,
                    "step": ps.step,
                    "last_commit_round": ps.last_commit_round,
                    "proposal_seen": ps.proposal_seen,
                },
            })
    return {"round_state": rs, "peers": peers}


def check_tx(env, params):
    """Run CheckTx against the app without touching the mempool
    (reference rpc/core/mempool.go:188 CheckTx)."""
    tx = bytes.fromhex(params["tx"])
    r = env.app_conns.mempool.check_tx(tx)
    return {
        "code": r.code,
        "data": _hx(r.data),
        "log": r.log,
        "gas_wanted": str(r.gas_wanted),
    }


def consensus_params(env, params):
    p = env.consensus.sm_state.consensus_params
    return {
        "consensus_params": {
            "block": {"max_bytes": str(p.block.max_bytes),
                      "max_gas": str(p.block.max_gas)},
            "evidence": {
                "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
                "max_bytes": str(p.evidence.max_bytes),
            },
        }
    }


def broadcast_tx_sync(env, params):
    tx = bytes.fromhex(params["tx"])
    if _txlife.enabled:
        _txlife.track(tx, "arrival", src="rpc")
    try:
        env.mempool.check_tx(tx)
        code, log = 0, ""
    except (ErrTxInCache, ErrMempoolFull, ErrTxTooLarge, ValueError) as e:
        code, log = 1, str(e)
    return {"code": code, "log": log, "hash": _hx(tmhash(tx))}


def broadcast_tx_async(env, params):
    tx = bytes.fromhex(params["tx"])
    if _txlife.enabled:
        _txlife.track(tx, "arrival", src="rpc")
    submit = getattr(env.mempool, "submit_tx", None)
    if submit is not None:
        # truly async: enqueue into the admission pipeline and return
        # without waiting for the window to drain
        fut = submit(tx)
        fut.add_done_callback(lambda f: f.exception())  # fire and forget
    else:
        try:
            env.mempool.check_tx(tx)
        except Exception:  # noqa: BLE001 — async: fire and forget
            pass
    return {"code": 0, "hash": _hx(tmhash(tx))}


def broadcast_tx_commit(env, params, timeout_s: float = 30.0):
    """Submit and wait for the tx to land in a block (reference
    rpc/core/mempool.go BroadcastTxCommit via event subscription)."""
    tx = bytes.fromhex(params["tx"])
    if _txlife.enabled:
        _txlife.track(tx, "arrival", src="rpc")
    sub = env.event_bus.subscribe(
        f"btc-{tmhash(tx).hex()[:8]}", f"tm.event = 'Tx' AND tx.hash = '{_hx(tmhash(tx))}'"
    )
    try:
        from ..utils.pubsub import SubscriptionCancelled

        env.mempool.check_tx(tx)
        try:
            msg = sub.next(timeout=timeout_s)
        except SubscriptionCancelled:
            msg = None
        if msg is None:
            raise RPCError(-32603, "timed out waiting for tx commit")
        return {
            "check_tx": {"code": 0},
            "tx_result": {"code": getattr(msg.data["result"], "code", 0)},
            "hash": _hx(tmhash(tx)),
            "height": str(msg.data["height"]),
        }
    except (ErrTxInCache, ErrMempoolFull, ErrTxTooLarge, ValueError) as e:
        return {"check_tx": {"code": 1, "log": str(e)}, "hash": _hx(tmhash(tx))}
    finally:
        env.event_bus.unsubscribe_all(f"btc-{tmhash(tx).hex()[:8]}")


def unconfirmed_txs(env, params):
    limit = int(params.get("limit", 30))
    txs = env.mempool.reap_max_txs(limit) if env.mempool else []
    return {
        "n_txs": str(len(txs)),
        "total": str(env.mempool.size() if env.mempool else 0),
        "total_bytes": str(env.mempool.total_bytes() if env.mempool else 0),
        "txs": [_hx(t) for t in txs],
    }


def num_unconfirmed_txs(env, params):
    return {
        "n_txs": str(env.mempool.size() if env.mempool else 0),
        "total_bytes": str(env.mempool.total_bytes() if env.mempool else 0),
    }


def _as_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "t", "yes")


def _paginate(items, params, order_key=None):
    """page/per_page/order_by handling shared by the search routes
    (reference rpc/core/tx.go TxSearch + rpc/core/env.go validatePage:
    per_page defaults to 30 capped at 100; page is 1-based; out-of-range
    pages are an error; order_by is "asc" (default) or "desc")."""
    order = str(params.get("order_by", "asc") or "asc").lower()
    if order not in ("asc", "desc"):
        raise RPCError(-32602, f"invalid order_by {order!r}")
    if order_key is not None:
        items = sorted(items, key=order_key, reverse=(order == "desc"))
    elif order == "desc":
        items = list(reversed(items))
    try:
        per_page = min(max(int(params.get("per_page", 30)), 1), 100)
        page = int(params.get("page", 1))
    except (TypeError, ValueError):
        raise RPCError(-32602, "page/per_page must be integers")
    total = len(items)
    pages = max((total + per_page - 1) // per_page, 1)
    if page < 1 or page > pages:
        raise RPCError(-32602, f"page {page} out of range [1, {pages}]")
    lo = (page - 1) * per_page
    return items[lo : lo + per_page], total


def _tx_proof(env, height: int, index: int, _cache=None):
    """Merkle inclusion proof of tx `index` in block `height`'s data
    hash (reference types/tx.go:79 Txs.Proof). `_cache` (dict keyed by
    height) lets tx_search build each block's tree once per page instead
    of once per result."""
    entry = _cache.get(height) if _cache is not None else None
    if entry is None:
        blk = env.block_store.load_block(height)
        if blk is None:
            return None
        root, proofs = merkle.proofs_from_byte_slices(
            [tmhash(t) for t in blk.data.txs]
        )
        entry = (blk.data.txs, root, proofs)
        if _cache is not None:
            _cache[height] = entry
    txs, root, proofs = entry
    if index >= len(proofs):
        return None
    p = proofs[index]
    return {
        "root_hash": _hx(root),
        "data": _hx(txs[index]),
        "proof": {
            "total": str(p.total),
            "index": str(p.index),
            "leaf_hash": _b64(p.leaf_hash),
            "aunts": [_b64(a) for a in p.aunts],
        },
    }


def tx(env, params):
    h = bytes.fromhex(params["hash"])
    rec = env.tx_indexer.get(h) if env.tx_indexer else None
    if rec is None:
        raise RPCError(-32603, "tx not found")
    out = {
        "hash": _hx(h),
        "height": str(rec["height"]),
        "index": rec["index"],
        "tx_result": {"code": rec["code"], "data": _hx(rec["data"])},
        "tx": _hx(rec["tx"]),
    }
    if _as_bool(params.get("prove", False)):
        proof = _tx_proof(env, rec["height"], rec["index"])
        if proof is not None:
            out["proof"] = proof
    return out


def tx_search(env, params):
    query = params.get("query", "")
    recs = env.tx_indexer.search(query) if env.tx_indexer else []
    page, total = _paginate(
        recs, params, order_key=lambda r: (r["height"], r["index"])
    )
    prove = _as_bool(params.get("prove", False))
    txs = []
    proof_cache: dict = {}
    for r in page:
        item = {
            "hash": _hx(tmhash(r["tx"])),
            "height": str(r["height"]),
            "index": r["index"],
            "tx_result": {"code": r["code"]},
        }
        if prove:
            proof = _tx_proof(env, r["height"], r["index"], proof_cache)
            if proof is not None:
                item["proof"] = proof
        txs.append(item)
    return {"txs": txs, "total_count": str(total)}


def block_search(env, params):
    query = params.get("query", "")
    heights = env.block_indexer.search(query) if env.block_indexer else []
    page, total = _paginate(heights, params, order_key=lambda h: h)
    out = []
    for h in page:
        blk = env.block_store.load_block(h)
        if blk is not None:
            out.append({"block_id": {"hash": _hx(blk.hash())},
                        "block": _block_json(blk)})
    return {"blocks": out, "total_count": str(total)}


def broadcast_evidence(env, params):
    """Submit proto-encoded (hex) evidence to the pool (reference
    rpc/core/evidence.go BroadcastEvidence); the evidence reactor then
    gossips it to peers."""
    from ..types.evidence import EvidenceError, decode_evidence

    raw = params.get("evidence", "")
    try:
        ev = decode_evidence(bytes.fromhex(raw))
    except Exception as e:  # noqa: BLE001 — caller sent garbage
        raise RPCError(-32602, f"invalid evidence: {e}") from e
    if env.evidence_pool is None:
        raise RPCError(-32603, "evidence pool unavailable")
    try:
        env.evidence_pool.add_evidence(ev)
    except EvidenceError as e:
        raise RPCError(-32603, f"evidence rejected: {e}") from e
    return {"hash": _hx(ev.hash())}


def genesis_chunked(env, params):
    """Genesis split into base64 chunks for large documents (reference
    rpc/core/net.go GenesisChunked)."""
    import base64

    chunk_size = 16 * 1024 * 1024
    doc = env.genesis_doc.to_json().encode()
    chunks = [
        doc[i : i + chunk_size] for i in range(0, len(doc), chunk_size)
    ] or [b""]
    idx = int(params.get("chunk", 0))
    if not 0 <= idx < len(chunks):
        raise RPCError(-32602, f"chunk {idx} out of range [0, {len(chunks)})")
    return {
        "chunk": str(idx),
        "total": str(len(chunks)),
        "data": base64.b64encode(chunks[idx]).decode(),
    }


def _dial(env, params):
    if env.switch is None:
        raise RPCError(-32603, "p2p switch unavailable")
    peers = params.get("peers") or params.get("seeds") or []
    dialed = []
    for addr in peers:
        try:
            host, _, port = addr.rpartition("@")[-1].rpartition(":")
            env.switch.dial_peer(host, int(port))
            dialed.append(addr)
        except Exception:  # noqa: BLE001 — unreachable peers are skipped
            continue
    return {"log": f"dialed {len(dialed)}/{len(peers)}"}


def unsafe_dial_seeds(env, params):
    return _dial(env, params)


def unsafe_dial_peers(env, params):
    # the reference's `persistent` flag is not supported: this switch
    # has no redial list, so accepting the flag would silently lie
    return _dial(env, params)


unsafe_dial_peers.__doc__ = unsafe_dial_seeds.__doc__ = (
    "Unsafe operator route: dial the given host:port peers now "
    "(reference rpc/core/net.go UnsafeDialSeeds/UnsafeDialPeers)."
)


def unsafe_flush_mempool(env, params):
    """Drop every transaction from the mempool (reference
    rpc/core/dev.go:9 UnsafeFlushMempool)."""
    if env.mempool is None:
        raise RPCError(-32603, "mempool unavailable")
    env.mempool.flush()
    return {}


def _light_serve(env):
    if env.light_serve is None:
        raise RPCError(-32603, "light serving surface disabled "
                               "(config [light] serve = false)")
    return env.light_serve


def _validator_set_json(vals) -> dict:
    return {
        "validators": [
            {
                "address": _hx(v.address),
                "pub_key": _hx(v.pub_key.bytes()),
                "pub_key_type": v.pub_key.type_tag(),
                "voting_power": str(v.voting_power),
                "proposer_priority": str(v.proposer_priority),
            }
            for v in vals.validators
        ],
    }


def _light_block_json(lb) -> dict:
    return {
        "signed_header": {
            "header": _header_json(lb.signed_header.header),
            "commit": _commit_json(lb.signed_header.commit),
        },
        "validator_set": _validator_set_json(lb.validators),
    }


def light_status(env, params):
    """Serving-surface introspection: accumulator root/size, subscriber
    count, cache hit/miss totals, per-height verify amortization."""
    srv = _light_serve(env)
    st = srv.stats()
    st["base_height"] = str(st["base_height"] or 0)
    st["heights_served"] = str(st["heights_served"])
    return st


def light_mmr_proof(env, params):
    """MMR ancestry proof for one committed height against the current
    accumulator snapshot; the client re-binds it to a header hash it
    trusts (see light.client.verify_ancestry)."""
    srv = _light_serve(env)
    try:
        h = int(params.get("height", 0))
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, f"invalid height: {params.get('height')}") from e
    try:
        proof = srv.ancestry_proof(h)
    except IndexError as e:
        raise RPCError(-32603, str(e)) from e
    size, root = srv.mmr_snapshot()
    return {
        "height": str(h),
        "base_height": str(srv.base_height),
        "leaf_index": str(proof.leaf_index),
        "mmr_size": str(size),
        "mmr_root": _hx(root),
        "proof": proof.encode().hex(),
        "proof_bytes": proof.num_bytes(),
    }


def light_bisect(env, params):
    """Server-side skipping verification: the minimal pivot chain from a
    client's trusted height to the target under validator-set churn.
    Every pivot's commit is verified through the shared cache, so the
    per-height batch verify is paid once regardless of how many clients
    ask."""
    srv = _light_serve(env)
    try:
        trusted = int(params.get("trusted_height", 0))
        target = int(params.get("height", 0))
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, "invalid trusted_height/height") from e
    try:
        pivots = srv.bisect(trusted, target)
    except (ValueError, KeyError) as e:
        raise RPCError(-32603, str(e)) from e
    return {
        "trusted_height": str(trusted),
        "target_height": str(target),
        "pivots": [_light_block_json(lb) for lb in pivots],
        "pivot_heights": [str(lb.height) for lb in pivots],
    }


def _da_serve(env):
    if env.da_serve is None:
        raise RPCError(-32603, "data-availability sampling disabled "
                               "(config [da] enabled = false)")
    return env.da_serve


def da_status(env, params):
    """DA serving-surface introspection: shard geometry, retained height
    window, blocks encoded, samples served, withholding-test hits."""
    srv = _da_serve(env)
    st = srv.stats()
    st["min_height"] = str(st["min_height"] or 0)
    st["max_height"] = str(st["max_height"] or 0)
    return st


def da_sample(env, params):
    """One extended-chunk opening: the chunk at `index` of `height`'s
    erasure-coded payload plus its Merkle path to the header's da_root
    commitment. Sampling clients (da/sampler.py) call this with seeded
    random indices and verify each opening against the header."""
    srv = _da_serve(env)
    try:
        h = int(params.get("height", 0))
        idx = int(params.get("index", -1))
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, "invalid height/index") from e
    got = srv.sample(h, idx)
    if got is None:
        raise RPCError(-32603, f"no sample for height {h} index {idx}")
    chunk, proof, com = got
    return {
        "height": str(h),
        "index": idx,
        "chunk": chunk.hex(),
        "proof": {
            "total": str(proof.total),
            "index": str(proof.index),
            "leaf_hash": _b64(proof.leaf_hash),
            "aunts": [_b64(a) for a in proof.aunts],
        },
        "commitment": {
            "shards": com.n,
            "data_shards": com.k,
            "payload_len": str(com.payload_len),
            "chunks_root": _hx(com.chunks_root),
            "da_root": _hx(com.root()),
        },
    }


def da_pc_commitments(env, params):
    """The 2D polynomial-commitment track's per-height commitment list:
    grid geometry plus one compressed KZG commitment per column. A
    sampling client downloads this once per height, runs the
    parity-linearity (lying-encoder) check, then verifies constant-size
    multiproof openings from da_pc_sample against it."""
    srv = _da_serve(env)
    try:
        h = int(params.get("height", 0))
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, "invalid height") from e
    com = srv.pc_commitments(h)
    if com is None:
        raise RPCError(-32603, f"no pc commitment for height {h}")
    return {
        "height": str(h),
        "rows": com.n_r,
        "data_rows": com.k_r,
        "cols": com.n_c,
        "data_cols": com.k_c,
        "payload_len": str(com.payload_len),
        "commitments": [c.hex() for c in com.commitments],
        "pc_root": _hx(com.root()),
    }


def da_pc_sample(env, params):
    """One multiproof sample: every requested column opened at `row`
    by s 32-byte evaluations plus ONE 48-byte aggregated KZG proof
    (da/pc.py). `cols` is comma-separated column indices."""
    srv = _da_serve(env)
    try:
        h = int(params.get("height", 0))
        row = int(params.get("row", -1))
        cols = [int(c) for c in str(params.get("cols", "")).split(",")]
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, "invalid height/row/cols") from e
    got = srv.pc_sample(h, row, cols)
    if got is None:
        raise RPCError(
            -32603, f"no pc sample for height {h} row {row}")
    ys, proof = got
    return {
        "height": str(h),
        "row": row,
        "cols": cols,
        "ys": ["%064x" % y for y in ys],
        "proof": proof.hex(),
    }


def _replication_feed(env):
    feed = getattr(env, "replication_feed", None)
    if feed is None:
        raise RPCError(-32603, "replication feed disabled "
                               "(config [replication] serve = false)")
    return feed


def replication_status(env, params):
    """Replication-plane introspection. On a core node: feed tip,
    retention window and subscriber count. On a serving replica: apply
    cursor, lag, bootstrap state and forwarding counters."""
    feed = getattr(env, "replication_feed", None)
    if feed is not None:
        st = feed.status()
        st["role"] = "core"
        return st
    rep = getattr(env, "replication_replica", None)
    if rep is not None:
        st = rep.status()
        st["role"] = "replica"
        return st
    raise RPCError(-32603, "replication disabled")


def replication_snapshot(env, params):
    """Bootstrap snapshot metadata at the current feed tip (statesync
    Snapshot shape: height/format/chunks/hash + metadata). A joining
    replica fetches this, then pulls chunks, verifies the hash, and
    restores before tailing the feed."""
    feed = _replication_feed(env)
    try:
        meta, _chunks = feed.snapshot()
    except RuntimeError as e:
        raise RPCError(-32603, str(e)) from e
    return {
        "height": str(meta.height),
        "format": meta.format,
        "chunks": meta.chunks,
        "hash": meta.hash.hex(),
        "metadata": _b64(meta.metadata),
    }


def replication_snapshot_chunk(env, params):
    """One chunk of the bootstrap snapshot blob (b64). `height` pins the
    snapshot the caller negotiated — a chunk from a newer rebuild must
    not be silently spliced into an older restore."""
    feed = _replication_feed(env)
    try:
        idx = int(params.get("chunk", -1))
        want_h = int(params.get("height", 0))
    except (TypeError, ValueError) as e:
        raise RPCError(-32602, "invalid chunk/height") from e
    try:
        meta, chunks = feed.snapshot()
    except RuntimeError as e:
        raise RPCError(-32603, str(e)) from e
    if want_h and meta.height != want_h:
        raise RPCError(-32603,
                       f"snapshot moved: have {meta.height}, want {want_h}")
    if not (0 <= idx < len(chunks)):
        raise RPCError(-32602, f"chunk {idx} out of range [0, {len(chunks)})")
    return {"height": str(meta.height), "chunk": idx,
            "data": _b64(chunks[idx])}


# unsafe operator routes, served only when rpc.unsafe is enabled
# (reference rpc/core/routes.go AddUnsafeRoutes gated by config Unsafe)
UNSAFE_ROUTES = {
    "unsafe_dial_seeds": unsafe_dial_seeds,
    "unsafe_dial_peers": unsafe_dial_peers,
    "unsafe_flush_mempool": unsafe_flush_mempool,
}

ROUTES = {
    "health": health,
    "dump_trace": dump_trace,
    "status": status,
    "broadcast_evidence": broadcast_evidence,
    "genesis_chunked": genesis_chunked,
    "abci_info": abci_info,
    "abci_query": abci_query,
    "block": block,
    "block_by_hash": block_by_hash,
    "blockchain": blockchain,
    "header": header,
    "header_by_hash": header_by_hash,
    "commit": commit,
    "check_tx": check_tx,
    "dump_consensus_state": dump_consensus_state,
    "block_results": block_results,
    "validators": validators,
    "genesis": genesis,
    "net_info": net_info,
    "consensus_state": consensus_state,
    "consensus_params": consensus_params,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_async": broadcast_tx_async,
    "broadcast_tx_commit": broadcast_tx_commit,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "tx": tx,
    "tx_search": tx_search,
    "block_search": block_search,
    "light_status": light_status,
    "light_mmr_proof": light_mmr_proof,
    "light_bisect": light_bisect,
    "da_status": da_status,
    "da_sample": da_sample,
    "da_pc_commitments": da_pc_commitments,
    "da_pc_sample": da_pc_sample,
    "replication_status": replication_status,
    "replication_snapshot": replication_snapshot,
    "replication_snapshot_chunk": replication_snapshot_chunk,
}

# The stateless serving replica exposes exactly the consensus-free
# surfaces: light streaming/proofs/bisection, DA sampling, admission
# forwarding, and introspection. Everything else (blocks, consensus
# state, indexers) needs stores a replica deliberately does not have.
REPLICA_ROUTES = {
    name: ROUTES[name]
    for name in (
        "health",
        "dump_trace",
        "light_status",
        "light_mmr_proof",
        "light_bisect",
        "da_status",
        "da_sample",
        "da_pc_commitments",
        "da_pc_sample",
        "broadcast_tx_sync",
        "broadcast_tx_async",
        "replication_status",
    )
}
