"""RPC clients (reference rpc/client/http + rpc/client/local)."""

from __future__ import annotations

import json
import urllib.request


class HTTPClient:
    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, params: dict | None = None,
             timeout: float | None = None):
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id, "method": method,
            "params": params or {},
        }).encode()
        req = urllib.request.Request(
            self.base_url, data=body,
            headers={"Content-Type": "application/json"},
        )
        t = self.timeout if timeout is None else timeout
        with urllib.request.urlopen(req, timeout=t) as resp:
            out = json.loads(resp.read())
        if "error" in out:
            raise RuntimeError(f"rpc error: {out['error']}")
        return out["result"]

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(**params):
            return self.call(name, params)

        return method


class LocalClient:
    """In-process client over the same route table
    (reference rpc/client/local)."""

    def __init__(self, env):
        from .routes import ROUTES

        self._env = env
        self._routes = ROUTES

    def call(self, method: str, params: dict | None = None):
        fn = self._routes[method]
        return fn(self._env, params or {})

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(**params):
            return self.call(name, params)

        return method
