"""JSON-RPC 2.0 server: HTTP POST + GET-URI + WebSocket subscriptions.

Behavior parity: reference rpc/jsonrpc/server — http_json_handler.go
(POST body {jsonrpc, id, method, params}), uri handler (GET
/method?param=value), and ws_handler.go (subscribe/unsubscribe streaming
events). The WebSocket implementation is a minimal RFC 6455 server
(text frames, no extensions) on top of the same threading HTTP server.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from .routes import ROUTES, RPCError

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _ws_accept(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _WS_MAGIC).encode()).digest()
    ).decode()


def _ws_send_text(wfile, data: str) -> None:
    payload = data.encode()
    header = b"\x81"  # FIN + text
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < 65536:
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    wfile.write(header + payload)
    wfile.flush()


def _ws_read_frame(rfile) -> tuple[int, bytes] | None:
    head = rfile.read(2)
    if len(head) < 2:
        return None
    opcode = head[0] & 0x0F
    masked = head[1] & 0x80
    n = head[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", rfile.read(2))[0]
    elif n == 127:
        n = struct.unpack(">Q", rfile.read(8))[0]
    mask = rfile.read(4) if masked else b"\x00" * 4
    data = bytearray(rfile.read(n))
    for i in range(len(data)):
        data[i] ^= mask[i % 4]
    return opcode, bytes(data)


class RPCServer:
    def __init__(self, env, host: str = "127.0.0.1", port: int = 0,
                 routes=None):
        self.env = env
        self.routes = ROUTES if routes is None else routes
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            # ---- JSON-RPC over POST --------------------------------
            def do_POST(self):
                try:
                    ln = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(ln) or b"{}")
                except Exception:
                    return self._respond_err(None, -32700, "parse error")
                self._dispatch(req)

            # ---- URI routes + websocket over GET -------------------
            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    return self._websocket()
                u = urlparse(self.path)
                method = u.path.strip("/")
                params = dict(parse_qsl(u.query))
                if method == "light_stream":
                    return self._light_stream(params)
                if method == "replication_feed":
                    return self._replication_feed(params)
                # URI params arrive as "5" (quoted) or 0xABC (hex) per the
                # reference's URI style; normalize both so handlers that
                # do bytes.fromhex / int() see plain values. The 0x strip
                # only applies to byte-valued params — a quoted string
                # legitimately starting with 0x must survive.
                for k, v in params.items():
                    quoted = len(v) >= 2 and v[0] == v[-1] == '"'
                    v = v.strip('"')
                    if not quoted and k in ("tx", "hash", "data", "evidence") \
                            and (v.startswith("0x") or v.startswith("0X")):
                        v = v[2:]
                    params[k] = v
                self._dispatch({"jsonrpc": "2.0", "id": -1, "method": method,
                                "params": params})

            def _dispatch(self, req):
                method = req.get("method", "")
                rid = req.get("id", -1)
                fn = outer.routes.get(method)
                if fn is None:
                    return self._respond_err(rid, -32601,
                                             f"method {method} not found")
                try:
                    result = fn(outer.env, req.get("params") or {})
                except RPCError as e:
                    return self._respond_err(rid, e.code, str(e))
                except Exception as e:  # noqa: BLE001
                    return self._respond_err(rid, -32603, str(e))
                body = json.dumps(
                    {"jsonrpc": "2.0", "id": rid, "result": result}
                ).encode()
                self._write(200, body)

            def _respond_err(self, rid, code, msg):
                body = json.dumps(
                    {"jsonrpc": "2.0", "id": rid,
                     "error": {"code": code, "message": msg}}
                ).encode()
                self._write(200, body)

            def _write(self, status, body):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # ---- light-client streaming ----------------------------
            def _light_stream(self, params):
                """GET /light_stream: chunked-transfer JSONL of committed
                header payloads (height/hash/mmr root+proof), one line
                per height, pushed as consensus commits. Optional
                ``limit=N`` closes the stream after N payloads (load
                generators and tests); ``timeout_s`` caps how long the
                stream waits for the next commit (default 30 s);
                ``since=H`` replays retained payloads with height > H
                before the live tail (failover cursor resume)."""
                srv = getattr(outer.env, "light_serve", None)
                if srv is None:
                    body = json.dumps({"error": "light serving disabled"}
                                      ).encode()
                    return self._write(503, body)
                limit = int(params.get("limit", 0) or 0)
                timeout_s = float(params.get("timeout_s", 30.0) or 30.0)
                since = params.get("since")
                since = int(since) if since not in (None, "") else None
                sub_id, sub = srv.subscribe(since=since)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/jsonl; charset=utf-8")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    sent = 0
                    while not limit or sent < limit:
                        payload = sub.pop(timeout=timeout_s)
                        if payload is None:
                            break
                        line = (json.dumps(payload) + "\n").encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()
                        sent += 1
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass  # client went away mid-stream
                finally:
                    srv.unsubscribe(sub_id)

            # ---- replication feed ----------------------------------
            def _replication_feed(self, params):
                """GET /replication_feed: chunked-transfer JSONL of
                replication frames. ``cursor=H`` resumes after height H
                — retained frames > H replay first (gap-free), then the
                live tail. A cursor older than the retention window gets
                409 (the replica must re-bootstrap from the snapshot
                surface). The first line is a control record
                ``{"tip": T, "min": M}`` so the consumer can size its
                catch-up lag."""
                feed = getattr(outer.env, "replication_feed", None)
                if feed is None:
                    body = json.dumps({"error": "replication feed disabled"}
                                      ).encode()
                    return self._write(503, body)
                from ..replication.feed import CursorTooOld

                cursor = int(params.get("cursor", 0) or 0)
                limit = int(params.get("limit", 0) or 0)
                timeout_s = float(params.get("timeout_s", 30.0) or 30.0)
                try:
                    sub_id, sub, replay, tip = feed.subscribe(cursor)
                except CursorTooOld as e:
                    body = json.dumps({"error": str(e),
                                       "min": e.min_height}).encode()
                    return self._write(409, body)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/jsonl; charset=utf-8")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def _send(text: str) -> None:
                        line = (text + "\n").encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()

                    _send(json.dumps({"tip": tip, "min": feed.min_height}))
                    sent = 0
                    for line in replay:
                        _send(line)
                        sent += 1
                        if limit and sent >= limit:
                            break
                    while not limit or sent < limit:
                        line = sub.pop(timeout=timeout_s)
                        if line is None:
                            break
                        _send(line)
                        sent += 1
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass  # replica went away mid-stream
                finally:
                    feed.unsubscribe(sub_id)

            # ---- websocket subscriptions ---------------------------
            def _websocket(self):
                key = self.headers.get("Sec-WebSocket-Key", "")
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", _ws_accept(key))
                self.end_headers()
                client_id = f"ws-{id(self)}"
                subs: dict[str, object] = {}
                lock = threading.Lock()
                stop = threading.Event()

                def pump():
                    from ..utils.pubsub import SubscriptionCancelled

                    while not stop.is_set():
                        with lock:
                            items = list(subs.items())
                        for q, sub in items:
                            try:
                                msg = sub.next(timeout=0.05)
                            except SubscriptionCancelled:
                                with lock:
                                    subs.pop(q, None)
                                try:
                                    with lock:
                                        _ws_send_text(self.wfile, json.dumps({
                                            "jsonrpc": "2.0",
                                            "id": -1,
                                            "error": {
                                                "code": -32000,
                                                "message": "subscription cancelled"
                                                           " (client too slow)",
                                                "data": q,
                                            },
                                        }))
                                except OSError:
                                    stop.set()
                                continue
                            if msg is None:
                                continue
                            try:
                                with lock:
                                    _ws_send_text(self.wfile, json.dumps({
                                        "jsonrpc": "2.0",
                                        "id": -1,
                                        "result": {
                                            "query": q,
                                            "data": _render_event(msg),
                                            "events": msg.events,
                                        },
                                    }))
                            except OSError:
                                stop.set()
                                return
                        if not items:
                            stop.wait(0.05)

                t = threading.Thread(target=pump, daemon=True)
                t.start()
                try:
                    while not stop.is_set():
                        frame = _ws_read_frame(self.rfile)
                        if frame is None:
                            break
                        opcode, data = frame
                        if opcode == 0x8:  # close
                            break
                        if opcode == 0x9:  # ping -> pong
                            with lock:
                                self.wfile.write(b"\x8a\x00")
                            continue
                        if opcode != 0x1:
                            continue
                        try:
                            req = json.loads(data)
                        except Exception:
                            continue
                        method = req.get("method")
                        params = req.get("params") or {}
                        rid = req.get("id", -1)
                        if method == "subscribe":
                            q = params.get("query", "")
                            try:
                                sub = outer.env.event_bus.subscribe(client_id, q)
                                with lock:
                                    subs[q] = sub
                                    _ws_send_text(self.wfile, json.dumps(
                                        {"jsonrpc": "2.0", "id": rid,
                                         "result": {}}))
                            except ValueError as e:
                                with lock:
                                    _ws_send_text(self.wfile, json.dumps(
                                        {"jsonrpc": "2.0", "id": rid,
                                         "error": {"code": -32602,
                                                   "message": str(e)}}))
                        elif method == "unsubscribe":
                            q = params.get("query", "")
                            outer.env.event_bus.unsubscribe(client_id, q)
                            with lock:
                                subs.pop(q, None)
                                _ws_send_text(self.wfile, json.dumps(
                                    {"jsonrpc": "2.0", "id": rid, "result": {}}))
                finally:
                    stop.set()
                    outer.env.event_bus.unsubscribe_all(client_id)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def _render_event(msg) -> dict:
    d = msg.data
    if isinstance(d, dict):
        out = {"type": d.get("type", "")}
        if "height" in d:
            out["height"] = str(d["height"])
        if "tx" in d:
            out["tx"] = d["tx"].hex().upper()
        if "block" in d:
            out["block_height"] = str(d["block"].header.height)
        return out
    return {"type": str(type(d))}
