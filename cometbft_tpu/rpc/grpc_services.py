"""Node gRPC services (reference rpc/grpc/server/services/*):

- VersionService.GetVersion
- BlockService.GetByHeight / GetLatest
- BlockResultsService.GetBlockResults
- PruningService.Set/GetBlockRetainHeight,
  Set/GetBlockResultsRetainHeight (the privileged data-companion API
  feeding the pruner's companion retain heights)

Hand-rolled request/response protos over grpc generic handlers (the
image has grpcio but no codegen plugin; see abci/grpc_transport.py for
the same pattern). The pruning service is intended for the PRIVILEGED
listener: bind it to a separate loopback address, as the reference does
(rpc/grpc/server privileged vs non-privileged servers).
"""

from __future__ import annotations

from concurrent import futures

from ..encoding import proto as pb

VERSION_SERVICE = "cometbft.services.version.v1.VersionService"
BLOCK_SERVICE = "cometbft.services.block.v1.BlockService"
BLOCK_RESULTS_SERVICE = (
    "cometbft.services.block_results.v1.BlockResultsService"
)
PRUNING_SERVICE = "cometbft.services.pruning.v1.PruningService"

_ident = bytes

NODE_VERSION = "0.3.0"  # this framework's release version
ABCI_VERSION = "2.1.0"
P2P_PROTOCOL = 9
BLOCK_PROTOCOL = 11


class GrpcRPCServer:
    """Non-privileged services (version/block/block results) plus,
    when a pruner is supplied, the privileged pruning service."""

    def __init__(self, addr: str, *, block_store=None, state_store=None,
                 pruner=None, max_workers: int = 4):
        import grpc

        self.block_store = block_store
        self.state_store = state_store
        self.pruner = pruner
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._register(grpc)
        hostport = addr.removeprefix("tcp://") or "127.0.0.1:0"
        self.port = self._server.add_insecure_port(hostport)
        self.addr = f"{hostport.rsplit(':', 1)[0]}:{self.port}"

    # ------------------------------------------------------------------
    def _register(self, grpc) -> None:
        def h(fn):
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: fn(req),
                request_deserializer=_ident,
                response_serializer=_ident,
            )

        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                VERSION_SERVICE, {"GetVersion": h(self._get_version)}
            ),
            grpc.method_handlers_generic_handler(
                BLOCK_SERVICE,
                {
                    "GetByHeight": h(self._get_by_height),
                    "GetLatest": h(self._get_latest),
                    "GetLatestHeight": h(self._get_latest_height),
                },
            ),
            grpc.method_handlers_generic_handler(
                BLOCK_RESULTS_SERVICE,
                {"GetBlockResults": h(self._get_block_results)},
            ),
        ))
        if self.pruner is not None:
            self._server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    PRUNING_SERVICE,
                    {
                        "SetBlockRetainHeight": h(self._set_block_retain),
                        "GetBlockRetainHeight": h(self._get_block_retain),
                        "SetBlockResultsRetainHeight": h(
                            self._set_results_retain
                        ),
                        "GetBlockResultsRetainHeight": h(
                            self._get_results_retain
                        ),
                    },
                ),
            ))

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=1.0)

    # -- version --------------------------------------------------------
    def _get_version(self, req: bytes) -> bytes:
        return (
            pb.f_string(1, NODE_VERSION)
            + pb.f_string(2, ABCI_VERSION)
            + pb.f_varint(3, P2P_PROTOCOL)
            + pb.f_varint(4, BLOCK_PROTOCOL)
        )

    # -- block ----------------------------------------------------------
    def _block_response(self, height: int) -> bytes:
        blk = self.block_store.load_block(height)
        if blk is None:
            raise ValueError(f"no block at height {height}")
        bid = pb.f_bytes(1, blk.hash())
        return pb.f_embedded(1, bid) + pb.f_embedded(2, blk.encode())

    def _get_by_height(self, req: bytes) -> bytes:
        d = pb.fields_to_dict(req)
        return self._block_response(pb.to_i64(d.get(1, 0)))

    def _get_latest(self, req: bytes) -> bytes:
        return self._block_response(self.block_store.height())

    def _get_latest_height(self, req: bytes) -> bytes:
        return pb.f_varint(1, self.block_store.height())

    # -- block results ---------------------------------------------------
    def _get_block_results(self, req: bytes) -> bytes:
        d = pb.fields_to_dict(req)
        h = pb.to_i64(d.get(1, 0)) or self.block_store.height()
        # the full stored FinalizeBlockResponse (tx results, validator
        # updates, app hash) — not the 32-byte results hash the header
        # commits to, which lives in load_finalize_response
        raw = (
            self.state_store.load_abci_responses(h)
            if self.state_store is not None else None
        )
        return pb.f_varint(1, h) + pb.f_bytes(2, raw or b"")

    # -- pruning (privileged data-companion API) -------------------------
    def _set_block_retain(self, req: bytes) -> bytes:
        d = pb.fields_to_dict(req)
        self.pruner.set_companion_block_retain_height(pb.to_i64(d.get(1, 0)))
        return b""

    def _get_block_retain(self, req: bytes) -> bytes:
        return pb.f_varint(1, self.pruner.app_retain_height()) + pb.f_varint(
            2, self.pruner.companion_block_retain_height()
        )

    def _set_results_retain(self, req: bytes) -> bytes:
        d = pb.fields_to_dict(req)
        self.pruner.set_companion_block_results_retain_height(
            pb.to_i64(d.get(1, 0))
        )
        return b""

    def _get_results_retain(self, req: bytes) -> bytes:
        return pb.f_varint(
            1, self.pruner.companion_block_results_retain_height()
        )


class GrpcRPCClient:
    """Client for the services above (reference rpc/grpc/client)."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        import grpc

        self._channel = grpc.insecure_channel(addr.removeprefix("tcp://"))
        self._timeout = timeout_s

    def _call(self, service: str, method: str, payload: bytes = b"") -> bytes:
        fn = self._channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=_ident,
            response_deserializer=_ident,
        )
        return fn(payload, timeout=self._timeout)

    def close(self) -> None:
        self._channel.close()

    def get_version(self) -> dict:
        d = pb.fields_to_dict(self._call(VERSION_SERVICE, "GetVersion"))
        return {
            "node": pb.as_bytes(d.get(1, b"")).decode(),
            "abci": pb.as_bytes(d.get(2, b"")).decode(),
            "p2p": pb.to_i64(d.get(3, 0)),
            "block": pb.to_i64(d.get(4, 0)),
        }

    def get_block_by_height(self, height: int):
        from ..types.block import Block

        out = self._call(
            BLOCK_SERVICE, "GetByHeight", pb.f_varint(1, height)
        )
        d = pb.fields_to_dict(out)
        return Block.decode(pb.as_bytes(d.get(2, b"")))

    def get_latest_block(self):
        from ..types.block import Block

        out = self._call(BLOCK_SERVICE, "GetLatest")
        d = pb.fields_to_dict(out)
        return Block.decode(pb.as_bytes(d.get(2, b"")))

    def get_latest_height(self) -> int:
        out = self._call(BLOCK_SERVICE, "GetLatestHeight")
        return pb.to_i64(pb.fields_to_dict(out).get(1, 0))

    def get_block_results(self, height: int = 0) -> tuple[int, bytes]:
        out = self._call(
            BLOCK_RESULTS_SERVICE, "GetBlockResults", pb.f_varint(1, height)
        )
        d = pb.fields_to_dict(out)
        return pb.to_i64(d.get(1, 0)), pb.as_bytes(d.get(2, b""))

    def set_block_retain_height(self, h: int) -> None:
        self._call(PRUNING_SERVICE, "SetBlockRetainHeight", pb.f_varint(1, h))

    def get_block_retain_height(self) -> tuple[int, int]:
        d = pb.fields_to_dict(
            self._call(PRUNING_SERVICE, "GetBlockRetainHeight")
        )
        return pb.to_i64(d.get(1, 0)), pb.to_i64(d.get(2, 0))

    def set_block_results_retain_height(self, h: int) -> None:
        self._call(
            PRUNING_SERVICE, "SetBlockResultsRetainHeight", pb.f_varint(1, h)
        )

    def get_block_results_retain_height(self) -> int:
        d = pb.fields_to_dict(
            self._call(PRUNING_SERVICE, "GetBlockResultsRetainHeight")
        )
        return pb.to_i64(d.get(1, 0))