"""Cross-node flight recorder: merge per-node trace sinks into one
correlated timeline, attribute per-height wall time, and triage stalls.

Each node writes its own JSONL sink (utils/trace.py) with records
stamped by a stable node id and, via the consensus reactor's wire
hooks, one ``p2p.send``/``p2p.recv`` event per consensus message. This
module is the read side:

* `merge(paths)` loads N sinks and aligns their wall clocks. Every
  matched send→recv pair of the same wire message gives one inequality
  ``recv - send = latency + skew(dst) - skew(src)`` with latency > 0;
  taking the **minimum** delta per directed pair approaches
  ``latency_min + skew(dst) - skew(src)``, and when both directions
  exist the classic NTP trick cancels the (symmetric) latency:
  ``theta = (d_ab - d_ba) / 2 = skew(b) - skew(a)``. Offsets propagate
  breadth-first from a reference node, so any connected world aligns
  even if some pairs only ever talked one way.
* `critical_path(h)` reconstructs the commit pipeline for one height —
  proposal broadcast → prevote quorum → precommit quorum → commit →
  apply — and attributes each node's wall time to gossip (proposal +
  parts in flight), verify (commit-sig crypto inside ApplyBlock) and
  apply (the rest of ApplyBlock).
* `stall_report()` detects live-but-not-finalizing nodes: the process
  still emits records (live) but its height stopped while peers' tip
  moved on or its rounds churn in place. The classifier walks the
  message pipeline in causal order and names the first class of
  message the stuck node never received at its stuck height — which
  peer/message to go look at, not just "it's stuck".

Pure stdlib, no tracer dependency at runtime: analysis must run on a
laptop against sinks scp'd out of a broken testnet.
"""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict

# Wire-message classes in causal pipeline order for one height: a node
# cannot prevote before it has the proposal + parts, cannot precommit
# before prevotes, cannot commit before precommits. The stall
# classifier reports the FIRST absent class, which is the earliest
# broken link in the chain.
PIPELINE_ORDER = ("proposal", "block_part", "prevote", "precommit")

# A node whose newest record is older than this (scaled by world span)
# is "dead" — crashed or shut down — and belongs to a different triage
# (restart it) than a live-but-stalled node (debug its message flow).
_LIVE_SLACK_S = 2.0
_ADVANCE_SLACK_S = 3.0


def load_records(path: str) -> list[dict]:
    """Parse one JSONL sink, skipping unparseable lines (a killed node
    may leave a truncated final record)."""
    out = []
    with open(path, "rb") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "ts" in rec and "name" in rec:
                out.append(rec)
    return out


def discover(paths) -> list[str]:
    """Expand files/directories into trace sink paths. A directory is
    searched for the runner layout (``node*/data/trace.jsonl``), a bare
    ``data/trace.jsonl`` and top-level ``*.jsonl`` files."""
    found: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            found.append(p)
            continue
        if not os.path.isdir(p):
            continue
        direct = os.path.join(p, "data", "trace.jsonl")
        if os.path.isfile(direct):
            found.append(direct)
        for ent in sorted(os.listdir(p)):
            sub = os.path.join(p, ent)
            if os.path.isdir(sub):
                cand = os.path.join(sub, "data", "trace.jsonl")
                if os.path.isfile(cand):
                    found.append(cand)
            elif ent.endswith(".jsonl"):
                found.append(sub)
    # De-dup, preserve order.
    seen: set[str] = set()
    uniq = []
    for f in found:
        ap = os.path.abspath(f)
        if ap not in seen:
            seen.add(ap)
            uniq.append(f)
    return uniq


class NodeTrace:
    """One node's records plus the identity used to join them."""

    __slots__ = ("key", "name", "path", "records", "offset_s")

    def __init__(self, key: str, name: str, path: str, records: list[dict]):
        self.key = key
        self.name = name
        self.path = path
        self.records = records
        self.offset_s = 0.0


def _node_key(records: list[dict], path: str) -> str:
    for r in records:
        nid = r.get("node")
        if nid:
            return str(nid)
    pids = Counter(r.get("pid") for r in records if r.get("pid") is not None)
    if pids:
        return f"pid{pids.most_common(1)[0][0]}"
    return os.path.basename(os.path.dirname(path) or path)


def _node_name(records: list[dict], path: str, key: str) -> str:
    for r in records:
        if r.get("name") == "node.boot" and r.get("moniker"):
            mk = str(r["moniker"])
            if mk != "node":  # the config default is not a name
                return mk
    # Runner layout: .../node3/data/trace.jsonl -> "node3".
    parts = os.path.abspath(path).split(os.sep)
    for part in reversed(parts[:-1]):
        if part and part != "data":
            return part
    return key[:8]


def _match_key(r: dict):
    """Identity of one wire message as seen from both ends: the sender's
    p2p.send and the receiver's p2p.recv of the SAME frame carry the
    same classifier fields, which is what lets the merger pair them."""
    return (
        r.get("msg"), r.get("height"), r.get("round"),
        r.get("type"), r.get("idx"), r.get("step"), r.get("chan"),
        r.get("n"),
    )


def _estimate_offsets(traces: list[NodeTrace]) -> dict[str, float]:
    """Per-node clock offsets (seconds to SUBTRACT from raw ts)."""
    # Earliest send/recv per (src, dst, message identity). Min matters:
    # gossip can re-send the same vote after a reconnect, and pairing
    # a first send with a later re-delivery would inflate the delta.
    sends: dict[tuple, float] = {}
    recvs: dict[tuple, float] = {}
    for t in traces:
        for r in t.records:
            nm = r.get("name")
            if nm == "p2p.send":
                k = (t.key, r.get("peer"), _match_key(r))
                ts = r["ts"]
                if k not in sends or ts < sends[k]:
                    sends[k] = ts
            elif nm == "p2p.recv":
                k = (r.get("peer"), t.key, _match_key(r))
                ts = r["ts"]
                if k not in recvs or ts < recvs[k]:
                    recvs[k] = ts
    # Min delta per directed pair ~= latency_min + skew(dst) - skew(src).
    deltas: dict[tuple[str, str], float] = {}
    for k, sts in sends.items():
        rts = recvs.get(k)
        if rts is None:
            continue
        pair = (k[0], k[1])
        d = rts - sts
        if pair not in deltas or d < deltas[pair]:
            deltas[pair] = d
    fwd: dict[str, dict[str, float]] = defaultdict(dict)
    for (a, b), d in deltas.items():
        fwd[a][b] = d
    # Reference: the busiest sink (most records) — ties broken by key so
    # repeated merges of the same world pick the same reference.
    ref = max(traces, key=lambda t: (len(t.records), t.key)).key
    offsets = {ref: 0.0}
    queue = [ref]
    while queue:
        a = queue.pop(0)
        neighbors = set(fwd.get(a, ())) | {x for x in fwd if a in fwd[x]}
        for b in sorted(neighbors):
            if b in offsets:
                continue
            d_ab = fwd.get(a, {}).get(b)
            d_ba = fwd.get(b, {}).get(a)
            if d_ab is not None and d_ba is not None:
                theta = (d_ab - d_ba) / 2.0  # latency cancels
            elif d_ab is not None:
                theta = d_ab  # one-way: off by min latency, best we have
            else:
                theta = -d_ba
            offsets[b] = offsets[a] + theta
            queue.append(b)
    for t in traces:
        offsets.setdefault(t.key, 0.0)
    return offsets


class MergedTrace:
    """N aligned node traces plus the unified, time-sorted record list.

    Merged records are the loaded dicts with two additions: ``_node``
    (the owning node's key) and ``_t`` (skew-adjusted timestamp)."""

    def __init__(self, traces: list[NodeTrace]):
        self.traces = traces
        self.by_key = {t.key: t for t in traces}
        offsets = _estimate_offsets(traces)
        self.offsets = offsets
        self.records: list[dict] = []
        for t in traces:
            t.offset_s = offsets[t.key]
            for r in t.records:
                r["_node"] = t.key
                r["_t"] = r["ts"] - t.offset_s
                self.records.append(r)
        self.records.sort(key=lambda r: r["_t"])

    # -- naming ---------------------------------------------------------
    def display_name(self, key: str) -> str:
        t = self.by_key.get(key)
        return t.name if t is not None else str(key)[:8]

    def _peer_name(self, peer_id) -> str:
        """Map a wire peer id back to a merged node's display name."""
        if peer_id in self.by_key:
            return self.display_name(peer_id)
        return str(peer_id)[:8] if peer_id else "?"

    # -- basic queries ---------------------------------------------------
    def heights(self) -> list[int]:
        """All heights some node committed (consensus or blocksync)."""
        hs: set[int] = set()
        for r in self.records:
            if r.get("name") in ("consensus.finalize_commit", "blocksync.block"):
                h = r.get("height")
                if isinstance(h, int):
                    hs.add(h)
        return sorted(hs)

    def tx_lifecycles(self) -> dict[str, list[dict]]:
        """tx hex -> that tx's ``tx.lifecycle`` records across every
        node, in aligned time order (tools/latency_analyze.py input).
        Records carry the merge additions ``_node``/``_t`` plus the
        emitter's ``stage`` and within-process ``mono`` clock."""
        out: dict[str, list[dict]] = defaultdict(list)
        for r in self.records:
            if r.get("name") == "tx.lifecycle" and r.get("tx"):
                out[str(r["tx"])].append(r)
        return dict(out)

    def timeline(self, height: int | None = None,
                 names: set[str] | None = None) -> list[dict]:
        out = []
        for r in self.records:
            if height is not None and r.get("height") != height:
                continue
            if names is not None and r.get("name") not in names:
                continue
            out.append(r)
        return out

    # -- critical path ---------------------------------------------------
    def critical_path(self, height: int) -> dict:
        """Reconstruct the commit pipeline for one height.

        Anchor is the proposer's earliest ``p2p.send`` of the proposal
        (fallback: first block part). Per node, the consensus step
        spans for the height give propose/prevote/precommit durations,
        the apply_block span splits into verify (validate_ms — the
        commit-sig crypto) and apply (the rest), and gossip is the
        in-flight time from the anchor to the node's last proposal/part
        receipt. The slowest committer defines the wall clock."""
        rep: dict = {
            "height": height, "committed": False, "proposer": None,
            "anchor_t": None, "wall_ms": None, "per_node": {},
            "phase_ms": {}, "slowest": None,
        }
        # self.records is time-sorted, so the first matching send is the
        # earliest; a proposal anchor is preferred over a bare part (a
        # restarting node may re-gossip parts before any proposal).
        anchor = None
        for r in self.records:
            if (r.get("name") == "p2p.send" and r.get("height") == height
                    and r.get("msg") in ("proposal", "block_part")):
                if anchor is None or (anchor["msg"] != "proposal"
                                      and r["msg"] == "proposal"):
                    anchor = r
        if anchor is not None:
            rep["anchor_t"] = anchor["_t"]
            rep["proposer"] = self.display_name(anchor["_node"])

        phase_max: dict[str, float] = {}
        commit_ts: dict[str, float] = {}
        for t in self.traces:
            nd: dict = {}
            last_data_recv = None
            step_ms: dict[str, float] = {}
            apply_rec = None
            commit_t = None
            commit_round = None
            for r in t.records:
                if r.get("height") != height:
                    continue
                nm = r.get("name")
                if nm == "consensus.step":
                    step = r.get("step")
                    if step:
                        step_ms[step] = step_ms.get(step, 0.0) + \
                            float(r.get("dur_ms") or 0.0)
                elif nm == "consensus.finalize_commit":
                    commit_t = r["_t"]
                    commit_round = r.get("round")
                elif nm == "state.apply_block":
                    apply_rec = r
                elif nm == "blocksync.block":
                    if commit_t is None:
                        commit_t = r["_t"]
                    if apply_rec is None:
                        apply_rec = r
                elif nm == "p2p.recv" and r.get("msg") in (
                        "proposal", "block_part"):
                    if last_data_recv is None or r["_t"] > last_data_recv:
                        last_data_recv = r["_t"]
            for step, label in (("PROPOSE", "propose_ms"),
                                ("PREVOTE", "prevote_ms"),
                                ("PRECOMMIT", "precommit_ms")):
                if step in step_ms:
                    nd[label] = round(step_ms[step], 3)
            if anchor is not None and last_data_recv is not None:
                nd["gossip_ms"] = round(
                    max(0.0, (last_data_recv - anchor["_t"]) * 1e3), 3)
            if apply_rec is not None:
                if apply_rec.get("name") == "state.apply_block":
                    verify = float(apply_rec.get("validate_ms") or 0.0)
                    total = float(apply_rec.get("dur_ms") or 0.0)
                    nd["verify_ms"] = round(verify, 3)
                    nd["apply_ms"] = round(max(0.0, total - verify), 3)
                else:  # blocksync span has its own split
                    nd["verify_ms"] = round(
                        float(apply_rec.get("verify_ms") or 0.0), 3)
                    nd["apply_ms"] = round(
                        float(apply_rec.get("apply_ms") or 0.0), 3)
            if commit_t is not None:
                commit_ts[t.key] = commit_t
                nd["commit_t"] = commit_t
                if commit_round is not None:
                    nd["commit_round"] = commit_round
                if anchor is not None:
                    nd["commit_latency_ms"] = round(
                        max(0.0, (commit_t - anchor["_t"]) * 1e3), 3)
            if nd:
                rep["per_node"][t.name] = nd
                for k, v in nd.items():
                    if k.endswith("_ms"):
                        phase_max[k] = max(phase_max.get(k, 0.0), v)
        rep["committed"] = bool(commit_ts)
        rep["phase_ms"] = {k: round(v, 3) for k, v in phase_max.items()}
        if commit_ts:
            slowest_key = max(commit_ts, key=lambda k: commit_ts[k])
            rep["slowest"] = self.display_name(slowest_key)
            if anchor is not None:
                rep["wall_ms"] = round(
                    max(0.0, (commit_ts[slowest_key] - anchor["_t"]) * 1e3), 3)
        return rep

    # -- stall triage ----------------------------------------------------
    def stall_report(self) -> dict:
        """Classify live-but-not-finalizing nodes.

        A node is STALLED when it is still emitting records (live) but
        its committed height lags the world tip by >= 2 or its rounds
        churn (round >= 2) at a height it cannot finish, and it has not
        advanced for a while. For each stalled node the classifier
        walks PIPELINE_ORDER at the stuck height and names the first
        message class with zero receipts — plus, when peers are already
        past that height, which connected peers never sent the catchup
        (stored-commit precommit) votes it needs."""
        if not self.records:
            return {"status": "empty", "tip": None, "nodes": {},
                    "stalled": []}
        world_start = self.records[0]["_t"]
        world_end = self.records[-1]["_t"]
        span = max(0.0, world_end - world_start)
        live_slack = max(_LIVE_SLACK_S, 0.1 * span)
        advance_slack = max(_ADVANCE_SLACK_S, 0.2 * span)

        nodes: dict[str, dict] = {}
        tip = 0
        for t in self.traces:
            last_t = world_start
            committed = 0
            advance_t = None
            cur_height = None
            cur_height_t = None
            for r in t.records:
                if r["_t"] > last_t:
                    last_t = r["_t"]
                nm = r.get("name")
                if nm in ("consensus.finalize_commit", "blocksync.block"):
                    h = r.get("height")
                    if isinstance(h, int) and h > committed:
                        committed = h
                        advance_t = r["_t"]
                elif nm == "consensus.step":
                    h = r.get("height")
                    if isinstance(h, int) and (
                            cur_height_t is None or r["_t"] >= cur_height_t):
                        cur_height = h
                        cur_height_t = r["_t"]
            if cur_height is None:
                cur_height = committed + 1 if committed else None
            max_round = 0
            if cur_height is not None:
                for r in t.records:
                    if (r.get("name") == "consensus.step"
                            and r.get("height") == cur_height):
                        rd = r.get("round")
                        if isinstance(rd, int) and rd > max_round:
                            max_round = rd
            tip = max(tip, committed)
            nodes[t.key] = {
                "name": t.name, "committed": committed,
                "height": cur_height, "max_round": max_round,
                "last_t": last_t, "advance_t": advance_t,
                "offset_s": round(t.offset_s, 6),
                "records": len(t.records),
            }

        stalled = []
        for t in self.traces:
            info = nodes[t.key]
            live = (world_end - info["last_t"]) <= live_slack
            info["live"] = live
            gap = world_end - (info["advance_t"]
                               if info["advance_t"] is not None
                               else world_start)
            lagging = tip - info["committed"] >= 2
            churning = info["max_round"] >= 2
            if not (live and gap > advance_slack and (lagging or churning)):
                continue
            h = info["height"]
            recv_counts: Counter = Counter()
            votes_by_peer: Counter = Counter()
            peers_seen: set = set()
            for r in t.records:
                if r.get("name") != "p2p.recv":
                    continue
                peers_seen.add(r.get("peer"))
                if r.get("height") != h:
                    continue
                msg = r.get("msg")
                cls = r.get("type") if msg == "vote" else msg
                if cls in PIPELINE_ORDER:
                    recv_counts[cls] += 1
                    if cls == "precommit":
                        votes_by_peer[r.get("peer")] += 1
            missing = [c for c in PIPELINE_ORDER if recv_counts[c] == 0]
            first_missing = missing[0] if missing else None
            silent_peers = sorted(
                self._peer_name(p) for p in peers_seen
                if p is not None and votes_by_peer[p] == 0)
            if tip > (info["committed"] or 0) and recv_counts["precommit"] == 0:
                # Peers are past this height: finishing it needs the
                # stored commit's precommits (catchup votes), and none
                # arrived. That beats an earlier missing class for
                # triage because the block data may simply be what the
                # node already has from before it stalled.
                if "precommit" in missing:
                    first_missing = "precommit"
                detail = (
                    f"peers are at height {tip} but no catchup precommit "
                    f"votes for height {h} ever arrived"
                    + (f"; connected peers never gossiping them: "
                       f"{', '.join(silent_peers)}" if silent_peers else "")
                )
            elif first_missing is not None:
                detail = (f"no {first_missing} received at height {h} "
                          f"(rounds reached {info['max_round']})")
            else:
                detail = (f"all message classes seen at height {h} yet no "
                          f"commit; rounds reached {info['max_round']}")
            stalled.append({
                "node": info["name"], "node_id": t.key, "height": h,
                "committed": info["committed"], "max_round": info["max_round"],
                "first_missing": first_missing, "missing": missing,
                "recv_counts": dict(recv_counts),
                "silent_peers": silent_peers,
                "stalled_for_s": round(gap, 3), "detail": detail,
            })
        return {
            "status": "stall" if stalled else "ok",
            "tip": tip or None,
            "span_s": round(span, 3),
            "nodes": {nodes[k]["name"]: {kk: vv for kk, vv in nodes[k].items()
                                         if kk != "name"}
                      for k in nodes},
            "stalled": stalled,
        }

    def summary(self) -> dict:
        hs = self.heights()
        return {
            "nodes": {
                t.name: {
                    "node_id": t.key, "path": t.path,
                    "records": len(t.records),
                    "offset_s": round(t.offset_s, 6),
                } for t in self.traces
            },
            "records": len(self.records),
            "heights": {"min": hs[0], "max": hs[-1]} if hs else None,
            "tenants": self.tenant_rollup() or None,
        }

    def tenant_rollup(self) -> dict:
        """Per-tenant share of the shared verify scheduler's coalesced
        dispatches (crypto.sched_coalesce spans): how many dispatches
        each tenant rode in, its signature volume, and the dispatch
        wall it shared. Empty when no scheduler spans were recorded."""
        out: dict[str, dict] = {}
        for r in self.records:
            if r.get("name") != "crypto.sched_coalesce":
                continue
            per = r.get("per_tenant_sigs") or {}
            dur = float(r.get("dur_ms", 0.0) or 0.0)
            for tenant, sigs in per.items():
                agg = out.setdefault(
                    tenant, {"dispatches": 0, "sigs": 0, "ms": 0.0})
                agg["dispatches"] += 1
                agg["sigs"] += int(sigs)
                agg["ms"] += dur
        for agg in out.values():
            agg["ms"] = round(agg["ms"], 3)
        return out


def merge(paths) -> MergedTrace:
    """Load + align the sinks under `paths` (files or directories)."""
    files = discover(paths)
    traces = []
    for f in files:
        records = load_records(f)
        if not records:
            continue
        key = _node_key(records, f)
        name = _node_name(records, f, key)
        traces.append(NodeTrace(key, name, f, records))
    if not traces:
        raise ValueError(f"no trace records found under {list(paths)!r}")
    # Two sinks claiming the same key (in-process worlds sharing one
    # tracer) stay separate traces; suffix for unique dict keys.
    seen: dict[str, int] = {}
    for t in traces:
        n = seen.get(t.key, 0)
        seen[t.key] = n + 1
        if n:
            t.key = f"{t.key}#{n}"
    return MergedTrace(traces)


# ----------------------------------------------------------------------
# text renderers (tools/trace_analyze.py and the e2e runner's report)
# ----------------------------------------------------------------------
def render_summary(mt: MergedTrace) -> str:
    s = mt.summary()
    lines = ["flight recorder: %d records from %d node(s)" % (
        s["records"], len(s["nodes"]))]
    if s["heights"]:
        lines.append("heights committed: %d..%d" % (
            s["heights"]["min"], s["heights"]["max"]))
    for name, info in s["nodes"].items():
        lines.append("  %-12s id=%s.. offset=%+.3fms records=%d" % (
            name, str(info["node_id"])[:8], info["offset_s"] * 1e3,
            info["records"]))
    if s.get("tenants"):
        lines.append("verify scheduler tenants:")
        for tenant, agg in sorted(s["tenants"].items()):
            lines.append(
                "  %-16s dispatches=%d sigs=%d shared_wall=%.1fms" % (
                    tenant, agg["dispatches"], agg["sigs"], agg["ms"]))
    return "\n".join(lines)


def render_timeline(records: list[dict], mt: MergedTrace,
                    limit: int = 0) -> str:
    if not records:
        return "(no records)"
    shown = records[-limit:] if limit else records
    t0 = records[0]["_t"]
    lines = []
    if limit and len(records) > limit:
        lines.append(f"... ({len(records) - limit} earlier records elided)")
    for r in shown:
        extra = []
        for k in ("height", "round", "step", "msg", "type", "idx",
                  "dur_ms", "validate_ms", "verify_ms", "txs"):
            if k in r:
                extra.append(f"{k}={r[k]}")
        if "peer" in r:
            extra.append(f"peer={mt._peer_name(r['peer'])}")
        lines.append("%10.3fs %-10s %-24s %s" % (
            r["_t"] - t0, mt.display_name(r["_node"]), r["name"],
            " ".join(extra)))
    return "\n".join(lines)


def render_critical_path(cp: dict) -> str:
    h = cp["height"]
    if not cp["per_node"]:
        return f"height {h}: no records"
    lines = [
        "height %d: %s  wall=%s  proposer=%s  slowest=%s" % (
            h, "committed" if cp["committed"] else "NOT COMMITTED",
            ("%.1fms" % cp["wall_ms"]) if cp["wall_ms"] is not None else "?",
            cp["proposer"] or "?", cp["slowest"] or "?"),
    ]
    cols = ("gossip_ms", "propose_ms", "prevote_ms", "precommit_ms",
            "verify_ms", "apply_ms", "commit_latency_ms")
    lines.append("  %-12s %s" % ("node", " ".join("%11s" % c.replace("_ms", "")
                                                  for c in cols)))
    for name in sorted(cp["per_node"]):
        nd = cp["per_node"][name]
        cells = " ".join(
            "%11s" % (("%.1f" % nd[c]) if c in nd else "-") for c in cols)
        lines.append("  %-12s %s" % (name, cells))
    if cp["phase_ms"]:
        lines.append("  worst-node phase maxima: " + "  ".join(
            "%s=%.1fms" % (k.replace("_ms", ""), v)
            for k, v in sorted(cp["phase_ms"].items())))
    return "\n".join(lines)


def render_stall_report(rep: dict) -> str:
    if rep["status"] == "empty":
        return "stall triage: no records"
    lines = ["stall triage: %s (tip height %s, world span %.1fs)" % (
        rep["status"].upper(), rep["tip"], rep["span_s"])]
    for name, info in sorted(rep["nodes"].items()):
        lines.append(
            "  %-12s committed=%-5s at_height=%-5s max_round=%-3s "
            "live=%s" % (name, info["committed"], info["height"],
                         info["max_round"], info.get("live")))
    for s in rep["stalled"]:
        lines.append("  STALLED %s: stuck at height %s for %.1fs "
                     "(rounds up to %s)" % (
                         s["node"], s["height"], s["stalled_for_s"],
                         s["max_round"]))
        lines.append("    first missing message class: %s" %
                     (s["first_missing"] or "none"))
        lines.append("    %s" % s["detail"])
        if s["recv_counts"]:
            lines.append("    received at stuck height: " + ", ".join(
                "%s=%d" % (k, v) for k, v in sorted(s["recv_counts"].items())))
    if rep["status"] == "ok":
        lines.append("  no live-but-stalled node detected")
    return "\n".join(lines)
