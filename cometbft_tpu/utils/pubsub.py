"""Event pub/sub with the reference's query language.

Behavior parity: reference internal/pubsub (Server, :~600) +
internal/pubsub/query (the `tm.event='NewBlock' AND tx.height > 5`
language). Supported operators: =, !=, <, <=, >, >=, CONTAINS, EXISTS,
combined with AND (the reference's language has no OR). Values compare
numerically when both sides parse as numbers, else as strings.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field


# ---------------------------------------------------------------- query ---
_TOKEN = re.compile(
    r"\s*(?:(?P<key>[\w.]+)\s*(?P<op><=|>=|!=|=|<|>|\bCONTAINS\b|\bEXISTS\b)"
    r"\s*(?P<val>'[^']*'|[\w.\-]+)?)\s*"
)


@dataclass
class _Condition:
    key: str
    op: str
    value: str | None

    def matches(self, events: dict[str, list[str]]) -> bool:
        vals = events.get(self.key)
        if self.op == "EXISTS":
            return vals is not None
        if vals is None:
            return False
        for v in vals:
            if self._match_one(v):
                return True
        return False

    def _match_one(self, v: str) -> bool:
        want = self.value
        if self.op == "CONTAINS":
            return want in v
        try:
            a, b = float(v), float(want)
            if self.op == "=":
                return a == b
            if self.op == "!=":
                return a != b
            if self.op == "<":
                return a < b
            if self.op == "<=":
                return a <= b
            if self.op == ">":
                return a > b
            if self.op == ">=":
                return a >= b
        except (TypeError, ValueError):
            pass
        if self.op == "=":
            return v == want
        if self.op == "!=":
            return v != want
        return False


class Query:
    """Parsed AND-combination of conditions (reference pubsub/query)."""

    def __init__(self, s: str):
        self.source = s
        self.conditions: list[_Condition] = []
        for clause in re.split(r"\bAND\b", s):
            clause = clause.strip()
            if not clause:
                continue
            m = _TOKEN.fullmatch(clause)
            if not m:
                raise ValueError(f"bad query clause: {clause!r}")
            val = m.group("val")
            if val is not None and val.startswith("'"):
                val = val[1:-1]
            op = m.group("op")
            if op == "EXISTS" and val is not None:
                raise ValueError("EXISTS takes no value")
            if op != "EXISTS" and val is None:
                raise ValueError(f"operator {op} needs a value")
            self.conditions.append(_Condition(m.group("key"), op, val))
        if not self.conditions:
            raise ValueError("empty query")

    def matches(self, events: dict[str, list[str]]) -> bool:
        return all(c.matches(events) for c in self.conditions)


# ---------------------------------------------------------------- server --
@dataclass
class Message:
    data: object
    events: dict[str, list[str]] = field(default_factory=dict)


class SubscriptionCancelled(Exception):
    """The subscription was dropped (slow-consumer overflow or explicit
    unsubscribe); the consumer should resubscribe if it still cares."""


class Subscription:
    def __init__(self, query: Query, capacity: int = 256):
        self.query = query
        self.capacity = capacity
        self._buf: list[Message] = []
        self._cv = threading.Condition()
        self.cancelled = False

    def publish(self, msg: Message) -> None:
        """Buffer a matching message; a subscriber that stops draining is
        cancelled at capacity (reference pubsub drops slow subscribers
        rather than buffering unboundedly — internal/pubsub/pubsub.go)."""
        with self._cv:
            if self.cancelled:
                return
            if len(self._buf) >= self.capacity:
                self.cancelled = True
                self._buf.clear()
                self._cv.notify_all()
                return
            self._buf.append(msg)
            self._cv.notify_all()

    def next(self, timeout: float | None = None) -> Message | None:
        """Pop the next message, or None on timeout. Raises
        SubscriptionCancelled once the subscription was dropped (capacity
        overflow or unsubscribe) so consumers can resubscribe instead of
        polling a dead buffer forever."""
        with self._cv:
            if self.cancelled:
                raise SubscriptionCancelled(self.query.source)
            if not self._buf:
                self._cv.wait(timeout)
            if self.cancelled:
                raise SubscriptionCancelled(self.query.source)
            if self._buf:
                return self._buf.pop(0)
            return None

    def cancel(self) -> None:
        with self._cv:
            self.cancelled = True
            self._buf.clear()
            self._cv.notify_all()

    def drain(self) -> list[Message]:
        with self._cv:
            out, self._buf = self._buf, []
            return out


class PubSubServer:
    def __init__(self):
        self._subs: dict[tuple[str, str], Subscription] = {}
        self._lock = threading.Lock()

    def subscribe(self, client_id: str, query_str: str) -> Subscription:
        q = Query(query_str)
        sub = Subscription(q)
        with self._lock:
            self._subs[(client_id, query_str)] = sub
        return sub

    def unsubscribe(self, client_id: str, query_str: str) -> None:
        with self._lock:
            sub = self._subs.pop((client_id, query_str), None)
        if sub:
            sub.cancel()

    def unsubscribe_all(self, client_id: str) -> None:
        with self._lock:
            gone = [k for k in self._subs if k[0] == client_id]
            for k in gone:
                self._subs.pop(k).cancel()

    def publish(self, data, events: dict[str, list[str]] | None = None) -> None:
        msg = Message(data, events or {})
        with self._lock:
            subs = list(self._subs.items())
        dead = []
        for key, sub in subs:
            if sub.cancelled:
                dead.append(key)
                continue
            if sub.query.matches(msg.events):
                sub.publish(msg)
        if dead:
            with self._lock:
                for key in dead:
                    if self._subs.get(key) is not None and self._subs[key].cancelled:
                        self._subs.pop(key, None)
