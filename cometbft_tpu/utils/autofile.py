"""Size-rotated file groups (reference libs/autofile/group.go).

A Group writes to `<path>` (the head) and rotates it to
`<path>.000`, `<path>.001`, … when the head exceeds head_size_limit,
deleting the oldest chunks once the whole group exceeds
total_size_limit. GroupReader replays the group in order across chunk
boundaries. The consensus WAL keeps its own CRC-framed rotation (it
predates this utility); Group is the general-purpose building block the
reference exposes for any append-heavy log.
"""

from __future__ import annotations

import os
import threading


class Group:
    def __init__(self, head_path: str,
                 head_size_limit: int = 10 * 1024 * 1024,
                 total_size_limit: int = 1024 * 1024 * 1024):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._head = open(head_path, "ab")
        self.min_index, self.max_index = self._scan_indexes()

    def _scan_indexes(self) -> tuple[int, int]:
        base = os.path.basename(self.head_path)
        d = os.path.dirname(self.head_path) or "."
        idx = [
            int(name[len(base) + 1:])
            for name in os.listdir(d)
            if name.startswith(base + ".")
            and name[len(base) + 1:].isdigit()
        ]
        return (min(idx), max(idx)) if idx else (0, -1)

    # ------------------------------------------------------------------
    def write(self, data: bytes) -> None:
        with self._lock:
            self._head.write(data)

    def write_line(self, line: str) -> None:
        self.write(line.encode() + b"\n")

    def flush(self) -> None:
        with self._lock:
            self._head.flush()
            os.fsync(self._head.fileno())

    def head_size(self) -> int:
        with self._lock:
            self._head.flush()
            return os.path.getsize(self.head_path)

    def total_size(self) -> int:
        total = self.head_size()
        for i in range(self.min_index, self.max_index + 1):
            try:
                total += os.path.getsize(f"{self.head_path}.{i:03d}")
            except FileNotFoundError:
                pass
        return total

    def maybe_rotate(self) -> bool:
        """Rotate when the head is over its limit; prune oldest chunks
        while the group is over the total limit (checkHeadSizeLimit +
        checkTotalSizeLimit in the reference's processTicks)."""
        rotated = False
        if self.head_size() > self.head_size_limit:
            with self._lock:
                self._head.close()
                self.max_index += 1
                os.rename(
                    self.head_path, f"{self.head_path}.{self.max_index:03d}"
                )
                self._head = open(self.head_path, "ab")
                rotated = True
        while (
            self.total_size() > self.total_size_limit
            and self.min_index <= self.max_index
        ):
            try:
                os.unlink(f"{self.head_path}.{self.min_index:03d}")
            except FileNotFoundError:
                pass
            self.min_index += 1
        return rotated

    def close(self) -> None:
        with self._lock:
            self._head.close()

    # ------------------------------------------------------------------
    def reader(self):
        return GroupReader(self)


class GroupReader:
    """Reads the whole group oldest-chunk-first, then the head."""

    def __init__(self, group: Group):
        self._paths = [
            f"{group.head_path}.{i:03d}"
            for i in range(group.min_index, group.max_index + 1)
            if os.path.exists(f"{group.head_path}.{i:03d}")
        ]
        self._paths.append(group.head_path)
        self._idx = 0
        self._f = None

    def read(self, n: int = -1) -> bytes:
        out = b""
        while n < 0 or len(out) < n:
            if self._f is None:
                if self._idx >= len(self._paths):
                    break
                self._f = open(self._paths[self._idx], "rb")
            chunk = self._f.read(n - len(out) if n >= 0 else -1)
            if not chunk:
                self._f.close()
                self._f = None
                self._idx += 1
                continue
            out += chunk
        return out

    def lines(self):
        buf = self.read()
        for line in buf.splitlines():
            yield line.decode()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
