"""Prometheus-compatible metrics (reference per-subsystem metrics.go +
scripts/metricsgen).

A minimal registry with Counter / Gauge / Histogram supporting labels
and the text exposition format, served by `MetricsServer` at the
instrumentation listen address (reference node/node.go:537). Subsystem
metric bundles mirror the reference's generated structs.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

NAMESPACE = "cometbft"


class _Metric:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.labels = labels
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def _key(self, label_values: tuple) -> tuple:
        if len(label_values) != len(self.labels):
            raise ValueError(
                f"{self.name}: expected labels {self.labels}, got {label_values}"
            )
        return label_values

    def _fmt_labels(self, key: tuple) -> str:
        if not self.labels:
            return ""
        pairs = ",".join(
            f'{k}="{v}"' for k, v in zip(self.labels, key)
        )
        return "{" + pairs + "}"


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, *labels) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def expose(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labels:
            return [f"{self.name} 0"]
        return [f"{self.name}{self._fmt_labels(k)} {v}" for k, v in items]


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, *labels) -> None:
        with self._lock:
            self._values[self._key(tuple(labels))] = value

    def add(self, amount: float, *labels) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def expose(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labels:
            return [f"{self.name} 0"]
        return [f"{self.name}{self._fmt_labels(k)} {v}" for k, v in items]


class Histogram(_Metric):
    TYPE = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, name, help_, labels, buckets=None):
        super().__init__(name, help_, labels)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, *labels) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            self._sums[key] = self._sums.get(key, 0.0) + value

    def expose(self) -> list[str]:
        out = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                cum = 0
                base = self._fmt_labels(key)[1:-1] if self.labels else ""
                for i, b in enumerate(self.buckets):
                    cum = counts[i]
                    le = f'le="{b}"'
                    lbl = "{" + (base + "," if base else "") + le + "}"
                    out.append(f"{self.name}_bucket{lbl} {cum}")
                lbl = "{" + (base + "," if base else "") + 'le="+Inf"' + "}"
                out.append(f"{self.name}_bucket{lbl} {counts[-1]}")
                sfx = "{" + base + "}" if base else ""
                out.append(f"{self.name}_sum{sfx} {self._sums[key]}")
                out.append(f"{self.name}_count{sfx} {counts[-1]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str = "",
                labels: tuple = ()) -> Counter:
        return self._add(Counter(f"{NAMESPACE}_{subsystem}_{name}", help_,
                                 tuple(labels)))

    def gauge(self, subsystem: str, name: str, help_: str = "",
              labels: tuple = ()) -> Gauge:
        return self._add(Gauge(f"{NAMESPACE}_{subsystem}_{name}", help_,
                               tuple(labels)))

    def histogram(self, subsystem: str, name: str, help_: str = "",
                  labels: tuple = (), buckets=None) -> Histogram:
        return self._add(
            Histogram(f"{NAMESPACE}_{subsystem}_{name}", help_,
                      tuple(labels), buckets)
        )

    def _add(self, m: _Metric):
        with self._lock:
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        lines = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


DEFAULT_REGISTRY = Registry()


# -- subsystem bundles (reference */metrics.go) -----------------------------
class ConsensusMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.height = reg.gauge("consensus", "height", "Current height")
        self.rounds = reg.gauge("consensus", "rounds", "Round of the height")
        self.validators = reg.gauge("consensus", "validators",
                                    "Validator count")
        self.missing_validators = reg.gauge(
            "consensus", "missing_validators",
            "Validators absent from the last commit")
        self.block_interval_seconds = reg.histogram(
            "consensus", "block_interval_seconds",
            "Time between consecutive blocks")
        self.num_txs = reg.gauge("consensus", "num_txs", "Txs in last block")
        self.block_size_bytes = reg.gauge("consensus", "block_size_bytes",
                                          "Last block size")
        self.total_txs = reg.counter("consensus", "total_txs",
                                     "Total committed txs")


class MempoolMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.size = reg.gauge("mempool", "size", "Pending txs")
        self.failed_txs = reg.counter("mempool", "failed_txs",
                                      "CheckTx rejections")
        self.recheck_times = reg.counter("mempool", "recheck_times",
                                         "Post-block rechecks")


class P2PMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.peers = reg.gauge("p2p", "peers", "Connected peers")
        self.message_receive_bytes_total = reg.counter(
            "p2p", "message_receive_bytes_total", "Bytes received",
            labels=("chan",))
        self.message_send_bytes_total = reg.counter(
            "p2p", "message_send_bytes_total", "Bytes sent",
            labels=("chan",))


class StateMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.block_processing_time = reg.histogram(
            "state", "block_processing_time",
            "ApplyBlock wall time (reference execution.go:230)")
        self.block_verify_time = reg.histogram(
            "state", "block_verify_time",
            "Commit signature verification wall time (TPU kernel path)")


_BUNDLES: dict[str, object] = {}


def consensus_metrics() -> ConsensusMetrics:
    b = _BUNDLES.get("consensus")
    if b is None:
        b = _BUNDLES["consensus"] = ConsensusMetrics()
    return b


def mempool_metrics() -> MempoolMetrics:
    b = _BUNDLES.get("mempool")
    if b is None:
        b = _BUNDLES["mempool"] = MempoolMetrics()
    return b


def p2p_metrics() -> P2PMetrics:
    b = _BUNDLES.get("p2p")
    if b is None:
        b = _BUNDLES["p2p"] = P2PMetrics()
    return b


def state_metrics() -> StateMetrics:
    b = _BUNDLES.get("state")
    if b is None:
        b = _BUNDLES["state"] = StateMetrics()
    return b


class MetricsServer:
    """Serves the registry at /metrics (reference prometheus listener)."""

    def __init__(self, registry: Registry | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        reg = registry or DEFAULT_REGISTRY

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = reg.expose_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
