"""Prometheus-compatible metrics (reference per-subsystem metrics.go +
scripts/metricsgen).

A minimal registry with Counter / Gauge / Histogram supporting labels
and the text exposition format, served by `MetricsServer` at the
instrumentation listen address (reference node/node.go:537). Subsystem
metric bundles mirror the reference's generated structs; singleton
accessors (`consensus_metrics()` ...) hand the hot paths their bundle
against `DEFAULT_REGISTRY`, and `reset_bundles()` clears everything so
metric state cannot leak across tests.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

NAMESPACE = "cometbft"


def set_namespace(ns: str) -> None:
    """Set the metric-name prefix (config [instrumentation] namespace).

    Affects metrics registered after the call; node startup invokes it
    before any subsystem bundle is created.
    """
    global NAMESPACE
    if ns:
        NAMESPACE = ns


def _escape_label(v) -> str:
    # Prometheus text format: backslash, double-quote and newline must
    # be escaped inside label values.
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


class _Metric:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.labels = labels
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def _key(self, label_values: tuple) -> tuple:
        if len(label_values) != len(self.labels):
            raise ValueError(
                f"{self.name}: expected labels {self.labels}, got {label_values}"
            )
        return label_values

    def _fmt_labels(self, key: tuple) -> str:
        if not self.labels:
            return ""
        pairs = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in zip(self.labels, key)
        )
        return "{" + pairs + "}"

    def values(self) -> dict[tuple, float]:
        """Snapshot of current samples keyed by label-value tuple."""
        with self._lock:
            return dict(self._values)


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, *labels) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def expose(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labels:
            return [f"{self.name} 0"]
        return [f"{self.name}{self._fmt_labels(k)} {v}" for k, v in items]


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, *labels) -> None:
        with self._lock:
            self._values[self._key(tuple(labels))] = value

    def add(self, amount: float, *labels) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def remove(self, *labels) -> None:
        """Drop one labelled series (e.g. a disconnected peer's gauge)."""
        key = self._key(tuple(labels))
        with self._lock:
            self._values.pop(key, None)

    def expose(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labels:
            return [f"{self.name} 0"]
        return [f"{self.name}{self._fmt_labels(k)} {v}" for k, v in items]


class Histogram(_Metric):
    TYPE = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, name, help_, labels, buckets=None):
        super().__init__(name, help_, labels)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        # per-(labelset, bucket index) exemplar: (id, value, epoch ts) —
        # latest observation wins, like the prometheus client libraries
        self._exemplars: dict[tuple, dict[int, tuple]] = {}

    def observe(self, value: float, *labels, exemplar: str | None = None
                ) -> None:
        key = self._key(tuple(labels))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            bucket_idx = len(self.buckets)  # +Inf
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    bucket_idx = min(bucket_idx, i)
            counts[-1] += 1  # +Inf
            self._sums[key] = self._sums.get(key, 0.0) + value
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[bucket_idx] = (
                    str(exemplar), value, time.time())

    def exemplars(self) -> dict[tuple, dict[int, tuple]]:
        """{labels: {bucket index: (id, value, ts)}} — bucket index
        len(buckets) is +Inf. For the OpenMetrics exposition and the
        latency-observatory tooling (a p99 bucket's exemplar names a
        concrete tx hash to look up in the trace sink)."""
        with self._lock:
            return {k: dict(v) for k, v in self._exemplars.items()}

    def expose_openmetrics(self) -> list[str]:
        """Bucket lines with `# {trace_id}` exemplar suffixes
        (OpenMetrics syntax). Only served when the scraper opts in
        (GET /metrics?exemplars=1): exemplar suffixes are not valid in
        the classic text format that default scrapes negotiate."""
        out = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
            exem = {k: dict(v) for k, v in self._exemplars.items()}
        for key, counts in items:
            base = self._fmt_labels(key)[1:-1] if self.labels else ""
            ex = exem.get(key, {})
            for i, b in enumerate(self.buckets):
                le = f'le="{b}"'
                lbl = "{" + (base + "," if base else "") + le + "}"
                line = f"{self.name}_bucket{lbl} {counts[i]}"
                e = ex.get(i)
                if e is not None:
                    line += (f' # {{trace_id="{_escape_label(e[0])}"}}'
                             f" {e[1]} {e[2]}")
                out.append(line)
            lbl = "{" + (base + "," if base else "") + 'le="+Inf"' + "}"
            line = f"{self.name}_bucket{lbl} {counts[-1]}"
            e = ex.get(len(self.buckets))
            if e is not None:
                line += (f' # {{trace_id="{_escape_label(e[0])}"}}'
                         f" {e[1]} {e[2]}")
            out.append(line)
            sfx = "{" + base + "}" if base else ""
            out.append(f"{self.name}_sum{sfx} {sums[key]}")
            out.append(f"{self.name}_count{sfx} {counts[-1]}")
        return out

    def snapshot(self) -> dict[tuple, dict]:
        """{labels: {"count": n, "sum": s}} for programmatic readers."""
        with self._lock:
            return {
                k: {"count": c[-1], "sum": self._sums.get(k, 0.0)}
                for k, c in self._counts.items()
            }

    def expose(self) -> list[str]:
        out = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                base = self._fmt_labels(key)[1:-1] if self.labels else ""
                for i, b in enumerate(self.buckets):
                    le = f'le="{b}"'
                    lbl = "{" + (base + "," if base else "") + le + "}"
                    out.append(f"{self.name}_bucket{lbl} {counts[i]}")
                lbl = "{" + (base + "," if base else "") + 'le="+Inf"' + "}"
                out.append(f"{self.name}_bucket{lbl} {counts[-1]}")
                sfx = "{" + base + "}" if base else ""
                out.append(f"{self.name}_sum{sfx} {self._sums[key]}")
                out.append(f"{self.name}_count{sfx} {counts[-1]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._names: set[str] = set()
        self._lock = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str = "",
                labels: tuple = ()) -> Counter:
        return self._add(Counter(f"{NAMESPACE}_{subsystem}_{name}", help_,
                                 tuple(labels)))

    def gauge(self, subsystem: str, name: str, help_: str = "",
              labels: tuple = ()) -> Gauge:
        return self._add(Gauge(f"{NAMESPACE}_{subsystem}_{name}", help_,
                               tuple(labels)))

    def histogram(self, subsystem: str, name: str, help_: str = "",
                  labels: tuple = (), buckets=None) -> Histogram:
        return self._add(
            Histogram(f"{NAMESPACE}_{subsystem}_{name}", help_,
                      tuple(labels), buckets)
        )

    def _add(self, m: _Metric):
        with self._lock:
            if m.name in self._names:
                raise ValueError(f"metric {m.name!r} already registered")
            self._names.add(m.name)
            self._metrics.append(m)
        return m

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._names.clear()

    def expose_text(self, openmetrics: bool = False) -> str:
        """Text exposition; `openmetrics=True` adds exemplar suffixes to
        histogram bucket lines (served only on explicit opt-in —
        GET /metrics?exemplars=1 — since the classic format has no
        exemplar syntax)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            if openmetrics and isinstance(m, Histogram):
                lines.extend(m.expose_openmetrics())
            else:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


DEFAULT_REGISTRY = Registry()


# -- subsystem bundles (reference */metrics.go) -----------------------------

# Sub-second buckets for the tx-lifecycle waterfall: single-node stage
# latencies live in the 0.5ms–2.5s band (admission windows are ~ms,
# consensus rounds ~100ms–1s); the default buckets start too coarse.
TX_STAGE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5)


class ConsensusMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.height = reg.gauge("consensus", "height", "Current height")
        self.rounds = reg.gauge("consensus", "rounds", "Round of the height")
        self.validators = reg.gauge("consensus", "validators",
                                    "Validator count")
        self.missing_validators = reg.gauge(
            "consensus", "missing_validators",
            "Validators absent from the last commit")
        self.block_interval_seconds = reg.histogram(
            "consensus", "block_interval_seconds",
            "Time between consecutive blocks")
        self.num_txs = reg.gauge("consensus", "num_txs", "Txs in last block")
        self.block_size_bytes = reg.gauge("consensus", "block_size_bytes",
                                          "Last block size")
        self.total_txs = reg.counter("consensus", "total_txs",
                                     "Total committed txs")
        self.step_duration_seconds = reg.histogram(
            "consensus", "step_duration_seconds",
            "Time spent in each consensus step", labels=("step",))
        # tx lifecycle observatory (utils/txlife.py): consensus-side
        # waterfall stages + the end-to-end arrival->commit latency,
        # bucket exemplars carrying sampled tx hashes
        self.tx_stage_seconds = reg.histogram(
            "consensus", "tx_stage_seconds",
            "Per-tx lifecycle stage latency, consensus-side stages "
            "(proposal_wait/consensus/apply/notify); sampled txs only",
            labels=("stage",), buckets=TX_STAGE_BUCKETS)
        self.tx_commit_seconds = reg.histogram(
            "consensus", "tx_commit_seconds",
            "Per-tx end-to-end arrival->commit latency; sampled txs only",
            buckets=TX_STAGE_BUCKETS)
        # speculative proposal assembly (ISSUE 11): hit = the block built
        # during the previous height's commit gap was consumed bit-exact
        # by enter_propose; discard = a round bump, valid_block lock,
        # late precommit, or mempool update invalidated it
        self.speculation_total = reg.counter(
            "consensus", "speculation_total",
            "Speculative proposal assemblies by outcome",
            labels=("outcome",))
        # certificate-native consensus (ISSUE 17): one AggregateCommit
        # frame replaces N precommit frames for catchup gossip
        self.cert_gossip_total = reg.counter(
            "consensus", "cert_gossip_total",
            "Aggregate-precommit certificates received via gossip, by "
            "outcome (applied/dup/redundant/stale/invalid/non_bls/"
            "disabled)", labels=("outcome",))


class MempoolMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.size = reg.gauge("mempool", "size", "Pending txs")
        self.failed_txs = reg.counter("mempool", "failed_txs",
                                      "CheckTx rejections")
        self.recheck_times = reg.counter("mempool", "recheck_times",
                                         "Post-block rechecks")
        self.tx_bytes = reg.gauge(
            "mempool", "tx_bytes",
            "Total bytes of pending txs (running counter, not a scan)")
        # micro-batched admission pipeline (PR 8): windows amortize the
        # app round-trip + signature verify + lock acquisition
        self.admit_window_size = reg.histogram(
            "mempool", "admit_window_size",
            "Txs per admission window drained by the pipeline")
        self.admit_queue_depth = reg.gauge(
            "mempool", "admit_queue_depth",
            "Txs waiting in the admission queue")
        self.admit_latency = reg.histogram(
            "mempool", "admit_latency",
            "Seconds from enqueue to admission verdict")
        # tx lifecycle observatory (utils/txlife.py): mempool-side
        # waterfall stages, bucket exemplars carrying sampled tx hashes
        self.tx_stage_seconds = reg.histogram(
            "mempool", "tx_stage_seconds",
            "Per-tx lifecycle stage latency, mempool-side stages "
            "(admit_wait/verify/app_check); sampled txs only",
            labels=("stage",), buckets=TX_STAGE_BUCKETS)


class P2PMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.peers = reg.gauge("p2p", "peers", "Connected peers")
        self.message_receive_bytes_total = reg.counter(
            "p2p", "message_receive_bytes_total", "Bytes received",
            labels=("chan",))
        self.message_send_bytes_total = reg.counter(
            "p2p", "message_send_bytes_total", "Bytes sent",
            labels=("chan",))
        # Per-peer reactor state (VERDICT Next #3: the rejoin-stall
        # debugging data) — fed from the consensus reactor's PeerState.
        self.peer_height = reg.gauge(
            "p2p", "peer_height", "Last known consensus height per peer",
            labels=("peer",))
        self.peer_round = reg.gauge(
            "p2p", "peer_round", "Last known consensus round per peer",
            labels=("peer",))
        # backpressure-aware broadcast queue (tx gossip off the
        # admission path): depth is load, drops are shed backlog
        self.broadcast_queue_depth = reg.gauge(
            "p2p", "broadcast_queue_depth",
            "Frames waiting in the async broadcast queue")
        self.broadcast_queue_dropped = reg.counter(
            "p2p", "broadcast_queue_dropped",
            "Frames dropped from a saturated broadcast queue")
        self.broadcast_queue_wait_seconds = reg.histogram(
            "p2p", "broadcast_queue_wait_seconds",
            "Enqueue->send wait of frames in the async broadcast queue",
            buckets=TX_STAGE_BUCKETS)
        # per-channel MConnection send backlog (ISSUE 11): messages
        # queued or mid-flight on the channel, summed across peers —
        # the instrument that shows where the zero-copy send path backs
        # up under sustained block-part fan-out
        self.send_queue_depth = reg.gauge(
            "p2p", "send_queue_depth",
            "Messages queued on an MConnection send channel",
            labels=("chan",))


class StateMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.block_processing_time = reg.histogram(
            "state", "block_processing_time",
            "ApplyBlock wall time (reference execution.go:230)")
        self.block_verify_time = reg.histogram(
            "state", "block_verify_time",
            "Commit signature verification wall time (TPU kernel path)")


class StoreMetrics:
    # commit bytes span ~100 B certificates to multi-MB signature
    # columns at 10k validators
    COMMIT_BUCKETS = (128, 512, 2048, 8192, 32768, 131072, 524288, 2097152)

    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.commit_bytes = reg.histogram(
            "store", "commit_bytes",
            "Encoded canonical-commit bytes written per block "
            "(certificate-native BLS heights shrink this ~N/1)",
            buckets=StoreMetrics.COMMIT_BUCKETS)


class BlockSyncMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.syncing = reg.gauge("blocksync", "syncing",
                                 "1 while block sync is running")
        self.latest_block_height = reg.gauge(
            "blocksync", "latest_block_height",
            "Highest height applied by block sync")
        self.num_peers = reg.gauge("blocksync", "num_peers",
                                   "Peers in the block pool")
        self.pending_requests = reg.gauge(
            "blocksync", "pending_requests",
            "In-flight block requests without a block yet")
        self.peer_height = reg.gauge(
            "blocksync", "peer_height",
            "Reported chain height per pool peer", labels=("peer",))
        self.blocks_applied_total = reg.counter(
            "blocksync", "blocks_applied_total",
            "Blocks verified and applied by block sync")
        self.bad_blocks_total = reg.counter(
            "blocksync", "bad_blocks_total",
            "Blocks that failed verification (request redone)")
        self.cert_verify_seconds = reg.histogram(
            "blocksync", "cert_verify_seconds",
            "Certificate (one-pairing) commit verification wall time "
            "during replay, per commit", buckets=TX_STAGE_BUCKETS)


class StateSyncMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.syncing = reg.gauge("statesync", "syncing",
                                 "1 while state sync is running")
        self.snapshots_discovered_total = reg.counter(
            "statesync", "snapshots_discovered_total",
            "Snapshots offered by peers")
        self.chunks_applied_total = reg.counter(
            "statesync", "chunks_applied_total",
            "Snapshot chunks accepted by the app")


class LightClientMetrics:
    PROOF_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)

    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.headers_verified_total = reg.counter(
            "light", "headers_verified_total",
            "Light blocks verified (sequential + skipping)")
        self.bisections_total = reg.counter(
            "light", "bisections_total",
            "Bisection steps taken during skipping verification")
        self.serve_subscribers = reg.gauge(
            "light", "serve_subscribers",
            "Live /light_stream subscribers on the serving surface")
        self.verify_cache_hits_total = reg.counter(
            "light", "verify_cache_hits_total",
            "Verified-commit cache hits (fan-out amortized over one "
            "VerifyCommitLight per height)")
        self.verify_cache_misses_total = reg.counter(
            "light", "verify_cache_misses_total",
            "Verified-commit cache misses (each pays one batch verify)")
        self.proof_bytes = reg.histogram(
            "light", "proof_bytes",
            "Encoded MMR ancestry proof sizes served to light clients",
            buckets=self.PROOF_BUCKETS)
        self.stream_dropped_total = reg.counter(
            "light", "stream_dropped_total",
            "Stream payloads dropped oldest-first on slow subscribers")


class DAMetrics:
    # DA openings carry a whole chunk, so the buckets run larger than
    # the light-client MMR proof sizes
    PROOF_BUCKETS = (128, 256, 512, 1024, 2048, 4096, 8192, 16384)

    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.samples_served_total = reg.counter(
            "da", "samples_served_total",
            "Chunk+proof samples served to DAS clients")
        self.proof_bytes = reg.histogram(
            "da", "proof_bytes",
            "Per-sample opening sizes (chunk + Merkle path) served",
            buckets=self.PROOF_BUCKETS)
        self.reconstruct_total = reg.counter(
            "da", "reconstruct_total",
            "Reed-Solomon reconstructions attempted from sampled shards")
        self.pc_commits_total = reg.counter(
            "da", "pc_commits_total",
            "Payloads committed on the 2D polynomial-commitment track")
        self.pc_samples_served_total = reg.counter(
            "da", "pc_samples_served_total",
            "Multiproof (row, columns) samples served to DAS clients")
        self.pc_proof_bytes = reg.histogram(
            "da", "pc_proof_bytes",
            "Per-sample multiproof response sizes (evals + one opening)",
            buckets=self.PROOF_BUCKETS)


class CryptoMetrics:
    BATCH_BUCKETS = (1, 64, 256, 1024, 4096, 10240, 16384, 65536)

    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.batch_size = reg.histogram(
            "crypto", "batch_size", "Ed25519 batch-verify sizes",
            buckets=self.BATCH_BUCKETS)
        self.path_selected_total = reg.counter(
            "crypto", "path_selected_total",
            "Dispatch decisions per verify path "
            "(native/rlc/ladder/delta/cpu) and curve",
            labels=("path", "curve"))
        self.verify_seconds = reg.histogram(
            "crypto", "verify_seconds",
            "Batch-verify wall time submit→result",
            labels=("path", "curve"))
        self.calibration_us_per_sig = reg.gauge(
            "crypto", "calibration_us_per_sig",
            "Calibrated host-stage dispatch terms", labels=("term",))
        self.msm_native_total = reg.counter(
            "crypto", "msm_native_total",
            "G1 multi-scalar multiplications run on the native "
            "Pippenger engine")
        self.msm_oracle_total = reg.counter(
            "crypto", "msm_oracle_total",
            "G1 multi-scalar multiplications that fell back to the "
            "Python oracle")
        self.mesh_devices = reg.gauge(
            "crypto", "mesh_devices",
            "Device count of the active verify mesh (0/absent = mesh off)")
        self.mesh_batches_total = reg.counter(
            "crypto", "mesh_batches_total",
            "Batches placed per mesh device: sharded mega-batch shards "
            "and streamed whole-commit placements (skew attribution)",
            labels=("device", "mode"))
        self.sched_queue_depth = reg.gauge(
            "crypto", "sched_queue_depth",
            "Verify requests queued in the shared scheduler, per tenant",
            labels=("tenant",))
        self.sched_coalesced_total = reg.counter(
            "crypto", "sched_coalesced_total",
            "Verify requests that shared a coalesced mega-batch dispatch, "
            "per request source (consensus/blocksync/light/admission)",
            labels=("source",))
        self.sched_batch_sigs = reg.histogram(
            "crypto", "sched_batch_sigs",
            "Signatures per coalesced scheduler dispatch",
            buckets=CryptoMetrics.BATCH_BUCKETS)


class ReplicationMetrics:
    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.feed_subscribers = reg.gauge(
            "replication", "feed_subscribers",
            "Live replication-feed subscribers (serving replicas)")
        self.feed_frames_total = reg.counter(
            "replication", "feed_frames_total",
            "Frames emitted on the replication feed")
        self.feed_bytes_total = reg.counter(
            "replication", "feed_bytes_total",
            "Frame bytes fanned out to feed subscribers")
        self.feed_lag_heights = reg.gauge(
            "replication", "feed_lag_heights",
            "Replica apply lag behind the core tip, in heights "
            "(readiness input for the replica /healthz)")
        self.replica_applied_total = reg.counter(
            "replication", "replica_applied_total",
            "Feed frames applied into replica serving state")
        self.replica_apply_seconds = reg.histogram(
            "replication", "replica_apply_seconds",
            "Per-frame replica apply latency (decode + DA re-encode + "
            "MMR append)", buckets=TX_STAGE_BUCKETS)
        self.forwarded_txs_total = reg.counter(
            "replication", "forwarded_txs_total",
            "broadcast_tx_* forwarded replica->core by tenant and outcome "
            "(ok/rejected/error)", labels=("tenant", "outcome"))


class WatchtowerMetrics:
    """Streaming safety auditor bundle (watchtower/auditor.py)."""

    def __init__(self, reg: Registry | None = None):
        reg = reg or DEFAULT_REGISTRY
        self.checks_total = reg.counter(
            "watchtower", "checks_total",
            "Audit checks run, by check (fork/equivocation/cert/da/"
            "stall) and outcome (ok/violation/error)",
            labels=("check", "outcome"))
        self.alarm = reg.gauge(
            "watchtower", "alarm",
            "1 while a check's alarm is raised, 0 once clear "
            "(safety alarms latch for the life of the auditor)",
            labels=("check",))
        self.feed_lag_heights = reg.gauge(
            "watchtower", "feed_lag_heights",
            "Audit lag behind each watched node's feed tip, in heights",
            labels=("node",))
        self.audit_seconds = reg.histogram(
            "watchtower", "audit_seconds",
            "Per-height audit latency (all checks against one frame)",
            labels=("check",), buckets=TX_STAGE_BUCKETS)
        self.evidence_submitted_total = reg.counter(
            "watchtower", "evidence_submitted_total",
            "DuplicateVoteEvidence submissions back to watched nodes "
            "over RPC, by outcome (ok/rejected/error)",
            labels=("outcome",))


_BUNDLES: dict[str, object] = {}
_BUNDLES_LOCK = threading.Lock()


def _bundle(name: str, cls):
    b = _BUNDLES.get(name)
    if b is None:
        with _BUNDLES_LOCK:
            b = _BUNDLES.get(name)
            if b is None:
                b = _BUNDLES[name] = cls()
    return b


def consensus_metrics() -> ConsensusMetrics:
    return _bundle("consensus", ConsensusMetrics)


def mempool_metrics() -> MempoolMetrics:
    return _bundle("mempool", MempoolMetrics)


def p2p_metrics() -> P2PMetrics:
    return _bundle("p2p", P2PMetrics)


def state_metrics() -> StateMetrics:
    return _bundle("state", StateMetrics)


def blocksync_metrics() -> BlockSyncMetrics:
    return _bundle("blocksync", BlockSyncMetrics)


def store_metrics() -> StoreMetrics:
    return _bundle("store", StoreMetrics)


def statesync_metrics() -> StateSyncMetrics:
    return _bundle("statesync", StateSyncMetrics)


def light_metrics() -> LightClientMetrics:
    return _bundle("light", LightClientMetrics)


def da_metrics() -> DAMetrics:
    return _bundle("da", DAMetrics)


def crypto_metrics() -> CryptoMetrics:
    return _bundle("crypto", CryptoMetrics)


def replication_metrics() -> ReplicationMetrics:
    return _bundle("replication", ReplicationMetrics)


def watchtower_metrics() -> WatchtowerMetrics:
    return _bundle("watchtower", WatchtowerMetrics)


def reset_bundles() -> None:
    """Test hook: drop all bundles and empty DEFAULT_REGISTRY in place.

    In-place (`Registry.clear`) so references held by a live
    `MetricsServer` keep working; the duplicate-name guard permits
    re-registration after the clear.
    """
    with _BUNDLES_LOCK:
        _BUNDLES.clear()
        DEFAULT_REGISTRY.clear()


def _default_height_fn() -> float:
    """Consensus height as the liveness signal for /healthz: the bundle
    gauge is set by `_finalize_commit` on every decided block."""
    return consensus_metrics().height.values().get((), 0.0)


class MetricsServer:
    """Serves the registry at /metrics (reference prometheus listener).

    Routes:

    * ``GET /metrics`` — classic text exposition. Append
      ``?exemplars=1`` for OpenMetrics-style exemplar suffixes on
      histogram buckets (opt-in: classic scrapes must stay parseable).
    * ``GET /healthz`` — liveness for e2e drivers and soak loops: 200
      while consensus height has advanced within `health_window_s`
      seconds, 503 once it stalls longer than that. The server start is
      treated as an advance (grace window for boot/genesis). JSON body
      with height / seconds-since-advance either way. An optional
      ``ready_fn() -> (bool, dict)`` gates readiness on top of the
      stall check (serving replicas report 503 while snapshot-
      bootstrapping or lagging the feed); its detail dict is merged
      into the JSON body.

    Other paths get 404, other methods 405 — matching what a prometheus
    scraper expects from a metrics endpoint.
    """

    def __init__(self, registry: Registry | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 health_window_s: float = 30.0, height_fn=None,
                 ready_fn=None):
        reg = registry or DEFAULT_REGISTRY
        height_fn = height_fn or _default_height_fn
        # health state shared with handler threads: last observed height
        # and the monotonic instant it last changed
        health = {"height": None, "advanced": time.monotonic()}
        health_lock = threading.Lock()
        window_s = float(health_window_s)

        def health_probe() -> tuple[bool, dict]:
            try:
                h = float(height_fn())
            except Exception:  # noqa: BLE001 — probe must not 500
                h = 0.0
            now = time.monotonic()
            with health_lock:
                if health["height"] is None or h != health["height"]:
                    health["height"] = h
                    health["advanced"] = now
                idle = now - health["advanced"]
            ok = idle <= window_s
            info = {"status": "ok" if ok else "stalled",
                    "height": h,
                    "since_advance_s": round(idle, 3),
                    "window_s": window_s}
            if ready_fn is not None:
                try:
                    ready, detail = ready_fn()
                except Exception:  # noqa: BLE001 — probe must not 500
                    ready, detail = False, {"ready_error": True}
                info.update(detail)
                if not ready:
                    ok = False
                    info["status"] = "not_ready"
            return ok, info

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _refuse(self, code: int, msg: str):
                body = msg.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    ok, info = health_probe()
                    body = (json.dumps(info) + "\n").encode()
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path != "/metrics":
                    self._refuse(404, "not found; metrics at /metrics\n")
                    return
                om = "exemplars=1" in query.split("&")
                body = reg.expose_text(openmetrics=om).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _method_not_allowed(self):
                self._refuse(405, "method not allowed\n")

            do_POST = _method_not_allowed
            do_PUT = _method_not_allowed
            do_DELETE = _method_not_allowed
            do_PATCH = _method_not_allowed
            do_HEAD = _method_not_allowed

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
