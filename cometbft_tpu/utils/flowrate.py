"""Sliding-window transfer-rate monitor (reference internal/flowrate).

Tracks bytes over a window to expose an average rate and an optional
limiter (reference flowrate.Monitor/Limit); used by block-sync peer
scoring and MConnection throttling.
"""

from __future__ import annotations

import threading
import time


class Monitor:
    def __init__(self, window_s: float = 10.0, now=None):
        self._window = window_s
        self._now = now or time.monotonic
        self._samples: list[tuple[float, int]] = []
        self._total = 0
        self._lock = threading.Lock()

    def update(self, n: int) -> None:
        t = self._now()
        with self._lock:
            self._samples.append((t, n))
            self._total += n
            self._trim(t)

    def _trim(self, t: float) -> None:
        cutoff = t - self._window
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.pop(0)

    def rate(self) -> float:
        """Bytes/second over the window."""
        t = self._now()
        with self._lock:
            self._trim(t)
            if not self._samples:
                return 0.0
            span = max(t - self._samples[0][0], 1e-9)
            return sum(n for _, n in self._samples) / span

    def total(self) -> int:
        with self._lock:
            return self._total

    def limit(self, want: int, rate_limit: float) -> int:
        """How many of `want` bytes may be sent now to respect rate_limit
        (0 = wait); simple token calculation over the window."""
        if rate_limit <= 0:
            return want
        current = self.rate()
        if current >= rate_limit:
            return 0
        burst = int((rate_limit - current) * self._window / 4)
        return max(0, min(want, burst))
