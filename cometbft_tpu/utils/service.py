"""Service lifecycle base (reference libs/service/service.go).

BaseService gives every long-running component the same contract the
reference enforces: idempotent start (ErrAlreadyStarted), stop exactly
once (ErrAlreadyStopped), a quit event background loops select on, wait
for termination, and reset-after-stop. Subclasses implement on_start /
on_stop; the provided `spawn` helper tracks daemon threads so stop can
join them.
"""

from __future__ import annotations

import threading


class ErrAlreadyStarted(RuntimeError):
    pass


class ErrAlreadyStopped(RuntimeError):
    pass


class BaseService:
    def __init__(self, name: str | None = None):
        self.name = name or type(self).__name__
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self._mtx = threading.Lock()
        self._threads: list[threading.Thread] = []

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        with self._mtx:
            if self._stopped:
                raise ErrAlreadyStopped(
                    f"{self.name} stopped; reset() before restarting"
                )
            if self._started:
                raise ErrAlreadyStarted(f"{self.name} already started")
            self._started = True
        self.on_start()

    def stop(self) -> None:
        with self._mtx:
            if self._stopped:
                raise ErrAlreadyStopped(f"{self.name} already stopped")
            self._stopped = True
        self._quit.set()
        self.on_stop()
        for t in self._threads:
            t.join(timeout=5)

    def reset(self) -> None:
        """Stop -> reset -> start is the reference's restart contract
        (service.go Reset: only valid on a stopped service)."""
        with self._mtx:
            if not self._stopped:
                raise RuntimeError(f"{self.name} must be stopped to reset")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
            self._threads = []

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the service quits (reference Service.Wait)."""
        return self._quit.wait(timeout)

    @property
    def quit(self) -> threading.Event:
        return self._quit

    # -- template hooks -------------------------------------------------
    def on_start(self) -> None:  # noqa: B027 — optional hook
        pass

    def on_stop(self) -> None:  # noqa: B027 — optional hook
        pass

    # -- helpers --------------------------------------------------------
    def spawn(self, fn, *args, name: str | None = None) -> threading.Thread:
        t = threading.Thread(
            target=fn, args=args, daemon=True,
            name=name or f"{self.name}-worker",
        )
        self._threads.append(t)
        t.start()
        return t
