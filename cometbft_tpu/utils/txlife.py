"""Per-transaction lifecycle observatory (ISSUE 9 tentpole).

A sampled tx-hash tracker that stamps monotonic timestamps at every
stage a transaction crosses on its way to a block:

    arrival          RPC broadcast_tx_* or mempool gossip receive
    enqueue          admission-queue submit (pipeline path)
    verify_start     window signature-verify stage opens
    verify_end       window signature-verify stage closes
    app_check        app CheckTx accepted the tx
    insert           tx entered the mempool FIFO
    reap             proposer reaped it into a proposal block
    gossip           first block-bytes/part broadcast of that proposal
    prevote_quorum   +2/3 prevotes for the block containing it
    precommit_quorum +2/3 precommits (enter_commit)
    apply            FinalizeBlock returned for its block
    commit           app Commit finished for its block
    notify           event bus published its Tx event

Sampling is a deterministic hash prefix — ``sha256(tx)[:4]`` below a
threshold derived from ``rate`` (1 in N, default 64) — so every node
samples the SAME txs without coordination, and the traceview merger can
correlate a tx's ``tx.lifecycle`` records across per-node sinks through
the existing clock alignment. Each stamp is recorded at most once per
tx per stage (first wins: re-gossiped duplicates don't restamp), with a
``mono`` perf_counter value for exact within-process deltas; analyzers
fall back to the aligned wall clock across processes.

Two consumers ride on the stamps:

* trace records (``tx.lifecycle`` events in the JSONL sink) feeding
  utils/traceview.py + tools/latency_analyze.py — the stage waterfall
  that decomposes p50/p99 commit latency;
* per-stage Prometheus histograms (mempool/consensus bundles) observed
  on the fly, with the tx hash attached as an exemplar so a p99 bucket
  links back to a concrete trace.

Cost model: the hot-path guard is one module bool (``txlife.enabled``),
mirroring utils/trace.py. Per SAMPLED tx the work is a few dict ops
under a small lock; per unsampled tx it is one 4-byte int compare
(callers that already hold the tx key) or one sha256 (arrival sites).
Block-sweep stamp sites (reap/quorum/apply) hash each block's txs once
and cache the sampled subset. tools/trace_overhead.py --lifecycle
measures the end-to-end block-rate cost against the <=5% budget.

Configuration: ``[instrumentation] txlife_sample_rate`` (node config)
or the ``COMETBFT_TPU_TXLIFE`` env var (wins over config; picked up at
import by subprocess nodes). 0 disables the tracker entirely.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict

from . import trace as _trace
from .metrics import consensus_metrics, mempool_metrics

DEFAULT_RATE = 64

# All stage names, in causal order (informational; duplicates tolerated
# across paths — e.g. the direct admission path never stamps enqueue).
STAGES = (
    "arrival", "enqueue", "verify_start", "verify_end", "app_check",
    "insert", "reap", "gossip", "prevote_quorum", "precommit_quorum",
    "apply", "commit", "notify",
)

# The telescoping boundary chain: consecutive boundaries define the
# 7-stage waterfall below, so per-tx stage spans sum EXACTLY to the
# end-to-end arrival->notify latency when every boundary is present.
BOUNDARIES = (
    "arrival", "verify_start", "verify_end", "insert", "reap",
    "precommit_quorum", "commit", "notify",
)

# (waterfall label, start stages in preference order, end stage).
# app_check spans verify_end->insert (the app round plus the µs-scale
# locked insert); apply spans precommit_quorum->commit (validate +
# FinalizeBlock + Commit).
WATERFALL = (
    ("admit_wait",    ("arrival", "enqueue"), "verify_start"),
    ("verify",        ("verify_start",),      "verify_end"),
    ("app_check",     ("verify_end",),        "insert"),
    ("proposal_wait", ("insert",),            "reap"),
    ("consensus",     ("reap",),              "precommit_quorum"),
    ("apply",         ("precommit_quorum",),  "commit"),
    ("notify",        ("commit",),            "notify"),
)
_BY_END = {end: (label, starts) for label, starts, end in WATERFALL}
_MEMPOOL_LABELS = frozenset(("admit_wait", "verify", "app_check"))

# Live per-tx stage maps, LRU-capped: txs that never commit (rejected,
# evicted, node behind) must not grow memory without bound.
MAX_LIVE = 4096

rate: int = DEFAULT_RATE
enabled: bool = rate > 0
_threshold32: int = (1 << 32) // rate if rate else 0

_lock = threading.Lock()
_live: "OrderedDict[bytes, dict[str, float]]" = OrderedDict()


def configure(sample_rate: int) -> None:
    """Set the sampling rate (1 in N; 0 disables). Node startup calls
    this with ``instrumentation.txlife_sample_rate`` unless the
    COMETBFT_TPU_TXLIFE env var already chose at import time."""
    global rate, enabled, _threshold32
    r = max(0, int(sample_rate))
    rate = r
    enabled = r > 0
    _threshold32 = (1 << 32) // r if r else 0


def reset() -> None:
    """Test hook: drop live state and restore the import-time rate."""
    with _lock:
        _live.clear()
    env = os.environ.get("COMETBFT_TPU_TXLIFE")
    if env is not None:
        try:
            configure(int(env))
            return
        except ValueError:
            pass
    configure(DEFAULT_RATE)


def key_of(tx: bytes) -> bytes:
    return hashlib.sha256(bytes(tx)).digest()


def sampled(key: bytes) -> bool:
    """Deterministic hash-prefix sampling decision for a tx key."""
    return enabled and int.from_bytes(key[:4], "big") < _threshold32


def sampled_keys(txs) -> list[tuple[int, bytes]]:
    """[(index, key)] for the sampled txs of a block/window — hash each
    tx once; callers cache the result per block."""
    if not enabled:
        return []
    th = _threshold32
    out = []
    for i, tx in enumerate(txs):
        k = hashlib.sha256(bytes(tx)).digest()
        if int.from_bytes(k[:4], "big") < th:
            out.append((i, k))
    return out


def track(tx: bytes, stage: str, **fields) -> None:
    """Stamp `stage` for a raw tx (hashes it; arrival-site helper)."""
    if enabled:
        stage_key(key_of(tx), stage, **fields)


def stage_block(pairs, stage: str, **fields) -> None:
    """Stamp `stage` for every (index, key) pair of a sampled block."""
    for _i, k in pairs:
        stage_key(k, stage, **fields)


def stage_key(key: bytes, stage: str, **fields) -> None:
    """Stamp `stage` for a tx key (first stamp per stage wins). Feeds
    the per-stage histograms and emits one tx.lifecycle trace record."""
    if not enabled or key is None:
        return
    if int.from_bytes(key[:4], "big") >= _threshold32:
        return
    now = time.perf_counter()
    delta = label = None
    e2e = None
    with _lock:
        st = _live.get(key)
        if st is None:
            st = _live[key] = {}
            while len(_live) > MAX_LIVE:
                _live.popitem(last=False)
        elif stage in st:
            return
        else:
            _live.move_to_end(key)
        st[stage] = now
        wf = _BY_END.get(stage)
        if wf is not None:
            label, starts = wf
            for s in starts:
                t0 = st.get(s)
                if t0 is not None:
                    delta = now - t0
                    break
        if stage == "commit":
            t0 = st.get("arrival")
            if t0 is not None:
                e2e = now - t0
        if stage == "notify":
            _live.pop(key, None)
    txhex = key.hex()[:16]
    if delta is not None:
        if label in _MEMPOOL_LABELS:
            mempool_metrics().tx_stage_seconds.observe(
                delta, label, exemplar=txhex)
        else:
            consensus_metrics().tx_stage_seconds.observe(
                delta, label, exemplar=txhex)
    if e2e is not None:
        consensus_metrics().tx_commit_seconds.observe(e2e, exemplar=txhex)
    if _trace.enabled:
        _trace.emit("tx.lifecycle", "event", tx=txhex, stage=stage,
                    mono=round(now, 6), **fields)


_env = os.environ.get("COMETBFT_TPU_TXLIFE")
if _env is not None:
    try:
        configure(int(_env))
    except ValueError:
        pass
del _env
