"""Crash-point injection for crash/recovery testing.

Behavior parity: reference internal/fail/fail.go — `fail_point()` is
sprinkled at every dangerous gap in ApplyBlock/finalizeCommit
(reference internal/state/execution.go:251,258,293,301 and the WAL vote
path state.go:843); when the FAIL_TEST_INDEX environment variable is
set to N, the N-th call kills the process, letting tests verify that
WAL + handshake replay recover from a crash at exactly that point.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_call_index = -1


def _target() -> int:
    v = os.environ.get("FAIL_TEST_INDEX")
    return int(v) if v else -1


def fail_point() -> None:
    """Die (exit code 1) if this is the FAIL_TEST_INDEX-th call."""
    global _call_index
    target = _target()
    if target < 0:
        return
    with _lock:
        _call_index += 1
        if _call_index == target:
            os._exit(1)


def reset() -> None:
    global _call_index
    with _lock:
        _call_index = -1
