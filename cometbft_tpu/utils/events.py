"""Synchronous event switch (reference libs/events/events.go).

The older fire-and-listen callback registry the reference keeps beside
the query-based pubsub event bus: listeners register per event name and
fire_event invokes them inline. Used where a component wants plain
callbacks without subscription plumbing (the reference's consensus
internals use it for round-state notifications).
"""

from __future__ import annotations

import threading


class EventSwitch:
    def __init__(self):
        self._lock = threading.Lock()
        # event name -> {listener id -> callback}
        self._listeners: dict[str, dict[str, object]] = {}

    def add_listener(self, listener_id: str, event: str, cb) -> None:
        with self._lock:
            self._listeners.setdefault(event, {})[listener_id] = cb

    def remove_listener(self, listener_id: str, event: str | None = None) -> None:
        with self._lock:
            events = [event] if event else list(self._listeners)
            for e in events:
                self._listeners.get(e, {}).pop(listener_id, None)

    def fire_event(self, event: str, data=None) -> None:
        with self._lock:
            cbs = list(self._listeners.get(event, {}).values())
        for cb in cbs:
            cb(data)
