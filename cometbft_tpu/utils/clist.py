"""Concurrent doubly-linked list with blocking iteration (reference
libs/clist/clist.go).

The reference's mempool/evidence gossip routines park on the list tail:
`front()` / `CElement.next_wait()` block until an element exists, so a
gossip goroutine wakes exactly when there is something new to send
instead of polling. Removal keeps detached elements traversable
(`removed` flag + next/prev kept) so an iterator standing on a removed
element can step off it, exactly as the reference documents.
"""

from __future__ import annotations

import threading


class CElement:
    __slots__ = ("value", "_next", "_prev", "removed", "_cond")

    def __init__(self, value, cond: threading.Condition):
        self.value = value
        self._next: CElement | None = None
        self._prev: CElement | None = None
        self.removed = False
        self._cond = cond

    def next(self) -> "CElement | None":
        with self._cond:
            return self._next

    def prev(self) -> "CElement | None":
        with self._cond:
            return self._prev

    def next_wait(self, timeout: float | None = None) -> "CElement | None":
        """Block until this element has a successor OR it is removed
        (a removed element's next is whatever followed it)."""
        with self._cond:
            while self._next is None and not self.removed:
                if not self._cond.wait(timeout):
                    return None
            return self._next


class CList:
    def __init__(self, max_len: int | None = None):
        self._cond = threading.Condition()
        self._head: CElement | None = None
        self._tail: CElement | None = None
        self._len = 0
        self.max_len = max_len

    def __len__(self) -> int:
        with self._cond:
            return self._len

    def front(self) -> CElement | None:
        with self._cond:
            return self._head

    def back(self) -> CElement | None:
        with self._cond:
            return self._tail

    def front_wait(self, timeout: float | None = None) -> CElement | None:
        with self._cond:
            while self._head is None:
                if not self._cond.wait(timeout):
                    return None
            return self._head

    def push_back(self, value) -> CElement:
        with self._cond:
            if self.max_len is not None and self._len >= self.max_len:
                raise OverflowError(f"clist maxed at {self.max_len}")
            el = CElement(value, self._cond)
            el._prev = self._tail
            if self._tail is not None:
                self._tail._next = el
            else:
                self._head = el
            self._tail = el
            self._len += 1
            self._cond.notify_all()
            return el

    def remove(self, el: CElement) -> None:
        with self._cond:
            if el.removed:
                return
            el.removed = True
            if el._prev is not None:
                el._prev._next = el._next
            else:
                self._head = el._next
            if el._next is not None:
                el._next._prev = el._prev
            else:
                self._tail = el._prev
            self._len -= 1
            # wake waiters parked on el.next_wait(): removal is progress
            self._cond.notify_all()

    def __iter__(self):
        el = self.front()
        while el is not None:
            if not el.removed:
                yield el.value
            el = el.next()
