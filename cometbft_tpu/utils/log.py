"""Leveled key-value logger (reference libs/log).

The reference logs structured key-value pairs with per-module levels
(libs/log/tm_logger.go + filter.go) and lazy formatting. This maps that
onto a thin layer: Logger.with_fields binds context (module, peer,
height...), level filtering happens before any formatting work, and the
sink is pluggable (stderr text by default; tests capture records).
"""

from __future__ import annotations

import sys
import threading
import time

DEBUG, INFO, WARN, ERROR, NONE = 10, 20, 30, 40, 100
_NAMES = {DEBUG: "D", INFO: "I", WARN: "W", ERROR: "E"}
_LEVELS = {"debug": DEBUG, "info": INFO, "warn": WARN, "error": ERROR,
           "none": NONE}


class _Config:
    def __init__(self):
        self.default_level = INFO
        self.module_levels: dict[str, int] = {}
        self.sink = self._stderr_sink
        self._lock = threading.Lock()

    @staticmethod
    def _stderr_sink(level: int, msg: str, fields: dict) -> None:
        ts = time.strftime("%H:%M:%S")
        kv = " ".join(f"{k}={v}" for k, v in fields.items())
        sys.stderr.write(f"{_NAMES.get(level, '?')}[{ts}] {msg} {kv}\n")


_config = _Config()


def set_level(spec: str) -> None:
    """'info' or per-module 'consensus:debug,p2p:none,*:info'
    (reference log level flag format)."""
    with _config._lock:
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                mod, _, lvl = part.partition(":")
                lv = _LEVELS.get(lvl.strip())
                if lv is None:
                    raise ValueError(f"unknown log level {lvl!r}")
                if mod == "*":
                    _config.default_level = lv
                else:
                    _config.module_levels[mod.strip()] = lv
            else:
                lv = _LEVELS.get(part)
                if lv is None:
                    raise ValueError(f"unknown log level {part!r}")
                _config.default_level = lv


def set_sink(sink) -> None:
    """sink(level, msg, fields) — tests and alternative outputs."""
    _config.sink = sink


class Logger:
    __slots__ = ("module", "fields")

    def __init__(self, module: str, fields: dict | None = None):
        self.module = module
        self.fields = fields or {}

    def with_fields(self, **kw) -> "Logger":
        merged = dict(self.fields)
        merged.update(kw)
        return Logger(self.module, merged)

    def _enabled(self, level: int) -> bool:
        floor = _config.module_levels.get(self.module, _config.default_level)
        return level >= floor

    def _log(self, level: int, msg: str, kw: dict) -> None:
        if not self._enabled(level):
            return  # fields stay unformatted below the floor (lazy)
        fields = {"module": self.module}
        fields.update(self.fields)
        fields.update(kw)
        _config.sink(level, msg, fields)

    def debug(self, msg: str, **kw) -> None:
        self._log(DEBUG, msg, kw)

    def info(self, msg: str, **kw) -> None:
        self._log(INFO, msg, kw)

    def warn(self, msg: str, **kw) -> None:
        self._log(WARN, msg, kw)

    def error(self, msg: str, **kw) -> None:
        self._log(ERROR, msg, kw)


def logger(module: str) -> Logger:
    return Logger(module)
