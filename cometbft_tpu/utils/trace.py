"""Lightweight span/event tracer writing JSONL to a configurable sink.

The reference ships OpenTelemetry-style consensus tracing out of tree;
here a single-process JSONL tracer is enough to attribute wall time
across consensus steps, ApplyBlock stages, blocksync fetch→verify→apply
and crypto batch-verify dispatch (ISSUE 3 tentpole part 1). ISSUE 6
grows it into the data plane of the cross-node flight recorder: every
record carries a stable node identity, and p2p wire-message hooks give
the merger (utils/traceview.py) send→recv edges between sinks.

Design constraints:

* Near-zero overhead when disabled. `enabled` is a plain module bool;
  hot paths guard with ``if trace.enabled:`` so the disabled cost is one
  global load. `span()` returns a shared no-op context manager so
  un-guarded ``with trace.span(...)`` sites stay cheap too.
* One JSON object per line. Writes are buffered with a bounded
  staleness: the sink is flushed when FLUSH_INTERVAL_S has passed since
  the last flush (checked at each emit), by `tail()`, and at graceful
  shutdown — per-record flushing costs a syscall per consensus wire
  message once the p2p hooks are on, which measurably slows a loaded
  multi-node host. A SIGKILLed node loses at most the last interval's
  records. Every record carries ``ts`` (epoch seconds), ``pid`` (merge
  safety across e2e nodes), ``name`` and ``kind`` ("span" or "event");
  spans add ``dur_ms``; callers attach free-form fields. Once
  `set_node()` ran, records also carry ``node`` — the cross-process join
  key the traceview merger aligns sinks on.
* Fork safety: ``pid`` is re-stamped and the sink reopened via an
  at-fork hook, so a process forked after configure() never stamps the
  parent's pid on its records (and never shares the parent's buffered
  file object).
* Sink selection: `configure(path)` from node config
  (``[instrumentation] trace_sink``), or the ``COMETBFT_TPU_TRACE``
  environment variable at import time (picked up by subprocess nodes
  and bench.py without config plumbing).
"""

from __future__ import annotations

import json
import os
import threading
import time

enabled = False
_path: str | None = None
_fh = None
_lock = threading.Lock()
_pid = os.getpid()
_node = ""

# bounded write staleness: flush at most this long after a record was
# buffered (see module docstring — per-record flush is too expensive
# once the p2p wire hooks multiply the record rate)
FLUSH_INTERVAL_S = 0.25
_last_flush = 0.0


def configure(path: str) -> None:
    """Open (append) the JSONL sink at `path` and enable tracing."""
    global enabled, _path, _fh, _pid, _last_flush
    with _lock:
        if _fh is not None:
            _fh.close()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _fh = open(path, "a", encoding="utf-8", buffering=1 << 16)
        _path = path
        _pid = os.getpid()
        _last_flush = 0.0
        enabled = True


def disable() -> None:
    global enabled, _path, _fh, _node
    with _lock:
        enabled = False
        if _fh is not None:
            _fh.close()
        _fh = None
        _path = None
        _node = ""


def path() -> str | None:
    return _path


def set_node(node_id: str) -> None:
    """Stamp a stable node identity (p2p node id) on every subsequent
    record. One identity per process: the first caller wins, so an
    in-process multi-node test doesn't flap the field mid-sink (its
    records are disambiguated by the per-message ``peer`` fields
    instead). Cleared by disable()."""
    global _node
    if not _node:
        _node = str(node_id)


def node_id() -> str:
    return _node


def _before_fork() -> None:
    # Drain the buffer in the parent so the child's inherited copy is
    # empty — otherwise the child's close() below would re-write records
    # the parent also flushes later (duplicate lines in the sink).
    try:
        with _lock:
            if _fh is not None:
                _fh.flush()
    except Exception:  # noqa: BLE001 — fork must proceed regardless
        pass


def _after_fork_in_child() -> None:
    # A forked child must stamp its OWN pid and must not share the
    # parent's buffered file object (interleaved partial writes). The
    # lock is replaced too: another thread may have held it at fork
    # time, which would deadlock the child forever.
    global _pid, _fh, _lock, _last_flush
    _lock = threading.Lock()
    _pid = os.getpid()
    # first emit in the child flushes at once: multiprocessing children
    # exit via os._exit(), which skips buffered-file shutdown
    _last_flush = 0.0
    if _fh is not None:
        try:
            _fh.close()
        except OSError:
            pass
        try:
            _fh = open(_path, "a", encoding="utf-8", buffering=1 << 16) \
                if _path else None
        except OSError:
            _fh = None


if hasattr(os, "register_at_fork"):  # POSIX only; harmless otherwise
    os.register_at_fork(before=_before_fork,
                        after_in_child=_after_fork_in_child)


def emit(name: str, kind: str = "event", **fields) -> None:
    """Write one record. No-op (single bool check) when disabled."""
    if not enabled:
        return
    rec = {"ts": time.time(), "pid": _pid, "name": name, "kind": kind}
    if _node:
        rec["node"] = _node
    rec.update(fields)
    line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
    global _last_flush
    with _lock:
        if _fh is None:  # raced with disable()
            return
        _fh.write(line)
        now = time.monotonic()
        if now - _last_flush >= FLUSH_INTERVAL_S:
            _fh.flush()
            _last_flush = now


def flush() -> None:
    """Force buffered records to disk (readers that bypass tail())."""
    with _lock:
        if _fh is not None:
            _fh.flush()


def event(name: str, **fields) -> None:
    emit(name, "event", **fields)


class _Span:
    __slots__ = ("name", "fields", "_t0")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields

    def add(self, **fields) -> None:
        self.fields.update(fields)

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        emit(self.name, "span", dur_ms=round(dur_ms, 3), **self.fields)
        return False


class _NoopSpan:
    __slots__ = ()

    def add(self, **fields) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, **fields):
    """Context manager timing a block; writes one span record on exit."""
    if not enabled:
        return _NOOP
    return _Span(name, fields)


def tail(n: int = 100) -> list[dict]:
    """Last `n` parsed records from the sink (for the dump_trace RPC).

    The seek-back window starts at 256 KiB and grows geometrically until
    it holds `n` parseable lines or reaches the beginning of the file,
    so large `n` (or oversized records) can't silently come up short.
    A window that starts mid-file drops its first line — it may be a
    truncated record half — but at BOF the first line is kept."""
    p = _path
    if p is None or not os.path.exists(p):
        return []
    with _lock:
        if _fh is not None:
            _fh.flush()
    with open(p, "rb") as f:
        window = 256 * 1024
        while True:
            # Re-measure every iteration: the sink can be truncated or
            # rotated under the reader (logrotate, a restarting node
            # reopening in "w" mode), and seeking against a stale size
            # would either raise or decode a window that no longer
            # exists as garbage half-lines.
            f.seek(0, os.SEEK_END)
            size = f.tell()
            start = max(0, size - window)
            f.seek(start)
            data = f.read(size - start)
            lines = data.decode("utf-8", "replace").splitlines()
            if start > 0 and lines:
                lines = lines[1:]
            out = []
            for line in lines:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
            if len(out) >= n or start == 0:
                return out[-n:]
            window *= 4


class TailReader:
    """Incremental follow-mode reader over a JSONL trace sink.

    `poll()` returns the records appended since the last call, holding
    any trailing partial line in a remainder buffer until its newline
    lands. Rotation/truncation-safe: when the file's current size drops
    below the saved offset the writer replaced or truncated the sink,
    so the reader resets to the beginning of the new file instead of
    seeking past EOF (the bug tail() had: a stale seek yields garbage).
    A missing file is not an error — the writer may not have started
    yet — poll() just returns nothing until it appears.
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._rest = b""

    def poll(self, max_bytes: int = 4 << 20) -> list[dict]:
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < self._offset:
                    # truncated or rotated under us: start over on the
                    # new contents and drop the stale partial line
                    self._offset = 0
                    self._rest = b""
                if size == self._offset:
                    return []
                f.seek(self._offset)
                chunk = f.read(min(size - self._offset, max_bytes))
        except OSError:
            return []
        self._offset += len(chunk)
        buf = self._rest + chunk
        lines = buf.split(b"\n")
        self._rest = lines.pop()  # b"" when chunk ended on a newline
        out = []
        for line in lines:
            if not line:
                continue
            try:
                out.append(json.loads(line.decode("utf-8", "replace")))
            except ValueError:
                continue
        return out


# ----------------------------------------------------------------------
# Span-name registry: every name passed to trace.span()/trace.event()/
# trace.emit() anywhere in the tree must be declared here, and every
# declared name must have a live call site — tools/trace_lint.py
# enforces both directions from the tier-1 suite. The flight-recorder
# analysis layer (utils/traceview.py, tools/trace_analyze.py) keys its
# reconstruction on these names, so renaming one is a cross-cutting
# change, not a local edit.
SPAN_REGISTRY = {
    "node.boot": "node identity: moniker + full node id, once per process start",
    "consensus.step": "span closing the consensus step being left (height/round/dur_ms/next)",
    "consensus.finalize_commit": "block decided at height/round, with tx count",
    "consensus.propose_speculative": "one speculative proposal assembly overlapping the previous height's commit gap (height/txs/bytes)",
    "consensus.cert_aggregate": "one aggregate-precommit certificate verified from catchup gossip (height/round/signers/outcome/dur_ms)",
    "state.apply_block": "ApplyBlock with validate/finalize/commit/save stage breakdown",
    "blocksync.block": "one fast-synced block: fetch→verify→apply breakdown",
    "crypto.batch_verify": "one batch-verify dispatch: path, n, modeled host/wire/device terms",
    "crypto.commit_partition": "per-curve share of one commit verification",
    "crypto.bls_aggregate": "one BLS partition collapsed to aggregate pairing check(s) (n/pairing_checks)",
    "crypto.mesh_submit": "one sharded mega-batch across the verify mesh (n/b/n_devices/shard_lanes)",
    "crypto.stream_place": "one streamed commit placed on a mesh device (device/n/b)",
    "crypto.sched_coalesce": "one shared-scheduler dispatch: n_requests/sigs/tenants/sources/per_tenant_sigs (crypto/sched.py)",
    "mempool.admit_window": "one micro-batched admission window: n/dup/sig_fail/app_fail/admitted + stage ms",
    "tx.lifecycle": "one stage crossing of a sampled tx (tx/stage/mono; utils/txlife.py — hash-prefix sampled, correlated across nodes by tx)",
    "p2p.send": "consensus wire message handed to a peer (msg/height/round/peer)",
    "p2p.zero_copy_send": "one multiplexed message fully packetized via memoryview slicing (chan/bytes/packets)",
    "p2p.recv": "consensus wire message received from a peer (msg/height/round/peer)",
    "light.mmr_append": "one committed header folded into the MMR accumulator (height/leaf/size/dur_ms)",
    "light.serve_proof": "one MMR ancestry proof generated for a light client (height/size/bytes)",
    "da.encode": "one committed payload erasure-coded + committed (height/bytes/shards/shard_bytes)",
    "da.serve_sample": "one extended-chunk opening served to a sampling client (height/index)",
    "da.sample_verify": "one sample proof verified against the header's da_root (index/n/ok)",
    "da.pc_commit": "one payload committed on the 2D KZG track: per-column commitments + parity extension (height/rows/cols/bytes)",
    "crypto.msm_opening": "one KZG opening-proof quotient committed via G1 MSM (n/cols)",
    "replication.feed_send": "one committed height's frame fanned out on the replication feed (height/subs/bytes)",
    "replication.replica_apply": "one feed frame applied into replica serving state (height/da/dur_ms)",
    "consensus.conflicting_vote": "conflicting signed votes from one validator at one HRS (height/round/type/vote_a/vote_b hex) — the watchtower's equivocation feed",
    "watchtower.audit": "one audited feed frame: every check run against a height (node/height/checks/dur_ms)",
    "watchtower.verdict": "one watchtower finding (check/node/height/safety/detail) — safety verdicts fail an audited e2e run",
}


_env = os.environ.get("COMETBFT_TPU_TRACE")
if _env:
    configure(_env)
del _env
