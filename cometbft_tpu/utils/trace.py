"""Lightweight span/event tracer writing JSONL to a configurable sink.

The reference ships OpenTelemetry-style consensus tracing out of tree;
here a single-process JSONL tracer is enough to attribute wall time
across consensus steps, ApplyBlock stages, blocksync fetch→verify→apply
and crypto batch-verify dispatch (ISSUE 3 tentpole part 1).

Design constraints:

* Near-zero overhead when disabled. `enabled` is a plain module bool;
  hot paths guard with ``if trace.enabled:`` so the disabled cost is one
  global load. `span()` returns a shared no-op context manager so
  un-guarded ``with trace.span(...)`` sites stay cheap too.
* One JSON object per line, flushed per record so a killed node leaves
  a readable trace. Every record carries ``ts`` (epoch seconds), ``pid``
  (merge safety across e2e nodes), ``name`` and ``kind`` ("span" or
  "event"); spans add ``dur_ms``; callers attach free-form fields.
* Sink selection: `configure(path)` from node config
  (``[instrumentation] trace_sink``), or the ``COMETBFT_TPU_TRACE``
  environment variable at import time (picked up by subprocess nodes
  and bench.py without config plumbing).
"""

from __future__ import annotations

import json
import os
import threading
import time

enabled = False
_path: str | None = None
_fh = None
_lock = threading.Lock()
_pid = os.getpid()


def configure(path: str) -> None:
    """Open (append) the JSONL sink at `path` and enable tracing."""
    global enabled, _path, _fh, _pid
    with _lock:
        if _fh is not None:
            _fh.close()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _fh = open(path, "a", encoding="utf-8")
        _path = path
        _pid = os.getpid()
        enabled = True


def disable() -> None:
    global enabled, _path, _fh
    with _lock:
        enabled = False
        if _fh is not None:
            _fh.close()
        _fh = None
        _path = None


def path() -> str | None:
    return _path


def emit(name: str, kind: str = "event", **fields) -> None:
    """Write one record. No-op (single bool check) when disabled."""
    if not enabled:
        return
    rec = {"ts": time.time(), "pid": _pid, "name": name, "kind": kind}
    rec.update(fields)
    line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
    with _lock:
        if _fh is None:  # raced with disable()
            return
        _fh.write(line)
        _fh.flush()


def event(name: str, **fields) -> None:
    emit(name, "event", **fields)


class _Span:
    __slots__ = ("name", "fields", "_t0")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields

    def add(self, **fields) -> None:
        self.fields.update(fields)

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        emit(self.name, "span", dur_ms=round(dur_ms, 3), **self.fields)
        return False


class _NoopSpan:
    __slots__ = ()

    def add(self, **fields) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, **fields):
    """Context manager timing a block; writes one span record on exit."""
    if not enabled:
        return _NOOP
    return _Span(name, fields)


def tail(n: int = 100) -> list[dict]:
    """Last `n` parsed records from the sink (for the dump_trace RPC)."""
    p = _path
    if p is None or not os.path.exists(p):
        return []
    with _lock:
        if _fh is not None:
            _fh.flush()
    with open(p, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - 256 * 1024))
        lines = f.read().decode("utf-8", "replace").splitlines()
    out = []
    for line in lines[-n:]:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


_env = os.environ.get("COMETBFT_TPU_TRACE")
if _env:
    configure(_env)
del _env
