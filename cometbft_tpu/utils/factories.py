"""Deterministic fixtures: signers, validator sets, commits, chains.

Mirrors the role of the reference's internal/test fixture kit (commit.go,
validator.go): every layer's tests build real, verifiable artifacts. For
large validator sets the Ed25519 keys are *scalar signers* — the secret is
a raw scalar a with pubkey [a]B computed by the device fixed-base ladder in
one batch, and signatures finished host-side as S = r + k*a (mod L). These
are standard verifiable Ed25519 signatures; only derivation-from-seed is
skipped, which verifiers never see.
"""

from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass

import numpy as np

from ..crypto import ed25519_ref as ref
from ..crypto.ed25519 import Ed25519PubKey
from ..types import (
    Block,
    BlockID,
    Commit,
    CommitSig,
    Data,
    Header,
    PartSetHeader,
    Timestamp,
    Validator,
    ValidatorSet,
)
from ..types.block import BlockIDFlag


@dataclass
class ScalarSigner:
    scalar: int
    pub_bytes: bytes

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self.pub_bytes)

    def address(self) -> bytes:
        return self.pub_key().address()


@functools.lru_cache(maxsize=8)
def _fixed_base_fn(n: int):
    import jax
    import jax.numpy as jnp

    from ..ops import curve as C

    @jax.jit
    def run(digs):
        return C.compress(C.fixed_base(digs))

    return run


def _fixed_base_batch(scalars: list[int]) -> np.ndarray:
    """[s]B for a batch of scalars via the device ladder -> (N, 32) encodings.

    Padded to power-of-two buckets so each bucket size compiles once.
    """
    import jax.numpy as jnp

    from ..crypto.ed25519 import _bucket
    from ..ops import curve as C

    n = len(scalars)
    b = _bucket(max(n, 1))
    padded = scalars + [1] * (b - n)
    digs = jnp.asarray(C.scalar_digits(padded))
    return np.asarray(_fixed_base_fn(b)(digs))[:n]


def make_signers(n: int, seed: int = 0) -> list[ScalarSigner]:
    rng = np.random.default_rng(seed)
    scalars = [int.from_bytes(rng.bytes(32), "little") % ref.L or 1 for _ in range(n)]
    pubs = _fixed_base_batch(scalars)
    return [ScalarSigner(s, pubs[i].tobytes()) for i, s in enumerate(scalars)]


class RPool:
    """Pre-batched R nonce points for chunked chain generation.

    batch_sign's per-call _fixed_base_batch pays one device round trip
    (~150 ms through the tunnel); generating a 50k-block x 1000-signer
    chain that way spends 2+ hours on round trips alone. The pool
    computes R encodings for `blocks_per_fill` commits in ONE device
    call and hands them out per block."""

    def __init__(self, n_signers: int, blocks_per_fill: int = 32,
                 seed: int = 1):
        self.n = n_signers
        self.per_fill = blocks_per_fill
        self.seed = seed
        self._buf: list[tuple[list[int], np.ndarray]] = []

    def next(self) -> tuple[list[int], np.ndarray]:
        if not self._buf:
            rng = np.random.default_rng(self.seed)
            self.seed += 1
            total = self.n * self.per_fill
            rs = [
                int.from_bytes(rng.bytes(32), "little") % ref.L or 1
                for _ in range(total)
            ]
            encs = _fixed_base_batch(rs)
            for i in range(self.per_fill):
                lo = i * self.n
                self._buf.append((rs[lo:lo + self.n], encs[lo:lo + self.n]))
        return self._buf.pop()


def batch_sign(signers: list[ScalarSigner], msgs: list[bytes], seed: int = 1,
               nonces: tuple[list[int], np.ndarray] | None = None) -> list[bytes]:
    """One signature per (signer, msg) pair, R points computed on device
    (or taken from a pre-batched RPool draw via `nonces`)."""
    if nonces is not None:
        rs, r_encs = nonces
        rs, r_encs = rs[:len(signers)], r_encs[:len(signers)]
    else:
        rng = np.random.default_rng(seed)
        rs = [int.from_bytes(rng.bytes(32), "little") % ref.L or 1 for _ in signers]
        r_encs = _fixed_base_batch(rs)
    sigs = []
    for signer, msg, r, r_enc in zip(signers, msgs, rs, r_encs):
        r_b = r_enc.tobytes()
        k = int.from_bytes(
            hashlib.sha512(r_b + signer.pub_bytes + msg).digest(), "little"
        ) % ref.L
        s = (r + k * signer.scalar) % ref.L
        sigs.append(r_b + s.to_bytes(32, "little"))
    return sigs


def sign_with_scalar(signer: ScalarSigner, msg: bytes) -> bytes:
    """One host-side signature (deterministic nonce); for single-vote paths
    (consensus state machine, privval) where device batching has nothing to
    amortize. Standard verifiable Ed25519 output."""
    r = (
        int.from_bytes(
            hashlib.sha512(b"nonce" + signer.pub_bytes + msg).digest(), "little"
        )
        % ref.L
        or 1
    )
    r_enc = ref._encode_point(*ref._ext_to_affine(ref._ext_scalar_mul(r, ref.B_POINT)))
    k = (
        int.from_bytes(
            hashlib.sha512(r_enc + signer.pub_bytes + msg).digest(), "little"
        )
        % ref.L
    )
    s = (r + k * signer.scalar) % ref.L
    return r_enc + s.to_bytes(32, "little")


def sign_vote(signer: ScalarSigner, vote, chain_id: str) -> None:
    vote.signature = sign_with_scalar(signer, vote.sign_bytes(chain_id))


def make_validator_set(
    signers: list[ScalarSigner], powers: list[int] | None = None
) -> ValidatorSet:
    powers = powers or [10] * len(signers)
    return ValidatorSet(
        [Validator.from_pub_key(s.pub_key(), p) for s, p in zip(signers, powers)]
    )


def make_block_id(tag: bytes = b"block") -> BlockID:
    h = hashlib.sha256(tag).digest()
    return BlockID(h, PartSetHeader(1, hashlib.sha256(tag + b"parts").digest()))


from ..types.block import block_id_for  # re-export for existing callers


def make_chain(
    n_blocks: int,
    n_validators: int = 4,
    chain_id: str = "replay-chain",
    txs_per_block: int = 2,
    app=None,
    block_store=None,
    seed: int = 0,
    backend: str = "cpu",
    nil_votes: dict[int, set[int]] | None = None,
    corrupt_sig: tuple[int, int] | None = None,
    verify_last_commit: bool = True,
    r_pool: "RPool | None" = None,
    start_state=None,
    start_commit: Commit | None = None,
    start_height: int = 1,
):
    """Generate a fully-valid signed chain by actually running the executor.

    Returns (block_store, final_state, genesis_state, signers). Every block
    is built with create_proposal_block, committed by all validators
    (device-batched signing), and applied through ABCI — so replaying the
    store reproduces byte-identical state.

    nil_votes maps height -> validator indices casting NIL precommits in
    that height's commit. corrupt_sig=(height, idx) flips a byte of that
    commit signature after signing (the corrupted commit still propagates
    into the next block's embedded LastCommit, so verification during
    generation is elided for such chains — they exist to test that replay
    REJECTS them).

    verify_last_commit=False skips LastCommit verification during
    generation: the commits are signed here and known-valid, and at
    north-star scale (50k blocks x 1000 validators) re-verifying each
    one with the pure-Python oracle costs ~4.4 s/block — the REPLAY of
    the generated store is where verification is measured. r_pool
    amortizes the device nonce-point round trip over many blocks.
    start_state/start_commit/start_height continue a chain from a prior
    make_chain call's (state, last_commit) so arbitrarily long chains
    build in bounded-memory chunks into one shared block_store.
    """
    from ..abci.client import AppConns
    from ..abci.kvstore import KVStoreApp
    from ..state.execution import BlockExecutor, make_genesis_state
    from ..storage import BlockStore, MemKV

    signers = make_signers(n_validators, seed=seed)
    vals = make_validator_set(signers)
    by_addr = {s.address(): s for s in signers}
    app = app or KVStoreApp()
    store = block_store or BlockStore(MemKV())
    executor = BlockExecutor(AppConns(app), backend=backend)
    genesis = make_genesis_state(chain_id, vals)
    state = start_state if start_state is not None else genesis.copy()

    last_commit = start_commit if start_commit is not None else Commit()
    for h in range(start_height, start_height + n_blocks):
        txs = [b"k%d-%d=v%d" % (h, i, i) for i in range(txs_per_block)]
        proposer = state.validators.get_proposer()
        block = executor.create_proposal_block(
            h, state, last_commit, proposer.address, txs,
            block_time=state.last_block_time,
        )
        bid = block_id_for(block)
        vals_h = state.validators  # the set that signs height h's commit
        state = executor.apply_block(
            state, bid, block,
            last_commit_preverified=(
                corrupt_sig is not None or not verify_last_commit
            ),
        )
        commit = make_commit(
            chain_id, h, 0, bid, vals_h, by_addr,
            time_ns=state.last_block_time.unix_ns() + 1_000_000_000,
            nil=(nil_votes or {}).get(h),
            r_pool=r_pool,
        )
        if corrupt_sig is not None and corrupt_sig[0] == h:
            cs = commit.signatures[corrupt_sig[1]]
            sig = bytearray(cs.signature)
            sig[0] ^= 0xFF
            cs.signature = bytes(sig)
            commit.invalidate_memos()
        store.save_block(block, commit)
        last_commit = commit
    return store, state, genesis, signers


def make_commit(
    chain_id: str,
    height: int,
    round_: int,
    block_id: BlockID,
    vals: ValidatorSet,
    signers_by_addr: dict[bytes, ScalarSigner],
    time_ns: int = 1_700_000_000_000_000_000,
    absent: set[int] | None = None,
    nil: set[int] | None = None,
    sign_seed: int | None = None,
    r_pool: "RPool | None" = None,
) -> Commit:
    """A commit signed by every validator (minus `absent` indices; `nil`
    indices sign a NIL precommit), ordered to match the validator set."""
    absent = absent or set()
    nil = nil or set()
    commit = Commit(height=height, round=round_, block_id=block_id, signatures=[])
    sig_slots = []
    signers, msgs = [], []
    for idx, val in enumerate(vals.validators):
        if idx in absent:
            commit.signatures.append(CommitSig.absent())
            sig_slots.append(None)
            continue
        ts = Timestamp.from_unix_ns(time_ns + idx)
        cs = CommitSig(
            block_id_flag=BlockIDFlag.NIL if idx in nil else BlockIDFlag.COMMIT,
            validator_address=val.address,
            timestamp=ts,
            signature=b"",
        )
        commit.signatures.append(cs)
        sig_slots.append(idx)
        signers.append(signers_by_addr[val.address])
        msgs.append(None)  # filled after sign bytes known
    # sign bytes depend on the commit structure built above
    j = 0
    for idx in range(len(vals.validators)):
        if sig_slots[idx] is None:
            continue
        msgs[j] = commit.vote_sign_bytes(chain_id, idx)
        j += 1
    sigs = batch_sign(
        signers, msgs, seed=(sign_seed if sign_seed is not None else height),
        nonces=r_pool.next() if r_pool is not None else None,
    )
    j = 0
    for idx in range(len(vals.validators)):
        if sig_slots[idx] is None:
            continue
        commit.signatures[idx].signature = sigs[j]
        j += 1
    return commit
