"""Thread-safe fixed-size bit array.

Behavior parity: reference internal/bits/bit_array.go (BitArray, :445 LoC) —
vote presence tracking in VoteSet, block-part tracking in PartSet, and the
VoteSetBits gossip messages. Python representation is a single int used as a
bitmask (arbitrary precision, so no word bookkeeping), guarded by a lock the
way the reference guards with sync.Mutex.
"""

from __future__ import annotations

import random
import threading


class BitArray:
    __slots__ = ("_n", "_bits", "_lock")

    def __init__(self, n: int, bits: int = 0):
        if n < 0:
            raise ValueError("BitArray size must be >= 0")
        self._n = n
        self._bits = bits & ((1 << n) - 1)
        self._lock = threading.Lock()

    # -- core ops ---------------------------------------------------------
    def size(self) -> int:
        return self._n

    def get(self, i: int) -> bool:
        if not 0 <= i < self._n:
            return False
        with self._lock:
            return bool((self._bits >> i) & 1)

    def set(self, i: int, v: bool = True) -> bool:
        """Set bit i; returns False when out of range (reference SetIndex)."""
        if not 0 <= i < self._n:
            return False
        with self._lock:
            if v:
                self._bits |= 1 << i
            else:
                self._bits &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        with self._lock:
            return BitArray(self._n, self._bits)

    def _raw(self) -> int:
        with self._lock:
            return self._bits

    # -- set algebra (sizes may differ; reference semantics) --------------
    def or_(self, other: "BitArray") -> "BitArray":
        """Union, sized to the larger operand (reference Or)."""
        n = max(self._n, other._n)
        return BitArray(n, self._raw() | other._raw())

    def and_(self, other: "BitArray") -> "BitArray":
        """Intersection, sized to the smaller operand (reference And)."""
        n = min(self._n, other._n)
        return BitArray(n, self._raw() & other._raw())

    def not_(self) -> "BitArray":
        return BitArray(self._n, ~self._raw())

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set here but not in other; keeps this size (reference Sub)."""
        return BitArray(self._n, self._raw() & ~other._raw())

    # -- queries ----------------------------------------------------------
    def is_empty(self) -> bool:
        return self._raw() == 0

    def is_full(self) -> bool:
        return self._raw() == (1 << self._n) - 1 if self._n else True

    def num_true(self) -> int:
        return bin(self._raw()).count("1")

    def true_indices(self) -> list[int]:
        bits = self._raw()
        return [i for i in range(self._n) if (bits >> i) & 1]

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set bit (reference PickRandom); (0, False) if none."""
        idx = self.true_indices()
        if not idx:
            return 0, False
        return (rng or random).choice(idx), True

    # -- encoding / display -----------------------------------------------
    def to_bytes(self) -> bytes:
        return self._raw().to_bytes((self._n + 7) // 8 or 1, "little")

    @classmethod
    def from_bytes(cls, n: int, data: bytes) -> "BitArray":
        return cls(n, int.from_bytes(data, "little"))

    def __eq__(self, other) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self._n == other._n and self._raw() == other._raw()

    def __repr__(self) -> str:
        bits = self._raw()
        s = "".join("x" if (bits >> i) & 1 else "_" for i in range(self._n))
        return f"BA{{{self._n}:{s}}}"
