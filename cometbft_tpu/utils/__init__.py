"""Utilities: test/bench factories, service lifecycle, WAL primitives."""
