"""Prevotes + precommits for every round of one height.

Behavior parity: reference internal/consensus/height_vote_set.go —
round-keyed VoteSets created on demand, a cap on peer-initiated "catchup"
rounds (one per peer), POL (proof-of-lock) lookup scanning rounds
descending.
"""

from __future__ import annotations

from ..types.basic import BlockID
from ..types.validator_set import ValidatorSet
from ..types.vote import SignedMsgType, Vote
from ..types.vote_set import VoteSet


class HeightVoteSet:
    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._sets: dict[int, dict[SignedMsgType, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self.set_round(0)

    def _ensure_round(self, r: int):
        if r not in self._sets:
            self._sets[r] = {
                SignedMsgType.PREVOTE: VoteSet(
                    self.chain_id, self.height, r, SignedMsgType.PREVOTE, self.val_set
                ),
                SignedMsgType.PRECOMMIT: VoteSet(
                    self.chain_id, self.height, r, SignedMsgType.PRECOMMIT, self.val_set
                ),
            }

    def set_round(self, r: int) -> None:
        """Track a new current round (creates r and r+1 like the reference)."""
        self._ensure_round(r)
        self._ensure_round(r + 1)
        self.round = max(self.round, r)

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """Route a vote to its round's set. Peer votes for unknown future
        rounds are capped at one catchup round per peer (reference :~100)."""
        if vote.round not in self._sets:
            if peer_id:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) < 2:
                    self._ensure_round(vote.round)
                    rounds.append(vote.round)
                else:
                    return False  # GossipVotesAndPrecommitsError equivalent
            else:
                self._ensure_round(vote.round)
        return self._sets[vote.round][vote.type].add_vote(vote)

    def prevotes(self, r: int) -> VoteSet | None:
        return self._sets.get(r, {}).get(SignedMsgType.PREVOTE)

    def precommits(self, r: int) -> VoteSet | None:
        return self._sets.get(r, {}).get(SignedMsgType.PRECOMMIT)

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Highest round with a prevote +2/3 majority (reference POLInfo)."""
        for r in sorted(self._sets, reverse=True):
            vs = self.prevotes(r)
            if vs is not None:
                maj, ok = vs.two_thirds_majority()
                if ok:
                    return r, maj
        return -1, None

    def set_peer_maj23(self, round_: int, vtype: SignedMsgType, peer_id: str,
                       block_id: BlockID) -> None:
        self._ensure_round(round_)
        self._sets[round_][vtype].set_peer_maj23(peer_id, block_id)
