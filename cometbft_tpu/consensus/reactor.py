"""Consensus reactor: gossips consensus messages over p2p channels.

Behavior parity: reference internal/consensus/reactor.go — the reactor
owns the State/Data/Vote channels (:152) and relays between the switch
and the consensus state machine. The reference's per-peer gossip
routines (:567,735) push deltas based on peer round state; v1 here
broadcasts proposals/blocks/votes to all peers (loopback-net semantics
over real sockets) — peer-state-aware gossip is the known next step.
"""

from __future__ import annotations

import threading

from ..encoding import proto as pb
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types import Proposal, Vote
from .state import ConsensusState, ProposalMessage, VoteMessage
from .wal import BlockBytesMessage

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22


def encode_consensus_msg(msg) -> bytes:
    if isinstance(msg, VoteMessage):
        return pb.f_embedded(1, msg.vote.encode())
    if isinstance(msg, ProposalMessage):
        return pb.f_embedded(2, msg.proposal.encode())
    if isinstance(msg, BlockBytesMessage):
        return pb.f_embedded(
            3,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_bytes(3, msg.block_bytes),
        )
    raise TypeError(f"unsupported consensus message {type(msg)}")


def decode_consensus_msg(buf: bytes):
    fields = pb.parse_fields(buf)
    if not fields:
        raise ValueError("empty consensus message")
    fnum, _, v = fields[0]
    v = bytes(v)
    if fnum == 1:
        return VoteMessage(Vote.decode(v))
    if fnum == 2:
        return ProposalMessage(Proposal.decode(v))
    if fnum == 3:
        d = pb.fields_to_dict(v)
        return BlockBytesMessage(
            pb.to_i64(d.get(1, 0)), pb.to_i64(d.get(2, 0)), bytes(d.get(3, b""))
        )
    raise ValueError(f"unknown consensus message tag {fnum}")


def _channel_for(msg) -> int:
    if isinstance(msg, VoteMessage):
        return VOTE_CHANNEL
    if isinstance(msg, ProposalMessage):
        return STATE_CHANNEL
    return DATA_CHANNEL


class ConsensusReactor(Reactor):
    """Messages are re-gossiped on a short interval until the height moves
    on — the liveness job of the reference's per-peer gossip routines
    (vote/data retransmission), in broadcast form: receivers dedupe (a
    repeated vote is a no-op in VoteSet), so retransmission is idempotent.
    Without it, messages sent before a peer connects are lost forever and
    a 2-validator net deadlocks at startup."""

    REGOSSIP_INTERVAL_S = 0.25

    def __init__(self, cs: ConsensusState):
        self.cs = cs
        self.switch = None
        self._recent: list[tuple[int, object]] = []  # (height, msg)
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        cs.broadcast = self.broadcast_msg

    def channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=7),
        ]

    def set_switch(self, switch) -> None:
        self.switch = switch
        if self._thread is None:
            self._thread = threading.Thread(target=self._regossip_loop,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _msg_height(self, msg) -> int:
        if isinstance(msg, VoteMessage):
            return msg.vote.height
        if isinstance(msg, ProposalMessage):
            return msg.proposal.height
        return msg.height

    def broadcast_msg(self, msg) -> None:
        h = self._msg_height(msg)
        with self._lock:
            self._recent = [(mh, m) for mh, m in self._recent if mh >= self.cs.height]
            self._recent.append((h, msg))
        if self.switch is not None:
            self.switch.broadcast(_channel_for(msg), encode_consensus_msg(msg))

    def _regossip_loop(self) -> None:
        while not self._stopped.is_set():
            self._stopped.wait(self.REGOSSIP_INTERVAL_S)
            if self.switch is None or not self.switch.peers():
                continue
            cur = self.cs.height
            with self._lock:
                batch = [m for mh, m in self._recent if mh >= cur]
            for msg in batch:
                self.switch.broadcast(
                    _channel_for(msg), encode_consensus_msg(msg)
                )

    def add_peer(self, peer) -> None:
        """Catch a late joiner up on the current height's messages."""
        cur = self.cs.height
        with self._lock:
            batch = [m for mh, m in self._recent if mh >= cur]
        for msg in batch:
            peer.send(_channel_for(msg), encode_consensus_msg(msg))

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        self.cs.send(decode_consensus_msg(msg), peer_id=peer.id)
