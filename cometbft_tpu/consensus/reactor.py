"""Consensus reactor: per-peer state-aware gossip over p2p channels.

Behavior parity: reference internal/consensus/reactor.go — the reactor
owns the State/Data/Vote channels (:152) and runs per-peer gossip
driven by each peer's advertised round state:

- NewRoundStep broadcasts on every step change (:455) update
  PeerState; HasVote (:525) marks individual votes seen.
- gossipDataRoutine (:567): the proposal and its block PARTS flow to
  peers at our height by bitmap difference; peers on earlier heights
  get parts of the committed block from the store (:683
  gossipDataForCatchup).
- gossipVotesRoutine (:735): votes flow by VoteSet-bitmap difference —
  current-round prevotes/precommits, POL prevotes, last-commit
  precommits for peers one height back, and stored commit signatures
  for peers further back (rs.Height >= prs.Height+2 -> LoadCommit).
- queryMaj23Routine (:893): same-height peers are periodically told
  which blocks we see +2/3 votes for; they answer with VoteSetBits
  bitmaps that prune the vote gossip difference.

Blocks never travel whole: the proposer splits them into 64 KiB merkle-
proved parts (types/part_set.py, reference types/part_set.go) and every
receiver reassembles + verifies against the proposal's PartSetHeader
before the state machine sees BlockBytes.
"""

from __future__ import annotations

import threading
import time

from ..encoding import proto as pb
from ..crypto import merkle
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types import Proposal, Vote
from ..types.basic import BlockID, PartSetHeader
from ..types.part_set import PART_SIZE, Part, PartSet
from ..types.vote import SignedMsgType
from ..utils import trace
from ..utils.log import logger
from ..utils.metrics import p2p_metrics
from ..types.agg_commit import AggregateCommit
from .state import ConsensusState, ProposalMessage, RoundStep, VoteMessage
from .wal import AggregateCommitMessage, BlockBytesMessage

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22

# Largest validator set any peer-supplied vote index or bitmap may claim
# (reference MaxVotesCount = 10000); bounds HasVote indexes and the
# VoteSetBits bit_length so one message cannot force millions of marks.
MAX_VALIDATORS = 10_000

_log = logger("cons-reactor")


# ----------------------------------------------------------------------
# wire messages
# ----------------------------------------------------------------------
class NewRoundStepMessage:
    __slots__ = ("height", "round", "step", "last_commit_round")

    def __init__(self, height, round_, step, last_commit_round=-1):
        self.height = height
        self.round = round_
        self.step = step
        self.last_commit_round = last_commit_round


class HasVoteMessage:
    __slots__ = ("height", "round", "type", "index")

    def __init__(self, height, round_, type_, index):
        self.height = height
        self.round = round_
        self.type = type_
        self.index = index


class BlockPartMessage:
    __slots__ = ("height", "round", "part")

    def __init__(self, height, round_, part: Part):
        self.height = height
        self.round = round_
        self.part = part


class NewValidBlockMessage:
    """Advertises a known-valid block's part-set header (reference
    NewValidBlockMessage): lets peers verify parts for a block they have
    no proposal for (catchup / late joiners). Safety: the commit votes
    sign the BlockID, which includes this header — a forged header can
    never assemble into a committable block."""

    __slots__ = ("height", "round", "psh", "is_commit")

    def __init__(self, height, round_, psh: PartSetHeader, is_commit=False):
        self.height = height
        self.round = round_
        self.psh = psh
        self.is_commit = is_commit


class VoteSetMaj23Message:
    __slots__ = ("height", "round", "type", "block_id")

    def __init__(self, height, round_, type_, block_id):
        self.height = height
        self.round = round_
        self.type = type_
        self.block_id = block_id


class VoteSetBitsMessage:
    __slots__ = ("height", "round", "type", "block_id", "bits")

    def __init__(self, height, round_, type_, block_id, bits: int):
        self.height = height
        self.round = round_
        self.type = type_
        self.block_id = block_id
        self.bits = bits


def _encode_proof(p: merkle.Proof) -> bytes:
    out = (
        pb.f_varint(1, p.total)
        + pb.f_varint(2, p.index)
        + pb.f_bytes(3, p.leaf_hash)
    )
    for a in p.aunts:
        out += pb.f_bytes(4, a, emit_empty=True)
    return out


def _decode_proof(buf: bytes) -> merkle.Proof:
    aunts = []
    total = index = 0
    leaf = b""
    for f, _, v in pb.parse_fields(buf):
        if f == 1:
            total = pb.to_i64(v)
        elif f == 2:
            index = pb.to_i64(v)
        elif f == 3:
            leaf = pb.as_bytes(v)
        elif f == 4:
            aunts.append(pb.as_bytes(v))
    return merkle.Proof(total=total, index=index, leaf_hash=leaf, aunts=aunts)


def encode_consensus_msg(msg) -> bytes:
    if isinstance(msg, VoteMessage):
        return pb.f_embedded(1, msg.vote.encode())
    if isinstance(msg, ProposalMessage):
        return pb.f_embedded(2, msg.proposal.encode())
    if isinstance(msg, BlockBytesMessage):
        return pb.f_embedded(
            3,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_bytes(3, msg.block_bytes),
        )
    if isinstance(msg, NewRoundStepMessage):
        return pb.f_embedded(
            4,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_varint(3, int(msg.step))
            + pb.f_varint(4, msg.last_commit_round + 1),
        )
    if isinstance(msg, HasVoteMessage):
        return pb.f_embedded(
            5,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_varint(3, int(msg.type))
            + pb.f_varint(4, msg.index + 1),
        )
    if isinstance(msg, BlockPartMessage):
        part = (
            pb.f_varint(1, msg.part.index + 1)
            + pb.f_bytes(2, msg.part.bytes_)
            + pb.f_embedded(3, _encode_proof(msg.part.proof))
        )
        return pb.f_embedded(
            6,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_embedded(3, part),
        )
    if isinstance(msg, VoteSetMaj23Message):
        return pb.f_embedded(
            7,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_varint(3, int(msg.type))
            + pb.f_embedded(4, msg.block_id.encode()),
        )
    if isinstance(msg, NewValidBlockMessage):
        return pb.f_embedded(
            9,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_embedded(3, msg.psh.encode())
            + (pb.f_varint(4, 1) if msg.is_commit else b""),
        )
    if isinstance(msg, AggregateCommitMessage):
        # one +2/3 aggregate-precommit certificate (ISSUE 17): replaces
        # the N per-vote frames of catchup gossip on BLS validator sets
        return pb.f_embedded(10, msg.cert.encode())
    if isinstance(msg, VoteSetBitsMessage):
        # bitmap travels as little-endian bytes: a varint caps out at 63
        # validators, real sets are larger (reference BitArray proto)
        nbytes = (msg.bits.bit_length() + 7) // 8 or 1
        return pb.f_embedded(
            8,
            pb.f_varint(1, msg.height)
            + pb.f_varint(2, msg.round)
            + pb.f_varint(3, int(msg.type))
            + pb.f_embedded(4, msg.block_id.encode())
            + pb.f_bytes(5, msg.bits.to_bytes(nbytes, "little")),
        )
    raise TypeError(f"unsupported consensus message {type(msg)}")


def decode_consensus_msg(buf: bytes):
    fields = pb.parse_fields(buf)
    if not fields:
        raise ValueError("empty consensus message")
    fnum, _, v = fields[0]
    v = pb.as_bytes(v)
    d = pb.fields_to_dict(v) if fnum != 1 and fnum != 2 else None
    if fnum == 1:
        return VoteMessage(Vote.decode(v))
    if fnum == 2:
        return ProposalMessage(Proposal.decode(v))
    if fnum == 3:
        return BlockBytesMessage(
            pb.to_i64(d.get(1, 0)), pb.to_i64(d.get(2, 0)), pb.as_bytes(d.get(3, b""))
        )
    if fnum == 4:
        return NewRoundStepMessage(
            pb.to_i64(d.get(1, 0)),
            pb.to_i64(d.get(2, 0)),
            pb.to_i64(d.get(3, 0)),
            pb.to_i64(d.get(4, 0)) - 1,
        )
    if fnum == 5:
        return HasVoteMessage(
            pb.to_i64(d.get(1, 0)),
            pb.to_i64(d.get(2, 0)),
            SignedMsgType(pb.to_i64(d.get(3, 0))),
            pb.to_i64(d.get(4, 0)) - 1,
        )
    if fnum == 6:
        pd = pb.fields_to_dict(pb.as_bytes(d.get(3, b"")))
        part = Part(
            index=pb.to_i64(pd.get(1, 0)) - 1,
            bytes_=pb.as_bytes(pd.get(2, b"")),
            proof=_decode_proof(pb.as_bytes(pd.get(3, b""))),
        )
        return BlockPartMessage(
            pb.to_i64(d.get(1, 0)), pb.to_i64(d.get(2, 0)), part
        )
    if fnum == 7:
        return VoteSetMaj23Message(
            pb.to_i64(d.get(1, 0)),
            pb.to_i64(d.get(2, 0)),
            SignedMsgType(pb.to_i64(d.get(3, 0))),
            BlockID.decode(pb.as_bytes(d.get(4, b""))),
        )
    if fnum == 8:
        return VoteSetBitsMessage(
            pb.to_i64(d.get(1, 0)),
            pb.to_i64(d.get(2, 0)),
            SignedMsgType(pb.to_i64(d.get(3, 0))),
            BlockID.decode(pb.as_bytes(d.get(4, b""))),
            int.from_bytes(pb.as_bytes(d.get(5, b"")), "little"),
        )
    if fnum == 9:
        return NewValidBlockMessage(
            pb.to_i64(d.get(1, 0)),
            pb.to_i64(d.get(2, 0)),
            PartSetHeader.decode(pb.as_bytes(d.get(3, b""))),
            bool(pb.to_i64(d.get(4, 0))),
        )
    if fnum == 10:
        return AggregateCommitMessage(AggregateCommit.decode(v))
    raise ValueError(f"unknown consensus message tag {fnum}")


# ----------------------------------------------------------------------
# flight-recorder wire hook (ISSUE 6): classify consensus wire messages
# into p2p.send / p2p.recv trace records WITHOUT constructing
# Vote/Proposal objects — only the outer tag and the height/round (and
# vote-type / index) varints are peeked. Installed on the switch via
# set_msg_tracer so the p2p layer stays ignorant of the wire format;
# the traceview merger pairs these records across per-node sinks to
# align clocks and build message edges.
# ----------------------------------------------------------------------
# HasVote (tag 5) is deliberately absent: it is the chattiest frame on
# the state channel (every vote received is re-announced to every
# peer), carries no payload the analyzers use, and tracing it measurably
# inflates sink volume on dense vote gossip.
_WIRE_MSG_KINDS = {
    1: "vote", 2: "proposal", 3: "block_bytes", 4: "new_round_step",
    6: "block_part", 7: "vote_set_maj23",
    8: "vote_set_bits", 9: "new_valid_block", 10: "agg_commit",
}
_VOTE_TYPE_NAMES = {1: "prevote", 2: "precommit", 32: "proposal"}
# Mempool channel id duplicated here (mempool/reactor.py) to keep the
# wire hook import-free of the mempool package: its tx frames become
# msg="txs" records, adding tx-gossip edges to the clock alignment.
_MEMPOOL_CHANNEL = 0x30
_TRACE_CHANNELS = frozenset(
    (STATE_CHANNEL, DATA_CHANNEL, VOTE_CHANNEL, _MEMPOOL_CHANNEL))


def peek_wire_msg(raw: bytes) -> dict | None:
    """Cheap metadata peek of an encoded consensus wire message:
    {"msg": kind, "height": h, "round": r, [+ "type"/"idx"/"step"]}.
    Returns None for unknown tags."""
    fields = pb.parse_fields(raw)
    if not fields:
        return None
    tag, _, v = fields[0]
    kind = _WIRE_MSG_KINDS.get(tag)
    if kind is None:
        return None
    emb = pb.fields_to_dict(pb.as_bytes(v))
    out: dict = {"msg": kind}
    if tag in (1, 2):  # Vote / Proposal protos: 2=height, 3=round
        out["height"] = pb.to_i64(emb.get(2, 0))
        out["round"] = pb.to_i64(emb.get(3, 0))
        if tag == 1:
            t = pb.to_i64(emb.get(1, 0))
            out["type"] = _VOTE_TYPE_NAMES.get(t, t)
            out["idx"] = pb.to_i64(emb.get(7, 0))
    else:  # wrapper messages: 1=height, 2=round
        out["height"] = pb.to_i64(emb.get(1, 0))
        out["round"] = pb.to_i64(emb.get(2, 0))
        if tag == 4:
            out["step"] = pb.to_i64(emb.get(3, 0))
        elif tag == 6:
            pd = pb.fields_to_dict(pb.as_bytes(emb.get(3, b"")))
            out["idx"] = pb.to_i64(pd.get(1, 0)) - 1
        elif tag in (7, 8):
            t = pb.to_i64(emb.get(3, 0))
            out["type"] = _VOTE_TYPE_NAMES.get(t, t)
    return out


def trace_wire_msg(direction: str, peer_id: str, chan_id: int,
                   raw: bytes) -> None:
    """Switch msg_tracer hook: one p2p.send/p2p.recv event per consensus
    wire message. Must never raise — a malformed frame is the receive
    path's problem; an exception here would tear down the peer."""
    if chan_id not in _TRACE_CHANNELS:
        return
    try:
        if chan_id == _MEMPOOL_CHANNEL:
            # tx gossip frame: repeated field 1, one element per tx
            meta = {"msg": "txs",
                    "n": sum(1 for f, _w, _v in pb.parse_fields(raw)
                             if f == 1)}
        else:
            meta = peek_wire_msg(raw)
        if meta is None:
            return
        if direction == "send":
            trace.event("p2p.send", peer=peer_id, chan=chan_id,
                        bytes=len(raw), **meta)
        else:
            trace.event("p2p.recv", peer=peer_id, chan=chan_id,
                        bytes=len(raw), **meta)
    except Exception:  # noqa: BLE001 — tracing must not disturb p2p
        pass


# ----------------------------------------------------------------------
# per-peer round state (reference internal/consensus/peer_state.go)
# ----------------------------------------------------------------------
class PeerState:
    def __init__(self, peer):
        self.peer = peer
        self.lock = threading.Lock()
        self.height = 0
        self.round = -1
        self.step = 0
        self.last_commit_round = -1
        self.proposal_seen = False
        self.parts: set[int] = set()  # part indexes at (height, round)
        self.catchup_parts: set[int] = set()  # parts sent for peer's height
        self.catchup_height = 0
        self.catchup_time = 0.0  # last catchup (re)start, for retry
        self.last_maj23_query = 0.0
        self.last_step_send = 0.0  # periodic NewRoundStep re-send
        # (height, round, type) -> set of validator indexes known to peer
        self.votes_seen: dict[tuple[int, int, int], set[int]] = {}
        # height -> monotonic time an AggregateCommit frame was last
        # sent (ISSUE 17): one certificate replaces the whole vote
        # column, so re-sends are time-gated instead of bitmap-diffed
        self.certs_sent: dict[int, float] = {}

    def mark_cert_sent(self, height: int, now: float,
                       resend_s: float) -> bool:
        """True when a certificate for `height` should be sent now (and
        records the send); False inside the re-send window."""
        with self.lock:
            if now - self.certs_sent.get(height, -1e9) < resend_s:
                return False
            self.certs_sent[height] = now
            while len(self.certs_sent) > 8:
                self.certs_sent.pop(next(iter(self.certs_sent)))
        return True

    def apply_new_round_step(self, m: NewRoundStepMessage) -> None:
        with self.lock:
            if (m.height, m.round) != (self.height, self.round):
                self.proposal_seen = False
                self.parts = set()
            if m.height != self.height:
                # keep only vote knowledge still useful (same height or
                # the commit for the previous height)
                self.votes_seen = {
                    k: v for k, v in self.votes_seen.items()
                    if k[0] >= m.height - 1
                }
            self.height = m.height
            self.round = m.round
            self.step = m.step
            self.last_commit_round = m.last_commit_round
        # per-peer reactor state gauges (VERDICT Next #3: rejoin-stall
        # debugging needs every peer's view of height/round exported)
        pid = getattr(self.peer, "id", "") or ""
        if pid:
            pm = p2p_metrics()
            pm.peer_height.set(m.height, pid[:16])
            pm.peer_round.set(m.round, pid[:16])

    def mark_vote(self, height: int, round_: int, type_: int, index: int):
        if index < 0 or index > MAX_VALIDATORS:
            return
        with self.lock:
            self.votes_seen.setdefault((height, round_, int(type_)), set()).add(
                index
            )
            # votes_seen keys are peer-influenced (HasVote/VoteSetBits at
            # arbitrary heights): bound the dict so junk heights cannot
            # accumulate — oldest keys go first
            while len(self.votes_seen) > 64:
                self.votes_seen.pop(next(iter(self.votes_seen)))

    def has_vote(self, height: int, round_: int, type_: int, index: int) -> bool:
        with self.lock:
            return index in self.votes_seen.get(
                (height, round_, int(type_)), ()
            )

    def mark_part(self, height: int, round_: int, index: int) -> None:
        with self.lock:
            if (height, round_) == (self.height, self.round):
                self.parts.add(index)

    def snapshot(self):
        with self.lock:
            return (self.height, self.round, self.step, self.proposal_seen,
                    set(self.parts))


class ConsensusReactor(Reactor):
    """State-aware gossip: one routine per peer pushes exactly what that
    peer is missing (proposal, block parts, votes), with catchup service
    for peers on earlier heights."""

    GOSSIP_SLEEP_S = 0.01
    PEER_QUERY_MAJ23_INTERVAL_S = 2.0
    CERT_RESEND_S = 2.0  # AggregateCommit re-send window per height
    # bounds on attacker-controlled buffers
    MAX_PART_INDEX = 2047  # parts per block (128 MiB at 64 KiB parts)
    MAX_HEADERLESS_PARTS = 256  # buffered before the proposal arrives
    MAX_VB_CANDIDATES = 4  # distinct NewValidBlock headers per height
    CATCHUP_CACHE_SIZE = 8  # committed-block PartSets kept for laggards
    MAX_VALIDATORS = MAX_VALIDATORS  # per-message vote-index/bitmap cap

    def __init__(self, cs: ConsensusState, block_store=None):
        self.cs = cs
        self.block_store = block_store if block_store is not None else cs.block_store
        self.switch = None
        self._peers: dict[str, PeerState] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        # our round's outbound data (proposer side + relayed)
        self._round_parts: PartSet | None = None
        self._round_parts_hr: tuple[int, int] = (0, -1)
        # reassembly of the incoming proposal block
        self._assembling: dict[int, Part] = {}
        self._assembling_hdr: PartSetHeader | None = None
        self._assembling_hr: tuple[int, int] = (0, -1)
        # committed-block PartSets / commit-vote lists served to lagging
        # peers, keyed by height (bounded LRU: peers lagging at different
        # heights must not thrash a single-entry cache with full
        # re-merkleizations, and one vote send must not rebuild the list)
        self._catchup_cache: dict[int, PartSet] = {}
        self._catchup_votes: dict[int, tuple] = {}
        # height -> AggregateCommit | None (ISSUE 17): the stored
        # commit's certificate when the height committed cert-natively,
        # so lagging peers get ONE frame instead of the vote column
        self._catchup_certs: dict[int, object] = {}
        # height-keyed assembly of a known-valid block (catchup path):
        # headers arrive via NewValidBlock, parts verified against them.
        # Multiple candidates per height, bounded: a forged header from
        # one peer must never pin the slot and starve honest headers
        # (safety holds regardless — commits sign the part-set header —
        # this bound is about liveness and memory).
        self._vb_height = 0
        self._vb_candidates: dict[bytes, tuple[PartSetHeader, dict[int, Part]]] = {}
        # encoded BlockPartMessage frames keyed (height, round, index):
        # gossiping P parts to N peers otherwise re-encodes the same
        # merkle-proved part N times (catchup frames carry the PEER's
        # round, hence round in the key); bounded FIFO
        self._part_frame_cache: dict[tuple[int, int, int], bytes] = {}
        cs.broadcast = self.broadcast_msg
        cs.on_new_step = self._on_new_step
        cs.on_has_vote = self._on_has_vote

    # -- Reactor interface ---------------------------------------------
    def channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=7),
        ]

    def set_switch(self, switch) -> None:
        self.switch = switch
        # arm the flight recorder's wire hook (no-op until tracing is
        # enabled; the switch fans it to every peer's send/recv path)
        if hasattr(switch, "set_msg_tracer"):
            switch.set_msg_tracer(trace_wire_msg)

    def stop(self) -> None:
        self._stopped.set()

    def add_peer(self, peer) -> None:
        ps = PeerState(peer)
        with self._lock:
            self._peers[peer.id] = ps
            t = threading.Thread(
                target=self._gossip_routine, args=(ps,), daemon=True,
                name=f"gossip-{peer.id[:8]}",
            )
            self._threads[peer.id] = t
        peer.send(STATE_CHANNEL, encode_consensus_msg(self._our_step_msg()))
        t.start()

    def remove_peer(self, peer, reason) -> None:
        with self._lock:
            self._peers.pop(peer.id, None)
            self._threads.pop(peer.id, None)
        pm = p2p_metrics()
        pm.peer_height.remove(peer.id[:16])
        pm.peer_round.remove(peer.id[:16])

    # -- outbound hooks from the state machine -------------------------
    def _our_step_msg(self) -> NewRoundStepMessage:
        cs = self.cs
        lcr = -1
        if cs.last_commit is not None:
            lcr = cs.last_commit.round
        return NewRoundStepMessage(cs.height, cs.round, int(cs.step), lcr)

    def _on_new_step(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                STATE_CHANNEL, encode_consensus_msg(self._our_step_msg())
            )

    def _on_has_vote(self, vote: Vote) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                STATE_CHANNEL,
                encode_consensus_msg(
                    HasVoteMessage(
                        vote.height, vote.round, vote.type, vote.validator_index
                    )
                ),
            )

    def broadcast_msg(self, msg) -> None:
        """Outbound seam for the state machine: proposals and our block
        bytes become round data served by the gossip routines; votes are
        pulled from the vote sets by difference, so no direct send."""
        if isinstance(msg, BlockBytesMessage):
            ps = PartSet.from_data(msg.block_bytes)
            with self._lock:
                self._round_parts = ps
                self._round_parts_hr = (msg.height, msg.round)
                # frames cached for an earlier (h, r) generation must
                # not alias the new round's parts
                self._part_frame_cache.clear()
        elif isinstance(msg, ProposalMessage):
            # proposal itself is picked up from cs.proposal by gossip;
            # nothing to store (cs sets cs.proposal before broadcasting)
            pass
        elif isinstance(msg, VoteMessage) and msg.direct:
            # a vote deliberately absent from our own vote set (the
            # byzantine equivocation shadow) — gossip pull would never
            # pick it up, so push it to every peer once
            raw = encode_consensus_msg(msg)
            with self._lock:
                peers = [ps.peer for ps in self._peers.values()]
            for peer in peers:
                try:
                    peer.send(VOTE_CHANNEL, raw)
                except Exception:  # noqa: BLE001 — peer mid-disconnect
                    pass
        # other VoteMessage: served from cs.votes by the vote gossip

    # -- inbound --------------------------------------------------------
    def receive(self, chan_id: int, peer, raw: bytes) -> None:
        msg = decode_consensus_msg(raw)
        with self._lock:
            ps = self._peers.get(peer.id)
        if ps is None:
            return
        if isinstance(msg, NewRoundStepMessage):
            ps.apply_new_round_step(msg)
        elif isinstance(msg, HasVoteMessage):
            ps.mark_vote(msg.height, msg.round, msg.type, msg.index)
        elif isinstance(msg, VoteMessage):
            v = msg.vote
            ps.mark_vote(v.height, v.round, v.type, v.validator_index)
            self.cs.send(msg, peer_id=peer.id)
        elif isinstance(msg, ProposalMessage):
            p = msg.proposal
            if (p.height, p.round) == (self.cs.height, self.cs.round):
                with ps.lock:
                    ps.proposal_seen = True
                self._begin_assembly(p, peer.id)
            self.cs.send(msg, peer_id=peer.id)
        elif isinstance(msg, BlockPartMessage):
            if (
                not 0 <= msg.part.index <= self.MAX_PART_INDEX
                or len(msg.part.bytes_) > PART_SIZE
            ):
                return
            ps.mark_part(msg.height, msg.round, msg.part.index)
            self._add_part(msg, peer.id)
        elif isinstance(msg, NewValidBlockMessage):
            with self._lock:
                if msg.height != self.cs.height:
                    return
                if self._vb_height != msg.height:
                    self._vb_height = msg.height
                    self._vb_candidates = {}
                key = msg.psh.hash
                if (
                    key not in self._vb_candidates
                    and len(self._vb_candidates) < self.MAX_VB_CANDIDATES
                    and 0 < msg.psh.total <= self.MAX_PART_INDEX + 1
                ):
                    self._vb_candidates[key] = (msg.psh, {})
        elif isinstance(msg, AggregateCommitMessage):
            # one-pairing verification happens in the state machine
            # (scheduler-routed), not on the p2p receive thread
            self.cs.send(msg, peer_id=peer.id)
        elif isinstance(msg, BlockBytesMessage):
            # legacy whole-block message: still accepted (tests, tools)
            self.cs.send(msg, peer_id=peer.id)
        elif isinstance(msg, VoteSetMaj23Message):
            self._answer_maj23(peer, msg)
        elif isinstance(msg, VoteSetBitsMessage):
            # the peer's bitmap for (height, round, type): every set bit
            # is a vote we need not gossip to it (reference peer_state
            # ApplyVoteSetBitsMessage). Bounded: one crafted message must
            # not force millions of marks.
            bits = msg.bits
            if bits.bit_length() > MAX_VALIDATORS:
                return
            i = 0
            while bits:
                if bits & 1:
                    ps.mark_vote(msg.height, msg.round, msg.type, i)
                bits >>= 1
                i += 1

    def _try_complete_locked(self, height: int, round_: int):
        """Caller holds self._lock. Returns assembled bytes when the
        round assembly is complete, else None."""
        hdr = self._assembling_hdr
        if hdr is None or len(self._assembling) != hdr.total:
            return None
        if not all(i in self._assembling for i in range(hdr.total)):
            return None
        parts = [self._assembling[i] for i in range(hdr.total)]
        data = PartSet(parts, hdr).assemble()
        self._assembling = {}
        self._assembling_hr = (0, -1)
        self._assembling_hdr = None
        # serve the parts onward to peers that still miss them
        self._round_parts = PartSet(parts, hdr)
        self._round_parts_hr = (height, round_)
        self._part_frame_cache.clear()
        return data

    def _begin_assembly(self, proposal: Proposal, peer_id: str) -> None:
        with self._lock:
            hr = (proposal.height, proposal.round)
            if self._assembling_hr != hr:
                self._assembling = {}
                self._assembling_hr = hr
            # adopt (or re-assert) the proposal's header; drop any
            # headerless-buffered parts that fail its proofs
            self._assembling_hdr = proposal.block_id.part_set_header
            bad = [
                i for i, part in self._assembling.items()
                if not PartSet.verify_part(self._assembling_hdr, part)
            ]
            for i in bad:
                self._assembling.pop(i)
            data = self._try_complete_locked(hr[0], hr[1])
        if data is not None:
            self.cs.send(
                BlockBytesMessage(hr[0], hr[1], data), peer_id=peer_id
            )

    def _add_part(self, msg: BlockPartMessage, peer_id: str) -> None:
        data = None
        hr = (msg.height, msg.round)
        with self._lock:
            if hr == self._assembling_hr or hr == (
                self.cs.height, self.cs.round
            ):
                if hr != self._assembling_hr:
                    # parts may arrive before the proposal: buffer them
                    # under the current round with an unknown header
                    self._assembling = {}
                    self._assembling_hr = hr
                    self._assembling_hdr = None
                hdr = self._assembling_hdr
                if hdr is None:
                    # headerless buffering is bounded: these parts are
                    # unverifiable until the proposal arrives, so a peer
                    # must not be able to grow the dict without limit
                    # (overflow parts are re-gossiped by bitmap diff)
                    if len(self._assembling) < self.MAX_HEADERLESS_PARTS:
                        self._assembling[msg.part.index] = msg.part
                elif PartSet.verify_part(hdr, msg.part):
                    self._assembling[msg.part.index] = msg.part
                    data = self._try_complete_locked(hr[0], hr[1])
                else:
                    _log.debug("invalid block part", height=msg.height,
                               index=msg.part.index, peer=peer_id[:8])
            if data is None and msg.height == self.cs.height:
                # known-valid block path (catchup): verify against any
                # announced NewValidBlock header, round-agnostic
                if self._vb_height != self.cs.height:
                    self._vb_candidates = {}
                    self._vb_height = self.cs.height
                for vhdr, vparts in self._vb_candidates.values():
                    if not PartSet.verify_part(vhdr, msg.part):
                        continue
                    vparts[msg.part.index] = msg.part
                    if len(vparts) == vhdr.total and all(
                        i in vparts for i in range(vhdr.total)
                    ):
                        parts = [vparts[i] for i in range(vhdr.total)]
                        data = PartSet(parts, vhdr).assemble()
                        vparts.clear()
                    break
        if data is not None:
            self.cs.send(
                BlockBytesMessage(msg.height, msg.round, data),
                peer_id=peer_id,
            )

    def _answer_maj23(self, peer, m: VoteSetMaj23Message) -> None:
        if m.height != self.cs.height:
            return
        vs = (
            self.cs.votes.prevotes(m.round)
            if m.type == SignedMsgType.PREVOTE
            else self.cs.votes.precommits(m.round)
        )
        if vs is None:
            return
        vs.set_peer_maj23(peer.id, m.block_id)
        ba = vs.bit_array_by_block_id(m.block_id)
        bits = 0
        if ba is not None:
            for i in range(ba.size()):
                if ba.get(i):
                    bits |= 1 << i
        peer.send(
            VOTE_CHANNEL,
            encode_consensus_msg(
                VoteSetBitsMessage(m.height, m.round, m.type, m.block_id, bits)
            ),
        )

    # -- per-peer gossip routine ---------------------------------------
    def _gossip_routine(self, ps: PeerState) -> None:
        while not self._stopped.is_set():
            with self._lock:
                alive = self._peers.get(ps.peer.id) is ps
            if not alive:
                return
            try:
                sent = self._gossip_data(ps)
                sent = self._gossip_votes(ps) or sent
                self._maybe_query_maj23(ps)
                self._maybe_resend_step(ps)
            except Exception as e:  # noqa: BLE001 — peer loops must survive
                _log.warn("gossip error", peer=ps.peer.id[:8],
                          err=f"{type(e).__name__}: {e}"[:120])
                sent = False
            if not sent:
                time.sleep(self.GOSSIP_SLEEP_S)

    STEP_RESEND_S = 2.0

    def _maybe_resend_step(self, ps: PeerState) -> None:
        """Re-broadcast our NewRoundStep to this peer periodically.

        State sync otherwise rests on the single add_peer-time send plus
        step-change broadcasts; if a peer misses those while both nodes
        are idle-waiting (no +2/3 -> no timeouts armed -> no new steps),
        its stale view of us (height 0) keeps its gossip routine from
        sending the very votes that would unstick the round — a mutual
        stall observed live on two-validator nets. A 2 s heartbeat of
        ~30 bytes makes peer state self-healing."""
        now = time.monotonic()
        if now - ps.last_step_send < self.STEP_RESEND_S:
            return
        ps.last_step_send = now
        ps.peer.send(
            STATE_CHANNEL, encode_consensus_msg(self._our_step_msg())
        )

    def _maybe_query_maj23(self, ps: PeerState) -> None:
        """Periodically tell a same-height peer which blocks we see +2/3
        votes for; it answers with VoteSetBits so vote gossip skips what
        it already has (reference queryMaj23Routine :893)."""
        now = time.monotonic()
        with ps.lock:
            if now - ps.last_maj23_query < self.PEER_QUERY_MAJ23_INTERVAL_S:
                return
            ps.last_maj23_query = now
            h = ps.height
        cs = self.cs
        if h != cs.height:
            return
        for vtype, vs in (
            (SignedMsgType.PREVOTE, cs.votes.prevotes(cs.round)),
            (SignedMsgType.PRECOMMIT, cs.votes.precommits(cs.round)),
        ):
            maj23 = getattr(vs, "maj23", None) if vs is not None else None
            if maj23 is None:
                continue
            ps.peer.send(
                STATE_CHANNEL,
                encode_consensus_msg(
                    VoteSetMaj23Message(cs.height, cs.round, vtype, maj23)
                ),
            )

    PART_FRAME_CACHE_SIZE = 256

    def _part_frame(self, h: int, r: int, part) -> bytes:
        """Encoded BlockPartMessage frame, cached per (height, round,
        index) so N peer gossip routines share one encode per part."""
        key = (h, r, part.index)
        with self._lock:
            frame = self._part_frame_cache.get(key)
        if frame is None:
            frame = encode_consensus_msg(BlockPartMessage(h, r, part))
            with self._lock:
                frame = self._part_frame_cache.setdefault(key, frame)
                while len(self._part_frame_cache) > self.PART_FRAME_CACHE_SIZE:
                    self._part_frame_cache.pop(
                        next(iter(self._part_frame_cache))
                    )
        return frame

    def _gossip_data(self, ps: PeerState) -> bool:
        cs = self.cs
        h, r, step, prop_seen, peer_parts = ps.snapshot()
        if h == 0:
            return False
        # catchup: peer is on an earlier height — serve the committed
        # block's parts from the store (reference gossipDataForCatchup)
        if h < cs.height:
            if self.block_store is None:
                return False
            with self._lock:
                cps = self._catchup_cache.get(h)
            if cps is None:
                # one store load + encode + merkleization per height, NOT
                # per part: the cache is consulted before touching the
                # store (a 32-part block would otherwise decode 32 times
                # per lagging peer)
                blk = self.block_store.load_block(h)
                if blk is None:
                    return False
                cps = PartSet.from_data(blk.encode())
                with self._lock:
                    cps = self._catchup_cache.setdefault(h, cps)
                    while len(self._catchup_cache) > self.CATCHUP_CACHE_SIZE:
                        self._catchup_cache.pop(
                            next(iter(self._catchup_cache))
                        )
            announce = False
            now = time.monotonic()
            with ps.lock:
                if ps.catchup_height != h:
                    ps.catchup_height = h
                    ps.catchup_parts = set()
                    ps.catchup_time = now
                    announce = True
                missing = [
                    p for p in cps.parts if p.index not in ps.catchup_parts
                ]
                if not missing and not announce:
                    # everything sent but the peer is still stuck at h:
                    # assume loss and retransmit after a grace period
                    if now - ps.catchup_time < 2.0:
                        return False
                    ps.catchup_parts = set()
                    ps.catchup_time = now
                    missing = list(cps.parts)
                    announce = True
                part = missing[0] if missing else None
                if part is not None:
                    ps.catchup_parts.add(part.index)
            if announce:
                # header first, so the peer can verify the parts
                # (reference NewValidBlockMessage semantics)
                ps.peer.send(
                    DATA_CHANNEL,
                    encode_consensus_msg(
                        NewValidBlockMessage(h, r, cps.header, is_commit=True)
                    ),
                )
            if part is not None:
                ps.peer.send(DATA_CHANNEL, self._part_frame(h, r, part))
            return True
        if h != cs.height:
            return False
        # proposal
        if cs.proposal is not None and not prop_seen and r == cs.round:
            ps.peer.send(
                DATA_CHANNEL,
                encode_consensus_msg(ProposalMessage(cs.proposal)),
            )
            with ps.lock:
                ps.proposal_seen = True
            return True
        # block parts by bitmap difference
        with self._lock:
            parts = self._round_parts
            hr = self._round_parts_hr
        if parts is not None and hr == (cs.height, cs.round) and r == cs.round:
            for part in parts.parts:
                if part.index not in peer_parts:
                    ps.peer.send(
                        DATA_CHANNEL, self._part_frame(hr[0], hr[1], part)
                    )
                    ps.mark_part(hr[0], hr[1], part.index)
                    return True
        return False

    def _pick_send_vote(self, ps: PeerState, vs) -> bool:
        """Send one vote from `vs` the peer hasn't seen (reference
        PickSendVote)."""
        if vs is None:
            return False
        ba = vs.bit_array()
        vtype = vs.signed_msg_type
        for i in range(ba.size()):
            if ba.get(i) and not ps.has_vote(vs.height, vs.round, vtype, i):
                v = vs.get_by_index(i)
                if v is None:
                    continue
                ps.peer.send(VOTE_CHANNEL, encode_consensus_msg(VoteMessage(v)))
                ps.mark_vote(vs.height, vs.round, vtype, i)
                return True
        return False

    def _cert_for_height(self, height: int):
        """The stored commit's AggregateCommit for a cert-native height
        (None when the height committed with a signature column). Cached
        beside the catchup PartSets."""
        with self._lock:
            if height in self._catchup_certs:
                return self._catchup_certs[height]
        store = self.block_store
        cert = None
        if store is not None:
            commit = store.load_block_commit(height) \
                or store.load_seen_commit(height)
            cert = getattr(commit, "cert", None)
        with self._lock:
            self._catchup_certs[height] = cert
            while len(self._catchup_certs) > self.CATCHUP_CACHE_SIZE:
                self._catchup_certs.pop(next(iter(self._catchup_certs)))
        return cert

    def _maybe_send_cert(self, ps: PeerState, height: int) -> bool:
        """Certificate-native catchup (ISSUE 17): send ONE
        AggregateCommit frame for `height` instead of gossiping the vote
        column, time-gated per (peer, height) for re-delivery."""
        cert = self._cert_for_height(height)
        if cert is None:
            return False
        if not ps.mark_cert_sent(height, time.monotonic(),
                                 self.CERT_RESEND_S):
            return False
        ps.peer.send(
            VOTE_CHANNEL,
            encode_consensus_msg(AggregateCommitMessage(cert)),
        )
        return True

    def _commit_as_voteset(self, height: int):
        """Stored commit -> precommit votes for catchup gossip (reference
        gossipVotesRoutine LoadCommit path). Cached per height beside the
        catchup PartSets: one vote is sent per gossip iteration and the
        reconstruction must not repeat per vote."""
        with self._lock:
            cached = self._catchup_votes.get(height)
        if cached is not None:
            return cached
        store = self.block_store
        if store is None:
            return None
        commit = store.load_block_commit(height) or store.load_seen_commit(
            height
        )
        if commit is None:
            return None
        if getattr(commit, "cert", None) is not None:
            # cert-native commit: per-validator signatures are gone from
            # the store — catchup is served by _maybe_send_cert instead
            return None
        votes = []
        for idx, csig in enumerate(commit.signatures):
            if csig.is_absent():
                continue
            votes.append(
                Vote(
                    type=SignedMsgType.PRECOMMIT,
                    height=height,
                    round=commit.round,
                    block_id=csig.effective_block_id(commit.block_id),
                    timestamp=csig.timestamp,
                    validator_address=csig.validator_address,
                    validator_index=idx,
                    signature=csig.signature,
                )
            )
        out = (commit.round, votes)
        with self._lock:
            self._catchup_votes[height] = out
            while len(self._catchup_votes) > self.CATCHUP_CACHE_SIZE:
                self._catchup_votes.pop(next(iter(self._catchup_votes)))
        return out

    def _gossip_votes(self, ps: PeerState) -> bool:
        cs = self.cs
        h, r, step, _, _ = ps.snapshot()
        if h == 0:
            return False
        if h == cs.height:
            # current-height votes by difference: peer round prevotes /
            # precommits, our round, POL
            for vs in (
                cs.votes.prevotes(r),
                cs.votes.precommits(r),
                cs.votes.prevotes(cs.round),
                cs.votes.precommits(cs.round),
            ):
                if self._pick_send_vote(ps, vs):
                    return True
            # a peer still on NEW_HEIGHT may be waiting for the previous
            # height's precommits to finalize its own commit (reference
            # gossipVotesForHeight's RoundStepNewHeight -> LastCommit)
            if (
                cs.last_commit is not None
                and step == int(RoundStep.NEW_HEIGHT)
                and self._pick_send_vote(ps, cs.last_commit)
            ):
                return True
            return False
        if h == cs.height - 1 and cs.last_commit is not None:
            if self._maybe_send_cert(ps, h):
                return True
            return self._pick_send_vote(ps, cs.last_commit)
        if h < cs.height - 1:
            if self._maybe_send_cert(ps, h):
                return True
            got = self._commit_as_voteset(h)
            if got is None:
                return False
            cround, votes = got
            for v in votes:
                if not ps.has_vote(h, cround, SignedMsgType.PRECOMMIT,
                                   v.validator_index):
                    ps.peer.send(
                        VOTE_CHANNEL, encode_consensus_msg(VoteMessage(v))
                    )
                    ps.mark_vote(h, cround, SignedMsgType.PRECOMMIT,
                                 v.validator_index)
                    return True
        return False
