from .height_vote_set import HeightVoteSet
from .ticker import ManualTicker, TimeoutInfo, TimeoutTicker
from .wal import WAL, EndHeightMessage, TimedWALMessage

__all__ = [
    "HeightVoteSet",
    "ManualTicker",
    "TimeoutInfo",
    "TimeoutTicker",
    "WAL",
    "EndHeightMessage",
    "TimedWALMessage",
]
