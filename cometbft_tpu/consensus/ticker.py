"""Timeout scheduling for consensus steps.

Behavior parity: reference internal/consensus/ticker.go — one pending
timeout at a time; scheduling a new one replaces the old (timeoutRoutine
drops stale timers for older height/round/step). Two implementations:

- TimeoutTicker: real wall-clock threading.Timer, fires into a callback.
- ManualTicker: test double — records schedules; tests fire explicitly
  (the reference's scripted state tests replace the ticker the same way).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int  # RoundStep value

    def _key(self):
        return (self.height, self.round, self.step)


def _newer(a: TimeoutInfo, b: TimeoutInfo) -> bool:
    """True when a is for a later (height, round, step) than b."""
    return a._key() > b._key()


class TimeoutTicker:
    def __init__(self, on_timeout):
        self._on_timeout = on_timeout
        self._lock = threading.Lock()
        self._timer: threading.Timer | None = None
        self._pending: TimeoutInfo | None = None
        self._stopped = False

    def schedule(self, ti: TimeoutInfo) -> None:
        with self._lock:
            if self._stopped:
                return
            # Ignore schedules older than the pending one (reference
            # timeoutRoutine: newti must be >= for same HRS handling).
            if self._pending is not None and _newer(self._pending, ti):
                return
            if self._timer is not None:
                self._timer.cancel()
            self._pending = ti
            self._timer = threading.Timer(ti.duration_s, self._fire, (ti,))
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._lock:
            if self._stopped or self._pending is not ti:
                return
            self._pending = None
        self._on_timeout(ti)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()


class ManualTicker:
    """Deterministic ticker for scripted tests."""

    def __init__(self, on_timeout=None):
        self._on_timeout = on_timeout
        self.scheduled: list[TimeoutInfo] = []

    def schedule(self, ti: TimeoutInfo) -> None:
        self.scheduled.append(ti)

    def fire_last(self) -> TimeoutInfo:
        ti = self.scheduled[-1]
        if self._on_timeout:
            self._on_timeout(ti)
        return ti

    def stop(self) -> None:
        pass
