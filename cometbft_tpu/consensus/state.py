"""The Tendermint consensus state machine.

Behavior parity: reference internal/consensus/state.go —
- the single-threaded receive loop processing peer messages, own messages,
  and timeouts, WAL-logging each message BEFORE acting on it
  (receiveRoutine :775-863; own messages fsync via WriteSync :830);
- the step functions enterNewRound :1043, enterPropose :1130,
  enterPrevote :1312, enterPrevoteWait, enterPrecommit :1514,
  enterPrecommitWait, enterCommit :1649, tryFinalizeCommit :1712,
  finalizeCommit :1740 with the lock/unlock/valid-block (POL) rules;
- vote accounting addVote :2161 including last-commit precommits from the
  previous height;
- crash recovery: catchup_replay re-handles WAL records logged after the
  last #ENDHEIGHT (reference internal/consensus/replay.go:94), with
  signing idempotence delegated to the FilePV last-sign state.

Gossip transport: over real p2p the consensus reactor gossips proposals
as 64 KiB merkle-proved parts (consensus/reactor.py, reference
internal/consensus/reactor.go); the in-process loopback path used by
tests can also deliver whole blocks via BlockBytesMessage through
_handle_block_bytes. The part-set machinery defines BlockID either way
(types/part_set.py).
"""

from __future__ import annotations

import enum
import queue
import threading
import time
import time as _time
from dataclasses import dataclass, field as dc_field

from ..state.execution import BlockExecutor, BlockValidationError, validate_block
from ..utils import trace
from ..utils import txlife as _txlife
from ..utils.fail import fail_point
from ..utils.log import logger
from ..utils.metrics import consensus_metrics
from ..types import (
    Block,
    BlockID,
    Commit,
    Proposal,
    Timestamp,
    ValidatorSet,
    Vote,
)
from ..types.block import block_id_for
from ..types.evidence import evidence_list_hash
from ..types.vote import SignedMsgType
from ..types.vote_set import ErrVoteConflictingVotes, VoteSet
from .height_vote_set import HeightVoteSet
from .ticker import TimeoutInfo, TimeoutTicker
from .wal import (
    AggregateCommitMessage,
    BlockBytesMessage,
    MsgInfo,
    TimeoutMessage,
    WAL,
)


class RoundStep(enum.IntEnum):
    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


@dataclass
class TimeoutConfig:
    """Step timeouts (reference config/config.go ConsensusConfig defaults,
    scaled down for in-process nets by tests)."""

    propose: float = 3.0
    propose_delta: float = 0.5
    prevote: float = 1.0
    prevote_delta: float = 0.5
    precommit: float = 1.0
    precommit_delta: float = 0.5
    commit: float = 1.0

    def propose_timeout(self, round_: int) -> float:
        return self.propose + self.propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.prevote + self.prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.precommit + self.precommit_delta * round_


@dataclass
class ProposalMessage:
    proposal: Proposal


@dataclass
class VoteMessage:
    vote: Vote
    # votes normally propagate by gossip pull from the vote sets; a
    # vote that is deliberately NOT in our own set (the byzantine
    # shadow from privval/byzantine.py) must be pushed on the wire
    # explicitly or it never leaves the process. Local-only flag —
    # the codec encodes just the vote.
    direct: bool = False


@dataclass
class _SpeculativeProposal:
    """A proposal block assembled ahead of enter_propose, with everything
    the assembly depended on so the consume seam can prove nothing moved.
    `state` is identity-compared: a different object means ApplyBlock ran
    again (app hash, valset, results all derive from it)."""

    height: int
    state: object
    last_commit_hash: bytes
    mempool_version: int
    block: Block
    block_id: BlockID


class _CertVoteSetShim:
    """Stand-in for the last-commit VoteSet after a restart whose stored
    seen commit is certificate-native (ISSUE 17): the per-validator
    signatures are unrecoverable from the BLS aggregate, so this quacks
    just enough of VoteSet — catchup gossip and proposal embedding read
    the commit back via make_commit(); vote accounting and per-index
    queries degrade to no-ops."""

    signed_msg_type = SignedMsgType.PRECOMMIT

    def __init__(self, height: int, cert_commit, val_set):
        self.height = height
        self.round = cert_commit.round
        self.val_set = val_set
        self._cc = cert_commit

    def make_commit(self):
        return self._cc

    def add_vote(self, vote, peer_id: str = "") -> bool:
        return False

    def size(self) -> int:
        return self._cc.size()

    def bit_array(self):
        from ..utils.bits import BitArray

        return BitArray(self._cc.size())  # all clear: no votes to gossip

    def get_by_index(self, idx: int):
        return None

    def two_thirds_majority(self):
        return self._cc.block_id, True

    def has_two_thirds_any(self) -> bool:
        return True


class ConsensusState:
    """One validator's consensus engine over an in-process transport."""

    def __init__(
        self,
        chain_id: str,
        sm_state,
        executor: BlockExecutor,
        block_store,
        privval,
        wal: WAL,
        broadcast=None,
        timeouts: TimeoutConfig | None = None,
        tx_source=None,
        name: str = "",
        now_ns=None,
        ticker_factory=None,
        speculative: bool = False,
        mempool_version=None,
        cert_native: bool = True,
    ):
        self.chain_id = chain_id
        self.sm_state = sm_state
        self.executor = executor
        self.block_store = block_store
        self.privval = privval
        self.wal = wal
        self.broadcast = broadcast or (lambda msg: None)
        self.timeouts = timeouts or TimeoutConfig()
        self.tx_source = tx_source or (lambda: [])
        self.name = name or (privval.address().hex()[:8] if privval else "observer")
        self.now_ns = now_ns or time.time_ns
        # speculative proposal assembly (ISSUE 11): when enabled and this
        # node proposes the next height, reap + block assembly run in a
        # background worker during the commit gap; mempool_version is the
        # staleness probe the consume seam checks (CListMempool.version)
        self.speculative = speculative
        self.mempool_version = mempool_version or (lambda: 0)
        # certificate-native consensus (ISSUE 17): fold +2/3 BLS
        # precommits into one AggregateCommit for gossip, storage and
        # proposal embedding. Inert on non-BLS validator sets.
        self.cert_native = cert_native
        self._spec_lock = threading.Lock()
        self._spec_thread: threading.Thread | None = None
        self._spec: _SpeculativeProposal | None = None

        self._log = logger("consensus").with_fields(node=self.name)
        self._last_commit_mono: float | None = None
        self.inbox: queue.Queue = queue.Queue()
        # reactor hooks: step-change broadcast + HasVote announcements
        # (reference broadcastNewRoundStepMessage / broadcastHasVoteMessage)
        self.on_new_step = None
        self.on_has_vote = None
        self.ticker = (ticker_factory or TimeoutTicker)(self._on_ticker_timeout)
        self.evidence: list[ErrVoteConflictingVotes] = []
        self.decided: dict[int, BlockID] = {}  # height -> committed block id
        self._replay_mode = False
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._step_cv = threading.Condition()
        # round-state snapshot lock: the consensus thread holds it across
        # each _process (one message/timeout = one atomic round-state
        # transition), so RPC dump routes can take a CONSISTENT snapshot
        # by acquiring it instead of retry-sampling racy fields. RLock:
        # handlers re-enter _process-held paths via the WAL replay seam.
        self.rs_mutex = threading.RLock()

        # --- RoundState ---
        self.height = sm_state.last_block_height + 1
        self.round = 0
        self.step = RoundStep.NEW_HEIGHT
        self._step_t0 = time.perf_counter()
        self.validators: ValidatorSet = sm_state.validators.copy()
        self.proposal: Proposal | None = None
        self.proposal_block: Block | None = None
        self.proposal_block_id: BlockID | None = None
        self.locked_round = -1
        self.locked_block: Block | None = None
        self.locked_block_id: BlockID | None = None
        self.valid_round = -1
        self.valid_block: Block | None = None
        self.valid_block_id: BlockID | None = None
        self.votes = HeightVoteSet(chain_id, self.height, self.validators)
        self.commit_round = -1
        self.last_commit: VoteSet | None = None
        self.triggered_timeout_precommit = False
        # tx lifecycle observatory: sampled (index, key) pairs of the
        # current proposal block, hashed once per (height, block id)
        self._txlife_cache: tuple | None = None

    # ==================================================================
    # lifecycle
    # ==================================================================
    def reconstruct_last_commit(self) -> None:
        """Rebuild the last-commit VoteSet from the stored seen commit
        (reference state.go reconstructLastCommit) — restart path."""
        h = self.sm_state.last_block_height
        if h == 0 or self.block_store is None:
            return
        seen = self.block_store.load_seen_commit(h)
        if seen is None:
            return
        vals = self.sm_state.last_validators
        if getattr(seen, "cert", None) is not None:
            # certificate-native seen commit: the per-validator
            # signatures are unrecoverable from the aggregate, so stand
            # in a shim that serves the commit back (catchup gossip,
            # proposal embedding) and no-ops vote accounting
            self.last_commit = _CertVoteSetShim(h, seen, vals)
            return
        vs = VoteSet(self.chain_id, h, seen.round, SignedMsgType.PRECOMMIT, vals)
        for idx, cs in enumerate(seen.signatures):
            if cs.is_absent():
                continue
            vs.add_vote(
                Vote(
                    type=SignedMsgType.PRECOMMIT,
                    height=h,
                    round=seen.round,
                    block_id=cs.effective_block_id(seen.block_id),
                    timestamp=cs.timestamp,
                    validator_address=cs.validator_address,
                    validator_index=idx,
                    signature=cs.signature,
                ),
                verify=False,  # stored commit was verified before saving
            )
        self.last_commit = vs

    def extensions_enabled(self, height: int) -> bool:
        """Vote extensions active at `height` (reference
        ConsensusParams.ABCI.VoteExtensionsEnabled)."""
        eh = self.sm_state.consensus_params.abci.vote_extensions_enable_height
        return eh > 0 and height >= eh

    def reset_to_state(self, sm_state) -> None:
        """Re-anchor a not-yet-started instance to a newer state (the
        block-sync / state-sync → consensus hand-off; reference
        SwitchToConsensus, consensus/reactor.go:113)."""
        if self._thread is not None:
            raise RuntimeError("cannot reset a running consensus instance")
        self.sm_state = sm_state
        self.height = sm_state.last_block_height + 1
        self.round = 0
        self.step = RoundStep.NEW_HEIGHT
        self.validators = sm_state.validators.copy()
        self.votes = HeightVoteSet(self.chain_id, self.height, self.validators)
        self.last_commit = None
        self.proposal = None
        self.proposal_block = None
        self.proposal_block_id = None

    def start(self, replay_wal: bool = True) -> None:
        if self.last_commit is None and self.height > self.sm_state.initial_height:
            self.reconstruct_last_commit()
        if replay_wal:
            self.catchup_replay()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cs-{self.name}")
        self._thread.start()
        self._schedule_round0_start()

    def _schedule_round0_start(self):
        # NewHeight -> round 0 after timeout_commit (immediately at genesis).
        self.ticker.schedule(
            TimeoutInfo(0.0, self.height, 0, int(RoundStep.NEW_HEIGHT))
        )

    def stop(self) -> None:
        self._stopped.set()
        self.ticker.stop()
        self.inbox.put(None)
        if self._thread:
            self._thread.join(timeout=5)
        self.wal.flush()

    # ==================================================================
    # inbound
    # ==================================================================
    def send(self, msg, peer_id: str) -> None:
        """Deliver a message from a peer (thread-safe)."""
        self.inbox.put(MsgInfo(msg, peer_id))

    def _on_ticker_timeout(self, ti: TimeoutInfo) -> None:
        self.inbox.put(ti)

    def _run(self) -> None:
        while not self._stopped.is_set():
            item = self.inbox.get()
            if item is None:
                break
            try:
                self._process(item)
            except Exception:  # noqa: BLE001 — reference panics halt chain
                import traceback

                traceback.print_exc()
                self._stopped.set()
                raise

    def _process(self, item) -> None:
        before = (self.height, self.round, int(self.step))
        try:
            with self.rs_mutex:
                self._process_inner(item)
        finally:
            if self.on_new_step is not None and (
                (self.height, self.round, int(self.step)) != before
            ):
                try:
                    self.on_new_step()  # reactor broadcasts NewRoundStep
                except Exception:  # noqa: BLE001
                    pass

    def _process_inner(self, item) -> None:
        if isinstance(item, TimeoutInfo):
            self.wal.write(
                TimeoutMessage(ti_height(item), item.round, item.step)
            )
            self._handle_timeout(item)
        elif isinstance(item, MsgInfo):
            inner = item.msg
            wal_msg = MsgInfo(_wal_payload(inner), item.peer_id)
            if item.peer_id == "":
                self.wal.write_sync(wal_msg)  # own msgs hit disk first
                fail_point()  # reference state.go:843 (own msg persisted)
                self._handle_msg(inner, item.peer_id)
            else:
                self.wal.write(wal_msg)
                try:
                    self._handle_msg(inner, item.peer_id)
                except Exception:
                    # A malformed peer message must never halt consensus
                    # (reference drops it and punishes the peer); only our
                    # own messages are trusted to be well-formed.
                    pass
        with self._step_cv:
            self._step_cv.notify_all()

    def _handle_msg(self, msg, peer_id: str) -> None:
        if isinstance(msg, (VoteMessage, Vote)):
            self._handle_vote(msg.vote if isinstance(msg, VoteMessage) else msg,
                              peer_id)
        elif isinstance(msg, (ProposalMessage, Proposal)):
            self._handle_proposal(
                msg.proposal if isinstance(msg, ProposalMessage) else msg, peer_id
            )
        elif isinstance(msg, BlockBytesMessage):
            self._handle_block_bytes(msg, peer_id)
        elif isinstance(msg, AggregateCommitMessage):
            self._handle_cert(msg, peer_id)
        else:
            raise TypeError(f"unknown consensus message {type(msg)}")

    # ==================================================================
    # handlers
    # ==================================================================
    def _handle_proposal(self, p: Proposal, peer_id: str) -> None:
        # reference defaultSetProposal (state.go:1876)
        if self.proposal is not None:
            return
        if p.height != self.height or p.round != self.round:
            return
        p.basic_validate()
        proposer = self.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            p.sign_bytes(self.chain_id), p.signature
        ):
            raise BlockValidationError("invalid proposal signature")
        self.proposal = p
        if (
            self.proposal_block is not None
            and self.proposal_block_id is not None
            and self.proposal_block_id == p.block_id
        ):
            self._on_complete_proposal()

    def _handle_block_bytes(self, bb: BlockBytesMessage, peer_id: str) -> None:
        if bb.height != self.height:
            return
        if self.proposal_block is not None:
            return
        block = Block.decode(bb.block_bytes)
        bid = block_id_for(block)
        committed_id = None
        if self.commit_round >= 0:
            committed_id, _ = self.votes.precommits(self.commit_round).two_thirds_majority()
        wanted = (self.proposal is not None and bid == self.proposal.block_id) or (
            committed_id is not None and bid == committed_id
        )
        if not wanted and self.proposal is not None:
            return  # not the block we're looking for; drop
        self.proposal_block = block
        self.proposal_block_id = bid
        if self.proposal is not None and bid == self.proposal.block_id:
            self._on_complete_proposal()
        elif committed_id is not None and bid == committed_id:
            self._try_finalize_commit(self.height)

    def _on_complete_proposal(self) -> None:
        # reference handleCompleteProposal (state.go:2045)
        if self.step == RoundStep.PROPOSE:
            self.enter_prevote(self.height, self.round)
        elif self.step == RoundStep.COMMIT or self.commit_round >= 0:
            self._try_finalize_commit(self.height)

    def _handle_vote(self, v: Vote, peer_id: str) -> None:
        # reference tryAddVote/addVote (state.go:2095,2161)
        if v.height + 1 == self.height and v.type == SignedMsgType.PRECOMMIT:
            if self.step != RoundStep.NEW_HEIGHT or self.last_commit is None:
                return
            try:
                self.last_commit.add_vote(v)
            except ErrVoteConflictingVotes as e:
                self.evidence.append(e)
                self._trace_conflicting_votes(e)
            except Exception:
                pass
            return
        if v.height != self.height:
            return
        if (
            v.type == SignedMsgType.PRECOMMIT
            and not v.is_nil()
            and self.extensions_enabled(self.height)
            and peer_id != ""
        ):
            # reference addVote: peers' precommits must carry a valid
            # extension signature AND pass the app's VerifyVoteExtension
            if not self._verify_vote_extension(v):
                return
        try:
            added = self.votes.add_vote(v, peer_id)
        except ErrVoteConflictingVotes as e:
            self.evidence.append(e)
            self._trace_conflicting_votes(e)
            pool = getattr(self.executor, "evidence_pool", None)
            if pool is not None:  # reference evidencePool.ReportConflictingVotes
                pool.report_conflicting_votes(e.vote_a, e.vote_b)
            if not e.added:
                return
            added = True
        except Exception:
            if peer_id == "":
                raise  # own vote must never be invalid
            return  # bad peer vote: drop (peer punishment at p2p layer)
        if not added:
            return

        if self.on_has_vote is not None:
            try:
                self.on_has_vote(v)  # reactor broadcasts HasVote
            except Exception:  # noqa: BLE001 — gossip must not stall consensus
                pass

        if v.type == SignedMsgType.PREVOTE:
            self._after_prevote(v)
        else:
            self._after_precommit(v)

    def _verify_vote_extension(self, v: Vote) -> bool:
        _, val = self.validators.get_by_address(v.validator_address)
        if val is None:
            return False
        if not v.extension_signature:
            return False
        if not val.pub_key.verify_signature(
            v.extension_sign_bytes(self.chain_id), v.extension_signature
        ):
            return False
        return bool(
            self.executor.app.consensus.verify_vote_extension(
                v.height, v.validator_address, v.extension
            )
        )

    def _after_prevote(self, v: Vote) -> None:
        prevotes = self.votes.prevotes(v.round)
        maj, ok = prevotes.two_thirds_majority()
        if ok:
            # unlock on a later-round POL for a different block (state.go:2230)
            if (
                self.locked_block is not None
                and self.locked_round < v.round <= self.round
                and self.locked_block_id != maj
            ):
                self.locked_round = -1
                self.locked_block = None
                self.locked_block_id = None
            # track the most recent possible valid block (state.go:2246)
            if (
                not maj.is_zero()
                and (self.valid_round < v.round)
                and v.round == self.round
            ):
                if self.proposal_block_id == maj:
                    self.valid_round = v.round
                    self.valid_block = self.proposal_block
                    self.valid_block_id = maj
            if (_txlife.enabled and not maj.is_zero()
                    and self.proposal_block is not None
                    and self.proposal_block_id == maj):
                _txlife.stage_block(
                    self._lifecycle_pairs(self.proposal_block, maj),
                    "prevote_quorum", height=self.height, round=v.round)

        if self.round < v.round and prevotes.has_two_thirds_any():
            self.enter_new_round(self.height, v.round)
        elif self.round == v.round and self.step >= RoundStep.PREVOTE:
            if ok and (maj.is_zero() or maj == self.proposal_block_id
                       or maj == self.locked_block_id):
                self.enter_precommit(self.height, v.round)
            elif prevotes.has_two_thirds_any() and self.step == RoundStep.PREVOTE:
                self.enter_prevote_wait(self.height, v.round)
        elif (
            self.proposal is not None
            and 0 <= self.proposal.pol_round == v.round
            and self.step == RoundStep.PROPOSE
            and self._proposal_complete()
        ):
            self.enter_prevote(self.height, self.round)

    def _after_precommit(self, v: Vote) -> None:
        self._check_precommit_progress(v.round)

    def _check_precommit_progress(self, r: int) -> None:
        """Drive step transitions off round r's precommit set — shared by
        per-vote accounting and certificate application (ISSUE 17)."""
        precommits = self.votes.precommits(r)
        maj, ok = precommits.two_thirds_majority()
        if ok:
            self.enter_new_round(self.height, r)
            self.enter_precommit(self.height, r)
            if not maj.is_zero():
                self.enter_commit(self.height, r)
            else:
                self.enter_precommit_wait(self.height, r)
        elif self.round <= r and precommits.has_two_thirds_any():
            self.enter_new_round(self.height, r)
            self.enter_precommit_wait(self.height, r)

    def _handle_cert(self, msg: AggregateCommitMessage, peer_id: str) -> None:
        """One +2/3 aggregate-precommit certificate from catchup gossip
        (ISSUE 17): replaces N vote frames for a lagging node. Verified
        with ONE pairing (through the shared VerifyScheduler when the
        executor has one), then folded into the height-vote-set so the
        ordinary precommit progress rules fire."""
        cert = msg.cert
        m = consensus_metrics()
        if not self.cert_native:
            m.cert_gossip_total.inc(1.0, "disabled")
            return
        if cert.height != self.height:
            m.cert_gossip_total.inc(1.0, "stale")
            return
        if not self.validators.all_bls():
            m.cert_gossip_total.inc(1.0, "non_bls")
            return
        self.votes._ensure_round(cert.round)
        vs = self.votes.precommits(cert.round)
        if vs.cert is not None:
            m.cert_gossip_total.inc(1.0, "dup")
            return
        _, ok = vs.two_thirds_majority()
        if ok:
            # vote gossip already reached quorum on its own
            m.cert_gossip_total.inc(1.0, "redundant")
            return
        from ..types.agg_commit import CertCommit
        from ..types.validation import CertCommitVerifier

        bv = CertCommitVerifier(
            self.chain_id, self.validators,
            CertCommit(cert, len(self.validators)),
        )
        sched = getattr(self.executor, "verify_sched", None)
        t0 = time.perf_counter()
        if sched is not None:
            verified, _ = sched.submit(
                bv, self.executor.sched_tenant, "consensus"
            ).result()
        else:
            verified, _ = bv.verify()
        if trace.enabled:
            trace.emit(
                "consensus.cert_aggregate", "span",
                dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                height=cert.height, round=cert.round,
                signers=cert.signer_count(),
                outcome="verified" if verified else "invalid",
            )
        if not verified:
            m.cert_gossip_total.inc(1.0, "invalid")
            return  # bad peer certificate: drop (punishment at p2p layer)
        try:
            added = vs.apply_certificate(cert)
        except Exception:
            m.cert_gossip_total.inc(1.0, "invalid")
            return
        if not added:
            m.cert_gossip_total.inc(1.0, "dup")
            return
        m.cert_gossip_total.inc(1.0, "applied")
        self._check_precommit_progress(cert.round)

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        # reference handleTimeout (state.go:982)
        if ti.height != self.height:
            return
        step = RoundStep(ti.step)
        if step == RoundStep.NEW_HEIGHT:
            self.enter_new_round(self.height, 0)
            return
        if ti.round < self.round or (
            ti.round == self.round and step < self.step
        ):
            return
        if step == RoundStep.PROPOSE:
            self.enter_prevote(self.height, ti.round)
        elif step == RoundStep.PREVOTE_WAIT:
            self.enter_precommit(self.height, ti.round)
        elif step == RoundStep.PRECOMMIT_WAIT:
            self.enter_precommit(self.height, ti.round)
            self.enter_new_round(self.height, ti.round + 1)

    # ==================================================================
    # step functions
    # ==================================================================
    def _update_step(self, round_: int, step: RoundStep) -> None:
        # Every step transition funnels through here: close the span for
        # the step being left (tracer + step-duration histogram), then
        # switch. One perf_counter read per transition when idle.
        prev = self.step
        if prev != step:
            now = time.perf_counter()
            dur = now - self._step_t0
            self._step_t0 = now
            consensus_metrics().step_duration_seconds.observe(dur, prev.name)
            if trace.enabled:
                trace.emit(
                    "consensus.step", "span", step=prev.name,
                    height=self.height, round=self.round,
                    dur_ms=round(dur * 1e3, 3), next=step.name,
                )
        self.round = round_
        self.step = step

    def enter_new_round(self, h: int, r: int) -> None:
        if h != self.height or r < self.round or (
            r == self.round and self.step != RoundStep.NEW_HEIGHT
        ):
            return
        if r > self.round:
            self.validators.increment_proposer_priority(r - self.round)
        self._log.debug("entering new round", height=h, round=r)
        consensus_metrics().rounds.set(r)
        self._update_step(r, RoundStep.NEW_ROUND)
        self.triggered_timeout_precommit = False
        if r != 0:
            self.proposal = None
            self.proposal_block = None
            self.proposal_block_id = None
        self.votes.set_round(r + 1)
        self.enter_propose(h, r)

    def enter_propose(self, h: int, r: int) -> None:
        if h != self.height or r < self.round or (
            r == self.round and self.step >= RoundStep.PROPOSE
        ):
            return
        self._update_step(r, RoundStep.PROPOSE)
        self.ticker.schedule(
            TimeoutInfo(self.timeouts.propose_timeout(r), h, r,
                        int(RoundStep.PROPOSE))
        )
        if self._proposal_complete():
            self.enter_prevote(h, r)
            return
        if self.privval is None:
            return
        proposer = self.validators.get_proposer()
        if proposer.address != self.privval.address():
            return
        # --- we are the proposer (defaultDecideProposal, state.go:1180) ---
        if self.valid_block is not None:
            block, bid = self.valid_block, self.valid_block_id
        else:
            last_commit = self._last_commit_for_proposal()
            spec = self._take_speculative(h, r, last_commit)
            if spec is not None:
                block, bid = spec.block, spec.block_id
            else:
                block = self.executor.create_proposal_block(
                    h, self.sm_state, last_commit, proposer.address,
                    self.tx_source(),
                    block_time=self._proposal_block_time(),
                )
                # encode exactly once: the memo feeds block_id_for's
                # part-set, the BlockBytesMessage broadcast below, and
                # _finalize_commit's size gauge
                block.__dict__["_enc_memo"] = block.encode()
                bid = block_id_for(block)
        if _txlife.enabled:
            _txlife.stage_block(self._lifecycle_pairs(block, bid), "reap",
                                height=h)
        proposal = Proposal(
            height=h, round=r, pol_round=self.valid_round, block_id=bid,
            timestamp=Timestamp.from_unix_ns(self.now_ns()),
        )
        self.privval.sign_proposal(self.chain_id, proposal)
        bb = BlockBytesMessage(
            h, r, block.__dict__.get("_enc_memo") or block.encode()
        )
        if not self._replay_mode:
            self.broadcast(ProposalMessage(proposal))
            self.broadcast(bb)
            if _txlife.enabled:
                _txlife.stage_block(self._lifecycle_pairs(block, bid),
                                    "gossip", height=h)
        self.send(ProposalMessage(proposal), "")
        self.send(bb, "")

    def _lifecycle_pairs(self, block, bid):
        """Sampled (index, key) pairs for a proposal block's txs —
        hashed ONCE per (height, block id) so the reap/gossip/quorum
        stamp sweeps don't re-hash the block per stage."""
        if block is None or bid is None:
            return ()
        tag = (self.height, bid.hash)
        cache = self._txlife_cache
        if cache is not None and cache[0] == tag:
            return cache[1]
        pairs = _txlife.sampled_keys(block.data.txs)
        self._txlife_cache = (tag, pairs)
        return pairs

    def _proposal_block_time(self) -> Timestamp:
        if self.height == self.sm_state.initial_height:
            return self.sm_state.last_block_time
        return Timestamp.from_unix_ns(self.now_ns())

    def _last_commit_for_proposal(self) -> Commit:
        if self.height == self.sm_state.initial_height:
            return Commit()
        assert self.last_commit is not None, "no last commit at height > initial"
        commit = self.last_commit.make_commit()
        if self.cert_native:
            # fold the +2/3 precommit column into one BLS certificate so
            # the proposed block embeds it natively (ISSUE 17) — no-op
            # for non-BLS/mixed sets or non-uniform timestamps
            from ..types.agg_commit import fold_commit

            commit = fold_commit(commit, self.sm_state.last_validators)
        return commit

    # ------------------------------------------------------------------
    # speculative proposal assembly (ISSUE 11)
    # ------------------------------------------------------------------
    def _maybe_speculate(self) -> None:
        """Kick off background proposal assembly for the height just
        entered, overlapping the reap + create_proposal_block + encode
        work with the NEW_HEIGHT commit gap (where the PR-9 observatory
        attributed 42.9% of e2e p50 as proposal_wait). Runs only when
        this node is the round-0 proposer; enter_propose consumes the
        result through _take_speculative, which re-checks everything the
        assembly depended on and discards on any mismatch — the cold
        path is always correct, speculation only ever saves time."""
        with self._spec_lock:
            if self._spec is not None:
                # previous height's block was never consumed (e.g. a
                # valid_block lock superseded it)
                self._spec = None
                consensus_metrics().speculation_total.inc(1.0, "discard")
        if (
            not self.speculative
            or self._replay_mode
            or self.privval is None
            or self.height == self.sm_state.initial_height
        ):
            return
        if self.validators.get_proposer().address != self.privval.address():
            return
        h = self.height
        state = self.sm_state
        last_commit = self._last_commit_for_proposal()
        mv = self.mempool_version()
        proposer_addr = self.privval.address()

        def work():
            t0 = time.perf_counter()
            try:
                # block_time is omitted on purpose: non-initial heights
                # derive the header time from median_time(last_commit),
                # which is frozen in the snapshot above — so the result
                # is bit-exact with the cold path
                block = self.executor.create_proposal_block(
                    h, state, last_commit, proposer_addr, self.tx_source()
                )
                enc = block.encode()
                block.__dict__["_enc_memo"] = enc
                bid = block_id_for(block)
            except Exception:  # noqa: BLE001 — speculation must never hurt
                return
            with self._spec_lock:
                if self._spec_thread is not t:
                    # superseded by a newer height's worker: drop
                    consensus_metrics().speculation_total.inc(
                        1.0, "discard")
                    return
                self._spec = _SpeculativeProposal(
                    height=h, state=state,
                    last_commit_hash=last_commit.hash(),
                    mempool_version=mv, block=block, block_id=bid,
                )
            if trace.enabled:
                trace.emit(
                    "consensus.propose_speculative", "span",
                    dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                    height=h, txs=len(block.data.txs), bytes=len(enc),
                )

        t = threading.Thread(target=work, daemon=True,
                             name=f"cs-spec-{self.name}")
        self._spec_thread = t
        t.start()

    def _take_speculative(self, h: int, r: int, last_commit: Commit):
        """The correctness seam: hand back the speculative block only if
        every input it was assembled from is still what enter_propose
        would use — otherwise discard. Joining an in-flight worker is
        never slower than redoing the same assembly on this thread."""
        t = self._spec_thread
        if t is None:
            return None
        t.join()
        self._spec_thread = None
        with self._spec_lock:
            spec, self._spec = self._spec, None
        if spec is None:
            consensus_metrics().speculation_total.inc(1.0, "discard")
            return None
        ok = (
            r == 0
            and spec.height == h
            and spec.state is self.sm_state
            and spec.mempool_version == self.mempool_version()
            and spec.last_commit_hash == last_commit.hash()
            and spec.block.header.evidence_hash == self._evidence_hash_now()
        )
        consensus_metrics().speculation_total.inc(
            1.0, "hit" if ok else "discard")
        return spec if ok else None

    def _evidence_hash_now(self) -> bytes:
        """Hash of the evidence create_proposal_block would include NOW
        (same pending_evidence budget it applies)."""
        pool = getattr(self.executor, "evidence_pool", None)
        if pool is None:
            return evidence_list_hash([])
        params = self.sm_state.consensus_params
        cap = min(params.evidence.max_bytes, params.block.max_bytes // 10)
        return evidence_list_hash(pool.pending_evidence(cap))

    def _proposal_complete(self) -> bool:
        return (
            self.proposal is not None
            and self.proposal_block is not None
            and self.proposal_block_id == self.proposal.block_id
        )

    def enter_prevote(self, h: int, r: int) -> None:
        if h != self.height or r < self.round or (
            r == self.round and self.step >= RoundStep.PREVOTE
        ):
            return
        self._update_step(r, RoundStep.PREVOTE)
        # defaultDoPrevote (state.go:1365)
        if self.locked_block is not None:
            self._sign_and_send_vote(SignedMsgType.PREVOTE, self.locked_block_id)
            return
        if self.proposal_block is None or not self._proposal_complete():
            self._sign_and_send_vote(SignedMsgType.PREVOTE, BlockID())
            return
        try:
            validate_block(
                self.sm_state, self.proposal_block,
                backend=self.executor.backend,
            )
            app_accepts = self.executor.process_proposal(self.proposal_block)
        except BlockValidationError:
            app_accepts = False
        self._sign_and_send_vote(
            SignedMsgType.PREVOTE,
            self.proposal_block_id if app_accepts else BlockID(),
        )

    def enter_prevote_wait(self, h: int, r: int) -> None:
        if h != self.height or r < self.round or (
            r == self.round and self.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        self._update_step(r, RoundStep.PREVOTE_WAIT)
        self.ticker.schedule(
            TimeoutInfo(self.timeouts.prevote_timeout(r), h, r,
                        int(RoundStep.PREVOTE_WAIT))
        )

    def enter_precommit(self, h: int, r: int) -> None:
        if h != self.height or r < self.round or (
            r == self.round and self.step >= RoundStep.PRECOMMIT
        ):
            return
        self._update_step(r, RoundStep.PRECOMMIT)
        prevotes = self.votes.prevotes(r)
        maj, ok = prevotes.two_thirds_majority()
        if not ok:
            self._sign_and_send_vote(SignedMsgType.PRECOMMIT, BlockID())
            return
        if maj.is_zero():
            if self.locked_block is not None:
                self.locked_round = -1
                self.locked_block = None
                self.locked_block_id = None
            self._sign_and_send_vote(SignedMsgType.PRECOMMIT, BlockID())
            return
        if self.locked_block_id == maj:
            self.locked_round = r  # relock
            self._sign_and_send_vote(SignedMsgType.PRECOMMIT, maj)
            return
        if self.proposal_block_id == maj and self._proposal_complete():
            try:
                validate_block(
                    self.sm_state, self.proposal_block,
                    backend=self.executor.backend,
                )
            except BlockValidationError as e:
                raise RuntimeError(f"+2/3 prevoted an invalid block: {e}") from e
            self.locked_round = r
            self.locked_block = self.proposal_block
            self.locked_block_id = maj
            self._sign_and_send_vote(SignedMsgType.PRECOMMIT, maj)
            return
        # +2/3 for a block we don't have: precommit nil, mark valid
        self.valid_round = r
        self.valid_block = None
        self.valid_block_id = maj
        self._sign_and_send_vote(SignedMsgType.PRECOMMIT, BlockID())

    def enter_precommit_wait(self, h: int, r: int) -> None:
        # Reference enterPrecommitWait: does NOT change the step; a
        # triggered flag prevents each extra precommit from restarting the
        # timer (TriggeredTimeoutPrecommit, reference state.go:1614).
        if h != self.height or r != self.round or self.triggered_timeout_precommit:
            return
        self.triggered_timeout_precommit = True
        self.ticker.schedule(
            TimeoutInfo(self.timeouts.precommit_timeout(r), h, r,
                        int(RoundStep.PRECOMMIT_WAIT))
        )

    def enter_commit(self, h: int, r: int) -> None:
        if h != self.height or self.step == RoundStep.COMMIT:
            return
        self._update_step(self.round, RoundStep.COMMIT)
        self.commit_round = r
        maj, ok = self.votes.precommits(r).two_thirds_majority()
        assert ok and not maj.is_zero()
        if self.locked_block_id == maj:
            self.proposal_block = self.locked_block
            self.proposal_block_id = self.locked_block_id
        elif self.proposal_block_id != maj:
            # clear a mismatched proposal block so the committed one can
            # arrive via gossip (reference enterCommit sets ProposalBlock
            # to nil + fresh parts for the committed BlockID)
            self.proposal_block = None
            self.proposal_block_id = None
        if (_txlife.enabled and self.proposal_block is not None
                and self.proposal_block_id == maj):
            _txlife.stage_block(
                self._lifecycle_pairs(self.proposal_block, maj),
                "precommit_quorum", height=h, round=r)
        self._try_finalize_commit(h)

    def _try_finalize_commit(self, h: int) -> None:
        if self.commit_round < 0:
            return
        maj, ok = self.votes.precommits(self.commit_round).two_thirds_majority()
        if not ok or maj.is_zero():
            return
        if self.proposal_block_id != maj or self.proposal_block is None:
            return  # waiting for the block to arrive
        if _txlife.enabled:
            # block may have arrived after enter_commit (late gossip):
            # first-wins dedupes with the enter_commit stamp
            _txlife.stage_block(
                self._lifecycle_pairs(self.proposal_block, maj),
                "precommit_quorum", height=h, round=self.commit_round)
        self._finalize_commit(h, maj)

    def _finalize_commit(self, h: int, maj: BlockID) -> None:
        # reference finalizeCommit (state.go:1740)
        block = self.proposal_block
        precommits = self.votes.precommits(self.commit_round)
        seen_commit = precommits.make_commit()
        if self.block_store is not None:
            store_seen = seen_commit
            full_seen = None
            if self.cert_native:
                # persist the certificate as the canonical seen commit;
                # the full column rides along so the store can keep it
                # in its recent evidence window (ISSUE 17)
                from ..types.agg_commit import fold_commit

                store_seen = fold_commit(seen_commit, self.validators)
                if store_seen is not seen_commit:
                    full_seen = seen_commit
            self.block_store.save_block(
                block, store_seen, full_seen_commit=full_seen
            )
            if self.extensions_enabled(h):
                self.block_store.save_extended_commit(
                    precommits.make_extended_commit()
                )
        self.wal.write_end_height(h)
        new_state = self.executor.apply_block(
            self.sm_state, maj, block,
        )
        self.decided[h] = maj
        self._log.info(
            "finalized block", height=h, round=self.commit_round,
            txs=len(block.data.txs), hash=block.hash().hex()[:16],
        )
        if trace.enabled:
            trace.event(
                "consensus.finalize_commit", height=h,
                round=self.commit_round, txs=len(block.data.txs),
            )
        m = consensus_metrics()
        m.height.set(h)
        m.validators.set(len(self.validators))
        m.num_txs.set(len(block.data.txs))
        m.total_txs.inc(len(block.data.txs))
        m.block_size_bytes.set(
            len(block.__dict__.get("_enc_memo") or block.encode())
        )
        m.missing_validators.set(
            sum(1 for cs in seen_commit.signatures if cs.is_absent())
        )
        now = _time.monotonic()
        if self._last_commit_mono is not None:
            m.block_interval_seconds.observe(now - self._last_commit_mono)
        self._last_commit_mono = now
        self._update_to_state(new_state, precommits)

    def _update_to_state(self, new_state, last_precommits: VoteSet) -> None:
        self.sm_state = new_state
        # close the COMMIT step BEFORE bumping the height: the span must
        # be stamped with the height that was committed, not the next
        # one (the flight recorder's per-height reconstruction keys
        # every step span on its height)
        self._update_step(0, RoundStep.NEW_HEIGHT)
        self.height = new_state.last_block_height + 1
        self.validators = new_state.validators.copy()
        self.round = 0
        self.proposal = None
        self.proposal_block = None
        self.proposal_block_id = None
        self.locked_round = -1
        self.locked_block = None
        self.locked_block_id = None
        self.valid_round = -1
        self.valid_block = None
        self.valid_block_id = None
        self.commit_round = -1
        self.last_commit = last_precommits
        self.triggered_timeout_precommit = False
        self.votes = HeightVoteSet(self.chain_id, self.height, self.validators)
        self.ticker.schedule(
            TimeoutInfo(self.timeouts.commit, self.height, 0,
                        int(RoundStep.NEW_HEIGHT))
        )
        self._maybe_speculate()

    # ==================================================================
    # voting
    # ==================================================================
    def _sign_and_send_vote(self, vtype: SignedMsgType, block_id: BlockID) -> None:
        if self.privval is None:
            return
        idx, val = self.validators.get_by_address(self.privval.address())
        if val is None:
            return
        bid = block_id or BlockID()
        ts = Timestamp.from_unix_ns(self.now_ns())
        if (
            self.cert_native
            and vtype == SignedMsgType.PRECOMMIT
            and not bid.is_zero()
            and self.proposal is not None
            and self.proposal.round == self.round
            and self.validators.all_bls()
        ):
            # PBTS-style uniform precommit timestamp (ISSUE 17): every
            # correct validator precommitting this proposal signs the
            # proposer's timestamp, so the +2/3 commit folds into one
            # BLS certificate. A validator missing the proposal signs
            # its own time; the fold then falls back to the full column.
            ts = self.proposal.timestamp
        vote = Vote(
            type=vtype,
            height=self.height,
            round=self.round,
            block_id=bid,
            timestamp=ts,
            validator_address=val.address,
            validator_index=idx,
        )
        extend = (
            vtype == SignedMsgType.PRECOMMIT
            and not vote.is_nil()
            and self.extensions_enabled(self.height)
        )
        if extend:
            # app-supplied extension rides the precommit
            # (reference state.go signVote -> ExtendVote)
            vote.extension = self.executor.app.consensus.extend_vote(
                self.height, self.round, vote.block_id.hash
            )
        self.privval.sign_vote(self.chain_id, vote, sign_extension=extend)
        if not self._replay_mode:
            self.broadcast(VoteMessage(vote))
            # byzantine injection seam (privval/byzantine.py): a
            # double-signing privval hands back a second, conflicting
            # signed vote for the same HRS. It goes to PEERS ONLY —
            # never into our own vote set — so the equivocation is
            # observable on the wire exactly like a remote adversary's.
            equivocate = getattr(self.privval, "equivocate", None)
            if equivocate is not None:
                shadow = equivocate(self.chain_id, vote)
                if shadow is not None:
                    self.broadcast(VoteMessage(shadow, direct=True))
        self.send(VoteMessage(vote), "")

    def _trace_conflicting_votes(self, e) -> None:
        """Surface an equivocation pair on the trace sink: p2p vote
        records carry no signatures, so this is the only place the
        watchtower can recover both SIGNED votes to build
        DuplicateVoteEvidence from."""
        if not trace.enabled:
            return
        try:
            a, b = e.vote_a, e.vote_b
            trace.event(
                "consensus.conflicting_vote",
                height=a.height, round=a.round, type=int(a.type),
                val=a.validator_address.hex(),
                vote_a=a.encode().hex(), vote_b=b.encode().hex(),
            )
        except Exception:  # noqa: BLE001 — tracing must not stall consensus
            pass

    # ==================================================================
    # WAL crash recovery
    # ==================================================================
    def catchup_replay(self) -> None:
        """Re-handle messages logged after the last #ENDHEIGHT
        (reference internal/consensus/replay.go:94)."""
        msgs = self.wal.search_for_end_height(self.height - 1)
        if msgs is None:
            if self.height - 1 > 0:
                return  # fresh WAL beyond genesis: nothing to replay
            msgs = []
        self._replay_mode = True
        try:
            for tm in msgs:
                m = tm.msg
                if isinstance(m, MsgInfo):
                    try:
                        self._handle_msg(m.msg, m.peer_id)
                    except Exception:
                        pass  # tolerate stale/duplicate replay artifacts
                elif isinstance(m, TimeoutMessage):
                    try:
                        self._handle_timeout(
                            TimeoutInfo(0.0, m.height, m.round, m.step)
                        )
                    except Exception:
                        pass
        finally:
            self._replay_mode = False

    # ==================================================================
    # test helpers
    # ==================================================================
    def wait_for_height(self, h: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._step_cv:
            while self.height < h:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped.is_set():
                    return self.height >= h
                self._step_cv.wait(remaining)
        return True


def ti_height(ti: TimeoutInfo) -> int:
    return ti.height


def _wal_payload(msg):
    if isinstance(msg, VoteMessage):
        return msg.vote
    if isinstance(msg, ProposalMessage):
        return msg.proposal
    return msg
