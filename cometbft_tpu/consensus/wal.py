"""Write-ahead log for the consensus state machine.

Behavior parity: reference internal/consensus/wal.go (BaseWAL :57-68) +
internal/autofile/group.go —
- every record is CRC32-framed: crc(4, big) | length(4, big) | payload
  (reference internal/consensus/wal.go WALEncoder).
- records are TimedWALMessage{time, msg}; the msg union covers EndHeight
  markers, received consensus messages, and timeout firings — everything
  the receive loop processes, written BEFORE processing.
- `write_sync` fsyncs (own messages must hit disk before they hit the
  wire, reference state.go:830); `write` is buffered.
- log files rotate at max_file_bytes (autofile.Group's size rotation);
  `search_for_end_height` scans newest-to-oldest like the reference.

Encodings use the project's proto helpers; payloads embed the existing
wire encodings of Vote/Proposal, so a WAL survives process restarts and
code reloads (no pickling).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass

from ..encoding import proto as pb
from ..types import Proposal, Vote

MAX_MSG_BYTES = 2 * 1024 * 1024


@dataclass
class EndHeightMessage:
    """#ENDHEIGHT marker: height H fully committed (reference wal.go:38)."""

    height: int


@dataclass
class MsgInfo:
    """A consensus message from a peer ("" = self) entering the loop."""

    msg: object  # Vote | Proposal | full-block bytes wrapper
    peer_id: str = ""


@dataclass
class BlockBytesMessage:
    """Proposal block payload (full-block gossip seam; parts later)."""

    height: int
    round: int
    block_bytes: bytes


@dataclass
class AggregateCommitMessage:
    """Certificate-native catchup gossip (ISSUE 17): one verified +2/3
    aggregate-precommit certificate replacing N vote frames. Defined
    here beside BlockBytesMessage so the WAL can frame it without
    importing the state machine."""

    cert: object  # types.agg_commit.AggregateCommit


@dataclass
class TimeoutMessage:
    height: int
    round: int
    step: int
    duration_ms: int = 0


@dataclass
class TimedWALMessage:
    time_ns: int
    msg: object


def _encode_msg(m) -> bytes:
    if isinstance(m, EndHeightMessage):
        return pb.f_embedded(2, pb.f_varint(1, m.height, emit_zero=True))
    if isinstance(m, MsgInfo):
        inner = m.msg
        if isinstance(inner, Vote):
            body = pb.f_embedded(1, inner.encode())
        elif isinstance(inner, Proposal):
            body = pb.f_embedded(2, inner.encode())
        elif isinstance(inner, BlockBytesMessage):
            body = pb.f_embedded(
                3,
                pb.f_varint(1, inner.height)
                + pb.f_varint(2, inner.round)
                + pb.f_bytes(3, inner.block_bytes),
            )
        elif isinstance(inner, AggregateCommitMessage):
            body = pb.f_embedded(4, inner.cert.encode())
        else:
            raise TypeError(f"unsupported WAL MsgInfo payload {type(inner)}")
        return pb.f_embedded(3, body + pb.f_string(15, m.peer_id))
    if isinstance(m, TimeoutMessage):
        return pb.f_embedded(
            4,
            pb.f_varint(1, m.height)
            + pb.f_varint(2, m.round)
            + pb.f_varint(3, m.step)
            + pb.f_varint(4, m.duration_ms),
        )
    raise TypeError(f"unsupported WAL message {type(m)}")


def _decode_timed(payload: bytes) -> TimedWALMessage:
    t, msg = 0, None
    for fnum, _, v in pb.parse_fields(payload):
        if fnum == 1:
            t = pb.to_i64(v)
        else:
            msg = _decode_msg_field(fnum, pb.as_bytes(v))
    if msg is None:
        raise ValueError("WAL record without message")
    return TimedWALMessage(t, msg)


def _decode_msg_field(fnum: int, v: bytes):
    if fnum == 2:
        return EndHeightMessage(pb.to_i64(pb.fields_to_dict(v).get(1, 0)))
    if fnum == 3:
        d = pb.fields_to_dict(v)
        peer = pb.as_bytes(d.get(15, b"")).decode()
        if 1 in d:
            return MsgInfo(Vote.decode(pb.as_bytes(d[1])), peer)
        if 2 in d:
            return MsgInfo(Proposal.decode(pb.as_bytes(d[2])), peer)
        if 3 in d:
            bd = pb.fields_to_dict(pb.as_bytes(d[3]))
            return MsgInfo(
                BlockBytesMessage(
                    pb.to_i64(bd.get(1, 0)),
                    pb.to_i64(bd.get(2, 0)),
                    pb.as_bytes(bd.get(3, b"")),
                ),
                peer,
            )
        if 4 in d:
            from ..types.agg_commit import AggregateCommit

            return MsgInfo(
                AggregateCommitMessage(AggregateCommit.decode(pb.as_bytes(d[4]))),
                peer,
            )
        raise ValueError("unknown MsgInfo payload")
    if fnum == 4:
        d = pb.fields_to_dict(v)
        return TimeoutMessage(
            pb.to_i64(d.get(1, 0)), pb.to_i64(d.get(2, 0)),
            pb.to_i64(d.get(3, 0)), pb.to_i64(d.get(4, 0)),
        )
    raise ValueError(f"unknown WAL message tag {fnum}")


def _encode_timed(tm: TimedWALMessage) -> bytes:
    payload = pb.f_varint(1, tm.time_ns) + _encode_msg(tm.msg)
    crc = zlib.crc32(payload)
    return struct.pack(">II", crc, len(payload)) + payload


class WALCorruptionError(Exception):
    pass


class WAL:
    """Rolling-file CRC-framed WAL."""

    def __init__(self, path: str, max_file_bytes: int = 16 * 1024 * 1024):
        self.path = path
        self.max_file_bytes = max_file_bytes
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self._head_path(), "ab")

    # -- file layout: path.000, path.001, ... plus head at `path` ---------
    def _head_path(self) -> str:
        return self.path

    def _rolled_paths(self) -> list[str]:
        d = os.path.dirname(self.path) or "."
        base = os.path.basename(self.path)
        out = []
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    out.append(os.path.join(d, name))
        return sorted(out)

    def _maybe_rotate_locked(self):
        if self._f.tell() < self.max_file_bytes:
            return
        self._f.close()
        rolled = self._rolled_paths()
        nxt = (
            int(os.path.basename(rolled[-1]).rsplit(".", 1)[1]) + 1 if rolled else 0
        )
        os.replace(self._head_path(), f"{self.path}.{nxt:03d}")
        self._f = open(self._head_path(), "ab")

    # ------------------------------------------------------------------
    def write(self, msg) -> None:
        tm = TimedWALMessage(time.time_ns(), msg)
        with self._lock:
            self._f.write(_encode_timed(tm))

    def write_sync(self, msg) -> None:
        tm = TimedWALMessage(time.time_ns(), msg)
        with self._lock:
            self._f.write(_encode_timed(tm))
            self._f.flush()
            os.fsync(self._f.fileno())
            self._maybe_rotate_locked()

    def flush(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            self._f.close()

    def write_end_height(self, height: int) -> None:
        self.write_sync(EndHeightMessage(height))

    # ------------------------------------------------------------------
    @staticmethod
    def _read_file(path: str, strict: bool = True):
        out = []
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos < len(data):
            if pos + 8 > len(data):
                break  # torn tail write: tolerated (crash mid-write)
            crc, ln = struct.unpack_from(">II", data, pos)
            if ln > MAX_MSG_BYTES:
                raise WALCorruptionError(f"record length {ln} too large")
            payload = data[pos + 8: pos + 8 + ln]
            if len(payload) < ln:
                break  # torn tail
            if zlib.crc32(payload) != crc:
                if strict:
                    raise WALCorruptionError(f"crc mismatch at offset {pos}")
                break
            out.append(_decode_timed(payload))
            pos += 8 + ln
        return out

    def read_all(self):
        self.flush()
        msgs = []
        for p in self._rolled_paths() + [self._head_path()]:
            if os.path.exists(p):
                msgs.extend(self._read_file(p))
        return msgs

    def search_for_end_height(self, height: int):
        """Messages logged AFTER EndHeight(height); None if marker absent
        (reference wal.go SearchForEndHeight)."""
        msgs = self.read_all()
        for i in range(len(msgs) - 1, -1, -1):
            m = msgs[i].msg
            if isinstance(m, EndHeightMessage) and m.height == height:
                return msgs[i + 1:]
        return None
