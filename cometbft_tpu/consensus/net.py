"""In-process consensus networks over a loopback transport.

The reference tests whole consensus networks inside one process with
switches over in-memory connections (reference p2p/test_util.go:348
MakeConnectedSwitches, internal/consensus/common_test.go); this module is
that harness for our stack: N ConsensusStates wired broadcast-to-all, each
with its own KVStore app, stores, WAL, and FilePV.
"""

from __future__ import annotations

import os

from ..abci.client import AppConns
from ..abci.kvstore import KVStoreApp
from ..evidence import EvidencePool
from ..mempool import CListMempool
from ..privval import FilePV
from ..state.execution import BlockExecutor, make_genesis_state
from ..storage import BlockStore, MemKV, StateStore
from ..types import Validator, ValidatorSet
from .state import ConsensusState, TimeoutConfig
from .wal import WAL


FAST_TIMEOUTS = TimeoutConfig(
    propose=0.6, propose_delta=0.2,
    prevote=0.3, prevote_delta=0.1,
    precommit=0.3, precommit_delta=0.1,
    commit=0.05,
)


class InProcessNode:
    def __init__(self, idx, pv, chain_id, genesis, wal_path, net, timeouts,
                 tx_source=None, app_factory=None):
        self.idx = idx
        self.pv = pv
        self.net = net
        self.app = app_factory() if app_factory is not None else KVStoreApp()
        self.block_store = BlockStore(MemKV())
        self.state_store = StateStore(MemKV())
        conns = AppConns(self.app)
        self.mempool = CListMempool(conns)
        self.evidence_pool = EvidencePool(
            state_store=self.state_store, block_store=self.block_store,
            chain_id=chain_id,
        )
        self.executor = BlockExecutor(
            conns, state_store=self.state_store,
            block_store=self.block_store, backend="cpu",
            mempool=self.mempool, evidence_pool=self.evidence_pool,
        )
        self.wal = WAL(wal_path)
        self.cs = ConsensusState(
            chain_id=chain_id,
            sm_state=genesis.copy(),
            executor=self.executor,
            block_store=self.block_store,
            privval=pv,
            wal=self.wal,
            broadcast=lambda msg, _i=idx: net.broadcast(_i, msg),
            timeouts=timeouts,
            tx_source=tx_source or self._reap_txs,
            name=f"node{idx}",
            # same wiring as node.py: speculative round-0 proposals with
            # the mempool version as the staleness probe (ISSUE 11)
            speculative=True,
            mempool_version=lambda: self.mempool.version,
        )

    def _reap_txs(self):
        # columnar reap, as in production (node/node.py tx_source)
        return self.mempool.reap_columns(max_bytes=1 << 20)


class InProcessNetwork:
    """N validators, full-mesh instant delivery (loopback)."""

    def __init__(self, n: int, tmpdir: str, chain_id: str = "loop-chain",
                 timeouts: TimeoutConfig = FAST_TIMEOUTS, power: int = 10,
                 consensus_params=None, app_factory=None,
                 key_type: str = "tendermint/PubKeyEd25519"):
        self.chain_id = chain_id
        self.app_factory = app_factory
        self.pvs = [
            FilePV.generate(
                os.path.join(tmpdir, f"pv{i}.key.json"),
                os.path.join(tmpdir, f"pv{i}.state.json"),
                key_type=key_type,
            )
            for i in range(n)
        ]
        vals = ValidatorSet(
            [Validator.from_pub_key(pv.pub_key(), power) for pv in self.pvs]
        )
        self.genesis = make_genesis_state(chain_id, vals)
        if consensus_params is not None:
            from dataclasses import replace as _replace

            self.genesis = _replace(
                self.genesis, consensus_params=consensus_params
            )
        self.nodes = [
            InProcessNode(
                i, self.pvs[i], chain_id, self.genesis,
                os.path.join(tmpdir, f"wal{i}"), self, timeouts,
                app_factory=app_factory,
            )
            for i in range(n)
        ]
        self._partitioned: set[int] = set()
        for node in self.nodes:
            node.mempool.on_new_tx.append(
                lambda tx, _i=node.idx: self.gossip_tx(_i, tx)
            )

    def gossip_tx(self, from_idx: int, tx: bytes) -> None:
        """Mempool gossip seam (reference mempool/reactor.go)."""
        if from_idx in self._partitioned:
            return
        for node in self.nodes:
            if node.idx == from_idx or node.idx in self._partitioned:
                continue
            try:
                node.mempool.check_tx(tx, from_peer=f"node{from_idx}")
            except Exception:
                pass  # dup / full / rejected: drop like the reference

    def broadcast(self, from_idx: int, msg) -> None:
        if from_idx in self._partitioned:
            return
        for node in self.nodes:
            if node.idx != from_idx and node.idx not in self._partitioned:
                node.cs.send(msg, peer_id=f"node{from_idx}")

    def partition(self, idx: int) -> None:
        """Cut a node off (both directions)."""
        self._partitioned.add(idx)

    def heal(self, idx: int) -> None:
        self._partitioned.discard(idx)

    def start(self) -> None:
        for node in self.nodes:
            node.cs.start(replay_wal=False)

    def stop(self) -> None:
        for node in self.nodes:
            node.cs.stop()

    def wait_for_height(self, h: int, timeout: float = 60.0) -> bool:
        return all(
            n.cs.wait_for_height(h, timeout) for n in self.nodes
            if n.idx not in self._partitioned
        )
