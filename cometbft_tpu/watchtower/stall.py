"""Online port of traceview's stall classifier (utils/traceview.py
stall_report): the same triage — slack-scaled advance gap, walk the
pipeline message classes at the stuck height, name the first class
with zero receipts and the peers that stayed silent — but fed
incrementally from streaming trace records instead of a post-mortem
merge, so the ~1/15 rejoin stall names its node while it is happening.

State per node is bounded: receive counters are kept only for heights
at or above the node's last committed height (older heights can no
longer be the stuck one), so a long-running audit does not accumulate
the whole world's records the way the post-mortem merger does.

Clock handling: records keep their producer timestamps and "now" is
the maximum timestamp seen across all nodes, so the classifier never
outruns the sinks it reads (a slow poll loop cannot fabricate a
stall). Cross-node clock skew below the slack floor (2 s live, 3 s
advance — both scale up with world span exactly like traceview's) is
absorbed; the post-mortem path remains the tool for worlds with worse
clocks.
"""

from __future__ import annotations

from collections import Counter

PIPELINE_ORDER = ("proposal", "block_part", "prevote", "precommit")

LIVE_SLACK_S = 2.0
ADVANCE_SLACK_S = 3.0


class _NodeState:
    __slots__ = ("name", "first_t", "last_t", "advance_t", "committed",
                 "cur_height", "cur_height_t", "round_by_height",
                 "recv_counts", "precommit_peers", "peers_seen", "records")

    def __init__(self, name: str):
        self.name = name
        self.first_t = None
        self.last_t = None
        self.advance_t = None
        self.committed = 0
        self.cur_height = None
        self.cur_height_t = None
        self.round_by_height: dict[int, int] = {}
        # (height, class) -> receipts; (height, peer) -> precommit votes
        self.recv_counts: Counter = Counter()
        self.precommit_peers: Counter = Counter()
        self.peers_seen: set = set()
        self.records = 0

    def _prune(self) -> None:
        floor = self.committed
        if floor <= 0:
            return
        for key in [k for k in self.recv_counts if k[0] < floor]:
            del self.recv_counts[key]
        for key in [k for k in self.precommit_peers if k[0] < floor]:
            del self.precommit_peers[key]
        for h in [h for h in self.round_by_height if h < floor]:
            del self.round_by_height[h]


class OnlineStallClassifier:
    """Ingest trace records per node; classify() at any point."""

    def __init__(self, live_slack_s: float = LIVE_SLACK_S,
                 advance_slack_s: float = ADVANCE_SLACK_S):
        self.live_slack_floor = live_slack_s
        self.advance_slack_floor = advance_slack_s
        self.nodes: dict[str, _NodeState] = {}
        # p2p node id -> friendly name, learned from the records' own
        # `node` stamp (every tailed sink names itself), so silent-peer
        # lists read "node2", not a 40-hex id
        self.peer_names: dict[str, str] = {}
        self._t_min = None
        self._t_max = None

    # -- ingestion -------------------------------------------------------
    def ingest(self, node: str, rec: dict) -> None:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            return
        st = self.nodes.get(node)
        if st is None:
            st = self.nodes[node] = _NodeState(node)
        nid = rec.get("node")
        if nid and nid not in self.peer_names:
            self.peer_names[nid] = node
        st.records += 1
        if st.first_t is None or ts < st.first_t:
            st.first_t = ts
        if st.last_t is None or ts > st.last_t:
            st.last_t = ts
        if self._t_min is None or ts < self._t_min:
            self._t_min = ts
        if self._t_max is None or ts > self._t_max:
            self._t_max = ts

        name = rec.get("name")
        if name in ("consensus.finalize_commit", "blocksync.block"):
            h = rec.get("height")
            if isinstance(h, int) and h > st.committed:
                st.committed = h
                st.advance_t = ts
                st._prune()
        elif name == "consensus.step":
            h = rec.get("height")
            if isinstance(h, int):
                if st.cur_height_t is None or ts >= st.cur_height_t:
                    st.cur_height = h
                    st.cur_height_t = ts
                rd = rec.get("round")
                if isinstance(rd, int):
                    prev = st.round_by_height.get(h, 0)
                    if rd > prev:
                        st.round_by_height[h] = rd
        elif name == "p2p.recv":
            st.peers_seen.add(rec.get("peer"))
            h = rec.get("height")
            if isinstance(h, int) and h >= st.committed:
                msg = rec.get("msg")
                cls = rec.get("type") if msg == "vote" else msg
                if cls in PIPELINE_ORDER:
                    st.recv_counts[(h, cls)] += 1
                    if cls == "precommit":
                        st.precommit_peers[(h, rec.get("peer"))] += 1

    # -- classification --------------------------------------------------
    def classify(self) -> dict:
        """Same report shape as traceview.stall_report, computed from
        the incremental state."""
        if not self.nodes or self._t_max is None:
            return {"status": "empty", "tip": None, "nodes": {},
                    "stalled": []}
        world_start = self._t_min
        world_end = self._t_max
        span = max(0.0, world_end - world_start)
        live_slack = max(self.live_slack_floor, 0.1 * span)
        advance_slack = max(self.advance_slack_floor, 0.2 * span)

        tip = max(st.committed for st in self.nodes.values())
        nodes_out: dict[str, dict] = {}
        stalled = []
        for st in self.nodes.values():
            cur_height = st.cur_height
            if cur_height is None:
                cur_height = st.committed + 1 if st.committed else None
            max_round = st.round_by_height.get(cur_height, 0) \
                if cur_height is not None else 0
            live = (world_end - st.last_t) <= live_slack
            gap = world_end - (st.advance_t if st.advance_t is not None
                               else world_start)
            info = {
                "committed": st.committed, "height": cur_height,
                "max_round": max_round, "live": live,
                "records": st.records,
            }
            nodes_out[st.name] = info
            lagging = tip - st.committed >= 2
            churning = max_round >= 2
            if not (live and gap > advance_slack and (lagging or churning)):
                continue
            h = cur_height
            recv_counts = {c: st.recv_counts.get((h, c), 0)
                           for c in PIPELINE_ORDER}
            missing = [c for c in PIPELINE_ORDER if recv_counts[c] == 0]
            first_missing = missing[0] if missing else None
            silent_peers = sorted(
                self.peer_names.get(p, str(p)) for p in st.peers_seen
                if p is not None and st.precommit_peers.get((h, p), 0) == 0)
            if tip > (st.committed or 0) and recv_counts["precommit"] == 0:
                # catchup special case (traceview stall_report:474):
                # peers are past this height, so finishing it needs the
                # stored commit's precommits — and none arrived
                if "precommit" in missing:
                    first_missing = "precommit"
                detail = (
                    f"peers are at height {tip} but no catchup precommit "
                    f"votes for height {h} ever arrived"
                    + (f"; connected peers never gossiping them: "
                       f"{', '.join(silent_peers)}" if silent_peers else "")
                )
            elif first_missing is not None:
                detail = (f"no {first_missing} received at height {h} "
                          f"(rounds reached {max_round})")
            else:
                detail = (f"all message classes seen at height {h} yet no "
                          f"commit; rounds reached {max_round}")
            stalled.append({
                "node": st.name, "height": h, "committed": st.committed,
                "max_round": max_round, "first_missing": first_missing,
                "missing": missing, "recv_counts": recv_counts,
                "silent_peers": silent_peers,
                "stalled_for_s": round(gap, 3), "detail": detail,
            })
        return {
            "status": "stall" if stalled else "ok",
            "tip": tip or None,
            "span_s": round(span, 3),
            "nodes": nodes_out,
            "stalled": stalled,
        }
