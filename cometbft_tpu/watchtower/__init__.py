"""Streaming safety auditor + byzantine accountability plane.

A watchtower is the external-auditor role the replication feed makes
cheap: a stateless process tailing N core nodes' feeds (and optional
trace sinks) that continuously re-checks what the chain claims —
conflicting commits, equivocation, certificate validity, data
availability, and live stalls — and emits structured verdicts instead
of waiting for a post-mortem.
"""

from .auditor import Watchtower
from .checks import (
    build_duplicate_vote_evidence,
    column_votes,
    commit_signers,
    fork_culprits,
)
from .stall import OnlineStallClassifier

__all__ = [
    "Watchtower",
    "OnlineStallClassifier",
    "commit_signers",
    "fork_culprits",
    "column_votes",
    "build_duplicate_vote_evidence",
]
