"""The watchtower auditor: stateless online safety + liveness checks.

Shaped like a serving replica (replication/replica.py) but holding no
serving state at all: one feed-tail thread per watched core node folds
frames into a bounded per-node window, every ingested height is
audited against the OTHER nodes' windows (fork detection, cross-feed
equivocation) and against itself (certificate consistency), a
background sampler fleet probes data availability over `da_sample`,
and an online stall classifier runs over the nodes' streaming trace
sinks. Findings become structured verdicts:

- `trace.event("watchtower.verdict", ...)` + optional JSONL file
- `watchtower_*` metrics (checks_total{check,outcome}, a latching
  alarm gauge per check, per-node feed lag, audit latency)
- in-memory `verdicts` / `safety_verdicts()` — what the e2e runner
  fails an audited world on.

Check taxonomy (the `check` label everywhere):

==============  ======  ==============================================
check           safety  trigger
==============  ======  ==============================================
fork            yes     conflicting commits at one height across
                        feeds; culprits = signer-set intersection
equivocation    yes     DuplicateVoteEvidence built from conflicting-
                        vote trace records or cross-feed commit
                        columns, verified, and submitted back to every
                        watched node over broadcast_evidence
cert            yes     a frame's BLS certificate fails re-derivation
                        against the valset, or disagrees with the
                        retained signature column in the window
da              no      sampling confidence stalled / withheld chunks
                        for `da_alarm_after` consecutive sweeps
stall           no      live node not finalizing (online traceview
                        triage: first missing class + silent peers)
==============  ======  ==============================================

Every decoded object is verified before it can raise a safety verdict
— an unverifiable candidate is dropped, not reported — which is what
keeps the clean-world false-positive rate at zero by construction.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict

from ..crypto import merkle
from ..da.commit import DACommitment
from ..da.sampler import Sampler
from ..light.store import _decode_vals
from ..rpc.client import HTTPClient
from ..types import Header
from ..types.agg_commit import (
    AggCommitError,
    AggregateCommit,
    CertCommit,
    decode_commit_any,
)
from ..types.block import Commit
from ..utils import trace
from ..utils.metrics import watchtower_metrics
from ..utils.trace import TailReader
from . import checks
from .stall import OnlineStallClassifier

SAFETY_CHECKS = ("fork", "equivocation", "cert")


class _Frame:
    """One decoded feed frame: everything the checks need, nothing the
    serving plane would (no payloads, no MMR)."""

    __slots__ = ("height", "header", "last", "seen", "vals",
                 "cert_kind", "cert", "da_root", "da_k", "da_m")

    def __init__(self, height):
        self.height = height
        self.header = None
        self.last = None
        self.seen = None
        self.vals = None
        self.cert_kind = "none"
        self.cert = None  # AggregateCommit when the frame carried one
        self.da_root = None
        self.da_k = 0
        self.da_m = 0


class _WatchedNode:
    def __init__(self, name: str, url: str, retain: int):
        self.name = name
        self.url = url
        self.retain = max(2, int(retain))
        self.frames: OrderedDict[int, _Frame] = OrderedDict()
        self.tip = 0  # feed control-record tip
        self.cursor = 0  # highest ingested frame height
        self.feed_connects = 0
        self.lock = threading.Lock()

    def put(self, frame: _Frame) -> None:
        with self.lock:
            self.frames[frame.height] = frame
            while len(self.frames) > self.retain:
                self.frames.popitem(last=False)
            if frame.height > self.cursor:
                self.cursor = frame.height
            if frame.height > self.tip:
                self.tip = frame.height

    def get(self, height: int) -> _Frame | None:
        with self.lock:
            return self.frames.get(height)


class Watchtower:
    """Audit N core nodes' replication feeds + trace sinks online.

    `nodes` maps node name -> RPC base url (http://host:port);
    `trace_sinks` maps node name -> JSONL sink path (optional — without
    it the stall and trace-equivocation checks idle). All checks can
    also be driven synchronously through `ingest_frame` /
    `handle_trace_record` / `da_sweep`, which is how the adversarial
    fixtures pin them without a network.
    """

    def __init__(self, nodes: dict[str, str], *,
                 chain_id: str = "",
                 trace_sinks: dict[str, str] | None = None,
                 full_commit_window: int = 16,
                 da_interval_s: float = 2.0,
                 da_samples: int = 4,
                 da_alarm_after: int = 2,
                 stall_interval_s: float = 1.0,
                 verdict_path: str = "",
                 feed_timeout_s: float = 5.0,
                 retain: int = 512,
                 submit_evidence: bool = True,
                 client_factory=None):
        self.chain_id = chain_id
        self.full_commit_window = int(full_commit_window)
        self.da_interval_s = float(da_interval_s)
        self.da_samples = int(da_samples)
        self.da_alarm_after = int(da_alarm_after)
        self.stall_interval_s = float(stall_interval_s)
        self.verdict_path = verdict_path
        self.feed_timeout_s = float(feed_timeout_s)
        self.submit_evidence = submit_evidence
        self._client_factory = client_factory or HTTPClient

        self.nodes: dict[str, _WatchedNode] = {
            name: _WatchedNode(name, url, retain)
            for name, url in nodes.items()
        }
        self.trace_sinks = dict(trace_sinks or {})
        self.stall = OnlineStallClassifier()

        self.verdicts: list[dict] = []
        self._verdict_keys: set = set()
        self._verdict_lock = threading.Lock()
        self._verdict_fh = None

        self._submitted_evidence: set[bytes] = set()
        self._da_fail_streak: dict[str, int] = {}
        self._da_alarmed: set[str] = set()
        self._stalled_seen: set = set()

        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._resps: list = []

    # ------------------------------------------------------------------
    # verdicts
    # ------------------------------------------------------------------
    def _verdict(self, check: str, key, **fields) -> bool:
        """Record one finding (deduplicated by `key`); returns True when
        it is new. Safety verdicts latch the alarm gauge for the life
        of the auditor — a fork does not un-happen."""
        m = watchtower_metrics()
        with self._verdict_lock:
            if (check, key) in self._verdict_keys:
                return False
            self._verdict_keys.add((check, key))
            rec = {"check": check, "safety": check in SAFETY_CHECKS,
                   "ts": time.time(), **fields}
            self.verdicts.append(rec)
            if self.verdict_path:
                if self._verdict_fh is None:
                    self._verdict_fh = open(self.verdict_path, "a",
                                            encoding="utf-8")
                self._verdict_fh.write(
                    json.dumps(rec, separators=(",", ":"), default=str)
                    + "\n")
                self._verdict_fh.flush()
        m.checks_total.inc(1.0, check, "violation")
        m.alarm.set(1.0, check)
        trace.event("watchtower.verdict", **rec)
        return True

    def _ok(self, check: str) -> None:
        watchtower_metrics().checks_total.inc(1.0, check, "ok")

    def _error(self, check: str) -> None:
        watchtower_metrics().checks_total.inc(1.0, check, "error")

    def safety_verdicts(self) -> list[dict]:
        with self._verdict_lock:
            return [v for v in self.verdicts if v["safety"]]

    def clear_alarm(self, check: str) -> None:
        """Non-safety alarms (da) clear when the condition passes."""
        watchtower_metrics().alarm.set(0.0, check)

    # ------------------------------------------------------------------
    # frame ingestion + per-height audit
    # ------------------------------------------------------------------
    def ingest_frame(self, node_name: str, raw: dict) -> _Frame:
        """Decode one feed frame dict and audit its height."""
        node = self.nodes[node_name]
        f = _Frame(int(raw["h"]))
        t0 = time.perf_counter()
        f.header = Header.decode(bytes.fromhex(raw["hdr"]))
        if not self.chain_id:
            self.chain_id = f.header.chain_id
        if raw.get("vals"):
            f.vals = _decode_vals(bytes.fromhex(raw["vals"]))
        if raw.get("last"):
            f.last = decode_commit_any(bytes.fromhex(raw["last"]))
        if raw.get("seen"):
            f.seen = decode_commit_any(bytes.fromhex(raw["seen"]))
        cert = raw.get("cert") or {}
        f.cert_kind = cert.get("kind", "none")
        if f.cert_kind in ("cert_native", "bls_agg") and cert.get("data"):
            f.cert = AggregateCommit.decode(bytes.fromhex(cert["data"]))
        da = raw.get("da")
        if da is not None:
            f.da_k = int(da.get("k", 0))
            f.da_m = int(da.get("m", 0))
            if da.get("root"):
                f.da_root = bytes.fromhex(da["root"])
        node.put(f)
        with trace.span("watchtower.audit", node=node_name,
                        height=f.height) as sp:
            n_checks = self._audit_height(node, f)
            sp.add(checks=n_checks)
        watchtower_metrics().audit_seconds.observe(
            time.perf_counter() - t0, "frame")
        self._set_lag(node)
        return f

    def _set_lag(self, node: _WatchedNode) -> None:
        lag = float(max(0, node.tip - node.cursor))
        watchtower_metrics().feed_lag_heights.set(lag, node.name)

    def _audit_height(self, node: _WatchedNode, f: _Frame) -> int:
        n = 0
        n += self._check_cert(node, f)
        n += self._check_fork(node, f)
        n += self._check_column_equivocation(node, f)
        return n

    # -- certificate consistency ----------------------------------------
    def _check_cert(self, node: _WatchedNode, f: _Frame) -> int:
        """Re-derive the frame's certificate against the valset, and —
        when the frame also retains the full signature column — against
        the column (the PR-17 full_commit_window seam, audited from
        outside the node)."""
        if f.cert is None:
            return 0
        ran = 0
        vals = f.vals
        try:
            if vals is not None:
                ran += 1
                try:
                    f.cert.verify(self.chain_id, vals)
                    self._ok("cert")
                except AggCommitError as e:
                    self._verdict(
                        "cert", ("verify", node.name, f.height),
                        node=node.name, height=f.height,
                        kind=f.cert_kind, detail=str(e))
            # column cross-check: only meaningful while the store still
            # retains the full column next to the fold (bls_agg frames
            # inside the window); cert-native frames carry no column
            seen = f.seen
            if (isinstance(seen, Commit) and seen.signatures
                    and vals is not None
                    and node.tip - f.height <= self.full_commit_window):
                ran += 1
                probs = checks.cert_commit_matches_column(
                    CertCommit(f.cert, len(vals)), seen, vals)
                if probs:
                    self._verdict(
                        "cert", ("column", node.name, f.height),
                        node=node.name, height=f.height,
                        kind=f.cert_kind, detail="; ".join(probs))
                else:
                    self._ok("cert")
        except Exception as e:  # noqa: BLE001 — audit must not die
            self._error("cert")
            trace.event("watchtower.audit", node=node.name,
                        height=f.height, error=f"cert: {e}")
        return ran

    # -- fork detection ---------------------------------------------------
    def _check_fork(self, node: _WatchedNode, f: _Frame) -> int:
        """Compare this node's commit at `f.height` against every other
        watched node's. Two commits for different block ids at one
        height = fork; the culprits are the validators in BOTH signer
        sets (>= 1/3 by quorum intersection)."""
        mine = f.seen
        if mine is None:
            return 0
        ran = 0
        for other in self.nodes.values():
            if other is node:
                continue
            of = other.get(f.height)
            if of is None or of.seen is None:
                continue
            ran += 1
            try:
                if of.seen.block_id.key() == mine.block_id.key():
                    self._ok("fork")
                    continue
                vals = f.vals or of.vals
                culprits = checks.fork_culprits(mine, of.seen, vals)
                pair = tuple(sorted((node.name, other.name)))
                self._verdict(
                    "fork", (pair, f.height),
                    height=f.height, nodes=list(pair),
                    block_a=mine.block_id.hash.hex(),
                    block_b=of.seen.block_id.hash.hex(),
                    culprits=[a.hex() for a in culprits],
                    detail=(f"conflicting commits at height {f.height}: "
                            f"{len(culprits)} overlapping signer(s)"))
            except Exception as e:  # noqa: BLE001
                self._error("fork")
                trace.event("watchtower.audit", node=node.name,
                            height=f.height, error=f"fork: {e}")
        return ran

    # -- equivocation -----------------------------------------------------
    def _check_column_equivocation(self, node: _WatchedNode,
                                   f: _Frame) -> int:
        """Cross-feed commit-column scan: a validator COMMIT-signing
        different block ids at one height/round across two nodes' seen
        commits is equivocation provable from the columns alone."""
        if not isinstance(f.seen, Commit) or not f.seen.signatures:
            return 0
        vals = f.vals
        if vals is None:
            return 0
        ran = 0
        for other in self.nodes.values():
            if other is node:
                continue
            of = other.get(f.height)
            if of is None or not isinstance(of.seen, Commit):
                continue
            ran += 1
            try:
                evs = checks.cross_column_equivocations(
                    f.seen, of.seen, vals, self.chain_id)
                if not evs:
                    self._ok("equivocation")
                for ev in evs:
                    self._report_equivocation(ev, source="column")
            except Exception as e:  # noqa: BLE001
                self._error("equivocation")
                trace.event("watchtower.audit", node=node.name,
                            height=f.height, error=f"equivocation: {e}")
        return ran

    def handle_trace_record(self, node_name: str, rec: dict) -> None:
        """One streamed trace record: feed the stall classifier, and
        turn `consensus.conflicting_vote` records — the only place both
        SIGNED votes of an equivocation pair surface — into verified
        DuplicateVoteEvidence."""
        self.stall.ingest(node_name, rec)
        if rec.get("name") != "consensus.conflicting_vote":
            return
        pair = checks.decode_conflicting_vote_record(rec)
        if pair is None:
            return
        vote_a, vote_b = pair
        vals = self._vals_at(vote_a.height)
        if vals is None:
            return
        ev = checks.build_duplicate_vote_evidence(
            vote_a, vote_b, vals, self.chain_id)
        if ev is None:
            self._ok("equivocation")
            return
        self._report_equivocation(ev, source=f"trace:{node_name}")

    def _vals_at(self, height: int):
        for node in self.nodes.values():
            f = node.get(height)
            if f is not None and f.vals is not None:
                return f.vals
        return None

    def _report_equivocation(self, ev, source: str) -> None:
        h = ev.hash()
        with self._verdict_lock:
            if h in self._submitted_evidence:
                return
            self._submitted_evidence.add(h)
        self._verdict(
            "equivocation", h.hex(),
            height=ev.height,
            validator=ev.address().hex(),
            vote_type=int(ev.vote_a.type),
            round=ev.vote_a.round,
            source=source,
            detail=(f"validator {ev.address().hex()[:12]} double-signed "
                    f"type {int(ev.vote_a.type)} at height {ev.height} "
                    f"round {ev.vote_a.round}"))
        if self.submit_evidence:
            self.submit_duplicate_vote(ev)

    def submit_duplicate_vote(self, ev) -> dict[str, str]:
        """Push verified evidence back into every watched node's pool —
        the accountability leg: the pool gossips + commits it, so the
        equivocator is slashed by the chain itself, not just logged."""
        m = watchtower_metrics()
        results: dict[str, str] = {}
        wire = ev.wrapped().hex()
        for node in self.nodes.values():
            try:
                self._client_factory(node.url).broadcast_evidence(
                    evidence=wire)
                results[node.name] = "ok"
                m.evidence_submitted_total.inc(1.0, "ok")
            except RuntimeError:
                # the pool rejects duplicates/known evidence — expected
                # once any one submission has gossiped ahead of us
                results[node.name] = "rejected"
                m.evidence_submitted_total.inc(1.0, "rejected")
            except Exception:  # noqa: BLE001 — node down mid-audit
                results[node.name] = "error"
                m.evidence_submitted_total.inc(1.0, "error")
        return results

    # ------------------------------------------------------------------
    # DA withholding watchdog
    # ------------------------------------------------------------------
    def da_sweep(self, node_name: str, fetch=None) -> object | None:
        """One sampling sweep against `node_name`'s newest DA-carrying
        frame. Withheld/unverifiable samples (or no reachable samples
        at all while a root is advertised) count toward a consecutive-
        failure streak; the alarm raises at `da_alarm_after` and clears
        on the next confident sweep."""
        node = self.nodes[node_name]
        target = None
        with node.lock:
            for f in reversed(node.frames.values()):
                if f.da_root is not None and f.da_k > 0:
                    target = f
                    break
        if target is None:
            return None
        t0 = time.perf_counter()
        n = target.da_k + target.da_m
        sampler = Sampler(
            client_id=hash(node_name) & 0x7FFFFFFF,
            n=n, k=target.da_k, samples=self.da_samples,
            seed=target.height,
        )
        if fetch is None:
            fetch = lambda h, i: self._rpc_fetch_sample(node, h, i)  # noqa: E731
        try:
            res = sampler.run(target.height, target.da_root, fetch)
        except Exception as e:  # noqa: BLE001 — transport died mid-sweep
            self._error("da")
            trace.event("watchtower.audit", node=node_name,
                        height=target.height, error=f"da: {e}")
            return None
        watchtower_metrics().audit_seconds.observe(
            time.perf_counter() - t0, "da")
        bad = res.detected_withholding or res.samples_ok == 0
        if bad:
            streak = self._da_fail_streak.get(node_name, 0) + 1
            self._da_fail_streak[node_name] = streak
            if streak >= self.da_alarm_after:
                self._da_alarmed.add(node_name)
                self._verdict(
                    "da", (node_name, target.height),
                    node=node_name, height=target.height,
                    samples_ok=res.samples_ok,
                    samples_failed=res.samples_failed,
                    failed_indices=res.failed_indices,
                    confidence=round(res.confidence, 4),
                    detail=(f"availability confidence stalled at "
                            f"{res.confidence:.2%} after {streak} "
                            f"consecutive failing sweeps"))
        else:
            self._da_fail_streak[node_name] = 0
            if node_name in self._da_alarmed:
                self._da_alarmed.discard(node_name)
                if not self._da_alarmed:
                    self.clear_alarm("da")
            self._ok("da")
        return res

    def _rpc_fetch_sample(self, node: _WatchedNode, height: int,
                          index: int):
        """da_sample over RPC, parsed into the Sampler's (chunk, proof,
        commitment) transport triple; None = withheld/unknown."""
        try:
            r = self._client_factory(node.url).da_sample(
                height=str(height), index=index)
        except RuntimeError:
            return None  # RPC-level error: no sample for that index
        chunk = bytes.fromhex(r["chunk"])
        pr = r["proof"]
        proof = merkle.Proof(
            total=int(pr["total"]), index=int(pr["index"]),
            leaf_hash=base64.b64decode(pr["leaf_hash"]),
            aunts=[base64.b64decode(a) for a in pr["aunts"]],
        )
        cm = r["commitment"]
        com = DACommitment(
            n=int(cm["shards"]), k=int(cm["data_shards"]),
            payload_len=int(cm["payload_len"]),
            chunks_root=bytes.fromhex(cm["chunks_root"]),
        )
        return chunk, proof, com

    # ------------------------------------------------------------------
    # live stall classification
    # ------------------------------------------------------------------
    def stall_pass(self) -> dict:
        """Classify current per-node trace state; new stalls verdict."""
        t0 = time.perf_counter()
        rep = self.stall.classify()
        watchtower_metrics().audit_seconds.observe(
            time.perf_counter() - t0, "stall")
        for s in rep["stalled"]:
            key = (s["node"], s["height"])
            if key in self._stalled_seen:
                continue
            self._stalled_seen.add(key)
            self._verdict(
                "stall", key,
                node=s["node"], height=s["height"],
                committed=s["committed"], max_round=s["max_round"],
                first_missing=s["first_missing"],
                silent_peers=s["silent_peers"],
                stalled_for_s=s["stalled_for_s"],
                detail=s["detail"])
        if rep["status"] == "ok" and rep["nodes"]:
            self._ok("stall")
        return rep

    # ------------------------------------------------------------------
    # background loops
    # ------------------------------------------------------------------
    def _tail_feed_once(self, node: _WatchedNode) -> None:
        url = (f"{node.url}/replication_feed"
               f"?cursor={node.cursor}&timeout_s={self.feed_timeout_s}")
        with urllib.request.urlopen(
                url, timeout=self.feed_timeout_s + 10) as resp:
            self._resps.append(resp)
            node.feed_connects += 1
            try:
                for raw in resp:
                    if self._stop.is_set():
                        return
                    line = raw.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if "h" not in obj:  # control record {"tip", "min"}
                        if int(obj.get("tip", 0)) > node.tip:
                            node.tip = int(obj["tip"])
                        self._set_lag(node)
                        continue
                    self.ingest_frame(node.name, obj)
            finally:
                try:
                    self._resps.remove(resp)
                except ValueError:
                    pass

    def _feed_loop(self, node: _WatchedNode) -> None:
        while not self._stop.is_set():
            try:
                self._tail_feed_once(node)
            except urllib.error.HTTPError as e:
                if self._stop.is_set():
                    return
                if e.code == 409:
                    # cursor out of the retention window: an auditor has
                    # no snapshot to restore — jump to the live tip and
                    # audit from there (heights skipped are recorded as
                    # a gap in status(), never as a verdict)
                    try:
                        st = self._client_factory(
                            node.url).replication_status()
                        node.cursor = max(node.cursor,
                                          int(st.get("tip", 0)) - 1)
                    except Exception:  # noqa: BLE001
                        pass
                self._stop.wait(0.5)
            except Exception:  # noqa: BLE001 — node restarting
                if self._stop.is_set():
                    return
                self._stop.wait(0.3)

    def _da_loop(self) -> None:
        while not self._stop.wait(self.da_interval_s):
            for name in list(self.nodes):
                if self._stop.is_set():
                    return
                try:
                    self.da_sweep(name)
                except Exception:  # noqa: BLE001
                    self._error("da")

    def _stall_loop(self) -> None:
        readers = {name: TailReader(path)
                   for name, path in self.trace_sinks.items()}
        while not self._stop.is_set():
            for name, reader in readers.items():
                for rec in reader.poll():
                    self.handle_trace_record(name, rec)
            self.stall_pass()
            self._stop.wait(self.stall_interval_s)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        for node in self.nodes.values():
            t = threading.Thread(target=self._feed_loop, args=(node,),
                                 name=f"wt-feed-{node.name}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._da_loop, name="wt-da",
                             daemon=True)
        t.start()
        self._threads.append(t)
        if self.trace_sinks:
            t = threading.Thread(target=self._stall_loop, name="wt-stall",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for resp in list(self._resps):
            try:
                resp.close()  # unblock a live chunked read
            except Exception:  # noqa: BLE001
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        with self._verdict_lock:
            if self._verdict_fh is not None:
                self._verdict_fh.close()
                self._verdict_fh = None

    # ------------------------------------------------------------------
    def ready(self) -> tuple[bool, dict]:
        """healthz readiness: every watched feed has delivered at least
        one frame (the auditor cannot audit what it cannot see)."""
        per_node = {n.name: n.cursor for n in self.nodes.values()}
        ok = all(c > 0 for c in per_node.values()) if per_node else False
        return ok, {"watchtower": True, "audited": per_node,
                    "verdicts": len(self.verdicts)}

    def status(self) -> dict:
        with self._verdict_lock:
            by_check: dict[str, int] = {}
            for v in self.verdicts:
                by_check[v["check"]] = by_check.get(v["check"], 0) + 1
            n_verdicts = len(self.verdicts)
            n_safety = sum(1 for v in self.verdicts if v["safety"])
        return {
            "chain_id": self.chain_id,
            "nodes": {
                n.name: {"url": n.url, "tip": n.tip, "audited": n.cursor,
                         "frames": len(n.frames),
                         "feed_connects": n.feed_connects}
                for n in self.nodes.values()
            },
            "verdicts": n_verdicts,
            "safety_verdicts": n_safety,
            "verdicts_by_check": by_check,
            "evidence_submitted": len(self._submitted_evidence),
        }
