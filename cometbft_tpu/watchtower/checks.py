"""Pure audit checks over decoded feed frames.

Everything here is stateless and side-effect free: the auditor
(watchtower/auditor.py) feeds decoded commits / validator sets in and
turns the returned findings into verdicts, metrics and evidence
submissions. Keeping the logic pure is what lets the adversarial
fixtures (tests/test_watchtower.py) pin each check on constructed
conflicting objects without a network in sight.
"""

from __future__ import annotations

from ..types.basic import Timestamp
from ..types.block import BlockIDFlag, Commit
from ..types.evidence import DuplicateVoteEvidence, EvidenceError
from ..types.vote import SignedMsgType, Vote


def commit_signers(commit, vals) -> set[bytes]:
    """Addresses that COMMIT-signed `commit`, resolved against `vals`.

    Works for both commit shapes: a plain Commit's slots carry their
    validator address; a CertCommit's synthesized column carries empty
    addresses, so identity comes from the slot POSITION in the
    validator set — the same rule the columnar replay path uses.
    """
    out: set[bytes] = set()
    if commit is None or vals is None:
        return out
    for i, cs in enumerate(commit.signatures):
        if cs.block_id_flag != BlockIDFlag.COMMIT:
            continue
        addr = cs.validator_address
        if not addr and i < len(vals):
            addr = vals.get_by_index(i).address
        if addr:
            out.add(addr)
    return out


def fork_culprits(commit_a, commit_b, vals) -> list[bytes]:
    """Name the validators that signed BOTH sides of a fork.

    Two valid +2/3 commits for different blocks at one height must
    share >= 1/3 of the voting power (quorum intersection) — the
    overlap IS the accountable byzantine set. Returns sorted addresses;
    empty when the commits agree on a block id (no fork).
    """
    if commit_a is None or commit_b is None:
        return []
    if commit_a.block_id.key() == commit_b.block_id.key():
        return []
    both = commit_signers(commit_a, vals) & commit_signers(commit_b, vals)
    return sorted(both)


def column_votes(commit, vals) -> dict[bytes, Vote]:
    """Reconstruct the precommit each COMMIT slot of a plain Commit
    attests to, keyed by validator address.

    Only slots with a real per-validator signature qualify — a
    CertCommit's synthesized column has none, and individual votes are
    not recoverable from an aggregate, so certificate frames simply
    contribute nothing to the cross-feed equivocation scan (their
    conflicts still surface through fork detection).
    """
    out: dict[bytes, Vote] = {}
    if commit is None or vals is None or not isinstance(commit, Commit):
        return out
    for i, cs in enumerate(commit.signatures):
        if cs.block_id_flag != BlockIDFlag.COMMIT or not cs.signature:
            continue
        addr = cs.validator_address
        if not addr and i < len(vals):
            addr = vals.get_by_index(i).address
        if not addr:
            continue
        out[addr] = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=commit.height,
            round=commit.round,
            block_id=commit.block_id,
            timestamp=cs.timestamp,
            validator_address=addr,
            validator_index=i,
            signature=cs.signature,
        )
    return out


def cross_column_equivocations(commit_a, commit_b, vals,
                               chain_id: str) -> list[DuplicateVoteEvidence]:
    """Equivocation pairs visible purely from two nodes' seen-commit
    columns at one height: a validator whose COMMIT slot in one column
    signs a different block id than in the other, at the SAME round.

    Verifies each candidate before returning it — a slot pair that does
    not verify (wrong power bookkeeping, forged signature) is dropped,
    never reported, which is what keeps the clean-world false-positive
    rate at zero.
    """
    if commit_a is None or commit_b is None or vals is None:
        return []
    if commit_a.round != commit_b.round:
        return []
    if commit_a.block_id.key() == commit_b.block_id.key():
        return []
    votes_a = column_votes(commit_a, vals)
    votes_b = column_votes(commit_b, vals)
    out = []
    for addr in sorted(votes_a.keys() & votes_b.keys()):
        ev = build_duplicate_vote_evidence(
            votes_a[addr], votes_b[addr], vals, chain_id)
        if ev is not None:
            out.append(ev)
    return out


def build_duplicate_vote_evidence(vote_a: Vote, vote_b: Vote, vals,
                                  chain_id: str,
                                  time: Timestamp | None = None
                                  ) -> DuplicateVoteEvidence | None:
    """Construct + verify DuplicateVoteEvidence from two signed votes.

    Returns None instead of raising when the pair is not actual,
    provable equivocation (same block, different HRS, unknown
    validator, bad signature): the callers feed in unverified
    candidates from trace records and cross-feed columns, and only
    verified evidence may reach broadcast_evidence — the nodes would
    reject anything less anyway.
    """
    if vote_a is None or vote_b is None or vals is None:
        return None
    _, val = vals.get_by_address(vote_a.validator_address)
    if val is None:
        return None
    try:
        ev = DuplicateVoteEvidence.from_votes(
            vote_a, vote_b,
            validator_power=val.voting_power,
            total_voting_power=vals.total_voting_power(),
            time=time or vote_a.timestamp,
        )
        ev.verify(chain_id, vals)
    except (EvidenceError, ValueError):
        return None
    return ev


def decode_conflicting_vote_record(rec: dict) -> tuple[Vote, Vote] | None:
    """Parse a `consensus.conflicting_vote` trace record's vote pair."""
    try:
        a = Vote.decode(bytes.fromhex(rec["vote_a"]))
        b = Vote.decode(bytes.fromhex(rec["vote_b"]))
    except (KeyError, ValueError, TypeError):
        return None
    return a, b


def cert_commit_matches_column(cert_commit, column, vals) -> list[str]:
    """Cross-check a CertCommit against the retained full column
    (the PR-17 full_commit_window seam, audited externally).

    Returns a list of human-readable discrepancies; empty = consistent.
    The bitmap must cover exactly the column's COMMIT slots and both
    must attest the same block id at the same height/round.
    """
    problems = []
    if cert_commit is None or column is None:
        return problems
    if cert_commit.height != column.height:
        problems.append(
            f"height {cert_commit.height} != column {column.height}")
        return problems
    if cert_commit.round != column.round:
        problems.append(
            f"round {cert_commit.round} != column {column.round}")
    if cert_commit.block_id.key() != column.block_id.key():
        problems.append("block id differs from retained column")
    n = len(column.signatures)
    for i in range(n):
        in_cert = cert_commit.cert.has_signer(i)
        in_col = column.signatures[i].block_id_flag == BlockIDFlag.COMMIT
        if in_cert != in_col:
            who = "certificate" if in_cert else "column"
            addr = column.signatures[i].validator_address
            if not addr and vals is not None and i < len(vals):
                addr = vals.get_by_index(i).address
            problems.append(
                f"signer {i} ({addr.hex()[:12]}) only in {who}")
    return problems
