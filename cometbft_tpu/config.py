"""Node configuration (reference config/config.go + config/toml.go).

A typed Config with the reference's sections (Base, RPC, P2P, Mempool,
Consensus, BlockSync, Storage, Instrumentation), TOML persistence, and
per-section validation. The `crypto_backend` flag is the TPU seam: "tpu"
routes batch verification through the device kernels, "cpu" uses the
pure-Python oracle (SURVEY §5.6's `crypto.backend` gate).
"""

from __future__ import annotations

import os

try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib

from dataclasses import asdict, dataclass, field


@dataclass
class BaseConfig:
    chain_id: str = ""
    moniker: str = "node"
    home: str = "."
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    # when set (tcp://host:port) the node LISTENS here and a remote
    # signer process dials in; FilePV is not used (reference
    # PrivValidatorListenAddr)
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    db_backend: str = "sqlite"  # sqlite | mem
    db_dir: str = "data"
    abci: str = "local"  # local | socket
    proxy_app: str = "unix:///tmp/app.sock"
    crypto_backend: str = "tpu"  # tpu | cpu
    # record grammar-relevant ABCI calls to data/abci_calls.log for the
    # e2e conformance checker (reference test/e2e/pkg/grammar)
    abci_call_log: bool = False
    # in-process kvstore app: take a snapshot every N heights so peers
    # can state-sync from this node (reference e2e app SnapshotInterval);
    # 0 disables
    snapshot_interval: int = 0

    def validate(self) -> None:
        if self.db_backend not in ("sqlite", "mem"):
            raise ValueError(f"unknown db_backend {self.db_backend}")
        if self.abci not in ("local", "socket"):
            raise ValueError(f"unknown abci mode {self.abci}")
        if self.crypto_backend not in ("tpu", "cpu"):
            raise ValueError(f"unknown crypto_backend {self.crypto_backend}")


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_body_bytes: int = 1_000_000
    # serve the unsafe_* operator routes (dial_seeds/dial_peers); off by
    # default like the reference's rpc.unsafe flag (config/config.go) —
    # anyone who can reach the listener could otherwise steer this
    # node's peer connections (eclipse-attack aid)
    unsafe: bool = False
    # gRPC services (reference [grpc] config): empty disables. The
    # privileged listener serves the pruning/data-companion API and
    # should stay on loopback.
    grpc_laddr: str = ""
    grpc_privileged_laddr: str = ""

    def validate(self) -> None:
        if self.max_body_bytes <= 0:
            raise ValueError("max_body_bytes must be positive")


@dataclass
class P2PConfig:
    laddr: str = "tcp://127.0.0.1:26656"
    persistent_peers: str = ""  # comma-separated host:port
    pex: bool = True
    addr_book_file: str = "config/addrbook.json"
    # refuse non-routable addresses in the book (reference
    # addr_book_strict). Off by default: this reproduction's nets run
    # on loopback, which strict mode would reject wholesale.
    addr_book_strict: bool = False
    # seed-crawler mode (reference p2p.seed_mode): crawl addresses,
    # serve addrs-on-request, never hold full peers
    seed_mode: bool = False
    # comma-separated host:port seed nodes dialed when the address book
    # cannot supply peers (reference p2p.seeds)
    seeds: str = ""
    # cadence of the PEX ensure-peers loop (or the crawl loop in seed
    # mode); e2e nets tighten this for fast seed-only bootstrap
    pex_interval_s: float = 30.0
    max_inbound_peers: int = 40
    max_outbound_peers: int = 10
    send_rate: int = 512_000  # bytes/s (reference 500 KB/s default)
    recv_rate: int = 512_000
    # data bytes per MConnection packet. 1024 keeps the reference's wire
    # shape; the receive path is frame-size-agnostic, so peers at
    # different sizes interoperate (e2e nets raise this — fewer
    # header/seal round-trips per block part)
    max_packet_payload_size: int = 1024
    # arm the fault-injection control channel (data/partition.json ->
    # transport-level peer blocking) — test harness only; a production
    # node must not expose a file that silently isolates it
    fault_injection: bool = False

    def validate(self) -> None:
        if self.max_inbound_peers < 0 or self.max_outbound_peers < 0:
            raise ValueError("peer limits must be >= 0")
        if self.pex_interval_s <= 0:
            raise ValueError("pex_interval_s must be positive")
        if self.seed_mode and not self.pex:
            raise ValueError("seed_mode requires pex")
        if self.max_packet_payload_size <= 0:
            raise ValueError("max_packet_payload_size must be positive")

    @staticmethod
    def _addr_list(raw: str) -> list[tuple[str, int]]:
        out = []
        for item in filter(None, raw.split(",")):
            host, port = item.strip().rsplit(":", 1)
            out.append((host, int(port)))
        return out

    def persistent_peer_list(self) -> list[tuple[str, int]]:
        return self._addr_list(self.persistent_peers)

    def seed_list(self) -> list[tuple[str, int]]:
        return self._addr_list(self.seeds)


@dataclass
class MempoolConfig:
    size: int = 5000
    cache_size: int = 10000
    max_tx_bytes: int = 1_048_576
    keep_invalid_txs_in_cache: bool = False
    # cap tx gossip fan-out per broadcast; 0 floods every peer
    # (reference's experimental max-gossip-connections bound)
    experimental_max_gossip_connections: int = 0
    # micro-batched admission pipeline: windows of up to
    # `admission_window` txs drained after at most
    # `admission_max_delay_ms` (latency bound), amortizing the app
    # round-trip, batch signature verify, and lock acquisition.
    # admission_window=0 disables the pipeline (per-tx admission).
    admission_window: int = 256
    admission_max_delay_ms: float = 2.0
    # batch-verify ed25519 signatures of STX-enveloped txs at admission
    admission_verify_sigs: bool = True

    def validate(self) -> None:
        if self.size <= 0 or self.cache_size <= 0:
            raise ValueError("mempool sizes must be positive")
        if self.admission_window < 0 or self.admission_max_delay_ms < 0:
            raise ValueError("admission window/delay must be >= 0")


@dataclass
class ConsensusConfig:
    wal_file: str = "data/cs.wal"
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    # speculative proposal assembly (ISSUE 11): when this node is the
    # next height's proposer, reap + build the proposal block in the
    # background during the previous height's commit gap; enter_propose
    # consumes it only if (height, last-commit, state, mempool) still
    # match, else discards bit-safely and rebuilds cold
    speculative_propose: bool = True
    # certificate-native consensus (ISSUE 17): on all-BLS validator
    # sets, precommits adopt the proposal timestamp so +2/3 folds into
    # ONE aggregate certificate — gossiped to lagging peers as a single
    # frame, embedded as the block's LastCommit, and stored canonically.
    # Mixed/ed25519 sets never fold, so wire and store bytes stay
    # identical to the pre-certificate format regardless of this flag.
    cert_native: bool = True

    def validate(self) -> None:
        for name in ("timeout_propose", "timeout_prevote", "timeout_precommit",
                     "timeout_commit"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")

    def timeouts(self):
        from .consensus.state import TimeoutConfig

        return TimeoutConfig(
            propose=self.timeout_propose,
            propose_delta=self.timeout_propose_delta,
            prevote=self.timeout_prevote,
            prevote_delta=self.timeout_prevote_delta,
            precommit=self.timeout_precommit,
            precommit_delta=self.timeout_precommit_delta,
            commit=self.timeout_commit,
        )


@dataclass
class BlockSyncConfig:
    enable: bool = True
    verify_mode: str = "batched"  # batched | full
    window: int = 32

    def validate(self) -> None:
        if self.verify_mode not in ("batched", "full"):
            raise ValueError(f"unknown verify_mode {self.verify_mode}")


@dataclass
class StateSyncConfig:
    """reference config.StateSyncConfig (config/config.go StateSync
    section): opt-in snapshot restore on boot, anchored at a trusted
    header (hash must come from an out-of-band source)."""

    enable: bool = False
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_s: int = 7 * 24 * 3600
    discovery_time_s: float = 2.0
    chunk_fetchers: int = 4
    temp_dir: str = ""
    # comma-separated RPC endpoints for light-client verification
    # (reference statesync.rpc_servers); used by `bootstrap-state` and
    # available to operators running statesync against known nodes
    rpc_servers: str = ""

    def validate(self) -> None:
        if self.enable:
            if self.trust_height <= 0:
                raise ValueError("statesync.trust_height required when enabled")
            if not self.trust_hash:
                raise ValueError("statesync.trust_hash required when enabled")


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False
    # heights of full signature columns kept beside a certificate-native
    # canonical seen commit (evidence window; ISSUE 17) — older columns
    # are dropped, the certificate remains verifiable forever
    full_commit_window: int = 64

    def validate(self) -> None:
        if self.full_commit_window < 0:
            raise ValueError("storage.full_commit_window must be >= 0")


@dataclass
class LightConfig:
    """Light-client streaming service (light/serve.py, ROADMAP #2).

    When `serve` is on, the node maintains an MMR accumulator over
    committed headers, exposes light_status/light_mmr_proof/light_bisect
    routes, and streams header+proof payloads at /light_stream. The
    verified-commit cache amortizes each height's batch verify across
    all subscribers."""

    serve: bool = False
    # verified-commit cache entries (heights) kept resident
    cache_size: int = 4096
    # per-subscriber payload queue bound; overflow drops oldest
    subscriber_queue: int = 4096
    # persist the MMR accumulator in the light column of the node DB
    # (mem-backed nodes rebuild from the block store on restart)
    persist_mmr: bool = True

    def validate(self) -> None:
        if self.cache_size <= 0:
            raise ValueError("light.cache_size must be positive")
        if self.subscriber_queue <= 0:
            raise ValueError("light.subscriber_queue must be positive")


@dataclass
class DAConfig:
    """Data-availability sampling (da/, ROADMAP #3).

    When `enabled`, every committed block's payload is split into
    `data_shards` chunks, extended with `parity_shards` Reed-Solomon
    parity chunks over GF(2^16), and committed to in the header's
    da_root. The node serves per-chunk opening proofs on da_sample and
    advertises the commitment on /light_stream; sampling clients
    (da/sampler.py) reach `confidence` that at least half the extended
    chunks — enough to reconstruct — are available."""

    enabled: bool = False
    data_shards: int = 16
    parity_shards: int = 16
    # samples each client draws per block; 0 derives the count from
    # `confidence` (da/sampler.py samples_for_confidence)
    samples_per_client: int = 0
    confidence: float = 0.99
    # extended-shard sets kept resident for serving samples
    retain_heights: int = 64
    # 2D polynomial-commitment track (da/pc.py, ROADMAP #1): per-column
    # KZG commitments + row/column erasure, bound into da_root via the
    # combined 0x04 root. Constant 48 B multiproof openings replace the
    # growing Merkle path; parity-linearity catches a lying encoder
    # with no fraud proofs.
    pc: bool = False
    pc_data_cols: int = 4
    pc_parity_cols: int = 4
    # payloads needing more data rows than this skip the PC track for
    # that height (opening cost scales with the column degree)
    pc_max_rows: int = 1024

    def validate(self) -> None:
        from .da.rs import MAX_SHARDS

        if self.data_shards < 1 or self.parity_shards < 1:
            raise ValueError("da shard counts must be >= 1")
        if self.pc_data_cols < 1 or self.pc_parity_cols < 1:
            raise ValueError("da pc column counts must be >= 1")
        if self.pc_max_rows < 1:
            raise ValueError("da.pc_max_rows must be >= 1")
        if self.data_shards + self.parity_shards > MAX_SHARDS:
            raise ValueError(
                f"da.data_shards + da.parity_shards must be <= {MAX_SHARDS}"
            )
        if self.samples_per_client < 0:
            raise ValueError("da.samples_per_client must be >= 0")
        if not (0.0 < self.confidence < 1.0):
            raise ValueError("da.confidence must be in (0, 1)")
        if self.retain_heights < 1:
            raise ValueError("da.retain_heights must be >= 1")


@dataclass
class ReplicationConfig:
    """Scale-out serving plane (replication/, ROADMAP #3).

    When `serve` is on (core role), the node publishes every committed
    height as one frame — header, validator set, canonical + seen
    commits, verified-commit certificate, 1x DA payload — on the
    resumable `/replication_feed` stream, retains the last
    `retain_frames` frames for cursor replay, and serves a bootstrap
    snapshot (MMR leaf sequence + retained frames) over
    replication_snapshot / replication_snapshot_chunk. Stateless
    replicas (`cli.py replica`, replication/replica.py) consume the
    feed and serve /light_stream, MMR proofs, bisection, DA samples and
    admission forwarding byte-identically with zero consensus state.
    The replica-role fields (core_url and below) are ignored by a core
    node; `cli.py replica` reads them."""

    serve: bool = False
    # frames kept resident for cursor replay; a replica whose cursor
    # falls behind this window re-bootstraps from the snapshot
    retain_frames: int = 1024
    # snapshot blob chunking for the statesync-shaped fetch protocol
    snapshot_chunk_bytes: int = 262144
    # ---- replica role (cli.py replica) ----
    core_url: str = ""  # http://host:port of the core feed
    # verify + forward broadcast_tx_* to the core through the replica's
    # own admission window (replica registers as its own DRR tenant)
    forward_admission: bool = True
    # healthz readiness: 503 while the feed-lag gauge exceeds this
    max_lag_heights: int = 16
    # replica tenant name on the shared VerifyScheduler ("" derives one)
    tenant: str = ""

    def validate(self) -> None:
        if self.retain_frames < 1:
            raise ValueError("replication.retain_frames must be >= 1")
        if self.snapshot_chunk_bytes < 1:
            raise ValueError(
                "replication.snapshot_chunk_bytes must be >= 1")
        if self.max_lag_heights < 0:
            raise ValueError("replication.max_lag_heights must be >= 0")


@dataclass
class WatchtowerConfig:
    """Streaming safety auditor (watchtower/, ROADMAP #5).

    Read by `cli.py watchtower`, never by a node: the auditor is a
    stateless external process that tails N core nodes' replication
    feeds (plus optional trace sinks) and runs the safety/liveness
    checks online. Core nodes only need `[replication] serve = true`.
    """

    # comma-separated core RPC base URLs (http://host:port) to audit
    node_urls: str = ""
    # comma-separated trace-sink paths for the online stall classifier
    # and the equivocation feed; empty disables trace-driven checks
    trace_sinks: str = ""
    # re-derive CertCommits against the retained column inside this
    # window of the tip (mirrors the store's full_commit_window)
    full_commit_window: int = 16
    # DA withholding watchdog cadence and per-sweep sample count
    da_interval_s: float = 2.0
    da_samples: int = 4
    # consecutive failed/stalled DA sweeps before the alarm raises
    da_alarm_after: int = 2
    # online stall classifier poll cadence
    stall_interval_s: float = 1.0
    # structured JSONL verdict log ("" = trace sink only)
    verdict_path: str = ""

    def validate(self) -> None:
        if self.full_commit_window < 0:
            raise ValueError(
                "watchtower.full_commit_window must be >= 0")
        if self.da_interval_s <= 0:
            raise ValueError("watchtower.da_interval_s must be positive")
        if self.da_samples < 1:
            raise ValueError("watchtower.da_samples must be >= 1")
        if self.da_alarm_after < 1:
            raise ValueError("watchtower.da_alarm_after must be >= 1")
        if self.stall_interval_s <= 0:
            raise ValueError(
                "watchtower.stall_interval_s must be positive")


@dataclass
class SchedConfig:
    """Shared verification scheduler (crypto/sched.py, ROADMAP #4).

    When `enabled`, every verify consumer on the node — consensus
    commit checks, blocksync replay windows, light-serve cache misses,
    mempool admission sig windows — submits its filled batch verifier
    to one process-wide scheduler (keyed by crypto backend) instead of
    dispatching directly. The scheduler coalesces concurrent requests
    into mega-batches bounded by `max_coalesce_sigs` /
    `max_coalesce_delay_ms` and services tenants (chain_ids) by
    deficit-round-robin weighted by `tenant_weight`. A lone request
    passes straight through with no added latency."""

    enabled: bool = True
    max_coalesce_sigs: int = 16384
    max_coalesce_delay_ms: float = 2.0
    stop_timeout_s: float = 2.0
    # this node's DRR weight when several chains share the scheduler
    tenant_weight: float = 1.0

    def validate(self) -> None:
        if self.max_coalesce_sigs < 1:
            raise ValueError("sched.max_coalesce_sigs must be >= 1")
        if self.max_coalesce_delay_ms < 0:
            raise ValueError("sched.max_coalesce_delay_ms must be >= 0")
        if self.stop_timeout_s <= 0:
            raise ValueError("sched.stop_timeout_s must be positive")
        if self.tenant_weight <= 0:
            raise ValueError("sched.tenant_weight must be positive")


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    # metric-name prefix (reference instrumentation.namespace)
    namespace: str = "cometbft"
    # JSONL span/event sink (utils/trace.py); empty disables tracing.
    # Relative paths resolve under the node home. The COMETBFT_TPU_TRACE
    # env var overrides at process level (subprocess nodes, bench.py).
    trace_sink: str = ""
    # tx lifecycle observatory (utils/txlife.py): sample 1 in N txs by
    # hash prefix; 0 disables. The COMETBFT_TPU_TXLIFE env var wins
    # over this (subprocess nodes, overhead harness).
    txlife_sample_rate: int = 64
    # /healthz on the metrics server: 200 while consensus height
    # advanced within this many seconds, 503 after
    healthz_window_s: float = 30.0

    def validate(self) -> None:
        if self.prometheus:
            addr = self.prometheus_listen_addr
            _, _, port = addr.rpartition(":")
            if not port.isdigit():
                raise ValueError(
                    "instrumentation.prometheus_listen_addr must end in"
                    f" :<port>, got {addr!r}"
                )
        if not self.namespace:
            raise ValueError("instrumentation.namespace must be non-empty")
        if self.txlife_sample_rate < 0:
            raise ValueError(
                "instrumentation.txlife_sample_rate must be >= 0")
        if self.healthz_window_s <= 0:
            raise ValueError(
                "instrumentation.healthz_window_s must be positive")


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    light: LightConfig = field(default_factory=LightConfig)
    da: DAConfig = field(default_factory=DAConfig)
    replication: ReplicationConfig = field(
        default_factory=ReplicationConfig)
    watchtower: WatchtowerConfig = field(
        default_factory=WatchtowerConfig)
    sched: SchedConfig = field(default_factory=SchedConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )

    def validate(self) -> None:
        for section in (self.base, self.rpc, self.p2p, self.mempool,
                        self.consensus, self.blocksync, self.statesync,
                        self.storage, self.light, self.da, self.replication,
                        self.watchtower, self.sched, self.instrumentation):
            section.validate()

    # -- paths ----------------------------------------------------------
    def path(self, rel: str) -> str:
        return os.path.join(self.base.home, rel)

    # -- TOML -----------------------------------------------------------
    def to_toml(self) -> str:
        def esc(s: str) -> str:
            # TOML basic-string escaping: a moniker or path containing a
            # quote/backslash must survive a save/load round trip.
            return (
                str(s)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
                .replace("\t", "\\t")
            )

        def emit(name, obj):
            lines = [f"[{name}]"]
            for k, v in asdict(obj).items():
                if isinstance(v, bool):
                    lines.append(f"{k} = {'true' if v else 'false'}")
                elif isinstance(v, (int, float)):
                    lines.append(f"{k} = {v}")
                else:
                    lines.append(f'{k} = "{esc(v)}"')
            return "\n".join(lines)

        parts = [
            emit("base", self.base),
            emit("rpc", self.rpc),
            emit("p2p", self.p2p),
            emit("mempool", self.mempool),
            emit("consensus", self.consensus),
            emit("blocksync", self.blocksync),
            emit("statesync", self.statesync),
            emit("storage", self.storage),
            emit("light", self.light),
            emit("da", self.da),
            emit("replication", self.replication),
            emit("watchtower", self.watchtower),
            emit("sched", self.sched),
            emit("instrumentation", self.instrumentation),
        ]
        return "\n\n".join(parts) + "\n"

    @classmethod
    def from_toml(cls, raw: str) -> "Config":
        d = tomllib.loads(raw)

        def mk(section_cls, sd):
            # forward compatibility: a config written by a NEWER build
            # may carry keys this build does not know; dropping them
            # (with a warning) instead of crashing is what lets a node
            # downgrade/upgrade across builds with one config file
            # (reference viper-based loading is tolerant the same way)
            from dataclasses import fields as _fields

            known = {f.name for f in _fields(section_cls)}
            unknown = [k for k in sd if k not in known]
            if unknown:
                from .utils.log import logger

                logger("config").warn(
                    "ignoring unknown config keys",
                    section=section_cls.__name__,
                    keys=",".join(sorted(unknown)),
                )
            return section_cls(**{k: v for k, v in sd.items() if k in known})

        cfg = cls(
            base=mk(BaseConfig, d.get("base", {})),
            rpc=mk(RPCConfig, d.get("rpc", {})),
            p2p=mk(P2PConfig, d.get("p2p", {})),
            mempool=mk(MempoolConfig, d.get("mempool", {})),
            consensus=mk(ConsensusConfig, d.get("consensus", {})),
            blocksync=mk(BlockSyncConfig, d.get("blocksync", {})),
            statesync=mk(StateSyncConfig, d.get("statesync", {})),
            storage=mk(StorageConfig, d.get("storage", {})),
            light=mk(LightConfig, d.get("light", {})),
            da=mk(DAConfig, d.get("da", {})),
            replication=mk(ReplicationConfig, d.get("replication", {})),
            watchtower=mk(WatchtowerConfig, d.get("watchtower", {})),
            sched=mk(SchedConfig, d.get("sched", {})),
            instrumentation=mk(InstrumentationConfig,
                               d.get("instrumentation", {})),
        )
        cfg.validate()
        return cfg

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_toml())

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_toml(f.read())
