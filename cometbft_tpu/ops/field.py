"""GF(2^255 - 19) arithmetic in JAX, vectorized over a trailing batch axis.

Representation: little-endian base-2^12 limbs in int32, shape (22, B).
p = 2^255 - 19; 22 * 12 = 264 bits, so 2^264 = 2^9 * 2^255 = 512 * (p + 19)
=> 2^264 ≡ 512 * 19 = 9728 (mod p), the carry-fold constant.

Invariant "loose": every limb in [0, 2^13). Products of two loose elements
sum at most 22 * (2^13 - 1)^2 < 2^31, so schoolbook multiplication never
overflows int32. `carry()` restores looseness; `freeze()` produces the
canonical representative (limbs < 2^12, value < p) for comparisons.

Why 12-bit limbs (not 16 or 25.5): the TPU VPU has int32 multiply but no
native 64-bit accumulate, so limb products plus their 22-term accumulation
must stay inside int32. 12-bit limbs leave 5 bits of headroom, which keeps
the loose/carry bound analysis simple and branch-free.

Design (not a port): the reference delegates all of this to
curve25519-voi's amd64 assembly (reference: go.mod:55,
crypto/ed25519/ed25519.go:13); we re-derive it for int32 SIMD lanes.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 22
BITS = 12
MASK = (1 << BITS) - 1
FOLD = 9728  # 2^264 mod p
P_INT = 2**255 - 19

# p in base-2^12 limbs: [4077, 4095 x 20, 7]
P_LIMBS = np.array(
    [(P_INT >> (BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)
assert sum(int(l) << (BITS * i) for i, l in enumerate(P_LIMBS)) == P_INT


def from_int(x: int, batch: int | None = None) -> np.ndarray:
    """Host-side: python int -> limb array (NLIMBS,) or broadcast (NLIMBS, B)."""
    x %= P_INT
    limbs = np.array([(x >> (BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32)
    if batch is None:
        return limbs
    return np.broadcast_to(limbs[:, None], (NLIMBS, batch)).copy()


def to_int(limbs) -> int:
    """Host-side: limb vector (NLIMBS,) -> python int (no reduction)."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (BITS * i) for i, v in enumerate(arr))


def const(x: int):
    """Constant field element shaped (NLIMBS, 1) for broadcasting against (NLIMBS, B)."""
    return jnp.asarray(from_int(x)[:, None])


def zeros_like(x):
    return jnp.zeros_like(x)


def _carry_pass(x):
    """One full carry pass over axis 0 with the 2^264 -> 9728 fold.

    Input limbs may be any int32 with |x| < 2^29 (see carry() for the
    margin analysis); output limbs are in [0, 2^12) except limb 0 which
    absorbs the fold. Signed arithmetic shifts (floor semantics) make
    this correct for negative limbs and value-negative inputs too.
    """
    out = []
    c = jnp.zeros_like(x[0])
    for j in range(NLIMBS):
        t = x[j] + c
        out.append(t & MASK)
        c = t >> BITS
    out[0] = out[0] + FOLD * c
    return jnp.stack(out)


def carry(x):
    """Restore the loose invariant (limbs in [0, 2^13)) for |limbs| < 2^29.

    Margin: pass 1 carries are < |x|max/2^12 <= 2^17, so the fold adds
    FOLD * 2^17 < 2^31 to limb 0 without overflow (this caps the domain at
    |x| < 2^29.7). Pass 2's carry chain collapses to <= 1 by limb 2, so its
    fold adds at most FOLD to limb 0 (< 2^14); the final mini-carry pushes
    limb 0's excess into limb 1, which stays < 2^13 (loose) without further
    propagation. Value is preserved mod p throughout, including for
    value-negative inputs (signed floor shifts).
    """
    x = _carry_pass(x)
    x = _carry_pass(x)
    l0 = x[0]
    l1 = x[1] + (l0 >> BITS)
    return jnp.concatenate([jnp.stack([l0 & MASK, l1]), x[2:]], axis=0)


def add(a, b):
    return carry(a + b)


# 2048*p limbwise: (a - b + SUB_BIAS) is positive limbwise (min limb
# 2048*7 = 14336 > 8191 = max loose limb) AND value-wise (max loose value
# < 2^265 + 2^252 < 2048*p ~= 2^266), so sub/neg never go value-negative
# and limb magnitudes stay < 2048*4095 < 2^23, inside carry()'s domain.
_SUB_BIAS = jnp.asarray((2048 * P_LIMBS.astype(np.int64)).astype(np.int32)[:, None])


def sub(a, b):
    return carry(a - b + _SUB_BIAS)


def neg(a):
    return carry(_SUB_BIAS - a)


def mul(a, b):
    """Schoolbook 22x22 limb multiply + fold + carry. a, b loose -> loose."""
    B = a.shape[1:]
    # t[k] = sum_{i+j=k} a[i]*b[j], k in [0, 42]; padded to 45 for carries.
    t = jnp.zeros((2 * NLIMBS + 1,) + B, dtype=jnp.int32)
    for i in range(NLIMBS):
        prod = a[i][None, :] * b  # (22, B)
        t = t.at[i : i + NLIMBS].add(prod)
    # Full carry over all 45 limbs (no fold yet; value < 2^540 fits 45 limbs).
    out = []
    c = jnp.zeros_like(t[0])
    for j in range(2 * NLIMBS + 1):
        v = t[j] + c
        out.append(v & MASK)
        c = v >> BITS
    t = jnp.stack(out)  # every limb in [0, 2^12), carry-out is zero
    # Fold limbs 22..43 into 0..21; limb 44 (<= 4: product < 2^530.4) folds
    # straight into limb 0 with 2^(12*44) = (2^264)^2 ≡ FOLD^2 (mod p).
    # lo[0] <= 4095 + FOLD*4095 + FOLD^2*4 < 2^28.7, inside carry()'s 2^29.
    lo = t[:NLIMBS] + FOLD * t[NLIMBS : 2 * NLIMBS]
    lo = lo.at[0].add((FOLD * FOLD) * t[2 * NLIMBS])
    return carry(lo)


def sq(a):
    return mul(a, a)


def mul_small(a, c: int):
    """Multiply by a small constant 0 <= c < 2^13."""
    assert 0 <= c < (1 << 13)
    return carry(a * c)


def _freeze_full_pass(x):
    """Carry pass without fold; returns (limbs, carry_out)."""
    out = []
    c = jnp.zeros_like(x[0])
    for j in range(NLIMBS):
        t = x[j] + c
        out.append(t & MASK)
        c = t >> BITS
    return jnp.stack(out), c


def freeze(a):
    """Canonical representative: limbs < 2^12, value in [0, p)."""
    a = carry(a)
    a, c = _freeze_full_pass(a)  # absorb limb-1 looseness; value < 2^264
    a = a.at[0].add(FOLD * c)
    a, c = _freeze_full_pass(a)
    a = a.at[0].add(FOLD * c)
    a, _ = _freeze_full_pass(a)
    # Fold bits >= 255 out of the top limb (bits 252..263 live there).
    top = a[NLIMBS - 1] >> 3
    a = a.at[NLIMBS - 1].set(a[NLIMBS - 1] & 7)
    a = a.at[0].add(19 * top)
    a, _ = _freeze_full_pass(a)  # value now < 2^255 + eps < 2p
    # Conditional subtract p.
    d = a - jnp.asarray(P_LIMBS[:, None])
    out = []
    c = jnp.zeros_like(d[0])
    for j in range(NLIMBS):
        t = d[j] + c
        out.append(t & MASK)
        c = t >> BITS
    d = jnp.stack(out)
    nonneg = c == 0  # carry-out 0 => a >= p
    return jnp.where(nonneg[None, :], d, a)


def eq(a, b):
    """Field equality (canonical compare). Returns bool (B,)."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


def is_zero(a):
    return jnp.all(freeze(a) == 0, axis=0)


def parity(a):
    """Least significant bit of the canonical representative. (B,) int32."""
    return freeze(a)[0] & 1


def select(cond, a, b):
    """cond: bool (B,); a, b: (NLIMBS, B)."""
    return jnp.where(cond[None, :], a, b)


def sqn(x, n: int):
    """n repeated squarings via lax.scan (keeps the traced graph small)."""
    if n <= 2:
        for _ in range(n):
            x = sq(x)
        return x
    return lax.scan(lambda c, _: (sq(c), None), x, None, length=n)[0]


def pow2523(x):
    """x^((p-5)/8) = x^(2^252 - 3), the exponent used for combined sqrt/inv.

    Standard square-and-multiply addition chain (11 muls + 252 squarings),
    re-derived from the exponent's binary structure.
    """
    x2 = sq(x)  # x^2
    x9 = mul(sq(sq(x2)), x)  # x^9
    x11 = mul(x9, x2)  # x^11
    x31 = mul(sq(x11), x9)  # x^(2^5 - 1)
    x_10 = mul(sqn(x31, 5), x31)  # 2^10 - 1
    x_20 = mul(sqn(x_10, 10), x_10)  # 2^20 - 1
    x_40 = mul(sqn(x_20, 20), x_20)  # 2^40 - 1
    x_50 = mul(sqn(x_40, 10), x_10)  # 2^50 - 1
    x_100 = mul(sqn(x_50, 50), x_50)  # 2^100 - 1
    x_200 = mul(sqn(x_100, 100), x_100)  # 2^200 - 1
    x_250 = mul(sqn(x_200, 50), x_50)  # 2^250 - 1
    return mul(sq(sq(x_250)), x)  # x^(2^252 - 3)


def invert(x):
    """x^(p-2) = x^(2^255 - 21) via pow2523: p-2 = 8*(2^252-3) + 3."""
    t = pow2523(x)
    for _ in range(3):
        t = sq(t)
    # t = x^(2^255 - 24); need * x^3
    return mul(t, mul(sq(x), x))


def from_bytes_le(b):
    """(B, 32) uint8 little-endian -> (22, B) loose limbs (value < 2^256).

    Callers that need only 255 bits (point decoding) mask the sign bit first.
    """
    b = b.astype(jnp.int32)
    padded = jnp.concatenate([b, jnp.zeros(b.shape[:-1] + (1,), jnp.int32)], axis=-1)
    limbs = []
    for j in range(NLIMBS):
        bit = BITS * j
        sb = bit // 8
        shift = bit % 8
        v = (padded[..., sb] >> shift) | (padded[..., sb + 1] << (8 - shift))
        limbs.append(v & MASK)
    return jnp.stack(limbs)  # (22, B)


def to_bytes_le(a):
    """(22, B) -> (B, 32) uint8 of the canonical representative."""
    a = freeze(a)  # limbs < 2^12, value < p < 2^255
    out = []
    for k in range(32):
        bit = 8 * k
        j = bit // BITS
        shift = bit % BITS
        v = a[j] >> shift
        if shift > BITS - 8 and j + 1 < NLIMBS:
            v = v | (a[j + 1] << (BITS - shift))
        out.append(v & 0xFF)
    return jnp.stack(out, axis=-1).astype(jnp.uint8)
