"""GF(2^255 - 19) arithmetic in JAX, vectorized over a trailing batch axis.

Representation: little-endian base-2^12 limbs in int32, shape (22, B).
p = 2^255 - 19; 22 * 12 = 264 bits, so 2^264 ≡ 512 * 19 = 9728 (mod p),
the carry-fold constant FOLD.

Loose invariant (what every op returns and accepts):
    limb 0   in [0, 13824)   (absorbs carry folds; < 2^13.76)
    limbs 1+ in [0, 4300)    (~canonical 2^12 plus ripple slack)
Schoolbook products then sum to at most
    2 * 13823 * 4299 + 20 * 4299^2 < 2^28.9  « int32,
so multiplication never overflows.

Carries are *parallel rounds*, not sequential chains: one round masks every
limb and shifts all carries up one position simultaneously (top carry folds
into limb 0 via FOLD). 2-3 rounds restore the loose invariant for every op's
intermediate bounds (documented per-op below). This keeps traced graphs ~10x
smaller than a sequential 22-step carry chain and maps to pure VPU ops.

Why 12-bit limbs: the TPU VPU has int32 multiply but no 64-bit accumulate,
so limb products plus their 22-term accumulation must fit in int32.

Design (not a port): the reference delegates field arithmetic to
curve25519-voi's amd64 assembly (reference: go.mod:55,
crypto/ed25519/ed25519.go:13); this is a re-derivation for int32 SIMD lanes.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 22
BITS = 12
MASK = (1 << BITS) - 1
FOLD = 9728  # 2^264 mod p
P_INT = 2**255 - 19

P_LIMBS = np.array(
    [(P_INT >> (BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)
assert sum(int(l) << (BITS * i) for i, l in enumerate(P_LIMBS)) == P_INT


def from_int(x: int, batch: int | None = None) -> np.ndarray:
    """Host-side: python int -> limb array (NLIMBS,) or broadcast (NLIMBS, B)."""
    x %= P_INT
    limbs = np.array([(x >> (BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32)
    if batch is None:
        return limbs
    return np.broadcast_to(limbs[:, None], (NLIMBS, batch)).copy()


def to_int(limbs) -> int:
    """Host-side: limb vector (NLIMBS,) -> python int (no reduction)."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (BITS * i) for i, v in enumerate(arr))


def const(x: int):
    """Constant field element shaped (NLIMBS, 1) for broadcasting."""
    return jnp.asarray(from_int(x)[:, None])


def _round(x, fold: bool):
    """One parallel carry round: mask all limbs, shift carries up one slot.

    Signed arithmetic shifts give floor semantics, so this is correct for
    negative limbs (value is preserved mod p). With fold=True the top
    carry re-enters limb 0 scaled by FOLD; with fold=False the top carry
    must be provably zero (only used on the wide product array).
    """
    m = x & MASK
    hi = x >> BITS
    up = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    if fold:
        top = jnp.concatenate(
            [FOLD * hi[-1:], jnp.zeros_like(hi[1:])], axis=0
        )
        return m + up + top
    return m + up


def carry(x):
    """Restore the loose invariant for |limbs| < 2^29 (3 folded rounds).

    Overflow margin: round 1's fold adds FOLD * (|x|max >> 12) < 2^30 to
    limb 0 — int32-safe up to |x| < 2^29.3. Convergence: round 1 leaves
    carries <= 2^17; round 2 collapses all but limbs 0-2 to < 4100 and
    limb 0/1 to < 2^15.1; round 3 lands the loose invariant (limb 0 <=
    4095 + FOLD = 13823, limbs 1.. < 4200). Worst-case chains were checked
    for the actual producers: add (2^14.8), sub (2^23.1), mul (2^28.7),
    mul_small (2^26.8).
    """
    x = _round(x, True)
    x = _round(x, True)
    return _round(x, True)


def add(a, b):
    """Loose + loose: limbs <= 27646; 2 rounds suffice (carries <= 6)."""
    return _round(_round(a + b, True), True)


# 2048*p limbwise: (a - b + SUB_BIAS) is positive limbwise (min limb
# 2048*7 = 14336 > 13823 = max loose limb) AND value-wise (max loose value
# < 2^265.01 < 2048*p ~= 2^266), so sub/neg never go value-negative and
# limb magnitudes stay < 2048*4095 + 13824 < 2^23.1, inside carry()'s domain.
_SUB_BIAS = jnp.asarray((2048 * P_LIMBS.astype(np.int64)).astype(np.int32)[:, None])


def _bias():
    return _KERNEL_BIAS if _KERNEL_BIAS is not None else _SUB_BIAS


def _p_const():
    """P_LIMBS as a (22, 1) value; inside kernels it is derived from the
    bias operand (= 2048 * P_LIMBS) since constants cannot be captured."""
    if _KERNEL_BIAS is not None:
        return _KERNEL_BIAS >> 11
    return jnp.asarray(P_LIMBS[:, None])


def sub(a, b):
    return carry(a - b + _bias())


def neg(a):
    return carry(_bias() - a)


_WIDE = 2 * NLIMBS + 1  # 45 rows; row 44 stays zero (max degree 42)


def _fold_wide(t):
    """(45, B) wide product -> loose (22, B), in 4 carry-shift rounds.

    Bound walk (conv rows < 2^29; rows 43-44 start at 0 since the max
    product degree is 42):
    - round 1 (unfolded): rows <= 4095 + 2^17 < 2^17.05; row 44 stays 0.
    - collapse: lo = t[:22] + FOLD*t[22:44] <= 2^17.05*(1+FOLD) < 1.32e9,
      int32-safe.  (b^22 = 2^264 ≡ FOLD mod p.)
    - round 2 over 23 rows (extra row catches the top carry):
      rows <= 4095 + (1.32e9 >> 12) < 2^18.3.
    - split-fold the top row T <= 2^18.3: T*b^22 ≡ FOLD*(T & MASK) at
      limb 0 (<= 2^25.3) + FOLD*(T >> 12) at limb 1 (<= 2^19.5) — the
      split keeps both contributions int32 where FOLD*T would overflow.
    - rounds 3-4 (folded) land the loose invariant: worst case is limb 1
      <= 4095 + (limb0 <= 4095+2^25.3 >> 12) < 4300.
    """
    batch = t.shape[1]
    t = _round(t, False)
    lo = t[:NLIMBS] + FOLD * t[NLIMBS : 2 * NLIMBS]
    lo = jnp.concatenate([lo, jnp.zeros((1, batch), jnp.int32)], axis=0)
    lo = _round(lo, False)
    top = lo[NLIMBS : NLIMBS + 1]
    x = jnp.concatenate(
        [
            lo[0:1] + FOLD * (top & MASK),
            lo[1:2] + FOLD * (top >> BITS),
            lo[2:NLIMBS],
        ],
        axis=0,
    )
    x = _round(x, True)
    return _round(x, True)


_PALLAS_TILE = 512


def _conv_rows_shifted(a, b):
    """(22, Bt) x (22, Bt) -> (45, Bt) wide product, shifted-row form.

    22 full-width multiply-accumulates (each (22, Bt)-shaped, full VPU
    sublane utilization) instead of 484 scalar-row ops — the layout the
    TPU vector unit wants, and a 20x smaller traced graph. Pure value
    form; runs identically under XLA and inside Pallas kernel bodies
    (measured faster in-kernel than ref-slice accumulation, whose
    unaligned sublane read-modify-writes Mosaic lowers poorly).
    """
    batch = a.shape[1]
    t = None
    for i in range(NLIMBS):
        rows = a[i][None, :] * b
        segs = []
        if i:
            segs.append(jnp.zeros((i, batch), jnp.int32))
        segs.append(rows)
        tail = _WIDE - NLIMBS - i
        if tail:
            segs.append(jnp.zeros((tail, batch), jnp.int32))
        shifted = jnp.concatenate(segs, axis=0) if len(segs) > 1 else segs[0]
        t = shifted if t is None else t + shifted
    return t


# --- kernel context: lets the shared curve/scalar code run INSIDE a fused
# Pallas kernel. When set (trace time only), mul/sq know not to nest a
# pallas_call (which is illegal), and sub/neg use a bias value passed in
# as a kernel input (pallas_call rejects captured array constants, so
# _SUB_BIAS cannot be closed over).
_IN_KERNEL = False
_KERNEL_BIAS = None


class kernel_mode:
    """Context manager marking that field ops are being traced inside a
    Pallas kernel body, with `sub_bias` the in-kernel value of _SUB_BIAS
    (sliced from a (22, 1) operand ref)."""

    def __init__(self, sub_bias=None):
        self.sub_bias = sub_bias

    def __enter__(self):
        global _IN_KERNEL, _KERNEL_BIAS
        self._prev = (_IN_KERNEL, _KERNEL_BIAS)
        _IN_KERNEL = True
        _KERNEL_BIAS = self.sub_bias
        return self

    def __exit__(self, *exc):
        global _IN_KERNEL, _KERNEL_BIAS
        _IN_KERNEL, _KERNEL_BIAS = self._prev
        return False


def _mul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = _fold_wide(_conv_rows_shifted(a_ref[...], b_ref[...]))


def _sq_kernel(a_ref, o_ref):
    a = a_ref[...]
    o_ref[...] = _fold_wide(_conv_rows_shifted(a, a))


def _use_pallas(*arrs) -> bool:
    import jax

    if jax.default_backend() != "tpu":
        return False
    b = arrs[0].shape[-1]
    return b >= 128 and (b % _PALLAS_TILE == 0 or b < _PALLAS_TILE)


def _pallas_binop(kernel, *arrs):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = arrs[0].shape[-1]
    tile = min(b, _PALLAS_TILE)
    spec = pl.BlockSpec((NLIMBS, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, b), jnp.int32),
        grid=(b // tile,),
        in_specs=[spec] * len(arrs),
        out_specs=spec,
    )(*arrs)


def _bcast(a, b):
    if a.shape[-1] != b.shape[-1]:
        wide = max(a.shape[-1], b.shape[-1])
        a = jnp.broadcast_to(a, (NLIMBS, wide))
        b = jnp.broadcast_to(b, (NLIMBS, wide))
    return a, b


def mul(a, b):
    """Schoolbook 22x22 limb multiply. Loose inputs -> loose output.

    Inside a fused kernel (kernel_mode) and on the CPU mesh this is a
    pure jnp DAG; standalone on TPU it becomes one Pallas kernel (round
    1's einsum formulation was HBM-bound AND blew up XLA compile time).

    Product limbs t[k] = sum_{i+j=k} a[i]b[j] < 2^29 (loose bound above).
    """
    a, b = _bcast(jnp.asarray(a), jnp.asarray(b))
    if _IN_KERNEL:
        return _fold_wide(_conv_rows_shifted(a, b))
    if _use_pallas(a, b):
        return _pallas_binop(_mul_kernel, a, b)
    return _fold_wide(_conv_rows_shifted(a, b))


def sq(a):
    """Squaring: one-input variant of mul (halves HBM reads on TPU)."""
    a = jnp.asarray(a)
    if _IN_KERNEL:
        return _fold_wide(_conv_rows_shifted(a, a))
    if _use_pallas(a):
        return _pallas_binop(_sq_kernel, a)
    return _fold_wide(_conv_rows_shifted(a, a))


def mul_small(a, c: int):
    """Multiply by a small constant 0 <= c < 2^13. |a*c| < 2^26.8 -> carry-able.

    Round 1 fold stays in int32: FOLD * (2^26.8 >> 12) < 2^28.1.
    """
    assert 0 <= c < (1 << 13)
    return carry(a * c)


def _seq_pass(x):
    """Sequential carry pass without fold; returns (limbs, carry_out (1,B)).

    Kernel-safe formulation: rows stay 2D and the result is a concat (no
    stack/scatter, which Mosaic cannot lower).
    """
    out = []
    c = jnp.zeros_like(x[0:1])
    for j in range(NLIMBS):
        t = x[j : j + 1] + c
        out.append(t & MASK)
        c = t >> BITS
    return jnp.concatenate(out, axis=0), c


def _edit_row0(a, delta):
    """a with delta (1,B) added to limb 0 (value-level, kernel-safe)."""
    return jnp.concatenate([a[0:1] + delta, a[1:]], axis=0)


def freeze(a):
    """Canonical representative: limbs < 2^12, value in [0, p).

    Rare op (a handful per signature vs thousands of muls), so the exact
    sequential passes here are fine.
    """
    a = carry(a)
    a, c = _seq_pass(a)
    a = _edit_row0(a, FOLD * c)
    a, c = _seq_pass(a)
    a = _edit_row0(a, FOLD * c)
    a, _ = _seq_pass(a)
    # Fold bits >= 255 out of the top limb (bits 252..263 live there).
    top = a[NLIMBS - 1 : NLIMBS] >> 3
    a = jnp.concatenate([a[: NLIMBS - 1], a[NLIMBS - 1 : NLIMBS] & 7], axis=0)
    a = _edit_row0(a, 19 * top)
    a, _ = _seq_pass(a)  # value now < 2^255 + eps < 2p
    # Conditional subtract p.
    d = a - _p_const()
    d, c = _seq_pass(d)
    nonneg = c == 0  # borrow-free => a >= p
    return jnp.where(nonneg, d, a)


def eq(a, b):
    """Field equality (canonical compare). Returns bool (B,)."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


def is_zero(a):
    return jnp.all(freeze(a) == 0, axis=0)


def parity(a):
    """Least significant bit of the canonical representative. (B,) int32."""
    return freeze(a)[0] & 1


def select(cond, a, b):
    """cond: bool (B,); a, b: (NLIMBS, B)."""
    return jnp.where(cond[None, :], a, b)


def sqn(x, n: int):
    """n repeated squarings via a loop primitive (small traced graph)."""
    if n <= 2:
        for _ in range(n):
            x = sq(x)
        return x
    if _IN_KERNEL:
        return lax.fori_loop(0, n, lambda i, v: sq(v), x)
    return lax.scan(lambda c, _: (sq(c), None), x, None, length=n)[0]


def pow2523(x):
    """x^((p-5)/8) = x^(2^252 - 3), the exponent for combined sqrt/inverse.

    Standard square-and-multiply addition chain (11 muls + 252 squarings),
    re-derived from the exponent's binary structure.
    """
    x2 = sq(x)  # x^2
    x9 = mul(sq(sq(x2)), x)  # x^9
    x11 = mul(x9, x2)  # x^11
    x31 = mul(sq(x11), x9)  # x^(2^5 - 1)
    x_10 = mul(sqn(x31, 5), x31)  # 2^10 - 1
    x_20 = mul(sqn(x_10, 10), x_10)  # 2^20 - 1
    x_40 = mul(sqn(x_20, 20), x_20)  # 2^40 - 1
    x_50 = mul(sqn(x_40, 10), x_10)  # 2^50 - 1
    x_100 = mul(sqn(x_50, 50), x_50)  # 2^100 - 1
    x_200 = mul(sqn(x_100, 100), x_100)  # 2^200 - 1
    x_250 = mul(sqn(x_200, 50), x_50)  # 2^250 - 1
    return mul(sq(sq(x_250)), x)  # x^(2^252 - 3)


def invert(x):
    """x^(p-2): p-2 = 8*(2^252 - 3) + 3."""
    t = pow2523(x)
    for _ in range(3):
        t = sq(t)
    return mul(t, mul(sq(x), x))


def from_bytes_le(b):
    """(B, 32) uint8 little-endian -> (22, B) loose limbs (value < 2^256).

    Callers that need only 255 bits (point decoding) mask the sign bit first.
    """
    b = b.astype(jnp.int32)
    padded = jnp.concatenate([b, jnp.zeros(b.shape[:-1] + (1,), jnp.int32)], axis=-1)
    limbs = []
    for j in range(NLIMBS):
        bit = BITS * j
        sb = bit // 8
        shift = bit % 8
        v = (padded[..., sb] >> shift) | (padded[..., sb + 1] << (8 - shift))
        limbs.append(v & MASK)
    return jnp.stack(limbs)  # (22, B)


def to_bytes_le(a):
    """(22, B) -> (B, 32) uint8 of the canonical representative."""
    a = freeze(a)  # limbs < 2^12, value < p < 2^255
    out = []
    for k in range(32):
        bit = 8 * k
        j = bit // BITS
        shift = bit % BITS
        v = a[j] >> shift
        if shift > BITS - 8 and j + 1 < NLIMBS:
            v = v | (a[j + 1] << (BITS - shift))
        out.append(v & 0xFF)
    return jnp.stack(out, axis=-1).astype(jnp.uint8)
