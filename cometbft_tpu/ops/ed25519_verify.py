"""Batched Ed25519 ZIP-215 verification — the TPU data-plane kernel.

Per-lane cofactored verification: each lane checks
    [8]([S]B + [k](-A) - R) == identity
with liberal (ZIP-215) decoding of A and R. This is the device half of the
reference's batch verifier (reference: crypto/ed25519/ed25519.go:207-240,
types/validation.go:214 verifyCommitBatch); unlike the CPU random-linear-
combination trick, per-lane verification is embarrassingly parallel on TPU
lanes AND yields the per-signature validity bitmap that the commit-verify
fallback scan needs (reference: types/validation.go:304-311) for free.

Host-side responsibilities (see crypto/ed25519.py): SHA-512 of
(R || A || M) reduced mod L -> k windows, S < L rejection, padding.
Device inputs are fixed-shape uint8/int32 arrays; no data-dependent
control flow — one trace per batch bucket, compiled once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import curve as C
from . import field as F


def verify_batch(a_bytes, r_bytes, s_wins, k_wins, live):
    """Batched ZIP-215 verify.

    a_bytes, r_bytes: (B, 32) uint8 — as-received A and R encodings.
    s_wins, k_wins:   (B, 64) int32 — 4-bit little-endian windows of S and
                      k = SHA-512(R||A||M) mod L (host-computed).
    live:             (B,) bool — padding mask (False lanes report False).

    Returns (B,) bool validity bitmap.
    """
    ok_a, a_pt = C.decompress(a_bytes)
    ok_r, r_pt = C.decompress(r_bytes)
    # [S]B + [k](-A)
    acc = C.shamir(s_wins, k_wins, C.neg(a_pt))
    acc = C.add(acc, C.neg(r_pt))
    ok_eq = C.is_identity(C.mul8(acc))
    return ok_a & ok_r & ok_eq & live


verify_batch_jit = jax.jit(verify_batch)
