"""Batched Ed25519 ZIP-215 verification — the TPU data-plane kernel.

Per-lane cofactored verification: each lane checks
    [8]([S]B + [k](-A) - R) == identity
with liberal (ZIP-215) decoding of A and R. This is the device half of the
reference's batch verifier (reference: crypto/ed25519/ed25519.go:207-240,
types/validation.go:214 verifyCommitBatch); unlike the CPU random-linear-
combination trick, per-lane verification is embarrassingly parallel on TPU
lanes AND yields the per-signature validity bitmap that the commit-verify
fallback scan needs (reference: types/validation.go:304-311) for free.

The whole pipeline runs on device (round 2): SHA-512(R||A||M) via the
ops/sha512 kernel, k = digest mod L via Barrett (ops/scalar), signed-digit
recoding, ZIP-215 decompression, the shared-doubling ladder, and the
S < L range check. The host only packs fixed-shape byte arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import curve as C
from . import field as F
from . import scalar as SC
from . import sha512 as H


def _digest_to_bytes(hi, lo):
    """(8, B) u32 big-endian word pairs -> (B, 64) digest bytes in
    hashlib order (byte i weighs 256^i in k)."""
    digest = []
    for w in range(8):
        for part in (hi, lo):
            v = part[w].astype(jnp.int32)
            digest.extend(
                [(v >> 24) & 0xFF, (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF]
            )
    return jnp.stack(digest, axis=-1).astype(jnp.uint8)


def verify_batch(a_bytes, r_bytes, s_bytes, msg_words, two_blocks, live):
    """Batched ZIP-215 verify, fully on device.

    a_bytes, r_bytes: (B, 32) uint8 — as-received A and R encodings.
    s_bytes:          (B, 32) uint8 — as-received S encodings.
    msg_words:        (B, 64) uint32 — SHA-512-padded R||A||M layout from
                      ops.sha512.pad_messages.
    two_blocks:       (B,) bool — per-lane 2-block flag from pad_messages.
    live:             (B,) bool — padding mask (False lanes report False).

    Returns (B,) bool validity bitmap.
    """
    hi, lo = H.sha512_two_blocks(msg_words, two_blocks)  # (8, B) u32, BE
    digest_bytes = _digest_to_bytes(hi, lo)  # (B, 64)

    k = SC.reduce512(digest_bytes)  # (22, B) canonical < L
    k_digits = SC.recode_signed(k)
    s_digits = SC.digits_from_bytes(s_bytes)
    s_ok = SC.lt_l(s_bytes)

    ok_a, a_pt = C.decompress(a_bytes)
    ok_r, r_pt = C.decompress(r_bytes)
    X, Y, Z = C.ladder_sub_mul8(s_digits, k_digits, C.neg(a_pt), r_pt)
    ok_eq = F.is_zero(X) & F.eq(Y, Z)
    bits = ok_a & ok_r & ok_eq & s_ok & live
    # scalar summary: every LIVE lane verified (padding/oversize lanes are
    # excluded). Fetching this single bool instead of the bitmap keeps the
    # happy-path device→host transfer at pure round-trip latency; the
    # bitmap is only pulled when the summary says some lane failed
    # (reference types/validation.go:304 falls back to a per-sig scan
    # only when the batch verify fails).
    return bits, jnp.all(bits | ~live)


verify_batch_jit = jax.jit(verify_batch)


def verify_batch_prehashed(a_bytes, r_bytes, s_bytes, k_bytes, live):
    """Batched ZIP-215 verify with the challenge scalar computed host-side.

    k_bytes: (B, 32) uint8 little-endian canonical k = SHA-512(R||A||M)
    mod L, hashed on the host. Shipping the 32-byte scalar instead of the
    256-byte padded message block cuts host->device bytes 2.75x — on a
    bandwidth-limited link that transfer, not the curve math, bounds
    sustained throughput — and drops the on-device SHA-512 + Barrett
    stages entirely. The curve-side check is identical to verify_batch:
    [8]([S]B + [k](-A) - R) == identity with liberal decoding.
    """
    k_digits = SC.digits_from_bytes(k_bytes)
    s_digits = SC.digits_from_bytes(s_bytes)
    s_ok = SC.lt_l(s_bytes)
    ok_a, a_pt = C.decompress(a_bytes)
    ok_r, r_pt = C.decompress(r_bytes)
    X, Y, Z = C.ladder_sub_mul8(s_digits, k_digits, C.neg(a_pt), r_pt)
    ok_eq = F.is_zero(X) & F.eq(Y, Z)
    bits = ok_a & ok_r & ok_eq & s_ok & live
    return bits, jnp.all(bits | ~live)


verify_batch_prehashed_jit = jax.jit(verify_batch_prehashed)


def decompress_pubkeys(a_bytes):
    """(B, 32) uint8 pubkey encodings -> (ok, negated extended point).

    The A half of the verification equation, split out so callers can
    keep a validator set's decompressed points resident on device: in
    commit replay the SAME pubkey column verifies every height, so the
    32 bytes/lane of A never need to re-cross the host->device link and
    the sqrt-decompression (one of the two per-lane exponentiations)
    runs once per validator-set change instead of once per commit."""
    ok_a, a_pt = C.decompress(a_bytes)
    return ok_a, C.neg(a_pt)


decompress_pubkeys_jit = jax.jit(decompress_pubkeys)


# delta-wire meta-array layout, shared by the host packer
# (crypto/ed25519._launch_device_delta) and the device unpacker
# (verify_batch_delta): [plen, slen, n_lo, n_mid, n_hi, pad*3,
# prefix[DELTA_PMAX], suffix[DELTA_PMAX]]
DELTA_META_HEADER = 8
DELTA_PMAX = 176  # >= MAX_INPUT_BYTES - 64 (max message length 175)
DELTA_META_LEN = DELTA_META_HEADER + 2 * DELTA_PMAX


def build_delta_msgs(a_enc, rs_mid, mlens, plen, slen, prefix, suffix):
    """Reconstruct the SHA-512-padded R||A||M blocks on device from a
    shared prefix/suffix plus per-lane delta bytes.

    Replay and commit verification hash messages that differ per lane
    only in a small middle section (the vote timestamp): the canonical
    sign-bytes prefix (type, height, round, block id) and suffix (chain
    id) are commit-invariant (types/block.py vote_sign_bytes cache).
    Shipping R||S plus the ~8-16 byte delta instead of a 32-byte
    host-hashed challenge scalar cuts the per-lane wire cost below 80
    bytes — on a bandwidth-limited host->device link that transfer is
    the throughput ceiling (PROFILE.md).

    a_enc:  (B, 32) uint8 pubkey encodings (device-resident cache).
    rs_mid: (B, 64 + MIDMAX) uint8 — R || S || mid bytes.
    mlens:  (B,) int32 — per-lane mid length.
    plen, slen: int32 scalars — shared prefix/suffix lengths (dynamic;
            the arrays are padded to a fixed max so jit keys only on
            the MIDMAX/bucket shapes).
    prefix, suffix: (PMAX,), (SMAX,) uint8 shared bytes.

    Returns (B, 64) uint32 big-endian padded words + (B,) two_blocks.
    """
    nbytes = H.PADDED_BYTES
    midmax = rs_mid.shape[1] - 64
    pos = jnp.arange(nbytes, dtype=jnp.int32)  # (256,)
    m_off = pos - 64
    mlens = mlens.astype(jnp.int32)
    total = plen + mlens + slen  # (B,) message length per lane
    head = jnp.concatenate([rs_mid[:, :32], a_enc], axis=1)  # (B,64) R||A
    head_b = jnp.take(head, jnp.clip(pos, 0, 63), axis=1).astype(jnp.int32)
    pfx_b = jnp.take(
        prefix, jnp.clip(m_off, 0, prefix.shape[0] - 1)
    ).astype(jnp.int32)
    mid_b = jnp.take(
        rs_mid[:, 64:], jnp.clip(m_off - plen, 0, midmax - 1), axis=1
    ).astype(jnp.int32)
    sfx_idx = m_off[None, :] - plen - mlens[:, None]  # (B, 256)
    sfx_b = jnp.take(
        suffix, jnp.clip(sfx_idx, 0, suffix.shape[0] - 1)
    ).astype(jnp.int32)
    b = jnp.where(
        m_off[None, :] < 0,
        head_b,
        jnp.where(
            m_off[None, :] < plen,
            pfx_b[None, :],
            jnp.where(
                m_off[None, :] < plen + mlens[:, None],
                mid_b,
                jnp.where(m_off[None, :] < total[:, None], sfx_b, 0),
            ),
        ),
    )
    # SHA-512 padding: 0x80 terminator + big-endian bit length at the
    # end of the last block (single block iff 64+total <= 111)
    b = jnp.where(pos[None, :] == 64 + total[:, None], 0x80, b)
    two = (64 + total) > 111
    blk = jnp.where(two, nbytes, nbytes // 2)
    bits = (64 + total) * 8  # < 2^16: two length bytes suffice
    b = jnp.where(pos[None, :] == blk[:, None] - 2, bits[:, None] >> 8, b)
    b = jnp.where(pos[None, :] == blk[:, None] - 1, bits[:, None] & 0xFF, b)
    words = (
        b.reshape(b.shape[0], H.PADDED_WORDS, 4).astype(jnp.uint32)
        @ jnp.asarray([1 << 24, 1 << 16, 1 << 8, 1], jnp.uint32)
    )
    return words, two


def verify_batch_delta(ok_a, neg_a, a_enc, packed, meta):
    """verify_batch with cached pubkeys AND device-side challenge
    hashing over reconstructed messages (build_delta_msgs).

    The wire is exactly TWO host arrays per submit — each device_put
    pays a fixed per-transfer cost on a tunneled runtime, which is why
    the 96-byte path packs R||S||k into one array:
      packed: (B, 64 + MIDMAX + 1) uint8 — R || S || mid || mlen.
      meta:   (360,) uint8 — [plen, slen, n_lo, n_mid, n_hi, pad*3,
              prefix[176], suffix[176]]; live lanes derive from n.
    """
    rs_mid = packed[:, :-1]
    mlens = packed[:, -1]
    meta32 = meta.astype(jnp.int32)
    plen = meta32[0]
    slen = meta32[1]
    n = meta32[2] | (meta32[3] << 8) | (meta32[4] << 16)
    live = jnp.arange(packed.shape[0], dtype=jnp.int32) < n
    h = DELTA_META_HEADER
    prefix = meta[h : h + DELTA_PMAX]
    suffix = meta[h + DELTA_PMAX :]
    words, two = build_delta_msgs(
        a_enc, rs_mid, mlens, plen, slen, prefix, suffix
    )
    hi, lo = H.sha512_two_blocks(words, two)
    digest_bytes = _digest_to_bytes(hi, lo)
    k = SC.reduce512(digest_bytes)
    k_digits = SC.recode_signed(k)
    s_bytes = rs_mid[:, 32:64]
    s_digits = SC.digits_from_bytes(s_bytes)
    s_ok = SC.lt_l(s_bytes)
    ok_r, r_pt = C.decompress(rs_mid[:, :32])
    X, Y, Z = C.ladder_sub_mul8(s_digits, k_digits, neg_a, r_pt)
    ok_eq = F.is_zero(X) & F.eq(Y, Z)
    bits = ok_a & ok_r & ok_eq & s_ok & live
    return bits, jnp.all(bits | ~live)


verify_batch_delta_jit = jax.jit(verify_batch_delta)


def verify_batch_cached_a(ok_a, neg_a, rsk, live):
    """verify_batch_prehashed with the pubkey stage precomputed by
    decompress_pubkeys (device-resident across submits).

    rsk: (B, 96) uint8 — R || S || k packed in one array so the
    per-commit host->device traffic is a single contiguous transfer
    (the link's fixed per-transfer cost matters at this rate)."""
    r_bytes = rsk[:, :32]
    s_bytes = rsk[:, 32:64]
    k_bytes = rsk[:, 64:]
    k_digits = SC.digits_from_bytes(k_bytes)
    s_digits = SC.digits_from_bytes(s_bytes)
    s_ok = SC.lt_l(s_bytes)
    ok_r, r_pt = C.decompress(r_bytes)
    X, Y, Z = C.ladder_sub_mul8(s_digits, k_digits, neg_a, r_pt)
    ok_eq = F.is_zero(X) & F.eq(Y, Z)
    bits = ok_a & ok_r & ok_eq & s_ok & live
    return bits, jnp.all(bits | ~live)


verify_batch_cached_a_jit = jax.jit(verify_batch_cached_a)
