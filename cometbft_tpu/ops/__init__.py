"""JAX/Pallas device kernels — the TPU data plane.

Layout convention: field elements are int32 arrays of shape (NLIMBS, B)
with the *batch* on the trailing axis, so every limb operation is a wide
vector op across TPU lanes and carry chains walk the (small) leading axis.
"""
