"""JAX/Pallas device kernels — the TPU data plane.

Layout convention: field elements are int32 arrays of shape (NLIMBS, B)
with the *batch* on the trailing axis, so every limb operation is a wide
vector op across TPU lanes and carry chains walk the (small) leading axis.
"""

import os as _os

import jax as _jax

# Persistent XLA compilation cache: the verify graph compiles in
# 20-40 s and the MSM accumulate kernel in ~2 min; without a disk cache
# every fresh process (each test run, each bench invocation) pays that
# again before its first verification. The JAX_COMPILATION_CACHE_DIR
# env var set in the package root is not honored by this jax build, so
# the config is applied here — every kernel module imports this package
# and jax is being imported anyway.
if _jax.config.jax_compilation_cache_dir is None:
    _jax.config.update(
        "jax_compilation_cache_dir",
        _os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            _os.path.join(
                _os.environ.get(
                    "XDG_CACHE_HOME", _os.path.expanduser("~/.cache")
                ),
                "cometbft_tpu",
                "jax",
            ),
        ),
    )
