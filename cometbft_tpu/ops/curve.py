"""Batched edwards25519 point operations in JAX.

Points are tuples (X, Y, Z, T) of (22, B) int32 limb arrays — extended
homogeneous coordinates on the twisted Edwards curve -x^2 + y^2 = 1 + d x^2 y^2
with x = X/Z, y = Y/Z, T = XY/Z.

The addition law used (add-2008-hwcd-3) is *complete* for a = -1 (a square
mod p) and d non-square, so it is valid for every curve point including the
8-torsion components that ZIP-215 liberal decoding admits — no branch needed
for doubling or identity inputs inside the table build.

Round-2 ladder design (all original TPU work, no reference counterpart —
the reference delegates to curve25519-voi assembly via
crypto/ed25519/ed25519.go:13):
- signed radix-16 digits in [-8, 7] (ops/scalar.py) halve table sizes;
  negation of a cached point is two selects and one field negation.
- tables live in "niels" form (Y+X, Y-X, 2dT [, 2Z]) so a cached-point
  addition costs 8 muls (7 when Z=1, the constant base table).
- doublings skip the T output except when the next op is an addition
  (dbl_no_t: 7 muls vs 8).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto import ed25519_ref as ref
from . import field as F

P = F.P_INT
_D2_INT = (2 * ref.D) % P

# Broadcastable (22, 1) constants.
D_C = F.const(ref.D)
D2_C = F.const(_D2_INT)
SQRT_M1_C = F.const(ref.SQRT_M1)
ONE_C = F.const(1)

# The same constants as one stacked host array — Pallas kernels cannot
# close over array constants, so fused kernels take this as an operand:
# rows [0:22)=2d, [22:44)=d, [44:66)=sqrt(-1).
_CONSTS_NP = np.concatenate(
    [F.from_int(_D2_INT)[:, None], F.from_int(ref.D)[:, None],
     F.from_int(ref.SQRT_M1)[:, None]], axis=1
).T.reshape(3 * F.NLIMBS, 1)

# While tracing inside a fused kernel this holds {'d2': (22,1) value, ...}
# so the shared point-op code below picks up operand-backed constants.
_KCONSTS: dict | None = None


def _kc(name, default):
    return _KCONSTS[name] if _KCONSTS is not None else default


def _row0_const(val: int, rows: int, cols: int):
    """Field element val*1 (only limb 0 set) synthesized in-kernel via iota
    — constants that are small integers never need an operand."""
    r = lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    return jnp.where(r == 0, val, 0)


def identity(batch: int):
    z = jnp.zeros((F.NLIMBS, batch), jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], (F.NLIMBS, batch))
    return (z, one, one, z)


def add(p, q):
    """Complete unified addition (add-2008-hwcd-3, a=-1). 9 muls."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(F.mul(T1, _kc("d2", D2_C)), T2)
    d = F.mul(F.add(Z1, Z1), Z2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def to_niels(p):
    """Extended -> cached niels form (Y+X, Y-X, 2dT, 2Z). 1 mul."""
    X, Y, Z, T = p
    return (F.add(Y, X), F.sub(Y, X), F.mul(T, _kc("d2", D2_C)), F.add(Z, Z))


def add_niels(p, n):
    """Extended + niels-cached point. 8 muls."""
    X1, Y1, Z1, T1 = p
    ypx2, ymx2, t2d2, z22 = n
    a = F.mul(F.sub(Y1, X1), ymx2)
    b = F.mul(F.add(Y1, X1), ypx2)
    c = F.mul(T1, t2d2)
    d = F.mul(Z1, z22)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def madd(p, an):
    """Extended + affine niels (Y+X, Y-X, 2dT with Z2=1). 7 muls."""
    X1, Y1, Z1, T1 = p
    ypx2, ymx2, t2d2 = an
    a = F.mul(F.sub(Y1, X1), ymx2)
    b = F.mul(F.add(Y1, X1), ypx2)
    c = F.mul(T1, t2d2)
    d = F.add(Z1, Z1)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def _dbl_efgh(p):
    X1, Y1, Z1, _ = p
    a = F.sq(X1)
    b = F.sq(Y1)
    zz = F.sq(Z1)
    c = F.add(zz, zz)
    e = F.sub(F.sub(F.sq(F.add(X1, Y1)), a), b)
    g = F.sub(b, a)
    f = F.sub(g, c)
    h = F.neg(F.add(a, b))
    return e, f, g, h


def dbl(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1). 4 sq + 4 mul."""
    e, f, g, h = _dbl_efgh(p)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def dbl_no_t(p):
    """Doubling that skips the T output (4 sq + 3 mul). The result is NOT
    valid as input to additions — only to further doublings / freezes."""
    e, f, g, h = _dbl_efgh(p)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), None)


def neg(p):
    X, Y, Z, T = p
    return (F.neg(X), Y, Z, F.neg(T))


def is_identity(p):
    X, Y, Z, _ = p
    return F.is_zero(X) & F.eq(Y, Z)


def eq_points(p, q):
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return F.eq(F.mul(X1, Z2), F.mul(X2, Z1)) & F.eq(F.mul(Y1, Z2), F.mul(Y2, Z1))


def _abs_diff_zero(a, b):
    """(1, B) int32 mask: canonical(a) == canonical(b). Kernel-safe
    keepdims formulation (no reductions to 1-D shapes)."""
    d = jnp.abs(F.freeze(a) - F.freeze(b))
    return (jnp.sum(d, axis=0, keepdims=True) == 0).astype(jnp.int32)


def _decompress_kernel(y_ref, sign_ref, bias_ref, consts_ref,
                       valid_o, x_o, t_o):
    """Fused ZIP-215 decompression (sqrt candidate + checks): ~280 field
    muls in one launch. y arrives as limbs (byte unpacking is mul-free at
    the XLA level); outputs x, t = x*y and the validity mask."""
    nl = F.NLIMBS
    with F.kernel_mode(bias_ref[...]):
        y = y_ref[...]
        batch = y.shape[1]
        d_c = consts_ref[nl : 2 * nl, :]
        sqrtm1 = consts_ref[2 * nl : 3 * nl, :]
        one = _row0_const(1, nl, batch)
        yy = F.sq(y)
        u = F.sub(yy, one)
        v = F.add(F.mul(yy, d_c), one)
        v3 = F.mul(F.sq(v), v)
        v7 = F.mul(F.sq(v3), v)
        x = F.mul(F.mul(u, v3), F.pow2523(F.mul(u, v7)))
        vxx = F.mul(v, F.sq(x))
        ok_direct = _abs_diff_zero(vxx, u)
        ok_flip = _abs_diff_zero(vxx, F.neg(u))
        x = jnp.where(ok_flip != 0, F.mul(x, sqrtm1), x)
        valid = ok_direct | ok_flip
        par = F.freeze(x)[0:1] & 1
        x = jnp.where(par != sign_ref[...], F.neg(x), x)
        t = F.mul(x, y)
    valid_o[...] = valid
    x_o[...] = x
    t_o[...] = t


def _decompress_pallas(y, sign):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch = y.shape[1]
    tile = min(batch, F._PALLAS_TILE)
    nl = F.NLIMBS
    point_spec = pl.BlockSpec((nl, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    bias_spec = pl.BlockSpec((nl, 1), lambda i: (0, 0), memory_space=pltpu.VMEM)
    consts_spec = pl.BlockSpec(
        (3 * nl, 1), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    valid, x, t = pl.pallas_call(
        _decompress_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((1, batch), jnp.int32),
            jax.ShapeDtypeStruct((nl, batch), jnp.int32),
            jax.ShapeDtypeStruct((nl, batch), jnp.int32),
        ],
        grid=(batch // tile,),
        in_specs=[point_spec, row_spec, bias_spec, consts_spec],
        out_specs=[row_spec, point_spec, point_spec],
    )(y, sign[None, :], jnp.asarray(F._SUB_BIAS), jnp.asarray(_CONSTS_NP))
    return valid[0] != 0, x, t


def decompress(b):
    """ZIP-215 liberal point decoding.

    b: (B, 32) uint8 encodings. Returns (valid: bool (B,), point).
    Non-canonical y (>= p) is reduced mod p; x == 0 with sign bit 1 is
    accepted as x = 0. Invalid (non-square x^2 candidate) lanes return
    valid=False with an arbitrary well-formed point.
    """
    b = jnp.asarray(b)
    sign = (b[:, 31].astype(jnp.int32) >> 7) & 1  # (B,)
    masked = b.at[:, 31].set(b[:, 31] & 0x7F)
    y = F.from_bytes_le(masked)  # < 2^255, loose
    one = jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], y.shape)
    if F._use_pallas(y):
        valid, x, t = _decompress_pallas(y, sign)
        return valid, (x, y, one, t)
    yy = F.sq(y)
    u = F.sub(yy, ONE_C)
    v = F.add(F.mul(yy, D_C), ONE_C)
    v3 = F.mul(F.sq(v), v)
    v7 = F.mul(F.sq(v3), v)
    x = F.mul(F.mul(u, v3), F.pow2523(F.mul(u, v7)))
    vxx = F.mul(v, F.sq(x))
    ok_direct = F.eq(vxx, u)
    ok_flip = F.eq(vxx, F.neg(u))
    x = F.select(ok_flip, F.mul(x, SQRT_M1_C), x)
    valid = ok_direct | ok_flip
    flip_sign = F.parity(x) != sign
    x = F.select(flip_sign, F.neg(x), x)
    return valid, (x, y, one, F.mul(x, y))


def compress(p):
    """(B, 32) uint8 canonical encodings (inverts Z; host/test use only)."""
    X, Y, Z, _ = p
    zi = F.invert(Z)
    x = F.freeze(F.mul(X, zi))
    y = F.mul(Y, zi)
    enc = F.to_bytes_le(y)
    return enc.at[:, 31].set(enc[:, 31] | ((x[0] & 1) << 7).astype(jnp.uint8))


# --- Constant base table: affine niels of [i]B for i in 0..8 ---
def _host_base_niels() -> np.ndarray:
    out = np.zeros((9, 3, F.NLIMBS), np.int32)
    out[0, 0] = F.from_int(1)  # identity: y+x=1, y-x=1, 2dxy=0
    out[0, 1] = F.from_int(1)
    for i in range(1, 9):
        x, y = ref._ext_to_affine(ref._ext_scalar_mul(i, ref.B_POINT))
        out[i, 0] = F.from_int((y + x) % P)
        out[i, 1] = F.from_int((y - x) % P)
        out[i, 2] = F.from_int((2 * ref.D * x * y) % P)
    return out


BASE_NIELS = jnp.asarray(_host_base_niels())  # (9, 3, 22)


def lane_table(p):
    """Per-lane niels table of [i]p for i in 0..8, one (9, 4, 22, B) array.

    Built as a 7-step scan of P_{k+1} = P_k + P (one traced add body; an
    unrolled dbl/add chain costs the same muls but 7x the graph)."""
    batch = p[0].shape[1]
    n1 = to_niels(p)

    def body(pk, _):
        nxt = add_niels(pk, n1)
        return nxt, jnp.stack(to_niels(nxt))

    _, rest = lax.scan(body, p, None, length=7)  # (7, 4, 22, B)
    ident = (
        jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], (F.NLIMBS, batch)),
        jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], (F.NLIMBS, batch)),
        jnp.zeros((F.NLIMBS, batch), jnp.int32),
        jnp.broadcast_to(jnp.asarray(F.from_int(2))[:, None], (F.NLIMBS, batch)),
    )
    head = jnp.stack([jnp.stack(ident), jnp.stack(n1)])  # (2, 4, 22, B)
    return jnp.concatenate([head, rest], axis=0)  # (9, 4, 22, B)


def _select_rows(rows, ncomps, idx_row, batch):
    """Select a table entry per lane by a (1, B) index in 0..8.

    rows(entry, comp) -> (22, ?) array; where-loop formulation
    (kernel-safe: no einsum/gather). Returns `ncomps` (22, B) arrays."""
    comps = []
    for c in range(ncomps):
        acc = None
        for e in range(9):
            row = jnp.broadcast_to(rows(e, c), (F.NLIMBS, batch))
            term = jnp.where(idx_row == e, row, 0)
            acc = term if acc is None else acc + term
        comps.append(acc)
    return comps


def _apply_sign_affine(sign_row, ypx, ymx, t2d):
    return (
        jnp.where(sign_row, ymx, ypx),
        jnp.where(sign_row, ypx, ymx),
        jnp.where(sign_row, F.neg(t2d), t2d),
    )


def _base_madd(r, ws_row, base_rows=None):
    """madd of [digit]B from the constant base table (signed select).

    base_rows: callable(entry, comp) -> (22, 1-or-B) row; defaults to the
    module-level table (XLA path). Kernels pass a VMEM-ref view instead —
    pallas_call rejects captured array constants.
    """
    if base_rows is None:
        base_rows = lambda e, c: BASE_NIELS[e, c][:, None]
    ypx, ymx, t2d = _select_rows(
        base_rows, 3, jnp.abs(ws_row), ws_row.shape[1]
    )
    return madd(r, _apply_sign_affine(ws_row < 0, ypx, ymx, t2d))


def _window_step(r, tbl_rows, ws_row, wk_row, base_rows=None):
    """One radix-16 window: 4 doublings + base madd + lane add.

    r: extended point of (22, B) arrays; tbl_rows: callable(entry, comp)
    -> (22, B) lane-table component; ws_row/wk_row: (1, B) signed digits.
    Pure value-form — runs identically inside the Pallas kernel and on
    the XLA (CPU) path.
    """
    r = dbl_no_t(r)
    r = dbl_no_t(r)
    r = dbl_no_t(r)
    r = dbl(r)
    r = _base_madd(r, ws_row, base_rows)
    # lane-table niels add (4th component z2 carries no sign)
    lypx, lymx, lt2d, lz2 = _select_rows(
        tbl_rows, 4, jnp.abs(wk_row), wk_row.shape[1]
    )
    ypx, ymx, t2d = _apply_sign_affine(wk_row < 0, lypx, lymx, lt2d)
    return add_niels(r, (ypx, ymx, t2d, lz2))


def _kernel_identity(batch: int):
    """Identity point synthesized in-kernel (no captured constants)."""
    z = jnp.zeros((F.NLIMBS, batch), jnp.int32)
    one = _row0_const(1, F.NLIMBS, batch)
    return (z, one, one, z)


def _ladder_sub_kernel(ax, ay, az, at, rx, ry, rz, rt, ws_ref, wk_ref,
                       base_ref, bias_ref, consts_ref, xo, yo, zo, tbl):
    """THE fused Pallas kernel: per tile it builds the 9-entry lane table
    of A in VMEM, runs all 64 shared-doubling windows (fori_loop — one
    traced window body), subtracts R and multiplies by the cofactor, all
    without leaving VMEM. One launch per ladder instead of ~350: on this
    runtime each pallas launch carries ~0.4 ms of serial overhead, which
    dominated the round-2 per-window formulation.

    Outputs: X, Y, Z of [8]([s]B + [k]A - R); the identity test runs at
    the XLA level (freeze has no multiplies).
    """
    global _KCONSTS
    nl = F.NLIMBS
    with F.kernel_mode(bias_ref[...]):
        _KCONSTS = {"d2": consts_ref[0:nl, :]}
        try:
            a_pt = (ax[...], ay[...], az[...], at[...])
            batch = a_pt[0].shape[1]

            # Lane table of [e]A, e in 0..8, niels form, in VMEM scratch.
            ident_n = (
                _row0_const(1, nl, batch),
                _row0_const(1, nl, batch),
                jnp.zeros((nl, batch), jnp.int32),
                _row0_const(2, nl, batch),
            )
            n1 = to_niels(a_pt)
            entries = [ident_n, n1]
            pk = a_pt
            for _ in range(7):
                pk = add_niels(pk, n1)
                entries.append(to_niels(pk))
            for e, niels in enumerate(entries):
                for c in range(4):
                    tbl[(e * 4 + c) * nl : (e * 4 + c + 1) * nl, :] = niels[c]

            def tbl_rows(e, c):
                base = (e * 4 + c) * nl
                return tbl[base : base + nl, :]

            def base_rows(e, c):
                base = (e * 3 + c) * nl
                return base_ref[base : base + nl, :]

            def body(i, r):
                w = 63 - i
                ws = ws_ref[pl_dslice(w, 1), :]
                wk = wk_ref[pl_dslice(w, 1), :]
                return _window_step(r, tbl_rows, ws, wk, base_rows)

            r = lax.fori_loop(0, 64, body, _kernel_identity(batch))
            r = add(r, neg((rx[...], ry[...], rz[...], rt[...])))
            for _ in range(3):
                r = dbl_no_t(r)
                r = (r[0], r[1], r[2], None)
        finally:
            _KCONSTS = None
    xo[...], yo[...], zo[...] = r[0], r[1], r[2]


pl_dslice = None  # bound lazily (pallas import is TPU-path-only)


def _ladder_sub_mul8_pallas(s_digits, k_digits, a_point, r_point):
    global pl_dslice
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    pl_dslice = pl.dslice
    batch = s_digits.shape[1]
    tile = min(batch, F._PALLAS_TILE)
    nl = F.NLIMBS
    base_flat = jnp.asarray(BASE_NIELS).reshape(9 * 3 * nl, 1)
    bias = jnp.asarray(F._SUB_BIAS)
    consts = jnp.asarray(_CONSTS_NP)

    point_spec = pl.BlockSpec((nl, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    dig_spec = pl.BlockSpec((64, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    base_spec = pl.BlockSpec(
        (9 * 3 * nl, 1), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    bias_spec = pl.BlockSpec((nl, 1), lambda i: (0, 0), memory_space=pltpu.VMEM)
    consts_spec = pl.BlockSpec(
        (3 * nl, 1), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _ladder_sub_kernel,
        out_shape=[jax.ShapeDtypeStruct((nl, batch), jnp.int32)] * 3,
        grid=(batch // tile,),
        in_specs=[point_spec] * 8 + [dig_spec, dig_spec, base_spec,
                                     bias_spec, consts_spec],
        out_specs=[point_spec] * 3,
        scratch_shapes=[pltpu.VMEM((9 * 4 * nl, tile), jnp.int32)],
    )(*a_point, *r_point, s_digits, k_digits, base_flat, bias, consts)
    return tuple(out)


def ladder_sub_mul8(s_digits, k_digits, a_point, r_point):
    """(X, Y, Z) of [8]([s]B + [k]a_point - r_point) — the whole ZIP-215
    verification equation left side. On TPU this is ONE fused kernel."""
    if F._use_pallas(s_digits):
        return _ladder_sub_mul8_pallas(s_digits, k_digits, a_point, r_point)
    r = ladder(s_digits, k_digits, a_point)
    r = add(r, neg(r_point))
    m = mul8(r)
    return (m[0], m[1], m[2])


def ladder(s_digits, k_digits, a_point):
    """[s]B + [k]a_point with shared doublings, signed radix-16 digits.

    s_digits, k_digits: (64, B) int32 in [-8, 7], little-endian (digit i
    weighs 16^i) — from ops.scalar.recode_signed. a_point: batched extended
    point. Scans digits from most to least significant. XLA value-form
    (the TPU path runs the fused kernel via ladder_sub_mul8 instead).
    """
    batch = s_digits.shape[1]
    tbl = lane_table(a_point)
    xs = (jnp.flip(s_digits, axis=0), jnp.flip(k_digits, axis=0))

    def tbl_rows_factory(tblv):
        def tbl_rows(e, c):
            return tblv[e, c]

        return tbl_rows

    def body(r, w):
        ws, wk = w
        r = _window_step(r, tbl_rows_factory(tbl), ws[None, :], wk[None, :])
        return r, None

    r0 = identity(batch)
    r, _ = lax.scan(body, r0, xs)
    return r


def fixed_base(s_digits):
    """[s]B from signed digits (64, B) — keygen/test helper."""
    batch = s_digits.shape[1]

    def body(r, ws):
        r = dbl_no_t(r)
        r = dbl_no_t(r)
        r = dbl_no_t(r)
        r = dbl(r)
        r = _base_madd(r, ws[None, :])
        return r, None

    r, _ = lax.scan(body, identity(batch), jnp.flip(s_digits, axis=0))
    return r


def mul8(p):
    def body(xyz, _):
        r = dbl_no_t((xyz[0], xyz[1], xyz[2], None))
        return (r[0], r[1], r[2]), None

    (x, y, z), _ = lax.scan(body, (p[0], p[1], p[2]), None, length=3)
    return (x, y, z, None)


def scalar_digits(scalars) -> np.ndarray:
    """Host-side: python ints (< 2^253) -> (64, N) int32 signed digits.

    Same recoding as ops.scalar.recode_signed, for host-held scalars
    (test/bench data generation)."""
    half = int("8" * 64, 16)
    out = np.zeros((64, len(scalars)), np.int32)
    for lane, s in enumerate(scalars):
        t = s + half
        for i in range(64):
            out[i, lane] = ((t >> (4 * i)) & 15) - 8
    return out
