"""Batched edwards25519 point operations in JAX.

Points are tuples (X, Y, Z, T) of (22, B) int32 limb arrays — extended
homogeneous coordinates on the twisted Edwards curve -x^2 + y^2 = 1 + d x^2 y^2
with x = X/Z, y = Y/Z, T = XY/Z.

The addition law used (add-2008-hwcd-3) is *complete* for a = -1 (a square
mod p) and d non-square, so it is valid for every curve point including the
8-torsion components that ZIP-215 liberal decoding admits — no branch needed
for doubling or identity inputs inside the table build.

Behavior parity target: the curve math backing the reference's batch
verifier (reference: crypto/ed25519/ed25519.go:207-240 via curve25519-voi);
the *design* (limb layout, complete-formula ladder, windowed Shamir scan)
is TPU-native and original.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto import ed25519_ref as ref
from . import field as F

P = F.P_INT
_D2_INT = (2 * ref.D) % P

# Broadcastable (22, 1) constants.
D_C = F.const(ref.D)
D2_C = F.const(_D2_INT)
SQRT_M1_C = F.const(ref.SQRT_M1)
ONE_C = F.const(1)


def identity(batch: int):
    z = jnp.zeros((F.NLIMBS, batch), jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], (F.NLIMBS, batch))
    return (z, one, one, z)


def add(p, q):
    """Complete unified addition (add-2008-hwcd-3, a=-1)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(F.mul(T1, D2_C), T2)
    d = F.mul(F.add(Z1, Z1), Z2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def dbl(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1); valid for all points."""
    X1, Y1, Z1, _ = p
    a = F.sq(X1)
    b = F.sq(Y1)
    zz = F.sq(Z1)
    c = F.add(zz, zz)
    e = F.sub(F.sub(F.sq(F.add(X1, Y1)), a), b)
    g = F.sub(b, a)  # aA + B with a = -1
    f = F.sub(g, c)  # hwcd: F = G - C ... sign fixed by tests vs oracle
    h = F.neg(F.add(a, b))  # aA - B
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def neg(p):
    X, Y, Z, T = p
    return (F.neg(X), Y, Z, F.neg(T))


def is_identity(p):
    X, Y, Z, _ = p
    return F.is_zero(X) & F.eq(Y, Z)


def eq_points(p, q):
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return F.eq(F.mul(X1, Z2), F.mul(X2, Z1)) & F.eq(F.mul(Y1, Z2), F.mul(Y2, Z1))


def decompress(b):
    """ZIP-215 liberal point decoding.

    b: (B, 32) uint8 encodings. Returns (valid: bool (B,), point).
    Non-canonical y (>= p) is reduced mod p; x == 0 with sign bit 1 is
    accepted as x = 0. Invalid (non-square x^2 candidate) lanes return
    valid=False with an arbitrary well-formed point.
    """
    b = jnp.asarray(b)
    sign = (b[:, 31].astype(jnp.int32) >> 7) & 1  # (B,)
    masked = b.at[:, 31].set(b[:, 31] & 0x7F)
    y = F.from_bytes_le(masked)  # < 2^255, loose
    yy = F.sq(y)
    u = F.sub(yy, ONE_C)
    v = F.add(F.mul(yy, D_C), ONE_C)
    v3 = F.mul(F.sq(v), v)
    v7 = F.mul(F.sq(v3), v)
    x = F.mul(F.mul(u, v3), F.pow2523(F.mul(u, v7)))
    vxx = F.mul(v, F.sq(x))
    ok_direct = F.eq(vxx, u)
    ok_flip = F.eq(vxx, F.neg(u))
    x = F.select(ok_flip, F.mul(x, SQRT_M1_C), x)
    valid = ok_direct | ok_flip
    flip_sign = F.parity(x) != sign
    x = F.select(flip_sign, F.neg(x), x)
    return valid, (x, y, jnp.broadcast_to(jnp.asarray(F.from_int(1))[:, None], y.shape), F.mul(x, y))


def compress(p):
    """(B, 32) uint8 canonical encodings (inverts Z; host/test use only)."""
    X, Y, Z, _ = p
    zi = F.invert(Z)
    x = F.freeze(F.mul(X, zi))
    y = F.mul(Y, zi)
    enc = F.to_bytes_le(y)
    return enc.at[:, 31].set(enc[:, 31] | ((x[0] & 1) << 7).astype(jnp.uint8))


# --- Fixed-base window table: TB[i] = i * B, i in 0..15, extended affine ---
def _host_table() -> np.ndarray:
    out = np.zeros((16, 4, F.NLIMBS), np.int32)
    for i in range(16):
        pt = ref._ext_scalar_mul(i, ref.B_POINT)
        if i == 0:
            x, y = 0, 1
        else:
            x, y = ref._ext_to_affine(pt)
        out[i, 0] = F.from_int(x)
        out[i, 1] = F.from_int(y)
        out[i, 2] = F.from_int(1)
        out[i, 3] = F.from_int((x * y) % P)
    return out


BASE_TABLE = jnp.asarray(_host_table())  # (16, 4, 22)


def _select_const(table, wins):
    """Select rows of a constant (16, 4, 22) table per lane. wins: (B,) int32."""
    mask = (wins[None, :] == jnp.arange(16, dtype=jnp.int32)[:, None]).astype(jnp.int32)
    # (16,B) x (16,4,22) -> (4,22,B)
    return jnp.einsum("tb,tcl->clb", mask, table)


def _select_lane(table, wins):
    """Select from a per-lane (16, 4, 22, B) table. wins: (B,) int32."""
    mask = (wins[None, :] == jnp.arange(16, dtype=jnp.int32)[:, None]).astype(jnp.int32)
    return (mask[:, None, None, :] * table).sum(0)


def _lane_table(a_point):
    """Per-lane window table [0, A, 2A, ..., 15A] as one (16, 4, 22, B) array."""
    batch = a_point[0].shape[1]
    pts = [identity(batch), a_point]
    for _ in range(14):
        pts.append(add(pts[-1], a_point))
    return jnp.stack([jnp.stack(p) for p in pts])  # (16, 4, 22, B)


def shamir(s_wins, k_wins, a_point):
    """[s]B + [k]A with shared doublings (Straus/Shamir), 4-bit windows.

    s_wins, k_wins: (B, 64) int32 nibble windows, little-endian (window w
    covers bits [4w, 4w+4)). a_point: batched extended point. The ladder
    scans windows from most to least significant under lax.scan; every
    iteration does 4 doublings + 2 complete additions, identical across
    lanes (no data-dependent control flow).
    """
    batch = s_wins.shape[0]
    ta = _lane_table(a_point)  # (16,4,22,B)
    xs = (
        jnp.flip(s_wins.T, axis=0),  # (64, B), most-significant first
        jnp.flip(k_wins.T, axis=0),
    )

    def body(r, w):
        ws, wk = w
        r = dbl(dbl(dbl(dbl(r))))
        sb = _select_const(BASE_TABLE, ws)
        r = add(r, (sb[0], sb[1], sb[2], sb[3]))
        sa = _select_lane(ta, wk)
        r = add(r, (sa[0], sa[1], sa[2], sa[3]))
        return r, None

    r0 = identity(batch)
    r, _ = lax.scan(body, r0, xs)
    return r


def mul8(p):
    return dbl(dbl(dbl(p)))


def scalar_windows(scalars) -> np.ndarray:
    """Host-side: iterable of python ints -> (B, 64) int32 nibble windows."""
    out = np.zeros((len(scalars), 64), np.int32)
    for i, s in enumerate(scalars):
        for w in range(64):
            out[i, w] = (s >> (4 * w)) & 15
    return out
