"""Batched SHA-512 on device — the hash half of Ed25519 verification.

Computes k = SHA-512(R ‖ A ‖ M) for every lane of a signature batch in one
fused elementwise pass, so the host never hashes (the reference leans on
Go's assembly SHA-512 inside curve25519-voi; here the whole digest lives
on the TPU next to the curve math — SURVEY §7 phase 1's "SHA-512 kernel").

TPU has no native 64-bit integers, so each uint64 is an explicit
(hi, lo) pair of uint32 lanes; rotations/shifts/adds are spelled out per
half. The 80 rounds are unrolled (static), producing a pure elementwise
graph XLA fuses into a few VPU loops — no tables, no gathers.

Input layout: messages are pre-padded to exactly two 128-byte SHA-512
blocks (supports R‖A‖M up to 239 bytes — canonical votes are ~122 bytes),
delivered as (B, 64) uint32 big-endian words.

Constants are derived, not transcribed: K[t] = frac(cbrt(prime_t)) and
IV[i] = frac(sqrt(prime_i)) scaled to 64 bits, computed with exact integer
roots and spot-checked against FIPS 180-4 values at import.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

MAX_INPUT_BYTES = 239  # two 128-byte blocks minus 0x80 pad byte and 16-byte length
PADDED_BYTES = 256
PADDED_WORDS = 64  # uint32 big-endian words


def _primes(n: int) -> list[int]:
    out, c = [], 2
    while len(out) < n:
        if all(c % p for p in out):
            out.append(c)
        c += 1
    return out


def _icbrt(n: int) -> int:
    x = int(round(n ** (1 / 3)))
    while x * x * x > n:
        x -= 1
    while (x + 1) ** 3 <= n:
        x += 1
    return x


_PRIMES = _primes(80)
_K64 = [_icbrt(p << 192) & ((1 << 64) - 1) for p in _PRIMES]
_IV64 = [math.isqrt(p << 128) & ((1 << 64) - 1) for p in _PRIMES[:8]]
assert _K64[0] == 0x428A2F98D728AE22 and _K64[79] == 0x6C44198C4A475817
assert _IV64[0] == 0x6A09E667F3BCC908 and _IV64[7] == 0x5BE0CD19137E2179

_KHI = np.array([k >> 32 for k in _K64], np.uint32)
_KLO = np.array([k & 0xFFFFFFFF for k in _K64], np.uint32)


def _add2(a, b):
    lo = a[1] + b[1]
    carry = (lo < b[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def _add(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = _add2(acc, x)
    return acc


def _rotr(x, n: int):
    hi, lo = x
    if n == 32:
        return (lo, hi)
    if n < 32:
        return (
            (hi >> n) | (lo << (32 - n)),
            (lo >> n) | (hi << (32 - n)),
        )
    n -= 32
    return (
        (lo >> n) | (hi << (32 - n)),
        (hi >> n) | (lo << (32 - n)),
    )


def _shr(x, n: int):
    assert 0 < n < 32
    hi, lo = x
    return (hi >> n, (lo >> n) | (hi << (32 - n)))


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def _bsig0(x):
    return _xor3(_rotr(x, 28), _rotr(x, 34), _rotr(x, 39))


def _bsig1(x):
    return _xor3(_rotr(x, 14), _rotr(x, 18), _rotr(x, 41))


def _ssig0(x):
    return _xor3(_rotr(x, 1), _rotr(x, 8), _shr(x, 7))


def _ssig1(x):
    return _xor3(_rotr(x, 19), _rotr(x, 61), _shr(x, 6))


def _ch(e, f, g):
    return (
        (e[0] & f[0]) ^ (~e[0] & g[0]),
        (e[1] & f[1]) ^ (~e[1] & g[1]),
    )


def _maj(a, b, c):
    return (
        (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
        (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
    )


def sha512_two_blocks(words, two_blocks=None):
    """words: (B, 64) uint32 — up to two pre-padded big-endian SHA-512
    blocks per lane. two_blocks: (B,) bool — lanes whose padded message
    spans both blocks (None = all). Short lanes (standard one-block
    padding in block 1) take the state after block 1.

    Returns (hi, lo): each (8, B) uint32 — the digest as 8 big-endian
    64-bit words split into halves.

    The 80 rounds run under lax.scan with a rolling 16-word message window
    in the carry (the first 16 rounds select the input word instead of the
    schedule expansion via a where on the round index) — one traced round
    body instead of 160 unrolled rounds keeps compile time flat.
    """
    from jax import lax

    words = words.astype(jnp.uint32)
    B = words.shape[0]
    khi = jnp.asarray(_KHI)
    klo = jnp.asarray(_KLO)

    state = [
        (
            jnp.full((B,), iv >> 32, jnp.uint32),
            jnp.full((B,), iv & 0xFFFFFFFF, jnp.uint32),
        )
        for iv in _IV64
    ]

    def round_body(carry, xs):
        (a, b, c, d, e, f, g, h), whi, wlo = carry
        t, kh, kl = xs
        # message schedule: rolling window w[0..15]; expanded word
        exp = _add(
            _ssig1((whi[14], wlo[14])),
            (whi[9], wlo[9]),
            _ssig0((whi[1], wlo[1])),
            (whi[0], wlo[0]),
        )
        use_input = t < 16
        wt = (
            jnp.where(use_input, whi[0], exp[0]),
            jnp.where(use_input, wlo[0], exp[1]),
        )
        kt = (jnp.broadcast_to(kh, a[0].shape), jnp.broadcast_to(kl, a[0].shape))
        t1 = _add(h, _bsig1(e), _ch(e, f, g), kt, wt)
        t2 = _add2(_bsig0(a), _maj(a, b, c))
        state2 = (_add2(t1, t2), a, b, c, _add2(d, t1), e, f, g)
        whi = jnp.concatenate([whi[1:], wt[0][None]], axis=0)
        wlo = jnp.concatenate([wlo[1:], wt[1][None]], axis=0)
        return (state2, whi, wlo), None

    states = []
    for blk in range(2):
        whi = jnp.stack([words[:, blk * 32 + 2 * j] for j in range(16)])
        wlo = jnp.stack([words[:, blk * 32 + 2 * j + 1] for j in range(16)])
        init = (tuple(state), whi, wlo)
        xs = (jnp.arange(80, dtype=jnp.int32), khi, klo)
        (out, _, _), _ = lax.scan(round_body, init, xs)
        state = [_add2(s, v) for s, v in zip(state, out)]
        states.append(state)
    if two_blocks is None:
        final = states[1]
    else:
        tb = jnp.asarray(two_blocks)
        final = [
            (jnp.where(tb, s2[0], s1[0]), jnp.where(tb, s2[1], s1[1]))
            for s1, s2 in zip(states[0], states[1])
        ]
    hi = jnp.stack([s[0] for s in final])
    lo = jnp.stack([s[1] for s in final])
    return hi, lo


def pad_messages(msgs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Host helper: messages -> ((B, 64) uint32 big-endian padded words,
    (B,) bool two-block flags).

    Standard SHA-512 padding per lane: messages <= 111 bytes fit one block
    (bit length at bytes 120..127), longer ones span two (length at bytes
    248..255). Vectorized for the common case of uniform-length messages
    (commit sign-bytes share a length); per-item loop otherwise.
    """
    n = len(msgs)
    buf = np.zeros((n, PADDED_BYTES), np.uint8)
    lens = np.fromiter((len(m) for m in msgs), np.int64, n) if n else np.zeros(0, np.int64)
    if n and lens.max(initial=0) > MAX_INPUT_BYTES:
        raise ValueError("message exceeds two SHA-512 blocks")
    two = lens > 111
    if n and (lens == lens[0]).all():
        ln = int(lens[0])
        if ln:
            buf[:, :ln] = np.frombuffer(b"".join(msgs), np.uint8).reshape(n, ln)
        buf[:, ln] = 0x80
    else:
        for i, m in enumerate(msgs):
            ln = len(m)
            buf[i, :ln] = np.frombuffer(m, np.uint8)
            buf[i, ln] = 0x80
    bitlen = (lens * 8).astype(">u8").view(np.uint8).reshape(n, 8)
    buf[two, 248:256] = bitlen[two]
    buf[~two, 120:128] = bitlen[~two]
    words = buf.reshape(n, PADDED_WORDS, 4).astype(np.uint32) @ np.array(
        [1 << 24, 1 << 16, 1 << 8, 1], np.uint32
    )
    return words, two
