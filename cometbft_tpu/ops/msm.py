"""Random-linear-combination batch verification as one multi-scalar
multiplication — the TPU Pippenger engine.

The reference's CPU batch verifier (crypto/ed25519/ed25519.go:207-240 via
curve25519-voi) collapses N verifications into ONE check

    [8]( [c]B - sum_i [z_i]R_i - sum_i [z_i h_i]A_i ) == identity,
    c = sum_i z_i s_i  (mod L),  z_i random 128-bit, h_i = H(R||A||M)

which is a 2N-point multi-scalar multiplication. Naive Pippenger bucket
accumulation is a scatter — hostile to SIMD lanes — so the TPU engine
inverts the data flow: the HOST (numpy, cometbft_tpu/crypto/rlc.py)
computes all scalars and signed base-2^C digits, sorts the (window,
bucket) contributions, and ships a dense (W*K, S) gather table; the
DEVICE then runs

  1. batched ZIP-215 decompression of all A_i, R_i (existing kernel),
  2. S sequential rounds of lane-parallel mixed additions — each round
     gathers one point per (window, bucket) lane and folds it in,
  3. a masked-tree weighted bucket reduction (sum_b (b+1)*B_b as a
     sum over weight bits of tree-reduced masked partials),
  4. a Horner combine over windows (10 doublings + 1 add per window),
  5. [c]B via the fixed-base ladder, final add, cofactor x8, identity
     check -> ONE scalar verdict.

Per-signature device cost ~1350 field muls vs ~3450 for the per-lane
ladder (ops/ed25519_verify.py) — the bucket axis (W*K = 13312 lanes)
keeps the VPU full while the digit structure lives host-side where
sorting is free. On batch failure the caller falls back to the per-lane
bitmap kernel, mirroring the reference's fallback scan
(types/validation.go:304-311).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import curve as C
from . import field as F

# Signed digit decomposition: base 2^C_BITS, buckets hold |digit| in
# [1, K]; every (scalar-class, window) pair owns its own K-lane region
# (26 windows for the 253-bit z*h scalars + 13 for the 128-bit z),
# ordered by descending weight. REGION_DBL[r] is how many doublings the
# Horner chain applies BEFORE folding region r in: 10 when the weight
# drops a window, 0 when region r shares its window with the previous
# one (the z/m split of windows 0..12). Layout authority:
# cometbft_tpu/crypto/rlc.py region_of_m / region_of_z.
C_BITS = 10
K_BUCKETS = 1 << (C_BITS - 1)  # 512
N_WINDOWS = 26
Z_WINDOWS = 13
N_REGIONS = N_WINDOWS + Z_WINDOWS  # 39
WK = N_REGIONS * K_BUCKETS  # 19968 bucket-lanes
REGION_DBL = tuple(
    [0]
    + [C_BITS] * 13  # m24..m12
    + [0 if i % 2 else C_BITS for i in range(1, 26)]  # z12, m11, z11, ...
)
# regions: r0=m25; r1..r13 = m24..m12 (10 dbl each); r14=z12 (0);
# r15=m11 (10); r16=z11 (0); ...; r37=m0 (10); r38=z0 (0)
assert len(REGION_DBL) == N_REGIONS


def _accum_weight_kernel(stream_ref, w_ref, bias_ref, consts_ref,
                         xo, yo, zo, to, acc):
    """Fused accumulate + per-lane weight kernel.

    Grid (n_tiles, S): for one 512-lane tile, S sequential rounds each
    fold one gathered niels point into the VMEM accumulator (7-mul
    madd); the final round multiplies the accumulator by the lane's
    bucket weight (<= 2^C_BITS) with a 10-step double-and-add. One
    launch replaces the ~1300 per-mul launches of the jnp formulation —
    the same fusion lesson as the ladder kernel (ops/curve.py round 2).

    stream_ref: (72, tile) gathered rows for this (s, tile): ypx at
    0:22, the sign flag at row 22, ymx at 24:46, t2d at 48:70 — limb
    groups padded to 24 rows because pallas TPU block sublane dims must
    be multiples of 8. w_ref: (1, tile) int32 weights.
    acc: (4*nl, tile) VMEM scratch persisting across the S minor steps.
    """
    nl = F.NLIMBS
    s = pl.program_id(1)
    n_s = pl.num_programs(1)
    with F.kernel_mode(bias_ref[...]):
        C._KCONSTS = {"d2": consts_ref[0:nl, :]}
        try:
            tile = stream_ref.shape[1]

            @pl.when(s == 0)
            def _init():
                ident = C._kernel_identity(tile)
                for i in range(4):
                    acc[i * nl : (i + 1) * nl, :] = ident[i]

            cur = tuple(acc[i * nl : (i + 1) * nl, :] for i in range(4))
            ypx = stream_ref[0:nl, :]
            ymx = stream_ref[24 : 24 + nl, :]
            t2d = stream_ref[48 : 48 + nl, :]
            negf = stream_ref[22:23, :] != 0
            a = jnp.where(negf, ymx, ypx)
            b = jnp.where(negf, ypx, ymx)
            t = jnp.where(negf, F.neg(t2d), t2d)
            cur = C.madd(cur, (a, b, t))
            for i in range(4):
                acc[i * nl : (i + 1) * nl, :] = cur[i]

            @pl.when(s == n_s - 1)
            def _finish():
                accp = tuple(
                    acc[i * nl : (i + 1) * nl, :] for i in range(4)
                )
                w = w_ref[...]  # (1, tile)
                # seed from the top bit via select (Mosaic rejects the
                # add-onto-identity-constant graph shape), then classic
                # double-and-add over the remaining bits
                ident = C._kernel_identity(tile)
                top = ((w >> (C_BITS - 1)) & 1) != 0
                r = tuple(
                    jnp.where(top, a_c, i_c)
                    for a_c, i_c in zip(accp, ident)
                )
                for bit in range(C_BITS - 2, -1, -1):
                    r = C.dbl(r)
                    radd = C.add(r, accp)
                    sel = ((w >> bit) & 1) != 0
                    r = tuple(jnp.where(sel, ra, rr)
                              for ra, rr in zip(radd, r))
                xo[...], yo[...], zo[...], to[...] = r
        finally:
            C._KCONSTS = None


pl = None  # bound lazily (pallas import is TPU-path-only)


def _accumulate_weighted_pallas(niels, gather_idx, gather_neg, weights):
    """Kernel-path accumulation: ONE row-gather (XLA) + ONE pallas launch.

    niels: 3 coords (22, M). gather_idx/gather_neg: (S, WK).
    weights: (W, K) int32. Returns weighted per-lane extended points
    (4 x (22, WK)).
    """
    global pl
    import jax
    from jax.experimental import pallas as _pl
    from jax.experimental.pallas import tpu as pltpu

    pl = _pl
    nl = F.NLIMBS
    S = gather_idx.shape[0]
    tile = 512
    # one efficient row-major gather per coord; rows padded to 24 (block
    # sublane dims must divide by 8), the sign flag rides in pad row 22
    flat = gather_idx.reshape(-1)
    streams = []
    pad2 = None
    for c in niels:
        rows = c.T  # (M, 22)
        g = jnp.take(rows, flat, axis=0)  # (S*WK, 22)
        g = g.reshape(S, WK, nl).transpose(0, 2, 1)  # (S, nl, WK)
        if pad2 is None:
            pad2 = jnp.zeros((S, 1, WK), jnp.int32)
        streams.append(g)
    neg_row = gather_neg.astype(jnp.int32)[:, None, :]  # (S, 1, WK)
    stream = jnp.concatenate(
        [streams[0], neg_row, pad2,
         streams[1], pad2, pad2,
         streams[2], pad2, pad2],
        axis=1,
    ).reshape(S * 72, WK)
    w_arr = weights.reshape(1, WK).astype(jnp.int32)
    bias = jnp.asarray(F._SUB_BIAS)
    consts = jnp.asarray(C._CONSTS_NP)

    n_tiles = WK // tile
    stream_spec = _pl.BlockSpec(
        (72, tile), lambda t, s: (s, t), memory_space=pltpu.VMEM
    )
    w_spec = _pl.BlockSpec(
        (1, tile), lambda t, s: (0, t), memory_space=pltpu.VMEM
    )
    bias_spec = _pl.BlockSpec(
        (nl, 1), lambda t, s: (0, 0), memory_space=pltpu.VMEM
    )
    consts_spec = _pl.BlockSpec(
        (3 * nl, 1), lambda t, s: (0, 0), memory_space=pltpu.VMEM
    )
    out_spec = _pl.BlockSpec(
        (nl, tile), lambda t, s: (0, t), memory_space=pltpu.VMEM
    )
    out = _pl.pallas_call(
        _accum_weight_kernel,
        out_shape=[jax.ShapeDtypeStruct((nl, WK), jnp.int32)] * 4,
        grid=(n_tiles, S),
        in_specs=[stream_spec, w_spec, bias_spec, consts_spec],
        out_specs=[out_spec] * 4,
        scratch_shapes=[pltpu.VMEM((4 * nl, tile), jnp.int32)],
    )(stream, w_arr, bias, consts)
    return tuple(out)


def _region_tree_sum(weighted):
    """Plain (unweighted) pairwise tree over the K axis per region:
    (22, WK) -> (22, N_REGIONS). Lane counts shrink fast, so XLA's
    fused jnp path handles it without launch-overhead concerns."""
    pts = tuple(
        x.reshape(F.NLIMBS, N_REGIONS, K_BUCKETS) for x in weighted
    )
    k = K_BUCKETS
    while k > 1:
        half = k // 2
        p = tuple(
            x[..., :half].reshape(F.NLIMBS, -1) for x in pts
        )
        q = tuple(
            x[..., half : 2 * half].reshape(F.NLIMBS, -1) for x in pts
        )
        s = C.add(p, q)
        pts = tuple(x.reshape(F.NLIMBS, N_REGIONS, half) for x in s)
        k = half
    return tuple(x[..., 0] for x in pts)


def _identity_niels(batch: int):
    one = jnp.broadcast_to(
        jnp.asarray(F.from_int(1))[:, None], (F.NLIMBS, batch)
    )
    zero = jnp.zeros((F.NLIMBS, batch), jnp.int32)
    return one, one, zero  # (Y+X, Y-X, 2dT) of (0, 1)


def _accumulate(niels, gather_idx, gather_neg):
    """S rounds of lane-parallel mixed adds.

    niels: (ypx, ymx, t2d) each (22, M) — all points + identity sentinel.
    gather_idx: (S, WK) int32 into M; gather_neg: (S, WK) bool.
    Returns extended-coords accumulators (22, WK).
    """
    ypx, ymx, t2d = niels

    def body(acc, sl):
        idx, neg = sl
        g_ypx = jnp.take(ypx, idx, axis=1)
        g_ymx = jnp.take(ymx, idx, axis=1)
        g_t2d = jnp.take(t2d, idx, axis=1)
        a = F.select(neg, g_ymx, g_ypx)
        b = F.select(neg, g_ypx, g_ymx)
        t = F.select(neg, F.neg(g_t2d), g_t2d)
        return C.madd(acc, (a, b, t)), None

    acc0 = C.identity(WK)
    acc, _ = lax.scan(body, acc0, (gather_idx, gather_neg))
    return acc


def _bucket_reduce(acc, weights):
    """(22, WK) accumulators -> per-window sums sum_lane w_lane * B_lane.

    weights: (W, K) int32 per-lane digit values from the host layout
    (lane weights are data, not structure: hot digit values are split
    across several lanes sharing a weight, so non-uniform scalar
    distributions cost nothing on device).

    Masked-tree: sum w_l B_l = sum_j 2^j (sum_{l: bit_j(w_l)} B_l).
    All C_BITS bit-masked copies are stacked as extra lanes so ONE
    pairwise tree folds the bucket axis for every bit at once (same
    device flops as per-bit trees, 10x smaller XLA graph), then a short
    Horner pass combines the bit partials. Returns extended coords with
    lanes = N_WINDOWS.
    """
    # lanes (WK,) -> (1, W, K), broadcast against the bit axis -> (J, W, K)
    pts = tuple(
        x.reshape(F.NLIMBS, 1, N_REGIONS, K_BUCKETS) for x in acc
    )
    nbits = C_BITS
    # mask (J, W, K): bit j of each lane's weight
    bits = jnp.arange(nbits, dtype=jnp.int32)[:, None, None]
    mask = (((weights[None] >> bits) & 1) != 0)[None]

    ident4 = (
        jnp.zeros((F.NLIMBS, 1, 1, 1), jnp.int32),
        jnp.asarray(F.from_int(1))[:, None, None, None],
        jnp.asarray(F.from_int(1))[:, None, None, None],
        jnp.zeros((F.NLIMBS, 1, 1, 1), jnp.int32),
    )
    masked = tuple(
        jnp.broadcast_to(
            jnp.where(mask, x, i),
            (F.NLIMBS, nbits, N_REGIONS, K_BUCKETS),
        )
        for x, i in zip(pts, ident4)
    )

    k = K_BUCKETS
    while k > 1:
        half = k // 2
        flat_p = tuple(
            x[..., :half].reshape(F.NLIMBS, -1) for x in masked
        )
        flat_q = tuple(
            x[..., half : 2 * half].reshape(F.NLIMBS, -1) for x in masked
        )
        s = C.add(flat_p, flat_q)
        masked = tuple(
            x.reshape(F.NLIMBS, nbits, N_REGIONS, half) for x in s
        )
        k = half
    partials = tuple(x[..., 0] for x in masked)  # (22, J, W)

    # Horner over bits: S = sum_j 2^j T_j
    s = tuple(x[:, nbits - 1] for x in partials)
    for j in range(nbits - 2, -1, -1):
        s = C.dbl(s)
        s = C.add(s, tuple(x[:, j] for x in partials))
    return s


def _window_combine(win_sums):
    """Horner over regions (already ordered by descending weight):
    REGION_DBL[r] doublings, then fold region r's sum in. Regions that
    share a window (the z/m split) get 0 doublings between them.

    win_sums: extended coords (22, N_REGIONS). Returns (22, 1)."""

    def ten_dbl(p):
        for _ in range(C_BITS):
            p = C.dbl(p)
        return p

    def body(acc, xs):
        r_idx, flag = xs
        pt = tuple(
            lax.dynamic_slice_in_dim(x, r_idx, 1, axis=1) for x in win_sums
        )
        acc = lax.cond(flag > 0, ten_dbl, lambda p: p, acc)
        return C.add(acc, pt), None

    acc0 = C.identity(1)
    acc, _ = lax.scan(
        body,
        acc0,
        (
            jnp.arange(N_REGIONS),
            jnp.asarray(REGION_DBL, dtype=jnp.int32),
        ),
    )
    return acc


def expand_stream(stream, stream_neg, counts, s_rounds):
    """Dense contribution stream -> (S, WK) gather table, on device.

    The host ships ~2 bytes per contribution (crypto/rlc.py); the
    padded per-round table the accumulate kernel wants is rebuilt here
    with a cumsum + masked take. stream: (L,) uint16/uint32 where L is
    tier-padded to a multiple of 8192 (stable jit shapes across the
    per-batch random layouts): the first C entries are the dense
    contributions, every trailing slot holds the identity sentinel, and
    invalid gathers target L-1; stream_neg: bit-packed signs over the
    full padded length (L/8 bytes); counts: (WK,) — sum(counts) = C.
    """
    counts = counts.astype(jnp.int32)
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix
    pos = offsets[None, :] + jnp.arange(s_rounds, dtype=jnp.int32)[:, None]
    valid = jnp.arange(s_rounds, dtype=jnp.int32)[:, None] < counts[None, :]
    pos = jnp.where(valid, pos, stream.shape[0] - 1)
    idx = jnp.take(stream, pos).astype(jnp.int32)
    negb = jnp.take(stream_neg, pos >> 3).astype(jnp.int32)
    neg = ((negb >> (pos & 7)) & 1) != 0
    neg = neg & valid  # padding gathers the identity, sign irrelevant
    return idx, neg


def rlc_verify(a_bytes, r_bytes, live, gather_idx, gather_neg, weights,
               c_digits):
    """One-scalar RLC batch verification.

    a_bytes, r_bytes: (B, 32) uint8 encodings.
    live: (B,) bool — padding lanes excluded from the decompression check
          (their z_i are zero host-side, so they never enter the sum).
    gather_idx: (S, WK) int32 — point index per round per bucket-lane;
          R_i at i, A_i at B+i, identity sentinel at 2B.
    gather_neg: (S, WK) bool — effective sign (digit sign pre-negated
          host-side to absorb the -R, -A in the equation).
    weights: (W, K) int32 — per-lane digit weights (host layout).
    c_digits: (64, 1) int32 — signed nibble digits of c = sum z_i s_i.

    Returns scalar bool: the whole batch verifies.
    """
    ok_a, a_pt = C.decompress(a_bytes)
    ok_r, r_pt = C.decompress(r_bytes)

    # affine niels (Z=1 after decompress): (Y+X, Y-X, 2dT)
    def niels_of(p):
        n = C.to_niels(p)
        return n[0], n[1], n[2]

    na, nr = niels_of(a_pt), niels_of(r_pt)
    ident = _identity_niels(1)
    niels = tuple(
        jnp.concatenate([r_c, a_c, i_c], axis=1)
        for r_c, a_c, i_c in zip(nr, na, ident)
    )

    if F._use_pallas(jnp.zeros((F.NLIMBS, WK), jnp.int32)):
        weighted = _accumulate_weighted_pallas(
            niels, gather_idx, gather_neg, weights
        )
        win_sums = _region_tree_sum(weighted)
    else:
        acc = _accumulate(niels, gather_idx, gather_neg)
        win_sums = _bucket_reduce(acc, weights)
    msm = _window_combine(win_sums)
    total = C.add(msm, C.fixed_base(c_digits))
    ok_eq = C.is_identity(C.mul8(total))[0]
    ok_points = jnp.all(ok_a | ~live) & jnp.all(ok_r | ~live)
    return ok_eq & ok_points


rlc_verify_jit = jax.jit(rlc_verify)


def rlc_verify_stream(a_bytes, r_bytes, live, stream, stream_neg, counts,
                      weights, c_digits, *, s_rounds: int):
    """rlc_verify over the compact wire format: the (S, WK) table is
    expanded on device (expand_stream) from the dense contribution
    stream, so the host->device link carries ~2 B/contribution."""
    gather_idx, gather_neg = expand_stream(
        stream, stream_neg, counts, s_rounds
    )
    return rlc_verify(a_bytes, r_bytes, live, gather_idx, gather_neg,
                      weights, c_digits)


rlc_verify_stream_jit = jax.jit(
    rlc_verify_stream, static_argnames=("s_rounds",)
)
