"""Batched arithmetic mod L (the ed25519 group order) on device.

L = 2^252 + 27742317777372353535851937790883648493. Three jobs, all
vectorized over the signature batch with no host round-trips:

- `reduce512`: SHA-512 digests (512-bit little-endian) -> canonical
  scalars < L via Barrett reduction (HAC Alg 14.42) in base-2^12 limbs.
- `recode_signed`: scalar -> 64 signed radix-16 digits in [-8, 7] for the
  windowed ladder, via the add-0x888...8 trick (adding 8 to every nibble
  with full carry propagation turns unsigned nibbles into signed digits).
- `lt_l`: the ZIP-215 "reject S >= L" range check as a borrow chain.

Behavior parity: the reference's scalar handling lives inside
curve25519-voi (reference: crypto/ed25519/ed25519.go:13 imports); the
Barrett/limb formulation here is an original TPU design sharing the 12-bit
limb machinery of ops/field.py.

Carry discipline: Barrett needs *exact* limb values (digits feed floor/
compare steps), so after each convolution we run a few parallel masking
rounds to shrink carries, then one sequential ripple pass for exactness.
Sequential passes are O(nlimbs) scalar steps over (B,) vectors — cheap
relative to the curve ladder, and only ~4 of them run per signature.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import field as F

BITS = F.BITS
MASK = F.MASK

L_INT = 2**252 + 27742317777372353535851937790883648493
HALF_INT = int("8" * 64, 16)  # 0x888...8: adds 8 to each of 64 nibbles

_K = 22  # L occupies 22 base-2^12 limbs (bit 252 lives in limb 21)
_MU_INT = (1 << (BITS * 2 * _K)) // L_INT  # floor(b^44 / L), 23 limbs


def _to_limbs(x: int, n: int) -> np.ndarray:
    return np.array([(x >> (BITS * i)) & MASK for i in range(n)], np.int32)


L_LIMBS = jnp.asarray(_to_limbs(L_INT, _K)[:, None])
_MU_LIMBS = jnp.asarray(_to_limbs(_MU_INT, 23)[:, None])
_HALF_LIMBS = jnp.asarray(_to_limbs(HALF_INT, _K)[:, None])


def bytes_to_limbs(b, nlimbs: int):
    """(B, nbytes) uint8 little-endian -> (nlimbs, B) int32 12-bit limbs."""
    b = b.astype(jnp.int32)
    pad = jnp.zeros(b.shape[:-1] + (1,), jnp.int32)
    padded = jnp.concatenate([b, pad], axis=-1)
    nbytes = b.shape[-1]
    limbs = []
    for j in range(nlimbs):
        bit = BITS * j
        sb = bit // 8
        if sb >= nbytes:
            limbs.append(jnp.zeros(b.shape[:-1], jnp.int32))
            continue
        shift = bit % 8
        v = padded[..., sb] >> shift
        if sb + 1 <= nbytes:
            v = v | (padded[..., min(sb + 1, nbytes)] << (8 - shift))
        limbs.append(v & MASK)
    return jnp.stack(limbs)


def _canon(x, extra_rounds: int = 2):
    """Exact canonicalization: limbs in [0, 2^12), value preserved.

    A few parallel rounds shrink carries to <= 1, then one unrolled
    sequential ripple finishes exactly. Input limbs must be >= 0.
    The final carry out of the top limb is returned as (1, B) (callers for
    which it must be zero assert statically via value bounds). Rows stay
    2D (kernel-safe: no stack/scatter).
    """
    for _ in range(extra_rounds):
        m = x & MASK
        hi = x >> BITS
        x = m + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    out = []
    c = jnp.zeros_like(x[0:1])
    for j in range(x.shape[0]):
        t = x[j : j + 1] + c
        out.append(t & MASK)
        c = t >> BITS
    return jnp.concatenate(out, axis=0), c


def _conv(a, b):
    """Plain (no modular fold) limb convolution: (n,B) x (m,B) -> (n+m-1,B).

    Shifted-row form (n full-width MACs) — small traced graph, VPU-shaped.
    """
    n, m = a.shape[0], b.shape[0]
    wide = n + m - 1
    batch = a.shape[1]
    t = jnp.zeros((wide, batch), jnp.int32)
    for i in range(n):
        rows = a[i][None, :] * b
        t = t + jnp.concatenate(
            [
                jnp.zeros((i, batch), jnp.int32),
                rows,
                jnp.zeros((wide - m - i, batch), jnp.int32),
            ],
            axis=0,
        )
    return t


def _sub_borrow(a, b):
    """a - b limbwise with sequential borrow. Returns (diff, borrow (1,B)).

    a, b canonical limbs of equal length; diff is the base-2^12 two's
    complement result (i.e. a - b mod b^n), borrow_out is 1 where a < b.
    """
    out = []
    c = jnp.zeros_like(a[0:1])
    for j in range(a.shape[0]):
        t = a[j : j + 1] - b[j : j + 1] - c
        out.append(t & MASK)
        c = (t >> BITS) & 1  # arithmetic shift of negative -> -1; mask to 1
    return jnp.concatenate(out, axis=0), c


def reduce512(digest_bytes):
    """(B, 64) uint8 little-endian 512-bit values -> (22, B) canonical < L."""
    x = bytes_to_limbs(digest_bytes, 43)  # already canonical
    q1 = x[_K - 1:]  # floor(x / b^21): 22 limbs
    q2 = _conv(q1, jnp.broadcast_to(_MU_LIMBS, (23, x.shape[1])))
    # q1*mu < b^45: one extra row absorbs the conv carries (parallel canon
    # rounds shift carries up one row and would drop the top one).
    q2 = jnp.concatenate([q2, jnp.zeros((1, q2.shape[1]), jnp.int32)], axis=0)
    q2, _ = _canon(q2)
    q3 = q2[_K + 1:]  # floor(q2 / b^23)
    r2 = _conv(q3, jnp.broadcast_to(L_LIMBS, (_K, x.shape[1])))[: _K + 1]
    r2, _ = _canon(r2)
    r1 = x[: _K + 1]
    r, _ = _sub_borrow(r1, r2)  # r >= 0 mathematically; borrow ignored
    lpad = jnp.concatenate(
        [jnp.broadcast_to(L_LIMBS, (_K, r.shape[1])),
         jnp.zeros((1, r.shape[1]), jnp.int32)], axis=0)
    for _ in range(2):  # Barrett leaves r < 3L
        d, borrow = _sub_borrow(r, lpad)
        r = jnp.where(borrow == 0, d, r)
    return r[:_K]


def lt_l(s_bytes):
    """(B, 32) uint8 little-endian -> bool (B,): value < L (ZIP-215 S check)."""
    s = bytes_to_limbs(s_bytes, _K)
    _, borrow = _sub_borrow(s, jnp.broadcast_to(L_LIMBS, s.shape))
    return (borrow == 1)[0]


def recode_signed(limbs):
    """Canonical (22, B) scalar < 2^255 -> (64, B) int32 digits in [-8, 7].

    value = sum_i digit_i * 16^i. Implemented by adding 0x888...8 (with a
    full carry ripple) and subtracting 8 from every resulting nibble.
    """
    t = limbs + _HALF_LIMBS
    t, _ = _canon(t, extra_rounds=0)  # sums <= 2*4095: one ripple suffices
    digits = []
    for i in range(64):
        limb, pos = divmod(4 * i, BITS)
        nib = (t[limb : limb + 1] >> pos) & 15
        digits.append(nib - 8)
    return jnp.concatenate(digits, axis=0)


def digits_from_bytes(b32):
    """(B, 32) uint8 scalar encoding -> (64, B) signed digits.

    Values >= 2^256 - HALF would overflow nibble 64; callers reject such
    lanes independently (lt_l), so garbage digits there are harmless.
    """
    return recode_signed(F.from_bytes_le(b32))
