"""Durable stores: KV abstraction, block store, state store.

Mirrors the reference's storage split (internal/store BlockStore over a
cometbft-db KV backend, internal/state state store) with Python-native
backends: in-memory dict and SQLite (single-file, transactional).
"""

from .kv import KVStore, MemKV, SqliteKV, open_kv  # noqa: F401
from .blockstore import BlockStore  # noqa: F401
from .statestore import StateStore  # noqa: F401
