"""Relational event sink (reference internal/state/indexer/sink/psql).

The reference's psql sink writes blocks, tx results, and their events
into relational tables so operators can query with plain SQL instead of
the node's query language. This is that sink over sqlite (the database
engine this framework ships with; the schema matches the reference's
blocks / tx_results / events / attributes layout, so pointing it at
postgres later is a connection-string change, not a redesign).

Wire it like the KV indexers: EventSinkService subscribes to the event
bus and feeds the sink; or call index_block/index_tx directly (the CLI's
reindex-event can target it too).
"""

from __future__ import annotations

import sqlite3
import threading

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
    rowid INTEGER PRIMARY KEY,
    height BIGINT NOT NULL,
    chain_id TEXT NOT NULL,
    created_at TEXT NOT NULL DEFAULT (datetime('now')),
    UNIQUE (height, chain_id)
);
CREATE TABLE IF NOT EXISTS tx_results (
    rowid INTEGER PRIMARY KEY,
    block_id BIGINT NOT NULL REFERENCES blocks(rowid),
    index_in_block INTEGER NOT NULL,
    created_at TEXT NOT NULL DEFAULT (datetime('now')),
    tx_hash TEXT NOT NULL,
    tx_result BLOB NOT NULL,
    UNIQUE (block_id, index_in_block)
);
CREATE TABLE IF NOT EXISTS events (
    rowid INTEGER PRIMARY KEY,
    block_id BIGINT NOT NULL REFERENCES blocks(rowid),
    tx_id BIGINT REFERENCES tx_results(rowid),
    type TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
    event_id BIGINT NOT NULL REFERENCES events(rowid),
    key TEXT NOT NULL,
    composite_key TEXT NOT NULL,
    value TEXT
);
CREATE INDEX IF NOT EXISTS idx_attributes_composite
    ON attributes (composite_key, value);
"""


class SQLSink:
    def __init__(self, path: str = ":memory:", chain_id: str = ""):
        self.chain_id = chain_id
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._db.executescript(_SCHEMA)
            self._db.commit()

    # ------------------------------------------------------------------
    def _insert_events(self, cur, block_rowid, tx_rowid, events: dict):
        """events: composite "type.key" -> [values] (the bus's shape)."""
        by_type: dict[str, list[tuple[str, str, str]]] = {}
        for composite, values in (events or {}).items():
            etype, _, key = composite.rpartition(".")
            if not etype:
                etype = composite
            for v in values:
                by_type.setdefault(etype, []).append((key, composite, v))
        for etype, attrs in by_type.items():
            cur.execute(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                (block_rowid, tx_rowid, etype),
            )
            eid = cur.lastrowid
            cur.executemany(
                "INSERT INTO attributes (event_id, key, composite_key, value)"
                " VALUES (?, ?, ?, ?)",
                [(eid, k, ck, v) for k, ck, v in attrs],
            )

    def index_block(self, height: int, events: dict | None = None) -> None:
        with self._lock:
            cur = self._db.cursor()
            cur.execute(
                "INSERT OR IGNORE INTO blocks (height, chain_id)"
                " VALUES (?, ?)",
                (height, self.chain_id),
            )
            cur.execute(
                "SELECT rowid FROM blocks WHERE height=? AND chain_id=?",
                (height, self.chain_id),
            )
            block_rowid = cur.fetchone()[0]
            self._insert_events(cur, block_rowid, None, events or {})
            self._db.commit()

    def index_tx(self, height: int, index: int, tx_hash: bytes,
                 tx_result: bytes, events: dict | None = None) -> None:
        with self._lock:
            cur = self._db.cursor()
            cur.execute(
                "INSERT OR IGNORE INTO blocks (height, chain_id)"
                " VALUES (?, ?)",
                (height, self.chain_id),
            )
            cur.execute(
                "SELECT rowid FROM blocks WHERE height=? AND chain_id=?",
                (height, self.chain_id),
            )
            block_rowid = cur.fetchone()[0]
            # re-indexing the same (block, index): drop the old row's
            # dependent events/attributes first — INSERT OR REPLACE
            # assigns a fresh rowid, which would leave them dangling and
            # duplicate event rows on every reindex
            cur.execute(
                "SELECT rowid FROM tx_results"
                " WHERE block_id=? AND index_in_block=?",
                (block_rowid, index),
            )
            old = cur.fetchone()
            if old is not None:
                cur.execute(
                    "DELETE FROM attributes WHERE event_id IN"
                    " (SELECT rowid FROM events WHERE tx_id=?)",
                    (old[0],),
                )
                cur.execute("DELETE FROM events WHERE tx_id=?", (old[0],))
                cur.execute(
                    "DELETE FROM tx_results WHERE rowid=?", (old[0],)
                )
            cur.execute(
                "INSERT INTO tx_results"
                " (block_id, index_in_block, tx_hash, tx_result)"
                " VALUES (?, ?, ?, ?)",
                (block_rowid, index, tx_hash.hex().upper(), tx_result),
            )
            tx_rowid = cur.lastrowid
            self._insert_events(cur, block_rowid, tx_rowid, events or {})
            self._db.commit()

    # ------------------------------------------------------------------
    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        """Read-only SQL access (the sink's whole point). Writes are
        rejected via the sqlite authorizer for the duration of the call
        — operator dashboards get SELECT, not a mutation side door."""
        def _authorize(action, *_):
            if action in (sqlite3.SQLITE_SELECT, sqlite3.SQLITE_READ,
                          sqlite3.SQLITE_FUNCTION,
                          sqlite3.SQLITE_RECURSIVE):
                return sqlite3.SQLITE_OK
            return sqlite3.SQLITE_DENY

        with self._lock:
            self._db.set_authorizer(_authorize)
            try:
                return list(self._db.execute(sql, params))
            finally:
                # restore with an explicit allow-all: on some sqlite
                # builds set_authorizer(None) leaves the deny callback
                # installed and every later write fails "not authorized"
                self._db.set_authorizer(lambda *a: sqlite3.SQLITE_OK)

    def close(self) -> None:
        with self._lock:
            self._db.close()
