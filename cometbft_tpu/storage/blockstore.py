"""Block store: heights -> blocks, commits, seen-commits.

Behavior parity with reference internal/store/store.go:42 (BlockStore):
SaveBlock persists the block, its commit (the canonical +2/3 for
height-1... stored per height), and the "seen commit" used to propose the
next block; base/height track the retained range; Prune deletes below a
retain height (reference :309).
"""

from __future__ import annotations

import threading

from ..encoding import proto as pb
from ..types import Block, Commit
from ..types.agg_commit import decode_commit_any
from ..utils.metrics import store_metrics
from .kv import KVStore


def _key_block(h: int) -> bytes:
    return b"B:" + h.to_bytes(8, "big")


def _key_commit(h: int) -> bytes:
    return b"C:" + h.to_bytes(8, "big")


def _key_seen_commit(h: int) -> bytes:
    return b"SC:" + h.to_bytes(8, "big")


def _key_block_hash(block_hash: bytes) -> bytes:
    return b"BH:" + block_hash


def _key_height_hash(h: int) -> bytes:
    return b"HH:" + h.to_bytes(8, "big")


def _key_ext_commit(h: int) -> bytes:
    return b"EC:" + h.to_bytes(8, "big")


def _key_full_seen_commit(h: int) -> bytes:
    # full signature column retained beside a certificate-native seen
    # commit, recent heights only (evidence window; ISSUE 17)
    return b"SCF:" + h.to_bytes(8, "big")


_KEY_STATE = b"BS:state"


class BlockStore:
    # Full seen-commit columns are kept only this many recent heights
    # when the canonical seen commit is certificate-native: evidence for
    # older heights is already outside the evidence params' max window
    # in practice, and the certificate remains verifiable forever.
    DEFAULT_FULL_COMMIT_WINDOW = 64

    def __init__(self, db: KVStore, full_commit_window: int | None = None):
        self._db = db
        self._lock = threading.RLock()
        self._base = 0
        self._height = 0
        self.full_commit_window = (
            self.DEFAULT_FULL_COMMIT_WINDOW
            if full_commit_window is None else full_commit_window
        )
        raw = db.get(_KEY_STATE)
        if raw:
            d = pb.fields_to_dict(raw)
            self._base = pb.to_i64(d.get(1, 0))
            self._height = pb.to_i64(d.get(2, 0))

    def base(self) -> int:
        with self._lock:
            return self._base

    def height(self) -> int:
        with self._lock:
            return self._height

    def size(self) -> int:
        with self._lock:
            return 0 if self._height == 0 else self._height - self._base + 1

    def _save_meta(self, sets):
        payload = pb.f_varint(1, self._base) + pb.f_varint(2, self._height)
        sets.append((_KEY_STATE, payload))

    def save_block(self, block: Block, seen_commit: Commit,
                   full_seen_commit: Commit | None = None) -> None:
        h = block.header.height
        with self._lock:
            if self._height and h != self._height + 1:
                raise ValueError(
                    f"non-contiguous save: have {self._height}, got {h}"
                )
            seen_enc = seen_commit.encode()
            sets = [
                (_key_block(h), block.encode()),
                (_key_seen_commit(h), seen_enc),
                (_key_block_hash(block.hash()), h.to_bytes(8, "big")),
                (_key_height_hash(h), block.hash()),
            ]
            deletes: list[bytes] = []
            if full_seen_commit is not None:
                # certificate took the canonical slot: keep the full
                # column in the recent evidence window only
                sets.append(
                    (_key_full_seen_commit(h), full_seen_commit.encode())
                )
                if h - self.full_commit_window >= 1:
                    deletes.append(
                        _key_full_seen_commit(h - self.full_commit_window)
                    )
            if block.last_commit is not None and h > 1:
                canonical = block.last_commit.encode()
                sets.append((_key_commit(h - 1), canonical))
                store_metrics().commit_bytes.observe(len(canonical))
            else:
                store_metrics().commit_bytes.observe(len(seen_enc))
            self._height = h
            if self._base == 0:
                self._base = h
            self._save_meta(sets)
            self._db.write_batch(sets, deletes)

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        """Store a commit without its block — the state-sync bootstrap
        (reference store.go SaveSeenCommit): after a snapshot restore the
        node holds the light-verified commit at the restore height but no
        block, and block sync verifies H+1 against it. Also anchors
        base/height so blocksync resumes from the restore point."""
        with self._lock:
            sets = [(_key_seen_commit(height), commit.encode())]
            if self._height == 0:
                self._base = height
                self._height = height
                self._save_meta(sets)
            self._db.write_batch(sets)

    def load_block(self, height: int) -> Block | None:
        raw = self._db.get(_key_block(height))
        # our own stored bytes are canonical by construction: stash them
        # so BlockID/part-set work skips the re-encode
        return Block.decode(raw, trusted_bytes=True) if raw else None

    def load_block_meta(self, height: int) -> tuple[Block, int] | None:
        """(block, wire size) without a re-encode — the stored bytes'
        length IS the canonical size (reference store.go LoadBlockMeta
        serves BlockMeta.BlockSize the same way)."""
        raw = self._db.get(_key_block(height))
        if not raw:
            return None
        return Block.decode(raw, trusted_bytes=True), len(raw)

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        """O(1) via the hash→height index written at save time
        (reference internal/store/store.go LoadBlockByHash)."""
        raw = self._db.get(_key_block_hash(block_hash))
        if not raw:
            return None
        return self.load_block(int.from_bytes(raw, "big"))

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical commit FOR `height` (stored with block height+1).

        ONE read path for both store generations (ISSUE 17): pre-
        certificate stores hold plain signature columns, cert-native
        stores hold CertCommits — decode_commit_any routes on the bytes.
        """
        raw = self._db.get(_key_commit(height))
        return decode_commit_any(raw, trusted_bytes=True) if raw else None

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self._db.get(_key_seen_commit(height))
        return decode_commit_any(raw, trusted_bytes=True) if raw else None

    def load_seen_commit_full(self, height: int) -> Commit | None:
        """The full signature column for `height` when still inside the
        evidence window — falls back to the seen commit itself when that
        already IS a full column (non-BLS chains, pre-cert stores)."""
        raw = self._db.get(_key_full_seen_commit(height))
        if raw:
            return Commit.decode(raw, trusted_bytes=True)
        seen = self.load_seen_commit(height)
        if seen is not None and getattr(seen, "cert", None) is not None:
            return None  # aggregated away and outside the window
        return seen

    def save_extended_commit(self, ext_commit) -> None:
        """Seen commit WITH vote extensions (reference SaveBlockWithExtendedCommit
        :262) — kept per height while extensions are enabled."""
        self._db.set(_key_ext_commit(ext_commit.height), ext_commit.encode())

    def load_extended_commit(self, height: int):
        from ..types.extended_commit import ExtendedCommit

        raw = self._db.get(_key_ext_commit(height))
        return ExtendedCommit.decode(raw) if raw else None

    def delete_latest_block(self) -> None:
        """Remove the top block (rollback support; reference
        internal/store/store.go DeleteLatestBlock)."""
        with self._lock:
            if self._height == 0:
                raise ValueError("block store is empty")
            h = self._height
            deletes = [_key_block(h), _key_seen_commit(h),
                       _key_full_seen_commit(h),
                       _key_commit(h - 1), _key_height_hash(h)]
            bh = self._db.get(_key_height_hash(h))
            if bh:
                deletes.append(_key_block_hash(bh))
            self._height = h - 1
            if self._height < self._base:
                self._base = self._height
            sets: list = []
            self._save_meta(sets)
            self._db.write_batch(sets, deletes)

    def prune(self, retain_height: int) -> int:
        """Delete blocks below retain_height; returns number pruned
        (reference internal/store/store.go:309)."""
        with self._lock:
            if retain_height <= self._base:
                return 0
            if retain_height > self._height + 1:
                raise ValueError("cannot prune beyond store height + 1")
            deletes = []
            pruned = 0
            for h in range(self._base, retain_height):
                # the HH entry gives the block hash without a decode
                bh = self._db.get(_key_height_hash(h))
                if bh:
                    deletes.append(_key_block_hash(bh))
                deletes += [_key_block(h), _key_commit(h),
                            _key_seen_commit(h), _key_full_seen_commit(h),
                            _key_height_hash(h), _key_ext_commit(h)]
                pruned += 1
            self._base = retain_height
            sets: list = []
            self._save_meta(sets)
            self._db.write_batch(sets, deletes)
            return pruned
