"""Key-value store abstraction (the cometbft-db seam, reference go.mod:47).

Backends: MemKV (dict, tests) and SqliteKV (single-file, batched writes).
Keys and values are bytes; iteration is byte-ordered over a prefix.
"""

from __future__ import annotations

import sqlite3
import threading
from abc import ABC, abstractmethod
from typing import Iterator


class KVStore(ABC):
    @abstractmethod
    def get(self, key: bytes) -> bytes | None: ...

    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abstractmethod
    def iterate_prefix(self, prefix: bytes) -> Iterator[tuple[bytes, bytes]]: ...

    @abstractmethod
    def write_batch(self, sets: list[tuple[bytes, bytes]], deletes: list[bytes] = ()) -> None: ...

    @abstractmethod
    def close(self) -> None: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None


class MemKV(KVStore):
    def __init__(self):
        self._d: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            return self._d.get(key)

    def set(self, key, value):
        with self._lock:
            self._d[bytes(key)] = bytes(value)

    def delete(self, key):
        with self._lock:
            self._d.pop(key, None)

    def iterate_prefix(self, prefix):
        with self._lock:
            keys = sorted(k for k in self._d if k.startswith(prefix))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def write_batch(self, sets, deletes=()):
        with self._lock:
            for k, v in sets:
                self._d[bytes(k)] = bytes(v)
            for k in deletes:
                self._d.pop(k, None)

    def close(self):
        pass


class SqliteKV(KVStore):
    """Single-table SQLite KV; WAL mode for concurrent readers."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key):
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value),
            )
            self._conn.commit()

    def delete(self, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def iterate_prefix(self, prefix):
        # upper bound = prefix with its last non-0xff byte incremented
        # (exclusive): a suffix-based bound like prefix+b"\xff"*N would
        # silently exclude keys extending further than N bytes
        hi = None
        p = bytearray(prefix)
        for i in range(len(p) - 1, -1, -1):
            if p[i] != 0xFF:
                p[i] += 1
                hi = bytes(p[: i + 1])
                break
        with self._lock:
            if hi is None:  # all-0xff (or empty) prefix: no upper bound
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (prefix,)
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                    (prefix, hi),
                ).fetchall()
        for k, v in rows:
            if bytes(k).startswith(prefix):
                yield bytes(k), bytes(v)

    def write_batch(self, sets, deletes=()):
        with self._lock:
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                [(k, v) for k, v in sets],
            )
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k = ?", [(k,) for k in deletes])
            self._conn.commit()

    def close(self):
        with self._lock:
            self._conn.close()


def open_kv(path: str | None) -> KVStore:
    """None/':memory:' -> MemKV; otherwise SQLite at path."""
    if path in (None, ":memory:"):
        return MemKV()
    return SqliteKV(path)
