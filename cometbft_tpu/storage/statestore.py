"""State store: current state + per-height validator sets and ABCI results.

Behavior parity with reference internal/state/store.go:132: validators are
saved per height so light/evidence verification can look back; finalize
responses are saved for last_results_hash and reindexing; pruning removes
old heights (reference :297).
"""

from __future__ import annotations

from ..encoding import proto as pb
from .kv import KVStore

_KEY_STATE = b"S:cur"


def _key_vals(h: int) -> bytes:
    return b"SV:" + h.to_bytes(8, "big")


def _key_abci(h: int) -> bytes:
    return b"SA:" + h.to_bytes(8, "big")


def _key_params(h: int) -> bytes:
    return b"SP:" + h.to_bytes(8, "big")


class StateStore:
    def __init__(self, db: KVStore):
        self._db = db

    def save(self, state) -> None:
        from ..state.types import encode_validator_set

        # `validators` is the set for the NEXT height to commit; at genesis
        # (last_block_height == 0) that is initial_height, not 1 (reference
        # internal/state/store.go Bootstrap vs save split).
        next_height = max(state.last_block_height + 1, state.initial_height)
        sets = [(_KEY_STATE, state.encode())]
        # params used to validate block `next_height` (reference
        # internal/state/store.go saveConsensusParamsInfo)
        from ..state.types import encode_params

        sets.append((_key_params(next_height), encode_params(state.consensus_params)))
        if state.next_validators is not None:
            sets.append(
                (
                    _key_vals(next_height + 1),
                    encode_validator_set(state.next_validators),
                )
            )
        if state.validators is not None:
            sets.append(
                (_key_vals(next_height), encode_validator_set(state.validators))
            )
        self._db.write_batch(sets)

    def load(self):
        from ..state.types import State

        raw = self._db.get(_KEY_STATE)
        return State.decode(raw) if raw else None

    def load_consensus_params(self, height: int):
        """Params as of validating block `height`, or None if unsaved
        (reference internal/state/store.go LoadConsensusParams)."""
        from ..state.types import decode_params

        raw = self._db.get(_key_params(height))
        return decode_params(raw) if raw else None

    def load_validators(self, height: int):
        from ..state.types import decode_validator_set

        raw = self._db.get(_key_vals(height))
        return decode_validator_set(raw) if raw else None

    def save_finalize_response(self, height: int, payload: bytes) -> None:
        self._db.set(_key_abci(height), payload)

    def load_finalize_response(self, height: int) -> bytes | None:
        return self._db.get(_key_abci(height))

    def save_abci_responses(self, height: int, payload: bytes) -> None:
        """Full encoded FinalizeBlockResponse (reference
        state/store.go SaveFinalizeBlockResponse) — what reindexing and
        /block_results serve; save_finalize_response keeps only the
        results hash the header commits to."""
        self._db.set(b"AR:" + height.to_bytes(8, "big"), payload)

    def load_abci_responses(self, height: int) -> bytes | None:
        return self._db.get(b"AR:" + height.to_bytes(8, "big"))

    def prune(self, retain_height: int, current_height: int) -> int:
        deletes = []
        pruned = 0
        for h in range(1, retain_height):
            if self._db.has(_key_vals(h)) or self._db.has(_key_abci(h)):
                deletes += [_key_vals(h), _key_abci(h), _key_params(h),
                            b"AR:" + h.to_bytes(8, "big")]
                pruned += 1
        if deletes:
            self._db.write_batch([], deletes)
        return pruned
