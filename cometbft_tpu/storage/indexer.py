"""Tx + block indexers over the KV store.

Behavior parity: reference internal/state/txindex/kv (tx results by hash,
composite-key search) + internal/state/indexer/block/kv (block events by
height), fed by an IndexerService subscribed to the event bus
(internal/state/txindex/indexer_service.go).
"""

from __future__ import annotations

import threading

from ..crypto.keys import tmhash
from ..encoding import proto as pb
from ..utils.pubsub import Query
from .kv import KVStore, MemKV


def _key_tx(tx_hash: bytes) -> bytes:
    return b"TX:" + tx_hash


def _key_tx_height(height: int, index: int) -> bytes:
    return b"TH:" + height.to_bytes(8, "big") + index.to_bytes(4, "big")


def _key_block_events(height: int) -> bytes:
    return b"BE:" + height.to_bytes(8, "big")


class TxIndexer:
    """reference internal/state/txindex/kv/kv.go."""

    def __init__(self, db: KVStore | None = None):
        self._db = db or MemKV()
        self._lock = threading.Lock()

    def index(self, height: int, index: int, tx: bytes, result,
              events: dict[str, list[str]] | None = None) -> None:
        h = tmhash(tx)
        payload = (
            pb.f_varint(1, height)
            + pb.f_varint(2, index)
            + pb.f_bytes(3, tx)
            + pb.f_varint(4, getattr(result, "code", 0))
            + pb.f_bytes(5, getattr(result, "data", b""))
            + pb.f_bytes(6, _encode_events(events or {}))
        )
        with self._lock:
            self._db.write_batch(
                [(_key_tx(h), payload), (_key_tx_height(height, index), h)]
            )

    def get(self, tx_hash: bytes):
        raw = self._db.get(_key_tx(tx_hash))
        if raw is None:
            return None
        d = pb.fields_to_dict(raw)
        return {
            "height": pb.to_i64(d.get(1, 0)),
            "index": pb.to_i64(d.get(2, 0)),
            "tx": pb.as_bytes(d.get(3, b"")),
            "code": int(d.get(4, 0)),
            "data": pb.as_bytes(d.get(5, b"")),
            "events": _decode_events(pb.as_bytes(d.get(6, b""))),
        }

    def search(self, query_str: str, limit: int = 100) -> list[dict]:
        """Scan-match (reference kv search over composite keys)."""
        q = Query(query_str)
        out = []
        for _, tx_hash in self._db.iterate_prefix(b"TH:"):
            rec = self.get(tx_hash)
            if rec is None:
                continue
            events = dict(rec["events"])
            events.setdefault("tx.height", [str(rec["height"])])
            events.setdefault("tx.hash", [tmhash(rec["tx"]).hex().upper()])
            if q.matches(events):
                out.append(rec)
                if len(out) >= limit:
                    break
        return out


class BlockIndexer:
    """reference internal/state/indexer/block/kv."""

    def __init__(self, db: KVStore | None = None):
        self._db = db or MemKV()

    def index(self, height: int, events: dict[str, list[str]]) -> None:
        self._db.set(_key_block_events(height), _encode_events(events))

    def search(self, query_str: str, limit: int = 100) -> list[int]:
        q = Query(query_str)
        out = []
        for key, raw in self._db.iterate_prefix(b"BE:"):
            h = int.from_bytes(key[3:11], "big")
            events = _decode_events(raw)
            events.setdefault("block.height", [str(h)])
            if q.matches(events):
                out.append(h)
                if len(out) >= limit:
                    break
        return out


class IndexerService:
    """Subscribes to the event bus and feeds both indexers
    (reference internal/state/txindex/indexer_service.go)."""

    def __init__(self, event_bus, tx_indexer: TxIndexer,
                 block_indexer: BlockIndexer):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self._bus = event_bus
        self._tx_sub = event_bus.subscribe("indexer", "tm.event = 'Tx'")
        self._block_sub = event_bus.subscribe("indexer", "tm.event = 'NewBlock'")
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from ..utils.pubsub import SubscriptionCancelled

        while not self._stopped.is_set():
            try:
                msg = self._tx_sub.next(timeout=0.1)
            except SubscriptionCancelled:
                # slow-consumer overflow: events in the gap are lost (the
                # reference drops slow subscribers too); resubscribe
                self._tx_sub = self._bus.subscribe("indexer", "tm.event = 'Tx'")
                msg = None
            if msg is not None:
                d = msg.data
                self.tx_indexer.index(
                    d["height"], d["index"], d["tx"], d["result"], msg.events
                )
            try:
                bmsg = self._block_sub.next(timeout=0.05)
            except SubscriptionCancelled:
                self._block_sub = self._bus.subscribe(
                    "indexer", "tm.event = 'NewBlock'"
                )
                bmsg = None
            if bmsg is not None:
                self.block_indexer.index(
                    bmsg.data["block"].header.height, bmsg.events
                )

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=2)


def _encode_events(events: dict[str, list[str]]) -> bytes:
    out = b""
    for k, vals in events.items():
        for v in vals:
            out += pb.f_embedded(1, pb.f_string(1, k) + pb.f_string(2, v))
    return out


def _decode_events(buf: bytes) -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for f, _, v in pb.parse_fields(buf):
        if f == 1:
            d = pb.fields_to_dict(pb.as_bytes(v))
            k = pb.as_bytes(d.get(1, b"")).decode("utf-8", "replace")
            val = pb.as_bytes(d.get(2, b"")).decode("utf-8", "replace")
            out.setdefault(k, []).append(val)
    return out
