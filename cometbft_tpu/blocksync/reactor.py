"""Block-sync reactor: serve blocks to lagging peers and catch up from
the network.

Behavior parity: reference internal/blocksync/reactor.go — channel 0x40
with BlockRequest(1)/NoBlockResponse(2)/BlockResponse(3)/
StatusRequest(4)/StatusResponse(5); the pool routine verifies block H
with block H+1's LastCommit via VerifyCommitLight (:462) — the TPU
batch path — then ApplyBlock (:511), and reports IsCaughtUp so the node
can switch to consensus (:400 SwitchToConsensus).
"""

from __future__ import annotations

import threading

from ..crypto.sched import verify_context
from ..encoding import proto as pb
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types import Block
from ..types.block import block_id_for
from ..types.validation import CommitError, verify_commit_light
from ..utils import trace
from ..utils.log import logger
from ..utils.metrics import blocksync_metrics
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
_log = logger("blocksync")


def _msg(field: int, body: bytes = b"") -> bytes:
    return pb.f_embedded(field, body)


def encode_block_request(height: int) -> bytes:
    return _msg(1, pb.f_varint(1, height))


def encode_no_block(height: int) -> bytes:
    return _msg(2, pb.f_varint(1, height))


def encode_block_response(block: Block) -> bytes:
    return _msg(3, pb.f_embedded(1, block.encode()))


def encode_status_request() -> bytes:
    return _msg(4)


def encode_status_response(height: int, base: int) -> bytes:
    return _msg(5, pb.f_varint(1, height) + pb.f_varint(2, base))


class BlockSyncReactor(Reactor):
    def __init__(self, block_store, executor=None, state=None,
                 backend: str = "tpu"):
        """Serving side always works off block_store; the syncing side
        (pool routine) activates via sync() with an executor + state."""
        self.store = block_store
        self.executor = executor
        self.state = state
        self.backend = backend
        self.sched = None  # shared VerifyScheduler (crypto/sched.py)
        self.tenant = ""
        self.pool: BlockPool | None = None
        self._peers: dict[str, object] = {}
        self._lock = threading.Lock()
        self.on_caught_up = None  # callback(state) — SwitchToConsensus seam

    # -- Reactor interface -------------------------------------------------
    def channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=BLOCKSYNC_CHANNEL, priority=5)]

    def add_peer(self, peer) -> None:
        with self._lock:
            self._peers[peer.id] = peer
        peer.send(
            BLOCKSYNC_CHANNEL,
            encode_status_response(self.store.height(), self.store.base()),
        )
        peer.send(BLOCKSYNC_CHANNEL, encode_status_request())

    def remove_peer(self, peer, reason) -> None:
        with self._lock:
            self._peers.pop(peer.id, None)
        if self.pool is not None:
            self.pool.remove_peer(peer.id)

    def receive(self, chan_id: int, peer, raw: bytes) -> None:
        d = pb.fields_to_dict(raw)
        if 1 in d:  # BlockRequest
            h = pb.to_i64(pb.fields_to_dict(pb.as_bytes(d[1])).get(1, 0))
            blk = self.store.load_block(h)
            if blk is None:
                peer.send(BLOCKSYNC_CHANNEL, encode_no_block(h))
            else:
                peer.send(BLOCKSYNC_CHANNEL, encode_block_response(blk))
        elif 3 in d:  # BlockResponse
            if self.pool is not None:
                inner = pb.fields_to_dict(pb.as_bytes(d[3]))
                try:
                    blk = Block.decode(pb.as_bytes(inner.get(1, b"")))
                except Exception:  # noqa: BLE001 — malformed: drop
                    return
                self.pool.add_block(peer.id, blk)
        elif 4 in d:  # StatusRequest
            peer.send(
                BLOCKSYNC_CHANNEL,
                encode_status_response(self.store.height(), self.store.base()),
            )
        elif 5 in d:  # StatusResponse
            if self.pool is not None:
                f = pb.fields_to_dict(pb.as_bytes(d[5]))
                self.pool.set_peer_range(
                    peer.id, pb.to_i64(f.get(2, 0)) or 1, pb.to_i64(f.get(1, 0))
                )

    # -- syncing side ------------------------------------------------------
    def _send_request(self, peer_id: str, height: int) -> None:
        with self._lock:
            peer = self._peers.get(peer_id)
        if peer is not None:
            peer.send(BLOCKSYNC_CHANNEL, encode_block_request(height))

    def sync(self, timeout_s: float = 60.0, poll_s: float = 0.05):
        """Catch up from peers until caught up or timeout; returns the
        post-sync state (reference poolRoutine)."""
        import time as _time

        assert self.executor is not None and self.state is not None
        state = self.state
        self.pool = BlockPool(state.last_block_height + 1, self._send_request)
        # learn peer ranges
        with self._lock:
            peers = list(self._peers.values())
        _log.debug("block sync starting", from_height=state.last_block_height + 1,
                   peers=len(peers))
        for p in peers:
            p.send(BLOCKSYNC_CHANNEL, encode_status_request())
        start = _time.monotonic()
        deadline = start + timeout_s
        applied = 0
        m = blocksync_metrics()
        m.syncing.set(1)
        while _time.monotonic() < deadline:
            self.pool.make_requests()
            first, second = self.pool.peek_two_blocks()
            if first is None or second is None:
                if self.pool.is_caught_up():
                    break  # nothing (more) to fetch
                if (self.pool.max_peer_height() == 0
                        and _time.monotonic() - start > 3.0):
                    _log.debug("block sync: no peer reported a range")
                    break  # no peer ever reported a range
                self.pool.wait_for_blocks(poll_s)
                continue
            bid = block_id_for(first)
            t_fetch = _time.perf_counter()
            try:
                # block H is endorsed by H+1's LastCommit — the batch
                # verify hot path (reference reactor.go:462)
                with verify_context(self.sched, self.tenant, "blocksync"):
                    verify_commit_light(
                        state.chain_id,
                        state.validators,
                        bid,
                        first.header.height,
                        second.last_commit,
                        backend=self.backend,
                    )
            except CommitError as e:
                bad = self.pool.redo_request(first.header.height)
                m.bad_blocks_total.inc()
                _log.warn("invalid block from peer", height=first.header.height,
                          peer=(bad or "?")[:12], err=str(e)[:80])
                continue
            t_verify = _time.perf_counter()
            state = self.executor.apply_block(state, bid, first)
            self.store.save_block(first, second.last_commit)
            self.pool.pop_request()
            applied += 1
            m.blocks_applied_total.inc()
            m.latest_block_height.set(first.header.height)
            if trace.enabled:
                t_apply = _time.perf_counter()
                trace.emit(
                    "blocksync.block", "span",
                    height=first.header.height,
                    dur_ms=round((t_apply - t_fetch) * 1e3, 3),
                    verify_ms=round((t_verify - t_fetch) * 1e3, 3),
                    apply_ms=round((t_apply - t_verify) * 1e3, 3),
                )
        m.syncing.set(0)
        self.state = state
        _log.debug("block sync done", applied=applied,
                   height=state.last_block_height)
        if self.on_caught_up is not None:
            self.on_caught_up(state)
        return state
