"""Block sync: catch-up replay of stored/fetched chains."""

from .replay import ReplayEngine  # noqa: F401
