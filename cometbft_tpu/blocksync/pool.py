"""Block pool: pipelined block fetching across peers.

Behavior parity: reference internal/blocksync/pool.go — per-height
requesters fan out across reporting peers up to a request window;
arrived blocks queue for the apply loop, which always inspects TWO
consecutive blocks (PeekTwoBlocks :196) because block H is verified
with block H+1's LastCommit; PopRequest (:213) advances, RedoRequest
(:236) re-queues a height whose block failed verification and demotes
the sender. Peers report their (base, height) via status messages.
"""

from __future__ import annotations

import threading
import time

from ..utils.metrics import blocksync_metrics

REQUEST_WINDOW = 64       # in-flight heights (reference maxPendingRequests)
RETRY_SECONDS = 5.0       # per-height fetch timeout before trying a new peer


class _Requester:
    __slots__ = ("height", "peer_id", "block", "sent_at")

    def __init__(self, height: int):
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.sent_at = 0.0


class BlockPool:
    def __init__(self, start_height: int, send_request):
        """send_request(peer_id, height) dispatches a BlockRequest (the
        reactor provides it); start_height is the first height wanted."""
        self._lock = threading.Condition()
        self._send = send_request
        self.height = start_height          # next height the applier needs
        self._requesters: dict[int, _Requester] = {}
        self._peers: dict[str, tuple[int, int]] = {}  # id -> (base, height)
        self._stopped = False

    # -- peer management --------------------------------------------------
    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        with self._lock:
            self._peers[peer_id] = (base, height)
            m = blocksync_metrics()
            m.peer_height.set(height, peer_id)
            m.num_peers.set(len(self._peers))
            self._lock.notify_all()

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            if self._peers.pop(peer_id, None) is not None:
                m = blocksync_metrics()
                m.peer_height.remove(peer_id)
                m.num_peers.set(len(self._peers))
            for r in self._requesters.values():
                if r.peer_id == peer_id and r.block is None:
                    r.peer_id = None  # refetch from someone else

    def max_peer_height(self) -> int:
        with self._lock:
            return max((h for _, h in self._peers.values()), default=0)

    def is_caught_up(self) -> bool:
        with self._lock:
            best = max((h for _, h in self._peers.values()), default=0)
            return bool(self._peers) and self.height >= best

    # -- fetch scheduling --------------------------------------------------
    def make_requests(self) -> None:
        """Ensure a requester exists (and is assigned) for every height in
        the window; reassign timed-out fetches (reference
        makeRequestersRoutine + requester retry loop)."""
        now = time.monotonic()
        with self._lock:
            best = max((h for _, h in self._peers.values()), default=0)
            top = min(self.height + REQUEST_WINDOW, best)
            for h in range(self.height, top + 1):
                if h not in self._requesters:
                    self._requesters[h] = _Requester(h)
            sends = []
            for r in self._requesters.values():
                if r.block is not None:
                    continue
                if r.peer_id is not None and now - r.sent_at < RETRY_SECONDS:
                    continue
                peer = self._pick_peer(r.height, exclude=r.peer_id)
                if peer is None:
                    continue
                r.peer_id = peer
                r.sent_at = now
                sends.append((peer, r.height))
            blocksync_metrics().pending_requests.set(
                sum(1 for r in self._requesters.values() if r.block is None)
            )
        for peer, h in sends:
            self._send(peer, h)

    def _pick_peer(self, height: int, exclude: str | None) -> str | None:
        candidates = [
            pid for pid, (base, top) in self._peers.items()
            if base <= height <= top and pid != exclude
        ]
        if not candidates:
            # only the excluded peer has it: allow retrying it
            candidates = [
                pid for pid, (base, top) in self._peers.items()
                if base <= height <= top
            ]
        if not candidates:
            return None
        return candidates[height % len(candidates)]

    # -- block arrival / consumption ---------------------------------------
    def add_block(self, peer_id: str, block) -> bool:
        with self._lock:
            r = self._requesters.get(block.header.height)
            if r is None or r.block is not None:
                return False
            if r.peer_id != peer_id:
                return False  # unsolicited (reference drops + punishes)
            r.block = block
            self._lock.notify_all()
            return True

    def peek_two_blocks(self):
        """(block[height], block[height+1]) or (None, None-ish) if not
        both present yet."""
        with self._lock:
            first = self._requesters.get(self.height)
            second = self._requesters.get(self.height + 1)
            return (
                first.block if first else None,
                second.block if second else None,
            )

    def pop_request(self) -> None:
        """Height verified + applied: advance."""
        with self._lock:
            self._requesters.pop(self.height, None)
            self.height += 1

    def redo_request(self, height: int) -> str | None:
        """Block at `height` failed verification: drop it (and the next —
        its commit came from the same pipeline) and refetch; returns the
        peer that served the bad block (caller punishes)."""
        with self._lock:
            bad_peer = None
            for h in (height, height + 1):
                r = self._requesters.get(h)
                if r is None:
                    continue
                if h == height:
                    bad_peer = r.peer_id
                r.block = None
                r.peer_id = None
            if bad_peer is not None:
                self._peers.pop(bad_peer, None)
            return bad_peer

    def wait_for_blocks(self, timeout: float) -> None:
        with self._lock:
            first = self._requesters.get(self.height)
            second = self._requesters.get(self.height + 1)
            if first and first.block and second and second.block:
                return
            self._lock.wait(timeout)
