"""Chain replay: the block-sync apply loop (north-star workload #4).

Behavior parity with reference internal/blocksync/reactor.go:425-517: each
block is verified with the *next* block's LastCommit via VerifyCommitLight,
then applied through ABCI. Per-block that is one sig-verify-bound batch +
one FinalizeBlock round trip — the loop the TPU data plane must cut >=5x.

TPU-first design: instead of one device dispatch per height (the
reference's per-block CGo batch call), `window` heights of commit
signatures are packed into ONE mega-batch (10k+ lanes) and verified in a
single kernel launch while the host applies previously-verified blocks —
commit size no longer bounds device utilization (SURVEY §5.7's "sequence
length" analogue: batch across heights, not just within a commit).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..crypto import ed25519
from ..state.execution import BlockExecutor, BlockValidationError, validate_block
from ..storage import BlockStore
from ..types import Commit
from ..types.block import block_id_for
from ..types.validation import (
    CertCommitVerifier,
    CommitError,
    ErrInvalidSignature,
    ErrNotEnoughVotingPower,
)
from ..utils.metrics import blocksync_metrics


class _WindowPending:
    """Joined handle for one window's verification work: the ed25519
    mega-batch plus the certificate-native commits' one-pairing checks
    (ISSUE 17). Certificates never enter the signature mega-batch — each
    is a single pairing regardless of signer count."""

    def __init__(self, ed_pending, cert_checks):
        self.ed = ed_pending  # ed25519 pending | None (all-cert window)
        self.certs = cert_checks  # [(height, CertCommitVerifier, pending)]

    def prefetch(self):
        if self.ed is not None:
            self.ed.prefetch()

    def result(self):
        """(ok, bits) of the ed25519 lanes, raising first on any failed
        certificate with the same error taxonomy the column path uses."""
        from ..types.validation import _raise_cert_error

        m = blocksync_metrics()
        for h, bv, pend in self.certs:
            t0 = time.perf_counter()
            ok, _ = pend.result()
            m.cert_verify_seconds.observe(time.perf_counter() - t0)
            if not ok:
                try:
                    _raise_cert_error(bv.error)
                except CommitError as e:
                    raise type(e)(f"height {h}: {e}") from e
        if self.ed is None:
            return True, []
        return self.ed.result()


@dataclass
class ReplayStats:
    blocks: int = 0
    sigs_verified: int = 0
    elapsed_s: float = 0.0

    @property
    def blocks_per_sec(self) -> float:
        return self.blocks / self.elapsed_s if self.elapsed_s else 0.0


class ReplayEngine:
    """Replays a stored chain into an application.

    verify_mode:
      - "full": reference-faithful — VerifyCommitLight per height plus the
        full LastCommit verification inside block validation.
      - "batched": commit signatures for `window` consecutive heights are
        verified in one device mega-batch (per-sig bitmap checked, +2/3
        tallied per height), then blocks are applied with the in-validation
        re-verification elided (it would re-check the same signatures).
    """

    def __init__(
        self,
        block_store: BlockStore,
        executor: BlockExecutor,
        verify_mode: str = "batched",
        window: int = 64,
        backend: str = "tpu",
        depth: int | None = None,
        sched=None,
        tenant: str = "",
    ):
        # window=64 default: each window resolve pays one device->host
        # round trip (~100 ms on a tunneled runtime), so fewer, larger
        # windows amortize it; 64 heights x 150 validators still fits
        # the 16384-lane bucket
        if verify_mode not in ("full", "batched"):
            raise ValueError(f"unknown verify_mode {verify_mode}")
        self.store = block_store
        self.executor = executor
        self.verify_mode = verify_mode
        self.window = window
        self.backend = backend
        # in-flight window count: None = auto (see _pipeline_depth)
        self.depth = depth
        # optional crypto.sched.VerifyScheduler: window mega-batches
        # coalesce with other consumers' work at blocksync priority
        self.sched = sched
        self.tenant = tenant

    def _pipeline_depth(self) -> int:
        """Windows in flight at once. Single device: 2 (device verifies
        w+1 while the host applies w — deeper queues just park work
        behind one chip). Mesh: 1 + n_devices, so round-robin streaming
        keeps EVERY chip holding a window while the host applies."""
        if self.depth:
            return max(1, int(self.depth))
        eng = ed25519._mesh_engine()
        if eng is not None and eng.n_devices > 1:
            return 1 + eng.n_devices
        return 2

    def _commit_for(self, height: int) -> Commit | None:
        c = self.store.load_block_commit(height)
        if c is None:
            c = self.store.load_seen_commit(height)
        return c

    def _queue_window(self, chain_id, validators, lc_vals, prev_bid,
                      initial_height, blocks: list):
        """Submit (without blocking) every signature check a window of
        blocks needs; returns an opaque handle for _resolve_window.

        Split from the old synchronous check so run() can keep the
        device verifying window w+1 while the host applies window w —
        the replay loop is control-plane-bound (ABCI + stores + proto),
        and serializing host and device work wastes whichever is
        cheaper (VERDICT r3: verification was ~2 ms of a ~10 ms block
        budget)."""
        return self._window_batch(
            chain_id, validators, lc_vals, prev_bid, initial_height, blocks
        )

    def _resolve_window(self, handle) -> int:
        """Block on the device verdict; raise on any invalid signature
        or insufficient tally. Returns signatures verified."""
        pending, per_commit, nsigs = handle
        ok, bits = pending.result()
        if not ok:
            for i, b in enumerate(bits):
                if not b:
                    raise ErrInvalidSignature(
                        f"invalid signature in window lane {i}"
                    )
        for h, threshold, entries in per_commit:
            tally = sum(entries)
            if tally <= threshold:
                raise ErrNotEnoughVotingPower(
                    f"height {h}: tallied {tally} <= {threshold}"
                )
        return nsigs

    def _window_batch(self, chain_id, validators, lc_vals_first, prev_bid,
                      initial_height, blocks: list):
        """Batch every signature check the per-block path would do across a
        window of blocks, submitted (not resolved) in one device call.

        Two families of commits go into the mega-batch:

        1. Each block's EMBEDDED LastCommit, with full VerifyCommit
           semantics (reference types/validation.go:21-34: every non-absent
           signature — COMMIT and NIL votes alike — verified; COMMIT votes
           tallied to +2/3; commit bound to the predecessor's computed
           BlockID). This is exactly the check apply_block_preverified
           elides, so eliding it is sound.
        2. The STORED commit for the window's last block, VerifyCommitLight
           semantics (reference internal/blocksync/reactor.go:462: the tip
           needs an external +2/3 endorsement since no successor block in
           this window embeds one).

        The window only spans heights whose header.validators_hash equals
        validators.hash() (caller enforces), so every embedded LastCommit
        except the first block's was signed by `validators`; the first
        block's was signed by `lc_vals_first`.
        """
        from ..types.validation import _check_commit_basics, ErrInvalidCommitSize

        bv = ed25519.Ed25519BatchVerifier(backend=self.backend)
        per_commit: list[tuple[int, int, list[int]]] = []
        cert_bvs: list[tuple[int, CertCommitVerifier]] = []
        lane = 0
        singles = 0
        cert_sigs = 0

        def queue_commit_cert(commit, vals, height):
            """Certificate-native commit: ONE pairing check replaces the
            whole signature column. Power tally and bitmap consistency
            are enforced inside AggregateCommit.verify, so no per_commit
            entry is needed — a shortfall surfaces as
            ErrNotEnoughVotingPower through the verifier's error."""
            nonlocal cert_sigs
            cert_bvs.append((height, CertCommitVerifier(chain_id, vals, commit)))
            cert_sigs += commit.signer_count()

        def queue_commit_columnar(commit, vals, height, all_sigs):
            """Whole-commit queueing without per-CommitSig Python: the
            native decode columns + the frozen set's ed25519 columns
            feed one vectorized address check, one native sign-bytes
            build, and one add_batch. Returns False (caller takes the
            per-slot path) when any precondition is off — non-ed25519
            keys, hand-built commit, odd flags/lengths — so behavior is
            byte-identical where it matters and merely slower where it
            is rare."""
            nonlocal lane
            cols = commit.verify_columns()
            vcols = vals.ed25519_columns()
            if cols is None or vcols is None:
                return False
            flags, addrs, addr_lens, sig_lens, sigs, _, _ = cols
            addr_rows, pub_rows, powers = vcols
            absent = flags == 1
            # light (tip) semantics verify only COMMIT votes
            # (reference VerifyCommitLight); full semantics verify
            # every non-absent signature
            live = ~absent if all_sigs else flags == 2
            # structural gates: only ABSENT/COMMIT/NIL flags, 20-byte
            # addresses and 64-byte signatures on verified lanes
            if not (
                (absent | (flags == 2) | (flags == 3)).all()
                and (addr_lens[live] == 20).all()
                and (sig_lens[live] == 64).all()
                and (addr_lens[absent] == 0).all()
            ):
                return False
            if not (addrs[live] == addr_rows[live]).all():
                return False  # per-slot path localizes the mismatch
            sb = commit.vote_sign_bytes_blob(chain_id)
            if sb is None:
                return False
            msg_blob, lens = sb
            if live.all():
                bv.add_batch(pub_rows, sigs, msg_blob, lens)
            else:
                import numpy as _np

                idx = _np.nonzero(live)[0]
                offs = _np.zeros(len(lens) + 1, _np.int64)
                _np.cumsum(lens, out=offs[1:])
                parts = [
                    msg_blob[offs[i]:offs[i + 1]] for i in idx
                ]
                bv.add_batch(
                    pub_rows[idx], sigs[idx], b"".join(parts), lens[idx]
                )
            lane += int(live.sum())
            commit_power = int(powers[flags == 2].sum())
            per_commit.append(
                (height, vals.total_voting_power() * 2 // 3,
                 (commit_power,))
            )
            return True

        def queue_commit(commit, vals, expect_bid, height, all_sigs):
            nonlocal lane, singles
            _check_commit_basics(vals, commit, height, expect_bid)
            if commit.size() != len(vals):
                raise ErrInvalidCommitSize(
                    f"commit size {commit.size()} != validator set {len(vals)}"
                )
            if getattr(commit, "cert", None) is not None:
                queue_commit_cert(commit, vals, height)
                return
            if queue_commit_columnar(commit, vals, height, all_sigs):
                return
            entries = []
            msgs = commit.vote_sign_bytes_all(chain_id)
            for idx, cs in enumerate(commit.signatures):
                if cs.is_absent() or (not all_sigs and not cs.is_commit()):
                    continue
                val = vals.get_by_index(idx)
                if val is None or val.address != cs.validator_address:
                    raise ErrInvalidSignature(
                        f"address mismatch at height {height} index {idx}"
                    )
                msg = msgs[idx]
                before = bv.count()
                bv.add(val.pub_key, msg, cs.signature)
                if bv.count() == before:
                    # batch verifier refused the key type (no lane was
                    # consumed): verify singly, like _verify_items' fallback
                    if not val.pub_key.verify_signature(msg, cs.signature):
                        raise ErrInvalidSignature(
                            f"invalid signature at height {height} index {idx}"
                        )
                    singles += 1
                else:
                    lane += 1
                if cs.is_commit():
                    entries.append(val.voting_power)
            per_commit.append(
                (height, vals.total_voting_power() * 2 // 3, entries)
            )

        lc_vals = lc_vals_first
        for blk in blocks:
            h = blk.header.height
            if h != initial_height:
                if lc_vals is None:
                    raise BlockValidationError(
                        f"no validator set for last commit of height {h}"
                    )
                queue_commit(blk.last_commit, lc_vals, prev_bid, h - 1, all_sigs=True)
            prev_bid = block_id_for(blk)
            lc_vals = validators
        tip = blocks[-1].header.height
        commit = self._commit_for(tip)
        if commit is None:
            raise BlockValidationError(f"missing commit at height {tip}")
        queue_commit(commit, validators, prev_bid, tip, all_sigs=False)
        cert_checks = []
        if self.sched is not None:
            for ch, cbv in cert_bvs:
                cert_checks.append(
                    (ch, cbv, self.sched.submit(
                        cbv, tenant=self.tenant, source="blocksync"))
                )
            ed_pending = (
                self.sched.submit(bv, tenant=self.tenant, source="blocksync")
                if bv.count() else None
            )
        else:
            for ch, cbv in cert_bvs:
                cert_checks.append((ch, cbv, cbv.submit()))
            ed_pending = bv.submit() if bv.count() else None
        pending = _WindowPending(ed_pending, cert_checks)
        return pending, per_commit, lane + singles + cert_sigs

    def _light_check_window(self, state, blocks: list) -> int:
        """Synchronous window check (submit + resolve); kept for callers
        outside the pipelined run loop."""
        handle = self._queue_window(
            state.chain_id, state.validators, state.last_validators,
            state.last_block_id, state.initial_height, blocks,
        )
        return self._resolve_window(handle)

    def _load_window(self, h: int, tip: int, vals_hash: bytes) -> list:
        """Blocks [h .. h+window-1] bounded by tip and by the first
        validator-set change (empty list when block h is stored but
        belongs to a different set; raises when block h is missing)."""
        w_end = min(h + self.window - 1, tip)
        blocks = []
        for hh in range(h, w_end + 1):
            blk = self.store.load_block(hh)
            if blk is None:
                if hh == h:
                    raise BlockValidationError(f"missing block at height {h}")
                break
            if blk.header.validators_hash != vals_hash:
                break
            blocks.append(blk)
        return blocks

    def run(self, state, to_height: int | None = None) -> tuple[object, ReplayStats]:
        """Replay from state.last_block_height+1 to `to_height` (or tip).

        Batched mode pipelines depth-N (_pipeline_depth: 2 on a single
        device, 1 + n_devices on a mesh so round-robin streaming keeps
        every chip holding a window): windows w+1..w+N-1's signature
        batches are in flight while the host applies window w's blocks
        (sound within a constant-validator-set span: each window's
        verification inputs — validator set and predecessor block id —
        are known before w is applied; across a set change the pipeline
        drains and re-queues with the post-apply state)."""
        stats = ReplayStats()
        t0 = time.perf_counter()
        tip = to_height or self.store.height()
        h = state.last_block_height + 1
        if self.verify_mode == "batched" and h <= tip:
            from collections import deque

            depth = self._pipeline_depth()
            cur_hash = state.validators.hash()
            blocks = self._load_window(h, tip, cur_hash)
            if not blocks:
                raise BlockValidationError(f"cannot form window at height {h}")
            handle = self._queue_window(
                state.chain_id, state.validators, state.last_validators,
                state.last_block_id, state.initial_height, blocks,
            )
            q: deque = deque([(blocks, handle)])
            last_qed = blocks  # last window queued (speculation anchor)
            spec_dead = False  # stop speculating until the serial requeue

            def fill():
                # top the in-flight queue up to `depth` windows,
                # speculatively: problems in a later window's data must
                # not abort before the already-verified earlier windows
                # apply (they resurface in the serial re-queue below,
                # after that progress is durable)
                nonlocal last_qed, spec_dead
                while not spec_dead and len(q) < depth:
                    nh = last_qed[-1].header.height + 1
                    if nh > tip:
                        return
                    try:
                        nxt = self._load_window(nh, tip, cur_hash)
                        if not nxt:
                            spec_dead = True
                            return
                        # same-set continuation: every window in the
                        # span was signed by the CURRENT validator set
                        nxt_handle = self._queue_window(
                            state.chain_id, state.validators,
                            state.validators, block_id_for(last_qed[-1]),
                            state.initial_height, nxt,
                        )
                    except (CommitError, BlockValidationError):
                        spec_dead = True
                        return
                    # start the (fixed ~100 ms through a tunnel)
                    # device->host fetch early so it rides under later
                    # queueing/apply work instead of blocking resolve
                    nxt_handle[0].prefetch()
                    q.append((nxt, nxt_handle))
                    last_qed = nxt

            while q:
                fill()  # keep every device busy before blocking
                blocks, handle = q.popleft()
                handle[0].prefetch()
                stats.sigs_verified += self._resolve_window(handle)
                for block in blocks:
                    bid = block_id_for(block)
                    state = self.executor.apply_block_preverified(state, bid, block)
                    stats.blocks += 1
                nh = blocks[-1].header.height + 1
                if q or nh > tip:
                    continue
                # pipeline drained mid-chain: validator set changed at
                # the boundary (or speculation failed) — reload and
                # queue against the post-apply state
                cur_hash = state.validators.hash()
                spec_dead = False
                nxt = self._load_window(nh, tip, cur_hash)
                if not nxt:
                    raise BlockValidationError(
                        f"cannot form window at height {nh}"
                    )
                nxt_handle = self._queue_window(
                    state.chain_id, state.validators,
                    state.last_validators, state.last_block_id,
                    state.initial_height, nxt,
                )
                q.append((nxt, nxt_handle))
                last_qed = nxt
            stats.elapsed_s = time.perf_counter() - t0
            return state, stats
        # "full" mode: reference-faithful per-height verify + apply
        from ..crypto.sched import verify_context
        from ..types.validation import verify_commit_light

        while h <= tip:
            block = self.store.load_block(h)
            commit = self._commit_for(h)
            if block is None or commit is None:
                raise BlockValidationError(f"missing block/commit at {h}")
            bid = block_id_for(block)
            with verify_context(self.sched, self.tenant, "blocksync"):
                verify_commit_light(
                    state.chain_id, state.validators, bid, h, commit,
                    backend=self.backend,
                )
            stats.sigs_verified += sum(
                1 for cs in commit.signatures if cs.is_commit()
            )
            state = self.executor.apply_block(state, bid, block)
            stats.blocks += 1
            h += 1
        stats.elapsed_s = time.perf_counter() - t0
        return state, stats
