"""Wire encodings: minimal protobuf writer/reader for canonical sign-bytes,
storage, and framing. Hand-rolled (no generated code) — the handful of
consensus-critical messages are encoded explicitly for bit-exactness."""
