"""Minimal protobuf wire-format writer/reader.

Implements exactly the proto3 + gogoproto emission rules the reference's
canonical encodings rely on (reference: types/canonical.go:57-66,
internal/protoio varint-delimited framing):

- varint (base-128, two's-complement 10-byte for negative int64)
- zero-valued scalar fields are omitted
- *non-nullable* embedded messages (gogoproto.nullable=false) are always
  emitted, even when empty; nullable (pointer) ones only when present
- sfixed64 = 8-byte little-endian two's complement, wire type 1
"""

from __future__ import annotations

WT_VARINT = 0
WT_I64 = 1
WT_LEN = 2
WT_I32 = 5

_U64 = 1 << 64
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


# single-byte varints (the overwhelmingly common case: tags, small
# lengths, flags) come from a table instead of the shift loop — varint
# encoding is the hottest host function in replay profiles
_UV1 = [bytes((i,)) for i in range(0x80)]


def uvarint(v: int) -> bytes:
    if v < 0x80:
        if v < 0:
            raise ValueError("uvarint needs v >= 0")
        return _UV1[v]
    if v < 0x4000:
        return bytes(((v & 0x7F) | 0x80, v >> 7))
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def varint_i64(v: int) -> bytes:
    """int64/int32/enum encoding: two's complement as uint64."""
    if v < 0:
        v += _U64
    return uvarint(v)


def tag(field: int, wt: int) -> bytes:
    return uvarint((field << 3) | wt)


def f_varint(field: int, v: int, *, emit_zero: bool = False) -> bytes:
    if v == 0 and not emit_zero:
        return b""
    return tag(field, WT_VARINT) + varint_i64(v)


def f_sfixed64(field: int, v: int, *, emit_zero: bool = False) -> bytes:
    if v == 0 and not emit_zero:
        return b""
    return tag(field, WT_I64) + (v & (_U64 - 1)).to_bytes(8, "little")


def f_bytes(field: int, v: bytes, *, emit_empty: bool = False) -> bytes:
    if not v and not emit_empty:
        return b""
    return tag(field, WT_LEN) + uvarint(len(v)) + v


def f_string(field: int, v: str, *, emit_empty: bool = False) -> bytes:
    return f_bytes(field, v.encode("utf-8"), emit_empty=emit_empty)


def f_embedded(field: int, payload: bytes) -> bytes:
    """Non-nullable embedded message: ALWAYS emitted."""
    return tag(field, WT_LEN) + uvarint(len(payload)) + payload


def f_embedded_opt(field: int, payload: bytes | None) -> bytes:
    """Nullable embedded message: emitted only when not None."""
    if payload is None:
        return b""
    return f_embedded(field, payload)


def length_prefixed(payload: bytes) -> bytes:
    """Varint-delimited framing (reference internal/protoio MarshalDelimited)."""
    return uvarint(len(payload)) + payload


# ----- reader -----


def read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def to_i64(v: int) -> int:
    return v - _U64 if v > _I64_MAX else v


def parse_fields(buf: bytes) -> list[tuple[int, int, object]]:
    """Flat parse: list of (field_number, wire_type, value).

    value is int for varint/i64/i32, bytes for length-delimited.
    """
    out = []
    pos = 0
    while pos < len(buf):
        key, pos = read_uvarint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == WT_VARINT:
            v, pos = read_uvarint(buf, pos)
        elif wt == WT_I64:
            v = int.from_bytes(buf[pos : pos + 8], "little")
            pos += 8
        elif wt == WT_LEN:
            ln, pos = read_uvarint(buf, pos)
            v = buf[pos : pos + ln]
            if len(v) != ln:
                raise ValueError("truncated bytes field")
            pos += ln
        elif wt == WT_I32:
            v = int.from_bytes(buf[pos : pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.append((field, wt, v))
    return out


def fields_to_dict(buf: bytes) -> dict[int, object]:
    """Last-wins dict of field -> value (repeated fields: use parse_fields)."""
    return {f: v for f, _, v in parse_fields(buf)}


def as_bytes(v) -> bytes:
    """Coerce a parsed field value to bytes, REJECTING type confusion.

    parse_fields returns ints for varint/i64/i32 fields; calling the
    bytes() builtin on an attacker-chosen int allocates that many zero
    bytes (bytes(2**35) = 32 GiB) — a remote memory-exhaustion vector
    every wire decoder would otherwise inherit. Decoders must use this
    for every field they expect to be length-delimited.
    """
    if isinstance(v, int):
        raise ValueError("expected length-delimited field, got scalar")
    return bytes(v)
