"""Amino-compatible JSON for keys (reference crypto go-amino registry,
e.g. privval key files and genesis docs in the classic format):

    {"type": "tendermint/PubKeyEd25519", "value": "<base64>"}

The framework's own files use explicit hex + type fields; this codec
exists for interop with reference-formatted priv_validator_key.json /
genesis.json documents.
"""

from __future__ import annotations

import base64


def pub_key_to_json(pk) -> dict:
    return {
        "type": pk.type_tag(),
        "value": base64.b64encode(pk.bytes()).decode(),
    }


def pub_key_from_json(d: dict):
    from ..rpc.codec import pub_key_from_json as _mk

    return _mk(d.get("type", ""), base64.b64decode(d.get("value", "")))


def priv_key_to_json(pk) -> dict:
    tag = pk.type_tag().replace("PubKey", "PrivKey")
    return {
        "type": tag,
        "value": base64.b64encode(pk.bytes()).decode(),
    }


def priv_key_from_json(d: dict):
    tag = d.get("type", "")
    raw = base64.b64decode(d.get("value", ""))
    if "Secp256k1" in tag:
        from ..crypto.secp256k1 import Secp256k1PrivKey

        return Secp256k1PrivKey(raw)
    if "Sr25519" in tag:
        from ..crypto.sr25519 import Sr25519PrivKey

        return Sr25519PrivKey(raw)
    from ..crypto.ed25519 import Ed25519PrivKey

    return Ed25519PrivKey(raw)
