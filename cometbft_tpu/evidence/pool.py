"""Evidence pool: verify, persist, propose, prune.

Behavior parity: reference internal/evidence/pool.go —
- AddEvidence verifies against historical state (validator set at the
  evidence height) and persists it pending (:~130).
- PendingEvidence(maxBytes) returns proposable evidence (:~190).
- Update marks block-committed evidence, prunes expired (:~230) using the
  consensus params' evidence age (height AND time, both must expire).
- ReportConflictingVotes is the consensus-state hookup
  (reference internal/consensus/state.go:60-63); votes become
  DuplicateVoteEvidence on the next Update when the height's validator
  set is known.
"""

from __future__ import annotations

import threading

from ..storage.kv import KVStore, MemKV
from ..types import Timestamp
from ..types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
    decode_evidence,
)


def _key_pending(h: int, ev_hash: bytes) -> bytes:
    return b"EP:" + h.to_bytes(8, "big") + ev_hash


def _key_committed(ev_hash: bytes) -> bytes:
    return b"EC:" + ev_hash


class EvidencePool:
    def __init__(self, db: KVStore | None = None, state_store=None,
                 block_store=None, chain_id: str = ""):
        self._db = db or MemKV()
        self.state_store = state_store
        self.block_store = block_store
        self.chain_id = chain_id
        self._lock = threading.Lock()
        self._conflicting_votes: list = []  # (vote_a, vote_b) pairs

    # ------------------------------------------------------------------
    def _validators_at(self, height: int):
        if self.state_store is None:
            return None
        return self.state_store.load_validators(height)

    def add_evidence(self, ev) -> None:
        """Verify + persist (reference AddEvidence)."""
        with self._lock:
            if self._db.has(_key_committed(ev.hash())):
                return  # already committed: no-op
            if isinstance(ev, DuplicateVoteEvidence):
                vals = self._validators_at(ev.height)
                if vals is not None:
                    ev.verify(self.chain_id, vals)
                elif self.state_store is not None:
                    raise EvidenceError(
                        f"no validator set stored for height {ev.height}"
                    )
            self._db.set(_key_pending(ev.height, ev.hash()), ev.wrapped())

    def check_evidence(self, evidence: list, max_bytes: int | None = None
                       ) -> None:
        """Verify a block's proposed evidence before accepting the block
        (reference internal/evidence/pool.go CheckEvidence + verify.go):
        every item must verify against the historical validator set, and
        the total encoded size must respect the params cap."""
        total = 0
        seen = set()
        for ev in evidence:
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            total += len(ev.wrapped())
            if self._db.has(_key_committed(h)):
                raise EvidenceError("evidence already committed")
            if isinstance(ev, DuplicateVoteEvidence):
                vals = self._validators_at(ev.height)
                if vals is None:
                    raise EvidenceError(
                        f"no validator set stored for height {ev.height}"
                    )
                ev.verify(self.chain_id, vals)
            # LightClientAttackEvidence structural checks happen in the
            # light detector; here reject unknown shapes defensively
        if max_bytes is not None and total > max_bytes:
            raise EvidenceError(f"evidence bytes {total} > cap {max_bytes}")

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """Consensus hands us raw equivocations
        (reference Pool.ReportConflictingVotes)."""
        with self._lock:
            self._conflicting_votes.append((vote_a, vote_b))

    def _materialize_conflicts(self, state) -> None:
        pending, self._conflicting_votes = self._conflicting_votes, []
        for a, b in pending:
            vals = self._validators_at(a.height)
            if vals is None:
                continue
            _, val = vals.get_by_address(a.validator_address)
            if val is None:
                continue
            ev = DuplicateVoteEvidence.from_votes(
                a, b, val.voting_power, vals.total_voting_power(),
                state.last_block_time,
            )
            try:
                ev.verify(self.chain_id, vals)
            except EvidenceError:
                continue
            self._db.set(_key_pending(ev.height, ev.hash()), ev.wrapped())

    # ------------------------------------------------------------------
    def pending_evidence(self, max_bytes: int = 1 << 20) -> list:
        out, total = [], 0
        for _, raw in self._db.iterate_prefix(b"EP:"):
            if total + len(raw) > max_bytes:
                break
            out.append(decode_evidence(raw))
            total += len(raw)
        return out

    def update(self, state, committed_evidence: list) -> None:
        """Post-commit: mark committed, materialize reports, prune expired
        (reference Pool.Update)."""
        with self._lock:
            for ev in committed_evidence:
                self._db.set(_key_committed(ev.hash()), b"\x01")
                self._db.delete(_key_pending(ev.height, ev.hash()))
        self._materialize_conflicts(state)
        self._prune(state)

    def _prune(self, state) -> None:
        params = state.consensus_params.evidence
        min_height = state.last_block_height - params.max_age_num_blocks
        min_time_ns = (
            state.last_block_time.unix_ns()
            - params.max_age_duration_ns
        )
        deletes = []
        for key, raw in self._db.iterate_prefix(b"EP:"):
            h = int.from_bytes(key[3:11], "big")
            if h >= min_height:
                break  # keys are height-ordered
            ev = decode_evidence(raw)
            ts = getattr(ev, "timestamp", Timestamp())
            if ts.unix_ns() < min_time_ns:
                deletes.append(key)
        if deletes:
            self._db.write_batch([], deletes)

    def size(self) -> int:
        return sum(1 for _ in self._db.iterate_prefix(b"EP:"))
