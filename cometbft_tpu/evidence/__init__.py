from .pool import EvidencePool

__all__ = ["EvidencePool"]
