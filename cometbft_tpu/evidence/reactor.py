"""Evidence reactor: gossip pending evidence to peers.

Behavior parity: reference internal/evidence/reactor.go — one channel
(0x38), a per-peer broadcast routine that walks the pending list and
retries on an interval (the reference's clist blocking-iterate becomes
a poll loop over pending_evidence), and inbound evidence fed through
EvidencePool.add_evidence (verification included; invalid evidence is
dropped and logged, reference :120). A node that observes equivocation
can therefore inform the whole network, not just its own block
proposals.
"""

from __future__ import annotations

import threading
import time

from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types.evidence import decode_evidence
from ..utils.log import logger

EVIDENCE_CHANNEL = 0x38
BROADCAST_INTERVAL_S = 0.1
_log = logger("evidence")


class EvidenceReactor(Reactor):
    def __init__(self, pool):
        self.pool = pool
        self.switch = None
        self._peers: dict[str, object] = {}
        # peer id -> set of evidence hashes already sent
        self._sent: dict[str, set[bytes]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=EVIDENCE_CHANNEL, priority=6)]

    def set_switch(self, switch) -> None:
        self.switch = switch
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._broadcast_loop, daemon=True, name="ev-gossip"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def add_peer(self, peer) -> None:
        with self._lock:
            self._peers[peer.id] = peer
            self._sent.setdefault(peer.id, set())

    def remove_peer(self, peer, reason) -> None:
        with self._lock:
            self._peers.pop(peer.id, None)
            self._sent.pop(peer.id, None)

    def receive(self, chan_id: int, peer, raw: bytes) -> None:
        try:
            ev = decode_evidence(raw)
        except Exception:  # noqa: BLE001 — malformed: drop
            return
        try:
            self.pool.add_evidence(ev)
        except Exception as e:  # noqa: BLE001 — invalid evidence: drop
            _log.debug("rejected peer evidence", peer=peer.id[:8],
                       err=str(e)[:80])
            return
        with self._lock:
            sent = self._sent.get(peer.id)
        if sent is not None:
            sent.add(ev.hash())  # the sender obviously has it

    def _broadcast_loop(self) -> None:
        while not self._stopped.wait(BROADCAST_INTERVAL_S):
            try:
                pending = self.pool.pending_evidence()
            except Exception:  # noqa: BLE001
                continue
            if not pending:
                continue
            with self._lock:
                peers = list(self._peers.items())
            for pid, peer in peers:
                with self._lock:
                    sent = self._sent.setdefault(pid, set())
                for ev in pending:
                    h = ev.hash()
                    if h in sent:
                        continue
                    try:
                        peer.send(EVIDENCE_CHANNEL, ev.wrapped())
                        sent.add(h)
                    except Exception:  # noqa: BLE001 — peer going away
                        break
