from .file_pv import FilePV, SignStep, DoubleSignError

__all__ = ["FilePV", "SignStep", "DoubleSignError"]
