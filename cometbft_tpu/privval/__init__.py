from .file_pv import FilePV, SignStep, DoubleSignError
from .signer import SignerClient, SignerServer

__all__ = ["FilePV", "SignStep", "DoubleSignError", "SignerClient",
           "SignerServer"]
