"""Remote signer: the privval sidecar-process protocol.

Behavior parity: reference privval/signer_listener_endpoint.go,
signer_dialer_endpoint.go, signer_client.go, signer_server.go and
proto/cometbft/privval/v1 — the NODE listens on priv_validator_laddr;
the SIGNER process dials in and serves PubKey/SignVote/SignProposal/
Ping over varint-length-prefixed proto messages. The client side
(SignerClient) implements the PrivValidator surface, waits bounded time
for each response, and transparently survives signer reconnects; the
server side (SignerServer) wraps a FilePV (keeping its last-sign-state
double-sign protection in the signer process, where the key lives).

Message oneof: pub_key_request=1, pub_key_response=2,
sign_vote_request=3, signed_vote_response=4, sign_proposal_request=5,
signed_proposal_response=6, ping_request=7, ping_response=8.
"""

from __future__ import annotations

import socket
import threading
import time

from ..encoding import proto as pb
from ..types import Proposal, Vote
from ..utils.log import logger

_log = logger("privval")


# ----------------------------------------------------------------------
# framing: varint-delimited proto messages
# ----------------------------------------------------------------------
def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(pb.uvarint(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("signer connection closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket, max_size: int = 1 << 20) -> bytes:
    # varint length prefix, byte at a time (lengths are tiny)
    shift = 0
    length = 0
    while True:
        b = _recv_exact(sock, 1)[0]
        length |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
        if shift > 35:
            raise ValueError("corrupt length prefix")
    if length > max_size:
        raise ValueError(f"oversized signer message ({length} bytes)")
    return _recv_exact(sock, length)


def _err_field(err: str) -> bytes:
    return pb.f_embedded(99, pb.f_string(1, err)) if err else b""


def _parse_err(d: dict) -> str:
    if 99 not in d:
        return ""
    return bytes(pb.fields_to_dict(pb.as_bytes(d[99])).get(1, b"")).decode()


# ----------------------------------------------------------------------
# signer server (runs beside the key, dials the node)
# ----------------------------------------------------------------------
class SignerServer:
    """Wraps a FilePV and serves signing requests to a node, dialing
    (host, port) with retry (reference SignerServer + dialer endpoint)."""

    def __init__(self, pv, chain_id: str, host: str, port: int,
                 retry_interval_s: float = 0.2):
        self.pv = pv
        self.chain_id = chain_id
        self.host = host
        self.port = port
        self.retry_interval_s = retry_interval_s
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="signer-server"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=3.0
                )
            except OSError:
                if self._stopped.wait(self.retry_interval_s):
                    return
                continue
            sock.settimeout(None)
            try:
                self._serve(sock)
            except (ConnectionError, OSError, ValueError):
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

    def _serve(self, sock: socket.socket) -> None:
        while not self._stopped.is_set():
            raw = _recv_msg(sock)
            _send_msg(sock, self._handle(raw))

    def _handle(self, raw: bytes) -> bytes:
        fields = pb.parse_fields(raw)
        if not fields:
            return pb.f_embedded(2, _err_field("empty request"))
        fnum, _, v = fields[0]
        v = pb.as_bytes(v)
        if fnum == 1:  # PubKeyRequest
            pk = self.pv.pub_key()
            body = pb.f_string(1, pk.type_tag()) + pb.f_bytes(2, pk.bytes())
            return pb.f_embedded(2, body)
        if fnum == 3:  # SignVoteRequest {1: vote, 2: chain_id, 3: skip_ext}
            d = pb.fields_to_dict(v)
            try:
                vote = Vote.decode(pb.as_bytes(d.get(1, b"")))
                chain_id = pb.as_bytes(d.get(2, b"")).decode() or self.chain_id
                sign_ext = bool(pb.to_i64(d.get(3, 0)))
                self.pv.sign_vote(chain_id, vote, sign_extension=sign_ext)
                return pb.f_embedded(4, pb.f_embedded(1, vote.encode()))
            except Exception as e:  # noqa: BLE001 — double-sign guard etc.
                return pb.f_embedded(4, _err_field(str(e)[:200]))
        if fnum == 5:  # SignProposalRequest {1: proposal, 2: chain_id}
            d = pb.fields_to_dict(v)
            try:
                prop = Proposal.decode(pb.as_bytes(d.get(1, b"")))
                chain_id = pb.as_bytes(d.get(2, b"")).decode() or self.chain_id
                self.pv.sign_proposal(chain_id, prop)
                return pb.f_embedded(6, pb.f_embedded(1, prop.encode()))
            except Exception as e:  # noqa: BLE001
                return pb.f_embedded(6, _err_field(str(e)[:200]))
        if fnum == 7:  # Ping
            return pb.f_embedded(8, b"")
        return pb.f_embedded(2, _err_field(f"unknown request {fnum}"))


# ----------------------------------------------------------------------
# node side: listener + PrivValidator client
# ----------------------------------------------------------------------
class SignerClient:
    """PrivValidator over a remote signer connection. The node listens on
    (host, port); the signer dials in (reference SignerListenerEndpoint +
    SignerClient). Requests block until a signer is connected (bounded by
    `timeout_s`); a dropped connection is replaced by the next dial-in."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(1)
        self.addr = self._lsock.getsockname()
        self._conn: socket.socket | None = None
        self._conn_ready = threading.Event()
        # _lock guards only the connection REFERENCE (accept loop swaps
        # it); _req_lock serializes requests. Socket I/O happens outside
        # _lock so a fresh dial-in can replace a hung connection instead
        # of waiting out the full socket timeout behind it.
        self._lock = threading.Lock()
        self._req_lock = threading.Lock()  # one request in flight at a time
        self._stopped = threading.Event()
        self._pub_key = None
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="signer-listener"
        )
        self._accept_thread.start()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._stopped.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            with self._lock:
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                conn.settimeout(self.timeout_s)
                self._conn = conn
                self._conn_ready.set()
            _log.info("remote signer connected")

    def _request(self, payload: bytes) -> dict:
        """Send one request; returns the response oneof dict. Retries
        across a reconnect once."""
        deadline = time.monotonic() + self.timeout_s * 2
        last_err: Exception | None = None
        with self._req_lock:
            while time.monotonic() < deadline:
                if not self._conn_ready.wait(timeout=0.1):
                    continue
                with self._lock:
                    conn = self._conn
                if conn is None:
                    continue
                try:
                    _send_msg(conn, payload)
                    resp = _recv_msg(conn)
                    return pb.fields_to_dict(resp)
                except (ConnectionError, OSError, ValueError) as e:
                    last_err = e
                    try:
                        conn.close()
                    except OSError:
                        pass
                    with self._lock:
                        if self._conn is conn:
                            self._conn = None
                            self._conn_ready.clear()
        raise ConnectionError(
            f"no signer response within {self.timeout_s * 2:.1f}s: {last_err}"
        )

    # -- PrivValidator surface ----------------------------------------
    def pub_key(self):
        if self._pub_key is None:
            d = self._request(pb.f_embedded(1, b""))
            body = pb.fields_to_dict(pb.as_bytes(d.get(2, b"")))
            err = _parse_err(body)
            if err:
                raise RuntimeError(f"signer: {err}")
            from ..crypto.ed25519 import Ed25519PubKey

            self._pub_key = Ed25519PubKey(pb.as_bytes(body.get(2, b"")))
        return self._pub_key

    def address(self) -> bytes:
        return self.pub_key().address()

    def sign_vote(self, chain_id: str, vote, sign_extension: bool = False
                  ) -> None:
        body = pb.f_embedded(1, vote.encode()) + pb.f_string(2, chain_id)
        if sign_extension:
            body += pb.f_varint(3, 1)
        d = self._request(pb.f_embedded(3, body))
        resp = pb.fields_to_dict(pb.as_bytes(d.get(4, b"")))
        err = _parse_err(resp)
        if err:
            raise RuntimeError(f"signer refused vote: {err}")
        signed = Vote.decode(pb.as_bytes(resp.get(1, b"")))
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature

    def sign_proposal(self, chain_id: str, proposal) -> None:
        body = pb.f_embedded(1, proposal.encode()) + pb.f_string(2, chain_id)
        d = self._request(pb.f_embedded(5, body))
        resp = pb.fields_to_dict(pb.as_bytes(d.get(6, b"")))
        err = _parse_err(resp)
        if err:
            raise RuntimeError(f"signer refused proposal: {err}")
        signed = Proposal.decode(pb.as_bytes(resp.get(1, b"")))
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp

    def ping(self) -> bool:
        try:
            d = self._request(pb.f_embedded(7, b""))
            return 8 in d
        except ConnectionError:
            return False
