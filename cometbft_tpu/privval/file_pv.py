"""File-backed private validator with double-sign protection.

Behavior parity: reference privval/file.go —
- FilePVKey / FilePVLastSignState split across two files (:38,74): the key
  file is written once; the state file is rewritten (atomically) before
  every signature leaves the signer.
- CheckHRS (:99): refuse any (height, round, step) regression; for the same
  HRS, only re-serve the exact previous signature.
- signVote/signProposal (:306,341): if the new sign-bytes differ from the
  last signed bytes ONLY in the timestamp, re-serve the previous signature
  with the previous timestamp (:428 checkVotesOnlyDifferByTimestamp);
  anything else at the same HRS is a double-sign attempt and is refused.

The "sign bytes without timestamp" comparison re-encodes the canonical
message with the previous timestamp rather than regex-stripping fields —
same outcome as the reference's proto-unmarshal/zero/remarshal dance.
"""

from __future__ import annotations

import enum
import json
import os
import tempfile
from dataclasses import dataclass

from ..crypto.ed25519 import Ed25519PrivKey, Ed25519PubKey
from ..crypto.keys import PrivKey, PubKey
from ..types.basic import Timestamp
from ..types.vote import (
    SignedMsgType,
    canonical_proposal_bytes,
    canonical_vote_bytes,
)


class DoubleSignError(Exception):
    pass


class SignStep(enum.IntEnum):
    NONE = 0
    PROPOSE = 1
    PREVOTE = 2
    PRECOMMIT = 3


_PRECOMMIT_TYPE = SignedMsgType.PRECOMMIT


def _priv_key_class(key_type: str):
    """Key-file "key_type" tag -> PrivKey class. Ed25519 is the default
    (and the tag older key files lack); BLS validators sign votes with
    the same file format, so consensus signing keys stay swappable."""
    if key_type == "tendermint/PubKeyBls12_381":
        from ..crypto.bls import BlsPrivKey

        return BlsPrivKey
    if key_type == "tendermint/PubKeySecp256k1":
        from ..crypto.secp256k1 import Secp256k1PrivKey

        return Secp256k1PrivKey
    return Ed25519PrivKey

_VOTE_TO_STEP = {
    SignedMsgType.PREVOTE: SignStep.PREVOTE,
    SignedMsgType.PRECOMMIT: SignStep.PRECOMMIT,
}


@dataclass
class _LastSignState:
    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """True when (h, r, s) equals the last-signed HRS and a signature
        exists; raises on regression (reference CheckHRS :99)."""
        if self.height > height:
            raise DoubleSignError(f"height regression: {self.height} > {height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}: {self.round} > {round_}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at {height}/{round_}: "
                        f"{self.step} > {step}"
                    )
                if self.step == step:
                    if not self.signature:
                        raise DoubleSignError("no signature saved for repeated HRS")
                    return True
        return False


class FilePV:
    """types.PrivValidator backed by key + state files."""

    def __init__(self, priv_key: PrivKey, key_path: str | None,
                 state_path: str | None):
        self._priv = priv_key
        self._key_path = key_path
        self._state_path = state_path
        self._lss = _LastSignState()
        if state_path and os.path.exists(state_path):
            self._lss = self._load_state(state_path)

    # ------------------------------------------------------------------
    @classmethod
    def generate(cls, key_path: str | None = None, state_path: str | None = None,
                 key_type: str = "tendermint/PubKeyEd25519") -> "FilePV":
        pv = cls(_priv_key_class(key_type).generate(), key_path, state_path)
        if key_path:
            pv._save_key()
        if state_path:
            pv._save_state()  # reference writes both files at gen time
        return pv

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        with open(key_path) as f:
            d = json.load(f)
        klass = _priv_key_class(d.get("key_type", "tendermint/PubKeyEd25519"))
        return cls(klass(bytes.fromhex(d["priv_key"])), key_path, state_path)

    def _save_key(self):
        pub = self._priv.pub_key()
        _atomic_write_json(self._key_path, {
            "address": pub.address().hex(),
            "pub_key": pub.bytes().hex(),
            "priv_key": self._priv.bytes().hex(),
            "key_type": self._priv.type_tag(),
        })

    @staticmethod
    def _load_state(path: str) -> _LastSignState:
        with open(path) as f:
            d = json.load(f)
        return _LastSignState(
            height=d["height"], round=d["round"], step=d["step"],
            signature=bytes.fromhex(d["signature"]),
            sign_bytes=bytes.fromhex(d["sign_bytes"]),
        )

    def _save_state(self):
        if self._state_path:
            _atomic_write_json(self._state_path, {
                "height": self._lss.height, "round": self._lss.round,
                "step": self._lss.step,
                "signature": self._lss.signature.hex(),
                "sign_bytes": self._lss.sign_bytes.hex(),
            })

    # ------------------------------------------------------------------
    def pub_key(self) -> PubKey:
        return self._priv.pub_key()

    def address(self) -> bytes:
        return self.pub_key().address()

    def sign_vote(self, chain_id: str, vote, sign_extension: bool = False) -> None:
        """Sign a Vote in place (reference signVote :306). With
        sign_extension (precommits while vote extensions are enabled) the
        extension gets its own signature over the canonical extension
        sign-bytes — double-sign protection covers only the vote itself,
        matching the reference (extensions are deterministic app data)."""
        self._sign_vote_inner(chain_id, vote)
        if (
            sign_extension
            and not vote.is_nil()
            and vote.type == _PRECOMMIT_TYPE
        ):
            vote.extension_signature = self._priv.sign(
                vote.extension_sign_bytes(chain_id)
            )

    def _sign_vote_inner(self, chain_id: str, vote) -> None:
        step = _VOTE_TO_STEP.get(vote.type)
        if step is None:
            raise ValueError(f"unknown vote type {vote.type}")
        sign_bytes = vote.sign_bytes(chain_id)
        same_hrs = self._lss.check_hrs(vote.height, vote.round, int(step))
        if same_hrs:
            if sign_bytes == self._lss.sign_bytes:
                vote.signature = self._lss.signature
                return
            prev_ts = _vote_timestamp_if_only_ts_differs(
                self._lss.sign_bytes, sign_bytes, chain_id, vote
            )
            if prev_ts is not None:
                vote.timestamp = prev_ts
                vote.signature = self._lss.signature
                return
            raise DoubleSignError(
                f"conflicting vote data at {vote.height}/{vote.round}/{step.name}"
            )
        sig = self._priv.sign(sign_bytes)
        self._lss = _LastSignState(
            vote.height, vote.round, int(step), sig, sign_bytes
        )
        self._save_state()
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal) -> None:
        """Sign a Proposal in place (reference signProposal :341)."""
        sign_bytes = canonical_proposal_bytes(
            proposal.height, proposal.round, proposal.pol_round,
            proposal.block_id, proposal.timestamp, chain_id,
        )
        same_hrs = self._lss.check_hrs(
            proposal.height, proposal.round, int(SignStep.PROPOSE)
        )
        if same_hrs:
            if sign_bytes == self._lss.sign_bytes:
                proposal.signature = self._lss.signature
                return
            prev_ts = _proposal_timestamp_if_only_ts_differs(
                self._lss.sign_bytes, sign_bytes, chain_id, proposal
            )
            if prev_ts is not None:
                proposal.timestamp = prev_ts
                proposal.signature = self._lss.signature
                return
            raise DoubleSignError(
                f"conflicting proposal data at {proposal.height}/{proposal.round}"
            )
        sig = self._priv.sign(sign_bytes)
        self._lss = _LastSignState(
            proposal.height, proposal.round, int(SignStep.PROPOSE), sig, sign_bytes
        )
        self._save_state()
        proposal.signature = sig


def _atomic_write_json(path: str, obj) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".pv-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _parse_ts(sign_bytes: bytes, fnum: int) -> Timestamp | None:
    """Extract the Timestamp field from canonical sign-bytes
    (field 5 in CanonicalVote, field 6 in CanonicalProposal)."""
    from ..encoding import proto as pb

    _, n = pb.read_uvarint(sign_bytes, 0)
    d = pb.fields_to_dict(sign_bytes[n:])
    if fnum not in d:
        return None
    try:
        return Timestamp.decode(pb.as_bytes(d[fnum]))
    except Exception:
        return None


def _vote_timestamp_if_only_ts_differs(last_sb, new_sb, chain_id, vote):
    prev_ts = _parse_ts(last_sb, 5)
    if prev_ts is None:
        return None
    rebuilt = canonical_vote_bytes(
        vote.type, vote.height, vote.round, vote.block_id, prev_ts, chain_id
    )
    return prev_ts if rebuilt == last_sb else None


def _proposal_timestamp_if_only_ts_differs(last_sb, new_sb, chain_id, proposal):
    prev_ts = _parse_ts(last_sb, 6)
    if prev_ts is None:
        return None
    rebuilt = canonical_proposal_bytes(
        proposal.height, proposal.round, proposal.pol_round,
        proposal.block_id, prev_ts, chain_id,
    )
    return prev_ts if rebuilt == last_sb else None
