"""Test-only double-signing privval for byzantine fault injection.

`ByzantineValv` wraps a real FilePV and, when armed by a fault
schedule, hands the consensus state machine a SECOND conflicting
signed vote for the same (height, round, type) via the `equivocate`
hook in `_sign_and_send_vote`. The shadow vote is signed with the raw
private key — deliberately bypassing the FilePV LastSignState, which
exists precisely to prevent this — and votes for a fabricated block
id, so any two honest observers holding both votes can build
`DuplicateVoteEvidence` that verifies against the validator set.

The schedule rides the `COMETBFT_TPU_BYZANTINE` environment variable
as a JSON list of fault windows:

    [{"vote_type": "precommit", "from_height": 3, "to_height": 6}]

`vote_type` is "prevote", "precommit" or "any"; heights are
inclusive and 0/absent means unbounded. The e2e runner arms one node
per manifest `byzantine` entry by injecting the env var into that
node's subprocess only (e2e/runner.py), and node.py wraps the privval
at load time when the variable is present. Production configurations
never set it.
"""

from __future__ import annotations

import hashlib
import json
import os

from ..types.basic import BlockID, PartSetHeader
from ..types.vote import SignedMsgType, Vote

ENV_VAR = "COMETBFT_TPU_BYZANTINE"

_TYPE_NAMES = {
    "prevote": SignedMsgType.PREVOTE,
    "precommit": SignedMsgType.PRECOMMIT,
}


def parse_schedule(raw: str) -> list[dict]:
    """Validate + normalize a fault-schedule JSON string."""
    sched = json.loads(raw)
    if not isinstance(sched, list):
        raise ValueError("byzantine schedule must be a JSON list")
    out = []
    for w in sched:
        vt = w.get("vote_type", "any")
        if vt != "any" and vt not in _TYPE_NAMES:
            raise ValueError(f"unknown vote_type {vt!r}")
        out.append({
            "vote_type": vt,
            "from_height": int(w.get("from_height", 0)),
            "to_height": int(w.get("to_height", 0)),
        })
    return out


class ByzantineValv:
    """A PrivValidator that equivocates on schedule.

    Delegates every legitimate signing operation to the wrapped
    FilePV — the node's OWN votes stay protected by the last-sign
    state, so the process never crashes on its own double-sign guard —
    and fabricates the conflicting twin only through `equivocate`,
    which consensus broadcasts to peers without adding locally.
    """

    def __init__(self, inner, schedule: list[dict]):
        self._inner = inner
        self._schedule = schedule
        self.double_signed = 0

    # -- PrivValidator surface (delegation) -----------------------------
    def pub_key(self):
        return self._inner.pub_key()

    def address(self) -> bytes:
        return self._inner.address()

    def sign_vote(self, chain_id: str, vote, sign_extension: bool = False):
        return self._inner.sign_vote(chain_id, vote,
                                     sign_extension=sign_extension)

    def sign_proposal(self, chain_id: str, proposal):
        return self._inner.sign_proposal(chain_id, proposal)

    # -- the fault -------------------------------------------------------
    def _armed(self, vote) -> bool:
        for w in self._schedule:
            vt = w["vote_type"]
            if vt != "any" and _TYPE_NAMES[vt] != vote.type:
                continue
            if w["from_height"] and vote.height < w["from_height"]:
                continue
            if w["to_height"] and vote.height > w["to_height"]:
                continue
            return True
        return False

    def equivocate(self, chain_id: str, vote) -> Vote | None:
        """Return a conflicting signed twin of `vote`, or None.

        The twin votes for a block id derived from (but different to)
        the real one, at the same HRS with the same timestamp, signed
        with the raw key. Nil votes are skipped: a nil/non-nil pair at
        one HRS is still equivocation, but deriving the conflict from
        a real block id keeps the fixture deterministic either way.
        """
        if vote.is_nil() or not self._armed(vote):
            return None
        fake_hash = hashlib.sha256(b"equivocation:" + vote.block_id.hash
                                   ).digest()
        shadow = Vote(
            type=vote.type,
            height=vote.height,
            round=vote.round,
            block_id=BlockID(fake_hash,
                             PartSetHeader(1, fake_hash)),
            timestamp=vote.timestamp,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        shadow.signature = self._inner._priv.sign(
            shadow.sign_bytes(chain_id))
        self.double_signed += 1
        return shadow


def maybe_wrap(privval, env: dict | None = None):
    """Wrap `privval` when the byzantine env var is set (node.py)."""
    raw = (env if env is not None else os.environ).get(ENV_VAR)
    if not raw:
        return privval
    return ByzantineValv(privval, parse_schedule(raw))
