"""cometbft_tpu — a TPU-native BFT state-machine-replication framework.

A from-scratch rebuild of the capabilities of CometBFT (Tendermint
consensus + ABCI), designed TPU-first:

- **Control plane** (consensus state machine, p2p, storage, RPC): host-side
  Python/C++, sequential and I/O bound.
- **Data plane** (Ed25519/sr25519 batch signature verification, SHA-256
  merkle hashing): JAX kernels on TPU, batched over the signature axis,
  sharded over a device mesh with `shard_map` for multi-chip scale-out.

Reference behavior parity is tracked against CometBFT (see SURVEY.md);
file:line citations in docstrings point at the reference implementation
whose *behavior* (not code) each component mirrors.
"""

__version__ = "0.3.0"

# The persistent XLA compilation cache is configured in
# cometbft_tpu/ops/__init__.py (every device-kernel path imports it);
# this jax build ignores the JAX_COMPILATION_CACHE_DIR env var, so the
# config must be applied via jax.config.update after jax is imported.
