from .admission import AdmissionPipeline, parse_signed_tx, wrap_signed_tx
from .mempool import CListMempool, LRUTxCache, NopMempool, TxKey

__all__ = [
    "AdmissionPipeline",
    "CListMempool",
    "LRUTxCache",
    "NopMempool",
    "TxKey",
    "parse_signed_tx",
    "wrap_signed_tx",
]
