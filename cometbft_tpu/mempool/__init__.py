from .mempool import CListMempool, LRUTxCache, NopMempool, TxKey

__all__ = ["CListMempool", "LRUTxCache", "NopMempool", "TxKey"]
