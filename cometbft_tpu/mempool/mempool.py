"""Transaction mempool.

Behavior parity: reference mempool/clist_mempool.go —
- CheckTx admission through the app's mempool connection (:252 CheckTx,
  :389 resCbFirstTime): only code==OK txs enter the pool; everything seen
  recently sits in an LRU dedup cache (mempool/cache.go:35 LRUTxCache).
- Ordering: FIFO insertion order (the reference's concurrent linked list
  collapses to an ordered dict under Python's GIL; the wait/gossip seam
  is the on_new_tx/on_new_txs callbacks).
- Reap honors max_bytes/max_gas (:~500 ReapMaxBytesMaxGas).
- Update after a committed block (:~560): committed txs leave the pool
  (and stay in cache so peers can't replay them); survivors are
  re-CheckTx'd (recheck) because the app state changed.
- Lock/Unlock around proposal creation + update (reference Mempool
  interface, mempool/mempool.go:145).

Divergence from the reference, deliberate (PR 8): admission is split
into lock-free prechecks, an UNLOCKED app CheckTx round, and a locked
insert — so the mempool lock is never held across an app (or signature)
call on the admission path. The micro-batched pipeline
(mempool/admission.py) drives the same three stages once per window;
the direct path here is the window-of-one degenerate case. Gossip
callbacks fire from a dedicated notifier thread, never from the
admitting (RPC/peer) thread, so a slow subscriber cannot stall
admission.
"""

from __future__ import annotations

import hashlib
import threading

from ..utils.metrics import mempool_metrics
from ..utils import txlife as _txlife
from .txcolumns import TxColumns
from collections import OrderedDict, deque
from dataclasses import dataclass


def TxKey(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


class LRUTxCache:
    """Fixed-size LRU of tx keys (reference mempool/cache.go:35)."""

    def __init__(self, size: int = 10000):
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()

    def push(self, key: bytes) -> bool:
        """False if already present (moves it to front like the reference)."""
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self._size:
                self._map.popitem(last=False)
            return True

    def remove(self, key: bytes) -> None:
        with self._lock:
            self._map.pop(key, None)

    def has(self, key: bytes) -> bool:
        with self._lock:
            return key in self._map

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


@dataclass
class _MempoolTx:
    tx: bytes
    height: int  # height when admitted
    gas_wanted: int


class ErrTxInCache(Exception):
    pass


class ErrMempoolFull(Exception):
    def __init__(self, size, max_size):
        super().__init__(f"mempool full: {size} >= {max_size}")


class ErrTxTooLarge(Exception):
    pass


class CListMempool:
    def __init__(
        self,
        app_conns,
        max_txs: int = 5000,
        max_tx_bytes: int = 1024 * 1024,
        cache_size: int = 10000,
        keep_invalid_txs_in_cache: bool = False,
        recheck_window: int = 256,
        verify_sigs: bool = False,
    ):
        self.app = app_conns
        self.max_txs = max_txs
        self.max_tx_bytes = max_tx_bytes
        self.keep_invalid = keep_invalid_txs_in_cache
        self.recheck_window = max(1, recheck_window)
        # verify STX-enveloped tx signatures at admission even on the
        # direct (pipeline-less) path — one native single-verify per tx,
        # the honest per-tx baseline the batched pipeline amortizes
        self.verify_sigs = verify_sigs
        self.cache = LRUTxCache(cache_size)
        self._txs: OrderedDict[bytes, _MempoolTx] = OrderedDict()
        self._lock = threading.RLock()  # the consensus Lock/Unlock seam
        self._bytes = 0  # running byte total (total_bytes was an O(N) scan)
        # monotonic pool-content version: bumped whenever the set of
        # reapable txs changes (insert/update/flush). The speculative
        # proposal seam compares versions across the speculation window
        # — a bump means the reap it ran is stale and the block must be
        # discarded (ISSUE 11).
        self.version = 0
        self.height = 0
        # gossip seams (p2p reactor subscribes): on_new_txs gets the
        # whole admitted window in one call; on_new_tx is the legacy
        # per-tx form. Both fire from the notifier thread.
        self.on_new_tx: list = []
        self.on_new_txs: list = []
        self._notify_q: deque[list[bytes]] = deque(maxlen=1024)
        self._notify_cv = threading.Condition()
        self._notify_thread: threading.Thread | None = None
        self._notify_stopped = False
        # optional micro-batched admission pipeline; when attached,
        # check_tx/submit_tx route through it
        self.pipeline = None

    # -- Mempool interface -------------------------------------------------
    def lock(self) -> None:
        self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    def size(self) -> int:
        return len(self._txs)

    def total_bytes(self) -> int:
        return self._bytes

    # -- admission stages (shared by the direct path and the pipeline) ----
    def precheck(self, tx: bytes) -> bytes:
        """Lock-free per-tx admission prechecks: oversize, LRU dedup,
        fast-fail on a full pool. Returns the tx key; raises the per-tx
        rejection. Claims the cache slot (first-wins), so the caller
        owns cleanup on later rejection (note_rejected)."""
        if len(tx) > self.max_tx_bytes:
            raise ErrTxTooLarge(f"tx {len(tx)}B > {self.max_tx_bytes}B")
        key = TxKey(tx)
        if not self.cache.push(key):
            raise ErrTxInCache(f"tx {key.hex()[:12]} already seen")
        if len(self._txs) >= self.max_txs:
            self.cache.remove(key)
            raise ErrMempoolFull(len(self._txs), self.max_txs)
        return key

    def app_check_batch(self, txs: list[bytes]) -> list:
        """One app CheckTx round for a window of txs. Uses the client's
        batched `check_txs` when it has one (LocalClient: one shared-
        mutex acquisition per window; SocketClient: pipelined requests),
        else falls back to per-tx calls. Never called with the mempool
        lock held on the admission path."""
        conn = self.app.mempool
        fn = getattr(conn, "check_txs", None)
        if fn is not None:
            res = fn(txs)
            if res is not None and len(res) == len(txs):
                return res
        return [conn.check_tx(tx) for tx in txs]

    def note_rejected(self, key: bytes) -> None:
        """Bookkeeping for a tx rejected after precheck claimed its
        cache slot (app code != 0 or bad signature)."""
        if not self.keep_invalid:
            self.cache.remove(key)
        mempool_metrics().failed_txs.inc()

    def insert_batch(self, items: list[tuple[bytes, bytes, int]]):
        """Insert app-approved txs FIFO under ONE lock acquisition.
        items = [(key, tx, gas_wanted)]; returns a per-item list of
        None (inserted) or the rejection to deliver to that caller."""
        errs: list = []
        m = mempool_metrics()
        with self._lock:
            for key, tx, gas_wanted in items:
                if len(self._txs) >= self.max_txs:
                    self.cache.remove(key)
                    errs.append(ErrMempoolFull(len(self._txs), self.max_txs))
                    continue
                if key in self._txs:  # lost a race to an identical tx
                    errs.append(ErrTxInCache(f"tx {key.hex()[:12]} already seen"))
                    continue
                self._txs[key] = _MempoolTx(tx, self.height, gas_wanted)
                self._bytes += len(tx)
                errs.append(None)
                self.version += 1
            m.size.set(len(self._txs))
            m.tx_bytes.set(self._bytes)
        return errs

    # -- gossip notifier ---------------------------------------------------
    def notify_new_txs(self, txs: list[bytes]) -> None:
        """Hand newly admitted txs to the gossip subscribers from a
        dedicated thread — the admitting (RPC/peer/drainer) thread never
        runs subscriber code, so a slow peer cannot stall admission."""
        if not txs or not (self.on_new_tx or self.on_new_txs):
            return
        with self._notify_cv:
            if self._notify_stopped:
                return
            if self._notify_thread is None:
                self._notify_thread = threading.Thread(
                    target=self._notify_loop, daemon=True,
                    name="mempool-notify",
                )
                self._notify_thread.start()
            self._notify_q.append(list(txs))
            self._notify_cv.notify()

    def _notify_loop(self) -> None:
        while True:
            with self._notify_cv:
                while not self._notify_q and not self._notify_stopped:
                    self._notify_cv.wait()
                if self._notify_stopped:
                    return
                txs = self._notify_q.popleft()
            for cb in self.on_new_txs:
                try:
                    cb(txs)
                except Exception:  # noqa: BLE001 — subscriber bug ≠ mempool bug
                    pass
            for cb in self.on_new_tx:
                for tx in txs:
                    try:
                        cb(tx)
                    except Exception:  # noqa: BLE001
                        pass

    def attach_pipeline(self, pipeline) -> None:
        self.pipeline = pipeline

    def close(self) -> None:
        """Stop the admission pipeline and the notifier thread."""
        if self.pipeline is not None:
            # terminal close (refuses late submits) where available;
            # plain stop() keeps duck-typed pipelines working
            closer = getattr(self.pipeline, "close", None)
            if closer is not None:
                closer()
            else:
                self.pipeline.stop()
        with self._notify_cv:
            self._notify_stopped = True
            self._notify_cv.notify_all()
        t = self._notify_thread
        if t is not None:
            t.join(timeout=2.0)

    # -- admission entry points --------------------------------------------
    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        """Admit a tx (raises on rejection; reference CheckTx :252).
        Routed through the micro-batched pipeline when one is attached;
        the result is delivered via the tx's future so semantics are
        unchanged."""
        if self.pipeline is not None:
            self.pipeline.check_tx(tx, from_peer)
            return
        key = self.precheck(tx)
        _txlife.stage_key(key, "verify_start")
        if self.verify_sigs:
            from .admission import SIGN_CONTEXT, parse_signed_tx

            parsed = parse_signed_tx(tx)
            if parsed is not None:
                pub, sig, payload = parsed
                from ..crypto.ed25519 import Ed25519PubKey

                try:
                    ok = Ed25519PubKey(pub).verify_signature(
                        SIGN_CONTEXT + payload, sig)
                except ValueError:
                    ok = False
                if not ok:
                    self.note_rejected(key)
                    raise ValueError("tx rejected: invalid signature")
        _txlife.stage_key(key, "verify_end")
        resp = self.app_check_batch([tx])[0]  # no mempool lock held
        if resp.code != 0:
            self.note_rejected(key)
            raise ValueError(f"tx rejected by app: code {resp.code}")
        _txlife.stage_key(key, "app_check")
        err = self.insert_batch([(key, tx, resp.gas_wanted)])[0]
        if err is not None:
            raise err
        _txlife.stage_key(key, "insert")
        self.notify_new_txs([tx])

    def submit_tx(self, tx: bytes, from_peer: str = ""):
        """Non-blocking admission: returns a Future that raises the
        per-tx rejection (or resolves to None). Without a pipeline the
        work happens inline and the future is already resolved."""
        if self.pipeline is not None:
            return self.pipeline.submit(tx, from_peer)
        from concurrent.futures import Future

        fut: Future = Future()
        try:
            self.check_tx(tx, from_peer)
            fut.set_result(None)
        except Exception as exc:  # noqa: BLE001 — delivered via future
            fut.set_exception(exc)
        return fut

    def reap_max_txs(self, n: int = -1) -> list[bytes]:
        """First n txs in FIFO order without budget accounting (reference
        ReapMaxTxs — serves the unconfirmed_txs RPC page cheaply)."""
        with self._lock:
            out = []
            for t in self._txs.values():
                if 0 <= n <= len(out):
                    break
                out.append(t.tx)
            return out

    def reap_max_bytes_max_gas(self, max_bytes: int = -1, max_gas: int = -1
                               ) -> list[bytes]:
        """FIFO reap under byte/gas budgets (reference ReapMaxBytesMaxGas)."""
        out, total_b, total_g = [], 0, 0
        with self._lock:
            for t in self._txs.values():
                if max_bytes >= 0 and total_b + len(t.tx) > max_bytes:
                    break
                if max_gas >= 0 and total_g + t.gas_wanted > max_gas:
                    break
                out.append(t.tx)
                total_b += len(t.tx)
                total_g += t.gas_wanted
        return out

    def reap_columns(self, max_bytes: int = -1, max_gas: int = -1
                     ) -> TxColumns:
        """Columnar reap: the same FIFO budget walk as
        reap_max_bytes_max_gas, but the result is ONE contiguous blob +
        offsets built under a single lock acquisition — the proposal
        path carries it through prepare_proposal, Data hash/encode, and
        block parts without re-materializing per-tx byte strings."""
        chunks: list[bytes] = []
        offsets = [0]
        total_b, total_g = 0, 0
        with self._lock:
            for t in self._txs.values():
                if max_bytes >= 0 and total_b + len(t.tx) > max_bytes:
                    break
                if max_gas >= 0 and total_g + t.gas_wanted > max_gas:
                    break
                chunks.append(t.tx)
                total_b += len(t.tx)
                total_g += t.gas_wanted
                offsets.append(total_b)
        return TxColumns(b"".join(chunks), offsets)

    def update(self, height: int, committed_txs: list[bytes],
               results=None) -> None:
        """Post-commit bookkeeping + recheck (reference Update :~560).

        Caller must hold the mempool lock (the executor's commit path).
        The recheck runs in `recheck_window`-sized batches, so the
        consensus-held lock window costs ceil(N/window) app calls
        instead of N."""
        self.height = height
        self.version += 1
        for i, tx in enumerate(committed_txs):
            key = TxKey(tx)
            code = results[i].code if results else 0
            if code == 0:
                self.cache.push(key)  # committed: never re-admit
            elif not self.keep_invalid:
                self.cache.remove(key)
            dropped = self._txs.pop(key, None)
            if dropped is not None:
                self._bytes -= len(dropped.tx)
        # recheck survivors against the new app state
        if self._txs:
            mempool_metrics().recheck_times.inc()
        keys = list(self._txs.keys())
        for i in range(0, len(keys), self.recheck_window):
            chunk = keys[i:i + self.recheck_window]
            responses = self.app_check_batch([self._txs[k].tx for k in chunk])
            for key, resp in zip(chunk, responses):
                if resp.code != 0:
                    dropped = self._txs.pop(key, None)
                    if dropped is not None:
                        self._bytes -= len(dropped.tx)
                    if not self.keep_invalid:
                        self.cache.remove(key)
        m = mempool_metrics()
        m.size.set(len(self._txs))
        m.tx_bytes.set(self._bytes)

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self.cache.reset()
            self._bytes = 0
            self.version += 1
            m = mempool_metrics()
            m.size.set(0)
            m.tx_bytes.set(0)

    def txs_available(self) -> bool:
        return bool(self._txs)


class NopMempool:
    """Disabled mempool (reference mempool/nop_mempool.go:111)."""

    version = 0

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self) -> int:
        return 0

    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        raise RuntimeError("mempool disabled")

    def reap_max_bytes_max_gas(self, max_bytes: int = -1, max_gas: int = -1):
        return []

    def reap_columns(self, max_bytes: int = -1, max_gas: int = -1):
        return TxColumns(b"", [0])

    def update(self, height, committed_txs, results=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def txs_available(self) -> bool:
        return False
