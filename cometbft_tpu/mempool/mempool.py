"""Transaction mempool.

Behavior parity: reference mempool/clist_mempool.go —
- CheckTx admission through the app's mempool connection (:252 CheckTx,
  :389 resCbFirstTime): only code==OK txs enter the pool; everything seen
  recently sits in an LRU dedup cache (mempool/cache.go:35 LRUTxCache).
- Ordering: FIFO insertion order (the reference's concurrent linked list
  collapses to an ordered dict under Python's GIL; the wait/gossip seam
  is the on_new_tx callbacks).
- Reap honors max_bytes/max_gas (:~500 ReapMaxBytesMaxGas).
- Update after a committed block (:~560): committed txs leave the pool
  (and stay in cache so peers can't replay them); survivors are
  re-CheckTx'd (recheck) because the app state changed.
- Lock/Unlock around proposal creation + update (reference Mempool
  interface, mempool/mempool.go:145).
"""

from __future__ import annotations

import hashlib
import threading

from ..utils.metrics import mempool_metrics
from collections import OrderedDict
from dataclasses import dataclass


def TxKey(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


class LRUTxCache:
    """Fixed-size LRU of tx keys (reference mempool/cache.go:35)."""

    def __init__(self, size: int = 10000):
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()

    def push(self, key: bytes) -> bool:
        """False if already present (moves it to front like the reference)."""
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self._size:
                self._map.popitem(last=False)
            return True

    def remove(self, key: bytes) -> None:
        with self._lock:
            self._map.pop(key, None)

    def has(self, key: bytes) -> bool:
        with self._lock:
            return key in self._map

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


@dataclass
class _MempoolTx:
    tx: bytes
    height: int  # height when admitted
    gas_wanted: int


class ErrTxInCache(Exception):
    pass


class ErrMempoolFull(Exception):
    def __init__(self, size, max_size):
        super().__init__(f"mempool full: {size} >= {max_size}")


class ErrTxTooLarge(Exception):
    pass


class CListMempool:
    def __init__(
        self,
        app_conns,
        max_txs: int = 5000,
        max_tx_bytes: int = 1024 * 1024,
        cache_size: int = 10000,
        keep_invalid_txs_in_cache: bool = False,
    ):
        self.app = app_conns
        self.max_txs = max_txs
        self.max_tx_bytes = max_tx_bytes
        self.keep_invalid = keep_invalid_txs_in_cache
        self.cache = LRUTxCache(cache_size)
        self._txs: OrderedDict[bytes, _MempoolTx] = OrderedDict()
        self._lock = threading.RLock()  # the consensus Lock/Unlock seam
        self.height = 0
        self.on_new_tx: list = []  # gossip seam (p2p reactor subscribes)

    # -- Mempool interface -------------------------------------------------
    def lock(self) -> None:
        self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    def size(self) -> int:
        return len(self._txs)

    def total_bytes(self) -> int:
        return sum(len(t.tx) for t in self._txs.values())

    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        """Admit a tx (raises on rejection; reference CheckTx :252)."""
        if len(tx) > self.max_tx_bytes:
            raise ErrTxTooLarge(f"tx {len(tx)}B > {self.max_tx_bytes}B")
        key = TxKey(tx)
        if not self.cache.push(key):
            raise ErrTxInCache(f"tx {key.hex()[:12]} already seen")
        with self._lock:
            if len(self._txs) >= self.max_txs:
                self.cache.remove(key)
                raise ErrMempoolFull(len(self._txs), self.max_txs)
            resp = self.app.mempool.check_tx(tx)
            if resp.code != 0:
                if not self.keep_invalid:
                    self.cache.remove(key)
                mempool_metrics().failed_txs.inc()
                raise ValueError(f"tx rejected by app: code {resp.code}")
            self._txs[key] = _MempoolTx(tx, self.height, resp.gas_wanted)
            mempool_metrics().size.set(len(self._txs))
        for cb in self.on_new_tx:
            cb(tx)

    def reap_max_txs(self, n: int = -1) -> list[bytes]:
        """First n txs in FIFO order without budget accounting (reference
        ReapMaxTxs — serves the unconfirmed_txs RPC page cheaply)."""
        with self._lock:
            out = []
            for t in self._txs.values():
                if 0 <= n <= len(out):
                    break
                out.append(t.tx)
            return out

    def reap_max_bytes_max_gas(self, max_bytes: int = -1, max_gas: int = -1
                               ) -> list[bytes]:
        """FIFO reap under byte/gas budgets (reference ReapMaxBytesMaxGas)."""
        out, total_b, total_g = [], 0, 0
        with self._lock:
            for t in self._txs.values():
                if max_bytes >= 0 and total_b + len(t.tx) > max_bytes:
                    break
                if max_gas >= 0 and total_g + t.gas_wanted > max_gas:
                    break
                out.append(t.tx)
                total_b += len(t.tx)
                total_g += t.gas_wanted
        return out

    def update(self, height: int, committed_txs: list[bytes],
               results=None) -> None:
        """Post-commit bookkeeping + recheck (reference Update :~560).

        Caller must hold the mempool lock (the executor's commit path)."""
        self.height = height
        for i, tx in enumerate(committed_txs):
            key = TxKey(tx)
            code = results[i].code if results else 0
            if code == 0:
                self.cache.push(key)  # committed: never re-admit
            elif not self.keep_invalid:
                self.cache.remove(key)
            self._txs.pop(key, None)
        # recheck survivors against the new app state
        if self._txs:
            mempool_metrics().recheck_times.inc()
        for key in list(self._txs.keys()):
            t = self._txs[key]
            resp = self.app.mempool.check_tx(t.tx)
            if resp.code != 0:
                self._txs.pop(key, None)
                if not self.keep_invalid:
                    self.cache.remove(key)
        mempool_metrics().size.set(len(self._txs))

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self.cache.reset()
            mempool_metrics().size.set(0)

    def txs_available(self) -> bool:
        return bool(self._txs)


class NopMempool:
    """Disabled mempool (reference mempool/nop_mempool.go:111)."""

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self) -> int:
        return 0

    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        raise RuntimeError("mempool disabled")

    def reap_max_bytes_max_gas(self, max_bytes: int = -1, max_gas: int = -1):
        return []

    def update(self, height, committed_txs, results=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def txs_available(self) -> bool:
        return False
