"""Mempool reactor: tx gossip over p2p (reference mempool/reactor.go).

Broadcasts newly admitted txs to peers on the mempool channel; received
txs go through CheckTx with the sender recorded so they are not echoed
back (the reference tracks per-peer send state; v1 relies on the LRU
cache to stop loops)."""

from __future__ import annotations

import random

from ..encoding import proto as pb
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor

MEMPOOL_CHANNEL = 0x30


class MempoolReactor(Reactor):
    def __init__(self, mempool, max_gossip_peers: int = 0):
        """max_gossip_peers > 0 caps tx fan-out to that many peers per
        broadcast (the reference's experimental
        max-gossip-connections-to-{persistent,non-persistent}-peers
        bound, mempool/reactor.go): in dense topologies flooding every
        peer mostly delivers duplicates, and the cap trades redundancy
        for bandwidth. 0 = flood all peers (default, like the
        reference)."""
        self.mempool = mempool
        self.switch = None
        self.max_gossip_peers = max_gossip_peers
        mempool.on_new_tx.append(self._broadcast_tx)

    def channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    def set_switch(self, switch) -> None:
        self.switch = switch

    def _broadcast_tx(self, tx: bytes) -> None:
        if self.switch is None:
            return
        payload = pb.f_bytes(1, tx, emit_empty=True)
        if self.max_gossip_peers <= 0:
            self.switch.broadcast(MEMPOOL_CHANNEL, payload)
            return
        # sample a fresh subset per broadcast: a fixed prefix would
        # permanently starve the peers beyond the cap
        peers = list(self.switch.peers())
        if len(peers) > self.max_gossip_peers:
            peers = random.sample(peers, self.max_gossip_peers)
        for peer in peers:
            try:
                peer.send(MEMPOOL_CHANNEL, payload)
            except Exception:  # noqa: BLE001 — dead peer: skip
                continue

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        d = pb.fields_to_dict(msg)
        tx = pb.as_bytes(d.get(1, b""))
        try:
            self.mempool.check_tx(tx, from_peer=peer.id)
        except Exception:  # noqa: BLE001 — dup/full/invalid: drop
            pass
