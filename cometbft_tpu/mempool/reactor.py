"""Mempool reactor: tx gossip over p2p (reference mempool/reactor.go).

Broadcasts newly admitted txs to peers on the mempool channel; received
txs go through CheckTx with the sender recorded so they are not echoed
back (the reference tracks per-peer send state; v1 relies on the LRU
cache to stop loops).

PR 8: gossip is batched end-to-end. The mempool's notifier hands the
reactor whole admission windows (`on_new_txs`), which it coalesces into
one multi-tx wire frame (repeated field 1 — old single-tx frames are
the n=1 case, so mixed-version links keep working) and hands to the
switch's backpressure-aware broadcast queue instead of fanning out
per-tx from the admitting thread. Received frames feed the admission
pipeline via the non-blocking submit path."""

from __future__ import annotations

import random

from ..encoding import proto as pb
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..utils import txlife as _txlife

MEMPOOL_CHANNEL = 0x30


class MempoolReactor(Reactor):
    def __init__(self, mempool, max_gossip_peers: int = 0):
        """max_gossip_peers > 0 caps tx fan-out to that many peers per
        broadcast (the reference's experimental
        max-gossip-connections-to-{persistent,non-persistent}-peers
        bound, mempool/reactor.go): in dense topologies flooding every
        peer mostly delivers duplicates, and the cap trades redundancy
        for bandwidth. 0 = flood all peers (default, like the
        reference)."""
        self.mempool = mempool
        self.switch = None
        self.max_gossip_peers = max_gossip_peers
        # prefer the batched seam; plain mempool doubles (tests) may
        # only expose the legacy per-tx list
        batch_seam = getattr(mempool, "on_new_txs", None)
        if batch_seam is not None:
            batch_seam.append(self._broadcast_txs)
        else:
            mempool.on_new_tx.append(self._broadcast_tx)

    def channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    def set_switch(self, switch) -> None:
        self.switch = switch

    def _broadcast_tx(self, tx: bytes) -> None:
        self._broadcast_txs([tx])

    def _broadcast_txs(self, txs: list[bytes]) -> None:
        if self.switch is None or not txs:
            return
        # one frame per window: repeated field 1
        payload = b"".join(
            pb.f_bytes(1, tx, emit_empty=True) for tx in txs
        )
        if self.max_gossip_peers <= 0:
            # flood path: queue on the switch's async broadcast worker
            # (backpressure-aware) when available
            enqueue = getattr(self.switch, "queue_broadcast", None)
            if enqueue is not None:
                enqueue(MEMPOOL_CHANNEL, payload)
            else:
                self.switch.broadcast(MEMPOOL_CHANNEL, payload)
            return
        # sample a fresh subset per broadcast: a fixed prefix would
        # permanently starve the peers beyond the cap
        peers = list(self.switch.peers())
        if len(peers) > self.max_gossip_peers:
            peers = random.sample(peers, self.max_gossip_peers)
        for peer in peers:
            try:
                peer.send(MEMPOOL_CHANNEL, payload)
            except Exception:  # noqa: BLE001 — dead peer: skip
                continue

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        # multi-tx frames carry repeated field 1; fields_to_dict is
        # last-wins, so walk the raw field list
        txs = [
            pb.as_bytes(v)
            for f, _wt, v in pb.parse_fields(msg)
            if f == 1
        ]
        submit = getattr(self.mempool, "submit_tx", None)
        for tx in txs:
            if _txlife.enabled:
                _txlife.track(tx, "arrival", src="gossip")
            try:
                if submit is not None:
                    # non-blocking: the admission pipeline delivers the
                    # verdict to the future; peer gossip ignores it
                    fut = submit(tx, from_peer=peer.id)
                    fut.add_done_callback(_swallow)
                else:
                    self.mempool.check_tx(tx, from_peer=peer.id)
            except Exception:  # noqa: BLE001 — dup/full/invalid: drop
                pass


def _swallow(fut) -> None:
    fut.exception()  # consume so rejected gossip doesn't warn
