"""Mempool reactor: tx gossip over p2p (reference mempool/reactor.go).

Broadcasts newly admitted txs to peers on the mempool channel; received
txs go through CheckTx with the sender recorded so they are not echoed
back (the reference tracks per-peer send state; v1 relies on the LRU
cache to stop loops)."""

from __future__ import annotations

from ..encoding import proto as pb
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Reactor

MEMPOOL_CHANNEL = 0x30


class MempoolReactor(Reactor):
    def __init__(self, mempool):
        self.mempool = mempool
        self.switch = None
        mempool.on_new_tx.append(self._broadcast_tx)

    def channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    def set_switch(self, switch) -> None:
        self.switch = switch

    def _broadcast_tx(self, tx: bytes) -> None:
        if self.switch is not None:
            self.switch.broadcast(MEMPOOL_CHANNEL, pb.f_bytes(1, tx, emit_empty=True))

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        d = pb.fields_to_dict(msg)
        tx = pb.as_bytes(d.get(1, b""))
        try:
            self.mempool.check_tx(tx, from_peer=peer.id)
        except Exception:  # noqa: BLE001 — dup/full/invalid: drop
            pass
