"""Micro-batched CheckTx admission pipeline.

The per-tx admission path costs, for every tx: one app round-trip under
the mempool lock, one signature verify (when txs are signed), and one
lock acquisition — all serialized. Under sustained ingress from many
concurrent `broadcast_tx_*` callers and gossiping peers, those per-tx
costs dominate. This pipeline amortizes all three over a window:

  RPC handlers / peer receives --submit()--> admission queue
                                                  |
                             drainer collects a window
                             (<= `window` txs or `max_delay_s`)
                                                  |
            stage 0: per-tx prechecks, lock-free (size, LRU dedup)
            stage 1: ONE batch signature verify for the window
                     (crypto dispatch — the same engine that runs the
                     commit-verify mega-batches)
            stage 2: ONE batched app CheckTx round (`check_txs`),
                     no mempool lock held
            stage 3: mempool lock taken ONCE, survivors inserted FIFO
                                                  |
                       per-tx futures resolve -> blocked callers

`check_tx()` blocks on the tx's future and re-raises the per-tx error,
so `broadcast_tx_sync` semantics are identical to the direct path; only
the cost model changes. Lock-order note: the drainer takes the app lock
(inside `check_txs`) and the mempool lock at *disjoint* times, never
nested, while the consensus executor takes mempool-then-app — since the
drainer never holds the app lock while waiting on the mempool lock,
there is no ABBA deadlock.

Signed-tx envelope: txs of the form

    b"STX\\x01" | pub(32) | sig(64) | payload

get their ed25519 signature checked in stage 1 (sig over
``SIGN_CONTEXT + payload``); bare txs skip stage 1. The KVStore app
parses the payload's ``key=value`` regardless, so signed load rides
through the whole stack unchanged.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

from ..crypto.ed25519 import Ed25519BatchVerifier, Ed25519PubKey
from ..utils.metrics import mempool_metrics
from ..utils import trace as _trace
from ..utils import txlife as _txlife

STX_MAGIC = b"STX\x01"
SIGN_CONTEXT = b"cometbft-tpu/tx/v1"
_STX_HEADER = len(STX_MAGIC) + 32 + 64


def wrap_signed_tx(priv, payload: bytes) -> bytes:
    """Envelope `payload` with the signer's pubkey and signature."""
    sig = priv.sign(SIGN_CONTEXT + payload)
    return STX_MAGIC + priv.pub_key().bytes() + sig + payload


def parse_signed_tx(tx: bytes):
    """(pub_bytes, sig, payload) for an STX envelope, else None."""
    if not tx.startswith(STX_MAGIC) or len(tx) < _STX_HEADER:
        return None
    off = len(STX_MAGIC)
    return tx[off:off + 32], tx[off + 32:off + 96], tx[_STX_HEADER:]


def _fail(fut: Future, exc: Exception) -> None:
    """Fail a per-tx future, tolerating resolution races: stop() may
    fail an in-flight window that a wedged drainer later resolves (or
    the reverse), and a future must only be resolved once."""
    if not fut.done():
        try:
            fut.set_exception(exc)
        except Exception:  # noqa: BLE001 — lost the race, already done
            pass


def _ok(fut: Future) -> None:
    if not fut.done():
        try:
            fut.set_result(None)
        except Exception:  # noqa: BLE001 — lost the race, already done
            pass


class _Entry:
    __slots__ = ("tx", "from_peer", "future", "t_enqueue", "key",
                 "gas_wanted")

    def __init__(self, tx: bytes, from_peer: str):
        self.tx = tx
        self.from_peer = from_peer
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.key = None
        self.gas_wanted = 0


class AdmissionPipeline:
    """Window drainer over an admission queue feeding a CListMempool."""

    def __init__(
        self,
        mempool,
        window: int = 256,
        max_delay_s: float = 0.002,
        verify_sigs: bool = True,
        backend: str = "tpu",
        queue_limit: int = 0,
        sched=None,
        tenant: str = "",
    ):
        self.mempool = mempool
        self.window = max(1, int(window))
        self.max_delay_s = max(0.0, float(max_delay_s))
        self.verify_sigs = verify_sigs
        self.backend = backend
        self.sched = sched  # shared VerifyScheduler (crypto/sched.py)
        self.tenant = tenant
        # 0 = derive from window: enough backlog to keep the drainer fed
        # without letting a stalled app grow the queue unboundedly
        self.queue_limit = queue_limit or self.window * 64
        self._q: deque[_Entry] = deque()
        self._cv = threading.Condition()
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._closed = False
        # window the drainer popped but has not finished processing —
        # stop() fails these too when the drainer won't exit in time
        self._inflight: list[_Entry] = []
        self.stop_timeout_s = 2.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._cv:
            if self._thread is not None:
                return
            self._stopped = False
            self._thread = threading.Thread(
                target=self._drain_loop, daemon=True, name="mempool-admit"
            )
            self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=self.stop_timeout_s)
        self._thread = None
        # Fail whatever is still queued so blocked callers unblock — and
        # when the drainer did not exit within the timeout (wedged in a
        # slow app CheckTx round, say), the in-flight window too:
        # nobody else will ever resolve those futures. _fail/_ok
        # tolerate the drainer limping in later.
        with self._cv:
            pending = list(self._q)
            self._q.clear()
            pending.extend(self._inflight)
        exc = RuntimeError("admission pipeline stopped")
        for e in pending:
            _fail(e.future, exc)

    def close(self) -> None:
        """Terminal stop for node shutdown: also refuses future submits
        (no lazy drainer restart — late callers get an immediate error
        instead of parking on a queue nobody drains)."""
        with self._cv:
            self._closed = True
        self.stop()

    # -- producer side -----------------------------------------------------
    def submit(self, tx: bytes, from_peer: str = "") -> Future:
        """Enqueue a tx; the returned future resolves to None on
        admission or raises the per-tx rejection."""
        e = _Entry(tx, from_peer)
        with self._cv:
            if self._closed:
                e.future.set_exception(
                    RuntimeError("admission pipeline closed"))
                return e.future
            if self._stopped or self._thread is None:
                # lazy start: the first submit after construction (or a
                # node that never called start()) spins the drainer up
                self._stopped = False
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._drain_loop, daemon=True,
                        name="mempool-admit",
                    )
                    self._thread.start()
            if len(self._q) >= self.queue_limit:
                e.future.set_exception(
                    ErrAdmissionQueueFull(len(self._q), self.queue_limit))
                return e.future
            self._q.append(e)
            mempool_metrics().admit_queue_depth.set(len(self._q))
            self._cv.notify()
        if _txlife.enabled:
            _txlife.track(tx, "enqueue")
        return e.future

    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        """Blocking facade with direct-path semantics: raises the same
        ErrTxInCache/ErrMempoolFull/ErrTxTooLarge/ValueError the caller
        would get from CListMempool.check_tx."""
        self.submit(tx, from_peer).result()

    # -- drainer -----------------------------------------------------------
    def _drain_loop(self) -> None:
        while True:
            batch: list[_Entry] = []
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                # first tx opens the window; linger up to max_delay_s
                # for the window to fill (latency bound), then drain up
                # to `window` txs (size bound)
                deadline = self._q[0].t_enqueue + self.max_delay_s
                while (len(self._q) < self.window
                       and not self._stopped):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                while self._q and len(batch) < self.window:
                    batch.append(self._q.popleft())
                self._inflight = batch
                mempool_metrics().admit_queue_depth.set(len(self._q))
            if batch:
                try:
                    self._process_window(batch)
                except Exception as exc:  # noqa: BLE001 — deliver, don't die
                    for e in batch:
                        _fail(e.future, exc)
                finally:
                    with self._cv:
                        self._inflight = []

    def _process_window(self, batch: list[_Entry]) -> None:
        m = mempool_metrics()
        t0 = time.perf_counter()
        m.admit_window_size.observe(len(batch))

        # stage 0 — lock-free prechecks: oversize, LRU dedup (which also
        # collapses duplicates WITHIN the window: cache.push is
        # first-wins), fast-fail when the pool is already full
        live: list[_Entry] = []
        for e in batch:
            try:
                e.key = self.mempool.precheck(e.tx)
            except Exception as exc:  # noqa: BLE001 — per-tx verdict
                _fail(e.future, exc)
                continue
            live.append(e)
        n_dup = len(batch) - len(live)

        # stage 1 — one batch signature verify for the window's signed
        # envelopes, through the crypto dispatch (native/rlc/ladder)
        n_sig_fail = 0
        t1 = time.perf_counter()
        if _txlife.enabled:
            for e in live:
                _txlife.stage_key(e.key, "verify_start")
        if self.verify_sigs and live:
            live, n_sig_fail = self._verify_stage(live)
        t2 = time.perf_counter()
        if _txlife.enabled:
            for e in live:
                _txlife.stage_key(e.key, "verify_end")

        # stage 2 — one batched app CheckTx round; no mempool lock held
        n_app_fail = 0
        if live:
            results = self.mempool.app_check_batch([e.tx for e in live])
            kept: list[_Entry] = []
            for e, res in zip(live, results):
                if res.code != 0:
                    self.mempool.note_rejected(e.key)
                    _fail(e.future,
                          ValueError(f"tx rejected by app: code {res.code}"))
                    n_app_fail += 1
                    continue
                e.gas_wanted = res.gas_wanted
                kept.append(e)
            live = kept
        t3 = time.perf_counter()
        if _txlife.enabled:
            for e in live:
                _txlife.stage_key(e.key, "app_check")

        # stage 3 — single lock acquisition: insert survivors FIFO
        admitted: list[bytes] = []
        if live:
            errs = self.mempool.insert_batch(
                [(e.key, e.tx, e.gas_wanted) for e in live])
            for e, err in zip(live, errs):
                if err is not None:
                    _fail(e.future, err)
                else:
                    admitted.append(e.tx)
                    if _txlife.enabled:
                        _txlife.stage_key(e.key, "insert")
                    _ok(e.future)
        t4 = time.perf_counter()

        for e in batch:
            if e.future.done() and e.future.exception() is None:
                m.admit_latency.observe(t4 - e.t_enqueue)
        if admitted:
            self.mempool.notify_new_txs(admitted)
        if _trace.enabled:
            _trace.emit(
                "mempool.admit_window", "span",
                tenant=self.tenant,
                n=len(batch), dup=n_dup, sig_fail=n_sig_fail,
                app_fail=n_app_fail, admitted=len(admitted),
                sig_ms=round((t2 - t1) * 1e3, 3),
                app_ms=round((t3 - t2) * 1e3, 3),
                insert_ms=round((t4 - t3) * 1e3, 3),
                dur_ms=round((t4 - t0) * 1e3, 3),
            )

    def _verify_stage(self, live: list["_Entry"]):
        """One batch verify over the window's STX envelopes; rejects txs
        whose signature fails. Bare (non-envelope) txs pass through."""
        vf = None
        signed: list[tuple[int, bool]] = []  # (live index, precheck ok)
        for i, e in enumerate(live):
            parsed = parse_signed_tx(e.tx)
            if parsed is None:
                continue
            pub, sig, payload = parsed
            if vf is None:
                vf = Ed25519BatchVerifier(backend=self.backend)
            try:
                ok = vf.add(Ed25519PubKey(pub), SIGN_CONTEXT + payload, sig)
            except ValueError:
                ok = False
            signed.append((i, ok))
        if vf is None or not signed:
            return live, 0
        if self.sched is not None:
            _all_ok, bits = self.sched.submit(
                vf, tenant=self.tenant, source="admission").result()
        else:
            _all_ok, bits = vf.verify()
        bad: set[int] = set()
        for (i, pre_ok), bit in zip(signed, bits):
            if not (pre_ok and bit):
                bad.add(i)
        if not bad:
            return live, 0
        kept = []
        for i, e in enumerate(live):
            if i in bad:
                self.mempool.note_rejected(e.key)  # counts failed_txs
                _fail(e.future,
                      ValueError("tx rejected: invalid signature"))
            else:
                kept.append(e)
        return kept, len(bad)


class ErrAdmissionQueueFull(Exception):
    def __init__(self, depth, limit):
        super().__init__(f"admission queue full: {depth} >= {limit}")
