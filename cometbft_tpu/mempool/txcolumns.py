"""Columnar transaction batch: one contiguous blob + an offsets column.

The proposal path used to re-materialize per-tx byte strings at every
hop — reap copies them out of the mempool, prepare_proposal walks them
again, Data.hash() hashes them one by one, Data.encode() concatenates
them a fourth time. A TxColumns batch keeps the payloads in ONE
contiguous buffer with an offsets column, exposes the same sequence
protocol as list[bytes] (so the app, FinalizeBlock, and mempool.update
consume it unchanged), and memoizes the three expensive projections the
hot path needs: per-tx hashes, the Data proto payload, and the
byte-budget prefix (which shares the blob instead of copying it).

Bit-exactness contract: tx_hashes()/encode_data()/prefix_max_bytes()
must produce exactly what the list[bytes] code paths produce —
types/block.py's Data and abci's default prepare_proposal fast-path to
these methods only because the results are indistinguishable on the
wire (tests/test_txcolumns.py pins the equivalences).
"""

from __future__ import annotations

from bisect import bisect_right

from ..crypto.keys import tmhash
from ..encoding import proto as pb


class TxColumns:
    """Immutable columnar tx batch with list[bytes] semantics.

    tx i is ``blob[offsets[i]:offsets[i+1]]``; ``offsets`` has n+1
    entries with offsets[0] == 0. Per-tx access goes through memoryview
    slices of the shared blob; a materialized list[bytes] is built at
    most once (lazily) for consumers that iterate repeatedly.
    """

    __slots__ = ("blob", "offsets", "_hashes", "_data_enc", "_mat")

    def __init__(self, blob, offsets: list[int]):
        self.blob = blob
        self.offsets = offsets
        self._hashes: list[bytes] | None = None
        self._data_enc: bytes | None = None
        self._mat: list[bytes] | None = None

    @classmethod
    def from_txs(cls, txs) -> "TxColumns":
        """Columnarize any iterable of tx bytes (idempotent)."""
        if isinstance(txs, cls):
            return txs
        txs = list(txs)
        offsets = [0]
        total = 0
        for t in txs:
            total += len(t)
            offsets.append(total)
        return cls(b"".join(txs), offsets)

    # -- sequence protocol (list[bytes] compatibility) -----------------
    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __bool__(self) -> bool:
        return len(self) > 0

    def __getitem__(self, i):
        if isinstance(i, slice):
            return self.to_list()[i]
        o = self.offsets
        n = len(o) - 1
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError("tx index out of range")
        if self._mat is not None:
            return self._mat[i]
        return bytes(memoryview(self.blob)[o[i]:o[i + 1]])

    def __iter__(self):
        return iter(self.to_list())

    def __eq__(self, other):
        if isinstance(other, TxColumns):
            return self.to_list() == other.to_list()
        if isinstance(other, (list, tuple)):
            return self.to_list() == list(other)
        return NotImplemented

    __hash__ = None  # mutable-sequence semantics, like list[bytes]

    def __repr__(self) -> str:
        return f"TxColumns(n={len(self)}, bytes={self.total_bytes()})"

    # -- zero-copy access ----------------------------------------------
    def view(self, i: int) -> memoryview:
        """Memoryview of tx i over the shared blob (no copy)."""
        o = self.offsets
        return memoryview(self.blob)[o[i]:o[i + 1]]

    def iter_views(self):
        """Iterate memoryview slices without materializing bytes."""
        mv = memoryview(self.blob)
        o = self.offsets
        for i in range(len(o) - 1):
            yield mv[o[i]:o[i + 1]]

    def to_list(self) -> list[bytes]:
        """Materialized list[bytes] — built at most once per batch, so
        repeated full passes (app delivery, mempool.update) pay the
        per-tx copies a single time."""
        if self._mat is None:
            mv = memoryview(self.blob)
            o = self.offsets
            self._mat = [bytes(mv[o[i]:o[i + 1]])
                         for i in range(len(o) - 1)]
        return self._mat

    def total_bytes(self) -> int:
        return self.offsets[-1]

    # -- memoized hot-path projections ---------------------------------
    def tx_hashes(self) -> list[bytes]:
        """Per-tx tmhash column — exactly [tx_hash(t) for t in txs]."""
        if self._hashes is None:
            mv = memoryview(self.blob)
            o = self.offsets
            self._hashes = [tmhash(mv[o[i]:o[i + 1]])
                            for i in range(len(o) - 1)]
        return self._hashes

    def encode_data(self) -> bytes:
        """The Data proto payload — exactly the concatenation of
        pb.f_bytes(1, t, emit_empty=True) over the txs."""
        if self._data_enc is None:
            t1 = pb.tag(1, pb.WT_LEN)
            parts = []
            mv = memoryview(self.blob)
            o = self.offsets
            for i in range(len(o) - 1):
                parts.append(t1 + pb.uvarint(o[i + 1] - o[i]))
                parts.append(mv[o[i]:o[i + 1]])
            self._data_enc = b"".join(parts)
        return self._data_enc

    def prefix_max_bytes(self, max_tx_bytes: int) -> "TxColumns":
        """Longest prefix whose summed payload bytes fit the budget,
        SHARING the blob (the default prepare_proposal contract: walk
        FIFO, stop before the first tx that would overflow)."""
        o = self.offsets
        n = len(o) - 1
        # offsets are the cumulative byte sums, so the cut point is a
        # bisect; duplicates (empty txs) land after the run, matching
        # the reference loop's total-not-greater check
        k = bisect_right(o, max_tx_bytes) - 1
        if k >= n:
            return self
        if k < 0:
            k = 0
        return TxColumns(self.blob, o[:k + 1])
