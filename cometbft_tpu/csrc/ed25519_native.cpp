// Native Ed25519 sign/verify for the host-side control plane.
//
// The data plane (batch verification) runs on TPU (cometbft_tpu/ops);
// this covers the per-signature host path — individual gossiped votes,
// privval signing, p2p handshake identity — where the reference leans
// on curve25519-voi's assembly (reference crypto/ed25519/ed25519.go:13).
//
// Original implementation derived from RFC 8032 + the curve equations:
// - field GF(2^255-19): 5 x 51-bit limbs, products via unsigned __int128
// - points: extended homogeneous (X, Y, Z, T), complete a=-1 addition
// - scalars mod L: 4 x 64-bit words, Barrett-free binary reduction
// - verification uses ZIP-215 semantics: liberal decoding, cofactored
//   equation [8]([S]B - [k]A - R) == identity, S < L required
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in image).

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <thread>
#include <atomic>
#include <vector>
#include <array>
#include <string>
#include <unordered_map>
#include <mutex>
#include <shared_mutex>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint8_t u8;

// ----------------------------------------------------------- SHA-512 ----
namespace sha512 {

static const u64 K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline u64 rotr(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

struct Ctx {
    u64 h[8];
    u8 buf[128];
    u64 total;
    size_t fill;
};

static void init(Ctx *c) {
    static const u64 iv[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
        0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    memcpy(c->h, iv, sizeof iv);
    c->total = 0;
    c->fill = 0;
}

static void block(Ctx *c, const u8 *p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
        w[i] = ((u64)p[8 * i] << 56) | ((u64)p[8 * i + 1] << 48) |
               ((u64)p[8 * i + 2] << 40) | ((u64)p[8 * i + 3] << 32) |
               ((u64)p[8 * i + 4] << 24) | ((u64)p[8 * i + 5] << 16) |
               ((u64)p[8 * i + 6] << 8) | (u64)p[8 * i + 7];
    }
    for (int i = 16; i < 80; i++) {
        u64 s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
        u64 s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = c->h[0], b = c->h[1], d = c->h[3], e = c->h[4];
    u64 cc = c->h[2], f = c->h[5], g = c->h[6], h = c->h[7];
    for (int i = 0; i < 80; i++) {
        u64 S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
        u64 ch = (e & f) ^ (~e & g);
        u64 t1 = h + S1 + ch + K[i] + w[i];
        u64 S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
        u64 maj = (a & b) ^ (a & cc) ^ (b & cc);
        u64 t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
    c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx *c, const u8 *data, size_t len) {
    c->total += len;
    while (len) {
        size_t take = 128 - c->fill;
        if (take > len) take = len;
        memcpy(c->buf + c->fill, data, take);
        c->fill += take;
        data += take;
        len -= take;
        if (c->fill == 128) {
            block(c, c->buf);
            c->fill = 0;
        }
    }
}

static void final(Ctx *c, u8 out[64]) {
    u64 bits = c->total * 8;
    u8 pad = 0x80;
    update(c, &pad, 1);
    u8 z = 0;
    while (c->fill != 112) update(c, &z, 1);
    u8 lenb[16] = {0};
    for (int i = 0; i < 8; i++) lenb[15 - i] = (u8)(bits >> (8 * i));
    c->total -= 0;  // length bytes excluded from message length already counted
    // careful: update() counts these 16 bytes into total, harmless (total unused after)
    update(c, lenb, 16);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++) out[8 * i + j] = (u8)(c->h[i] >> (56 - 8 * j));
}

static void hash(const u8 *a, size_t an, const u8 *b, size_t bn,
                 const u8 *d, size_t dn, u8 out[64]) {
    Ctx c;
    init(&c);
    if (an) update(&c, a, an);
    if (bn) update(&c, b, bn);
    if (dn) update(&c, d, dn);
    final(&c, out);
}

}  // namespace sha512

// ----------------------------------------------- field GF(2^255-19) ----
namespace fe {

typedef struct { u64 v[5]; } F;  // 51-bit limbs

static const u64 MASK = (1ULL << 51) - 1;

static void set0(F *o) { memset(o->v, 0, sizeof o->v); }
static void set1(F *o) { set0(o); o->v[0] = 1; }

static void add(F *o, const F *a, const F *b) {
    for (int i = 0; i < 5; i++) o->v[i] = a->v[i] + b->v[i];
}

// o = a - b, with a 4p limbwise bias: b's limbs may be uncarried mul
// outputs (< 2^52), and 4 * (2^51 - 19) > 2^52 keeps every limb
// nonnegative while the value shift (4p) vanishes mod p
static void sub(F *o, const F *a, const F *b) {
    o->v[0] = a->v[0] + 0x7ffffffffffedULL * 4 - b->v[0];
    o->v[1] = a->v[1] + 0x7ffffffffffffULL * 4 - b->v[1];
    o->v[2] = a->v[2] + 0x7ffffffffffffULL * 4 - b->v[2];
    o->v[3] = a->v[3] + 0x7ffffffffffffULL * 4 - b->v[3];
    o->v[4] = a->v[4] + 0x7ffffffffffffULL * 4 - b->v[4];
}

static void carry(F *o) {
    for (int r = 0; r < 3; r++) {
        u64 c = 0;
        for (int i = 0; i < 5; i++) {
            u64 t = o->v[i] + c;
            o->v[i] = t & MASK;
            c = t >> 51;
        }
        o->v[0] += 19 * c;
    }
}

static void mul(F *o, const F *a, const F *b) {
    // fully unrolled 5x51 schoolbook with pre-scaled 19*b wraparounds
    // (donna-style layout; ~3x the looped version under -O2)
    const u64 a0 = a->v[0], a1 = a->v[1], a2 = a->v[2], a3 = a->v[3],
              a4 = a->v[4];
    const u64 b0 = b->v[0], b1 = b->v[1], b2 = b->v[2], b3 = b->v[3],
              b4 = b->v[4];
    const u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19,
              b4_19 = b4 * 19;
    u128 t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
              (u128)a3 * b2_19 + (u128)a4 * b1_19;
    u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
              (u128)a3 * b3_19 + (u128)a4 * b2_19;
    u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
              (u128)a3 * b4_19 + (u128)a4 * b3_19;
    u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
              (u128)a3 * b0 + (u128)a4 * b4_19;
    u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
              (u128)a3 * b1 + (u128)a4 * b0;
    u64 r0, r1, r2, r3, r4;
    u128 c;
    r0 = (u64)t0 & MASK; c = t0 >> 51;
    t1 += c; r1 = (u64)t1 & MASK; c = t1 >> 51;
    t2 += c; r2 = (u64)t2 & MASK; c = t2 >> 51;
    t3 += c; r3 = (u64)t3 & MASK; c = t3 >> 51;
    t4 += c; r4 = (u64)t4 & MASK; c = t4 >> 51;
    // top carry can reach ~2^63 with loose (sub-biased) inputs, so the
    // 19-fold must run in 128-bit and ripple once into limb 1; limbs end
    // < 2^51 + 2^17 — safely inside the next mul's accumulation bound
    u128 fold = c * 19 + r0;
    o->v[0] = (u64)fold & MASK;
    o->v[1] = r1 + (u64)(fold >> 51);
    o->v[2] = r2;
    o->v[3] = r3;
    o->v[4] = r4;
}

static void sq(F *o, const F *a) { mul(o, a, a); }

static void mul_small(F *o, const F *a, u64 s) {
    u128 c = 0;
    for (int i = 0; i < 5; i++) {
        u128 v = (u128)a->v[i] * s + c;
        o->v[i] = (u64)v & MASK;
        c = v >> 51;
    }
    o->v[0] += 19 * (u64)c;
    carry(o);
}

static void freeze(F *o) {
    carry(o);
    // conditional subtract p (possibly twice)
    for (int r = 0; r < 2; r++) {
        u64 t[5];
        t[0] = o->v[0] - 0x7ffffffffffedULL;
        u64 borrow = t[0] >> 63;
        t[0] &= ~(1ULL << 63);
        // do proper borrow chain
        __int128 acc = (__int128)o->v[0] - 0x7ffffffffffedULL;
        u64 res[5];
        res[0] = (u64)acc & MASK;
        acc >>= 51;
        for (int i = 1; i < 5; i++) {
            acc += (__int128)o->v[i] - 0x7ffffffffffffULL;
            res[i] = (u64)acc & MASK;
            acc >>= 51;
        }
        (void)borrow; (void)t;
        if (acc == 0) memcpy(o->v, res, sizeof res);  // o >= p: keep result
    }
}

static void to_bytes(u8 out[32], const F *a) {
    F t = *a;
    freeze(&t);
    u64 limbs[5];
    memcpy(limbs, t.v, sizeof limbs);
    for (int i = 0; i < 32; i++) out[i] = 0;
    int bit = 0;
    for (int l = 0; l < 5; l++) {
        for (int b = 0; b < 51; b++) {
            if (limbs[l] >> b & 1) out[(bit + b) / 8] |= (u8)(1 << ((bit + b) % 8));
        }
        bit += 51;
    }
}

static void from_bytes(F *o, const u8 in[32]) {
    // little-endian, top bit masked by caller if needed
    u64 limbs[5] = {0, 0, 0, 0, 0};
    for (int i = 0; i < 255; i++) {
        if (in[i / 8] >> (i % 8) & 1) limbs[i / 51] |= 1ULL << (i % 51);
    }
    memcpy(o->v, limbs, sizeof limbs);
}

static int is_zero(const F *a) {
    F t = *a;
    freeze(&t);
    u64 acc = 0;
    for (int i = 0; i < 5; i++) acc |= t.v[i];
    return acc == 0;
}

static int eq(const F *a, const F *b) {
    F d;
    sub(&d, a, b);
    carry(&d);
    return is_zero(&d);
}

static int parity(const F *a) {
    F t = *a;
    freeze(&t);
    return (int)(t.v[0] & 1);
}

// a^(2^252 - 3): shared exponent for invert + sqrt
static void pow2523(F *o, const F *a) {
    F x2, x9, x11, x31, t;
    sq(&x2, a);                       // 2
    sq(&t, &x2); sq(&t, &t);          // 8
    mul(&x9, &t, a);                  // 9
    mul(&x11, &x9, &x2);              // 11
    sq(&t, &x11); mul(&x31, &t, &x9); // 2^5-1
    F r = x31;
    for (int i = 0; i < 5; i++) sq(&r, &r);
    mul(&r, &r, &x31);                // 2^10-1
    F r10 = r;
    for (int i = 0; i < 10; i++) sq(&r, &r);
    mul(&r, &r, &r10);                // 2^20-1
    F r20 = r;
    for (int i = 0; i < 20; i++) sq(&r, &r);
    mul(&r, &r, &r20);                // 2^40-1
    for (int i = 0; i < 10; i++) sq(&r, &r);
    mul(&r, &r, &r10);                // 2^50-1
    F r50 = r;
    for (int i = 0; i < 50; i++) sq(&r, &r);
    mul(&r, &r, &r50);                // 2^100-1
    F r100 = r;
    for (int i = 0; i < 100; i++) sq(&r, &r);
    mul(&r, &r, &r100);               // 2^200-1
    for (int i = 0; i < 50; i++) sq(&r, &r);
    mul(&r, &r, &r50);                // 2^250-1
    sq(&r, &r); sq(&r, &r);
    mul(o, &r, a);                    // 2^252-3
}

static void invert(F *o, const F *a) {
    F t;
    pow2523(&t, a);  // a^(2^252-3)
    sq(&t, &t); sq(&t, &t); sq(&t, &t);  // a^(2^255-24)
    F a2, a3;
    sq(&a2, a);
    mul(&a3, &a2, a);
    mul(o, &t, &a3);  // exponent 2^255-24+3 = p-2... (8*(2^252-3)+3)
}

}  // namespace fe

// ------------------------------------------------- scalars mod L ---------
namespace sc {

// L = 2^252 + 27742317777372353535851937790883648493
static const u64 L[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                         0, 0x1000000000000000ULL};

// 256-bit big-endian-agnostic helpers over 4x64 LE words
static int cmp(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void sub(u64 o[4], const u64 a[4], const u64 b[4]) {
    unsigned char borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a[i] - b[i] - borrow;
        o[i] = (u64)t;
        borrow = (t >> 64) ? 1 : 0;
    }
}

// l0 = L - 2^252 (125 bits): 2^252 === -l0 (mod L), the fold constant
static const u64 L0[2] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL};

// r (n+2 words, zeroed by caller) = a (na words) * l0
static void mul_l0(u64 *r, const u64 *a, int na) {
    for (int i = 0; i < na; i++) {
        u128 carry = 0;
        for (int j = 0; j < 2; j++) {
            u128 t = (u128)a[i] * L0[j] + r[i + j] + carry;
            r[i + j] = (u64)t;
            carry = t >> 64;
        }
        for (int k = i + 2; carry; k++) {
            u128 t = (u128)r[k] + carry;
            r[k] = (u64)t;
            carry = t >> 64;
        }
    }
}

// lo = v mod 2^252 (4 words), hi = v >> 252 (nh words, trimmed)
static void split252(const u64 *v, int nv, u64 lo[4], u64 *hi, int *nh) {
    for (int i = 0; i < 4; i++) lo[i] = i < nv ? v[i] : 0;
    lo[3] &= 0x0fffffffffffffffULL;  // 252 = 3*64 + 60
    int n = nv - 3;
    if (n < 0) n = 0;
    for (int i = 0; i < n; i++) {
        u64 low = v[3 + i] >> 60;
        u64 high = (4 + i < nv) ? (v[4 + i] << 4) : 0;
        hi[i] = low | high;
    }
    while (n > 0 && hi[n - 1] == 0) n--;
    *nh = n;
}

// reduce a 512-bit LE value mod L via three signed folds at the 2^252
// boundary: x = hi*2^252 + lo === lo - hi*l0; the negative part rides in
// a second accumulator (A - B), folded symmetrically. ~25 word-muls vs
// the 512-iteration shift-subtract this replaces.
static void reduce512(u64 o[4], const u8 in[64]) {
    u64 A[10] = {0}, B[10] = {0};
    int na = 8, nb = 0;
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++) A[i] |= (u64)in[8 * i + j] << (8 * j);
    for (int round = 0; round < 3; round++) {
        u64 loA[4], hiA[7], loB[4], hiB[7];
        int nhA, nhB;
        split252(A, na, loA, hiA, &nhA);
        split252(B, nb, loB, hiB, &nhB);
        // A' = loA + hiB*l0 ; B' = loB + hiA*l0  (A - B preserved mod L)
        u64 pa[10] = {0}, pb[10] = {0};
        mul_l0(pa, hiB, nhB);
        mul_l0(pb, hiA, nhA);
        unsigned char cy = 0;
        for (int i = 0; i < 4; i++) {
            u128 t = (u128)pa[i] + loA[i] + cy;
            pa[i] = (u64)t;
            cy = (unsigned char)(t >> 64);
        }
        for (int i = 4; cy; i++) {
            u128 t = (u128)pa[i] + cy;
            pa[i] = (u64)t;
            cy = (unsigned char)(t >> 64);
        }
        cy = 0;
        for (int i = 0; i < 4; i++) {
            u128 t = (u128)pb[i] + loB[i] + cy;
            pb[i] = (u64)t;
            cy = (unsigned char)(t >> 64);
        }
        for (int i = 4; cy; i++) {
            u128 t = (u128)pb[i] + cy;
            pb[i] = (u64)t;
            cy = (unsigned char)(t >> 64);
        }
        memcpy(A, pa, sizeof A);
        memcpy(B, pb, sizeof B);
        na = nb = 10;
        while (na > 0 && A[na - 1] == 0) na--;
        while (nb > 0 && B[nb - 1] == 0) nb--;
    }
    // both < 2^253 < 2L now: bring under L, then r = (A - B) mod L
    u64 a4[4], b4[4];
    memcpy(a4, A, 32);
    memcpy(b4, B, 32);
    if (cmp(a4, L) >= 0) sub(a4, a4, L);
    if (cmp(b4, L) >= 0) sub(b4, b4, L);
    if (cmp(a4, b4) >= 0) {
        sub(o, a4, b4);
    } else {
        u64 t[4];
        sub(t, b4, a4);   // t = B - A
        sub(o, L, t);     // o = L - t
    }
}

static void from_bytes(u64 o[4], const u8 in[32]) {
    for (int i = 0; i < 4; i++) {
        o[i] = 0;
        for (int j = 0; j < 8; j++) o[i] |= (u64)in[8 * i + j] << (8 * j);
    }
}

static void to_bytes(u8 out[32], const u64 a[4]) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) out[8 * i + j] = (u8)(a[i] >> (8 * j));
}

// o = (a*b + c) mod L — schoolbook into 512 bits then reduce
static void muladd(u64 o[4], const u64 a[4], const u64 b[4], const u64 c[4]) {
    u64 wide[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)a[i] * b[j] + wide[i + j] + carry;
            wide[i + j] = (u64)t;
            carry = t >> 64;
        }
        wide[i + 4] += (u64)carry;
    }
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)wide[i] + c[i] + carry;
        wide[i] = (u64)t;
        carry = t >> 64;
    }
    for (int i = 4; i < 8 && carry; i++) {
        u128 t = (u128)wide[i] + carry;
        wide[i] = (u64)t;
        carry = t >> 64;
    }
    u8 bytes[64];
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++) bytes[8 * i + j] = (u8)(wide[i] >> (8 * j));
    reduce512(o, bytes);
}

}  // namespace sc

// --------------------------------------------------- curve points --------
namespace ge {

using fe::F;

struct P {
    F x, y, z, t;
};

// d = -121665/121666
static F D, D2, SQRTM1;
static P BASE;
static bool inited = false;

static void identity(P *o) {
    fe::set0(&o->x);
    fe::set1(&o->y);
    fe::set1(&o->z);
    fe::set0(&o->t);
}

static void add(P *o, const P *p, const P *q) {
    F a, b, c, d_, e, f, g, h, t0, t1;
    fe::sub(&t0, &p->y, &p->x); fe::carry(&t0);
    fe::sub(&t1, &q->y, &q->x); fe::carry(&t1);
    fe::mul(&a, &t0, &t1);
    fe::add(&t0, &p->y, &p->x);
    fe::add(&t1, &q->y, &q->x);
    fe::mul(&b, &t0, &t1);
    fe::mul(&c, &p->t, &D2);
    fe::mul(&c, &c, &q->t);
    fe::mul(&d_, &p->z, &q->z);
    fe::add(&d_, &d_, &d_);
    fe::sub(&e, &b, &a); fe::carry(&e);
    fe::sub(&f, &d_, &c); fe::carry(&f);
    fe::add(&g, &d_, &c);
    fe::add(&h, &b, &a);
    fe::mul(&o->x, &e, &f);
    fe::mul(&o->y, &g, &h);
    fe::mul(&o->z, &f, &g);
    fe::mul(&o->t, &e, &h);
}

static void dbl(P *o, const P *p) { add(o, p, p); }

static void neg(P *o, const P *p) {
    F zero;
    fe::set0(&zero);
    fe::sub(&o->x, &zero, &p->x); fe::carry(&o->x);
    o->y = p->y;
    o->z = p->z;
    fe::sub(&o->t, &zero, &p->t); fe::carry(&o->t);
}

// affine niels form (Z = 1): the 7-mul mixed-addition operand
struct Niels {
    F ypx, ymx, t2d;
};

static void madd(P *o, const P *p, const Niels *n) {
    F a, b, c, d_, e, f, g, h, t0;
    fe::sub(&t0, &p->y, &p->x); fe::carry(&t0);
    fe::mul(&a, &t0, &n->ymx);
    fe::add(&t0, &p->y, &p->x);
    fe::mul(&b, &t0, &n->ypx);
    fe::mul(&c, &p->t, &n->t2d);
    fe::add(&d_, &p->z, &p->z);
    fe::sub(&e, &b, &a); fe::carry(&e);
    fe::sub(&f, &d_, &c); fe::carry(&f);
    fe::add(&g, &d_, &c);
    fe::add(&h, &b, &a);
    fe::mul(&o->x, &e, &f);
    fe::mul(&o->y, &g, &h);
    fe::mul(&o->z, &f, &g);
    fe::mul(&o->t, &e, &h);
}

static void msub(P *o, const P *p, const Niels *n) {
    // add of -N: swap (Y+X, Y-X), negate 2dT
    Niels m;
    m.ypx = n->ymx;
    m.ymx = n->ypx;
    F zero;
    fe::set0(&zero);
    fe::sub(&m.t2d, &zero, &n->t2d); fe::carry(&m.t2d);
    madd(o, p, &m);
}

static void to_niels_affine(Niels *o, const P *p) {
    // normalize (one inversion) then cache (Y+X, Y-X, 2dT)
    F zi, x, y, t;
    fe::invert(&zi, &p->z);
    fe::mul(&x, &p->x, &zi);
    fe::mul(&y, &p->y, &zi);
    fe::mul(&t, &x, &y);
    fe::add(&o->ypx, &y, &x);
    fe::sub(&o->ymx, &y, &x); fe::carry(&o->ymx);
    fe::mul(&o->t2d, &t, &D2);
}

// multiples 1..128 of B in affine niels — radix-256 fixed-base madds
// (one-time init; the reference gets this from curve25519-voi's
// precomputed basepoint tables)
static Niels BASE_N[128];

// signed radix-16 digits: value = sum d_i 16^i, d_i in [-8, 8); 64 digits
static void recode16(const u8 s[32], signed char out[64]) {
    int carry = 0;
    for (int i = 0; i < 32; i++) {
        int lo = (s[i] & 15) + carry;
        carry = lo >= 8;
        out[2 * i] = (signed char)(lo - (carry << 4));
        int hi = (s[i] >> 4) + carry;
        carry = hi >= 8;
        out[2 * i + 1] = (signed char)(hi - (carry << 4));
    }
    // inputs < 2^253 (S and k are both < L): nibble 63 <= 1, so the
    // final carry is always 0 — no overflow digit exists
    (void)carry;
}

// signed radix-256 digits: value = sum d_i 256^i, d_i in [-128, 128);
// nw digits (callers size for the scalar range + final carry)
static void recode256(const u8 *s, int nbytes, signed char *out, int nw) {
    int carry = 0;
    for (int i = 0; i < nw; i++) {
        int d = (i < nbytes ? s[i] : 0) + carry;
        carry = d >= 128;
        out[i] = (signed char)(d - (carry << 8));
    }
}

// o = [s]p, 4-bit windows msb-first
static void scalar_mul(P *o, const u8 s[32], const P *p) {
    P table[16];
    identity(&table[0]);
    table[1] = *p;
    for (int i = 2; i < 16; i++) add(&table[i], &table[i - 1], p);
    P r;
    identity(&r);
    for (int i = 31; i >= 0; i--) {
        for (int half = 1; half >= 0; half--) {
            int nib = (s[i] >> (4 * half)) & 15;
            if (!(i == 31 && half == 1)) {
                dbl(&r, &r); dbl(&r, &r); dbl(&r, &r); dbl(&r, &r);
            }
            if (nib) add(&r, &r, &table[nib]);
        }
    }
    *o = r;
}

// ZIP-215 liberal decompression; returns 0 on failure
static int decompress(P *o, const u8 in[32]) {
    u8 yb[32];
    memcpy(yb, in, 32);
    int sign = yb[31] >> 7;
    yb[31] &= 0x7f;
    fe::from_bytes(&o->y, yb);  // NOT checked canonical: ZIP-215 liberal
    F yy, u, v, v3, v7, t0, x, vxx;
    fe::sq(&yy, &o->y);
    F one;
    fe::set1(&one);
    fe::sub(&u, &yy, &one); fe::carry(&u);
    fe::mul(&v, &yy, &D);
    fe::add(&v, &v, &one); fe::carry(&v);
    fe::sq(&v3, &v);
    fe::mul(&v3, &v3, &v);
    fe::sq(&v7, &v3);
    fe::mul(&v7, &v7, &v);
    fe::mul(&t0, &u, &v7);
    fe::pow2523(&t0, &t0);
    fe::mul(&x, &u, &v3);
    fe::mul(&x, &x, &t0);
    fe::sq(&vxx, &x);
    fe::mul(&vxx, &vxx, &v);
    F negu;
    fe::set0(&negu);
    fe::sub(&negu, &negu, &u); fe::carry(&negu);
    if (!fe::eq(&vxx, &u)) {
        if (!fe::eq(&vxx, &negu)) return 0;
        fe::mul(&x, &x, &SQRTM1);
    }
    if (fe::parity(&x) != sign) {
        F zero;
        fe::set0(&zero);
        fe::sub(&x, &zero, &x); fe::carry(&x);
    }
    o->x = x;
    fe::set1(&o->z);
    fe::mul(&o->t, &o->x, &o->y);
    return 1;
}

static void compress(u8 out[32], const P *p) {
    F zi, x, y;
    fe::invert(&zi, &p->z);
    fe::mul(&x, &p->x, &zi);
    fe::mul(&y, &p->y, &zi);
    fe::to_bytes(out, &y);
    out[31] |= (u8)(fe::parity(&x) << 7);
}

static int is_identity(const P *p) {
    return fe::is_zero(&p->x) && fe::eq(&p->y, &p->z);
}

static void init_constants() {
    if (inited) return;
    // d = -121665 * inv(121666)
    F n121665, n121666, inv121666, zero;
    fe::set0(&zero);
    fe::set0(&n121665); n121665.v[0] = 121665;
    fe::set0(&n121666); n121666.v[0] = 121666;
    fe::invert(&inv121666, &n121666);
    F d_;
    fe::mul(&d_, &n121665, &inv121666);
    fe::sub(&D, &zero, &d_); fe::carry(&D);
    fe::add(&D2, &D, &D); fe::carry(&D2);
    // sqrt(-1) = 2^((p-1)/4): compute via pow2523(-1)... use known bytes
    static const u8 sqrtm1_bytes[32] = {
        0xb0, 0xa0, 0x0e, 0x4a, 0x27, 0x1b, 0xee, 0xc4, 0x78, 0xe4, 0x2f,
        0xad, 0x06, 0x18, 0x43, 0x2f, 0xa7, 0xd7, 0xfb, 0x3d, 0x99, 0x00,
        0x4d, 0x2b, 0x0b, 0xdf, 0xc1, 0x4f, 0x80, 0x24, 0x83, 0x2b};
    fe::from_bytes(&SQRTM1, sqrtm1_bytes);
    // base point: y = 4/5
    F four, five, inv5, by;
    fe::set0(&four); four.v[0] = 4;
    fe::set0(&five); five.v[0] = 5;
    fe::invert(&inv5, &five);
    fe::mul(&by, &four, &inv5);
    u8 bb[32];
    fe::to_bytes(bb, &by);  // sign bit 0 => even x
    decompress(&BASE, bb);
    // 1..128 multiples of B as affine niels (one inversion each; ~0.5 ms
    // one-time — per-process, amortized across every verify)
    P cur = BASE;
    to_niels_affine(&BASE_N[0], &cur);
    for (int i = 1; i < 128; i++) {
        add(&cur, &cur, &BASE);
        to_niels_affine(&BASE_N[i], &cur);
    }
    inited = true;
}

// r += [k](-A) + [s]B via a shared Straus double-and-add chain:
// radix-16 for the variable base (8-entry per-call table), radix-256
// for B against the static 128-entry niels table. ~252 dbl + 64 add +
// 32 madd vs ~1100 ops for two independent ladders.
static void straus_sb_ka(P *o, const u8 s[32], const u8 k[32], const P *negA) {
    signed char dk[64], ds[32];
    recode16(k, dk);
    recode256(s, 32, ds, 32);
    P atab[8];  // 1..8 multiples of negA
    atab[0] = *negA;
    for (int i = 1; i < 8; i++) add(&atab[i], &atab[i - 1], negA);
    P r, t;
    identity(&r);
    for (int i = 63; i >= 0; i--) {
        if (i != 63) {
            dbl(&r, &r); dbl(&r, &r); dbl(&r, &r); dbl(&r, &r);
        }
        int d = dk[i];
        if (d > 0) add(&r, &r, &atab[d - 1]);
        else if (d < 0) {
            neg(&t, &atab[-d - 1]);
            add(&r, &r, &t);
        }
        if ((i & 1) == 0) {
            int db = ds[i >> 1];
            if (db > 0) madd(&r, &r, &BASE_N[db - 1]);
            else if (db < 0) msub(&r, &r, &BASE_N[-db - 1]);
        }
    }
    *o = r;
}

}  // namespace ge

// ------------------------------------------- AVX-512 IFMA engine --------
// 4-lane vectorized engine using vpmadd52{l,h}uq — the 52-bit
// multiply-accumulate the instruction set grew for exactly this field.
// Two lane disciplines share one type:
//  - point ops: lanes = the 4 independent field muls inside the unified
//    a=-1 Edwards addition (add-2008-hwcd-3): an add or double is TWO
//    vector muls instead of eight serial ones;
//  - decompression: lanes = 4 independent signatures through the
//    identical sqrt-chain control flow.
// Radix 2^52 (5 limbs, 260 bits): limb positions line up with the
// 52-bit instruction split, and 2^260 === 608 (mod p) folds overflow.
// Compiled only when -march=native enables IFMA (build-on-demand per
// machine, cometbft_tpu/crypto/native.py), with a runtime cpuid check.
#if defined(__AVX512IFMA__) && defined(__AVX512VL__) && defined(__AVX512DQ__)
#define ED25519_HAVE_IFMA 1
#include <immintrin.h>

#include "ed25519_ifma.inc"
#endif  // ED25519_HAVE_IFMA

// Decoded-pubkey cache shared by single and batch verification: commit
// verification re-checks the SAME validator set every height, so the
// sqrt exponentiation per A — roughly a third of the single-verify cost
// — runs once per validator. Decompression is deterministic, so caching
// the negated point by its 32-byte encoding is sound.
static std::unordered_map<std::string, ge::P> g_negA_cache;
static std::shared_mutex g_negA_mtx;

static bool cached_neg_decompress(ge::P *negA, const u8 pub[32]) {
    std::string key((const char *)pub, 32);
    {
        std::shared_lock<std::shared_mutex> rl(g_negA_mtx);
        auto it = g_negA_cache.find(key);
        if (it != g_negA_cache.end()) {
            *negA = it->second;
            return true;
        }
    }
    ge::P A;
    if (!ge::decompress(&A, pub)) return false;
    ge::neg(negA, &A);
    std::unique_lock<std::shared_mutex> wl(g_negA_mtx);
    if (g_negA_cache.size() > 65536) g_negA_cache.clear();
    g_negA_cache.emplace(std::move(key), *negA);
    return true;
}

// 8-way multi-buffer SHA-512 (AVX-512) for batch challenge hashing
#include "sha512_mb.inc"

// ------------------------------------------------------- public ABI ------
extern "C" {

// which engine serves verification: 1 = AVX-512 IFMA vector engine,
// 0 = portable scalar (tests/bench report this)
int ed25519_engine(void) {
#ifdef ED25519_HAVE_IFMA
    if (v4::usable()) return 1;
#endif
    return 0;
}

// Keccak-f[1600] permutation, in place on a 200-byte little-endian
// state — the inner loop of merlin/STROBE transcripts
// (crypto/merlin.py): sr25519 batches pay ~6 permutations per
// signature, and the Python permutation was ~60% of their remaining
// cost after the native MSM. Standard theta/rho+pi/chi/iota rounds;
// lane layout matches the Python reference (lane i = x + 5y).
static inline u64 k_rotl(u64 v, int n) {
    return n ? (v << n) | (v >> (64 - n)) : v;
}

void keccak_f1600(u8 *state) {
    static const u64 RC[24] = {
        0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
        0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
        0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
        0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
        0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
        0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
        0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
        0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
    };
    static const int ROT[5][5] = {
        {0, 36, 3, 41, 18}, {1, 44, 10, 45, 2}, {62, 6, 43, 15, 61},
        {28, 55, 25, 21, 56}, {27, 20, 39, 8, 14},
    };
    u64 a[25];
    memcpy(a, state, 200);
    for (int rnd = 0; rnd < 24; rnd++) {
        u64 c[5], d[5], b[25];
        for (int x = 0; x < 5; x++)
            c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
        for (int x = 0; x < 5; x++)
            d[x] = c[(x + 4) % 5] ^ k_rotl(c[(x + 1) % 5], 1);
        for (int i = 0; i < 25; i++) a[i] ^= d[i % 5];
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                b[y + 5 * ((2 * x + 3 * y) % 5)] =
                    k_rotl(a[x + 5 * y], ROT[x][y]);
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                a[x + 5 * y] = b[x + 5 * y] ^
                    ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]);
        a[0] ^= RC[rnd];
    }
    memcpy(state, a, 200);
}

// Generic Edwards multi-scalar multiplication RISTRETTO-identity check:
//   sum [k_i] P_i in the identity coset of ristretto255.
// P_i arrive as affine (x, y) 32-byte LE field elements (the caller —
// e.g. the sr25519 ristretto batch, crypto/sr25519.py — has already
// decoded and validated them; negation is the caller's x -> -x).
// Plain Pippenger, window c=8. The identity coset is the 4-torsion
// {(0,1), (0,-1), (+-i, 0)}, i.e. affine x*y == 0 — in extended
// coordinates exactly T == 0 (X*Y = Z*T, Z != 0). An exact-identity
// check would reject ~half of all VALID sr25519 batches: each
// signature equation holds only up to torsion on coset
// representatives (see crypto/sr25519.py _verify_rlc).
// Precondition: xs/ys/scalars each hold n 32-byte elements. n == 0 is
// legal and returns 1: the empty sum IS the identity (a zero-signature
// batch verifies vacuously, matching the Python oracle's behavior).
int edwards_msm_is_identity(u64 n, const u8 *xs, const u8 *ys,
                            const u8 *scalars) {
    ge::init_constants();
    if (n == 0) return 1;  // empty sum is the identity element
    const int C = 8, NBK = (1 << C) - 1, NW = 32;
    std::vector<ge::P> pts(n);
    for (u64 i = 0; i < n; i++) {
        fe::from_bytes(&pts[i].x, xs + i * 32);
        fe::from_bytes(&pts[i].y, ys + i * 32);
        fe::set1(&pts[i].z);
        fe::mul(&pts[i].t, &pts[i].x, &pts[i].y);
    }
    ge::P acc;
    ge::identity(&acc);
    std::vector<ge::P> buckets(NBK);
    for (int w = NW - 1; w >= 0; w--) {
        for (int b = 0; b < NBK; b++) ge::identity(&buckets[b]);
        bool any = false;
        for (u64 i = 0; i < n; i++) {
            int d = scalars[i * 32 + w];
            if (d) {
                ge::add(&buckets[d - 1], &buckets[d - 1], &pts[i]);
                any = true;
            }
        }
        if (w != NW - 1)
            for (int k = 0; k < C; k++) ge::dbl(&acc, &acc);
        if (!any) continue;
        // sum_d d * bucket[d-1] via suffix running sums
        ge::P running, total;
        ge::identity(&running);
        ge::identity(&total);
        for (int b = NBK - 1; b >= 0; b--) {
            ge::add(&running, &running, &buckets[b]);
            ge::add(&total, &total, &running);
        }
        ge::add(&acc, &acc, &total);
    }
    return fe::is_zero(&acc.t);
}

// verify: ZIP-215. Returns 1 valid, 0 invalid.
int ed25519_verify(const u8 *pub, const u8 *msg, u64 msg_len, const u8 *sig) {
#ifdef ED25519_HAVE_IFMA
    if (v4::usable()) return v4::verify_v4(pub, msg, msg_len, sig);
#endif
    ge::init_constants();
    // S < L
    u64 s_words[4];
    sc::from_bytes(s_words, sig + 32);
    if (sc::cmp(s_words, sc::L) >= 0) return 0;
    ge::P negA_c, R;
    if (!cached_neg_decompress(&negA_c, pub)) return 0;
    if (!ge::decompress(&R, sig)) return 0;
    // k = SHA512(R || A || M) mod L
    u8 digest[64];
    sha512::hash(sig, 32, pub, 32, msg, msg_len, digest);
    u64 k[4];
    sc::reduce512(k, digest);
    u8 kb[32];
    sc::to_bytes(kb, k);
    // check [8]([S]B + [k](-A) - R) == identity, one Straus chain
    ge::P negR, acc;
    ge::neg(&negR, &R);
    ge::straus_sb_ka(&acc, sig + 32, kb, &negA_c);
    ge::add(&acc, &acc, &negR);
    ge::dbl(&acc, &acc);
    ge::dbl(&acc, &acc);
    ge::dbl(&acc, &acc);
    return ge::is_identity(&acc);
}

// RLC batch verify (reference crypto/ed25519/ed25519.go:207-240 /
// curve25519-voi BatchVerifier): one Pippenger MSM checks
//   [8]([c]B + sum [z_i](-R_i) + sum [z_i h_i](-A_i)) == identity.
// Returns 1 when the whole batch verifies; 0 on any failure (caller
// falls back to per-signature verification for blame, mirroring
// types/validation.go:304-311). msgs are concatenated; msg_lens[i]
// gives each length.
int ed25519_batch_verify(u64 n, const u8 *pubs, const u8 *msgs,
                         const u64 *msg_lens, const u8 *sigs) {
#ifdef ED25519_HAVE_IFMA
    if (v4::usable()) return v4::batch_verify_v4(n, pubs, msgs, msg_lens, sigs);
#endif
    ge::init_constants();
    if (n == 0) return 0;
    // z seed: OS entropy once per batch, expanded by counter hashing.
    // Fail CLOSED without it: batch soundness rests on the z_i being
    // unpredictable to the signer, and any input-derived fallback is
    // attacker-influenced (fd exhaustion is attacker-reachable). A 0
    // return sends the caller to per-signature verification, which
    // needs no randomness. Read BEFORE the allocations so the failure
    // path leaks nothing.
    u8 seed[32];
    {
        FILE *f = fopen("/dev/urandom", "rb");
        size_t got = f ? fread(seed, 1, 32, f) : 0;
        if (f) fclose(f);
        if (got != 32) return 0;
    }
    const int ZW = 17, MW = 32, NW = 32;  // windows: z, z*h, Horner span
    ge::P *negR = new ge::P[n], *negA = new ge::P[n];
    signed char *zd = new signed char[n * ZW];
    signed char *md = new signed char[n * MW];
    u64 *offsets = new u64[n];
    {
        u64 off = 0;
        for (u64 i = 0; i < n; i++) { offsets[i] = off; off += msg_lens[i]; }
    }
    unsigned nthreads = std::thread::hardware_concurrency();
    if (nthreads == 0) nthreads = 1;
    if (nthreads > 8) nthreads = 8;
    if (n < 64) nthreads = 1;

    // ---- phase 1 (parallel over signatures): decompress, hash, digits;
    // per-thread partial c accumulators merged after join
    std::atomic<int> ok{1};
    std::vector<std::array<u64, 4>> partial_c(nthreads);
    auto sig_worker = [&](unsigned t) {
        u64 lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
        u64 c[4] = {0, 0, 0, 0};
        for (u64 i = lo; i < hi && ok.load(std::memory_order_relaxed); i++) {
            const u8 *pub = pubs + 32 * i, *sig = sigs + 64 * i;
            u64 s_words[4];
            sc::from_bytes(s_words, sig + 32);
            if (sc::cmp(s_words, sc::L) >= 0) { ok.store(0); break; }
            ge::P R;
            if (!cached_neg_decompress(&negA[i], pub)) {
                ok.store(0);
                break;
            }
            if (!ge::decompress(&R, sig)) {
                ok.store(0);
                break;
            }
            ge::neg(&negR[i], &R);
            u8 digest[64];
            sha512::hash(sig, 32, pub, 32, msgs + offsets[i], msg_lens[i],
                         digest);
            u64 h[4], z[4] = {0, 0, 0, 0}, m[4], zero[4] = {0, 0, 0, 0};
            sc::reduce512(h, digest);
            u8 zbuf[64], ctr[8];
            for (int b = 0; b < 8; b++) ctr[b] = (u8)(i >> (8 * b));
            sha512::hash(seed, 32, ctr, 8, nullptr, 0, zbuf);
            zbuf[0] |= 1;  // nonzero
            for (int b = 0; b < 8; b++) z[0] |= (u64)zbuf[b] << (8 * b);
            for (int b = 0; b < 8; b++) z[1] |= (u64)zbuf[8 + b] << (8 * b);
            sc::muladd(m, z, h, zero);     // m = z*h mod L
            sc::muladd(c, z, s_words, c);  // c += z*s mod L
            u8 zb[32] = {0}, mb[32];
            memcpy(zb, zbuf, 16);
            sc::to_bytes(mb, m);
            ge::recode256(zb, 16, &zd[i * ZW], ZW);
            ge::recode256(mb, 32, &md[i * MW], MW);
        }
        memcpy(partial_c[t].data(), c, 32);
    };
    if (nthreads == 1) {
        sig_worker(0);
    } else {
        std::vector<std::thread> ths;
        for (unsigned t = 0; t < nthreads; t++)
            ths.emplace_back(sig_worker, t);
        for (auto &th : ths) th.join();
    }

    int result = 0;
    if (ok.load()) {
        u64 c[4] = {0, 0, 0, 0};
        for (unsigned t = 0; t < nthreads; t++) {
            // c = (c + partial) mod L: both < L, one conditional subtract
            unsigned char cy = 0;
            for (int i = 0; i < 4; i++) {
                u128 s = (u128)c[i] + partial_c[t][i] + cy;
                c[i] = (u64)s;
                cy = (unsigned char)(s >> 64);
            }
            if (cy || sc::cmp(c, sc::L) >= 0) sc::sub(c, c, sc::L);
        }
        // ---- phase 2 (parallel over windows): Pippenger c=8 — scatter
        // into 128 signed buckets, suffix running-sum reduce
        ge::P win_sums[NW];
        bool win_live[NW];
        auto win_worker = [&](unsigned t) {
            ge::P buckets[128];
            bool used[128];
            ge::P tmp;
            for (int w = t; w < NW; w += (int)nthreads) {
                memset(used, 0, sizeof used);
                for (u64 i = 0; i < n; i++) {
                    if (w < ZW && zd[i * ZW + w]) {
                        int d = zd[i * ZW + w];
                        int b = (d > 0 ? d : -d) - 1;
                        ge::P *src = &negR[i];
                        if (!used[b]) {
                            if (d > 0) buckets[b] = *src;
                            else ge::neg(&buckets[b], src);
                            used[b] = true;
                        } else if (d > 0) {
                            ge::add(&buckets[b], &buckets[b], src);
                        } else {
                            ge::neg(&tmp, src);
                            ge::add(&buckets[b], &buckets[b], &tmp);
                        }
                    }
                    if (md[i * MW + w]) {
                        int d = md[i * MW + w];
                        int b = (d > 0 ? d : -d) - 1;
                        ge::P *src = &negA[i];
                        if (!used[b]) {
                            if (d > 0) buckets[b] = *src;
                            else ge::neg(&buckets[b], src);
                            used[b] = true;
                        } else if (d > 0) {
                            ge::add(&buckets[b], &buckets[b], src);
                        } else {
                            ge::neg(&tmp, src);
                            ge::add(&buckets[b], &buckets[b], &tmp);
                        }
                    }
                }
                // sum_b (b+1) * bucket[b] via suffix running sums
                ge::P acc, sum;
                bool acc_live = false, sum_live = false;
                ge::identity(&acc);
                ge::identity(&sum);
                for (int b = 127; b >= 0; b--) {
                    if (used[b]) {
                        if (acc_live) ge::add(&acc, &acc, &buckets[b]);
                        else { acc = buckets[b]; acc_live = true; }
                    }
                    if (acc_live) {
                        if (sum_live) ge::add(&sum, &sum, &acc);
                        else { sum = acc; sum_live = true; }
                    }
                }
                win_sums[w] = sum;
                win_live[w] = sum_live;
            }
        };
        if (nthreads == 1) {
            win_worker(0);
        } else {
            std::vector<std::thread> ths;
            for (unsigned t = 0; t < nthreads; t++)
                ths.emplace_back(win_worker, t);
            for (auto &th : ths) th.join();
        }
        // ---- Horner over windows with the [c]B digits folded in
        signed char cd[NW];
        u8 cb[32];
        sc::to_bytes(cb, c);
        ge::recode256(cb, 32, cd, NW);
        ge::P S;
        ge::identity(&S);
        for (int w = NW - 1; w >= 0; w--) {
            if (w != NW - 1)
                for (int d8 = 0; d8 < 8; d8++) ge::dbl(&S, &S);
            if (win_live[w]) ge::add(&S, &S, &win_sums[w]);
            int db = cd[w];
            if (db > 0) ge::madd(&S, &S, &ge::BASE_N[db - 1]);
            else if (db < 0) ge::msub(&S, &S, &ge::BASE_N[-db - 1]);
        }
        ge::dbl(&S, &S);
        ge::dbl(&S, &S);
        ge::dbl(&S, &S);
        result = ge::is_identity(&S);
    }
    delete[] negR;
    delete[] negA;
    delete[] zd;
    delete[] md;
    delete[] offsets;
    return result;
}

// sign: RFC 8032. seed is 32 bytes; out sig is 64 bytes.
void ed25519_sign(const u8 *seed, const u8 *pub, const u8 *msg, u64 msg_len,
                  u8 *sig_out) {
    ge::init_constants();
    u8 h[64];
    sha512::hash(seed, 32, nullptr, 0, nullptr, 0, h);
    u8 a_clamped[32];
    memcpy(a_clamped, h, 32);
    a_clamped[0] &= 248;
    a_clamped[31] &= 63;
    a_clamped[31] |= 64;
    // r = SHA512(prefix || msg) mod L
    u8 rdig[64];
    sha512::hash(h + 32, 32, msg, msg_len, nullptr, 0, rdig);
    u64 r[4];
    sc::reduce512(r, rdig);
    u8 rb[32];
    sc::to_bytes(rb, r);
    ge::P Rp;
    ge::scalar_mul(&Rp, rb, &ge::BASE);
    u8 Renc[32];
    ge::compress(Renc, &Rp);
    // k = SHA512(R || A || M) mod L
    u8 kdig[64];
    sha512::hash(Renc, 32, pub, 32, msg, msg_len, kdig);
    u64 k[4], a_words[4], s[4];
    sc::reduce512(k, kdig);
    // a mod L (clamped a < 2^255, reduce via 512-bit path)
    u8 a64[64] = {0};
    memcpy(a64, a_clamped, 32);
    sc::reduce512(a_words, a64);
    sc::muladd(s, k, a_words, r);  // s = k*a + r mod L
    memcpy(sig_out, Renc, 32);
    sc::to_bytes(sig_out + 32, s);
}

// pubkey from seed
void ed25519_pubkey(const u8 *seed, u8 *pub_out) {
    ge::init_constants();
    u8 h[64];
    sha512::hash(seed, 32, nullptr, 0, nullptr, 0, h);
    u8 a[32];
    memcpy(a, h, 32);
    a[0] &= 248;
    a[31] &= 63;
    a[31] |= 64;
    ge::P A;
    ge::scalar_mul(&A, a, &ge::BASE);
    ge::compress(pub_out, &A);
}

// sha512 for completeness (host tooling)
void sha512_digest(const u8 *msg, u64 len, u8 *out) {
    sha512::hash(msg, len, nullptr, 0, nullptr, 0, out);
}


// Batch challenge scalars: k_i = SHA-512(R_i || A_i || M_i) mod L,
// written at out + i*out_stride. Eight equal-length preimages at a time
// ride the AVX-512 multi-buffer SHA-512 (csrc/sha512_mb.inc) — the
// scalar hash loop was ~12 ms of every 10k-lane submit on the
// single-core host; commit sign bytes within a batch are uniformly
// sized, so grouping by length almost always fills full groups. The
// strided output serves both the k-blob export (stride 32) and the
// in-place R||S||k wire assembly (stride 96).
static void batch_k_strided(u64 n, const u8 *sigs, const u8 *pubs,
                            const u8 *msgs, const u64 *msg_lens, u8 *out,
                            u64 out_stride) {
    u64 off = 0;
    u64 i = 0;
    bool mb = sha512mb::usable();
    while (i < n) {
        u64 ml = msg_lens[i];
        u64 total = 64 + ml;
        u64 nblocks = (total + 17 + 127) / 128;
        bool group = mb && i + 8 <= n && nblocks <= 8;
        if (group) {
            for (int k = 1; k < 8; k++)
                if (msg_lens[i + k] != ml) { group = false; break; }
        }
        if (group) {
            alignas(64) u8 scratch[8][8 * 128];
            const u8 *ptrs[8];
            u8 digests[8][64];
            u64 o = off;
            for (int k = 0; k < 8; k++) {
                u8 *buf = scratch[k];
                // zero only the padding tail: bytes [0, total) are
                // overwritten by the copies below
                memset(buf + total, 0, nblocks * 128 - total);
                memcpy(buf, sigs + (i + k) * 64, 32);
                memcpy(buf + 32, pubs + (i + k) * 32, 32);
                memcpy(buf + 64, msgs + o, ml);
                buf[total] = 0x80;
                u64 bits = total * 8;
                u8 *lp = buf + nblocks * 128 - 8;
                for (int j = 0; j < 8; j++) lp[j] = (u8)(bits >> (56 - 8 * j));
                ptrs[k] = buf;
                o += ml;
            }
            sha512mb::hash8_padded(ptrs, nblocks, digests);
            for (int k = 0; k < 8; k++) {
                u64 kk[4];
                sc::reduce512(kk, digests[k]);
                sc::to_bytes(out + (i + k) * out_stride, kk);
            }
            i += 8;
            off = o;
        } else {
            u8 digest[64];
            sha512::hash(sigs + i * 64, 32, pubs + i * 32, 32, msgs + off,
                         ml, digest);
            u64 kk[4];
            sc::reduce512(kk, digest);
            sc::to_bytes(out + i * out_stride, kk);
            off += ml;
            i += 1;
        }
    }
}

void ed25519_batch_k(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                     const u64 *msg_lens, u8 *out) {
    batch_k_strided(n, sigs, pubs, msgs, msg_lens, out, 32);
}

// Assemble the device wire buffer R||S||k for n lanes directly into the
// caller's (stride 96) numpy array: one call replaces the Python-side
// k-blob round trip plus two numpy copies on the hot submit path
// (crypto/ed25519.py _launch_device).
void ed25519_pack_rsk(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                      const u64 *msg_lens, u8 *out_rsk) {
    for (u64 i = 0; i < n; i++) memcpy(out_rsk + i * 96, sigs + i * 64, 64);
    batch_k_strided(n, sigs, pubs, msgs, msg_lens, out_rsk + 64, 96);
}

}  // extern "C"

// RLC/MSM host packer — native port of crypto/rlc.py prepare
// (own extern "C" exports: rlc_pack, rlc_packer_threads)
#include "rlc_packer.inc"

// SHA-256 + RFC-6962 merkle root engine (own extern "C" exports)
#include "merkle_native.inc"

// Columnar Commit wire parser (own extern "C" exports)
#include "commit_codec.inc"

// secp256k1 ECDSA verify engine — 5x52 field, wNAF Strauss–Shamir
// (own extern "C" exports: secp256k1_verify, secp256k1_multi_verify;
// uses sha256_oneshot from merkle_native.inc, pool from rlc_packer.inc)
#include "secp256k1.inc"

// sr25519 batch verification — merlin/STROBE transcripts, ristretto
// decode, mod-L residue (own extern "C" exports; uses the fe/sc/ge
// cores, keccak_f1600 and edwards_msm_is_identity from this TU)
#include "sr25519_native.inc"

// BLS12-381 pairing engine — aggregate-signature track (own extern "C"
// exports; uses sha256n from merkle_native.inc, pool from rlc_packer.inc)
#include "bls12_381.inc"

// GF(2^16) Reed-Solomon erasure codec — data-availability sampling
// track (own extern "C" exports: rs_encode16, rs_reconstruct16,
// rs_gf16_threads; uses the pool from rlc_packer.inc)
#include "rs_gf16.inc"

// BLS12-381 G1 Pippenger MSM — KZG polynomial-commitment opening
// engine (own extern "C" exports: g1_msm, g1_msm_threads; uses the
// G1 core from bls12_381.inc, pool from rlc_packer.inc)
#include "g1_msm.inc"
