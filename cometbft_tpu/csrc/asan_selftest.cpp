// AddressSanitizer self-test driver for the native Ed25519 engine
// (reference runs its Go race detector + sanitizers over the crypto
// paths; this is the csrc analogue — SURVEY §5.2).
//
// Build + run via tools/asan_check.sh:
//   g++ -O1 -g -fsanitize=address,undefined csrc/ed25519_native.cpp \
//       csrc/asan_selftest.cpp -o /tmp/ed25519_asan && /tmp/ed25519_asan
//
// Exercises sign, single verify (valid / corrupted / truncated-ish
// garbage), and the threaded RLC batch with mixed message lengths, so
// ASAN/UBSAN sees every buffer path including the multi-thread phase.
// The secp256k1 and sr25519 engine units get the same treatment:
// embedded known-good vectors for the accept paths, synthesized r/s
// boundary values, bad point encodings, n==0 batches, identity
// results, and chunk-count determinism.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

typedef uint8_t u8;
typedef uint64_t u64;

extern "C" {
int ed25519_verify(const u8 *pub, const u8 *msg, u64 msg_len, const u8 *sig);
int ed25519_batch_verify(u64 n, const u8 *pubs, const u8 *msgs,
                         const u64 *msg_lens, const u8 *sigs);
void ed25519_sign(const u8 *seed, const u8 *pub, const u8 *msg, u64 msg_len,
                  u8 *sig_out);
void ed25519_pubkey(const u8 *seed, u8 *pub_out);
void ed25519_batch_k(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                     const u64 *msg_lens, u8 *out);
void ed25519_pack_rsk(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                      const u64 *msg_lens, u8 *out_rsk);
void keccak_f1600(u8 *state);
int edwards_msm_is_identity(u64 n, const u8 *xs, const u8 *ys,
                            const u8 *scalars);
void merkle_root_native(u64 n, const u8 *blob, const u64 *offs, u8 *out32);
void sha256_oneshot(const u8 *data, u64 len, u8 *out32);
long commit_parse(const u8 *buf, u64 len, u64 cap, u64 *head, u8 *flags,
                  u8 *addr_lens, u8 *addrs, int64_t *ts_s, int64_t *ts_n,
                  u8 *sig_lens, u8 *sigs, u64 *spans);
long rlc_pack(u64 n, u64 bucket, u64 depth, const u8 *pubs, const u8 *sigs,
              const u8 *msgs, const u64 *msg_lens, const u8 *skip,
              const u8 *zs, int elem_size, int nchunks, u8 *out_stream,
              u8 *out_neg, u8 *out_counts, int32_t *out_weights, u8 *out_c,
              u64 *out_s_rounds);
int rlc_packer_threads(void);
int secp256k1_engine(void);
int secp256k1_verify(const u8 *pub, const u8 *msg, u64 msg_len,
                     const u8 *sig);
long secp256k1_multi_verify(u64 n, const u8 *pubs, const u8 *msgs,
                            const u64 *msg_lens, const u8 *sigs, int nchunks,
                            u8 *out_ok);
int sr25519_engine(void);
void sr25519_challenge(const u8 *pub, const u8 *msg, u64 msg_len,
                       const u8 *r32, u8 *out32);
int sr25519_ristretto_decode(const u8 *in, u8 *out_x, u8 *out_y);
int sr25519_batch_residue(u64 n, const u8 *ss, const u8 *cs, const u8 *zs,
                          u8 *out_zc, u8 *out_zsum);
int sr25519_batch_verify(u64 n, const u8 *pubs, const u8 *msgs,
                         const u64 *msg_lens, const u8 *sigs, const u8 *zs);
int bls_engine(void);
int bls_pubkey(const u8 *sk32, u8 *out48);
int bls_pairing(const u8 *p48, const u8 *q96, u8 *out576);
int g1_msm(u64 n, const u8 *scalars, const u8 *points, const u8 *skip,
           int nchunks, u8 *out48);
int g1_msm_threads(void);
int bls_sign(const u8 *sk32, const u8 *msg, u64 mlen, const u8 *dst,
             u64 dlen, u8 *out96);
int bls_hash_to_g2(const u8 *msg, u64 mlen, const u8 *dst, u64 dlen,
                   u8 *out96);
int bls_verify(const u8 *pub48, const u8 *msg, u64 mlen, const u8 *dst,
               u64 dlen, const u8 *sig96);
int bls_g1_subgroup_check(const u8 *in48);
int bls_g2_subgroup_check(const u8 *in96);
int bls_aggregate_sigs(u64 n, const u8 *blob, int nchunks, u8 *out96);
int bls_aggregate_pubkeys(u64 n, const u8 *blob, const u8 *bitmap,
                          int nchunks, u8 *out48);
int bls_cert_verify(u64 n, const u8 *pubs, const u8 *bitmap, const u8 *msg,
                    u64 mlen, const u8 *agg_sig96, const u8 *dst, u64 dlen,
                    int nchunks);
int rs_gf16_threads(void);
long rs_encode16(u64 shard_len, uint32_t k, uint32_t m, const u8 *data,
                 u8 *parity_out, int nchunks);
long rs_reconstruct16(u64 shard_len, uint32_t k, uint32_t m, const u8 *shards,
                      const u8 *present, u8 *out, int nchunks);
}

// deterministic PRNG for the fuzz loops (no OS entropy in the harness)
static u64 lcg_state = 0x243F6A8885A308D3ULL;
static u8 lcg() {
    lcg_state = lcg_state * 6364136223846793005ULL + 1442695040888963407ULL;
    return (u8)(lcg_state >> 56);
}

// run commit_parse with tightly-sized heap buffers so ASAN catches any
// out-of-bounds write; result value is irrelevant (parse-or-reject)
static void parse_once(const u8 *buf, u64 len) {
    u64 cap = len / 6 + 4;
    u64 head[4];
    std::vector<u8> flags(cap), addr_lens(cap), addrs(cap * 20);
    std::vector<int64_t> ts_s(cap), ts_n(cap);
    std::vector<u8> sig_lens(cap), sigs_out(cap * 64);
    std::vector<u64> spans(cap * 2);
    long rc = commit_parse(buf, len, cap, head, flags.data(),
                           addr_lens.data(), addrs.data(), ts_s.data(),
                           ts_n.data(), sig_lens.data(), sigs_out.data(),
                           spans.data());
    (void)rc;
}

static int new_surface_checks() {
    // --- merkle + sha256: ragged leaves incl. empty, vs double hashing
    {
        std::vector<u8> blob;
        std::vector<u64> offs;
        offs.push_back(0);
        for (int i = 0; i < 100; i++) {
            u64 ln = (u64)(i % 7) * 31;
            for (u64 b = 0; b < ln; b++) blob.push_back(lcg());
            offs.push_back(blob.size());
        }
        u8 root[32], root2[32];
        merkle_root_native(100, blob.data(), offs.data(), root);
        merkle_root_native(100, blob.data(), offs.data(), root2);
        if (memcmp(root, root2, 32) != 0) {
            printf("FAIL: merkle root not deterministic\n");
            return 1;
        }
        merkle_root_native(0, nullptr, offs.data(), root);  // empty tree
        u8 d[32];
        sha256_oneshot(blob.data(), blob.size(), d);
        sha256_oneshot(nullptr, 0, d);
    }
    // --- batch_k: uniform (8-way multibuffer) + ragged (scalar) groups
    {
        const int N = 21;
        std::vector<u8> pubs(N * 32), sigs(N * 64), msgs;
        std::vector<u64> lens(N);
        for (int i = 0; i < N; i++) {
            for (int b = 0; b < 32; b++) pubs[i * 32 + b] = lcg();
            for (int b = 0; b < 64; b++) sigs[i * 64 + b] = lcg();
            u64 ln = (i < 16) ? 100 : (u64)(i % 5) * 53;
            lens[i] = ln;
            for (u64 b = 0; b < ln; b++) msgs.push_back(lcg());
        }
        std::vector<u8> out(N * 32);
        ed25519_batch_k(N, sigs.data(), pubs.data(), msgs.data(),
                        lens.data(), out.data());
        // pack_rsk writes stride-96 rows into the same shapes; its k
        // bytes must agree with batch_k's on every lane
        std::vector<u8> rsk(N * 96);
        ed25519_pack_rsk(N, sigs.data(), pubs.data(), msgs.data(),
                         lens.data(), rsk.data());
        for (int i = 0; i < N; i++) {
            if (memcmp(rsk.data() + i * 96, sigs.data() + i * 64, 64) ||
                memcmp(rsk.data() + i * 96 + 64, out.data() + i * 32, 32)) {
                printf("pack_rsk mismatch at %d\n", i);
                return 1;
            }
        }
    }
    // --- keccak permutation + generic MSM (bounds only; logic is
    // covered by the Python differential suites)
    {
        u8 st[200];
        for (int i = 0; i < 200; i++) st[i] = lcg();
        for (int r = 0; r < 8; r++) keccak_f1600(st);
        std::vector<u8> xs(7 * 32), ys(7 * 32), ks(7 * 32);
        for (auto *v : {&xs, &ys, &ks})
            for (auto &b : *v) b = lcg() & 0x3f;
        edwards_msm_is_identity(7, xs.data(), ys.data(), ks.data());
        // n == 0: the empty sum is the identity — must report 1, and
        // must never read the (irrelevant) input pointers
        if (edwards_msm_is_identity(0, xs.data(), ys.data(), ks.data()) != 1) {
            printf("edwards_msm_is_identity(0) != 1\n");
            return 1;
        }
    }
    // --- commit_parse: synthesized valid-ish wire, then mutation fuzz
    {
        std::vector<u8> wire;
        auto put_varint = [&](u64 v) {
            while (v >= 0x80) { wire.push_back((u8)(v | 0x80)); v >>= 7; }
            wire.push_back((u8)v);
        };
        put_varint((1 << 3) | 0); put_varint(7);    // height
        put_varint((2 << 3) | 0); put_varint(1);    // round
        for (int i = 0; i < 10; i++) {              // 10 CommitSigs
            std::vector<u8> sigbody;
            auto put_inner = [&](u64 v) {
                while (v >= 0x80) { sigbody.push_back((u8)(v | 0x80)); v >>= 7; }
                sigbody.push_back((u8)v);
            };
            put_inner((1 << 3) | 0); put_inner(2);           // flag COMMIT
            put_inner((2 << 3) | 2); put_inner(20);          // addr
            for (int b = 0; b < 20; b++) sigbody.push_back(lcg());
            put_inner((3 << 3) | 2); put_inner(4);           // ts
            put_inner((1 << 3) | 0); put_inner(1700000000u & 0x7f);
            put_inner((2 << 3) | 0); put_inner(5);
            put_inner((4 << 3) | 2); put_inner(64);          // sig
            for (int b = 0; b < 64; b++) sigbody.push_back(lcg());
            put_varint((4 << 3) | 2);
            put_varint(sigbody.size());
            wire.insert(wire.end(), sigbody.begin(), sigbody.end());
        }
        parse_once(wire.data(), wire.size());
        // truncations at every boundary
        for (u64 cut = 0; cut <= wire.size(); cut += 3)
            parse_once(wire.data(), cut);
        // random mutations
        std::vector<u8> mut = wire;
        for (int round_ = 0; round_ < 5000; round_++) {
            mut = wire;
            int flips = 1 + (lcg() % 6);
            for (int f = 0; f < flips; f++)
                mut[lcg_state % mut.size()] = lcg();
            parse_once(mut.data(), mut.size());
        }
        // pure garbage
        std::vector<u8> junk(257);
        for (int round_ = 0; round_ < 2000; round_++) {
            for (auto &b : junk) b = lcg();
            parse_once(junk.data(), 1 + (lcg_state % junk.size()));
        }
    }
    printf("asan new-surface checks ok (merkle, batch_k, commit_parse fuzz)\n");
    return 0;
}

// -- secp256k1 + sr25519 engine surfaces ----------------------------------
//
// Signed host-side (no native signers: RFC 6979 / schnorrkel nonces stay
// in Python), so the accept paths run over embedded known-good vectors;
// the reject paths are synthesized in place. Mirrors the differential
// pytest suite but under ASAN/UBSAN with tightly-sized heap buffers.

static const u8 K1_PUBS[132] = {0x02,0x15,0xdc,0x82,0x89,0xff,0x18,0xff,0x2b,0x69,0x2e,0xbe,0x42,0x3d,0x27,0xf3,0x5a,0x30,0x35,0xf9,0xec,0xf8,0xca,0x7c,0x9c,0xb8,0x2c,0xed,0x5e,0x1e,0x7a,0x31,0x0d,0x03,0x08,0x5e,0xa8,0x1d,0x26,0x20,0x32,0x1e,0x24,0xd7,0xe9,0xe1,0x43,0xe4,0x38,0xfc,0x7b,0x36,0x7a,0x36,0xf2,0x54,0x09,0x09,0xa9,0x69,0x21,0x2e,0x76,0x75,0x33,0xd2,0x03,0x6d,0xdd,0x8a,0x79,0xf3,0xb1,0xa0,0xcd,0xb4,0x5b,0x7c,0x1d,0x1b,0xed,0x7c,0x18,0xc0,0x2c,0xc4,0xd5,0xc3,0x9d,0xaa,0x4b,0x98,0x6e,0x8b,0x66,0x3f,0xcc,0x68,0xb4,0x03,0x66,0x01,0x9e,0x3b,0x00,0xc9,0x24,0xa2,0x46,0xf6,0x0f,0x81,0x43,0x0c,0x4d,0xe2,0x25,0xe4,0x7f,0xfd,0xbc,0x16,0x48,0xaf,0x67,0xd6,0x50,0xd0,0x57,0x12,0xe9,0x23};
static const u8 K1_SIGS[256] = {0x18,0xac,0xb4,0x9a,0xc9,0x4c,0x1d,0x80,0x5c,0xef,0x8e,0xa1,0xdd,0xf9,0xe0,0x6e,0x40,0xf1,0x2f,0xd7,0x57,0x8b,0x33,0x63,0x69,0xe8,0xf6,0x49,0x7d,0x7a,0x48,0xde,0x73,0x0a,0x6d,0xb0,0xf8,0x3b,0x87,0x34,0x62,0xf5,0xdc,0x41,0xfd,0x80,0x73,0x1d,0x6a,0xdf,0xac,0xf7,0xde,0x15,0xfb,0x83,0x03,0xc1,0x2a,0xdc,0x7f,0x5e,0xca,0x77,0x6a,0x56,0x33,0xe8,0xcd,0x18,0x6f,0x65,0x35,0x07,0x51,0xee,0xd6,0x86,0x38,0xaf,0x72,0x75,0x3e,0xd2,0x1f,0xfa,0x84,0x63,0x1c,0x2b,0xf7,0xf9,0x14,0xba,0x8a,0x7d,0x15,0x52,0x26,0x01,0x60,0xf2,0xf2,0x3f,0xcc,0xea,0x30,0x6d,0xc8,0x72,0x55,0x65,0x8e,0x12,0xe4,0xca,0x4a,0x7c,0x07,0x49,0xda,0x70,0xd8,0xc6,0xd0,0xea,0x51,0x78,0x3e,0xa9,0xc6,0x52,0x6e,0x0e,0xac,0xd3,0x94,0xf0,0xeb,0x2a,0x6f,0xe1,0x90,0x36,0x04,0xef,0x4f,0x8b,0x81,0x41,0xb4,0x4c,0xed,0xd8,0x9a,0x8d,0x9c,0x8f,0xfd,0x6c,0x5e,0x69,0xdc,0x1a,0x97,0x62,0x4c,0x3f,0x86,0x7a,0x46,0xd9,0x1d,0xe1,0x99,0x38,0x31,0x1a,0xb4,0xc8,0x62,0x12,0xd7,0xf4,0x10,0xdb,0xac,0x9b,0xcb,0xc7,0x5a,0xc2,0x54,0x2b,0xd4,0x40,0x36,0x2f,0x5a,0xbe,0xe9,0xf0,0x4f,0xe5,0x71,0x81,0x7d,0x40,0x0d,0xfc,0x9f,0x56,0x20,0x26,0x14,0x69,0xcb,0x2f,0x95,0xc6,0x22,0xf7,0x3a,0x15,0x26,0x93,0xaa,0x49,0xe8,0x23,0x17,0x62,0xd1,0xcb,0x6a,0x02,0xde,0x35,0x83,0x0a,0x0c,0x60,0x9d,0x01,0xb3,0x36,0x65,0x2b,0xb0,0x28,0xe3,0xf8,0x35,0xac,0xf9,0x71};
static const u8 K1_MSGS[227] = {0x61,0x73,0x61,0x6e,0x20,0x73,0x65,0x63,0x70,0x20,0x76,0x65,0x63,0x74,0x6f,0x72,0x20,0x6f,0x6e,0x65,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x78,0x00,0x01,0x02,0x03,0x04,0x05,0x06,0x07,0x08,0x09,0x0a,0x0b,0x0c,0x0d,0x0e,0x0f,0x10,0x11,0x12,0x13,0x14,0x15,0x16,0x17,0x18,0x19,0x1a,0x1b,0x1c,0x1d,0x1e,0x1f,0x20,0x21,0x22,0x23,0x24,0x25,0x26,0x27,0x28,0x29,0x2a,0x2b,0x2c,0x2d,0x2e,0x2f,0x30,0x31,0x32,0x33,0x34,0x35,0x36,0x37,0x38,0x39,0x3a,0x3b,0x3c,0x3d,0x3e,0x3f,0x40,0x41,0x42,0x43,0x44,0x45,0x46,0x47,0x48,0x49,0x4a,0x4b,0x4c,0x4d,0x4e,0x4f,0x50,0x51,0x52,0x53,0x54,0x55,0x56,0x57,0x58,0x59,0x5a,0x5b,0x5c,0x5d,0x5e,0x5f,0x60,0x61,0x62,0x63,0x64,0x65,0x66,0x67,0x68,0x69,0x6a,0x6b,0x6c,0x6d,0x6e,0x6f,0x70,0x71,0x72,0x73,0x74,0x75,0x76,0x77,0x78,0x79,0x7a,0x7b,0x7c,0x7d,0x7e,0x7f,0x80,0x81};
static const u64 K1_LENS[4] = {0, 20, 77, 130};
// secp256k1 group order n, big-endian (the r/s canonicality boundary)
static const u8 K1_ORDER[32] = {0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
                                0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfe,
                                0xba,0xae,0xdc,0xe6,0xaf,0x48,0xa0,0x3b,
                                0xbf,0xd2,0x5e,0x8c,0xd0,0x36,0x41,0x41};

static const u8 SR_PUBS[128] = {0x90,0x98,0x5f,0x87,0x2d,0x70,0xcf,0xe7,0x4b,0x17,0x57,0x3d,0x67,0x9b,0xa1,0x54,0x22,0x70,0x09,0xab,0x6a,0x14,0xa3,0x47,0x52,0x4d,0xd1,0x12,0x5d,0x71,0x4b,0x1b,0xe8,0x61,0x87,0xee,0x7f,0x11,0x29,0x97,0x39,0xd7,0x1a,0x77,0x8d,0xc0,0x26,0x61,0xe1,0x62,0x8a,0xd4,0x5a,0xaa,0x26,0xba,0x54,0x97,0x66,0x3e,0xde,0xc7,0x4f,0x2d,0x74,0x5c,0x17,0x96,0x44,0xcb,0x66,0x6f,0x7b,0x30,0x48,0xb2,0x0d,0x76,0xd2,0x6e,0xf7,0x38,0x56,0xff,0xc5,0x53,0xe5,0xb5,0x12,0x54,0x93,0x4f,0xf0,0xa5,0xa8,0x40,0xf4,0xbc,0xa5,0x59,0xc1,0x8c,0xba,0x51,0xf3,0xa9,0x03,0xc4,0x72,0x87,0x2b,0x7e,0x75,0x16,0x85,0x00,0x29,0xb7,0x50,0x14,0xad,0xbf,0x00,0x69,0x6e,0x4e,0x61,0x72};
static const u8 SR_SIGS[256] = {0x6e,0xce,0x8d,0x85,0x26,0x2e,0xc1,0xfc,0x47,0x1b,0xb6,0x02,0xd9,0x63,0x98,0x7a,0xd5,0x58,0x05,0xb0,0xa7,0x57,0x10,0x83,0x2b,0x01,0x41,0x0f,0xeb,0xa9,0x6b,0x08,0x79,0x62,0x37,0x83,0xa8,0xc2,0x0d,0xe0,0x51,0x34,0xea,0xf6,0xb7,0x85,0xca,0x19,0x29,0x5c,0x35,0x3e,0x29,0x3e,0x5f,0xe7,0xc1,0xbe,0xd4,0x89,0xd8,0x87,0xe4,0x82,0xcc,0x0c,0x4d,0xac,0xe9,0x25,0xc0,0x90,0x49,0x6c,0x55,0x7c,0x93,0x7c,0x39,0xf3,0x12,0x7c,0x25,0xc1,0xeb,0x17,0x81,0xd0,0xf5,0xd6,0xe7,0x99,0x63,0x6a,0x81,0x67,0x76,0xb3,0xad,0xa6,0x3c,0xb2,0xef,0x93,0x00,0xc6,0x82,0xa8,0x04,0x67,0x1e,0xfa,0x4b,0xcf,0x67,0x52,0x18,0xab,0xa6,0x35,0x28,0x05,0xf6,0xeb,0xe4,0x4b,0xa0,0x87,0xa2,0x4e,0x32,0xdb,0x84,0x42,0x89,0x66,0x21,0x92,0x6e,0xd6,0x12,0x55,0xbd,0x56,0xa4,0x85,0xe4,0xb8,0xb3,0x81,0x64,0x46,0x7d,0x7c,0x1e,0xdc,0x7b,0x16,0x13,0x12,0x88,0x0b,0xbd,0x76,0xba,0x8d,0xae,0x92,0xdb,0x9a,0xc2,0xdc,0x5f,0x2e,0x01,0x58,0xf4,0x4d,0x2a,0xca,0x20,0x9b,0x01,0x0e,0x6e,0x0e,0x4b,0xf8,0x6d,0x94,0xa3,0x81,0x46,0x70,0x65,0xa7,0x9f,0xfd,0xcc,0x2f,0xe0,0x2a,0x9e,0xc9,0x16,0x43,0xb3,0x09,0xb0,0x47,0xaa,0xba,0xe7,0x64,0x4e,0x24,0x66,0xbf,0x83,0xc1,0x31,0x6d,0x60,0x1f,0x61,0x0e,0xb7,0xc6,0x9f,0x03,0xee,0xf5,0x4b,0x9e,0x28,0x94,0xdb,0x9b,0xb4,0xf1,0x6d,0xde,0x59,0x16,0x05,0xae,0xd1,0x3e,0xfc,0x09,0xdd,0x66,0x09,0xde,0x9d,0x8b};
static const u8 SR_MSGS[159] = {0x61,0x73,0x61,0x6e,0x20,0x73,0x72,0x20,0x76,0x65,0x63,0x74,0x6f,0x72,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x79,0x00,0x01,0x02,0x03,0x04,0x05,0x06,0x07,0x08,0x09,0x0a,0x0b,0x0c,0x0d,0x0e,0x0f,0x10,0x11,0x12,0x13,0x14,0x15,0x16,0x17,0x18,0x19,0x1a,0x1b,0x1c,0x1d,0x1e,0x1f,0x20,0x21,0x22,0x23,0x24,0x25,0x26,0x27,0x28,0x29,0x2a,0x2b,0x2c,0x2d,0x2e,0x2f,0x30,0x31,0x32,0x33,0x34,0x35,0x36,0x37,0x38,0x39,0x3a,0x3b,0x3c,0x3d,0x3e,0x3f,0x40,0x41,0x42,0x43,0x44,0x45,0x46,0x47,0x48,0x49,0x4a,0x4b,0x4c,0x4d,0x4e,0x4f,0x50,0x51,0x52,0x53,0x54,0x55,0x56,0x57,0x58,0x59};
static const u64 SR_LENS[4] = {0, 14, 55, 90};
// ed25519 group order L, little-endian (the sr scalar canonicality bound)
static const u8 SR_ORDER_LE[32] = {0xed,0xd3,0xf5,0x5c,0x1a,0x63,0x12,0x58,
                                   0xd6,0x9c,0xf7,0xa2,0xde,0xf9,0xde,0x14,
                                   0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,
                                   0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x10};

static int secp256k1_checks() {
    if (secp256k1_engine() < 1) {
        printf("FAIL: secp256k1_engine < 1\n");
        return 1;
    }
    // accept path: every embedded vector verifies singly
    const u8 *msg = K1_MSGS;
    for (int i = 0; i < 4; i++) {
        if (!secp256k1_verify(K1_PUBS + i * 33, msg, K1_LENS[i],
                              K1_SIGS + i * 64)) {
            printf("FAIL: secp vector %d rejected\n", i);
            return 1;
        }
        msg += K1_LENS[i];
    }
    // r/s boundary cases on vector 1: r=0, s=0, s=n (order itself),
    // s=n-1 (>= n/2: upper-half malleability), all must reject without
    // touching out-of-range limbs
    u8 sig[64];
    const u8 *m1 = K1_MSGS + K1_LENS[0];
    const struct { int off; const u8 *src; } edges[] = {
        {0, nullptr},            // r = 0
        {32, nullptr},           // s = 0
        {32, K1_ORDER},          // s = n (non-canonical)
    };
    for (auto &e : edges) {
        memcpy(sig, K1_SIGS + 64, 64);
        if (e.src) memcpy(sig + e.off, e.src, 32);
        else       memset(sig + e.off, 0, 32);
        if (secp256k1_verify(K1_PUBS + 33, m1, K1_LENS[1], sig)) {
            printf("FAIL: secp r/s edge accepted (off %d)\n", e.off);
            return 1;
        }
    }
    memcpy(sig, K1_SIGS + 64, 64);
    memcpy(sig + 32, K1_ORDER, 32);
    sig[63] -= 1;  // s = n-1: canonical range but upper half -> reject
    if (secp256k1_verify(K1_PUBS + 33, m1, K1_LENS[1], sig)) {
        printf("FAIL: secp high-s accepted\n");
        return 1;
    }
    // invalid point encodings: bad parity byte, x >= p, identity-ish
    // all-zero key; each must reject cleanly
    u8 pub[33];
    memcpy(pub, K1_PUBS + 33, 33);
    pub[0] = 0x04;  // not a compressed-form prefix
    if (secp256k1_verify(pub, m1, K1_LENS[1], K1_SIGS + 64)) {
        printf("FAIL: secp bad parity byte accepted\n");
        return 1;
    }
    memset(pub, 0xff, 33); pub[0] = 0x02;  // x >= p
    u8 zpub[33]; memset(zpub, 0, 33); zpub[0] = 0x02;  // x=0: not on curve
    if (secp256k1_verify(pub, m1, K1_LENS[1], K1_SIGS + 64) ||
        secp256k1_verify(zpub, m1, K1_LENS[1], K1_SIGS + 64)) {
        printf("FAIL: secp invalid point accepted\n");
        return 1;
    }
    // multi-verify: n==0 returns 0; mixed batch (one corrupted) returns
    // the same bitmap for every chunk count
    if (secp256k1_multi_verify(0, nullptr, nullptr, nullptr, nullptr, 0,
                               nullptr) != 0) {
        printf("FAIL: secp multi(0) != 0\n");
        return 1;
    }
    std::vector<u8> sigs(K1_SIGS, K1_SIGS + 256);
    sigs[2 * 64 + 7] ^= 1;  // corrupt vector 2
    u8 ref[4];
    long nref = secp256k1_multi_verify(4, K1_PUBS, K1_MSGS, K1_LENS,
                                       sigs.data(), 1, ref);
    if (nref != 3 || !ref[0] || !ref[1] || ref[2] || !ref[3]) {
        printf("FAIL: secp multi bitmap wrong\n");
        return 1;
    }
    for (int nc : {0, 2, 3, 7}) {
        u8 got[4];
        long nv = secp256k1_multi_verify(4, K1_PUBS, K1_MSGS, K1_LENS,
                                         sigs.data(), nc, got);
        if (nv != nref || memcmp(got, ref, 4) != 0) {
            printf("FAIL: secp multi not chunk-deterministic (nc=%d)\n", nc);
            return 1;
        }
    }
    printf("asan secp256k1 checks ok (vectors, r/s edges, bad points, "
           "chunk determinism)\n");
    return 0;
}

static int sr25519_checks() {
    if (sr25519_engine() < 1) {
        printf("FAIL: sr25519_engine < 1\n");
        return 1;
    }
    // ristretto decode: valid pubkeys round through; the identity
    // (all-zero) encoding decodes to (0, 1); negated/noncanonical reject
    u8 x[32], y[32];
    for (int i = 0; i < 4; i++) {
        if (!sr25519_ristretto_decode(SR_PUBS + i * 32, x, y)) {
            printf("FAIL: sr pubkey %d undecodable\n", i);
            return 1;
        }
    }
    u8 ident[32]; memset(ident, 0, 32);
    if (!sr25519_ristretto_decode(ident, x, y)) {
        printf("FAIL: sr identity encoding rejected\n");
        return 1;
    }
    u8 one[32]; memset(one, 0, 32); one[0] = 1;
    for (int b = 0; b < 32; b++) {
        if (x[b] != 0 || y[b] != one[b]) {
            printf("FAIL: sr identity != (0,1)\n");
            return 1;
        }
    }
    u8 bad[32];
    memcpy(bad, SR_PUBS, 32); bad[0] ^= 1;  // negative field element
    u8 ff[32]; memset(ff, 0xff, 32);        // non-canonical (>= p)
    if (sr25519_ristretto_decode(bad, x, y) ||
        sr25519_ristretto_decode(ff, x, y)) {
        printf("FAIL: sr invalid encoding accepted\n");
        return 1;
    }
    // challenge: deterministic (same transcript twice -> same scalar)
    u8 c1[32], c2[32];
    sr25519_challenge(SR_PUBS, SR_MSGS, 14, SR_SIGS, c1);
    sr25519_challenge(SR_PUBS, SR_MSGS, 14, SR_SIGS, c2);
    if (memcmp(c1, c2, 32) != 0) {
        printf("FAIL: sr challenge not deterministic\n");
        return 1;
    }
    // batch residue: n==0 is the empty sum (zsum = 0); zero scalars
    // give identity results (z*0 = 0 even though z itself is forced
    // odd); s >= L rejects
    u8 zsum[32];
    if (sr25519_batch_residue(0, nullptr, nullptr, nullptr, nullptr,
                              zsum) != 1) {
        printf("FAIL: sr residue(0) != 1\n");
        return 1;
    }
    for (int b = 0; b < 32; b++)
        if (zsum[b]) { printf("FAIL: sr residue(0) zsum != 0\n"); return 1; }
    u8 ss[3 * 32], cs[3 * 32], zs[3 * 16], zc[3 * 32];
    memset(ss, 0, sizeof ss);             // s=0, c=0: identity residues
    memset(cs, 0, sizeof cs);
    for (auto &b : zs) b = lcg();
    if (sr25519_batch_residue(3, ss, cs, zs, zc, zsum) != 1) {
        printf("FAIL: sr residue rejected canonical batch\n");
        return 1;
    }
    for (int b = 0; b < 3 * 32; b++)
        if (zc[b]) { printf("FAIL: sr residue c=0 not identity\n"); return 1; }
    for (int b = 0; b < 32; b++)
        if (zsum[b]) { printf("FAIL: sr residue s=0 zsum != 0\n"); return 1; }
    for (auto &b : cs) b = lcg() & 0x0f;  // small => canonical scalars
    memcpy(ss + 32, SR_ORDER_LE, 32);     // s_1 = L: non-canonical
    if (sr25519_batch_residue(3, ss, cs, zs, zc, zsum) != 0) {
        printf("FAIL: sr residue accepted s >= L\n");
        return 1;
    }
    // batch verify: n==0 vacuously valid; embedded vectors accept under
    // two different z draws; one flipped bit (and a cleared marker)
    // fails the whole batch
    if (sr25519_batch_verify(0, nullptr, nullptr, nullptr, nullptr,
                             nullptr) != 1) {
        printf("FAIL: sr batch(0) != 1\n");
        return 1;
    }
    u8 z4[4 * 16];
    for (auto &b : z4) b = lcg();
    if (sr25519_batch_verify(4, SR_PUBS, SR_MSGS, SR_LENS, SR_SIGS,
                             z4) != 1) {
        printf("FAIL: sr valid batch rejected\n");
        return 1;
    }
    for (auto &b : z4) b = lcg();  // different randomizers, same verdict
    if (sr25519_batch_verify(4, SR_PUBS, SR_MSGS, SR_LENS, SR_SIGS,
                             z4) != 1) {
        printf("FAIL: sr valid batch rejected (z draw 2)\n");
        return 1;
    }
    std::vector<u8> sigs(SR_SIGS, SR_SIGS + 256);
    sigs[1 * 64 + 9] ^= 4;
    if (sr25519_batch_verify(4, SR_PUBS, SR_MSGS, SR_LENS, sigs.data(),
                             z4) != 0) {
        printf("FAIL: sr corrupted batch accepted\n");
        return 1;
    }
    sigs.assign(SR_SIGS, SR_SIGS + 256);
    sigs[3 * 64 + 63] &= 0x7f;  // schnorrkel marker bit cleared
    if (sr25519_batch_verify(4, SR_PUBS, SR_MSGS, SR_LENS, sigs.data(),
                             z4) != 0) {
        printf("FAIL: sr marker-less sig accepted\n");
        return 1;
    }
    printf("asan sr25519 checks ok (ristretto, challenge, residue, "
           "batch verify)\n");
    return 0;
}

// crypto/rlc.py slot_depth: ceil(mean + 4*sqrt(mean) + 4), mean =
// max(bucket/512, 1) — recomputed here so the harness exercises the
// same (bucket, depth) pairs the Python caller ships
static u64 slot_depth(u64 bucket) {
    double mean = bucket > 512 ? (double)bucket / 512.0 : 1.0;
    double d = mean + 4.0 * __builtin_sqrt(mean) + 4.0;
    u64 r = (u64)d;
    return (double)r < d ? r + 1 : r;
}

// one rlc_pack call with TIGHTLY-sized heap outputs (stream/neg exactly
// 39n entries) so ASAN catches any overrun of the emission cursors
static long pack_once(u64 n, u64 bucket, int elem_size, int nchunks,
                      const u8 *skip_override, std::vector<u8> *snap) {
    std::vector<u8> pubs(n * 32), sigs(n * 64), msgs, skip(n, 0), zs(n * 16);
    std::vector<u64> lens(n);
    for (u64 i = 0; i < n; i++) {
        for (int b = 0; b < 32; b++) pubs[i * 32 + b] = lcg();
        for (int b = 0; b < 64; b++) sigs[i * 64 + b] = lcg();
        for (int b = 0; b < 16; b++) zs[i * 16 + b] = lcg();
        u64 ln = (i % 4) * 33;  // ragged incl. zero-length
        lens[i] = ln;
        for (u64 b = 0; b < ln; b++) msgs.push_back(lcg());
    }
    if (skip_override) memcpy(skip.data(), skip_override, n);
    u64 cap = 39 * n;  // exact contribution bound: 13 z + 26 m digits
    std::vector<u8> stream(cap ? cap * (u64)elem_size : 1);
    std::vector<u8> neg(cap ? cap : 1), counts(39 * 512);
    std::vector<int32_t> weights(39 * 512);
    u8 c_out[32];
    u64 s_rounds = 0;
    long rc = rlc_pack(n, bucket, slot_depth(bucket), pubs.data(),
                       sigs.data(), msgs.data(), lens.data(), skip.data(),
                       zs.data(), elem_size, nchunks, stream.data(),
                       neg.data(), counts.data(), weights.data(), c_out,
                       &s_rounds);
    if (snap && rc >= 0) {
        snap->assign(stream.begin(), stream.begin() + (size_t)rc * elem_size);
        snap->insert(snap->end(), neg.begin(), neg.begin() + rc);
        snap->insert(snap->end(), counts.begin(), counts.end());
        const u8 *w = (const u8 *)weights.data();
        snap->insert(snap->end(), w, w + 39 * 512 * 4);
        snap->insert(snap->end(), c_out, c_out + 32);
        snap->push_back((u8)s_rounds);
    }
    return rc;
}

static int rlc_packer_checks() {
    if (rlc_packer_threads() < 1) {
        printf("FAIL: rlc_packer_threads < 1\n");
        return 1;
    }
    // n == 0 and all-skip: decline (-2), outputs untouched beyond zeroing
    u64 dummy = 0;
    u8 c_out[32];
    std::vector<u8> counts0(39 * 512);
    std::vector<int32_t> weights0(39 * 512);
    if (rlc_pack(0, 64, slot_depth(64), nullptr, nullptr, nullptr, nullptr,
                 nullptr, nullptr, 2, 0, nullptr, nullptr, counts0.data(),
                 weights0.data(), c_out, &dummy) != -2) {
        printf("FAIL: rlc_pack(n=0) != -2\n");
        return 1;
    }
    std::vector<u8> all_skip(40, 1);
    if (pack_once(40, 64, 2, 0, all_skip.data(), nullptr) != -2) {
        printf("FAIL: rlc_pack(all-skip) != -2\n");
        return 1;
    }
    // depth guard (-3: bucket beyond the uint8 counts bound) and the
    // uint16/bucket mismatch guard
    if (rlc_pack(1, 1 << 20, 300, nullptr, nullptr, nullptr, nullptr,
                 nullptr, nullptr, 4, 0, nullptr, nullptr, counts0.data(),
                 weights0.data(), c_out, &dummy) != -3 ||
        pack_once(4, 65536, 2, 0, nullptr, nullptr) != -3) {
        printf("FAIL: rlc_pack guard rcs\n");
        return 1;
    }
    // normal mixed-length batch with a partial skip mask, both widths
    std::vector<u8> some_skip(64, 0);
    for (int i = 0; i < 64; i += 5) some_skip[i] = 1;
    if (pack_once(64, 64, 2, 0, some_skip.data(), nullptr) <= 0 ||
        pack_once(64, 10240, 4, 0, some_skip.data(), nullptr) <= 0) {
        printf("FAIL: rlc_pack normal batches\n");
        return 1;
    }
    // max-bucket shape: 65536 needs uint32 stream and depth 178 <= 255
    if (pack_once(48, 65536, 4, 0, nullptr, nullptr) <= 0) {
        printf("FAIL: rlc_pack max bucket\n");
        return 1;
    }
    // determinism contract: chunked runs must be byte-identical (the
    // lcg is reseeded so both calls generate the same batch)
    u64 seed_snapshot = lcg_state;
    std::vector<u8> one, three;
    long r1 = pack_once(96, 1024, 2, 1, nullptr, &one);
    lcg_state = seed_snapshot;
    long r3 = pack_once(96, 1024, 2, 3, nullptr, &three);
    if (r1 <= 0 || r1 != r3 || one != three) {
        printf("FAIL: rlc_pack not chunk-count deterministic\n");
        return 1;
    }
    printf("asan rlc packer checks ok (guards, skip masks, max bucket, "
           "chunk determinism)\n");
    return 0;
}

// -- GF(2^16) Reed-Solomon codec surface ----------------------------------
//
// The DA erasure codec (rs_gf16.inc) runs shard-parallel across the
// worker pool, so ASAN must see the threaded apply_rows phase with
// tight heap buffers: parameter guards, the 4096-shard ceiling edge
// (tiny shards keep it cheap), a full encode->erase-m->reconstruct
// roundtrip, and the chunk-count determinism contract the da/ layer
// relies on.

static long rs_roundtrip(uint32_t k, uint32_t m, u64 shard_len, int nchunks,
                         std::vector<u8> *out_full) {
    u64 n = (u64)k + m;
    std::vector<u8> data(k * shard_len), parity(m * shard_len);
    for (auto &b : data) b = lcg();
    long rc = rs_encode16(shard_len, k, m, data.data(), parity.data(),
                          nchunks);
    if (rc != 0) return rc;
    std::vector<u8> full(n * shard_len);
    memcpy(full.data(), data.data(), data.size());
    memcpy(full.data() + data.size(), parity.data(), parity.size());
    // erase the FIRST m shards: survivors include every parity row
    std::vector<u8> present(n, 1), holes(full);
    for (uint32_t i = 0; i < m; i++) {
        present[i] = 0;
        memset(&holes[(size_t)i * shard_len], 0xAB, shard_len);
    }
    std::vector<u8> rec(n * shard_len);
    rc = rs_reconstruct16(shard_len, k, m, holes.data(), present.data(),
                          rec.data(), nchunks);
    if (rc != 0) return rc;
    if (rec != full) return -99;
    if (out_full) *out_full = std::move(rec);
    return 0;
}

static int rs_checks() {
    if (rs_gf16_threads() < 1) {
        printf("FAIL: rs_gf16_threads < 1\n");
        return 1;
    }
    u8 buf[8] = {0}, out[8];
    u8 two_present[2] = {1, 1};
    // parameter guards: k == 0, zero/odd shard length, shard ceiling
    if (rs_encode16(2, 0, 1, buf, out, 0) != -1 ||
        rs_encode16(0, 1, 1, buf, out, 0) != -1 ||
        rs_encode16(3, 1, 1, buf, out, 0) != -1 ||
        rs_encode16(2, 4000, 200, buf, out, 0) != -1 ||
        rs_reconstruct16(2, 0, 1, buf, two_present, out, 0) != -1 ||
        rs_reconstruct16(5, 1, 1, buf, two_present, out, 0) != -1) {
        printf("FAIL: rs guard rcs\n");
        return 1;
    }
    // fewer than k survivors: decline (-2), output untouched
    {
        uint32_t k = 4, m = 4;
        std::vector<u8> shards(8 * 16, 0), rec(8 * 16);
        u8 present[8] = {1, 1, 1, 0, 0, 0, 0, 0};
        if (rs_reconstruct16(16, k, m, shards.data(), present, rec.data(),
                             0) != -2) {
            printf("FAIL: rs insufficient survivors != -2\n");
            return 1;
        }
    }
    // roundtrips: small, parity-heavy, and the 4096-shard ceiling edge
    if (rs_roundtrip(1, 1, 2, 0, nullptr) != 0 ||
        rs_roundtrip(3, 7, 34, 0, nullptr) != 0 ||
        rs_roundtrip(16, 16, 256, 0, nullptr) != 0 ||
        rs_roundtrip(2048, 2048, 2, 0, nullptr) != 0) {
        printf("FAIL: rs roundtrips\n");
        return 1;
    }
    // chunk-count determinism across the worker pool split
    u64 seed_snapshot = lcg_state;
    std::vector<u8> one, again;
    if (rs_roundtrip(8, 8, 1000, 1, &one) != 0) {
        printf("FAIL: rs chunked roundtrip\n");
        return 1;
    }
    for (int nchunks : {2, 3, 7}) {
        lcg_state = seed_snapshot;
        if (rs_roundtrip(8, 8, 1000, nchunks, &again) != 0 || again != one) {
            printf("FAIL: rs not chunk-count deterministic (%d)\n", nchunks);
            return 1;
        }
    }
    printf("asan rs gf(2^16) checks ok (guards, survivors, max shards, "
           "chunk determinism)\n");
    return 0;
}

// -- BLS12-381 pairing engine surface -------------------------------------
//
// Keys are derived natively (unlike secp/sr there IS a native signer),
// so the whole accept path is synthesized in place: keygen -> sign ->
// PoP -> pooled aggregation -> the single cert pairing check. Per-key
// pairing verifies are capped at 3 (a pairing under ASAN costs real
// time); the 128-key "max-size" shape is covered by aggregation plus
// ONE cert check instead.

static const char BLS_DST[] = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";
static const char BLS_POP[] = "BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";

static int bls_checks() {
    if (bls_engine() < 1) {
        printf("FAIL: bls_engine < 1\n");
        return 1;
    }
    const u8 *dst = (const u8 *)BLS_DST;
    const u64 dlen = sizeof(BLS_DST) - 1;
    const u8 *pop_dst = (const u8 *)BLS_POP;
    const u64 plen = sizeof(BLS_POP) - 1;
    const u8 msg[] = "asan bls aggregate vector";
    const u64 mlen = sizeof(msg) - 1;
    const int N = 128;  // max-size aggregation shape, tight buffers
    std::vector<u8> sks(N * 32, 0), pubs(N * 48), sigs(N * 96);
    for (int i = 0; i < N; i++) {
        // deterministic scalars, all nonzero and far below the order
        sks[i * 32 + 30] = (u8)(i + 1);
        sks[i * 32 + 31] = (u8)(i * 7 + 3);
        if (!bls_pubkey(&sks[i * 32], &pubs[i * 48]) ||
            !bls_sign(&sks[i * 32], msg, mlen, dst, dlen, &sigs[i * 96])) {
            printf("FAIL: bls keygen/sign %d\n", i);
            return 1;
        }
        if (i < 3 && !bls_verify(&pubs[i * 48], msg, mlen, dst, dlen,
                                 &sigs[i * 96])) {
            printf("FAIL: bls valid signature %d rejected\n", i);
            return 1;
        }
    }
    // zero scalar (outside [1, r)) must decline keygen and sign
    u8 zsk[32], tmp96[96];
    memset(zsk, 0, 32);
    u8 tmp48[48];
    if (bls_pubkey(zsk, tmp48) || bls_sign(zsk, msg, mlen, dst, dlen,
                                           tmp96)) {
        printf("FAIL: bls zero scalar accepted\n");
        return 1;
    }
    // proof-of-possession: sign own pubkey bytes under the POP dst;
    // verifies for the owner, rejects under the wrong key
    u8 pop[96];
    if (!bls_sign(sks.data(), pubs.data(), 48, pop_dst, plen, pop) ||
        !bls_verify(pubs.data(), pubs.data(), 48, pop_dst, plen, pop)) {
        printf("FAIL: bls PoP cycle\n");
        return 1;
    }
    if (bls_verify(&pubs[48], &pubs[48], 48, pop_dst, plen, pop)) {
        printf("FAIL: bls PoP accepted for wrong key\n");
        return 1;
    }
    // hash-to-curve: deterministic, lands in the r-order subgroup
    u8 h1[96], h2[96];
    if (!bls_hash_to_g2(msg, mlen, dst, dlen, h1) ||
        !bls_hash_to_g2(msg, mlen, dst, dlen, h2) ||
        memcmp(h1, h2, 96) != 0 || bls_g2_subgroup_check(h1) != 1) {
        printf("FAIL: bls hash_to_g2\n");
        return 1;
    }
    // n == 0 aggregates decline without touching output buffers
    u8 agg[96], apk[48];
    if (bls_aggregate_sigs(0, nullptr, 0, agg) != 0 ||
        bls_aggregate_pubkeys(0, nullptr, nullptr, 0, apk) != 0) {
        printf("FAIL: bls aggregate(n=0) != 0\n");
        return 1;
    }
    // infinity encodings: subgroup checks report rc 2; the identity
    // pubkey fails KeyValidate inside aggregation; the all-infinity
    // SIGNATURE aggregate is representable (and then unverifiable)
    u8 inf48[48], inf96[96];
    memset(inf48, 0, 48); inf48[0] = 0xc0;
    memset(inf96, 0, 96); inf96[0] = 0xc0;
    u8 one_bit = 0x01;
    if (bls_g1_subgroup_check(inf48) != 2 ||
        bls_g2_subgroup_check(inf96) != 2 ||
        bls_aggregate_pubkeys(1, inf48, &one_bit, 0, apk) != 0) {
        printf("FAIL: bls identity-point handling\n");
        return 1;
    }
    if (bls_aggregate_sigs(1, inf96, 0, agg) != 1 ||
        memcmp(agg, inf96, 96) != 0 ||
        bls_verify(pubs.data(), msg, mlen, dst, dlen, agg)) {
        printf("FAIL: bls infinity-signature aggregate\n");
        return 1;
    }
    // P + (-P): the Zcash sort flag (0x20) toggles negation, so two
    // copies of a key with opposite flags aggregate to the identity —
    // the degenerate apk a rogue-key split lands on; must decline
    u8 pm[96];
    memcpy(pm, pubs.data(), 48);
    memcpy(pm + 48, pubs.data(), 48);
    pm[48] ^= 0x20;
    u8 both = 0x03;
    if (bls_aggregate_pubkeys(2, pm, &both, 0, apk) != 0) {
        printf("FAIL: bls P + -P aggregate accepted\n");
        return 1;
    }
    // non-canonical encodings: missing compression flag, x >= p
    u8 bad[48];
    memcpy(bad, pubs.data(), 48);
    bad[0] &= 0x7f;
    u8 big[48]; memset(big, 0xff, 48); big[0] = 0x9f;
    if (bls_g1_subgroup_check(bad) != -1 ||
        bls_g1_subgroup_check(big) != -1) {
        printf("FAIL: bls non-canonical encoding accepted\n");
        return 1;
    }
    // max-size aggregation: byte-identical across chunk counts, and the
    // whole column collapses to one passing cert check
    std::vector<u8> bitmap(N / 8, 0xff);
    u8 agg2[96], apk2[48];
    if (bls_aggregate_sigs(N, sigs.data(), 0, agg) != 1 ||
        bls_aggregate_pubkeys(N, pubs.data(), bitmap.data(), 0, apk) != 1) {
        printf("FAIL: bls max-size aggregation\n");
        return 1;
    }
    for (int nc : {1, 3, 8}) {
        if (bls_aggregate_sigs(N, sigs.data(), nc, agg2) != 1 ||
            bls_aggregate_pubkeys(N, pubs.data(), bitmap.data(), nc,
                                  apk2) != 1 ||
            memcmp(agg, agg2, 96) != 0 || memcmp(apk, apk2, 48) != 0) {
            printf("FAIL: bls aggregation not chunk-deterministic "
                   "(nc=%d)\n", nc);
            return 1;
        }
    }
    if (bls_cert_verify(N, pubs.data(), bitmap.data(), msg, mlen, agg,
                        dst, dlen, 0) != 1) {
        printf("FAIL: bls cert over full bitmap rejected\n");
        return 1;
    }
    // one signer covered a different message: aggregate still decodes,
    // the cert pairing check must fail
    if (!bls_sign(&sks[7 * 32], h1, 96, dst, dlen, &sigs[7 * 96]) ||
        bls_aggregate_sigs(N, sigs.data(), 0, agg) != 1 ||
        bls_cert_verify(N, pubs.data(), bitmap.data(), msg, mlen, agg,
                        dst, dlen, 0) != 0) {
        printf("FAIL: bls wrong-message cert accepted\n");
        return 1;
    }
    printf("asan bls12-381 checks ok (PoP, identity points, n==0, "
           "max-size aggregation, cert pairing)\n");
    return 0;
}


// -- KZG / G1 MSM engine surface ------------------------------------------
//
// Vectors are generated by the Python oracle (crypto/kzg.py) under the
// deterministic test SRS, so the same bytes pin the native engine here
// and in tests/test_kzg_native.py. The commit/open/verify roundtrip is
// closed natively: both MSMs (commitment and quotient witness) run
// through g1_msm and the opening equation e(C - [y]G1, G2) ==
// e(pi, [tau - z]G2) is checked as a GT byte comparison via
// bls_pairing. Reject paths (scalar >= r, bad encodings) and the
// skip/identity/zero-scalar/all-skip edge shapes run under tight
// buffers so ASAN sees every phase, including the threaded ones.

static const u8 KZG_SRS[192] = {
    0x97, 0xf1, 0xd3, 0xa7, 0x31, 0x97, 0xd7, 0x94, 0x26, 0x95, 0x63, 0x8c, 
    0x4f, 0xa9, 0xac, 0x0f, 0xc3, 0x68, 0x8c, 0x4f, 0x97, 0x74, 0xb9, 0x05, 
    0xa1, 0x4e, 0x3a, 0x3f, 0x17, 0x1b, 0xac, 0x58, 0x6c, 0x55, 0xe8, 0x3f, 
    0xf9, 0x7a, 0x1a, 0xef, 0xfb, 0x3a, 0xf0, 0x0a, 0xdb, 0x22, 0xc6, 0xbb, 
    0xa0, 0xf2, 0x89, 0x9e, 0xa6, 0x16, 0x6e, 0xc0, 0xec, 0x40, 0xce, 0xde, 
    0x6e, 0x0c, 0x10, 0x04, 0xad, 0x1e, 0xf8, 0x03, 0xe5, 0x48, 0xd7, 0x57, 
    0x45, 0x36, 0x72, 0x05, 0x87, 0x22, 0xa7, 0x91, 0x59, 0x23, 0xa9, 0xee, 
    0x55, 0xde, 0x12, 0x9a, 0xb9, 0xf9, 0x7b, 0x14, 0xd0, 0x4f, 0xea, 0xce, 
    0x85, 0xc4, 0xbb, 0x38, 0xb9, 0x52, 0xbb, 0x47, 0x27, 0xe6, 0x34, 0x2e, 
    0x9b, 0xb6, 0xf7, 0xae, 0xfb, 0xe8, 0x9e, 0xc8, 0x03, 0x69, 0x83, 0xc6, 
    0x73, 0xc0, 0x20, 0x39, 0x95, 0x75, 0xc8, 0x03, 0x2e, 0x3a, 0x3c, 0x58, 
    0xae, 0xd1, 0x31, 0x04, 0xa1, 0x77, 0x2e, 0xd9, 0xed, 0x04, 0xcc, 0x94, 
    0x83, 0xed, 0x6a, 0x9a, 0x29, 0x34, 0x12, 0x15, 0x9d, 0x0d, 0x00, 0x97, 
    0xea, 0x44, 0x54, 0x4b, 0x1c, 0xab, 0x76, 0x4f, 0x29, 0x72, 0x9b, 0x72, 
    0xa7, 0xb5, 0x3b, 0xeb, 0x92, 0x28, 0x0b, 0xd4, 0x20, 0x3d, 0x5b, 0x0a, 
    0x4b, 0x1b, 0x3c, 0xa6, 0xcc, 0x54, 0x0d, 0x21, 0x7e, 0x10, 0xf1, 0x5f, 
};

static const u8 KZG_COEFFS[128] = {
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x07, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x0b, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x0d, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x11, 
};

static const u8 KZG_QUOT[96] = {
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x01, 0xf5, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x62, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x11, 
};

static const u8 KZG_C[48] = {
    0x87, 0xb2, 0x6a, 0x12, 0x54, 0xb5, 0x70, 0xec, 0x02, 0xfd, 0x91, 0x12, 
    0x78, 0x80, 0xe3, 0x43, 0x7c, 0xca, 0x0e, 0x0b, 0x0f, 0x62, 0xea, 0x0b, 
    0x01, 0x5a, 0x1b, 0xeb, 0x54, 0xd4, 0x62, 0xea, 0xb2, 0x35, 0x0f, 0x8f, 
    0x69, 0xe4, 0xcf, 0x22, 0x29, 0x43, 0x1f, 0x86, 0xa5, 0x7d, 0x0d, 0xa5, 
};

static const u8 KZG_PI[48] = {
    0x91, 0xbf, 0x92, 0x51, 0xf1, 0xa1, 0xf9, 0xa3, 0x65, 0x13, 0xf7, 0xa4, 
    0xfc, 0xee, 0x0f, 0xb1, 0x91, 0x2a, 0xa0, 0x4a, 0x0c, 0x46, 0x4b, 0x30, 
    0x1d, 0x9f, 0x04, 0x5c, 0xa7, 0x24, 0x3e, 0x24, 0x74, 0x95, 0x72, 0x8e, 
    0x0f, 0x1e, 0x76, 0x50, 0xd8, 0xcc, 0x83, 0x76, 0xc3, 0x87, 0xc8, 0x21, 
};

static const u8 KZG_A[48] = {
    0x87, 0x3e, 0xa5, 0x64, 0x68, 0xa6, 0xab, 0x0b, 0x0e, 0x9f, 0x0b, 0xcf, 
    0x38, 0x22, 0xeb, 0x63, 0x48, 0x23, 0x7b, 0x2b, 0xa8, 0xcd, 0x37, 0x4b, 
    0xfe, 0x67, 0x59, 0x96, 0xc9, 0x81, 0x2e, 0x63, 0xe7, 0x14, 0xb3, 0x68, 
    0x20, 0x8f, 0x47, 0xe0, 0x27, 0x8a, 0xb1, 0xaa, 0x14, 0x76, 0x05, 0xac, 
};

static const u8 KZG_D2[96] = {
    0xa2, 0xda, 0x52, 0x1f, 0xff, 0xfe, 0xb2, 0x7b, 0x28, 0x1d, 0x17, 0x5b, 
    0xba, 0xbb, 0x95, 0xa2, 0xdc, 0xe1, 0x7f, 0x60, 0xdc, 0xde, 0x36, 0x5b, 
    0xfe, 0x15, 0x63, 0xb9, 0xbd, 0x79, 0x80, 0x9e, 0xec, 0xbf, 0x7f, 0xcb, 
    0x56, 0x3b, 0xe8, 0x06, 0xec, 0x24, 0x17, 0xc2, 0x52, 0x5c, 0x93, 0x0a, 
    0x0b, 0x79, 0x0a, 0x16, 0x94, 0xb1, 0xe7, 0x89, 0x88, 0xdd, 0xa9, 0x78, 
    0xa2, 0x7a, 0xbe, 0xbd, 0xec, 0xf4, 0x7a, 0xa1, 0x10, 0x3e, 0xb4, 0xcb, 
    0x4d, 0x81, 0x96, 0x3d, 0x9f, 0xfc, 0xfc, 0x0a, 0x94, 0x97, 0xa2, 0xf9, 
    0x31, 0xf3, 0xcf, 0xf4, 0xf0, 0xd6, 0xda, 0x00, 0xb1, 0x76, 0xeb, 0x8b, 
};

static const u8 G2_GEN[96] = {
    0x93, 0xe0, 0x2b, 0x60, 0x52, 0x71, 0x9f, 0x60, 0x7d, 0xac, 0xd3, 0xa0, 
    0x88, 0x27, 0x4f, 0x65, 0x59, 0x6b, 0xd0, 0xd0, 0x99, 0x20, 0xb6, 0x1a, 
    0xb5, 0xda, 0x61, 0xbb, 0xdc, 0x7f, 0x50, 0x49, 0x33, 0x4c, 0xf1, 0x12, 
    0x13, 0x94, 0x5d, 0x57, 0xe5, 0xac, 0x7d, 0x05, 0x5d, 0x04, 0x2b, 0x7e, 
    0x02, 0x4a, 0xa2, 0xb2, 0xf0, 0x8f, 0x0a, 0x91, 0x26, 0x08, 0x05, 0x27, 
    0x2d, 0xc5, 0x10, 0x51, 0xc6, 0xe4, 0x7a, 0xd4, 0xfa, 0x40, 0x3b, 0x02, 
    0xb4, 0x51, 0x0b, 0x64, 0x7a, 0xe3, 0xd1, 0x77, 0x0b, 0xac, 0x03, 0x26, 
    0xa8, 0x05, 0xbb, 0xef, 0xd4, 0x80, 0x56, 0xc8, 0xc1, 0x21, 0xbd, 0xb8, 
};

static const u8 MSM8_SCALARS[256] = {
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x01, 0x80, 0x01, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x02, 0x80, 0x06, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x03, 0x80, 0x0b, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x04, 0x80, 0x10, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x05, 0x80, 0x15, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x06, 0x80, 0x1a, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x07, 0x80, 0x1f, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 
    0x00, 0x08, 0x80, 0x24, 
};

static const u8 MSM8_POINTS[384] = {
    0x97, 0xf1, 0xd3, 0xa7, 0x31, 0x97, 0xd7, 0x94, 0x26, 0x95, 0x63, 0x8c, 
    0x4f, 0xa9, 0xac, 0x0f, 0xc3, 0x68, 0x8c, 0x4f, 0x97, 0x74, 0xb9, 0x05, 
    0xa1, 0x4e, 0x3a, 0x3f, 0x17, 0x1b, 0xac, 0x58, 0x6c, 0x55, 0xe8, 0x3f, 
    0xf9, 0x7a, 0x1a, 0xef, 0xfb, 0x3a, 0xf0, 0x0a, 0xdb, 0x22, 0xc6, 0xbb, 
    0xa5, 0x72, 0xcb, 0xea, 0x90, 0x4d, 0x67, 0x46, 0x88, 0x08, 0xc8, 0xeb, 
    0x50, 0xa9, 0x45, 0x0c, 0x97, 0x21, 0xdb, 0x30, 0x91, 0x28, 0x01, 0x25, 
    0x43, 0x90, 0x2d, 0x0a, 0xc3, 0x58, 0xa6, 0x2a, 0xe2, 0x8f, 0x75, 0xbb, 
    0x8f, 0x1c, 0x7c, 0x42, 0xc3, 0x9a, 0x8c, 0x55, 0x29, 0xbf, 0x0f, 0x4e, 
    0x89, 0xec, 0xe3, 0x08, 0xf9, 0xd1, 0xf0, 0x13, 0x17, 0x65, 0x21, 0x2d, 
    0xec, 0xa9, 0x96, 0x97, 0xb1, 0x12, 0xd6, 0x1f, 0x9b, 0xe9, 0xa5, 0xf1, 
    0xf3, 0x78, 0x0a, 0x51, 0x33, 0x5b, 0x3f, 0xf9, 0x81, 0x74, 0x7a, 0x0b, 
    0x2c, 0xa2, 0x17, 0x9b, 0x96, 0xd2, 0xc0, 0xc9, 0x02, 0x4e, 0x52, 0x24, 
    0xac, 0x9b, 0x60, 0xd5, 0xaf, 0xcb, 0xd5, 0x66, 0x3a, 0x8a, 0x44, 0xb7, 
    0xc5, 0xa0, 0x2f, 0x19, 0xe9, 0xa7, 0x7a, 0xb0, 0xa3, 0x5b, 0xd6, 0x58, 
    0x09, 0xbb, 0x5c, 0x67, 0xec, 0x58, 0x2c, 0x89, 0x7f, 0xeb, 0x04, 0xde, 
    0xcc, 0x69, 0x4b, 0x13, 0xe0, 0x85, 0x87, 0xf3, 0xff, 0x9b, 0x5b, 0x60, 
    0xb0, 0xe7, 0x79, 0x1f, 0xb9, 0x72, 0xfe, 0x01, 0x41, 0x59, 0xaa, 0x33, 
    0xa9, 0x86, 0x22, 0xda, 0x3c, 0xdc, 0x98, 0xff, 0x70, 0x79, 0x65, 0xe5, 
    0x36, 0xd8, 0x63, 0x6b, 0x5f, 0xcc, 0x5a, 0xc7, 0xa9, 0x1a, 0x8c, 0x46, 
    0xe5, 0x9a, 0x00, 0xdc, 0xa5, 0x75, 0xaf, 0x0f, 0x18, 0xfb, 0x13, 0xdc, 
    0xa6, 0xe8, 0x2f, 0x6d, 0xa4, 0x52, 0x0f, 0x85, 0xc5, 0xd2, 0x7d, 0x8f, 
    0x32, 0x9e, 0xcc, 0xfa, 0x05, 0x94, 0x4f, 0xd1, 0x09, 0x6b, 0x20, 0x73, 
    0x4c, 0x89, 0x49, 0x66, 0xd1, 0x2a, 0x9e, 0x2a, 0x9a, 0x97, 0x44, 0x52, 
    0x9d, 0x72, 0x12, 0xd3, 0x38, 0x83, 0x11, 0x3a, 0x0c, 0xad, 0xb9, 0x09, 
    0xb9, 0x28, 0xf3, 0xbe, 0xb9, 0x35, 0x19, 0xee, 0xcf, 0x01, 0x45, 0xda, 
    0x90, 0x3b, 0x40, 0xa4, 0xc9, 0x7d, 0xca, 0x00, 0xb2, 0x1f, 0x12, 0xac, 
    0x0d, 0xf3, 0xbe, 0x91, 0x16, 0xef, 0x2e, 0xf2, 0x7b, 0x2a, 0xe6, 0xbc, 
    0xd4, 0xc5, 0xbc, 0x2d, 0x54, 0xef, 0x5a, 0x70, 0x62, 0x7e, 0xfc, 0xb7, 
    0xa8, 0x5a, 0xe7, 0x65, 0x58, 0x81, 0x26, 0xf5, 0xe8, 0x60, 0xd0, 0x19, 
    0xc0, 0xe2, 0x62, 0x35, 0xf5, 0x67, 0xa9, 0xc0, 0xc0, 0xb2, 0xd8, 0xff, 
    0x30, 0xf3, 0xe8, 0xd4, 0x36, 0xb1, 0x08, 0x25, 0x96, 0xe5, 0xe7, 0x46, 
    0x2d, 0x20, 0xf5, 0xbe, 0x37, 0x64, 0xfd, 0x47, 0x3e, 0x57, 0xf9, 0xcf, 
};

static const u8 MSM8_EXPECT[48] = {
    0xb3, 0x16, 0xf0, 0xd9, 0x11, 0x30, 0xeb, 0xbf, 0x0f, 0x95, 0x12, 0x7c, 
    0x32, 0x5f, 0x24, 0x9b, 0x2a, 0x6b, 0x6c, 0xca, 0xa0, 0x80, 0xbe, 0x6c, 
    0xe1, 0xc0, 0x4b, 0xd7, 0x70, 0x28, 0xf3, 0xb2, 0xfa, 0xcd, 0x80, 0x83, 
    0x63, 0x64, 0xfa, 0x4c, 0x80, 0xc9, 0xbe, 0xce, 0xfd, 0xa0, 0x6e, 0x98, 
};

static const u8 MSM_RM1_SCALAR[32] = {
    0x73, 0xed, 0xa7, 0x53, 0x29, 0x9d, 0x7d, 0x48, 0x33, 0x39, 0xd8, 0x08, 
    0x09, 0xa1, 0xd8, 0x05, 0x53, 0xbd, 0xa4, 0x02, 0xff, 0xfe, 0x5b, 0xfe, 
    0xff, 0xff, 0xff, 0xff, 0x00, 0x00, 0x00, 0x00, 
};

static const u8 MSM_RM1_EXPECT[48] = {
    0xb7, 0xf1, 0xd3, 0xa7, 0x31, 0x97, 0xd7, 0x94, 0x26, 0x95, 0x63, 0x8c, 
    0x4f, 0xa9, 0xac, 0x0f, 0xc3, 0x68, 0x8c, 0x4f, 0x97, 0x74, 0xb9, 0x05, 
    0xa1, 0x4e, 0x3a, 0x3f, 0x17, 0x1b, 0xac, 0x58, 0x6c, 0x55, 0xe8, 0x3f, 
    0xf9, 0x7a, 0x1a, 0xef, 0xfb, 0x3a, 0xf0, 0x0a, 0xdb, 0x22, 0xc6, 0xbb, 
};


static int kzg_msm_checks() {
    if (g1_msm_threads() < 1) {
        printf("FAIL: g1_msm_threads < 1\n");
        return 1;
    }
    u8 inf[48], out[48], again[48];
    memset(inf, 0, 48);
    inf[0] = 0xc0;
    // n == 0: identity, accepted
    if (g1_msm(0, nullptr, nullptr, nullptr, 0, out) != 1 ||
        memcmp(out, inf, 48) != 0) {
        printf("FAIL: msm n==0\n");
        return 1;
    }
    // commit MSM: coefficients x SRS powers, chunk-count invariant
    if (g1_msm(4, KZG_COEFFS, KZG_SRS, nullptr, 0, out) != 1 ||
        memcmp(out, KZG_C, 48) != 0) {
        printf("FAIL: kzg commit msm\n");
        return 1;
    }
    for (int nc : {1, 3, 8}) {
        if (g1_msm(4, KZG_COEFFS, KZG_SRS, nullptr, nc, again) != 1 ||
            memcmp(again, out, 48) != 0) {
            printf("FAIL: msm not chunk-count deterministic (%d)\n", nc);
            return 1;
        }
    }
    // opening witness MSM: quotient x SRS[0..2]
    if (g1_msm(3, KZG_QUOT, KZG_SRS, nullptr, 0, out) != 1 ||
        memcmp(out, KZG_PI, 48) != 0) {
        printf("FAIL: kzg quotient msm\n");
        return 1;
    }
    // the opening equation, natively: e(A, G2) == e(pi, D2) in GT
    u8 gt_a[576], gt_pi[576];
    if (bls_pairing(KZG_A, G2_GEN, gt_a) != 1 ||
        bls_pairing(KZG_PI, KZG_D2, gt_pi) != 1 ||
        memcmp(gt_a, gt_pi, 576) != 0) {
        printf("FAIL: kzg opening pairing equation\n");
        return 1;
    }
    // 8-point shape with 0x80 scalar bytes: the max-bucket tier
    // (signed digit 128) in every byte window, chunk invariant
    if (g1_msm(8, MSM8_SCALARS, MSM8_POINTS, nullptr, 0, out) != 1 ||
        memcmp(out, MSM8_EXPECT, 48) != 0) {
        printf("FAIL: msm max-bucket vector\n");
        return 1;
    }
    for (int nc : {1, 3, 8}) {
        if (g1_msm(8, MSM8_SCALARS, MSM8_POINTS, nullptr, nc, again)
                != 1 || memcmp(again, out, 48) != 0) {
            printf("FAIL: msm8 not chunk-count deterministic (%d)\n",
                   nc);
            return 1;
        }
    }
    // all-skip mask: garbage in every skipped slot is never decoded
    u8 junk[8 * 48], skip_all[8];
    memset(junk, 0xEE, sizeof junk);
    memset(skip_all, 1, 8);
    if (g1_msm(8, MSM8_SCALARS, junk, skip_all, 0, out) != 1 ||
        memcmp(out, inf, 48) != 0) {
        printf("FAIL: msm all-skip\n");
        return 1;
    }
    // partial skip: garbage only under the skipped lanes, result
    // matches the dense call over the live lanes
    u8 mixed[8 * 48], skip_odd[8];
    memcpy(mixed, MSM8_POINTS, sizeof mixed);
    for (int i = 0; i < 8; i++) {
        skip_odd[i] = (u8)(i & 1);
        if (i & 1) memset(mixed + i * 48, 0xEE, 48);
    }
    u8 dense_sc[4 * 32], dense_pt[4 * 48];
    for (int i = 0; i < 4; i++) {
        memcpy(dense_sc + i * 32, MSM8_SCALARS + 2 * i * 32, 32);
        memcpy(dense_pt + i * 48, MSM8_POINTS + 2 * i * 48, 48);
    }
    if (g1_msm(8, MSM8_SCALARS, mixed, skip_odd, 0, out) != 1 ||
        g1_msm(4, dense_sc, dense_pt, nullptr, 0, again) != 1 ||
        memcmp(out, again, 48) != 0) {
        printf("FAIL: msm partial skip\n");
        return 1;
    }
    // zero scalar and identity point entries contribute nothing
    u8 zsc[2 * 32], zpt[2 * 48];
    memset(zsc, 0, sizeof zsc);
    zsc[63] = 9;  // entry 1: scalar 9 on the identity point
    memcpy(zpt, MSM8_POINTS, 48);  // entry 0: zero scalar, real point
    memcpy(zpt + 48, inf, 48);
    if (g1_msm(2, zsc, zpt, nullptr, 0, out) != 1 ||
        memcmp(out, inf, 48) != 0) {
        printf("FAIL: msm zero-scalar/identity\n");
        return 1;
    }
    // r - 1: the largest accepted scalar
    if (g1_msm(1, MSM_RM1_SCALAR, MSM8_POINTS, nullptr, 0, out) != 1 ||
        memcmp(out, MSM_RM1_EXPECT, 48) != 0) {
        printf("FAIL: msm r-1 scalar\n");
        return 1;
    }
    // rejects: scalar >= r, bad point encoding (live lane)
    u8 big_sc[32];
    memset(big_sc, 0xFF, 32);
    if (g1_msm(1, big_sc, MSM8_POINTS, nullptr, 0, out) != 0) {
        printf("FAIL: msm scalar >= r accepted\n");
        return 1;
    }
    if (g1_msm(8, MSM8_SCALARS, junk, nullptr, 0, out) != 0) {
        printf("FAIL: msm bad encoding accepted\n");
        return 1;
    }
    printf("asan kzg/g1-msm checks ok (commit/open/verify roundtrip, "
           "n==0, skip masks, identity, max-bucket tier, chunk "
           "determinism, reject paths)\n");
    return 0;
}

int main() {
    const int N = 96;
    std::vector<u8> pubs(N * 32), sigs(N * 64), msgs;
    std::vector<u64> lens(N);
    for (int i = 0; i < N; i++) {
        u8 seed[32];
        for (int b = 0; b < 32; b++) seed[b] = (u8)(i * 7 + b);
        ed25519_pubkey(seed, &pubs[i * 32]);
        // mixed lengths incl. zero-length message
        u64 ln = (u64)(i % 5) * 37;
        lens[i] = ln;
        std::vector<u8> m(ln);
        for (u64 b = 0; b < ln; b++) m[b] = (u8)(i + b);
        ed25519_sign(seed, &pubs[i * 32], m.data(), ln, &sigs[i * 64]);
        if (!ed25519_verify(&pubs[i * 32], m.data(), ln, &sigs[i * 64])) {
            printf("FAIL: valid signature %d rejected\n", i);
            return 1;
        }
        msgs.insert(msgs.end(), m.begin(), m.end());
    }
    if (!ed25519_batch_verify(N, pubs.data(), msgs.data(), lens.data(),
                              sigs.data())) {
        printf("FAIL: valid batch rejected\n");
        return 1;
    }
    // corrupt one signature: batch must fail, single must blame it
    sigs[5 * 64 + 3] ^= 1;
    if (ed25519_batch_verify(N, pubs.data(), msgs.data(), lens.data(),
                             sigs.data())) {
        printf("FAIL: corrupted batch accepted\n");
        return 1;
    }
    // garbage inputs must reject cleanly (no OOB reads)
    u8 junk_sig[64], junk_pub[32];
    memset(junk_sig, 0xEE, sizeof junk_sig);
    memset(junk_pub, 0xDD, sizeof junk_pub);
    if (ed25519_verify(junk_pub, nullptr, 0, junk_sig)) {
        printf("FAIL: junk accepted\n");
        return 1;
    }
    if (new_surface_checks() != 0) return 1;
    if (rlc_packer_checks() != 0) return 1;
    if (secp256k1_checks() != 0) return 1;
    if (sr25519_checks() != 0) return 1;
    if (rs_checks() != 0) return 1;
    if (bls_checks() != 0) return 1;
    if (kzg_msm_checks() != 0) return 1;
    printf("asan selftest ok (%d signatures, threaded batch)\n", N);
    return 0;
}
