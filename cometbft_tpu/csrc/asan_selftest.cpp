// AddressSanitizer self-test driver for the native Ed25519 engine
// (reference runs its Go race detector + sanitizers over the crypto
// paths; this is the csrc analogue — SURVEY §5.2).
//
// Build + run via tools/asan_check.sh:
//   g++ -O1 -g -fsanitize=address,undefined csrc/ed25519_native.cpp \
//       csrc/asan_selftest.cpp -o /tmp/ed25519_asan && /tmp/ed25519_asan
//
// Exercises sign, single verify (valid / corrupted / truncated-ish
// garbage), and the threaded RLC batch with mixed message lengths, so
// ASAN/UBSAN sees every buffer path including the multi-thread phase.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

typedef uint8_t u8;
typedef uint64_t u64;

extern "C" {
int ed25519_verify(const u8 *pub, const u8 *msg, u64 msg_len, const u8 *sig);
int ed25519_batch_verify(u64 n, const u8 *pubs, const u8 *msgs,
                         const u64 *msg_lens, const u8 *sigs);
void ed25519_sign(const u8 *seed, const u8 *pub, const u8 *msg, u64 msg_len,
                  u8 *sig_out);
void ed25519_pubkey(const u8 *seed, u8 *pub_out);
void ed25519_batch_k(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                     const u64 *msg_lens, u8 *out);
void ed25519_pack_rsk(u64 n, const u8 *sigs, const u8 *pubs, const u8 *msgs,
                      const u64 *msg_lens, u8 *out_rsk);
void keccak_f1600(u8 *state);
int edwards_msm_is_identity(u64 n, const u8 *xs, const u8 *ys,
                            const u8 *scalars);
void merkle_root_native(u64 n, const u8 *blob, const u64 *offs, u8 *out32);
void sha256_oneshot(const u8 *data, u64 len, u8 *out32);
long commit_parse(const u8 *buf, u64 len, u64 cap, u64 *head, u8 *flags,
                  u8 *addr_lens, u8 *addrs, int64_t *ts_s, int64_t *ts_n,
                  u8 *sig_lens, u8 *sigs, u64 *spans);
long rlc_pack(u64 n, u64 bucket, u64 depth, const u8 *pubs, const u8 *sigs,
              const u8 *msgs, const u64 *msg_lens, const u8 *skip,
              const u8 *zs, int elem_size, int nchunks, u8 *out_stream,
              u8 *out_neg, u8 *out_counts, int32_t *out_weights, u8 *out_c,
              u64 *out_s_rounds);
int rlc_packer_threads(void);
}

// deterministic PRNG for the fuzz loops (no OS entropy in the harness)
static u64 lcg_state = 0x243F6A8885A308D3ULL;
static u8 lcg() {
    lcg_state = lcg_state * 6364136223846793005ULL + 1442695040888963407ULL;
    return (u8)(lcg_state >> 56);
}

// run commit_parse with tightly-sized heap buffers so ASAN catches any
// out-of-bounds write; result value is irrelevant (parse-or-reject)
static void parse_once(const u8 *buf, u64 len) {
    u64 cap = len / 6 + 4;
    u64 head[4];
    std::vector<u8> flags(cap), addr_lens(cap), addrs(cap * 20);
    std::vector<int64_t> ts_s(cap), ts_n(cap);
    std::vector<u8> sig_lens(cap), sigs_out(cap * 64);
    std::vector<u64> spans(cap * 2);
    long rc = commit_parse(buf, len, cap, head, flags.data(),
                           addr_lens.data(), addrs.data(), ts_s.data(),
                           ts_n.data(), sig_lens.data(), sigs_out.data(),
                           spans.data());
    (void)rc;
}

static int new_surface_checks() {
    // --- merkle + sha256: ragged leaves incl. empty, vs double hashing
    {
        std::vector<u8> blob;
        std::vector<u64> offs;
        offs.push_back(0);
        for (int i = 0; i < 100; i++) {
            u64 ln = (u64)(i % 7) * 31;
            for (u64 b = 0; b < ln; b++) blob.push_back(lcg());
            offs.push_back(blob.size());
        }
        u8 root[32], root2[32];
        merkle_root_native(100, blob.data(), offs.data(), root);
        merkle_root_native(100, blob.data(), offs.data(), root2);
        if (memcmp(root, root2, 32) != 0) {
            printf("FAIL: merkle root not deterministic\n");
            return 1;
        }
        merkle_root_native(0, nullptr, offs.data(), root);  // empty tree
        u8 d[32];
        sha256_oneshot(blob.data(), blob.size(), d);
        sha256_oneshot(nullptr, 0, d);
    }
    // --- batch_k: uniform (8-way multibuffer) + ragged (scalar) groups
    {
        const int N = 21;
        std::vector<u8> pubs(N * 32), sigs(N * 64), msgs;
        std::vector<u64> lens(N);
        for (int i = 0; i < N; i++) {
            for (int b = 0; b < 32; b++) pubs[i * 32 + b] = lcg();
            for (int b = 0; b < 64; b++) sigs[i * 64 + b] = lcg();
            u64 ln = (i < 16) ? 100 : (u64)(i % 5) * 53;
            lens[i] = ln;
            for (u64 b = 0; b < ln; b++) msgs.push_back(lcg());
        }
        std::vector<u8> out(N * 32);
        ed25519_batch_k(N, sigs.data(), pubs.data(), msgs.data(),
                        lens.data(), out.data());
        // pack_rsk writes stride-96 rows into the same shapes; its k
        // bytes must agree with batch_k's on every lane
        std::vector<u8> rsk(N * 96);
        ed25519_pack_rsk(N, sigs.data(), pubs.data(), msgs.data(),
                         lens.data(), rsk.data());
        for (int i = 0; i < N; i++) {
            if (memcmp(rsk.data() + i * 96, sigs.data() + i * 64, 64) ||
                memcmp(rsk.data() + i * 96 + 64, out.data() + i * 32, 32)) {
                printf("pack_rsk mismatch at %d\n", i);
                return 1;
            }
        }
    }
    // --- keccak permutation + generic MSM (bounds only; logic is
    // covered by the Python differential suites)
    {
        u8 st[200];
        for (int i = 0; i < 200; i++) st[i] = lcg();
        for (int r = 0; r < 8; r++) keccak_f1600(st);
        std::vector<u8> xs(7 * 32), ys(7 * 32), ks(7 * 32);
        for (auto *v : {&xs, &ys, &ks})
            for (auto &b : *v) b = lcg() & 0x3f;
        edwards_msm_is_identity(7, xs.data(), ys.data(), ks.data());
        // n == 0: the empty sum is the identity — must report 1, and
        // must never read the (irrelevant) input pointers
        if (edwards_msm_is_identity(0, xs.data(), ys.data(), ks.data()) != 1) {
            printf("edwards_msm_is_identity(0) != 1\n");
            return 1;
        }
    }
    // --- commit_parse: synthesized valid-ish wire, then mutation fuzz
    {
        std::vector<u8> wire;
        auto put_varint = [&](u64 v) {
            while (v >= 0x80) { wire.push_back((u8)(v | 0x80)); v >>= 7; }
            wire.push_back((u8)v);
        };
        put_varint((1 << 3) | 0); put_varint(7);    // height
        put_varint((2 << 3) | 0); put_varint(1);    // round
        for (int i = 0; i < 10; i++) {              // 10 CommitSigs
            std::vector<u8> sigbody;
            auto put_inner = [&](u64 v) {
                while (v >= 0x80) { sigbody.push_back((u8)(v | 0x80)); v >>= 7; }
                sigbody.push_back((u8)v);
            };
            put_inner((1 << 3) | 0); put_inner(2);           // flag COMMIT
            put_inner((2 << 3) | 2); put_inner(20);          // addr
            for (int b = 0; b < 20; b++) sigbody.push_back(lcg());
            put_inner((3 << 3) | 2); put_inner(4);           // ts
            put_inner((1 << 3) | 0); put_inner(1700000000u & 0x7f);
            put_inner((2 << 3) | 0); put_inner(5);
            put_inner((4 << 3) | 2); put_inner(64);          // sig
            for (int b = 0; b < 64; b++) sigbody.push_back(lcg());
            put_varint((4 << 3) | 2);
            put_varint(sigbody.size());
            wire.insert(wire.end(), sigbody.begin(), sigbody.end());
        }
        parse_once(wire.data(), wire.size());
        // truncations at every boundary
        for (u64 cut = 0; cut <= wire.size(); cut += 3)
            parse_once(wire.data(), cut);
        // random mutations
        std::vector<u8> mut = wire;
        for (int round_ = 0; round_ < 5000; round_++) {
            mut = wire;
            int flips = 1 + (lcg() % 6);
            for (int f = 0; f < flips; f++)
                mut[lcg_state % mut.size()] = lcg();
            parse_once(mut.data(), mut.size());
        }
        // pure garbage
        std::vector<u8> junk(257);
        for (int round_ = 0; round_ < 2000; round_++) {
            for (auto &b : junk) b = lcg();
            parse_once(junk.data(), 1 + (lcg_state % junk.size()));
        }
    }
    printf("asan new-surface checks ok (merkle, batch_k, commit_parse fuzz)\n");
    return 0;
}

// crypto/rlc.py slot_depth: ceil(mean + 4*sqrt(mean) + 4), mean =
// max(bucket/512, 1) — recomputed here so the harness exercises the
// same (bucket, depth) pairs the Python caller ships
static u64 slot_depth(u64 bucket) {
    double mean = bucket > 512 ? (double)bucket / 512.0 : 1.0;
    double d = mean + 4.0 * __builtin_sqrt(mean) + 4.0;
    u64 r = (u64)d;
    return (double)r < d ? r + 1 : r;
}

// one rlc_pack call with TIGHTLY-sized heap outputs (stream/neg exactly
// 39n entries) so ASAN catches any overrun of the emission cursors
static long pack_once(u64 n, u64 bucket, int elem_size, int nchunks,
                      const u8 *skip_override, std::vector<u8> *snap) {
    std::vector<u8> pubs(n * 32), sigs(n * 64), msgs, skip(n, 0), zs(n * 16);
    std::vector<u64> lens(n);
    for (u64 i = 0; i < n; i++) {
        for (int b = 0; b < 32; b++) pubs[i * 32 + b] = lcg();
        for (int b = 0; b < 64; b++) sigs[i * 64 + b] = lcg();
        for (int b = 0; b < 16; b++) zs[i * 16 + b] = lcg();
        u64 ln = (i % 4) * 33;  // ragged incl. zero-length
        lens[i] = ln;
        for (u64 b = 0; b < ln; b++) msgs.push_back(lcg());
    }
    if (skip_override) memcpy(skip.data(), skip_override, n);
    u64 cap = 39 * n;  // exact contribution bound: 13 z + 26 m digits
    std::vector<u8> stream(cap ? cap * (u64)elem_size : 1);
    std::vector<u8> neg(cap ? cap : 1), counts(39 * 512);
    std::vector<int32_t> weights(39 * 512);
    u8 c_out[32];
    u64 s_rounds = 0;
    long rc = rlc_pack(n, bucket, slot_depth(bucket), pubs.data(),
                       sigs.data(), msgs.data(), lens.data(), skip.data(),
                       zs.data(), elem_size, nchunks, stream.data(),
                       neg.data(), counts.data(), weights.data(), c_out,
                       &s_rounds);
    if (snap && rc >= 0) {
        snap->assign(stream.begin(), stream.begin() + (size_t)rc * elem_size);
        snap->insert(snap->end(), neg.begin(), neg.begin() + rc);
        snap->insert(snap->end(), counts.begin(), counts.end());
        const u8 *w = (const u8 *)weights.data();
        snap->insert(snap->end(), w, w + 39 * 512 * 4);
        snap->insert(snap->end(), c_out, c_out + 32);
        snap->push_back((u8)s_rounds);
    }
    return rc;
}

static int rlc_packer_checks() {
    if (rlc_packer_threads() < 1) {
        printf("FAIL: rlc_packer_threads < 1\n");
        return 1;
    }
    // n == 0 and all-skip: decline (-2), outputs untouched beyond zeroing
    u64 dummy = 0;
    u8 c_out[32];
    std::vector<u8> counts0(39 * 512);
    std::vector<int32_t> weights0(39 * 512);
    if (rlc_pack(0, 64, slot_depth(64), nullptr, nullptr, nullptr, nullptr,
                 nullptr, nullptr, 2, 0, nullptr, nullptr, counts0.data(),
                 weights0.data(), c_out, &dummy) != -2) {
        printf("FAIL: rlc_pack(n=0) != -2\n");
        return 1;
    }
    std::vector<u8> all_skip(40, 1);
    if (pack_once(40, 64, 2, 0, all_skip.data(), nullptr) != -2) {
        printf("FAIL: rlc_pack(all-skip) != -2\n");
        return 1;
    }
    // depth guard (-3: bucket beyond the uint8 counts bound) and the
    // uint16/bucket mismatch guard
    if (rlc_pack(1, 1 << 20, 300, nullptr, nullptr, nullptr, nullptr,
                 nullptr, nullptr, 4, 0, nullptr, nullptr, counts0.data(),
                 weights0.data(), c_out, &dummy) != -3 ||
        pack_once(4, 65536, 2, 0, nullptr, nullptr) != -3) {
        printf("FAIL: rlc_pack guard rcs\n");
        return 1;
    }
    // normal mixed-length batch with a partial skip mask, both widths
    std::vector<u8> some_skip(64, 0);
    for (int i = 0; i < 64; i += 5) some_skip[i] = 1;
    if (pack_once(64, 64, 2, 0, some_skip.data(), nullptr) <= 0 ||
        pack_once(64, 10240, 4, 0, some_skip.data(), nullptr) <= 0) {
        printf("FAIL: rlc_pack normal batches\n");
        return 1;
    }
    // max-bucket shape: 65536 needs uint32 stream and depth 178 <= 255
    if (pack_once(48, 65536, 4, 0, nullptr, nullptr) <= 0) {
        printf("FAIL: rlc_pack max bucket\n");
        return 1;
    }
    // determinism contract: chunked runs must be byte-identical (the
    // lcg is reseeded so both calls generate the same batch)
    u64 seed_snapshot = lcg_state;
    std::vector<u8> one, three;
    long r1 = pack_once(96, 1024, 2, 1, nullptr, &one);
    lcg_state = seed_snapshot;
    long r3 = pack_once(96, 1024, 2, 3, nullptr, &three);
    if (r1 <= 0 || r1 != r3 || one != three) {
        printf("FAIL: rlc_pack not chunk-count deterministic\n");
        return 1;
    }
    printf("asan rlc packer checks ok (guards, skip masks, max bucket, "
           "chunk determinism)\n");
    return 0;
}

int main() {
    const int N = 96;
    std::vector<u8> pubs(N * 32), sigs(N * 64), msgs;
    std::vector<u64> lens(N);
    for (int i = 0; i < N; i++) {
        u8 seed[32];
        for (int b = 0; b < 32; b++) seed[b] = (u8)(i * 7 + b);
        ed25519_pubkey(seed, &pubs[i * 32]);
        // mixed lengths incl. zero-length message
        u64 ln = (u64)(i % 5) * 37;
        lens[i] = ln;
        std::vector<u8> m(ln);
        for (u64 b = 0; b < ln; b++) m[b] = (u8)(i + b);
        ed25519_sign(seed, &pubs[i * 32], m.data(), ln, &sigs[i * 64]);
        if (!ed25519_verify(&pubs[i * 32], m.data(), ln, &sigs[i * 64])) {
            printf("FAIL: valid signature %d rejected\n", i);
            return 1;
        }
        msgs.insert(msgs.end(), m.begin(), m.end());
    }
    if (!ed25519_batch_verify(N, pubs.data(), msgs.data(), lens.data(),
                              sigs.data())) {
        printf("FAIL: valid batch rejected\n");
        return 1;
    }
    // corrupt one signature: batch must fail, single must blame it
    sigs[5 * 64 + 3] ^= 1;
    if (ed25519_batch_verify(N, pubs.data(), msgs.data(), lens.data(),
                             sigs.data())) {
        printf("FAIL: corrupted batch accepted\n");
        return 1;
    }
    // garbage inputs must reject cleanly (no OOB reads)
    u8 junk_sig[64], junk_pub[32];
    memset(junk_sig, 0xEE, sizeof junk_sig);
    memset(junk_pub, 0xDD, sizeof junk_pub);
    if (ed25519_verify(junk_pub, nullptr, 0, junk_sig)) {
        printf("FAIL: junk accepted\n");
        return 1;
    }
    if (new_surface_checks() != 0) return 1;
    if (rlc_packer_checks() != 0) return 1;
    printf("asan selftest ok (%d signatures, threaded batch)\n", N);
    return 0;
}
