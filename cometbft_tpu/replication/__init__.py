"""Scale-out serving plane: replication feed + stateless replicas.

The core node publishes an ordered, resumable frame stream
(`ReplicationFeed`) off the commit hook; `Replica` processes consume it
— snapshot bootstrap first, then a cursor-tailed live feed — and serve
the light-client / DA surfaces byte-identically with zero consensus
state. See ROADMAP item #3 and README §serving-replicas.
"""

from .feed import CursorTooOld, ReplicationFeed
from .replica import Replica

__all__ = ["CursorTooOld", "ReplicationFeed", "Replica"]
