"""Stateless serving replica (ROADMAP item #3: scale-out serving).

A replica is a separate process (``cli.py replica``) holding ZERO
consensus state: no block store, no state machine, no p2p switch. It
bootstraps from the core's replication snapshot (statesync Snapshot
shape over ``replication_snapshot``/``replication_snapshot_chunk``),
then tails the core's ``/replication_feed`` stream, folding each frame
into its own serving state:

- a real ``LightServe`` over a frame-backed store facade — the MMR is
  rebuilt from the same leaf sequence (append-only post-order, so the
  accumulator is bit-exact) and commit verification runs lazily through
  the replica's own ``VerifiedCommitCache`` under the same block-commit/
  seen-commit resolution rules, so ``/light_stream`` lines, MMR
  ancestry proofs and bisection pivots are byte-identical to the core's;
- a real ``DAServe`` re-encoding each frame's 1x systematic payload
  (RS extension + shard commitment are deterministic) and cross-checking
  the advertised ``da_root``, so ``da_sample`` openings match byte-for-
  byte;
- an ``AdmissionPipeline`` over a forwarding mempool facade: txs hitting
  the replica's ``broadcast_tx_*`` are batch-verified in the REPLICA's
  admission window (the replica registers as its own tenant on the
  shared ``VerifyScheduler``, so the PR-15 DRR fairness bounds a hot
  replica), then admitted txs are forwarded to the core one
  ``broadcast_tx_sync`` each.

Readiness: ``/healthz`` on the replica's metrics listener reports 503
while snapshot-bootstrapping or while the ``replication_feed_lag_heights``
gauge exceeds ``max_lag_heights``, 200 once caught up and serving.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict

from ..crypto.keys import tmhash
from ..light.serve import LightServe
from ..light.store import _decode_vals
from ..mempool.admission import AdmissionPipeline
from ..mempool.mempool import ErrTxInCache, ErrTxTooLarge
from ..rpc.client import HTTPClient
from ..rpc.routes import Env, REPLICA_ROUTES
from ..rpc.server import RPCServer
from ..statesync.snapshots import Snapshot, SnapshotPool, blob_hash
from ..types import Header
from ..types.agg_commit import decode_commit_any
from ..utils import trace
from ..utils.metrics import MetricsServer, replication_metrics


class _ReplicaBlock:
    """Header-only block shim: every serving path a replica exercises
    (`LightServe.on_commit`, `_verify_height`) touches only `.header`."""

    __slots__ = ("header",)

    def __init__(self, header):
        self.header = header


class _FrameStore:
    """Block-store + state-store facade over applied feed frames.

    Mirrors the core's resolution semantics exactly: the canonical
    commit FOR height h is frame h+1's embedded LastCommit, the seen
    commit is frame h's own; validators at h ride frame h. Bounded to
    the same retention window as the feed — heights that age out serve
    None, exactly like a pruned core store."""

    def __init__(self, retain: int = 1024):
        self.retain = max(1, int(retain))
        self._frames: OrderedDict[int, tuple] = OrderedDict()
        self._lock = threading.Lock()

    def put(self, height, header, last_commit, seen_commit, vals) -> None:
        with self._lock:
            self._frames[height] = (header, last_commit, seen_commit, vals)
            while len(self._frames) > self.retain:
                self._frames.popitem(last=False)

    def _get(self, height: int):
        with self._lock:
            return self._frames.get(height)

    # -- block-store role -----------------------------------------------
    def load_block(self, height: int):
        f = self._get(height)
        return _ReplicaBlock(f[0]) if f is not None else None

    def load_block_commit(self, height: int):
        nxt = self._get(height + 1)
        if nxt is None or nxt[1] is None or not nxt[1].signatures:
            return None
        return nxt[1]

    def load_seen_commit(self, height: int):
        f = self._get(height)
        return f[2] if f is not None else None

    # -- state-store role -------------------------------------------------
    def load_validators(self, height: int):
        f = self._get(height)
        return f[3] if f is not None else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._frames)


class _CheckResult:
    __slots__ = ("code", "gas_wanted")

    def __init__(self):
        self.code = 0
        self.gas_wanted = 0


class _ForwardTarget:
    """AdmissionPipeline mempool facade that forwards admitted txs to
    the core instead of inserting them locally.

    precheck keeps the pipeline's direct-path semantics (oversize →
    ErrTxTooLarge, replica-local LRU dedup → ErrTxInCache) so bad or
    duplicate txs never cost a core round-trip; signature rejects are
    the pipeline's own batch-verify stage. A core rejection surfaces to
    the replica caller as the stage-3 insert error."""

    def __init__(self, client, tenant: str, max_tx_bytes: int = 1024 * 1024,
                 cache_size: int = 10000):
        self._client = client
        self.tenant = tenant
        self.max_tx_bytes = max_tx_bytes
        self.cache_size = max(1, int(cache_size))
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()
        self.forwarded_ok = 0
        self.forwarded_rejected = 0
        self.forward_errors = 0

    def precheck(self, tx: bytes) -> bytes:
        if len(tx) > self.max_tx_bytes:
            raise ErrTxTooLarge(
                f"tx too large: {len(tx)} > {self.max_tx_bytes}")
        key = tmhash(tx)
        with self._lock:
            if key in self._seen:
                raise ErrTxInCache("tx already seen by replica")
            self._seen[key] = None
            while len(self._seen) > self.cache_size:
                self._seen.popitem(last=False)
        return key

    def app_check_batch(self, txs):
        # the core re-runs CheckTx on forward; the replica stage is a
        # pass-through so forwarding cost stays one round-trip per tx
        return [_CheckResult() for _ in txs]

    def insert_batch(self, items):
        m = replication_metrics()
        errs = []
        for key, tx, _gas in items:
            try:
                r = self._client.broadcast_tx_sync(tx=tx.hex())
                code = int(r.get("code", 0))
            except Exception as e:  # noqa: BLE001 — core unreachable
                self.forward_errors += 1
                m.forwarded_txs_total.inc(1, self.tenant, "error")
                self.note_rejected(key)
                errs.append(ValueError(f"forward to core failed: {e}"))
                continue
            if code == 0:
                self.forwarded_ok += 1
                m.forwarded_txs_total.inc(1, self.tenant, "ok")
                errs.append(None)
            else:
                self.forwarded_rejected += 1
                m.forwarded_txs_total.inc(1, self.tenant, "rejected")
                self.note_rejected(key)
                errs.append(ValueError(
                    f"core rejected tx: {r.get('log', '')}"))
        return errs

    def note_rejected(self, key) -> None:
        with self._lock:
            self._seen.pop(key, None)

    def notify_new_txs(self, txs) -> None:
        pass


class _ReplicaMempool:
    """Env.mempool facade: the broadcast routes drive the replica's
    admission pipeline (sync blocks on the verdict, async enqueues)."""

    def __init__(self, pipeline: AdmissionPipeline):
        self.pipeline = pipeline

    def check_tx(self, tx: bytes, from_peer: str = "") -> None:
        self.pipeline.check_tx(tx, from_peer)

    def submit_tx(self, tx: bytes):
        return self.pipeline.submit(tx)

    def size(self) -> int:
        return 0

    def total_bytes(self) -> int:
        return 0

    def reap_max_txs(self, n: int):
        return []


class _DAShim:
    """Minimal config.DAConfig stand-in for a feed-driven DAServe: the
    geometry comes from the frames, not a local config file."""

    def __init__(self, k: int, m: int, retain_heights: int):
        self.enabled = True
        self.data_shards = k
        self.parity_shards = m
        self.retain_heights = retain_heights


class Replica:
    """Feed consumer + stateless serving surfaces for one core node."""

    def __init__(
        self,
        core_url: str,
        *,
        name: str = "",
        backend: str = "cpu",
        rpc_host: str = "127.0.0.1",
        rpc_port: int = 0,
        metrics_host: str = "127.0.0.1",
        metrics_port: int | None = None,
        retain_frames: int = 1024,
        max_lag_heights: int = 16,
        healthz_window_s: float = 30.0,
        forward_admission: bool = True,
        da_retain_heights: int = 64,
        light_cache_size: int = 4096,
        subscriber_queue: int = 4096,
        payload_retain: int = 4096,
        admission_window: int = 256,
        admission_max_delay_s: float = 0.002,
        feed_timeout_s: float = 30.0,
        sched=None,
        client=None,
    ):
        self.core_url = core_url.rstrip("/")
        self.name = name or f"replica-{id(self) & 0xFFFF:04x}"
        self.backend = backend
        self.rpc_host, self.rpc_port = rpc_host, rpc_port
        self.metrics_host, self.metrics_port = metrics_host, metrics_port
        self.retain_frames = retain_frames
        self.max_lag_heights = max_lag_heights
        self.healthz_window_s = healthz_window_s
        self.forward_admission = forward_admission
        self.da_retain_heights = da_retain_heights
        self.light_cache_size = light_cache_size
        self.subscriber_queue = subscriber_queue
        self.payload_retain = payload_retain
        self.admission_window = admission_window
        self.admission_max_delay_s = admission_max_delay_s
        self.feed_timeout_s = feed_timeout_s
        self.client = client or HTTPClient(self.core_url)
        self._own_sched = sched is None
        self.sched = sched

        self.chain_id: str = ""
        self.store: _FrameStore | None = None
        self.light_serve: LightServe | None = None
        self.da_serve = None
        self.pipeline: AdmissionPipeline | None = None
        self.env: Env | None = None
        self.rpc_server: RPCServer | None = None
        self.metrics_server: MetricsServer | None = None
        self.snapshots = SnapshotPool()
        self.snapshot_height = 0

        self.bootstrapped = False
        self.applied_height = 0
        self.core_tip = 0
        self.applied_frames = 0
        self.gaps = 0
        self.feed_connects = 0
        self.cert_kinds: dict[str, int] = {}
        self._apply_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._resp = None  # live feed response, closed on stop()

    # -- readiness ---------------------------------------------------------
    def _set_lag(self) -> None:
        lag = max(0, self.core_tip - self.applied_height)
        replication_metrics().feed_lag_heights.set(lag)

    def ready(self) -> tuple[bool, dict]:
        """healthz readiness probe: bootstrapped AND the feed-lag gauge
        within bounds (503 otherwise — load balancers drain us)."""
        lag = replication_metrics().feed_lag_heights.values().get((), 0.0)
        ok = self.bootstrapped and lag <= self.max_lag_heights
        return ok, {
            "replica": self.name,
            "bootstrapped": self.bootstrapped,
            "feed_lag_heights": lag,
            "max_lag_heights": self.max_lag_heights,
        }

    # -- serving state -----------------------------------------------------
    def _build_serving(self) -> None:
        self.store = _FrameStore(self.retain_frames)
        self.light_serve = LightServe(
            self.chain_id,
            self.store,
            self.store,
            backend=self.backend,
            cache_size=self.light_cache_size,
            subscriber_queue=self.subscriber_queue,
            sched=self.sched,
            tenant=self.name,
            payload_retain=self.payload_retain,
        )
        self.da_serve = None  # built lazily from the first DA frame
        self.light_serve.da_serve = None

    def _ensure_da(self, k: int, m: int) -> None:
        if self.da_serve is None:
            from ..da.serve import DAServe

            self.da_serve = DAServe(_DAShim(k, m, self.da_retain_heights))
            self.light_serve.da_serve = self.da_serve
            if self.env is not None:
                self.env.da_serve = self.da_serve

    # -- frame application -------------------------------------------------
    def _apply_frame(self, frame: dict, append_light: bool = True) -> bool:
        h = int(frame["h"])
        with self._apply_lock:
            if append_light and h <= self.applied_height:
                return False  # duplicate (reconnect overlap)
            t0 = time.perf_counter()
            with trace.span("replication.replica_apply", height=h) as sp:
                header = Header.decode(bytes.fromhex(frame["hdr"]))
                vals = (_decode_vals(bytes.fromhex(frame["vals"]))
                        if frame.get("vals") else None)
                last = (decode_commit_any(bytes.fromhex(frame["last"]))
                        if frame.get("last") else None)
                seen = (decode_commit_any(bytes.fromhex(frame["seen"]))
                        if frame.get("seen") else None)
                self.store.put(h, header, last, seen, vals)
                kind = (frame.get("cert") or {}).get("kind", "none")
                self.cert_kinds[kind] = self.cert_kinds.get(kind, 0) + 1
                da = frame.get("da")
                if da is not None:
                    self._ensure_da(int(da["k"]), int(da["m"]))
                    entry = self.da_serve.apply_payload(
                        h, bytes.fromhex(da["payload"]))
                    want = da.get("root")
                    if want and entry.da_root.hex() != want:
                        raise RuntimeError(
                            f"DA root mismatch at {h}: rebuilt "
                            f"{entry.da_root.hex()} != advertised {want}")
                if append_light:
                    if self.applied_height and h != self.applied_height + 1:
                        self.gaps += 1
                    self.light_serve.on_commit(_ReplicaBlock(header))
                    self.applied_height = h
                    self.applied_frames += 1
                    if h > self.core_tip:
                        self.core_tip = h
                sp.add(da=da is not None, applied=append_light)
            m = replication_metrics()
            m.replica_applied_total.inc()
            m.replica_apply_seconds.observe(time.perf_counter() - t0)
            self._set_lag()
        return True

    # -- snapshot bootstrap ------------------------------------------------
    def _bootstrap(self) -> None:
        meta = self.client.replication_snapshot()
        snap = Snapshot(
            height=int(meta["height"]),
            format=int(meta["format"]),
            chunks=int(meta["chunks"]),
            hash=bytes.fromhex(meta["hash"]),
            metadata=base64.b64decode(meta["metadata"]),
        )
        self.snapshots.add(snap, peer=self.core_url)
        best = self.snapshots.best()
        if best is None:
            raise RuntimeError("no acceptable replication snapshot")
        parts = []
        for i in range(best.chunks):
            r = self.client.replication_snapshot_chunk(
                chunk=str(i), height=str(best.height))
            parts.append(base64.b64decode(r["data"]))
        blob = b"".join(parts)
        if blob_hash(blob) != best.hash:
            self.snapshots.reject(best)
            raise RuntimeError("replication snapshot hash mismatch")
        doc = json.loads(blob)
        if self.chain_id and doc["chain_id"] != self.chain_id:
            raise RuntimeError(
                f"snapshot chain {doc['chain_id']!r} != {self.chain_id!r}")
        self.chain_id = doc["chain_id"]
        self.light_serve.chain_id = self.chain_id
        base = int(doc["base_height"])
        frames = [json.loads(line) for line in doc["frames"]]
        # seed the accumulator only up to the first retained frame, then
        # run the frames through the full apply path: the MMR grows
        # height-by-height exactly as the core's did, so the rendered
        # payload ring (the `since` replay source) and every frame-window
        # proof are byte-identical to what the core served at the time
        first_frame = frames[0]["h"] if frames else int(doc["height"]) + 1
        leaves = [bytes.fromhex(x) for x in doc["leaves"]]
        self.light_serve.bootstrap(base, leaves[:first_frame - base])
        for frame in frames:
            self._apply_frame(frame)
        with self._apply_lock:
            self.applied_height = int(doc["height"])
            self.snapshot_height = self.applied_height
            if self.applied_height > self.core_tip:
                self.core_tip = self.applied_height
            self._set_lag()

    def _rebootstrap(self) -> None:
        """Cursor fell out of the core's retention window: rebuild the
        serving state from a fresh snapshot (the old MMR cannot be
        extended across a gap)."""
        self.bootstrapped = False
        self._set_lag()
        self._build_serving()
        self._bootstrap()
        if self.env is not None:
            self.env.light_serve = self.light_serve
            self.env.da_serve = self.da_serve
        self.bootstrapped = True

    # -- feed tail ---------------------------------------------------------
    def _tail_once(self) -> None:
        url = (f"{self.core_url}/replication_feed"
               f"?cursor={self.applied_height}"
               f"&timeout_s={self.feed_timeout_s}")
        with urllib.request.urlopen(
                url, timeout=self.feed_timeout_s + 10) as resp:
            self._resp = resp
            self.feed_connects += 1
            try:
                for raw in resp:
                    if self._stop.is_set():
                        return
                    line = raw.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if "h" not in obj:  # control record: {"tip", "min"}
                        if int(obj.get("tip", 0)) > self.core_tip:
                            self.core_tip = int(obj["tip"])
                        self._set_lag()
                        continue
                    self._apply_frame(obj)
            finally:
                self._resp = None

    def _tail_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tail_once()
            except urllib.error.HTTPError as e:
                if self._stop.is_set():
                    return
                if e.code == 409:
                    try:
                        self._rebootstrap()
                    except Exception:  # noqa: BLE001 — retry after backoff
                        self._stop.wait(0.5)
                else:
                    self._stop.wait(0.2)
            except Exception:  # noqa: BLE001 — core down: reconnect loop
                if self._stop.is_set():
                    return
                self._stop.wait(0.2)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        st = self.client.replication_status()
        if st.get("role") not in (None, "core"):
            raise RuntimeError(f"{self.core_url} is not a core feed")
        self.chain_id = st.get("chain_id", "")
        self.core_tip = int(st.get("tip", 0))
        if self.sched is None:
            from ..crypto.sched import acquire_shared

            self.sched = acquire_shared(self.backend)
        self._build_serving()

        if self.forward_admission:
            target = _ForwardTarget(self.client, self.name)
            self.forward_target = target
            self.pipeline = AdmissionPipeline(
                target,
                window=self.admission_window,
                max_delay_s=self.admission_max_delay_s,
                verify_sigs=True,
                backend=self.backend,
                sched=self.sched,
                tenant=self.name,
            )
            self.pipeline.start()
            mempool = _ReplicaMempool(self.pipeline)
        else:
            self.forward_target = None
            mempool = None

        self.env = Env(
            mempool=mempool,
            light_serve=self.light_serve,
            da_serve=self.da_serve,
            replication_replica=self,
        )
        self.rpc_server = RPCServer(
            self.env, self.rpc_host, self.rpc_port, routes=REPLICA_ROUTES)
        self.rpc_server.start()
        if self.metrics_port is not None:
            self.metrics_server = MetricsServer(
                host=self.metrics_host, port=self.metrics_port,
                health_window_s=self.healthz_window_s,
                height_fn=lambda: self.applied_height,
                ready_fn=self.ready,
            )
            self.metrics_server.start()

        self._set_lag()
        if self.core_tip > 0:
            self._bootstrap()
        self.bootstrapped = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._tail_loop, daemon=True,
            name=f"replication-tail-{self.name}")
        self._thread.start()

    def stop_tail(self) -> None:
        """Stop consuming the feed but keep serving (failover tests kill
        the ingest half without tearing the surfaces down)."""
        self._stop.set()
        resp = self._resp
        if resp is not None:
            try:
                resp.close()
            except Exception:  # noqa: BLE001
                pass
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    def resume_tail(self) -> None:
        """Reconnect-with-cursor resume after stop_tail()."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._tail_loop, daemon=True,
            name=f"replication-tail-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self.stop_tail()
        if self.pipeline is not None:
            self.pipeline.close()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.light_serve is not None:
            self.light_serve.stop()
        if self.da_serve is not None:
            self.da_serve.stop()
        if self._own_sched and self.sched is not None:
            from ..crypto.sched import release_shared

            release_shared(self.sched)
            self.sched = None

    # -- introspection -----------------------------------------------------
    @property
    def rpc_addr(self) -> tuple[str, int] | None:
        return self.rpc_server.addr if self.rpc_server is not None else None

    @property
    def metrics_addr(self) -> tuple[str, int] | None:
        return (self.metrics_server.addr
                if self.metrics_server is not None else None)

    def status(self) -> dict:
        lag = max(0, self.core_tip - self.applied_height)
        fwd = self.forward_target
        return {
            "name": self.name,
            "chain_id": self.chain_id,
            "core_url": self.core_url,
            "bootstrapped": self.bootstrapped,
            "snapshot_height": self.snapshot_height,
            "applied_height": self.applied_height,
            "core_tip": self.core_tip,
            "lag_heights": lag,
            "applied_frames": self.applied_frames,
            "gaps": self.gaps,
            "feed_connects": self.feed_connects,
            "certs": dict(self.cert_kinds),
            "forwarded_ok": fwd.forwarded_ok if fwd else 0,
            "forwarded_rejected": fwd.forwarded_rejected if fwd else 0,
            "forward_errors": fwd.forward_errors if fwd else 0,
            "frames_retained": len(self.store) if self.store else 0,
            "mmr_size": (self.light_serve.mmr.leaf_count
                         if self.light_serve else 0),
        }
