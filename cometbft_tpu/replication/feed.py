"""Core-side replication feed (ROADMAP item #3: scale-out serving).

One ordered, resumable stream per core node carrying everything a
stateless serving replica needs to reproduce the serving surfaces
byte-for-byte: committed headers, the height's validator set, the
canonical + seen commits (so the replica's block/seen commit resolution
matches the core's exactly), a verified-commit certificate (BLS
``AggregateCommit`` when the commit aggregates, else the cached
``VerifiedCommitCache`` verdict), and the DA payload in 1x systematic
form (the RS extension and shard commitment are deterministic, so the
replica rebuilds the full 2x shard set + opening proofs locally).

The feed rides the same ``BlockExecutor.event_handlers`` hook as the
light and DA serving surfaces (wired after both, so their per-height
state is already rendered when a frame is built). Each frame is one
JSONL line keyed by a monotone height cursor; a subscriber passes the
last height it applied and receives a gap-free replay of retained
frames followed by the live tail. A cursor older than the retention
window raises ``CursorTooOld`` — the replica must re-bootstrap from the
snapshot surface (``snapshot()`` below, served over the statesync
chunk protocol in rpc/routes.py).
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

from ..da.commit import block_payload
from ..light.serve import StreamSubscriber
from ..light.store import _encode_vals
from ..statesync.snapshots import (
    FORMAT_REPLICATION_V1,
    Snapshot,
    blob_hash,
    chunk_blob,
)
from ..utils import trace
from ..utils.metrics import replication_metrics


class CursorTooOld(Exception):
    """The subscriber's cursor predates the retention window: frames it
    needs are gone, so resume is impossible — re-bootstrap instead."""

    def __init__(self, cursor: int, min_height: int):
        super().__init__(
            f"cursor {cursor} predates retained frames (oldest "
            f"{min_height}); re-bootstrap from snapshot"
        )
        self.cursor = cursor
        self.min_height = min_height


class ReplicationFeed:
    """Commit-hooked frame builder + retained-window fan-out."""

    def __init__(
        self,
        chain_id: str,
        block_store,
        state_store,
        light_serve=None,
        da_serve=None,
        retain_frames: int = 1024,
        snapshot_chunk_bytes: int = 262144,
        subscriber_queue: int = 4096,
    ):
        self.chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.light_serve = light_serve
        self.da_serve = da_serve
        self.retain_frames = max(1, int(retain_frames))
        self.snapshot_chunk_bytes = max(1, int(snapshot_chunk_bytes))
        self.subscriber_queue = subscriber_queue
        self._frames: OrderedDict[int, str] = OrderedDict()
        self._subs: dict[int, StreamSubscriber] = {}
        self._next_sub_id = 0
        self._lock = threading.Lock()
        self.tip = 0
        self.frames_emitted = 0
        # snapshot blob cache: rebuilt only when the tip moved
        self._snap_meta: Snapshot | None = None
        self._snap_chunks: list[bytes] = []

    # -- frame construction ----------------------------------------------
    def _cert_for(self, height: int, commit) -> dict:
        """Verified-commit certificate: a BLS aggregate when the commit
        folds into one (all-BLS uniform-timestamp), else the core
        cache's verdict for the height, else pending (the replica
        verifies lazily through its own cache, same resolution rules)."""
        if commit is not None:
            cert = getattr(commit, "cert", None)
            if cert is not None:
                # cert-native store (ISSUE 17): the seen commit IS the
                # certificate — no fold needed, reuse its aggregate
                return {"kind": "cert_native", "data": cert.encode().hex()}
            try:
                from ..types.agg_commit import AggregateCommit

                ac = AggregateCommit.from_commit(commit)
                return {"kind": "bls_agg", "data": ac.encode().hex()}
            except Exception:  # noqa: BLE001 — not an aggregatable commit
                pass
        if self.light_serve is not None:
            lb = self.light_serve.cache.peek(height)
            if lb is not None:
                return {"kind": "verdict", "verified": True}
        return {"kind": "pending"}

    def _build_frame(self, block) -> str:
        header = block.header
        h = header.height
        vals = self.state_store.load_validators(h)
        seen = self.block_store.load_seen_commit(h)
        frame = {
            "h": h,
            "hdr": header.encode().hex(),
            "vals": _encode_vals(vals).hex() if vals is not None else "",
            # block H's embedded LastCommit IS the canonical commit for
            # H-1: carrying both lets the replica's store facade mirror
            # the core's block-commit/seen-commit resolution exactly
            "last": block.last_commit.encode().hex(),
            "seen": seen.encode().hex() if seen is not None else "",
            "cert": self._cert_for(h, seen),
        }
        if self.da_serve is not None:
            payload = block_payload(block.data)
            da = {
                "payload": payload.hex(),
                "k": self.da_serve.k,
                "m": self.da_serve.m,
            }
            entry = self.da_serve.commitment(h)
            if entry is not None:
                da["root"] = entry.root().hex()
            frame["da"] = da
        return json.dumps(frame)

    # -- commit hook -------------------------------------------------------
    def on_commit(self, block, resp=None) -> None:
        h = block.header.height
        with self._lock:
            if h <= self.tip:
                return  # blocksync replay / restart overlap
        line = self._build_frame(block)
        with self._lock:
            if h <= self.tip:
                return
            self._frames[h] = line
            self.tip = h
            self.frames_emitted += 1
            while len(self._frames) > self.retain_frames:
                self._frames.popitem(last=False)
            subs = list(self._subs.values())
        m = replication_metrics()
        with trace.span("replication.feed_send", height=h,
                        subs=len(subs), bytes=len(line)):
            for sub in subs:
                sub.push(line)
        m.feed_frames_total.inc()
        m.feed_bytes_total.inc(len(line) * max(1, len(subs)))

    # -- subscriptions -----------------------------------------------------
    @property
    def min_height(self) -> int:
        """Oldest retained frame height (0 when nothing is retained)."""
        with self._lock:
            return next(iter(self._frames), 0)

    def subscribe(self, cursor: int = 0
                  ) -> tuple[int, StreamSubscriber, list[str], int]:
        """(sub_id, live subscriber, retained replay lines > cursor,
        tip at subscribe time). Atomic with frame emission, so the
        replay + live tail is gap-free and duplicate-free."""
        with self._lock:
            if self._frames:
                mn = next(iter(self._frames))
                if cursor + 1 < mn:
                    raise CursorTooOld(cursor, mn)
            elif cursor < self.tip:
                raise CursorTooOld(cursor, self.tip + 1)
            replay = [ln for h, ln in self._frames.items() if h > cursor]
            sub_id = self._next_sub_id
            self._next_sub_id += 1
            sub = self._subs[sub_id] = StreamSubscriber(self.subscriber_queue)
            replication_metrics().feed_subscribers.set(len(self._subs))
            return sub_id, sub, replay, self.tip

    def unsubscribe(self, sub_id: int) -> None:
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            replication_metrics().feed_subscribers.set(len(self._subs))
        if sub is not None:
            sub.close()

    # -- snapshot bootstrap surface ----------------------------------------
    def snapshot(self) -> tuple[Snapshot, list[bytes]]:
        """(metadata, chunks) of the bootstrap blob at the current tip.

        The blob carries the full MMR leaf sequence (header hashes from
        the accumulator base — replaying them rebuilds the core's MMR
        bit-exactly), the retained frame window (headers/commits/vals/
        DA payloads so the replica can serve proofs and bisection for
        recent heights), and the resume cursor. Rebuilt lazily, cached
        per tip."""
        if self.light_serve is None:
            raise RuntimeError("replication snapshot requires light serving")
        with self._lock:
            tip = self.tip
            if self._snap_meta is not None and self._snap_meta.height == tip:
                return self._snap_meta, list(self._snap_chunks)
            frames = list(self._frames.values())
        if tip == 0:
            raise RuntimeError("no committed heights to snapshot")
        size, _root = self.light_serve.mmr_snapshot()
        base = self.light_serve.base_height
        leaves = []
        for h in range(base, base + size):
            blk = self.block_store.load_block(h)
            if blk is None:
                raise RuntimeError(
                    f"snapshot leaf {h} missing from block store")
            leaves.append(blk.header.hash().hex())
        blob = json.dumps({
            "chain_id": self.chain_id,
            "base_height": base,
            "height": base + size - 1,
            "leaves": leaves,
            "frames": frames,
            "cursor": base + size - 1,
        }).encode()
        chunks = chunk_blob(blob, self.snapshot_chunk_bytes)
        meta = Snapshot(
            height=base + size - 1,
            format=FORMAT_REPLICATION_V1,
            chunks=len(chunks),
            hash=blob_hash(blob),
            metadata=json.dumps({"base_height": base}).encode(),
        )
        with self._lock:
            self._snap_meta, self._snap_chunks = meta, chunks
        return meta, list(chunks)

    # -- introspection / lifecycle -----------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "chain_id": self.chain_id,
                "tip": self.tip,
                "min_retained": next(iter(self._frames), 0),
                "frames_retained": len(self._frames),
                "frames_emitted": self.frames_emitted,
                "subscribers": len(self._subs),
            }

    def stop(self) -> None:
        with self._lock:
            subs = list(self._subs.values())
            self._subs.clear()
            replication_metrics().feed_subscribers.set(0)
        for s in subs:
            s.close()
