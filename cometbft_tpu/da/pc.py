"""2D polynomial-commitment DA: per-column KZG + row/column erasure.

The payload is chopped into 31-byte field chunks and laid out
column-major into a k_r x k_c matrix of Fr scalars. Each data column j
is the unique polynomial p_j of degree < k_r through its cells
(rows are evaluation points 0..k_r-1); rows k_r..n_r-1 are the ROW
extension (evaluating p_j past the data grid = a rate-1/2
Reed-Solomon code per column). Parity COLUMNS k_c..n_c-1 are Lagrange
combinations of the data columns evaluated at x = j', which commutes
with everything linear: cells, coefficients, and — the part the 1D
Merkle track cannot copy — the KZG commitments themselves.

That last fact is the fraud-proof-free lying-encoder defence
(`kzg.verify_parity_commitments`): a sampler checks ONCE per height,
from the commitment list alone, that every parity commitment is the
required linear combination of the data commitments. A Merkle root
has no such structure — hashes of garbage parity verify every opening
(pinned as the 1D-blindness test in tests/test_kzg_native.py).

Sampling cost is where the multiproof earns its keep: one (row, s
columns) sample is answered by s 32-byte evaluations plus ONE 48-byte
opening (`kzg.open_multi`), so marginal bytes/sample approach 32 + eps
instead of the 1D track's chunk + growing Merkle path (256 B at the
default geometry). The per-height commitment list (n_c x 48 B) is the
fixed overhead amortized across a client's samples.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..crypto import kzg
from ..utils import trace

# domain separation continues the DA ladder: 0x02 is the 1D root,
# 0x03 the PC root, 0x04 the combined header root (da/commit.py)
PC_ROOT_PREFIX = b"\x03"

_PC_ROOT_FMT = ">IIIIQ"  # n_r, k_r, n_c, k_c, payload_len

CHUNK_BYTES = 31  # 248-bit chunks embed injectively into Fr
EVAL_SIZE = 32  # one claimed cell value on the wire
SAMPLE_HEADER_BYTES = 12  # row + column-count + height framing


def _sha256(b) -> bytes:
    return hashlib.sha256(b).digest()


@dataclass(frozen=True)
class PCCommitment:
    """Geometry + the per-column commitment list a sampler verifies
    openings and parity-linearity against."""

    n_r: int  # extended rows (k_r data + k_r row parity)
    k_r: int  # data rows = column-polynomial degree bound
    n_c: int  # extended columns (k_c data + m_c parity)
    k_c: int  # data columns
    payload_len: int  # unpadded payload bytes
    commitments: tuple  # n_c compressed G1 points, 48 B each

    @property
    def m_c(self) -> int:
        return self.n_c - self.k_c

    def cols_root(self) -> bytes:
        return _sha256(b"".join(self.commitments))

    def root(self) -> bytes:
        return _sha256(
            PC_ROOT_PREFIX
            + struct.pack(_PC_ROOT_FMT, self.n_r, self.k_r,
                          self.n_c, self.k_c, self.payload_len)
            + self.cols_root()
        )

    def num_bytes(self) -> int:
        """Per-height wire overhead a sampling client downloads once:
        the commitment list plus the packed geometry."""
        return len(self.commitments) * kzg.POINT_SIZE + 24


def multiproof_num_bytes(n_cols: int) -> int:
    """Wire cost of one (row, n_cols columns) sample response: the
    claimed evaluations plus ONE constant-size opening. Counterpart of
    commit.proof_num_bytes on the 1D track."""
    return n_cols * EVAL_SIZE + kzg.PROOF_SIZE + SAMPLE_HEADER_BYTES


def payload_to_scalars(payload: bytes) -> list[int]:
    """31-byte big-endian chunks — each strictly < r, so the embedding
    is injective and needs no reduction. The tail chunk is zero-padded
    on the RIGHT so decode's fixed-width re-serialization lines up."""
    return [
        int.from_bytes(
            payload[off:off + CHUNK_BYTES].ljust(CHUNK_BYTES, b"\x00"),
            "big")
        for off in range(0, len(payload), CHUNK_BYTES)
    ]


def scalars_to_payload(scalars, payload_len: int) -> bytes:
    out = b"".join(s.to_bytes(CHUNK_BYTES, "big") for s in scalars)
    return out[:payload_len]


def grid_rows(payload_len: int, k_c: int) -> int:
    """k_r for a payload: column-major fill of 31-byte chunks across
    k_c data columns, at least one row."""
    chunks = max(1, -(-payload_len // CHUNK_BYTES))
    return max(1, -(-chunks // k_c))


class PCEncoding:
    """One height's full 2D encoding: cell matrix, column polynomials
    and commitments. The serving node retains this; samplers only ever
    see the PCCommitment plus (ys, proof) responses."""

    __slots__ = ("com", "col_coeffs", "cells")

    def __init__(self, com: PCCommitment, col_coeffs, cells):
        self.com = com
        self.col_coeffs = col_coeffs  # n_c lists, each deg < k_r
        self.cells = cells  # n_c columns x n_r rows of Fr ints

    def open_row_cols(self, row: int, cols, *, force_oracle=False):
        """(ys, proof48) for one multiproof sample: the claimed cells
        plus a single aggregated opening at z = row."""
        polys = [self.col_coeffs[j] for j in cols]
        coms = [self.com.commitments[j] for j in cols]
        return kzg.open_multi(polys, coms, row,
                              force_oracle=force_oracle)


def pc_encode(payload: bytes, k_c: int, m_c: int,
              srs: kzg.SRS | None = None) -> PCEncoding:
    """Encode + commit one payload on the 2D track.

    Data columns are interpolated from their column-major chunk cells;
    parity columns are Lagrange combinations of the data columns (same
    weights for coefficients and cells — linearity). Commitments are
    one MSM per column against the SRS powers."""
    n_c = k_c + m_c
    k_r = grid_rows(len(payload), k_c)
    n_r = 2 * k_r
    srs = (srs or kzg.setup(k_r)).grown(k_r)
    scalars = payload_to_scalars(payload)
    scalars += [0] * (k_r * k_c - len(scalars))
    xs_rows = list(range(k_r))
    with trace.span("da.pc_commit", rows=n_r, cols=n_c,
                    bytes=len(payload)):
        col_coeffs = []
        for j in range(k_c):
            ys = scalars[j * k_r:(j + 1) * k_r]
            col_coeffs.append(kzg.interpolate(xs_rows, ys))
        xs_cols = list(range(k_c))
        for jp in range(k_c, n_c):
            lam = kzg.lagrange_coeffs_at(xs_cols, jp)
            coeffs = [0] * k_r
            for j in range(k_c):
                cj = col_coeffs[j]
                for d in range(len(cj)):
                    coeffs[d] = (coeffs[d] + lam[j] * cj[d]) % kzg.R
            col_coeffs.append(coeffs)
        commitments = tuple(
            kzg.commit(c, srs) for c in col_coeffs
        )
        cells = [
            [kzg.poly_eval(c, i) for i in range(n_r)]
            for c in col_coeffs
        ]
    com = PCCommitment(n_r=n_r, k_r=k_r, n_c=n_c, k_c=k_c,
                       payload_len=len(payload),
                       commitments=commitments)
    return PCEncoding(com, col_coeffs, cells)


def decode_payload(enc: PCEncoding) -> bytes:
    """Payload back out of the data quadrant (tests/roundtrip)."""
    com = enc.com
    scalars = []
    for j in range(com.k_c):
        scalars.extend(enc.cells[j][:com.k_r])
    return scalars_to_payload(scalars, com.payload_len)


def verify_sample(com: PCCommitment, pc_root: bytes, row: int, cols,
                  ys, proof: bytes) -> bool:
    """Client-side check of one multiproof response: geometry binds to
    the advertised root, the row/columns are in range, and the single
    opening verifies against the sampled columns' commitments."""
    if com.root() != pc_root:
        return False
    if not (0 <= row < com.n_r) or not cols or len(cols) != len(ys):
        return False
    if any(not (0 <= j < com.n_c) for j in cols):
        return False
    coms = [com.commitments[j] for j in cols]
    return kzg.verify_multi(coms, row, ys, proof)


def verify_commitments(com: PCCommitment) -> bool:
    """The once-per-height lying-encoder check (see module docstring):
    parity commitments must be the Lagrange combinations of the data
    commitments — one batched MSM, no samples needed."""
    return kzg.verify_parity_commitments(list(com.commitments), com.k_c)


def make_inconsistent(enc: PCEncoding, seed: int = 0) -> PCEncoding:
    """The adversarial world: a proposer that commits HONESTLY to
    garbage parity columns. Every opening against the published
    commitments verifies — only the parity-linearity check (2D) or
    downstream reconstruction (too late) can tell. The 1D analogue
    (garbage parity shards under an honest Merkle root) is provably
    undetectable by opening samples; the paired tests pin both."""
    com = enc.com
    col_coeffs = [list(c) for c in enc.col_coeffs]
    for jp in range(com.k_c, com.n_c):
        h = hashlib.sha256(struct.pack(">QI", seed, jp)).digest()
        col_coeffs[jp] = [
            int.from_bytes(
                hashlib.sha256(h + struct.pack(">I", d)).digest(), "big"
            ) % kzg.R
            for d in range(com.k_r)
        ]
    commitments = tuple(
        enc.com.commitments[:com.k_c]
        + tuple(kzg.commit(col_coeffs[jp], kzg.setup(com.k_r))
                for jp in range(com.k_c, com.n_c))
    )
    cells = [
        [kzg.poly_eval(c, i) for i in range(com.n_r)]
        for c in col_coeffs
    ]
    bad_com = PCCommitment(
        n_r=com.n_r, k_r=com.k_r, n_c=com.n_c, k_c=com.k_c,
        payload_len=com.payload_len, commitments=commitments,
    )
    return PCEncoding(bad_com, col_coeffs, cells)
