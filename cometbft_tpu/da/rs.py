"""Systematic Reed-Solomon erasure code over GF(2^16) — numpy oracle.

The code is RS in *evaluation form*: the k data shards are the values
of the unique degree-<k polynomial at the points x = 0..k-1, and the m
parity shards are its evaluations at x = k..k+m-1. That makes the code
systematic by construction, and reconstruction from ANY k of the
n = k+m shards is Lagrange interpolation over the surviving points.
Shards are arrays of little-endian uint16 words; all shard arithmetic
is word-wise, so every output word depends only on the same word
column of the inputs — the property the native engine exploits to
parallelize over word ranges with chunk-count-invariant output.

Field: GF(2^16) under the primitive polynomial
x^16 + x^12 + x^3 + x + 1 (0x1100B); 2 generates the multiplicative
group (checked in tests), so the log/antilog tables come from a plain
shift-xor loop. `csrc/rs_gf16.inc` builds the identical tables — the
differential tests in tests/test_rs_native.py hold the two
implementations bit-equal on encode AND reconstruct.

The module-level `encode_shards` / `reconstruct_shards` prefer the
native engine and fall back to this oracle when the shared library is
unavailable (same graceful-degradation contract as the other csrc
engines).
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x1100B  # primitive; x is a generator (order(2) == 65535)
GF_ORDER = 1 << 16
GF_GROUP = GF_ORDER - 1  # multiplicative group order

# practical cap on total shards: keeps the O(k^2) Lagrange denominator
# pass bounded and matches RS_MAX_SHARDS in csrc/rs_gf16.inc
MAX_SHARDS = 4096


class RSError(Exception):
    pass


_EXP = None  # length 2*GF_GROUP so (log a + log b) indexes without a mod
_LOG = None


def _tables() -> tuple[np.ndarray, np.ndarray]:
    global _EXP, _LOG
    if _EXP is None:
        exp = np.zeros(2 * GF_GROUP, dtype=np.uint16)
        log = np.zeros(GF_ORDER, dtype=np.uint32)
        x = 1
        for i in range(GF_GROUP):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & GF_ORDER:
                x ^= GF_POLY
        exp[GF_GROUP:] = exp[:GF_GROUP]
        _EXP, _LOG = exp, log
    return _EXP, _LOG


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    exp, log = _tables()
    return int(exp[int(log[a]) + int(log[b])])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^16) division by zero")
    if a == 0:
        return 0
    exp, log = _tables()
    return int(exp[int(log[a]) + GF_GROUP - int(log[b])])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def _mul_vec(c: int, vec: np.ndarray) -> np.ndarray:
    """Scalar * vector over GF(2^16), vectorized through the tables."""
    if c == 0:
        return np.zeros_like(vec)
    exp, log = _tables()
    out = exp[int(log[c]) + log[vec]]
    # log[0] is a dummy slot — zero inputs must map to zero outputs
    np.copyto(out, 0, where=(vec == 0))
    return out


def _lagrange_rows(xs: list[int], ys: list[int]) -> list[list[int]]:
    """Coefficient rows for evaluating the degree-<k interpolant of
    points xs at each target y: out[r][j] is the weight of shard xs[j]
    in shard ys[r]. In GF(2^n), (a - b) == (a XOR b), so the classic
    Lagrange basis w_j(y) = P(y) / ((y^xs_j) * d_j) with
    P(y) = prod_i (y ^ xs_i) and d_j = prod_{i!=j} (xs_j ^ xs_i).
    O(k^2 + len(ys)*k) total, not O(len(ys)*k^2)."""
    k = len(xs)
    dens = []
    for j in range(k):
        d = 1
        xj = xs[j]
        for i in range(k):
            if i != j:
                d = gf_mul(d, xj ^ xs[i])
        dens.append(d)
    rows = []
    for y in ys:
        if y in xs:
            rows.append([1 if xs[j] == y else 0 for j in range(k)])
            continue
        p = 1
        for xi in xs:
            p = gf_mul(p, y ^ xi)
        rows.append(
            [gf_div(p, gf_mul(y ^ xs[j], dens[j])) for j in range(k)]
        )
    return rows


def _check_params(k: int, m: int) -> None:
    if k < 1 or m < 0 or k + m > MAX_SHARDS:
        raise RSError(f"bad RS parameters k={k} m={m} (max {MAX_SHARDS})")


def _as_words(shard: bytes) -> np.ndarray:
    if len(shard) % 2:
        raise RSError("shard length must be a whole number of uint16 words")
    return np.frombuffer(shard, dtype="<u2")


def encode_oracle(data_shards: list[bytes], m: int) -> list[bytes]:
    """Pure-numpy parity computation: m new shards extending the k
    given data shards. All shards must be equal even length."""
    k = len(data_shards)
    _check_params(k, m)
    if m == 0:
        return []
    arrs = [_as_words(s) for s in data_shards]
    words = len(arrs[0])
    if any(len(a) != words for a in arrs):
        raise RSError("data shards must be equal length")
    rows = _lagrange_rows(list(range(k)), list(range(k, k + m)))
    out = []
    for r in range(m):
        acc = np.zeros(words, dtype=np.uint16)
        for j in range(k):
            c = rows[r][j]
            if c:
                acc ^= _mul_vec(c, arrs[j])
        out.append(acc.astype("<u2").tobytes())
    return out


def reconstruct_oracle(
    shards: list[bytes | None], k: int, m: int
) -> list[bytes]:
    """Fill in every missing shard from any >= k survivors.

    `shards` is the full n = k+m list with None marking erasures. The
    interpolation set is the first k present shards in index order —
    a deterministic rule the native engine mirrors exactly.
    """
    _check_params(k, m)
    n = k + m
    if len(shards) != n:
        raise RSError(f"expected {n} shard slots, got {len(shards)}")
    present = [i for i, s in enumerate(shards) if s is not None]
    if len(present) < k:
        raise RSError(
            f"unrecoverable: {len(present)} shards present, need {k}"
        )
    xs = present[:k]
    arrs = [_as_words(shards[i]) for i in xs]
    words = len(arrs[0])
    if any(len(a) != words for a in arrs):
        raise RSError("shards must be equal length")
    missing = [i for i, s in enumerate(shards) if s is None]
    rows = _lagrange_rows(xs, missing)
    out = list(shards)
    for r, y in enumerate(missing):
        acc = np.zeros(words, dtype=np.uint16)
        for j in range(k):
            c = rows[r][j]
            if c:
                acc ^= _mul_vec(c, arrs[j])
        out[y] = acc.astype("<u2").tobytes()
    return out  # type: ignore[return-value]


# ------------------------------------------------------------------ dispatch

def encode_shards(
    data_shards: list[bytes], m: int, *, nchunks: int = 0
) -> list[bytes]:
    """Parity shards via the native engine when available, oracle
    otherwise. Output is bit-identical either way (differential-tested)."""
    k = len(data_shards)
    _check_params(k, m)
    if m == 0:
        return []
    from ..crypto import native

    if native.rs_available():
        shard_len = len(data_shards[0])
        if shard_len % 2 or any(len(s) != shard_len for s in data_shards):
            raise RSError("data shards must be equal even length")
        parity = native.rs_encode(
            b"".join(data_shards), k, m, shard_len, nchunks=nchunks
        )
        if parity is not None:
            return [
                parity[i * shard_len:(i + 1) * shard_len] for i in range(m)
            ]
    return encode_oracle(data_shards, m)


def reconstruct_shards(
    shards: list[bytes | None], k: int, m: int, *, nchunks: int = 0
) -> list[bytes]:
    """Reconstruct all n shards from any >= k survivors (native when
    available, oracle otherwise); counts into da_reconstruct_total."""
    from ..utils.metrics import da_metrics

    da_metrics().reconstruct_total.inc()
    _check_params(k, m)
    n = k + m
    if len(shards) != n:
        raise RSError(f"expected {n} shard slots, got {len(shards)}")
    from ..crypto import native

    if native.rs_available():
        lens = {len(s) for s in shards if s is not None}
        if len(lens) == 1 and not (shard_len := lens.pop()) % 2:
            present = bytes(1 if s is not None else 0 for s in shards)
            if sum(present) < k:
                raise RSError(
                    f"unrecoverable: {sum(present)} shards present, need {k}"
                )
            buf = b"".join(
                s if s is not None else b"\x00" * shard_len for s in shards
            )
            out = native.rs_reconstruct(
                buf, present, k, m, shard_len, nchunks=nchunks
            )
            if out is not None:
                return [
                    out[i * shard_len:(i + 1) * shard_len] for i in range(n)
                ]
    return reconstruct_oracle(shards, k, m)
