"""Node-side DA serving surface.

`DAServe` rides the same commit-time event-handler hook as the light
MMR accumulator (`BlockExecutor.event_handlers`): every applied block's
payload is RS-extended, committed, and retained for the last
`retain_heights` heights so samplers can fetch (chunk, opening proof)
pairs through the `da_sample` RPC route or the `/light_stream` payload
extension. It doubles as the proposal/validation encoder: the executor
asks it for `da_root_for(data)` when building a proposal and when
checking a peer's header.

An explicit withholding knob (`set_withholding`) exists for the
adversarial workload: a byzantine proposer that advertises a root but
refuses to serve some chunks. Samplers hitting a withheld index get
None — exactly the observable a DAS client turns into a
detection/alarm (tools/dasload.py drives a fleet against it).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..utils import trace
from ..utils.metrics import da_metrics
from . import pc as pcmod
from .commit import (
    DACommitment,
    block_payload,
    combined_root,
    commit_shards,
    extend_payload,
    proof_num_bytes,
)


class _HeightEntry:
    __slots__ = ("commitment", "shards", "proofs", "da_root", "pc")

    def __init__(self, commitment, shards, proofs, pc=None):
        self.commitment = commitment
        self.shards = shards
        self.proofs = proofs
        self.pc = pc  # PCEncoding when the 2D KZG track is on
        root = commitment.root()
        self.da_root = (root if pc is None
                        else combined_root(root, pc.com.root()))


class DAServe:
    def __init__(self, cfg):
        """`cfg` is the validated `config.DAConfig`."""
        self.cfg = cfg
        self.k = cfg.data_shards
        self.m = cfg.parity_shards
        self.pc_enabled = bool(getattr(cfg, "pc", False))
        self.pc_k_c = getattr(cfg, "pc_data_cols", 4)
        self.pc_m_c = getattr(cfg, "pc_parity_cols", 4)
        self.pc_max_rows = getattr(cfg, "pc_max_rows", 1024)
        self._lock = threading.Lock()
        self._heights: OrderedDict[int, _HeightEntry] = OrderedDict()
        self._withhold: dict[int, set[int]] = {}
        self._pc_withhold: dict[int, set[int]] = {}
        self._encoded = 0
        self._served = 0
        self._withheld_hits = 0
        self._pc_served = 0
        self._pc_withheld_hits = 0
        self._pc_skipped_rows = 0
        self.metrics = da_metrics()

    # --------------------------------------------------------- encoder side
    def da_root_for(self, data) -> bytes:
        """Root for a proposal's Data (also used to validate a peer's
        header against locally re-encoded chunks)."""
        payload = block_payload(data)
        shards = extend_payload(payload, self.k, self.m)
        com, _ = commit_shards(shards, self.k, len(payload))
        root = com.root()
        enc = self._pc_encode(payload)
        if enc is not None:
            return combined_root(root, enc.com.root())
        return root

    def _pc_encode(self, payload: bytes):
        """The 2D KZG encoding for one payload, or None when the track
        is off / the payload exceeds the row budget (a commitment per
        column is cheap; the SRS and opening costs scale with rows)."""
        if not self.pc_enabled:
            return None
        if pcmod.grid_rows(len(payload), self.pc_k_c) > self.pc_max_rows:
            with self._lock:
                self._pc_skipped_rows += 1
            return None
        enc = pcmod.pc_encode(payload, self.pc_k_c, self.pc_m_c)
        self.metrics.pc_commits_total.inc()
        return enc

    def on_commit(self, block, resp=None) -> None:
        """Commit-time hook (same contract as LightServe.on_commit):
        extend + commit + retain the applied block's payload."""
        self.apply_payload(block.header.height, block_payload(block.data))

    def apply_payload(self, height: int, payload: bytes) -> _HeightEntry:
        """Extend + commit + retain one height's raw payload. The RS
        extension and the shard commitment are deterministic, so a
        serving replica applying the payload off the replication feed
        rebuilds the commitment, shards and opening proofs byte-exactly
        (the feed carries the 1x systematic payload, not the 2x shard
        set). Returns the retained entry so callers can cross-check
        `entry.da_root` against an advertised root."""
        with trace.span(
            "da.encode", height=height, bytes=len(payload)
        ) as sp:
            shards = extend_payload(payload, self.k, self.m)
            com, proofs = commit_shards(shards, self.k, len(payload))
            sp.add(shards=com.n, shard_bytes=len(shards[0]))
        entry = _HeightEntry(com, shards, proofs,
                             pc=self._pc_encode(payload))
        with self._lock:
            self._heights[height] = entry
            self._encoded += 1
            while len(self._heights) > self.cfg.retain_heights:
                h, _ = self._heights.popitem(last=False)
                self._withhold.pop(h, None)
                self._pc_withhold.pop(h, None)
        return entry

    # --------------------------------------------------------- serving side
    def set_withholding(self, height: int, indices) -> None:
        """Adversarial harness: refuse to serve `indices` at `height`."""
        with self._lock:
            self._withhold[height] = set(indices)

    def set_pc_withholding(self, height: int, cols) -> None:
        """Adversarial harness, 2D track: refuse any multiproof sample
        touching one of `cols` at `height`."""
        with self._lock:
            self._pc_withhold[height] = set(cols)

    def corrupt_pc_parity(self, height: int, seed: int = 0) -> bool:
        """Adversarial harness: swap in the lying-encoder world —
        honest commitments over garbage parity columns, every opening
        still verifying (da/pc.py make_inconsistent). The entry's
        da_root IS recomputed: this models a proposer that built and
        advertised the block with garbage parity from the start, so
        every opening a sampler draws verifies against the advertised
        commitments and ONLY the parity-linearity check
        (`pc.verify_commitments`) catches it — the world the 2D design
        exists for."""
        with self._lock:
            entry = self._heights.get(height)
        if entry is None or entry.pc is None:
            return False
        entry.pc = pcmod.make_inconsistent(entry.pc, seed)
        entry.da_root = combined_root(
            entry.commitment.root(), entry.pc.com.root())
        return True

    def stream_fields(self, height: int) -> dict:
        """/light_stream payload extension for one height ({} when the
        height is not retained — e.g. DA enabled mid-run)."""
        with self._lock:
            entry = self._heights.get(height)
        if entry is None:
            return {}
        com = entry.commitment
        out = {
            "da_root": entry.da_root.hex(),
            "da_shards": com.n,
            "da_data_shards": com.k,
            "da_payload_len": com.payload_len,
        }
        if entry.pc is not None:
            pcc = entry.pc.com
            out["da_pc_root"] = pcc.root().hex()
            out["da_pc_rows"] = pcc.n_r
            out["da_pc_cols"] = pcc.n_c
            out["da_pc_data_cols"] = pcc.k_c
        return out

    def sample(self, height: int, index: int):
        """(chunk, Proof, DACommitment) for one sampled index, or None
        when the height is unknown / the index is withheld."""
        with self._lock:
            entry = self._heights.get(height)
            withheld = self._withhold.get(height, ())
        if entry is None or not (0 <= index < entry.commitment.n):
            return None
        if index in withheld:
            with self._lock:
                self._withheld_hits += 1
            return None
        chunk = entry.shards[index]
        proof = entry.proofs[index]
        nbytes = proof_num_bytes(chunk, proof)
        with trace.span(
            "da.serve_sample", height=height, index=index, bytes=nbytes
        ):
            self.metrics.samples_served_total.inc()
            self.metrics.proof_bytes.observe(nbytes)
            with self._lock:
                self._served += 1
        return chunk, proof, entry.commitment

    def pc_sample(self, height: int, row: int, cols):
        """(ys, proof48) answering one multiproof sample — `cols` are
        the client's sampled column indices, all opened at `row` by a
        single aggregated proof. None when the height is unknown, the
        track is off for it, the geometry is out of range, or any
        requested column is withheld."""
        with self._lock:
            entry = self._heights.get(height)
            withheld = self._pc_withhold.get(height, ())
        if entry is None or entry.pc is None:
            return None
        com = entry.pc.com
        cols = list(cols)
        if not cols or not (0 <= row < com.n_r):
            return None
        if any(not (0 <= j < com.n_c) for j in cols):
            return None
        if any(j in withheld for j in cols):
            with self._lock:
                self._pc_withheld_hits += 1
            return None
        nbytes = pcmod.multiproof_num_bytes(len(cols))
        with trace.span(
            "da.serve_sample", height=height, index=row,
            cols=len(cols), bytes=nbytes, track="pc",
        ):
            ys, proof = entry.pc.open_row_cols(row, cols)
            self.metrics.pc_samples_served_total.inc()
            self.metrics.pc_proof_bytes.observe(nbytes)
            with self._lock:
                self._pc_served += 1
        return ys, proof

    def pc_commitments(self, height: int):
        """The height's PCCommitment (geometry + per-column KZG
        commitment list), or None off-track."""
        with self._lock:
            entry = self._heights.get(height)
        return entry.pc.com if entry is not None and entry.pc else None

    def commitment(self, height: int) -> DACommitment | None:
        with self._lock:
            entry = self._heights.get(height)
        return entry.commitment if entry is not None else None

    def shards(self, height: int) -> list[bytes] | None:
        with self._lock:
            entry = self._heights.get(height)
        return list(entry.shards) if entry is not None else None

    def stats(self) -> dict:
        with self._lock:
            heights = list(self._heights)
            return {
                "enabled": True,
                "data_shards": self.k,
                "parity_shards": self.m,
                "retained_heights": len(heights),
                "min_height": heights[0] if heights else 0,
                "max_height": heights[-1] if heights else 0,
                "blocks_encoded": self._encoded,
                "samples_served": self._served,
                "withheld_hits": self._withheld_hits,
                "pc_enabled": self.pc_enabled,
                "pc_samples_served": self._pc_served,
                "pc_withheld_hits": self._pc_withheld_hits,
                "pc_skipped_rows": self._pc_skipped_rows,
            }

    def stop(self) -> None:
        with self._lock:
            self._heights.clear()
            self._withhold.clear()
            self._pc_withhold.clear()
