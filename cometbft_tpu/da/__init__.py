"""Data-availability sampling subsystem (ROADMAP #3).

Block data is erasure-coded with a systematic Reed-Solomon code over
GF(2^16) (`rs.py` numpy oracle, `csrc/rs_gf16.inc` native engine), the
extended chunks are committed into an RFC-6962 Merkle tree whose root
rides the header as `da_root` (`commit.py`), the proposer-side node
retains recent extended blocks and serves per-sample opening proofs
(`serve.py`), and light clients draw seeded random indices and verify
proofs until a configurable confidence that the block is
reconstructable (`sampler.py`).
"""

from .commit import DACommitment, block_payload, extend_payload  # noqa: F401
from .rs import RSError, encode_shards, reconstruct_shards  # noqa: F401
from .sampler import Sampler, SampleResult  # noqa: F401
from .serve import DAServe  # noqa: F401
