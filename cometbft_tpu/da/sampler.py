"""DAS client: seeded random sampling against a header's da_root.

The availability argument: a block is reconstructable unless MORE than
m of the n = k+m extended chunks are unavailable (any k survivors
reconstruct). So an adversary hiding the data must withhold >= m+1
chunks, and a uniformly random sample then fails with probability
>= (m+1)/n. After s independent samples that ALL verify,
P(block actually unavailable) <= (1 - (m+1)/n)^s — the client's
confidence is one minus that. With the default k = m (rate-1/2
extension) each sample halves the doubt, so ~7 samples reach 99%.

Index draws are seeded (sha256 counter stream over
seed/client_id/height/da_root), so a fleet of clients is reproducible
end-to-end while still sampling independently per client.
"""

from __future__ import annotations

import hashlib
import math
import struct
from dataclasses import dataclass, field

from ..utils import trace
from .commit import DACommitment, proof_num_bytes


def confidence_after(samples_ok: int, n: int, m: int) -> float:
    """P[reconstructable] lower bound after `samples_ok` verified
    samples of an n-chunk extension with parity budget m."""
    if n <= 0 or samples_ok <= 0:
        return 0.0
    p_hit = (m + 1) / n
    if p_hit >= 1.0:
        return 1.0
    return 1.0 - (1.0 - p_hit) ** samples_ok


def samples_for_confidence(target: float, n: int, m: int) -> int:
    """Smallest s with confidence_after(s, n, m) >= target."""
    if not 0.0 < target < 1.0:
        raise ValueError("confidence target must be in (0, 1)")
    p_hit = (m + 1) / n
    if p_hit >= 1.0:
        return 1
    return max(1, math.ceil(math.log(1.0 - target) / math.log(1.0 - p_hit)))


@dataclass
class SampleResult:
    height: int
    confident: bool  # reached the target with zero failures
    confidence: float  # achieved lower bound
    samples_ok: int = 0
    samples_failed: int = 0
    failed_indices: list = field(default_factory=list)
    proof_bytes: int = 0  # total wire bytes across this client's samples

    @property
    def detected_withholding(self) -> bool:
        return self.samples_failed > 0


class Sampler:
    """One light client's sampling loop.

    `fetch(height, index)` is the transport: it returns
    (chunk, proof, commitment-ish) or None (unavailable/withheld) —
    backed by the `da_sample` RPC route or an in-process DAServe.
    """

    def __init__(
        self,
        client_id: int,
        n: int,
        k: int,
        *,
        samples: int = 0,
        confidence: float = 0.99,
        seed: int = 0,
    ):
        self.client_id = client_id
        self.n = n
        self.k = k
        self.m = n - k
        self.confidence_target = confidence
        self.samples = samples or samples_for_confidence(
            confidence, n, self.m
        )
        self.seed = seed

    def indices(self, height: int, da_root: bytes) -> list[int]:
        """Seeded draw of `samples` indices in [0, n) — deterministic
        per (seed, client, height, root), uniform via rejection."""
        out: list[int] = []
        ctr = 0
        base = hashlib.sha256(
            struct.pack(">QQQ", self.seed, self.client_id, height) + da_root
        ).digest()
        limit = (1 << 32) - ((1 << 32) % self.n)
        while len(out) < self.samples:
            block = hashlib.sha256(
                base + struct.pack(">Q", ctr)
            ).digest()
            ctr += 1
            for off in range(0, 32, 4):
                v = int.from_bytes(block[off:off + 4], "big")
                if v < limit:
                    out.append(v % self.n)
                    if len(out) == self.samples:
                        break
        return out

    def verify_sample(
        self, com: DACommitment, da_root: bytes, index: int,
        chunk: bytes, proof,
    ) -> bool:
        """One opening proof checked end-to-end: geometry matches the
        header root, chunk hash sits at `index` under chunks_root."""
        with trace.span(
            "da.sample_verify", index=index, n=com.n
        ) as sp:
            ok = com.root() == da_root and com.verify_sample(
                index, chunk, proof
            )
            sp.add(ok=ok)
        return ok

    def run(self, height: int, da_root: bytes, fetch) -> SampleResult:
        ok = 0
        failed: list[int] = []
        nbytes = 0
        for index in self.indices(height, da_root):
            got = fetch(height, index)
            if got is None:
                failed.append(index)
                continue
            chunk, proof, com = got
            if not self.verify_sample(com, da_root, index, chunk, proof):
                failed.append(index)
                continue
            ok += 1
            nbytes += proof_num_bytes(chunk, proof)
        conf = confidence_after(ok, self.n, self.m)
        return SampleResult(
            height=height,
            confident=not failed and conf >= self.confidence_target,
            confidence=conf,
            samples_ok=ok,
            samples_failed=len(failed),
            failed_indices=failed,
            proof_bytes=nbytes,
        )


@dataclass
class PCSampleResult:
    """One client's verdict on the 2D polynomial-commitment track."""

    height: int
    confident: bool  # target confidence, zero failures, parity holds
    confidence: float
    commitments_ok: bool = True  # the parity-linearity check
    samples_ok: int = 0
    samples_failed: int = 0
    failed_cols: list = field(default_factory=list)
    proof_bytes: int = 0  # multiproof response bytes (evals + proof)
    commitment_bytes: int = 0  # once-per-height commitment download

    @property
    def detected_withholding(self) -> bool:
        return self.samples_failed > 0 or not self.commitments_ok


class PCSampler:
    """One light client's sampling loop on the 2D KZG track.

    A sample is one (row, s distinct columns) draw answered by s
    32-byte evaluations plus ONE 48-byte multiproof. Availability math
    is the column dimension's: withholding enough to block column
    reconstruction means hiding >= m_c + 1 of n_c columns, so each
    sampled column hits with probability >= (m_c + 1)/n_c. Columns are
    drawn DISTINCT, which only raises the detection probability over
    the with-replacement bound `confidence_after` computes — the
    reported confidence stays a valid lower bound.

    Before any sample counts, the client runs the once-per-height
    lying-encoder check (`pc.verify_commitments`): parity commitments
    must be the Lagrange combination of the data commitments. The 1D
    track has no analogue — a Merkle root over garbage parity shards
    verifies every opening (the pinned blindness test).

    `fetch(height, row, cols)` is the transport: (ys, proof) or None —
    backed by the `da_pc_sample` RPC route or an in-process DAServe.
    When an aggregated fetch comes back None the client re-probes the
    columns one at a time, so `failed_cols` names the withheld columns
    instead of the whole draw.
    """

    def __init__(
        self,
        client_id: int,
        n_c: int,
        k_c: int,
        n_r: int,
        *,
        samples: int = 0,
        confidence: float = 0.99,
        seed: int = 0,
    ):
        self.client_id = client_id
        self.n_c = n_c
        self.k_c = k_c
        self.m_c = n_c - k_c
        self.n_r = n_r
        self.confidence_target = confidence
        self.samples = min(
            n_c,
            samples or samples_for_confidence(confidence, n_c, self.m_c),
        )
        self.seed = seed

    def draw(self, height: int, pc_root: bytes) -> tuple[int, list[int]]:
        """Seeded (row, distinct columns) draw — deterministic per
        (seed, client, height, root), uniform via rejection."""
        base = hashlib.sha256(
            b"pc" + struct.pack(
                ">QQQ", self.seed, self.client_id, height) + pc_root
        ).digest()
        row_limit = (1 << 32) - ((1 << 32) % self.n_r)
        col_limit = (1 << 32) - ((1 << 32) % self.n_c)
        row = None
        cols: list[int] = []
        seen: set[int] = set()
        ctr = 0
        while row is None or len(cols) < self.samples:
            block = hashlib.sha256(
                base + struct.pack(">Q", ctr)).digest()
            ctr += 1
            for off in range(0, 32, 4):
                v = int.from_bytes(block[off:off + 4], "big")
                if row is None:
                    if v < row_limit:
                        row = v % self.n_r
                    continue
                if v >= col_limit:
                    continue
                c = v % self.n_c
                if c not in seen:
                    seen.add(c)
                    cols.append(c)
                    if len(cols) == self.samples:
                        break
        return row, cols

    def run(self, height: int, pc_root: bytes, com, fetch
            ) -> PCSampleResult:
        from . import pc as pcmod

        com_bytes = com.num_bytes()
        if com.root() != pc_root:
            return PCSampleResult(
                height=height, confident=False, confidence=0.0,
                commitments_ok=False, commitment_bytes=com_bytes,
            )
        commitments_ok = pcmod.verify_commitments(com)
        row, cols = self.draw(height, pc_root)
        ok = 0
        failed: list[int] = []
        nbytes = 0
        got = fetch(height, row, cols)
        if got is not None:
            ys, proof = got
            if pcmod.verify_sample(com, pc_root, row, cols, ys, proof):
                ok = len(cols)
                nbytes = pcmod.multiproof_num_bytes(len(cols))
            else:
                failed = list(cols)
        else:
            # aggregated draw refused: probe per column for attribution
            for c in cols:
                one = fetch(height, row, [c])
                if one is None:
                    failed.append(c)
                    continue
                ys, proof = one
                if pcmod.verify_sample(
                    com, pc_root, row, [c], ys, proof
                ):
                    ok += 1
                    nbytes += pcmod.multiproof_num_bytes(1)
                else:
                    failed.append(c)
        conf = confidence_after(ok, self.n_c, self.m_c)
        return PCSampleResult(
            height=height,
            confident=(commitments_ok and not failed
                       and conf >= self.confidence_target),
            confidence=conf,
            commitments_ok=commitments_ok,
            samples_ok=ok,
            samples_failed=len(failed),
            failed_cols=failed,
            proof_bytes=nbytes,
            commitment_bytes=com_bytes,
        )
