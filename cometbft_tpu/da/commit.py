"""DA commitments: extended-chunk Merkle root + per-sample openings.

The committed payload is the block's Data proto encoding — for
columnar blocks that is the memoized `TxColumns.encode_data()` buffer,
so the bytes the DA encoder consumes are the SAME buffer block
serialization already built (zero-copy; nothing re-materializes
per-tx). The payload is split into k equal data shards (implicitly
zero-padded), RS-extended to n = k+m shards, each shard is hashed, and
the chunk hashes go into an RFC-6962 tree (crypto/merkle, same
0x00/0x01 leaf/inner domain separation as light/mmr.py). Like the MMR,
the final `da_root` binds the tree shape under a 0x02 root prefix —
here (n, k, payload_len, chunks_root) — so a sampler cannot be lied to
about the geometry its confidence math depends on.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..crypto import merkle
from .rs import RSError, encode_shards

# same domain-separation discipline as light/mmr.py: 0x00/0x01 are
# RFC-6962 leaf/inner (crypto/merkle), 0x02 binds the root metadata,
# 0x03 the polynomial-commitment root (da/pc.py), 0x04 the combined
# header root when both tracks run
ROOT_PREFIX = b"\x02"
COMBINED_ROOT_PREFIX = b"\x04"

_ROOT_FMT = ">IIQ"  # n, k, payload_len


def _sha256(b) -> bytes:
    return hashlib.sha256(b).digest()


@dataclass(frozen=True)
class DACommitment:
    """Geometry + chunk-hash root a sampler verifies openings against."""

    n: int  # total extended shards (k data + m parity)
    k: int  # data shards (any k of n reconstruct the payload)
    payload_len: int  # unpadded payload bytes (strip point on decode)
    chunks_root: bytes  # RFC-6962 root over sha256(shard) leaves

    def root(self) -> bytes:
        return _sha256(
            ROOT_PREFIX
            + struct.pack(_ROOT_FMT, self.n, self.k, self.payload_len)
            + self.chunks_root
        )

    def verify_sample(
        self, index: int, chunk: bytes, proof: merkle.Proof
    ) -> bool:
        if index != proof.index or proof.total != self.n:
            return False
        return proof.verify(self.chunks_root, _sha256(chunk))


def shard_length(payload_len: int, k: int) -> int:
    """Even per-shard byte length covering the payload; >= 2 so empty
    blocks still commit to k well-formed one-word shards."""
    words = max(1, -(-payload_len // (2 * k)))
    return 2 * words


def split_payload(payload, k: int) -> list[bytes]:
    """k equal data shards, zero-padded; accepts bytes or memoryview
    (one copy of the payload total, into the shard slices)."""
    mv = memoryview(payload)
    shard_len = shard_length(len(mv), k)
    out = []
    for j in range(k):
        piece = bytes(mv[j * shard_len:(j + 1) * shard_len])
        if len(piece) < shard_len:
            piece = piece + b"\x00" * (shard_len - len(piece))
        out.append(piece)
    return out


def join_payload(data_shards: list[bytes], payload_len: int) -> bytes:
    return b"".join(data_shards)[:payload_len]


def extend_payload(
    payload, k: int, m: int, *, nchunks: int = 0
) -> list[bytes]:
    """Full extended shard list: k data shards + m RS parity shards."""
    data = split_payload(payload, k)
    return data + encode_shards(data, m, nchunks=nchunks)


def commit_shards(
    shards: list[bytes], k: int, payload_len: int
) -> tuple[DACommitment, list[merkle.Proof]]:
    """Commitment + one opening proof per extended chunk."""
    hashes = [_sha256(s) for s in shards]
    chunks_root, proofs = merkle.proofs_from_byte_slices(hashes)
    com = DACommitment(
        n=len(shards), k=k, payload_len=payload_len, chunks_root=chunks_root
    )
    return com, proofs


def block_payload(data) -> bytes:
    """The byte string the DA code commits to: the Data proto encoding
    (memoized single buffer for TxColumns-backed blocks)."""
    return data.encode()


def da_root_for_data(data, k: int, m: int, *, nchunks: int = 0) -> bytes:
    """Proposal/validation-time root: encode + commit, root only."""
    payload = block_payload(data)
    shards = extend_payload(payload, k, m, nchunks=nchunks)
    com, _ = commit_shards(shards, k, len(payload))
    return com.root()


def combined_root(root_1d: bytes, pc_root: bytes) -> bytes:
    """Header da_root when the polynomial-commitment track rides along
    with the 1D RS track: one hash binding both, domain-separated so
    neither single-track root can collide with it."""
    return _sha256(COMBINED_ROOT_PREFIX + root_1d + pc_root)


def proof_num_bytes(chunk: bytes, proof: merkle.Proof) -> int:
    """Wire-cost accounting for one sample: chunk + leaf hash + aunts
    + the fixed (total, index) header. Mirrors MMRProof.num_bytes()."""
    return len(chunk) + 32 * (1 + len(proof.aunts)) + 12


def reconstruct_payload(
    shards: list[bytes | None], com: DACommitment, *, nchunks: int = 0
) -> bytes:
    """Recover the payload from any >= k surviving shards and verify it
    against the commitment (re-derives the root; raises RSError when
    the survivors do not re-commit to the same da_root)."""
    from .rs import reconstruct_shards

    full = reconstruct_shards(
        shards, com.k, com.n - com.k, nchunks=nchunks
    )
    got, _ = commit_shards(full, com.k, com.payload_len)
    if got.root() != com.root():
        raise RSError("reconstructed shards do not match the commitment")
    return join_payload(full[: com.k], com.payload_len)
