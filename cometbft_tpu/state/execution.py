"""BlockExecutor: validate -> FinalizeBlock -> update state -> commit.

Behavior parity with reference internal/state/execution.go:
- ApplyBlock (:211): validateBlock, ABCI FinalizeBlock (:219), validator
  update validation (:261), updateState (:586) rotating the three
  validator sets, app Commit (:379), prune + events.
- validateBlock (internal/state/validation.go:92) runs
  state.last_validators.VerifyCommit on every block — the full-signature
  hot path that rides the TPU batch verifier.
- CreateProposalBlock (:109) assembles a block through PrepareProposal.
"""

from __future__ import annotations

from dataclasses import replace

from ..crypto import merkle
from ..crypto.ed25519 import Ed25519PubKey
from ..crypto.secp256k1 import Secp256k1PubKey
from ..types import (
    Block,
    BlockID,
    Commit,
    Data,
    Header,
    PartSetHeader,
    Timestamp,
    Validator,
    ValidatorSet,
    verify_commit,
)
from ..types.block import Consensus
from ..types.evidence import evidence_list_hash
from ..types.validation import CommitError
from .types import State


class BlockValidationError(Exception):
    pass


def _pub_key_from_update(vu) -> Ed25519PubKey | Secp256k1PubKey:
    """ABCI ValidatorUpdate pub_key_type dispatch (reference
    abci/types PubKeyType strings via crypto/encoding codec)."""
    t = vu.pub_key_type
    if t in ("ed25519", "tendermint/PubKeyEd25519"):
        return Ed25519PubKey(vu.pub_key_bytes)
    if t in ("secp256k1", "tendermint/PubKeySecp256k1"):
        return Secp256k1PubKey(vu.pub_key_bytes)
    raise BlockValidationError(f"unsupported validator key type {t!r}")


def median_time(commit: Commit, vals: ValidatorSet) -> Timestamp:
    """Voting-power-weighted median of commit timestamps (reference
    internal/state/state.go:266 MedianTime + types/time/time.go:35
    WeightedMedian): every non-ABSENT signature's timestamp counts
    (including NIL votes), validators are looked up by address, and the
    pick is the first sorted timestamp whose cumulative weight reaches
    total/2 (ties take the earlier timestamp).

    A certificate-native commit (CertCommit) carries ONE canonical
    timestamp all signers covered — the weighted median of N copies of
    one value is that value, so the answer is exact, not approximate.
    The branch must be explicit: the synthesized per-slot view has empty
    addresses, which the by-address walk would silently drop."""
    cert = getattr(commit, "cert", None)
    if cert is not None:
        return cert.timestamp
    fast = _median_time_columnar(commit, vals)
    if fast is not None:
        return fast
    pairs = []
    total = 0
    for cs in commit.signatures:
        if cs.is_absent():
            continue
        _, val = vals.get_by_address(cs.validator_address)
        if val is None:
            continue
        pairs.append((cs.timestamp.unix_ns(), val.voting_power))
        total += val.voting_power
    if not pairs:
        return Timestamp()
    pairs.sort()
    median = total // 2
    for ts, p in pairs:
        if median <= p:
            return Timestamp.from_unix_ns(ts)
        median -= p
    return Timestamp.from_unix_ns(pairs[-1][0])


def _median_time_columnar(commit: Commit, vals: ValidatorSet):
    """Vectorized weighted median over the decode columns — replay runs
    this once per block over 1000-signature commits. None (fall back to
    the per-slot walk) unless every live address matches the set
    positionally, which the batched verify has already required."""
    cols = commit.verify_columns() if hasattr(commit, "verify_columns") else None
    if cols is None:
        return None
    vcols = vals.ed25519_columns()
    if vcols is None:
        return None
    import numpy as np

    flags, addrs, addr_lens, _, _, ts_s, ts_n = cols
    addr_rows, _, powers = vcols
    if len(flags) != len(addr_rows):
        return None
    live = flags != 1
    if not (addrs[live] == addr_rows[live]).all():
        return None  # out-of-order/unknown addresses: slow path
    # int64 ns math wraps beyond +-292 years from epoch (e.g. the Go
    # zero time, seconds = -62135596800); the scalar walk uses exact
    # Python ints, so out-of-range timestamps take the slow path rather
    # than risk a divergent median
    if len(ts_s) and (np.abs(ts_s[live]) > 9_000_000_000).any():
        return None
    ts = ts_s[live] * 1_000_000_000 + ts_n[live]
    pw = powers[live]
    if not len(ts):
        return Timestamp()
    order = np.argsort(ts, kind="stable")
    ts, pw = ts[order], pw[order]
    median = int(pw.sum()) // 2
    cum = np.cumsum(pw)
    # the scalar walk returns the first i with median - cum[i-1] <=
    # pw[i], i.e. the first i with cum[i] >= median
    i = int(np.searchsorted(cum, median, side="left"))
    if i >= len(ts):
        i = len(ts) - 1
    return Timestamp.from_unix_ns(int(ts[i]))


def results_hash(tx_results) -> bytes:
    """last_results_hash input (reference types/results.go Hash)."""
    return merkle.hash_from_byte_slices([r.encode() for r in tx_results])


def validate_block(
    state: State,
    block: Block,
    backend: str = "tpu",
    last_commit_preverified: bool = False,
) -> None:
    """Full block validation against current state
    (reference internal/state/validation.go).

    last_commit_preverified elides only the signature re-verification of
    the LastCommit (structure, size, hashes, and median-time checks still
    run) — used by the batched replay path, which has already verified
    those exact signatures in a window mega-batch.
    """
    h = block.header
    if h.chain_id != state.chain_id:
        raise BlockValidationError(f"wrong chain id {h.chain_id}")
    expected_height = (
        state.initial_height
        if state.last_block_height == 0
        else state.last_block_height + 1
    )
    if h.height != expected_height:
        raise BlockValidationError(
            f"wrong height {h.height}, expected {expected_height}"
        )
    if h.last_block_id != state.last_block_id:
        raise BlockValidationError("wrong last_block_id")
    if h.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong validators_hash")
    if h.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong next_validators_hash")
    if h.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong consensus_hash")
    if h.app_hash != state.app_hash:
        raise BlockValidationError("wrong app_hash")
    if h.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong last_results_hash")
    if h.data_hash != block.data.hash():
        raise BlockValidationError("wrong data_hash")
    if h.last_commit_hash != block.last_commit.hash():
        raise BlockValidationError("wrong last_commit_hash")
    if h.evidence_hash != evidence_list_hash(block.evidence):
        raise BlockValidationError("wrong evidence_hash")

    if h.height == state.initial_height:
        if block.last_commit.signatures:
            raise BlockValidationError("initial block must have empty last commit")
    else:
        if len(block.last_commit.signatures) != len(state.last_validators):
            raise BlockValidationError("wrong last commit size")
        if not last_commit_preverified:
            try:
                verify_commit(
                    state.chain_id,
                    state.last_validators,
                    state.last_block_id,
                    h.height - 1,
                    block.last_commit,
                    backend=backend,
                )
            except CommitError as e:
                raise BlockValidationError(f"invalid last commit: {e}") from e
        # block time must be the weighted median of the last commit
        expected_time = median_time(block.last_commit, state.last_validators)
        if h.time != expected_time:
            raise BlockValidationError("block time != median commit time")
    if not h.proposer_address or len(h.proposer_address) != 20:
        raise BlockValidationError("invalid proposer address")
    if h.da_root and len(h.da_root) != 32:
        raise BlockValidationError("invalid da_root length")


def build_last_commit_info(block: Block, last_vals: ValidatorSet | None):
    """CommitInfo for FinalizeBlock (reference internal/state/execution.go
    buildLastCommitInfo): who signed the last commit, for incentives."""
    from ..abci.types import CommitInfo

    if block.header.height == 1 or last_vals is None:
        return CommitInfo()
    commit = block.last_commit
    cols = commit.verify_columns() if hasattr(commit, "verify_columns") else None
    if cols is not None and len(cols[0]) == len(last_vals.validators):
        present = (cols[0] != 1).tolist()  # flags != ABSENT
        votes = [
            (val.address, val.voting_power, p)
            for val, p in zip(last_vals.validators, present)
        ]
        return CommitInfo(round=commit.round, votes=votes)
    votes = []
    for idx, cs in enumerate(commit.signatures):
        val = last_vals.get_by_index(idx)
        if val is None:
            continue
        votes.append((val.address, val.voting_power, not cs.is_absent()))
    return CommitInfo(round=commit.round, votes=votes)


class BlockExecutor:
    def __init__(self, app_conns, state_store=None, block_store=None,
                 backend: str = "tpu", mempool=None, evidence_pool=None,
                 event_bus=None):
        self.app = app_conns
        self.state_store = state_store
        self.block_store = block_store
        self.backend = backend
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.event_bus = event_bus
        self.event_handlers: list = []
        self.pruner = None  # optional state.pruner.Pruner
        # optional da.DAServe: when set, proposals carry a DA commitment
        # in the header and apply_block re-derives and enforces it
        self.da_encoder = None
        # optional crypto.sched.VerifyScheduler: when set, LastCommit
        # verification inside validate_block routes through the shared
        # scheduler at consensus priority under this tenant (chain_id)
        self.verify_sched = None
        self.sched_tenant = ""

    # --- proposal side ---
    def create_proposal_block(
        self,
        height: int,
        state: State,
        last_commit: Commit,
        proposer_address: bytes,
        txs: list[bytes],
        block_time: Timestamp | None = None,
    ) -> Block:
        max_bytes = state.consensus_params.block.max_bytes
        ev_cap = min(state.consensus_params.evidence.max_bytes, max_bytes // 10)
        evidence = (
            self.evidence_pool.pending_evidence(ev_cap)
            if self.evidence_pool is not None
            else []
        )
        ev_size = sum(len(ev.wrapped()) for ev in evidence)
        local_last_commit = None
        eh = state.consensus_params.abci.vote_extensions_enable_height
        if eh > 0 and height > eh and self.block_store is not None:
            # deliver height-1's vote extensions to the app
            # (reference PrepareProposalRequest.LocalLastCommit)
            local_last_commit = self.block_store.load_extended_commit(
                height - 1
            )
        # evidence spends block budget before txs (reference MaxDataBytes)
        txs = self.app.consensus.prepare_proposal(
            txs, max_bytes - ev_size, local_last_commit
        )
        if height == state.initial_height:
            time = block_time or state.last_block_time
        else:
            time = median_time(last_commit, state.last_validators)
        data = Data(txs)
        da_root = (
            self.da_encoder.da_root_for(data)
            if self.da_encoder is not None
            else b""
        )
        header = Header(
            version=Consensus(),
            chain_id=state.chain_id,
            height=height,
            time=time,
            last_block_id=state.last_block_id,
            last_commit_hash=last_commit.hash(),
            data_hash=data.hash(),
            validators_hash=state.validators.hash(),
            next_validators_hash=state.next_validators.hash(),
            consensus_hash=state.consensus_params.hash(),
            app_hash=state.app_hash,
            last_results_hash=state.last_results_hash,
            evidence_hash=evidence_list_hash(evidence),
            proposer_address=proposer_address,
            da_root=da_root,
        )
        return Block(
            header=header, data=data, evidence=evidence,
            last_commit=last_commit,
        )

    def check_da_commitment(self, block: Block) -> None:
        """With DA enabled, the header's da_root must equal the root
        re-derived from the block's own payload — a proposer cannot
        commit to chunks that don't encode the data (apply-side gate;
        no-op when the node runs without a DA encoder)."""
        if self.da_encoder is None:
            return
        expected = self.da_encoder.da_root_for(block.data)
        if block.header.da_root != expected:
            raise BlockValidationError(
                "wrong da_root" if block.header.da_root else "missing da_root"
            )

    def process_proposal(self, block: Block) -> bool:
        from ..abci.types import ProposalStatus

        return (
            self.app.consensus.process_proposal(block.data.txs)
            == ProposalStatus.ACCEPT
        )

    # --- commit side ---
    def apply_block(
        self,
        state: State,
        block_id: BlockID,
        block: Block,
        last_commit_preverified: bool = False,
    ) -> State:
        import time as _time

        from ..abci.types import FinalizeBlockRequest
        from ..utils import trace
        from ..utils import txlife as _txlife
        from ..utils.fail import fail_point
        from ..utils.metrics import state_metrics

        # sampled txs of this block, hashed once: the apply/commit/notify
        # lifecycle stamps all sweep the same pairs
        life = _txlife.sampled_keys(block.data.txs) if _txlife.enabled else ()
        h_ = block.header.height
        t0 = _time.perf_counter()
        from ..crypto.sched import verify_context

        with verify_context(self.verify_sched, self.sched_tenant,
                            "consensus"):
            validate_block(
                state,
                block,
                backend=self.backend,
                last_commit_preverified=last_commit_preverified,
            )
        state_metrics().block_verify_time.observe(_time.perf_counter() - t0)
        self.check_da_commitment(block)
        if self.evidence_pool is not None and block.evidence:
            # reject fabricated misbehavior before it reaches the app
            # (reference internal/state/validation.go evpool.CheckEvidence)
            self.evidence_pool.check_evidence(
                block.evidence, state.consensus_params.evidence.max_bytes
            )

        t_validate = _time.perf_counter()
        fail_point()  # reference execution.go:251 (pre-FinalizeBlock)
        resp = self.app.consensus.finalize_block(
            FinalizeBlockRequest(
                txs=block.data.txs,
                decided_last_commit=build_last_commit_info(
                    block, state.last_validators
                ),
                misbehavior=[m for ev in block.evidence
                             for m in ev.to_abci_list()],
                hash=block.hash() or b"",
                height=block.header.height,
                time=block.header.time,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            )
        )
        if len(resp.tx_results) != len(block.data.txs):
            raise BlockValidationError("app returned wrong number of tx results")

        t_finalize = _time.perf_counter()
        if life:
            _txlife.stage_block(life, "apply", height=h_)
        fail_point()  # reference execution.go:258 (post-FinalizeBlock, pre-save)
        new_state = self._update_state(state, block_id, block, resp)

        # Commit with the mempool locked, then update it against the new
        # state (reference execution.go:379 Commit).
        fail_point()  # reference execution.go:293 (pre-Commit)
        if self.mempool is not None:
            self.mempool.lock()
            try:
                retain_height = self.app.consensus.commit()
                self.mempool.update(
                    block.header.height, block.data.txs, resp.tx_results
                )
            finally:
                self.mempool.unlock()
        else:
            retain_height = self.app.consensus.commit()
        if self.pruner is not None and retain_height:
            # the app's retain height feeds the background pruner
            # (reference execution.go Commit -> pruneBlocks)
            self.pruner.set_app_retain_height(retain_height)
        if self.evidence_pool is not None:
            self.evidence_pool.update(new_state, block.evidence)

        t_commit = _time.perf_counter()
        if life:
            _txlife.stage_block(life, "commit", height=h_)
        fail_point()  # reference execution.go:301 (post-Commit, pre-save)
        if self.state_store is not None:
            self.state_store.save(new_state)
            self.state_store.save_finalize_response(
                block.header.height, results_hash(resp.tx_results)
            )
            from ..abci import wire as _W

            self.state_store.save_abci_responses(
                block.header.height, _W.enc_finalize_resp(resp)
            )
        if self.event_bus is not None:
            # fire events (reference execution.go:313 fireEvents)
            self.event_bus.publish_new_block(block, resp)
            for i, tx in enumerate(block.data.txs):
                self.event_bus.publish_tx(
                    block.header.height, i, tx, resp.tx_results[i]
                )
            if resp.validator_updates:
                self.event_bus.publish_validator_set_updates(
                    resp.validator_updates
                )
        if life:
            # notify closes the lifecycle whether or not an event bus is
            # wired (without one there is simply nothing to wait on)
            _txlife.stage_block(life, "notify", height=h_)
        for handler in self.event_handlers:
            handler(block, resp)
        t_end = _time.perf_counter()
        state_metrics().block_processing_time.observe(t_end - t0)
        if trace.enabled:
            # One span per ApplyBlock carrying the per-stage breakdown
            # (validate = commit-sig verification, i.e. the crypto path).
            trace.emit(
                "state.apply_block", "span",
                height=block.header.height, txs=len(block.data.txs),
                dur_ms=round((t_end - t0) * 1e3, 3),
                validate_ms=round((t_validate - t0) * 1e3, 3),
                finalize_ms=round((t_finalize - t_validate) * 1e3, 3),
                commit_ms=round((t_commit - t_finalize) * 1e3, 3),
                save_events_ms=round((t_end - t_commit) * 1e3, 3),
            )
        return new_state

    def apply_block_preverified(self, state: State, block_id: BlockID, block: Block) -> State:
        """apply_block with LastCommit signatures already verified by the
        replay window mega-batch (all structural checks still run)."""
        return self.apply_block(state, block_id, block, last_commit_preverified=True)

    def _update_state(self, state: State, block_id: BlockID, block: Block, resp) -> State:
        n_vals = state.next_validators.copy()
        changed = state.last_height_validators_changed
        if resp.validator_updates:
            changes = []
            for vu in resp.validator_updates:
                changes.append(
                    Validator.from_pub_key(
                        _pub_key_from_update(vu), vu.power
                    )
                )
            n_vals.update_with_change_set(changes)
            changed = block.header.height + 2
        n_vals.increment_proposer_priority(1)
        # no defensive copies for the rotated sets: every mutator in the
        # codebase (here and consensus enter_new_round) operates on a
        # private .copy() first, so ValidatorSet objects reachable from
        # a State are never mutated in place — sharing them across the
        # rotation is safe and saves 2 full-set copies per block
        # (State.__post_init__ freezes the sets so a violation of that
        # convention raises instead of corrupting historical sets)
        return replace(
            state,
            last_block_height=block.header.height,
            last_block_id=block_id,
            last_block_time=block.header.time,
            last_validators=state.validators,
            validators=state.next_validators,
            next_validators=n_vals,
            last_height_validators_changed=changed,
            last_results_hash=results_hash(resp.tx_results),
            app_hash=resp.app_hash,
        )


def make_genesis_state(
    chain_id: str,
    validators: ValidatorSet,
    app_hash: bytes = b"",
    initial_height: int = 1,
    genesis_time: Timestamp | None = None,
    consensus_params=None,
) -> State:
    """Genesis -> State (reference internal/state/state.go MakeGenesisState)."""
    from .types import ConsensusParams

    return State(
        chain_id=chain_id,
        initial_height=initial_height,
        last_block_height=0,
        last_block_time=genesis_time or Timestamp.from_unix_ns(1_700_000_000_000_000_000),
        validators=validators.copy(),
        last_validators=None,  # empty at genesis (reference MakeGenesisState)
        next_validators=validators.copy_increment_proposer_priority(1),
        last_height_validators_changed=initial_height,
        consensus_params=consensus_params or ConsensusParams(),
    )
