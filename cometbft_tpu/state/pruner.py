"""Background pruner service.

Behavior parity: reference internal/state/pruner.go (509 LoC) — a
service that periodically prunes block and state stores up to an
"effective retain height": the minimum of the application's retain
height (returned by ABCI Commit) and, when a data companion is enabled,
the companion's block/block-results retain heights (settable via the
privileged pruning RPC service). Heights are persisted so pruning
resumes across restarts.
"""

from __future__ import annotations

import threading

_KEY_APP_RETAIN = b"PR:app"
_KEY_COMPANION_BLOCK = b"PR:dcb"
_KEY_COMPANION_RESULTS = b"PR:dcr"


class Pruner:
    def __init__(
        self,
        block_store,
        state_store,
        interval_s: float = 10.0,
        companion_enabled: bool = False,
    ):
        self.block_store = block_store
        self.state_store = state_store
        self.interval_s = interval_s
        self.companion_enabled = companion_enabled
        self._db = state_store._db
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    # -- retain-height setters (persisted) ---------------------------------
    def _get(self, key: bytes) -> int:
        raw = self._db.get(key)
        return int.from_bytes(raw, "big") if raw else 0

    def _set(self, key: bytes, h: int) -> None:
        self._db.set(key, h.to_bytes(8, "big"))

    def set_app_retain_height(self, h: int) -> None:
        """From ABCI Commit's retain_height (reference SetApplicationBlockRetainHeight)."""
        if h <= 0:
            return
        with self._lock:
            if h > self._get(_KEY_APP_RETAIN):
                self._set(_KEY_APP_RETAIN, h)
        self._wake.set()

    def set_companion_block_retain_height(self, h: int) -> None:
        if h <= 0:
            raise ValueError("retain height must be positive")
        with self._lock:
            self._set(_KEY_COMPANION_BLOCK, h)
        self._wake.set()

    def set_companion_block_results_retain_height(self, h: int) -> None:
        if h <= 0:
            raise ValueError("retain height must be positive")
        with self._lock:
            self._set(_KEY_COMPANION_RESULTS, h)
        self._wake.set()

    def app_retain_height(self) -> int:
        return self._get(_KEY_APP_RETAIN)

    def companion_block_retain_height(self) -> int:
        return self._get(_KEY_COMPANION_BLOCK)

    def companion_block_results_retain_height(self) -> int:
        return self._get(_KEY_COMPANION_RESULTS)

    def effective_retain_height(self) -> int:
        """min(app, companion) when the companion is enabled, else app
        (reference pruner.go findMinRetainHeight)."""
        app = self._get(_KEY_APP_RETAIN)
        if not self.companion_enabled:
            return app
        # companion enabled but silent (height 0) blocks pruning — its
        # data needs are unknown, so nothing may be deleted yet
        return min(app, self._get(_KEY_COMPANION_BLOCK))

    # -- service ------------------------------------------------------------
    def prune_once(self) -> tuple[int, int]:
        """One pruning pass; returns (blocks_pruned, states_pruned)."""
        retain = self.effective_retain_height()
        if retain <= 1:
            return 0, 0
        blocks = self.block_store.prune(retain)
        states = self.state_store.prune(retain, self.block_store.height())
        return blocks, states

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                self.prune_once()
            except Exception:  # noqa: BLE001 — pruning must never kill the node
                pass
            self._wake.wait(self.interval_s)
            self._wake.clear()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
