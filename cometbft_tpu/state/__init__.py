"""Execution state and the block executor (ABCI driving loop)."""

from .types import State, ConsensusParams  # noqa: F401
from .execution import BlockExecutor  # noqa: F401
