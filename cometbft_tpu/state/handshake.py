"""Boot-time ABCI handshake: catch the app up to the stored chain.

Behavior parity: reference internal/consensus/replay.go —
- Handshake (:241): ABCI Info -> compare the app's last height/hash with
  the block store -> ReplayBlocks (:283);
- InitChain on a fresh app (:307-338) with the genesis validators;
- blocks the app is missing are re-executed through FinalizeBlock+Commit
  (:505 replayBlock); blocks the *state* is missing go through the full
  executor (signatures were verified before they were stored, so the
  LastCommit re-verification is elided like the batched replay path);
- the final app hash must match the replayed state's app hash (:413).
"""

from __future__ import annotations

from dataclasses import replace

from ..abci.types import FinalizeBlockRequest, InitChainRequest, ValidatorUpdate
from ..types.block import block_id_for
from .execution import BlockExecutor, build_last_commit_info, results_hash


class HandshakeError(Exception):
    pass


class Handshaker:
    def __init__(self, state_store, block_store, genesis_state,
                 backend: str = "tpu"):
        self.state_store = state_store
        self.block_store = block_store
        self.genesis_state = genesis_state
        self.backend = backend
        self.blocks_replayed = 0

    def handshake(self, app_conns):
        """Returns the post-replay sm.State."""
        info = app_conns.query.info()
        app_height = info.last_block_height
        app_hash = info.last_block_app_hash

        state = self.state_store.load()
        if state is None:
            # bootstrap: persist genesis validators for heights 1 and 2
            # (reference internal/state/store.go Bootstrap)
            state = self.genesis_state.copy()
            self.state_store.save(state)

        if app_height == 0:
            # fresh app: InitChain with the genesis validator set
            res = app_conns.consensus.init_chain(
                InitChainRequest(
                    time=self.genesis_state.last_block_time,
                    chain_id=self.genesis_state.chain_id,
                    validators=[
                        ValidatorUpdate(
                            pub_key_bytes=v.pub_key.bytes(), power=v.voting_power
                        )
                        for v in self.genesis_state.validators.validators
                    ],
                    initial_height=self.genesis_state.initial_height,
                )
            )
            if state.last_block_height == 0 and res.app_hash:
                state = replace(state, app_hash=res.app_hash)
                app_hash = res.app_hash

        store_height = self.block_store.height()
        if app_height > store_height:
            raise HandshakeError(
                f"app height {app_height} ahead of store {store_height}"
            )
        if app_height > state.last_block_height:
            raise HandshakeError(
                f"app height {app_height} ahead of state "
                f"{state.last_block_height}"
            )

        executor = BlockExecutor(
            app_conns, state_store=self.state_store, backend=self.backend
        )
        for h in range(app_height + 1, store_height + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise HandshakeError(f"store missing block {h}")
            if h <= state.last_block_height:
                # app behind state: execute into the app only (:505)
                resp = app_conns.consensus.finalize_block(
                    FinalizeBlockRequest(
                        txs=block.data.txs,
                        decided_last_commit=build_last_commit_info(
                            block, self.state_store.load_validators(h - 1)
                            if h > 1 else None
                        ),
                        hash=block.hash() or b"",
                        height=h,
                        time=block.header.time,
                        next_validators_hash=block.header.next_validators_hash,
                        proposer_address=block.header.proposer_address,
                    )
                )
                app_conns.consensus.commit()
                app_hash = resp.app_hash
            else:
                # both state and app need the block: full apply, signature
                # re-verification elided (stored blocks were verified)
                state = executor.apply_block(
                    state, block_id_for(block), block,
                    last_commit_preverified=True,
                )
                app_hash = state.app_hash
            self.blocks_replayed += 1

        if state.last_block_height > 0 and app_hash != state.app_hash:
            raise HandshakeError(
                f"app hash {app_hash.hex()[:12]} != state "
                f"{state.app_hash.hex()[:12]} after replay"
            )
        return state
