"""The `State` value object (reference internal/state/state.go:352).

Everything needed to validate and execute the next block: rotated
validator sets (last/current/next), consensus params, app hash, last
results hash. Immutable-ish: every ApplyBlock produces a new State.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..encoding import proto as pb
from ..types import BlockID, Timestamp, Validator, ValidatorSet, ZERO_TIME
from ..types.basic import ZERO_BLOCK_ID
from ..types.validator_set import decode_pub_key, encode_pub_key


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 4 * 1024 * 1024  # reference types/params.go defaults
    max_gas: int = -1


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1024 * 1024


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple[str, ...] = ("ed25519",)


@dataclass(frozen=True)
class ABCIParams:
    vote_extensions_enable_height: int = 0


@dataclass(frozen=True)
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    abci: ABCIParams = field(default_factory=ABCIParams)

    def hash(self) -> bytes:
        """Hash over the consensus-critical params (reference
        types/params.go HashConsensusParams: SHA-256 of proto of
        block.max_bytes/max_gas)."""
        from ..crypto.keys import tmhash

        payload = pb.f_varint(1, self.block.max_bytes) + pb.f_varint(
            2, self.block.max_gas
        )
        return tmhash(payload)


def encode_params(cp: ConsensusParams) -> bytes:
    """Proto encoding of the full ConsensusParams (reference
    types/params.go ToProto) for per-height persistence."""
    block = pb.f_varint(1, cp.block.max_bytes) + pb.f_varint(2, cp.block.max_gas)
    ev = (
        pb.f_varint(1, cp.evidence.max_age_num_blocks)
        + pb.f_varint(2, cp.evidence.max_age_duration_ns)
        + pb.f_varint(3, cp.evidence.max_bytes)
    )
    val = b"".join(pb.f_string(1, t) for t in cp.validator.pub_key_types)
    abci = pb.f_varint(1, cp.abci.vote_extensions_enable_height)
    return (
        pb.f_embedded(1, block)
        + pb.f_embedded(2, ev)
        + pb.f_embedded(3, val)
        + pb.f_embedded(4, abci)
    )


def decode_params(buf: bytes) -> ConsensusParams:
    d = pb.fields_to_dict(buf)
    bd = pb.fields_to_dict(pb.as_bytes(d.get(1, b"")))
    ed = pb.fields_to_dict(pb.as_bytes(d.get(2, b"")))
    key_types = tuple(
        pb.as_bytes(v).decode()
        for f, _, v in pb.parse_fields(pb.as_bytes(d.get(3, b"")))
        if f == 1
    )
    ad = pb.fields_to_dict(pb.as_bytes(d.get(4, b"")))
    return ConsensusParams(
        block=BlockParams(
            max_bytes=pb.to_i64(bd.get(1, 0)) or BlockParams.max_bytes,
            max_gas=pb.to_i64(bd.get(2, 0)) or -1,
        ),
        evidence=EvidenceParams(
            max_age_num_blocks=pb.to_i64(ed.get(1, 0)),
            max_age_duration_ns=pb.to_i64(ed.get(2, 0)),
            max_bytes=pb.to_i64(ed.get(3, 0)),
        ),
        validator=ValidatorParams(pub_key_types=key_types or ("ed25519",)),
        abci=ABCIParams(
            vote_extensions_enable_height=pb.to_i64(ad.get(1, 0))
        ),
    )


def _encode_validator(v: Validator) -> bytes:
    return (
        pb.f_bytes(1, v.address)
        + pb.f_embedded(2, encode_pub_key(v.pub_key))
        + pb.f_varint(3, v.voting_power)
        + pb.f_varint(4, v.proposer_priority)
    )


def _decode_validator(buf: bytes) -> Validator:
    d = pb.fields_to_dict(buf)
    key_fields = pb.fields_to_dict(pb.as_bytes(d.get(2, b"")))
    pk = decode_pub_key(key_fields)
    return Validator(
        address=pb.as_bytes(d.get(1, b"")),
        pub_key=pk,
        voting_power=pb.to_i64(d.get(3, 0)),
        proposer_priority=pb.to_i64(d.get(4, 0)),
    )


def encode_validator_set(vs: ValidatorSet) -> bytes:
    out = b""
    for v in vs.validators:
        out += pb.f_embedded(1, _encode_validator(v))
    prop = vs.get_proposer()
    out += pb.f_bytes(2, prop.address)
    return out


def decode_validator_set(buf: bytes) -> ValidatorSet:
    vals = []
    prop_addr = b""
    for f, _, v in pb.parse_fields(buf):
        if f == 1:
            vals.append(_decode_validator(pb.as_bytes(v)))
        elif f == 2:
            prop_addr = pb.as_bytes(v)
    vs = ValidatorSet(vals, increment_first=False)
    # restore exact priorities (ValidatorSet() copies, order by power)
    if prop_addr:
        _, p = vs.get_by_address(prop_addr)
        vs.proposer = p
    return vs


@dataclass
class State:
    chain_id: str = ""
    initial_height: int = 1
    last_block_height: int = 0
    last_block_id: BlockID = ZERO_BLOCK_ID
    last_block_time: Timestamp = ZERO_TIME
    validators: ValidatorSet | None = None
    last_validators: ValidatorSet | None = None
    next_validators: ValidatorSet | None = None
    last_height_validators_changed: int = 1
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_params_changed: int = 1
    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def __post_init__(self):
        # State snapshots alias ValidatorSet objects (no defensive
        # copies); the convention is that every mutator works on a
        # private .copy(). Freezing here — the single choke point every
        # producer passes through (decode, statesync, rollback, genesis,
        # dataclasses.replace) — makes a violation fail loudly instead
        # of silently corrupting historical sets.
        for vs in (self.validators, self.last_validators, self.next_validators):
            if vs is not None:
                vs.freeze()

    def copy(self) -> "State":
        return replace(self)

    def encode(self) -> bytes:
        out = (
            pb.f_string(1, self.chain_id)
            + pb.f_varint(2, self.initial_height)
            + pb.f_varint(3, self.last_block_height)
            + pb.f_embedded(4, self.last_block_id.encode())
            + pb.f_embedded(5, self.last_block_time.encode())
            + pb.f_varint(8, self.last_height_validators_changed)
            + pb.f_bytes(10, self.last_results_hash)
            + pb.f_bytes(11, self.app_hash)
            + pb.f_varint(12, self.last_height_params_changed)
            + pb.f_embedded(13, encode_params(self.consensus_params))
        )
        if self.validators is not None:
            out += pb.f_embedded(6, encode_validator_set(self.validators))
        if self.last_validators is not None:
            out += pb.f_embedded(7, encode_validator_set(self.last_validators))
        if self.next_validators is not None:
            out += pb.f_embedded(9, encode_validator_set(self.next_validators))
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "State":
        d = pb.fields_to_dict(buf)
        return cls(
            chain_id=pb.as_bytes(d.get(1, b"")).decode(),
            initial_height=pb.to_i64(d.get(2, 1)),
            last_block_height=pb.to_i64(d.get(3, 0)),
            last_block_id=BlockID.decode(pb.as_bytes(d.get(4, b""))),
            last_block_time=Timestamp.decode(pb.as_bytes(d.get(5, b""))),
            validators=decode_validator_set(pb.as_bytes(d[6])) if 6 in d else None,
            last_validators=decode_validator_set(pb.as_bytes(d[7])) if 7 in d else None,
            next_validators=decode_validator_set(pb.as_bytes(d[9])) if 9 in d else None,
            last_height_validators_changed=pb.to_i64(d.get(8, 1)),
            last_results_hash=pb.as_bytes(d.get(10, b"")),
            app_hash=pb.as_bytes(d.get(11, b"")),
            last_height_params_changed=pb.to_i64(d.get(12, 1)),
            consensus_params=(
                decode_params(pb.as_bytes(d[13])) if 13 in d else ConsensusParams()
            ),
        )
