"""One-height state rollback (`rollback` CLI command).

Behavior parity: reference internal/state/rollback.go — overwrites the
latest persisted state (height n) with the state as of height n-1 so a
node can re-apply block n (e.g. after an app-hash divergence from a
faulty upgrade). Application state is NOT touched; the app must roll
back itself (or replay via handshake). With remove_block=True the
pending block n is also deleted when the block store ran ahead of the
state store.
"""

from __future__ import annotations

from dataclasses import replace


class RollbackError(Exception):
    pass


def rollback(block_store, state_store, remove_block: bool = False):
    """Returns (new_height, app_hash) after rolling back one height."""
    invalid_state = state_store.load()
    if invalid_state is None:
        raise RollbackError("no state found")

    height = block_store.height()

    # state/block saves aren't atomic: the block store may be one ahead
    # (block n+1 saved, state not yet updated) — just drop that block.
    if height == invalid_state.last_block_height + 1:
        if remove_block:
            block_store.delete_latest_block()
        return invalid_state.last_block_height, invalid_state.app_hash

    if height != invalid_state.last_block_height:
        raise RollbackError(
            f"state height ({invalid_state.last_block_height}) is not one "
            f"below or equal to blockstore height ({height})"
        )

    rollback_height = invalid_state.last_block_height - 1
    rollback_block = block_store.load_block(rollback_height)
    if rollback_block is None:
        raise RollbackError(f"block at height {rollback_height} not found")
    # app hash / last results hash for height n-1 live in block n's header
    latest_block = block_store.load_block(invalid_state.last_block_height)
    if latest_block is None:
        raise RollbackError(
            f"block at height {invalid_state.last_block_height} not found"
        )

    prev_last_vals = state_store.load_validators(rollback_height)
    if prev_last_vals is None:
        raise RollbackError(f"no validators stored for height {rollback_height}")

    val_change = min(
        invalid_state.last_height_validators_changed, rollback_height + 1
    )
    params_change = min(
        invalid_state.last_height_params_changed, rollback_height + 1
    )
    # restore the params as of validating block rollback_height+1 — a
    # params change that landed at the rolled-back height must not
    # survive the rollback (reference internal/state/rollback.go
    # LoadConsensusParams(rollbackHeight+1))
    prev_params = state_store.load_consensus_params(rollback_height + 1)

    rolled = replace(
        invalid_state,
        last_block_height=rollback_block.header.height,
        last_block_id=latest_block.header.last_block_id,
        last_block_time=rollback_block.header.time,
        next_validators=invalid_state.validators,
        validators=invalid_state.last_validators,
        last_validators=prev_last_vals,
        last_height_validators_changed=val_change,
        last_height_params_changed=params_change,
        last_results_hash=latest_block.header.last_results_hash,
        app_hash=latest_block.header.app_hash,
        **(
            {"consensus_params": prev_params} if prev_params is not None else {}
        ),
    )
    state_store.save(rolled)
    if remove_block:
        block_store.delete_latest_block()
    return rolled.last_block_height, rolled.app_hash
