"""BLS12-381 minimal-pubkey-size signatures with proof-of-possession.

The aggregate-signature track (ROADMAP item #2): 48-byte G1 public
keys, 96-byte G2 signatures, and the property that makes 10k-validator
commits cheap — n signatures over the same message verify with ONE
product-of-pairings check `e(apk, H(m)) == e(g1, sigma_agg)` after
aggregating public keys over the commit bitmap.

Scheme layout (IETF BLS signature draft, BLS12381G2_XMD:SHA-256_SSWU_RO
suite, POP variant):
- Fp / Fp2 / Fp6 / Fp12 tower: Fp2 = Fp[u]/(u^2+1),
  Fp6 = Fp2[v]/(v^3 - (1+u)), Fp12 = Fp6[w]/(w^2 - v).
- G1 on E1: y^2 = x^3 + 4 over Fp; G2 on the M-twist
  E2': y^2 = x^3 + 4(1+u) over Fp2. Zcash compressed serialization
  (0x80 compression / 0x40 infinity / 0x20 y-sign flag bits,
  lexicographic y ordering; G2 x serialized c1 || c0).
- hash-to-curve per RFC 9380 (expand_message_xmd/SHA-256, two Fp2
  field elements with L=64, simplified SWU on the 3-isogenous curve
  E': y^2 = x^3 + 240u*x + 1012(1+u), the degree-3 isogeny map back to
  E2', cofactor cleared with the h_eff scalar of §8.8.2). The isogeny
  map constants were re-derived from scratch via Velu's formulas
  (kernel = the unique Fp2-rational 3-torsion x-line of E') and agree
  with the RFC appendix.
- Pairing: ate-style Miller loop over |x| (x = -0xd201000000010000)
  with affine "ab-coordinate" line evaluation — G2 points enter the
  loop as (a, b) = (x'/xi, y'/xi) so every line is the sparse element
  yP + (s*a*xi - b)*w^3 - (s*xP)*w^5 with Fp2 coefficients — followed
  by conjugation (x < 0) and final exponentiation (easy part via
  conjugate/inverse + p^2-Frobenius, hard part a generic pow by
  (p^4 - p^2 + 1)/r).
- Proof-of-possession: pop = [sk]H_pop(pubkey_bytes) under the POP DST;
  verified with the same pairing product. Rogue-key aggregation is
  killed by requiring a valid PoP for every key before it may enter an
  aggregate (types/validator_set.py enforces this at valset
  construction).

This module is the differential ORACLE and the fallback: verification
routes to the native worker-pool engine (csrc/bls12_381.inc via
crypto/native.py) when the .so is available, and every native verdict
is pinned bit-for-bit against this code in tests/test_bls_native.py —
accept and reject paths both.

A module-level PAIRING_CHECK counter increments once per
product-of-pairings evaluation (native calls count once too): the
partition-dispatch tests assert a 10k-validator all-BLS commit costs
exactly one.
"""

from __future__ import annotations

import hashlib
import secrets

from . import native as _native
from .keys import BatchVerifier, PrivKey, PubKey, tmhash20

KEY_TYPE = "tendermint/PubKeyBls12_381"
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 48
SIG_SIZE = 96
POP_SIZE = 96

DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- parameters -----------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X_ABS = 0xD201000000010000  # |x|; the BLS parameter x is negative

G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2X = (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
       0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
G2Y = (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
       0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)

# G2 effective cofactor for clear_cofactor (RFC 9380 §8.8.2)
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# hard part of the final exponentiation: (p^4 - p^2 + 1) / r
LAMBDA_HARD = (P ** 4 - P ** 2 + 1) // R_ORDER

# --- Fp2 ------------------------------------------------------------------

XI = (1, 1)  # 1 + u: the sextic non-residue threading the whole tower


def _f2add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _f2sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _f2mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P,
            (a[0] * b[1] + a[1] * b[0]) % P)


def _f2sqr(a):
    return _f2mul(a, a)


def _f2neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def _f2inv(a):
    n = (a[0] * a[0] + a[1] * a[1]) % P
    ni = pow(n, P - 2, P)
    return (a[0] * ni % P, (-a[1]) * ni % P)


def _f2pow(a, e):
    out = (1, 0)
    while e:
        if e & 1:
            out = _f2mul(out, a)
        a = _f2sqr(a)
        e >>= 1
    return out


def _f2is_square(a):
    if a == (0, 0):
        return True
    n = (a[0] * a[0] + a[1] * a[1]) % P
    return pow(n, (P - 1) // 2, P) == 1


def _fsqrt(n):
    """sqrt in Fp (p = 3 mod 4), or None."""
    s = pow(n, (P + 1) // 4, P)
    return s if s * s % P == n else None


def _f2sqrt(a):
    """sqrt in Fp2 via the complex method, or None. Deterministic: the
    candidate is always verified by squaring (native mirrors this)."""
    if a == (0, 0):
        return (0, 0)
    if a[1] == 0:
        s = _fsqrt(a[0])
        if s is not None:
            return (s, 0)
        s = _fsqrt((-a[0]) % P)
        return None if s is None else (0, s)
    alpha = (a[0] * a[0] + a[1] * a[1]) % P
    s = _fsqrt(alpha)
    if s is None:
        return None
    inv2 = (P + 1) // 2
    delta = (a[0] + s) * inv2 % P
    c0 = _fsqrt(delta)
    if c0 is None:
        c0 = _fsqrt((a[0] - s) * inv2 % P)
        if c0 is None:
            return None
    c1 = a[1] * pow(2 * c0, P - 2, P) % P
    cand = (c0, c1)
    return cand if _f2sqr(cand) == a else None


# --- Fp6 / Fp12 tower -----------------------------------------------------

_F2ZERO = (0, 0)
_F2ONE = (1, 0)
_F6ZERO = (_F2ZERO, _F2ZERO, _F2ZERO)
_F6ONE = (_F2ONE, _F2ZERO, _F2ZERO)
FP12_ONE = (_F6ONE, _F6ZERO)


def _f6add(a, b):
    return (_f2add(a[0], b[0]), _f2add(a[1], b[1]), _f2add(a[2], b[2]))


def _f6sub(a, b):
    return (_f2sub(a[0], b[0]), _f2sub(a[1], b[1]), _f2sub(a[2], b[2]))


def _f6neg(a):
    return (_f2neg(a[0]), _f2neg(a[1]), _f2neg(a[2]))


def _f6mul(a, b):
    t0 = _f2mul(a[0], b[0])
    t1 = _f2mul(a[1], b[1])
    t2 = _f2mul(a[2], b[2])
    c0 = _f2add(t0, _f2mul(XI, _f2sub(
        _f2mul(_f2add(a[1], a[2]), _f2add(b[1], b[2])), _f2add(t1, t2))))
    c1 = _f2add(_f2sub(_f2mul(_f2add(a[0], a[1]), _f2add(b[0], b[1])),
                       _f2add(t0, t1)), _f2mul(XI, t2))
    c2 = _f2add(_f2sub(_f2mul(_f2add(a[0], a[2]), _f2add(b[0], b[2])),
                       _f2add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6mul_by_v(a):
    """a * v where v^3 = xi."""
    return (_f2mul(XI, a[2]), a[0], a[1])


def _f6inv(a):
    c0 = _f2sub(_f2sqr(a[0]), _f2mul(XI, _f2mul(a[1], a[2])))
    c1 = _f2sub(_f2mul(XI, _f2sqr(a[2])), _f2mul(a[0], a[1]))
    c2 = _f2sub(_f2sqr(a[1]), _f2mul(a[0], a[2]))
    t = _f2add(_f2mul(a[0], c0),
               _f2mul(XI, _f2add(_f2mul(a[2], c1), _f2mul(a[1], c2))))
    ti = _f2inv(t)
    return (_f2mul(c0, ti), _f2mul(c1, ti), _f2mul(c2, ti))


def _f12mul(a, b):
    aa = _f6mul(a[0], b[0])
    bb = _f6mul(a[1], b[1])
    c0 = _f6add(aa, _f6mul_by_v(bb))
    c1 = _f6sub(_f6sub(_f6mul(_f6add(a[0], a[1]), _f6add(b[0], b[1])), aa),
                bb)
    return (c0, c1)


def _f12sqr(a):
    return _f12mul(a, a)


def _f12conj(a):
    return (a[0], _f6neg(a[1]))


def _f12inv(a):
    t = _f6inv(_f6sub(_f6mul(a[0], a[0]), _f6mul_by_v(_f6mul(a[1], a[1]))))
    return (_f6mul(a[0], t), _f6neg(_f6mul(a[1], t)))


def _f12pow(a, e):
    out = FP12_ONE
    while e:
        if e & 1:
            out = _f12mul(out, a)
        a = _f12sqr(a)
        e >>= 1
    return out


# p^2-Frobenius component multipliers: gamma_k = xi^(k*(p^2-1)/6)
_G_P2 = [_f2pow(XI, k * (P * P - 1) // 6) for k in range(6)]


def _f12frob_p2(a):
    (a0, a1, a2), (b0, b1, b2) = a
    return ((a0, _f2mul(a1, _G_P2[2]), _f2mul(a2, _G_P2[4])),
            (_f2mul(b0, _G_P2[1]), _f2mul(b1, _G_P2[3]),
             _f2mul(b2, _G_P2[5])))


def _final_exp(f):
    f1 = _f12mul(_f12conj(f), _f12inv(f))        # f^(p^6 - 1)
    f2 = _f12mul(_f12frob_p2(f1), f1)            # ^(p^2 + 1)
    return _f12pow(f2, LAMBDA_HARD)              # ^((p^4-p^2+1)/r)


# --- G1 / G2 Jacobian arithmetic (a = 0 short Weierstrass) ----------------
# Points are (X, Y, Z) over the field ops; None = infinity.

def _jdbl(p, fmul, fadd, fsub):
    if p is None:
        return None
    x, y, z = p
    a = fmul(x, x)
    b = fmul(y, y)
    c = fmul(b, b)
    t = fadd(x, b)
    d = fsub(fsub(fmul(t, t), a), c)
    d = fadd(d, d)
    e = fadd(fadd(a, a), a)
    f = fmul(e, e)
    x3 = fsub(f, fadd(d, d))
    c8 = fadd(c, c)
    c8 = fadd(c8, c8)
    c8 = fadd(c8, c8)
    y3 = fsub(fmul(e, fsub(d, x3)), c8)
    z3 = fmul(fadd(y, y), z)
    return (x3, y3, z3)


def _jadd(p, q, fmul, fadd, fsub, zero):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = fmul(z1, z1)
    z2z2 = fmul(z2, z2)
    u1 = fmul(x1, z2z2)
    u2 = fmul(x2, z1z1)
    s1 = fmul(fmul(y1, z2), z2z2)
    s2 = fmul(fmul(y2, z1), z1z1)
    if u1 == u2:
        if s1 != s2:
            return None
        return _jdbl(p, fmul, fadd, fsub)
    h = fsub(u2, u1)
    rr = fsub(s2, s1)
    h2 = fmul(h, h)
    h3 = fmul(h2, h)
    u1h2 = fmul(u1, h2)
    x3 = fsub(fsub(fmul(rr, rr), h3), fadd(u1h2, u1h2))
    y3 = fsub(fmul(rr, fsub(u1h2, x3)), fmul(s1, h3))
    z3 = fmul(fmul(z1, z2), h)
    return (x3, y3, z3)


def _fp_mul(a, b):
    return a * b % P


def _fp_add(a, b):
    return (a + b) % P


def _fp_sub(a, b):
    return (a - b) % P


def _g1_dbl(p):
    return _jdbl(p, _fp_mul, _fp_add, _fp_sub)


def _g1_add(p, q):
    return _jadd(p, q, _fp_mul, _fp_add, _fp_sub, 0)


def _g1_mul(k, p):
    acc = None
    while k:
        if k & 1:
            acc = _g1_add(acc, p)
        p = _g1_dbl(p)
        k >>= 1
    return acc


def _g1_affine(p):
    if p is None:
        return None
    x, y, z = p
    zi = pow(z, P - 2, P)
    zi2 = zi * zi % P
    return (x * zi2 % P, y * zi2 * zi % P)


def _g2_dbl(p):
    return _jdbl(p, _f2mul, _f2add, _f2sub)


def _g2_add(p, q):
    return _jadd(p, q, _f2mul, _f2add, _f2sub, _F2ZERO)


def _g2_mul(k, p):
    acc = None
    while k:
        if k & 1:
            acc = _g2_add(acc, p)
        p = _g2_dbl(p)
        k >>= 1
    return acc


def _g2_affine(p):
    if p is None:
        return None
    x, y, z = p
    zi = _f2inv(z)
    zi2 = _f2sqr(zi)
    return (_f2mul(x, zi2), _f2mul(y, _f2mul(zi2, zi)))


_B2 = _f2mul((4, 0), XI)  # twist coefficient 4(1+u)


# --- serialization (zcash flags) ------------------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def _fp_from_bytes(b):
    v = int.from_bytes(b, "big")
    return v if v < P else None


def _y_is_larger_fp(y):
    return y > P - y


def _y_is_larger_fp2(y):
    n = _f2neg(y)
    return (y[1], y[0]) > (n[1], n[0])


def g1_compress(pt) -> bytes:
    """Affine (x, y) or None (infinity) -> 48 bytes."""
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 47
    x, y = pt
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if _y_is_larger_fp(y) else 0)
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g1_decompress(b: bytes):
    """48 bytes -> affine (x, y), "inf", or None when non-canonical.
    No subgroup check here; callers pair it with g1_subgroup_check."""
    if len(b) != 48 or not (b[0] & _FLAG_COMPRESSED):
        return None
    if b[0] & _FLAG_INFINITY:
        if b[0] != (_FLAG_COMPRESSED | _FLAG_INFINITY) or any(b[1:]):
            return None
        return "inf"
    sign = bool(b[0] & _FLAG_SIGN)
    x = _fp_from_bytes(bytes([b[0] & 0x1F]) + b[1:])
    if x is None:
        return None
    y = _fsqrt((pow(x, 3, P) + 4) % P)
    if y is None:
        return None
    if _y_is_larger_fp(y) != sign:
        y = P - y
    return (x, y)


def g2_compress(pt) -> bytes:
    """Affine ((x0,x1), (y0,y1)) or None -> 96 bytes (x as c1 || c0)."""
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 95
    x, y = pt
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if _y_is_larger_fp2(y) else 0)
    b = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g2_decompress(b: bytes):
    """96 bytes -> affine ((x0,x1),(y0,y1)), "inf", or None."""
    if len(b) != 96 or not (b[0] & _FLAG_COMPRESSED):
        return None
    if b[0] & _FLAG_INFINITY:
        if b[0] != (_FLAG_COMPRESSED | _FLAG_INFINITY) or any(b[1:]):
            return None
        return "inf"
    sign = bool(b[0] & _FLAG_SIGN)
    x1 = _fp_from_bytes(bytes([b[0] & 0x1F]) + b[1:48])
    x0 = _fp_from_bytes(b[48:])
    if x1 is None or x0 is None:
        return None
    x = (x0, x1)
    y = _f2sqrt(_f2add(_f2mul(_f2sqr(x), x), _B2))
    if y is None:
        return None
    if _y_is_larger_fp2(y) != sign:
        y = _f2neg(y)
    return (x, y)


def g1_subgroup_check(pt) -> bool:
    """Naive [r]P == O — the oracle's ground truth the native fast
    endomorphism check is differentially pinned against."""
    return _g1_mul(R_ORDER, (pt[0], pt[1], 1)) is None


def g2_subgroup_check(pt) -> bool:
    return _g2_mul(R_ORDER, (pt[0], pt[1], _F2ONE)) is None


# validated-pubkey memo: validator G1 keys repeat across every commit;
# the 15 ms naive subgroup check runs once per distinct key
_G1_OK_CACHE: dict[bytes, tuple] = {}


def _pubkey_point(pub: bytes):
    """KeyValidate: decode, reject infinity, subgroup check. Cached."""
    hit = _G1_OK_CACHE.get(pub)
    if hit is not None:
        return hit
    pt = g1_decompress(pub)
    if pt is None or pt == "inf" or not g1_subgroup_check(pt):
        return None
    if len(_G1_OK_CACHE) > 8192:
        _G1_OK_CACHE.clear()
    _G1_OK_CACHE[pub] = pt
    return pt


# --- hash-to-curve (RFC 9380, BLS12381G2_XMD:SHA-256_SSWU_RO) -------------

_H2C_L = 64


def _expand_message_xmd(msg: bytes, dst: bytes, n: int) -> bytes:
    ell = (n + 31) // 32
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds")
    dst_prime = dst + bytes([len(dst)])
    b0 = hashlib.sha256(
        b"\x00" * 64 + msg + n.to_bytes(2, "big") + b"\x00" + dst_prime
    ).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        bi = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, bi)) + bytes([i]) + dst_prime
        ).digest()
        out.append(bi)
    return b"".join(out)[:n]


def _hash_to_field_fp2(msg: bytes, dst: bytes, count: int):
    uniform = _expand_message_xmd(msg, dst, count * 2 * _H2C_L)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _H2C_L * (j + i * 2)
            coords.append(
                int.from_bytes(uniform[off:off + _H2C_L], "big") % P)
        out.append(tuple(coords))
    return out


# SSWU curve E': y^2 = x^3 + A'x + B' (3-isogenous to the twist)
_ISO_A = _f2mul((240, 0), (0, 1))               # 240u
_ISO_B = _f2mul((1012, 0), (1, 1))              # 1012(1+u)
_SSWU_Z = _f2neg((2, 1))                        # -(2+u)
_MB_DIV_A = _f2mul(_f2neg(_ISO_B), _f2inv(_ISO_A))
_B_DIV_ZA = _f2mul(_ISO_B, _f2inv(_f2mul(_SSWU_Z, _ISO_A)))


def _sgn0_fp2(a):
    if a[0] != 0:
        return a[0] & 1
    return a[1] & 1


def _sswu(u):
    """Simplified SWU: Fp2 element -> affine point on E'."""
    zu2 = _f2mul(_SSWU_Z, _f2sqr(u))
    tv = _f2add(_f2sqr(zu2), zu2)               # Z^2 u^4 + Z u^2
    if tv == _F2ZERO:
        x1 = _B_DIV_ZA
    else:
        x1 = _f2mul(_MB_DIV_A, _f2add(_F2ONE, _f2inv(tv)))
    gx1 = _f2add(_f2add(_f2mul(_f2sqr(x1), x1), _f2mul(_ISO_A, x1)), _ISO_B)
    if _f2is_square(gx1):
        x, y = x1, _f2sqrt(gx1)
    else:
        x = _f2mul(zu2, x1)
        gx2 = _f2add(_f2add(_f2mul(_f2sqr(x), x), _f2mul(_ISO_A, x)),
                     _ISO_B)
        y = _f2sqrt(gx2)
    if _sgn0_fp2(u) != _sgn0_fp2(y):
        y = _f2neg(y)
    return (x, y)


# degree-3 isogeny E' -> E2' (coefficients derived via Velu — which
# lands on the -y twin of the canonical map, an equally valid isogeny;
# the y-numerator below is negated to match RFC 9380 Appendix E.3
# exactly, pinned by the appendix-H hash_to_curve vectors.
# Low-degree-first.)
_ISO_XNUM = (
    (0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
     0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    (0,
     0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x08AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
     0),
)
_ISO_XDEN = (
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),
)
_ISO_YNUM = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0,
     0x05C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x08AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
     0),
)
_ISO_YDEN = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (1, 0),
)


def _poly_eval(coeffs, x):
    acc = _F2ZERO
    for c in reversed(coeffs):
        acc = _f2add(_f2mul(acc, x), c)
    return acc


def _iso_map(pt):
    """E' affine -> E2' affine (or None at the blown-up kernel)."""
    x, y = pt
    xd = _poly_eval(_ISO_XDEN, x)
    yd = _poly_eval(_ISO_YDEN, x)
    if xd == _F2ZERO or yd == _F2ZERO:
        return None
    xo = _f2mul(_poly_eval(_ISO_XNUM, x), _f2inv(xd))
    yo = _f2mul(y, _f2mul(_poly_eval(_ISO_YNUM, x), _f2inv(yd)))
    return (xo, yo)


def hash_to_g2(msg: bytes, dst: bytes = DST_SIG):
    """RFC 9380 hash_to_curve: affine G2 point on the twist, or None in
    the (cryptographically unreachable) degenerate cases."""
    u0, u1 = _hash_to_field_fp2(msg, dst, 2)
    q0 = _iso_map(_sswu(u0))
    q1 = _iso_map(_sswu(u1))
    if q0 is None or q1 is None:
        return None
    s = _g2_add((q0[0], q0[1], _F2ONE), (q1[0], q1[1], _F2ONE))
    cleared = _g2_mul(H_EFF, s)
    return _g2_affine(cleared)


def hash_to_g2_compressed(msg: bytes, dst: bytes = DST_SIG) -> bytes:
    """96-byte compressed H(m) — the differential-test surface."""
    if _native.bls_available():
        out = _native.bls_hash_to_g2(msg, dst)
        if out is not None:
            return out
    return g2_compress(hash_to_g2(msg, dst))


# --- pairing --------------------------------------------------------------

_INV_XI = _f2inv(XI)


def _ab_coords(pt):
    """Twist affine -> the Miller-loop (a, b) = (x/xi, y/xi) coords."""
    return (_f2mul(pt[0], _INV_XI), _f2mul(pt[1], _INV_XI))


def _sparse_line(c0, c3, c5):
    """c0 + c3*w^3 + c5*w^5 as a full Fp12 element (w^3 = v*w,
    w^5 = v^2*w)."""
    return ((c0, _F2ZERO, _F2ZERO), (_F2ZERO, c3, c5))


_X_BITS = bin(BLS_X_ABS)[3:]  # MSB consumed by loop init


def _miller_product(pairs):
    """prod_i f_{|x|, Q_i}(P_i), conjugated for x < 0. `pairs` is
    [((xP, yP), (aQ, bQ))] with G1 affine ints and G2 ab-coords.
    Returns None on degenerate arithmetic (cannot happen for checked
    subgroup inputs; guards divide-by-zero anyway)."""
    f = FP12_ONE
    ts = [q for _, q in pairs]
    for bit in _X_BITS:
        f = _f12sqr(f)
        for i, (pp, q) in enumerate(pairs):
            a, b = ts[i]
            if b == _F2ZERO:
                return None
            s = _f2mul(_f2add(_f2sqr(a), _f2add(_f2sqr(a), _f2sqr(a))),
                       _f2inv(_f2add(b, b)))          # 3a^2 / 2b
            c3 = _f2sub(_f2mul(_f2mul(s, a), XI), b)
            c5 = _f2neg((s[0] * pp[0] % P, s[1] * pp[0] % P))
            f = _f12mul(f, _sparse_line((pp[1], 0), c3, c5))
            s2xi = _f2mul(_f2sqr(s), XI)
            a3 = _f2sub(s2xi, _f2add(a, a))
            b3 = _f2sub(_f2mul(_f2mul(s, XI), _f2sub(a, a3)), b)
            ts[i] = (a3, b3)
            if bit == "1":
                a1, b1 = ts[i]
                aq, bq = q
                d = _f2sub(aq, a1)
                if d == _F2ZERO:
                    return None
                s = _f2mul(_f2sub(bq, b1), _f2inv(_f2mul(d, XI)))
                c3 = _f2sub(_f2mul(_f2mul(s, aq), XI), bq)
                c5 = _f2neg((s[0] * pp[0] % P, s[1] * pp[0] % P))
                f = _f12mul(f, _sparse_line((pp[1], 0), c3, c5))
                s2xi = _f2mul(_f2sqr(s), XI)
                a3 = _f2sub(s2xi, _f2add(a1, aq))
                b3 = _f2sub(_f2mul(_f2mul(s, XI), _f2sub(a1, a3)), b1)
                ts[i] = (a3, b3)
    return _f12conj(f)  # x < 0


# product-of-pairings evaluations since import — the "one pairing
# check per commit" acceptance counter (native calls increment it too)
PAIRING_CHECKS = 0


def pairing_checks() -> int:
    return PAIRING_CHECKS


def _pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, ONE Miller product + ONE final exp.
    pairs: [(G1 affine, G2 twist affine)]."""
    global PAIRING_CHECKS
    PAIRING_CHECKS += 1
    f = _miller_product([(pp, _ab_coords(q)) for pp, q in pairs])
    if f is None:
        return False
    return _final_exp(f) == FP12_ONE


def pairing_bytes(p48: bytes, q96: bytes) -> bytes | None:
    """Serialized GT element e(P, Q) — 12 Fp coordinates, 48-byte BE
    each, order c0.c0.c0 … c1.c2.c1. Differential surface pinning the
    native Miller loop + final exp bit-for-bit against the oracle."""
    p = g1_decompress(p48)
    q = g2_decompress(q96)
    if p in (None, "inf") or q in (None, "inf"):
        return None
    if not g1_subgroup_check(p) or not g2_subgroup_check(q):
        return None
    f = _miller_product([(p, _ab_coords(q))])
    if f is None:
        return None
    gt = _final_exp(f)
    out = b""
    for six in gt:
        for two in six:
            for c in two:
                out += c.to_bytes(48, "big")
    return out


# --- scheme ---------------------------------------------------------------

_G1_GEN = (G1X, G1Y)
_G1_GEN_NEG = (G1X, P - G1Y)


def _scalar_from_bytes(b: bytes) -> int:
    return int.from_bytes(b, "big")


def sk_to_pub(sk: int) -> bytes:
    if _native.bls_available():
        out = _native.bls_pubkey(sk.to_bytes(32, "big"))
        if out is not None:
            return out
    return g1_compress(_g1_affine(_g1_mul(sk, (G1X, G1Y, 1))))


def sign_python(sk: int, msg: bytes, dst: bytes = DST_SIG) -> bytes:
    h = hash_to_g2(msg, dst)
    sig = _g2_affine(_g2_mul(sk, (h[0], h[1], _F2ONE)))
    return g2_compress(sig)


def verify_python(pub: bytes, msg: bytes, sig: bytes,
                  dst: bytes = DST_SIG) -> bool:
    """The pure-Python verify — fallback and differential oracle.
    KeyValidate (reject identity, subgroup) + signature subgroup check
    + e(pk, H(m)) * e(-g1, sigma) == 1."""
    if len(sig) != SIG_SIZE:
        return False
    pk = _pubkey_point(pub) if len(pub) == PUB_KEY_SIZE else None
    if pk is None:
        return False
    sg = g2_decompress(sig)
    if sg is None or sg == "inf" or not g2_subgroup_check(sg):
        return False
    h = hash_to_g2(msg, dst)
    if h is None:
        return False
    return _pairing_product_is_one([(pk, h), (_G1_GEN_NEG, sg)])


def verify_one(pub: bytes, msg: bytes, sig: bytes,
               dst: bytes = DST_SIG) -> bool:
    if len(sig) != SIG_SIZE or len(pub) != PUB_KEY_SIZE:
        return False
    if _native.bls_available():
        got = _native.bls_verify(pub, msg, sig, dst)
        if got is not None:
            global PAIRING_CHECKS
            PAIRING_CHECKS += 1
            return bool(got)
    return verify_python(pub, msg, sig, dst)


def pop_prove(sk: int) -> bytes:
    pub = sk_to_pub(sk)
    if _native.bls_available():
        out = _native.bls_sign(sk.to_bytes(32, "big"), pub, DST_POP)
        if out is not None:
            return out
    return sign_python(sk, pub, DST_POP)


def pop_verify(pub: bytes, pop: bytes) -> bool:
    """Proof-of-possession: a valid signature over the pubkey bytes
    under the POP DST. Gate for aggregate membership."""
    return verify_one(pub, pub, pop, DST_POP)


# --- aggregation ----------------------------------------------------------

def aggregate_signatures(sigs, nchunks: int = 0) -> bytes | None:
    """Sum n G2 signatures -> one 96-byte aggregate; None if any input
    fails decode/subgroup. Native worker-pool when available."""
    sigs = list(sigs)
    if not sigs:
        return None
    if _native.bls_available():
        out = _native.bls_aggregate_sigs(b"".join(sigs), len(sigs), nchunks)
        if out is not None:
            return out
    acc = None
    for s in sigs:
        pt = g2_decompress(s)
        if pt is None:
            return None
        if pt == "inf":
            continue
        if not g2_subgroup_check(pt):
            return None
        acc = _g2_add(acc, (pt[0], pt[1], _F2ONE))
    return g2_compress(_g2_affine(acc))


def aggregate_pubkeys(pubs, bitmap: bytes | None = None,
                      nchunks: int = 0) -> bytes | None:
    """Aggregate pubkey over a signer bitmap (bit i set = pubs[i]
    participates; None = all). Every participating key is
    KeyValidate'd; identity aggregate rejected (the +-P PoP-pair
    degeneracy). Native path runs the per-chunk partial sums across
    the worker pool."""
    pubs = list(pubs)
    if bitmap is None:
        bitmap = bytes([0xFF] * ((len(pubs) + 7) // 8))
    if _native.bls_available():
        out = _native.bls_aggregate_pubkeys(
            b"".join(pubs), len(pubs), bitmap, nchunks)
        if out is not None:
            return out
    acc = None
    any_set = False
    for i, pb in enumerate(pubs):
        if not (bitmap[i >> 3] >> (i & 7)) & 1:
            continue
        any_set = True
        pt = _pubkey_point(pb) if len(pb) == PUB_KEY_SIZE else None
        if pt is None:
            return None
        acc = _g1_add(acc, (pt[0], pt[1], 1))
    if not any_set or acc is None:
        return None  # empty or identity aggregate: invalid
    aff = _g1_affine(acc)
    return g1_compress(aff)


def cert_verify(pubs, bitmap: bytes, msg: bytes, agg_sig: bytes,
                dst: bytes = DST_SIG, nchunks: int = 0) -> bool:
    """Aggregate-certificate check — the compact-commit hot path:
    e(apk(bitmap), H(msg)) == e(g1, sigma_agg) in ONE pairing-product
    evaluation. `pubs` lists the whole validator set's 48-byte keys in
    set order; bit i of bitmap marks signer i. Native path fuses the
    pool-parallel apk sum with the pairing check in a single call."""
    pubs = list(pubs)
    if not pubs or len(agg_sig) != SIG_SIZE:
        return False
    if _native.bls_available():
        got = _native.bls_cert_verify(
            b"".join(pubs), len(pubs), bitmap, msg, agg_sig, dst, nchunks)
        if got is not None:
            global PAIRING_CHECKS
            PAIRING_CHECKS += 1
            return bool(got)
    apk = aggregate_pubkeys(pubs, bitmap, nchunks)
    if apk is None:
        return False
    return verify_one(apk, msg, agg_sig, dst)


def aggregate_verify_items(items, dst: bytes = DST_SIG,
                           nchunks: int = 0) -> bool:
    """The commit fast path: n (pub, msg, sig) triples -> ONE
    product-of-pairings check. Messages are grouped (commit sign-bytes
    differ across validators only via per-slot timestamps, usually not
    at all): per distinct message the pubkeys aggregate into apk_j, all
    signatures aggregate into sigma_agg, and the single evaluation
    checks prod_j e(apk_j, H(m_j)) * e(-g1, sigma_agg) == 1.

    Returns the aggregate verdict only — callers needing a blame
    bitmap rescan per-signature on failure (BlsBatchVerifier.verify).
    """
    items = list(items)
    if not items:
        return False
    for pub, _m, sig in items:
        if len(pub) != PUB_KEY_SIZE or len(sig) != SIG_SIZE:
            return False
    global PAIRING_CHECKS
    if _native.bls_available():
        groups: dict[bytes, int] = {}
        gids = []
        for _p, m, _s in items:
            gid = groups.setdefault(m, len(groups))
            gids.append(gid)
        msgs = [m for m, _ in sorted(groups.items(), key=lambda kv: kv[1])]
        got = _native.bls_aggregate_verify(
            b"".join(p for p, _m, _s in items),
            b"".join(s for _p, _m, s in items),
            len(items), gids, msgs, dst, nchunks)
        if got is not None:
            PAIRING_CHECKS += 1
            return bool(got)
    # oracle path
    by_msg: dict[bytes, list] = {}
    for pub, m, _s in items:
        by_msg.setdefault(m, []).append(pub)
    pairs = []
    for m, pubs in by_msg.items():
        apk = None
        for pb in pubs:
            pt = _pubkey_point(pb)
            if pt is None:
                return False
            apk = _g1_add(apk, (pt[0], pt[1], 1))
        if apk is None:
            return False  # identity aggregate
        h = hash_to_g2(m, dst)
        if h is None:
            return False
        pairs.append((_g1_affine(apk), h))
    sagg = None
    for _p, _m, s in items:
        pt = g2_decompress(s)
        if pt in (None, "inf") or not g2_subgroup_check(pt):
            return False
        sagg = _g2_add(sagg, (pt[0], pt[1], _F2ONE))
    if sagg is None:
        return False
    pairs.append((_G1_GEN_NEG, _g2_affine(sagg)))
    return _pairing_product_is_one(pairs)


# --- key classes ----------------------------------------------------------

class BlsPubKey(PubKey):
    __slots__ = ("_b",)

    def __init__(self, b: bytes):
        if len(b) != PUB_KEY_SIZE:
            raise ValueError(f"bls12-381 pubkey must be {PUB_KEY_SIZE} bytes")
        self._b = bytes(b)

    def address(self) -> bytes:
        return tmhash20(self._b)

    def bytes(self) -> bytes:
        return self._b

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify_one(self._b, msg, sig)

    def type_tag(self) -> str:
        return KEY_TYPE

    def __repr__(self):
        return f"BlsPubKey({self._b.hex()[:16]}…)"


class BlsPrivKey(PrivKey):
    __slots__ = ("_d",)

    def __init__(self, key_bytes: bytes):
        if len(key_bytes) != PRIV_KEY_SIZE:
            raise ValueError("bls12-381 privkey must be 32 bytes")
        d = _scalar_from_bytes(key_bytes)
        if not (1 <= d < R_ORDER):
            raise ValueError("bls12-381 privkey scalar out of range")
        self._d = d

    @classmethod
    def generate(cls) -> "BlsPrivKey":
        while True:
            b = secrets.token_bytes(32)
            d = int.from_bytes(b, "big")
            if 1 <= d < R_ORDER:
                return cls(b)

    @classmethod
    def from_secret(cls, secret: bytes) -> "BlsPrivKey":
        fe = int.from_bytes(hashlib.sha256(secret).digest(), "big")
        d = fe % (R_ORDER - 1) + 1
        return cls(d.to_bytes(32, "big"))

    def sign(self, msg: bytes) -> bytes:
        if _native.bls_available():
            out = _native.bls_sign(self._d.to_bytes(32, "big"), msg, DST_SIG)
            if out is not None:
                return out
        return sign_python(self._d, msg)

    def pop(self) -> bytes:
        """Proof-of-possession over this key's public bytes."""
        return pop_prove(self._d)

    def pub_key(self) -> BlsPubKey:
        return BlsPubKey(sk_to_pub(self._d))

    def bytes(self) -> bytes:
        return self._d.to_bytes(32, "big")

    def type_tag(self) -> str:
        return KEY_TYPE


class BlsBatchVerifier(BatchVerifier):
    """BatchVerifier seam for BLS12-381: the whole batch collapses into
    one aggregate pairing check; a per-signature rescan provides the
    blame bitmap only when the aggregate fails (mirrors the sr25519
    RLC-then-scan shape)."""

    def __init__(self, backend: str = "host"):
        self._items: list[tuple[bytes, bytes, bytes]] = []
        self.backend = backend

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> bool:
        if not isinstance(pub_key, BlsPubKey):
            return False
        if len(sig) != SIG_SIZE:
            return False
        self._items.append((pub_key.bytes(), msg, sig))
        return True

    def count(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        if not self._items:
            return False, []
        if aggregate_verify_items(self._items):
            return True, [True] * len(self._items)
        bits = [verify_one(p, m, s) for p, m, s in self._items]
        return all(bits), bits
